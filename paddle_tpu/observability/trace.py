"""Structured span tracing — per-rank trace files + cross-rank merge.

The performance-attribution substrate (docs/OBSERVABILITY.md): every rank
writes a line-oriented JSON trace file (``trace_rank<r>_<pid>.jsonl``)
whose first line is a header carrying the rank and a **clock anchor** — a
``(perf_counter_ns, unix_ns)`` pair sampled back-to-back — and whose
remaining lines are spans/marks timestamped on the local
``perf_counter_ns`` clock. Sources: ``StepTimer`` (step phases), the
collective tracer (comm spans with bytes/axes/exposure), the serving
engine (per-request span chains), and anything else via :func:`span` /
:func:`mark`.

The merge tool aligns every rank onto one clock using the anchors
(``aligned_ns = ts - anchor.perf_ns + anchor.unix_ns``), emits a single
chrome trace (one process lane per rank) plus a JSON summary with
per-rank **skew** (how far each rank's step boundaries sit from the
fleet) and **straggler** stats (which rank finishes each step last, and
how wide the spread is)::

    python -m paddle_tpu.observability.trace merge <dir> \
        [--out merged_trace.json] [--summary merge_summary.json]

Gating mirrors the flight recorder: ``PADDLE_TPU_TRACE_SPANS=<dir>``
arms the per-rank writer at ``import paddle_tpu``; unset keeps every
:func:`span` call a single module-attribute read.
"""
from __future__ import annotations

import glob as _glob
import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["TraceWriter", "enable", "disable", "active", "span", "mark",
           "maybe_enable_from_env", "merge", "FORMAT_VERSION"]

FORMAT_VERSION = 1

#: the active writer — instrumentation reads this attribute on every
#: span, so it must stay a plain module global (no function call)
_active: Optional["TraceWriter"] = None


def _rank() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


class TraceWriter:
    """Append-only per-rank trace file (thread-safe, line-buffered).

    Every line is one JSON object. The header pins the clock anchor the
    merge tool needs; events carry raw ``perf_counter_ns`` timestamps so
    recording never pays a clock conversion.
    """

    def __init__(self, path: str, rank: Optional[int] = None,
                 meta: Optional[dict] = None):
        self.path = path
        self.rank = _rank() if rank is None else int(rank)
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "w", buffering=1)  # line-buffered: crash-safe
        header = {"type": "header", "version": FORMAT_VERSION,
                  "rank": self.rank, "pid": os.getpid(),
                  "clock": {"perf_ns": time.perf_counter_ns(),
                            "unix_ns": time.time_ns()}}
        if meta:
            header["meta"] = dict(meta)
        self._write(header)

    def _write(self, obj: dict):
        line = json.dumps(obj, separators=(",", ":"))
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")

    def span(self, cat: str, name: str, start_ns: int, end_ns: int,
             tid: int = 0, args: Optional[dict] = None):
        ev = {"type": "span", "cat": cat, "name": name,
              "ts": int(start_ns), "dur": max(int(end_ns - start_ns), 0),
              "tid": int(tid)}
        if args:
            ev["args"] = args
        self._write(ev)

    def mark(self, cat: str, name: str, ts_ns: Optional[int] = None,
             tid: int = 0, args: Optional[dict] = None):
        ev = {"type": "mark", "cat": cat, "name": name,
              "ts": int(time.perf_counter_ns() if ts_ns is None else ts_ns),
              "tid": int(tid)}
        if args:
            ev["args"] = args
        self._write(ev)

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()


def enable(trace_dir: str, rank: Optional[int] = None) -> TraceWriter:
    """Start the per-rank writer (idempotent). A second call while
    tracing is armed keeps the existing writer; if it asked for a
    different directory, that is almost certainly a bug (spans would
    land where the caller isn't looking), so it warns loudly."""
    global _active
    if _active is not None:
        want = os.path.abspath(trace_dir)
        have = os.path.dirname(os.path.abspath(_active.path))
        if want != have:
            import warnings
            warnings.warn(
                f"trace.enable({trace_dir!r}) ignored: tracing is "
                f"already writing to {have!r} — trace.disable() first "
                f"to redirect", RuntimeWarning, stacklevel=2)
        return _active
    r = _rank() if rank is None else int(rank)
    path = os.path.join(trace_dir, f"trace_rank{r}_{os.getpid()}.jsonl")
    _active = TraceWriter(path, rank=r)
    return _active


def disable():
    global _active
    if _active is None:
        return
    w, _active = _active, None
    w.close()


def active() -> Optional[TraceWriter]:
    return _active


def span(cat: str, name: str, start_ns: int, end_ns: int, tid: int = 0,
         args: Optional[dict] = None):
    """Record one span iff tracing is on (cheap no-op otherwise)."""
    w = _active
    if w is not None:
        w.span(cat, name, start_ns, end_ns, tid=tid, args=args)


def mark(cat: str, name: str, ts_ns: Optional[int] = None, tid: int = 0,
         args: Optional[dict] = None):
    w = _active
    if w is not None:
        w.mark(cat, name, ts_ns=ts_ns, tid=tid, args=args)


def maybe_enable_from_env() -> Optional[TraceWriter]:
    """``PADDLE_TPU_TRACE_SPANS=<dir>`` arms the writer at import; unset
    (or unusable dir) keeps tracing off — this runs at ``import
    paddle_tpu`` and must never kill the process."""
    d = os.environ.get("PADDLE_TPU_TRACE_SPANS", "").strip()
    if not d or d in ("0", "false", "off", "no"):
        return _active
    try:
        return enable(d)
    except OSError:
        return _active


# ---------------------------------------------------------------------------
# merge: N per-rank files -> one aligned chrome trace + skew summary
# ---------------------------------------------------------------------------

def _load_rank_file(path: str):
    """(header, events) — skips torn trailing lines (a crashed writer's
    last line may be partial; everything before it is still good)."""
    header, events = None, []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a crashed writer
            if obj.get("type") == "header":
                header = obj
            else:
                events.append(obj)
    if header is None:
        raise ValueError(f"{path}: no trace header line")
    return header, events


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(int(round(q * (len(s) - 1))), len(s) - 1)
    return s[idx]


def _goodput_rollup(ranks: List[dict], aligned: List[tuple]) -> dict:
    """Offline goodput reclassification (``merge --goodput``): replay the
    live :class:`~.goodput.GoodputLedger` split from the step spans'
    embedded shares (``data_time_s`` / ``exposed_collective_time_s`` /
    ``compile_s`` / ``ckpt_s`` — StepTimer writes them exactly so this
    path can), so old trace dirs get goodput numbers retroactively.

    Per lane: each step span's wall splits into its bins; the gaps
    *between* consecutive step spans are ``other_overhead``. Per rank: a
    relaunch (second lane, new pid) makes the gap between the first
    lane's last event and the second's first event ``restart`` badput —
    unless the successor lane opens with an elastic ``resize`` marker
    (the launcher's planned-resize relaunch), in which case the gap is
    ``reshard``. In-place resizes ride as ``cat="elastic"`` spans whose
    wall bins as ``reshard`` directly.
    """
    from .goodput import BINS
    bins = {b: 0.0 for b in BINS}
    lanes: Dict[str, dict] = {}
    for ts, r, ev in aligned:
        lane = lanes.setdefault(
            r["label"], {"rank": r["rank"], "steps": [],
                         "first_ns": ts, "last_ns": ts,
                         "resized": False})
        end = ts + int(ev.get("dur", 0)) if ev.get("type") == "span" else ts
        lane["first_ns"] = min(lane["first_ns"], ts)
        lane["last_ns"] = max(lane["last_ns"], end)
        if ev.get("cat") == "step" and ev.get("type") == "span":
            lane["steps"].append((ts, end, ev.get("args") or {}))
        elif ev.get("cat") == "elastic":
            if str(ev.get("name", "")).startswith("resize"):
                lane["resized"] = True
            if ev.get("type") == "span":
                lane["steps"].append((ts, end, {"__elastic__": True}))
    steps = 0
    for lane in lanes.values():
        lane["steps"].sort()
        prev_end = None
        for ts, end, a in lane["steps"]:
            if a.get("__elastic__"):
                # an in-place resize span: its whole wall is reshard
                bins["reshard"] += (end - ts) / 1e9
                if prev_end is not None and ts > prev_end:
                    bins["other_overhead"] += (ts - prev_end) / 1e9
                prev_end = max(prev_end or end, end)
                continue
            dur = float(a.get("step_time_s", (end - ts) / 1e9))
            shares = {
                "data_stall": float(a.get("data_time_s", 0.0)),
                "exposed_collective": float(
                    a.get("exposed_collective_time_s", 0.0)),
                "compile": float(a.get("compile_s", 0.0)),
                "checkpoint": float(a.get("ckpt_s", 0.0)),
            }
            scale = min(dur / max(sum(shares.values()), 1e-12), 1.0)
            for b, v in shares.items():
                bins[b] += v * scale
            bins["productive"] += dur - min(sum(shares.values()), dur)
            if prev_end is not None and ts > prev_end:
                bins["other_overhead"] += (ts - prev_end) / 1e9
            prev_end = end
            steps += 1
    # relaunch gaps: lanes of the same rank, ordered by first event
    by_rank: Dict[int, List[dict]] = {}
    for lane in lanes.values():
        by_rank.setdefault(lane["rank"], []).append(lane)
    for group in by_rank.values():
        group.sort(key=lambda ln: ln["first_ns"])
        for prev, nxt in zip(group, group[1:]):
            gap = (nxt["first_ns"] - prev["last_ns"]) / 1e9
            if gap > 0:
                # a successor lane born from a planned resize marks
                # itself; its rebirth gap is elasticity, not a crash
                bins["reshard" if nxt["resized"] else "restart"] += gap
    wall = sum(bins.values())
    return {"bins": {b: round(v, 6) for b, v in bins.items()},
            "wall_s": round(wall, 6), "steps": steps,
            "lanes": sorted(lanes),
            "job_goodput_fraction": round(
                bins["productive"] / wall, 6) if wall > 0 else 0.0}


def _request_rollup(aligned: List[tuple]) -> dict:
    """``merge --requests``: stitch each request's serving spans across
    rank/pid lanes into one per-request summary, keyed by the W3C trace
    id every serving span carries (``args.trace`` — the id the HTTP
    server echoed to the client). A client holding a ``traceparent``
    from an error body looks its request up here; cross-process chains
    (future router -> replica hops) fold into the same entry because
    the id survives the hop. Spans with no trace id fall back to a
    ``req:<id>`` key (pre-ledger writers)."""
    reqs: Dict[str, dict] = {}
    for ts, r, ev in aligned:
        if ev.get("cat") != "serving":
            continue
        a = ev.get("args") or {}
        key = a.get("trace") or (
            f"req:{a['req']}" if a.get("req") is not None else None)
        if key is None:
            continue
        end = ts + int(ev.get("dur", 0)) if ev.get("type") == "span" else ts
        q = reqs.setdefault(key, {
            "trace_id": a.get("trace"), "req_id": a.get("req"),
            "lanes": set(), "spans": 0, "first_ns": ts, "last_ns": end,
            "queue_wait_s": None, "prefill_chunks": 0,
            "prefill_tokens": 0, "compiles": 0, "preemptions": 0})
        q["lanes"].add(r["label"])
        q["spans"] += 1
        q["first_ns"] = min(q["first_ns"], ts)
        q["last_ns"] = max(q["last_ns"], end)
        name = ev.get("name")
        if name == "queue_wait":
            q["queue_wait_s"] = round((end - ts) / 1e9, 6)
        elif name == "prefill_chunk":
            q["prefill_chunks"] += 1
            q["prefill_tokens"] += int(a.get("tokens", 0))
            q["compiles"] += int(a.get("compiles", 0))
        elif name == "preempted":
            q["preemptions"] = max(q["preemptions"],
                                   int(a.get("preemptions", 0)))
        elif name == "request_done":
            # the authoritative completion record (ledger-enriched)
            for src, dst in (("finish_reason", "finish_reason"),
                             ("prompt_len", "prompt_len"),
                             ("generated", "generated"),
                             ("prefilled_tokens", "prefilled_tokens"),
                             ("cached_tokens", "cached_tokens"),
                             ("decode_tokens", "decode_tokens"),
                             ("kv_block_seconds", "kv_block_seconds"),
                             ("ttft_s", "ttft_s"),
                             ("latency_s", "latency_s"),
                             ("itl_p50_ms", "itl_p50_ms"),
                             ("itl_p99_ms", "itl_p99_ms")):
                if src in a:
                    q[dst] = a[src]
            q["preemptions"] = max(q["preemptions"],
                                   int(a.get("preemptions", 0)))
    out = {}
    for key, q in reqs.items():
        q["lanes"] = sorted(q["lanes"])
        q["wall_s"] = round((q["last_ns"] - q["first_ns"]) / 1e9, 6)
        del q["first_ns"], q["last_ns"]
        out[key] = q
    return {"requests": out, "count": len(out)}


def merge(trace_dir: str, out_trace: Optional[str] = None,
          out_summary: Optional[str] = None,
          pattern: str = "trace_rank*.jsonl",
          goodput: bool = False, requests: bool = False) -> dict:
    """Merge every per-rank trace file under ``trace_dir`` onto one
    clock. Writes a chrome trace (default ``merged_trace.json``) and a
    summary (default ``merge_summary.json``) into ``trace_dir`` and
    returns the summary dict.

    Alignment: each event's local ``perf_counter_ns`` timestamp is
    shifted by its rank's header anchor onto the unix-epoch clock, then
    the merged trace is re-zeroed at the earliest event. Skew/straggler
    stats come from the ``step`` spans (``args.step`` ids shared across
    ranks): per step, the spread between the first and last rank to
    finish, and which rank was last.
    """
    paths = sorted(_glob.glob(os.path.join(trace_dir, pattern)))
    if not paths:
        raise FileNotFoundError(
            f"no {pattern!r} files under {trace_dir!r}")
    ranks = []
    for p in paths:
        header, events = _load_rank_file(p)
        clock = header.get("clock", {})
        offset = int(clock.get("unix_ns", 0)) - int(clock.get("perf_ns", 0))
        ranks.append({"path": p, "rank": int(header.get("rank", 0)),
                      "offset": offset, "events": events,
                      "pid": header.get("pid")})

    # One lane per FILE, not per rank: a crash + relaunch leaves two
    # files for the same rank (the documented postmortem case), and
    # folding them together would silently clobber step end-times and
    # interleave two processes in one chrome lane. When a rank appears
    # once its lane label/pid stay the plain rank; duplicates get
    # "rank:pid" labels and unique synthetic chrome pids.
    rank_seen: Dict[int, int] = {}
    for r in ranks:
        rank_seen[r["rank"]] = rank_seen.get(r["rank"], 0) + 1
    next_pid = max((r["rank"] for r in ranks), default=0) + 1
    seen_labels: Dict[str, int] = {}
    for r in sorted(ranks, key=lambda x: (x["rank"], x["path"])):
        if rank_seen[r["rank"]] == 1:
            r["label"], r["chrome_pid"] = str(r["rank"]), r["rank"]
            r["lane_name"] = f"rank {r['rank']}"
        else:
            r["label"] = f"{r['rank']}:{r['pid']}"
            r["chrome_pid"], next_pid = next_pid, next_pid + 1
            r["lane_name"] = f"rank {r['rank']} (pid {r['pid']})"
        n = seen_labels.get(r["label"], 0)
        seen_labels[r["label"]] = n + 1
        if n:  # same rank AND same header pid: still one lane per file
            r["label"] = f"{r['label']}#{n}"

    # align every event onto the unix clock, then re-zero
    aligned = []
    for r in ranks:
        for ev in r["events"]:
            ts = int(ev.get("ts", 0)) + r["offset"]
            aligned.append((ts, r, ev))
    if not aligned:
        raise ValueError(f"trace files under {trace_dir!r} hold no events")
    aligned.sort(key=lambda t: t[0])
    t_zero = aligned[0][0]

    # -- chrome trace --------------------------------------------------------
    chrome: List[dict] = []
    for r in sorted(ranks, key=lambda r: (r["rank"], r["path"])):
        chrome.append({"ph": "M", "name": "process_name",
                       "pid": r["chrome_pid"],
                       "args": {"name": r["lane_name"]}})
    for ts, r, ev in aligned:
        d = {"name": ev.get("name", "?"), "cat": ev.get("cat", "user"),
             "pid": r["chrome_pid"], "tid": ev.get("tid", 0),
             "ts": (ts - t_zero) / 1000.0}  # chrome wants microseconds
        if ev.get("type") == "span":
            d["ph"] = "X"
            d["dur"] = int(ev.get("dur", 0)) / 1000.0
        else:
            d["ph"] = "i"
            d["s"] = "p"  # instant event, process-scoped
        if ev.get("args"):
            d["args"] = dict(ev["args"])
        chrome.append(d)

    # -- skew / straggler stats over shared step ids -------------------------
    # step end time per (step id, lane), aligned clock — lanes, not
    # ranks, so a relaunched rank's second file can't clobber the first
    step_ends: Dict[object, Dict[str, int]] = {}
    step_starts: Dict[object, Dict[str, int]] = {}
    lane_rank = {r["label"]: r["rank"] for r in ranks}
    for ts, r, ev in aligned:
        if ev.get("cat") != "step" or ev.get("type") != "span":
            continue
        sid = (ev.get("args") or {}).get("step")
        if sid is None:
            continue
        step_starts.setdefault(sid, {})[r["label"]] = ts
        step_ends.setdefault(sid, {})[r["label"]] = \
            ts + int(ev.get("dur", 0))
    spreads, start_spreads = [], []
    straggler_counts: Dict[str, int] = {}
    per_step = {}
    for sid, ends in sorted(step_ends.items(), key=lambda kv: str(kv[0])):
        if len(ends) < 2:
            continue
        last = max(ends, key=lambda k: ends[k])
        spread = max(ends.values()) - min(ends.values())
        spreads.append(spread)
        starts = step_starts.get(sid, {})
        if len(starts) >= 2:
            start_spreads.append(max(starts.values()) - min(starts.values()))
        straggler_counts[last] = straggler_counts.get(last, 0) + 1
        per_step[str(sid)] = {"end_spread_ns": spread,
                              "straggler_rank": lane_rank[last]}

    # -- comm rollup (bytes / exposure by axes, across ranks) ----------------
    comm: Dict[str, dict] = {}
    for ts, r, ev in aligned:
        if ev.get("cat") != "comm" or ev.get("type") != "span":
            continue
        a = ev.get("args") or {}
        key = str(a.get("axes", "world"))
        c = comm.setdefault(key, {"calls": 0, "bytes": 0, "seconds": 0.0,
                                  "exposed_seconds": 0.0,
                                  "overlapped_seconds": 0.0})
        c["calls"] += 1
        c["bytes"] += int(a.get("bytes", 0))
        c["seconds"] += int(ev.get("dur", 0)) / 1e9
        c["exposed_seconds"] += float(a.get("exposed_s", 0.0))
        c["overlapped_seconds"] += float(a.get("overlapped_s", 0.0))

    _ref_offset = min(ranks,
                      key=lambda x: (x["rank"], x["path"]))["offset"]
    summary = {
        "trace_dir": os.path.abspath(trace_dir),
        "ranks": sorted({r["rank"] for r in ranks}),
        "files": [os.path.basename(r["path"]) for r in ranks],
        "events": len(aligned),
        # offsets are relative to the LOWEST rank's (first) lane — file
        # order is lexicographic: trace_rank10_* sorts before
        # trace_rank2_*, so file order must not pick the reference
        "clock_offsets_ns": {r["label"]: r["offset"] - _ref_offset
                             for r in ranks},
        "steps_compared": len(spreads),
        "skew": {
            "step_end_spread_ns": {
                "mean": (sum(spreads) / len(spreads)) if spreads else 0.0,
                "max": max(spreads) if spreads else 0,
                "p50": _percentile([float(s) for s in spreads], 0.5),
            },
            "step_start_spread_ns_max": (max(start_spreads)
                                         if start_spreads else 0),
        },
        "straggler_counts": {str(k): v
                             for k, v in sorted(straggler_counts.items())},
        "per_step": per_step,
        "comm_by_axes": comm,
    }
    if goodput:
        summary["goodput"] = _goodput_rollup(ranks, aligned)
    if requests:
        summary["requests"] = _request_rollup(aligned)

    out_trace = out_trace or os.path.join(trace_dir, "merged_trace.json")
    out_summary = out_summary or os.path.join(trace_dir,
                                              "merge_summary.json")
    with open(out_trace, "w") as f:
        json.dump({"traceEvents": chrome, "displayTimeUnit": "ms"}, f)
    with open(out_summary, "w") as f:
        json.dump(summary, f, indent=1)
    summary["out_trace"] = out_trace
    summary["out_summary"] = out_summary
    return summary


def _main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.trace",
        description="cross-rank trace tools")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="merge per-rank trace files onto "
                        "one clock; emit chrome trace + skew summary")
    mp.add_argument("trace_dir")
    mp.add_argument("--out", default=None, help="chrome trace output path")
    mp.add_argument("--summary", default=None, help="summary JSON path")
    mp.add_argument("--goodput", action="store_true",
                    help="reclassify merged step spans into the goodput "
                         "ledger bins (offline job_goodput_fraction)")
    mp.add_argument("--requests", action="store_true",
                    help="group serving spans by W3C trace id across "
                         "lanes; emit a per-request summary (ttft, itl "
                         "percentiles, preemptions, KV block-seconds)")
    args = ap.parse_args(argv)
    if args.cmd == "merge":
        s = merge(args.trace_dir, out_trace=args.out,
                  out_summary=args.summary, goodput=args.goodput,
                  requests=args.requests)
        keys = ["ranks", "events", "steps_compared", "skew",
                "straggler_counts", "out_trace", "out_summary"]
        if args.goodput:
            keys.append("goodput")
        if args.requests:
            keys.append("requests")
        print(json.dumps({k: s[k] for k in keys}, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
