"""Collective-communication tracing.

Every collective in ``distributed/collective.py`` runs under
:func:`comm_scope`, which (1) emits a profiler RecordEvent span tagged with
group axes and payload bytes (rendered as a dedicated "collectives" lane +
counter events in the chrome-trace export), (2) bumps per-op registry
counters (``comm_bytes_total`` / ``comm_calls_total`` /
``comm_seconds_total``) that :class:`StepTimer` diffs into per-step comm
volume, and (3) feeds the flight recorder's ring so a postmortem shows the
last collectives in flight.

The span measures *host-side* time: on the compiled path that is trace
time (the collective itself is an XLA op fused into the step program);
eager/shard_map re-traces record every call. Bytes are per-shard payload
bytes — shape × itemsize of the local operand — which is the quantity a
per-step comm-volume counter wants.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional, Sequence

from . import flight_recorder
from .metrics import get_registry

__all__ = ["comm_scope", "comm_event", "payload_bytes", "comm_totals"]


_metrics_cache = None

#: Resilience seams (docs/RESILIENCE.md), installed from outside so this
#: hot path never imports the resilience package: a
#: ``resilience.Watchdog`` with ``watch_collectives()`` active arms a
#: deadline around every span; ``resilience.chaos.refresh()`` installs a
#: hang-injection hook. Both are one module-attribute read when unused.
_collective_watchdog = None
_chaos_hook = None


def _metrics():
    """The three per-collective counters, resolved once (they live in the
    default registry for the process's lifetime — no reason to take the
    registry lock on every collective)."""
    global _metrics_cache
    if _metrics_cache is None:
        reg = get_registry()
        _metrics_cache = (
            reg.counter("comm_bytes_total",
                        "payload bytes moved by collectives"),
            reg.counter("comm_calls_total", "collective invocations"),
            reg.counter("comm_seconds_total",
                        "host-side seconds inside collectives"))
    return _metrics_cache


def payload_bytes(x) -> int:
    """Per-shard payload bytes of a tensor / jax array / tracer / pytree
    list; 0 when the size cannot be determined (object collectives pass an
    explicit byte count instead)."""
    if x is None:
        return 0
    if isinstance(x, (list, tuple)):
        return sum(payload_bytes(e) for e in x)
    data = getattr(x, "data", x)  # Tensor -> jax array
    shape = getattr(data, "shape", None)
    dtype = getattr(data, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for s in shape:
        try:
            n *= int(s)
        except TypeError:
            return 0  # symbolic dim
    try:
        import numpy as np
        return n * int(np.dtype(dtype).itemsize)
    except Exception:
        return 0


def _axes_label(axes: Sequence[str]) -> str:
    axes = tuple(axes)
    return "x".join(axes) if axes else "world"


def _emit(op: str, axes_label: str, nbytes: int, t0: int, t1: int,
          extra: Optional[dict] = None):
    b, c, s = _metrics()
    b.inc(nbytes, op=op, axes=axes_label)
    c.inc(1, op=op, axes=axes_label)
    s.inc((t1 - t0) / 1e9, op=op, axes=axes_label)
    args = {"bytes": nbytes, "axes": axes_label}
    if extra:
        args.update(extra)
    from paddle_tpu import profiler
    profiler._emit_event(f"comm::{op}", t0, t1,
                         tid=threading.get_ident(), args=args, cat="comm")
    flight_recorder.record(flight_recorder.KIND_COMM, f"{op}@{axes_label}",
                           t0, t1, tid=threading.get_ident(), aux=nbytes,
                           args=args)


@contextlib.contextmanager
def comm_scope(op: str, axes: Sequence[str], payload=None,
               nbytes: Optional[int] = None, extra: Optional[dict] = None):
    """Span around one collective. Records even when the body raises — a
    failed collective is exactly what the flight recorder must show. A
    collective-armed watchdog puts its deadline around the whole span
    (chaos-injected hangs included: a wedged collective is precisely the
    event the deadline exists to catch)."""
    nbytes = payload_bytes(payload) if nbytes is None else int(nbytes)
    axes_label = _axes_label(axes)
    wd = _collective_watchdog
    token = None if wd is None else wd.arm(
        f"collective:{op}@{axes_label}", wd.collective_timeout)
    t0 = time.perf_counter_ns()
    try:
        hook = _chaos_hook
        if hook is not None:
            hook(op, axes_label)
        yield
    finally:
        if wd is not None:
            wd.disarm(token)
        _emit(op, axes_label, nbytes, t0, time.perf_counter_ns(), extra)


def comm_event(op: str, axes: Sequence[str], payload=None,
               nbytes: Optional[int] = None, extra: Optional[dict] = None):
    """Instantaneous comm record (for calls that fail fast, e.g. the
    unsupported raw send/recv): counters + flight recorder, zero span."""
    nbytes = payload_bytes(payload) if nbytes is None else int(nbytes)
    t = time.perf_counter_ns()
    _emit(op, _axes_label(axes), nbytes, t, t, extra)


def comm_totals(registry=None) -> dict:
    """(bytes, calls, seconds) summed over every op/axes label — the
    snapshot StepTimer diffs per step."""
    reg = registry or get_registry()
    out = {}
    for name in ("comm_bytes_total", "comm_calls_total",
                 "comm_seconds_total"):
        m = reg.get(name)
        out[name] = m.total() if m is not None else 0.0
    return out
