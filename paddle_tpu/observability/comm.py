"""Collective-communication tracing + exposure accounting.

Every collective in ``distributed/collective.py`` runs under
:func:`comm_scope`, which (1) emits a profiler RecordEvent span tagged with
group axes and payload bytes (rendered as a dedicated "collectives" lane +
counter events in the chrome-trace export), (2) bumps per-op registry
counters (``comm_bytes_total`` / ``comm_calls_total`` /
``comm_seconds_total``) that :class:`StepTimer` diffs into per-step comm
volume, and (3) feeds the flight recorder's ring so a postmortem shows the
last collectives in flight.

**Exposure accounting** (the attribution layer's signal, and the
before/after metric for all-reduce bucketing / comm-overlap work): code
that is actively computing wraps itself in :func:`compute_scope`
(``jit.TrainStep`` does), and every comm span classifies its wall time
against those compute intervals — the part that ran concurrently with
compute is *overlapped*, the remainder is *exposed* (the step got longer
because of it). Accumulated per axis-group into
``comm_exposed_seconds_total`` / ``comm_overlapped_seconds_total``, and
attached to each span's args (``exposed_s`` / ``overlapped_s``) for the
trace layer.

The span measures *host-side* time: on the compiled path that is trace
time (the collective itself is an XLA op fused into the step program);
eager/shard_map re-traces record every call. Bytes are per-shard payload
bytes — shape × itemsize of the local operand — which is the quantity a
per-step comm-volume counter wants.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time
from typing import Optional, Sequence

from . import flight_recorder, trace
from .metrics import get_registry

__all__ = ["comm_scope", "comm_event", "payload_bytes", "comm_totals",
           "compute_scope"]


_metrics_cache = None

#: Resilience seams (docs/RESILIENCE.md), installed from outside so this
#: hot path never imports the resilience package: a
#: ``resilience.Watchdog`` with ``watch_collectives()`` active arms a
#: deadline around every span; ``resilience.chaos.refresh()`` installs a
#: hang-injection hook. Both are one module-attribute read when unused.
_collective_watchdog = None
_chaos_hook = None


def _metrics():
    """The per-collective counters, resolved once (they live in the
    default registry for the process's lifetime — no reason to take the
    registry lock on every collective)."""
    global _metrics_cache
    if _metrics_cache is None:
        reg = get_registry()
        _metrics_cache = (
            reg.counter("comm_bytes_total",
                        "payload bytes moved by collectives"),
            reg.counter("comm_calls_total", "collective invocations"),
            reg.counter("comm_seconds_total",
                        "host-side seconds inside collectives"),
            reg.counter("comm_exposed_seconds_total",
                        "collective seconds NOT overlapped with compute "
                        "(the step got longer by this much), by axes"),
            reg.counter("comm_overlapped_seconds_total",
                        "collective seconds that ran concurrently with a "
                        "compute_scope, by axes"))
    return _metrics_cache


class _ComputeTracker:
    """Bounded record of recent compute intervals (perf_counter_ns).

    ``compute_scope`` regions push intervals here; a finishing comm span
    asks how much of its own window intersected them. Memory is bounded
    (a deque of the most recent closed intervals) — exposure is a
    per-step quantity, so anything older than the current step's window
    is irrelevant by the time it rotates out.
    """

    def __init__(self, keep: int = 512):
        self._lock = threading.Lock()
        self._open: dict = {}               # token -> start_ns
        self._closed = collections.deque(maxlen=keep)  # (start, end)
        self._tokens = itertools.count()

    def begin(self) -> int:
        token = next(self._tokens)
        with self._lock:
            self._open[token] = time.perf_counter_ns()
        return token

    def end(self, token: int):
        now = time.perf_counter_ns()
        with self._lock:
            start = self._open.pop(token, None)
            if start is not None:
                self._closed.append((start, now))

    def overlap_ns(self, t0: int, t1: int) -> int:
        """Nanoseconds of [t0, t1] covered by the UNION of compute
        intervals. Compute regions can nest/overlap across threads, so
        intervals are merged before measuring — two half-covering
        regions must not add up to "fully overlapped"."""
        if t1 <= t0:
            return 0
        now = time.perf_counter_ns()
        with self._lock:
            # prune intervals that ended before this span started —
            # comm spans arrive in (monotonic) time order, so they can
            # never intersect a later query; without this, a full deque
            # pays a 512-element copy+sort per collective forever.
            # _closed is appended in end-time order, so popleft is safe.
            while self._closed and self._closed[0][1] < t0:
                self._closed.popleft()
            intervals = list(self._closed) + \
                [(s, now) for s in self._open.values()]
        intervals.sort()
        total = 0
        cur_s = cur_e = None
        for s, e in intervals:
            if cur_e is None or s > cur_e:
                if cur_e is not None:
                    total += max(0, min(t1, cur_e) - max(t0, cur_s))
                cur_s, cur_e = s, e
            else:
                cur_e = max(cur_e, e)
        if cur_e is not None:
            total += max(0, min(t1, cur_e) - max(t0, cur_s))
        return min(total, t1 - t0)


_compute = _ComputeTracker()


@contextlib.contextmanager
def compute_scope():
    """Mark the caller as actively computing: any comm span that runs
    concurrently with this region counts as *overlapped* rather than
    *exposed*. Entered by ``jit.TrainStep`` around the compiled step;
    background-collective machinery (all-reduce bucketing) relies on the
    classification this enables."""
    token = _compute.begin()
    try:
        yield
    finally:
        _compute.end(token)


def payload_bytes(x) -> int:
    """Per-shard payload bytes of a tensor / jax array / tracer / pytree
    list; 0 when the size cannot be determined (object collectives pass an
    explicit byte count instead)."""
    if x is None:
        return 0
    if isinstance(x, (list, tuple)):
        return sum(payload_bytes(e) for e in x)
    data = getattr(x, "data", x)  # Tensor -> jax array
    shape = getattr(data, "shape", None)
    dtype = getattr(data, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for s in shape:
        try:
            n *= int(s)
        except TypeError:
            return 0  # symbolic dim
    try:
        import numpy as np
        return n * int(np.dtype(dtype).itemsize)
    except Exception:
        return 0


def _axes_label(axes: Sequence[str]) -> str:
    axes = tuple(axes)
    return "x".join(axes) if axes else "world"


def _emit(op: str, axes_label: str, nbytes: int, t0: int, t1: int,
          extra: Optional[dict] = None):
    b, c, s, exp, ovl = _metrics()
    b.inc(nbytes, op=op, axes=axes_label)
    c.inc(1, op=op, axes=axes_label)
    s.inc((t1 - t0) / 1e9, op=op, axes=axes_label)
    # exposure classification: the part of this span concurrent with a
    # compute_scope is overlapped; the rest lengthened the step (exposed)
    overlapped_ns = _compute.overlap_ns(t0, t1)
    exposed_ns = (t1 - t0) - overlapped_ns
    exp.inc(exposed_ns / 1e9, axes=axes_label)
    ovl.inc(overlapped_ns / 1e9, axes=axes_label)
    args = {"bytes": nbytes, "axes": axes_label,
            "exposed_s": exposed_ns / 1e9,
            "overlapped_s": overlapped_ns / 1e9}
    if extra:
        args.update(extra)
    from paddle_tpu import profiler
    profiler._emit_event(f"comm::{op}", t0, t1,
                         tid=threading.get_ident(), args=args, cat="comm")
    flight_recorder.record(flight_recorder.KIND_COMM, f"{op}@{axes_label}",
                           t0, t1, tid=threading.get_ident(), aux=nbytes,
                           args=args)
    trace.span("comm", f"{op}@{axes_label}", t0, t1,
               tid=threading.get_ident(), args=args)


@contextlib.contextmanager
def comm_scope(op: str, axes: Sequence[str], payload=None,
               nbytes: Optional[int] = None, extra: Optional[dict] = None):
    """Span around one collective. Records even when the body raises — a
    failed collective is exactly what the flight recorder must show. A
    collective-armed watchdog puts its deadline around the whole span
    (chaos-injected hangs included: a wedged collective is precisely the
    event the deadline exists to catch)."""
    nbytes = payload_bytes(payload) if nbytes is None else int(nbytes)
    axes_label = _axes_label(axes)
    wd = _collective_watchdog
    token = None if wd is None else wd.arm(
        f"collective:{op}@{axes_label}", wd.collective_timeout)
    t0 = time.perf_counter_ns()
    try:
        hook = _chaos_hook
        if hook is not None:
            hook(op, axes_label)
        yield
    finally:
        if wd is not None:
            wd.disarm(token)
        _emit(op, axes_label, nbytes, t0, time.perf_counter_ns(), extra)


def comm_event(op: str, axes: Sequence[str], payload=None,
               nbytes: Optional[int] = None, extra: Optional[dict] = None):
    """Instantaneous comm record (for calls that fail fast, e.g. the
    unsupported raw send/recv): counters + flight recorder, zero span."""
    nbytes = payload_bytes(payload) if nbytes is None else int(nbytes)
    t = time.perf_counter_ns()
    _emit(op, _axes_label(axes), nbytes, t, t, extra)


def comm_totals(registry=None) -> dict:
    """(bytes, calls, seconds, exposed, overlapped) summed over every
    label set — the snapshot StepTimer diffs per step."""
    reg = registry or get_registry()
    out = {}
    for name in ("comm_bytes_total", "comm_calls_total",
                 "comm_seconds_total", "comm_exposed_seconds_total",
                 "comm_overlapped_seconds_total"):
        m = reg.get(name)
        out[name] = m.total() if m is not None else 0.0
    return out


# the comm families are core telemetry: register them eagerly so scrapes
# and ``bench.py --emit-metrics`` show them (at zero) even before the
# first collective runs
_metrics()
