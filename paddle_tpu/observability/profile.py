"""On-demand device profiler capture — bounded ``jax.profiler`` windows.

Production jobs rarely run under the full ``paddle_tpu.profiler``; what
they need is a *small, bounded* device-trace window cut out of a live
run, on demand. Three entry points, all writing under
``PADDLE_TPU_TRACE_DIR`` (default ``/tmp/paddle_tpu_trace``):

- ``PADDLE_TPU_PROFILE_AT_STEP=<start>:<stop>`` — the hapi fit loop
  arms a :class:`StepWindow` that starts the capture entering step
  ``start`` and stops it after step ``stop`` (1-based, inclusive).
- ``POST /debug/profile?seconds=N`` on the serving HTTP server —
  bounded (≤ :data:`MAX_CAPTURE_SECONDS`), one capture at a time
  (``409`` while one is live), stopped by a background timer.
- ``python bench.py --profile`` — a capture window around a few
  committed-geometry train steps.

One capture at a time, process-wide: ``jax.profiler`` supports a single
active trace, so :func:`start_capture` raises :class:`CaptureBusy` when
a window is already open (the server maps that to ``409``). The
start/stop calls go through module-level seams (``_start_trace`` /
``_stop_trace``) so tests exercise the arming logic without a real
device trace. Arming never touches the jit layer — a profiler window
cannot retrace anything (the compile-once guard tests pin this).

Docs: docs/OBSERVABILITY.md#device-profiler.
"""
from __future__ import annotations

import os
import threading
import time
import warnings
from typing import Optional, Tuple

__all__ = ["CaptureBusy", "MAX_CAPTURE_SECONDS", "bound_seconds",
           "capture_active", "start_capture", "stop_capture",
           "capture_for", "start_timed_capture", "StepWindow",
           "step_window_from_env"]

#: fit-loop capture window, ``<start>:<stop>`` (1-based step ids,
#: inclusive)
ENV_PROFILE_AT_STEP = "PADDLE_TPU_PROFILE_AT_STEP"

#: hard ceiling on one on-demand capture — device traces are large and
#: the serving endpoint must stay abuse-proof
MAX_CAPTURE_SECONDS = 120.0


class CaptureBusy(RuntimeError):
    """A capture window is already open (one at a time, process-wide)."""


def _start_trace(path: str):  # seam — tests swap this out
    import jax
    jax.profiler.start_trace(path)


def _stop_trace():  # seam — tests swap this out
    import jax
    jax.profiler.stop_trace()


_lock = threading.Lock()
_active_dir: Optional[str] = None


def trace_dir() -> str:
    return os.environ.get("PADDLE_TPU_TRACE_DIR", "/tmp/paddle_tpu_trace")


def bound_seconds(seconds) -> float:
    """Validate + clamp a requested capture duration. Raises
    ``ValueError`` on garbage; silently clamps overlong requests to
    :data:`MAX_CAPTURE_SECONDS` (bounded is the contract, not an
    error)."""
    s = float(seconds)
    if not (s > 0):  # rejects 0, negatives AND NaN in one comparison
        raise ValueError(f"capture seconds must be > 0, got {seconds!r}")
    return min(s, MAX_CAPTURE_SECONDS)


def capture_active() -> Optional[str]:
    """The live capture's output directory, or None."""
    return _active_dir


def start_capture(label: str = "ondemand") -> str:
    """Open a device-trace window; returns the capture directory.
    Raises :class:`CaptureBusy` when one is already open."""
    global _active_dir
    with _lock:
        if _active_dir is not None:
            raise CaptureBusy(
                f"device profiler capture already running "
                f"({_active_dir})")
        out = os.path.join(trace_dir(),
                           f"profile_{label}_{int(time.time() * 1e3)}")
        os.makedirs(out, exist_ok=True)
        _start_trace(out)
        _active_dir = out
        return out


def stop_capture() -> Optional[str]:
    """Close the live window; returns its directory (None if none was
    open — stop is idempotent so timer threads and finally-blocks can
    both call it)."""
    global _active_dir
    with _lock:
        if _active_dir is None:
            return None
        out, _active_dir = _active_dir, None
        try:
            _stop_trace()
        except Exception:
            # a failed stop must not wedge the one-capture slot shut
            pass
        return out


def capture_for(seconds, label: str = "ondemand") -> str:
    """Blocking bounded capture (the bench.py path)."""
    s = bound_seconds(seconds)
    out = start_capture(label)
    try:
        time.sleep(s)
    finally:
        stop_capture()
    return out


def start_timed_capture(seconds, label: str = "serving") \
        -> Tuple[str, float]:
    """Non-blocking bounded capture (the HTTP endpoint's path): opens
    the window now, stops it from a daemon timer thread after
    ``seconds``. Returns ``(capture_dir, bounded_seconds)``."""
    s = bound_seconds(seconds)
    out = start_capture(label)

    def _stop_later():
        time.sleep(s)
        # only close OUR window — a capture that was stopped and
        # replaced before the timer fired must not be clipped
        if _active_dir == out:
            stop_capture()

    threading.Thread(target=_stop_later, daemon=True,
                     name="pt-profile-timer").start()
    return out, s


# ---------------------------------------------------------------------------
# fit-loop step window
# ---------------------------------------------------------------------------

class StepWindow:
    """Start/stop a capture across a step interval (1-based,
    inclusive). Driven per training step by the fit loop; ``close()``
    in the loop's finally so a window still open when training ends
    (stop > total steps, crash) is flushed, not lost."""

    def __init__(self, start: int, stop: int, label: str = "fit"):
        if start < 1 or stop < start:
            raise ValueError(
                f"profile window needs 1 <= start <= stop, got "
                f"{start}:{stop}")
        self.start = int(start)
        self.stop = int(stop)
        self.label = label
        self._dir: Optional[str] = None
        self._done = False

    @property
    def capture_dir(self) -> Optional[str]:
        return self._dir

    def on_step(self, step: int):
        """Called entering each step; opens/closes the window at the
        configured edges. A busy capture slot (another window live)
        skips this one with a warning instead of killing the fit."""
        if self._done:
            return
        if self._dir is None and self.start <= step <= self.stop:
            try:
                self._dir = start_capture(self.label)
            except CaptureBusy as e:
                warnings.warn(f"{ENV_PROFILE_AT_STEP} window skipped: {e}",
                              RuntimeWarning, stacklevel=2)
                self._done = True
                return
        elif self._dir is not None and step > self.stop:
            self.close()

    def close(self):
        if self._dir is not None:
            stop_capture()
            self._dir = self._dir  # path survives for callers/logs
        self._done = True


def step_window_from_env() -> Optional[StepWindow]:
    """Parse ``PADDLE_TPU_PROFILE_AT_STEP=<start>:<stop>`` (a single
    ``<step>`` means a one-step window). Malformed values warn and
    disarm — a typo must not take the training job down."""
    raw = os.environ.get(ENV_PROFILE_AT_STEP, "").strip()
    if not raw:
        return None
    try:
        if ":" in raw:
            a, b = raw.split(":", 1)
            return StepWindow(int(a), int(b))
        start = int(raw)
        return StepWindow(start, start)
    except ValueError as e:
        warnings.warn(
            f"ignoring malformed {ENV_PROFILE_AT_STEP}={raw!r}: {e}",
            RuntimeWarning, stacklevel=2)
        return None
