"""paddle_tpu.observability — production telemetry subsystem.

Four pieces (see docs/OBSERVABILITY.md):

- **metrics** — Counter/Gauge/Histogram registry with Prometheus-text and
  JSON exposition; env-gated HTTP exporter (``PADDLE_TPU_METRICS_PORT``).
- **step_timer** — per-step data/compute/collective decomposition,
  samples-or-tokens/sec and an MFU estimate (surfaced by the hapi
  ``StepTelemetry`` callback).
- **comm** — collective-communication tracing: every collective emits a
  tagged RecordEvent span (bytes + group axes), registry counters, and a
  flight-recorder entry.
- **flight_recorder** — always-on bounded ring of recent op/comm/step
  events dumped as postmortem JSON on crash/SIGTERM/SIGUSR1
  (``PADDLE_TPU_FLIGHT_RECORDER``).

Importing this package applies the env gates (a no-op when the vars are
unset), so ``import paddle_tpu`` alone arms the exporter/recorder in
production jobs.
"""
from . import comm, flight_recorder, metrics, step_timer  # noqa: F401
from .comm import comm_scope, comm_totals, payload_bytes  # noqa: F401
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, get_registry,
    maybe_start_exporter, start_exporter,
)
from .step_timer import StepTimer, peak_flops  # noqa: F401

__all__ = ["metrics", "step_timer", "comm", "flight_recorder",
           "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "get_registry", "start_exporter", "maybe_start_exporter",
           "StepTimer", "peak_flops", "comm_scope", "comm_totals",
           "payload_bytes"]

# env-gated side effects: both are no-ops unless their env var is set
metrics.maybe_start_exporter()
flight_recorder.maybe_enable_from_env()
