"""paddle_tpu.observability — production telemetry subsystem.

Eight pieces (see docs/OBSERVABILITY.md):

- **metrics** — Counter/Gauge/Histogram registry with Prometheus-text and
  JSON exposition; env-gated HTTP exporter (``PADDLE_TPU_METRICS_PORT``);
  label-cardinality guard (``PADDLE_TPU_METRICS_MAX_LABELSETS``).
- **step_timer** — per-step data/compute/collective decomposition,
  samples-or-tokens/sec and an MFU estimate (surfaced by the hapi
  ``StepTelemetry`` callback).
- **comm** — collective-communication tracing: every collective emits a
  tagged RecordEvent span (bytes + group axes), registry counters, and a
  flight-recorder entry; exposure accounting classifies each span's wall
  time as overlapped-with-compute vs exposed.
- **trace** — structured per-rank span files (step phases, comm spans,
  serving request chains) plus the cross-rank merge tool
  (``python -m paddle_tpu.observability.trace merge``), env-gated by
  ``PADDLE_TPU_TRACE_SPANS=<dir>``.
- **attribution** — phase-level step attribution (data / embedding+layers
  / loss-head / optimizer / exposed-collective) with cost-analysis FLOPs
  and an MFU-per-phase table (``bench.py --attribution``).
- **flight_recorder** — always-on bounded ring of recent
  op/comm/step/ckpt/data events dumped as postmortem JSON on
  crash/SIGTERM/SIGUSR1 (``PADDLE_TPU_FLIGHT_RECORDER``).
- **memory** — HBM observability: per-executable ``memory_report()``
  accounting, the subsystem memory ledger behind the ``hbm_*`` gauges,
  and the RESOURCE_EXHAUSTED postmortem path
  (``PADDLE_TPU_HBM_HEADROOM_WARN``).
- **profile** — bounded on-demand ``jax.profiler`` capture windows
  (``PADDLE_TPU_PROFILE_AT_STEP``, ``POST /debug/profile``,
  ``bench.py --profile``).
- **fleet** — live cross-rank telemetry bus over the job TCPStore:
  per-step heartbeats, a rank-0 ``FleetAggregator`` with online
  straggler detection, and the ``/fleetz`` JSON rollup
  (``PADDLE_TPU_FLEET``).
- **goodput** — the per-rank :class:`GoodputLedger` classifying all
  wall-clock into productive/compile/checkpoint/data-stall/exposed-
  collective/restart/rollback bins (``goodput_seconds_total{bin}``,
  ``job_goodput_fraction``).
- **numerics** — in-graph tensor-health telemetry: the ``numerics.tap``
  model seam, sampled instrumented train-step twin (``numerics_*``
  families, ``PADDLE_TPU_NUMERICS``), NaN provenance JSON on NaNGuard
  rollbacks, and calibration-grade per-tap activation-range sketches.
- **requests** — per-request serving ledger (queue wait, prefill/cached/
  decode tokens, ITL samples, KV block-seconds), W3C ``traceparent``
  helpers, tail-sampled exemplar log (``PADDLE_TPU_REQUEST_LOG_DIR``),
  and the ``/statusz`` payload/renderer (``PADDLE_TPU_REQUEST_LEDGER``).
- **slo** — declarative serving SLO targets (``PADDLE_TPU_SLO_*``) with
  multi-window burn-rate gauges (``serving_slo_*``) computed online
  from ledger completions.

Importing this package applies the env gates (a no-op when the vars are
unset), so ``import paddle_tpu`` alone arms the exporter/recorder/tracer
in production jobs.
"""
from . import (  # noqa: F401
    comm, fleet, flight_recorder, goodput, memory, metrics, numerics,
    profile, requests, slo, step_timer, trace,
)
from .comm import (  # noqa: F401
    comm_scope, comm_totals, compute_scope, payload_bytes,
)
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, get_registry,
    maybe_start_exporter, start_exporter,
)
from .step_timer import StepTimer, peak_flops  # noqa: F401

__all__ = ["metrics", "step_timer", "comm", "flight_recorder", "trace",
           "memory", "profile", "fleet", "goodput", "numerics",
           "requests", "slo",
           "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "get_registry", "start_exporter", "maybe_start_exporter",
           "StepTimer", "peak_flops", "comm_scope", "comm_totals",
           "compute_scope", "payload_bytes"]

# env-gated side effects: all are no-ops unless their env var is set
metrics.maybe_start_exporter()
flight_recorder.maybe_enable_from_env()
trace.maybe_enable_from_env()
fleet.maybe_enable_from_env()
