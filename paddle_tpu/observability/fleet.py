"""Fleet telemetry bus — live cross-rank heartbeats over the job TCPStore.

Every cross-rank question used to be answered offline: ``trace merge``
reconstructs straggler tables from span files after the job is dead.
This module answers them *while the job runs*:

- each rank publishes a compact **heartbeat** per step (step id,
  step/data/collective/exposed seconds, HBM in use, last
  flight-recorder event kind, goodput bins) to the job TCPStore under
  the epoch-namespaced key ``__fleet/{epoch}/hb/{rank}`` — the same
  control plane the preemption/rendezvous layers already ride;
- rank 0 runs a :class:`FleetAggregator` daemon thread folding the
  heartbeats into job-wide rollups: rank liveness (a heartbeat older
  than ``PADDLE_TPU_FLEET_STALE_S`` flips the rank to ``missing``), a
  rolling-median step time, **online straggler detection** (a rank
  > k×median for M consecutive *new* heartbeats raises
  ``fleet_straggler{rank}`` and a once-per-incident flight-recorder
  event), and the fleet-wide ``job_goodput_fraction``;
- the whole picture is served as JSON on ``/fleetz`` (metrics exporter
  and serving ``Server``) via :func:`fleetz_snapshot`, which degrades
  to a local-ledger-only view on ranks without an aggregator.

Heartbeat lanes are keyed **by rank**, so a crashed-and-relaunched rank
(new pid, same rank id) replaces its lane instead of duplicating it.

The publish path is a module-global seam (``_publisher``), read once per
step by :meth:`StepTimer.end_step` — zero cost when the bus is off.
Arming is env-gated (:func:`maybe_enable_from_env`): on by default when
``PADDLE_MASTER`` names a job store, killed by ``PADDLE_TPU_FLEET=0``.
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import deque
from typing import Optional

from . import flight_recorder, goodput
from .metrics import MetricsRegistry, get_registry

__all__ = ["HeartbeatPublisher", "FleetAggregator", "fleet_metrics",
           "publish_step", "depart", "note_step", "last_step_age_seconds",
           "healthz_fields", "fleetz_snapshot", "recent_heartbeats",
           "enable", "disable", "maybe_enable_from_env"]

#: last N heartbeats kept locally for postmortem appendices
_RECENT = 32


def _rank() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    except ValueError:
        return 0


def _world() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
    except ValueError:
        return 1


def _epoch() -> str:
    return os.environ.get("PADDLE_RESTART_EPOCH", "0")


def _hb_key(rank: int) -> str:
    return f"__fleet/{_epoch()}/hb/{rank}"


def job_id() -> str:
    """The operator-visible job identity: ``PADDLE_TPU_JOB_ID``, falling
    back to the store address (every rank of a job shares it)."""
    return os.environ.get("PADDLE_TPU_JOB_ID") or \
        os.environ.get("PADDLE_MASTER", "local")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def fleet_metrics(registry: Optional[MetricsRegistry] = None) -> dict:
    """The ``fleet_*`` metric families (created on first use) — the
    docs-drift gate instantiates this accessor."""
    r = registry or get_registry()
    return {
        "heartbeats": r.counter(
            "fleet_heartbeats_total", "heartbeat records published"),
        "straggler": r.gauge(
            "fleet_straggler",
            "1 while the rank is flagged as a straggler, by rank"),
        "live": r.gauge("fleet_ranks_live",
                        "ranks with a fresh heartbeat"),
        "missing": r.gauge(
            "fleet_ranks_missing",
            "ranks whose last heartbeat is past the staleness window"),
        "departed": r.gauge(
            "fleet_ranks_departed",
            "ranks retired at a consensus resize boundary (planned "
            "departure, not a failure)"),
        "median": r.gauge("fleet_step_seconds_median",
                          "fleet-wide rolling-median step time"),
    }


def _default_store():
    from paddle_tpu.distributed.tcp_store import job_store
    return job_store()


class HeartbeatPublisher:
    """Per-rank heartbeat emitter. ``store`` is anything with
    ``set(key, value)`` (the job TCPStore in production, a dict-backed
    fake in tests); it is resolved lazily so constructing the publisher
    never blocks on a socket."""

    def __init__(self, store=None, rank: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None):
        self._store = store
        self.rank = _rank() if rank is None else int(rank)
        self._m = fleet_metrics(registry)
        self.recent: deque = deque(maxlen=_RECENT)
        self._broken = False

    def _resolve_store(self):
        if self._store is None:
            self._store = _default_store()
        return self._store

    def publish(self, step: int, stats: dict):
        """Fold the StepTimer's per-step stats (plus HBM, last FR event
        kind, and the goodput snapshot) into one compact record and set
        it on the bus. Never raises — a dead store must not fail a
        training step (the aggregator sees the rank go ``missing``)."""
        rec = {
            "rank": self.rank, "pid": os.getpid(), "step": int(step),
            "t": time.time(),
            "step_time_s": round(float(stats.get("step_time_s", 0.0)), 6),
            "data_time_s": round(float(stats.get("data_time_s", 0.0)), 6),
            "collective_time_s": round(
                float(stats.get("collective_time_s", 0.0)), 6),
            "exposed_collective_time_s": round(
                float(stats.get("exposed_collective_time_s", 0.0)), 6),
            "hbm_in_use": _hbm_in_use(),
            "last_event": _last_event_kind(),
        }
        snap = goodput.snapshot()
        if snap is not None:
            rec["goodput"] = {"bins": snap["bins"],
                              "wall_s": snap["wall_s"],
                              "fraction": snap["job_goodput_fraction"]}
        self.recent.append(rec)
        self._set(rec)

    def depart(self, step: int, reason: str = "resize"):
        """Publish the rank's FINAL heartbeat, marked ``departed`` — a
        planned exit at a consensus resize boundary. The aggregator
        retires the lane (status ``departed``) instead of aging it into
        ``missing``, so a downsize raises no straggler/missing alarms."""
        rec = {"rank": self.rank, "pid": os.getpid(), "step": int(step),
               "t": time.time(), "departed": True, "reason": str(reason)}
        self.recent.append(rec)
        self._set(rec)

    def _set(self, rec: dict):
        if self._broken:
            return
        try:
            self._resolve_store().set(_hb_key(self.rank), json.dumps(rec))
            self._m["heartbeats"].inc()
        except Exception:
            # one warning, then stay quiet: the bus is telemetry, the
            # step loop is the product
            self._broken = True
            import warnings
            warnings.warn("[fleet] heartbeat publish failed; bus disabled "
                          "for this process", RuntimeWarning, stacklevel=2)


def _hbm_in_use() -> int:
    from . import memory
    try:
        snap = memory.snapshot()
        # CPU backends report no bytes_in_use; the named-owner ledger
        # total is the best available proxy there
        return int(snap.get("bytes_in_use") or snap.get("named_bytes") or 0)
    except Exception:
        return 0


def _last_event_kind() -> Optional[str]:
    return flight_recorder.last_kind()


class FleetAggregator:
    """Rank 0's folding thread (usable un-started, via :meth:`poll_once`,
    for deterministic tests).

    Lanes are keyed by rank — a relaunched rank's new-pid heartbeat
    *replaces* its lane. A lane whose heartbeat is older than
    ``stale_s`` reports ``status="missing"`` (the record is kept: the
    postmortem wants the rank's last known state). Straggler detection
    only advances on *new* heartbeats (step id moved), so a slow poller
    never double-counts one record."""

    def __init__(self, store=None, world: Optional[int] = None,
                 interval: Optional[float] = None,
                 stale_s: Optional[float] = None,
                 k: Optional[float] = None, m: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None):
        self._store = store
        self.world = _world() if world is None else int(world)
        self.interval = _env_float("PADDLE_TPU_FLEET_INTERVAL", 1.0) \
            if interval is None else float(interval)
        self.stale_s = _env_float("PADDLE_TPU_FLEET_STALE_S", 15.0) \
            if stale_s is None else float(stale_s)
        self.k = _env_float("PADDLE_TPU_FLEET_STRAGGLER_K", 1.5) \
            if k is None else float(k)
        self.m = int(_env_float("PADDLE_TPU_FLEET_STRAGGLER_STEPS", 3)) \
            if m is None else int(m)
        self._m = fleet_metrics(registry)
        self._lock = threading.Lock()
        self.lanes: dict = {}           # rank -> last parsed record
        self._seen_step: dict = {}      # rank -> last step id counted
        self._slow_streak: dict = {}    # rank -> consecutive slow steps
        self._departed_noted: set = set()  # lanes retired (FR event fired)
        self.stragglers: set = set()
        self.fleet_goodput: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _resolve_store(self):
        if self._store is None:
            self._store = _default_store()
        return self._store

    # -- one fold ----------------------------------------------------------
    def poll_once(self, now: Optional[float] = None) -> dict:
        """Read every rank's lane, update liveness/straggler/goodput
        state, refresh the ``fleet_*`` gauges; returns the rollup dict
        (what ``/fleetz`` serves). Store/parse failures degrade to the
        previous state — the aggregator must survive a dying job."""
        now = time.time() if now is None else now
        try:
            store = self._resolve_store()
            for rank in range(self.world):
                raw = store.get(_hb_key(rank))
                if not raw:
                    continue
                try:
                    rec = json.loads(raw)
                except (ValueError, TypeError):
                    continue  # torn/garbage write: keep the old lane
                with self._lock:
                    self.lanes[rank] = rec
        except Exception:
            pass  # store unreachable this tick: age-out still runs
        with self._lock:
            lanes = dict(self.lanes)
        live, missing, departed = [], [], []
        for rank, rec in lanes.items():
            if rec.get("departed"):
                # a planned resize exit: retire the lane — it must never
                # age into `missing` or trip the straggler detector
                departed.append(rank)
                if rank not in self._departed_noted:
                    self._departed_noted.add(rank)
                    self.stragglers.discard(rank)
                    self._m["straggler"].set(0, rank=rank)
                    t = time.time_ns()
                    flight_recorder.record(
                        flight_recorder.KIND_USER,
                        f"fleet_departed_rank{rank}", t, t, aux=rank,
                        args={"step": rec.get("step"),
                              "reason": rec.get("reason", "resize")})
                continue
            (missing if now - rec.get("t", 0) > self.stale_s
             else live).append(rank)
        self._detect_stragglers(lanes, live)
        self._fold_goodput(lanes)
        self._m["live"].set(len(live))
        self._m["departed"].set(len(departed))
        self._m["missing"].set(len(missing) +
                               max(self.world - len(lanes), 0))
        return self.rollup(now=now)

    def _detect_stragglers(self, lanes: dict, live: list):
        times = [lanes[r].get("step_time_s", 0.0) for r in live]
        times = [t for t in times if t > 0]
        if len(times) < 2:
            return
        median = statistics.median(times)
        self._m["median"].set(median)
        for rank in live:
            rec = lanes[rank]
            step = rec.get("step")
            if step is None or self._seen_step.get(rank) == step:
                continue  # no new heartbeat since the last fold
            self._seen_step[rank] = step
            slow = rec.get("step_time_s", 0.0) > self.k * median
            streak = self._slow_streak.get(rank, 0) + 1 if slow else 0
            self._slow_streak[rank] = streak
            if slow and streak >= self.m and rank not in self.stragglers:
                self.stragglers.add(rank)
                self._m["straggler"].set(1, rank=rank)
                t = time.time_ns()
                flight_recorder.record(
                    flight_recorder.KIND_USER, f"fleet_straggler_rank{rank}",
                    t, t, aux=rank,
                    args={"step_time_s": rec.get("step_time_s"),
                          "median_s": round(median, 6), "step": step})
            elif not slow and rank in self.stragglers:
                self.stragglers.discard(rank)
                self._m["straggler"].set(0, rank=rank)

    def _fold_goodput(self, lanes: dict):
        prod = wall = 0.0
        bins: dict = {}
        for rec in lanes.values():
            g = rec.get("goodput")
            if not g:
                continue
            wall += g.get("wall_s", 0.0)
            for b, v in g.get("bins", {}).items():
                bins[b] = bins.get(b, 0.0) + v
            prod += g.get("bins", {}).get("productive", 0.0)
        if wall > 0:
            frac = prod / wall
            self.fleet_goodput = {
                "bins": {b: round(v, 6) for b, v in bins.items()},
                "wall_s": round(wall, 6),
                "job_goodput_fraction": round(frac, 6)}
            goodput.goodput_metrics()["fraction"].set(frac)

    def rollup(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            lanes = dict(self.lanes)
        ranks = {}
        for rank, rec in sorted(lanes.items()):
            age = now - rec.get("t", now)
            if rec.get("departed"):
                status = "departed"
            elif age > self.stale_s:
                status = "missing"
            else:
                status = "live"
            ranks[str(rank)] = {
                **rec, "age_s": round(age, 3), "status": status,
                "straggler": rank in self.stragglers}
        return {"world": self.world, "ranks": ranks,
                "stragglers": sorted(self.stragglers),
                "goodput": self.fleet_goodput}

    # -- thread lifecycle --------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pt-fleet-aggregator", daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:
                pass  # next tick retries; the bus must outlive bad data

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)


# -- module seams (read by StepTimer / serving engine every step) ----------
_publisher: Optional[HeartbeatPublisher] = None
_aggregator: Optional[FleetAggregator] = None
_last_step_mono: Optional[float] = None


def note_step():
    """Stamp 'a step just finished' — feeds ``last_step_age_seconds`` on
    ``/healthz`` (train steps via StepTimer, serving via the engine)."""
    global _last_step_mono
    _last_step_mono = time.monotonic()


def last_step_age_seconds() -> Optional[float]:
    return None if _last_step_mono is None \
        else time.monotonic() - _last_step_mono


def publish_step(step: int, stats: dict):
    """StepTimer's per-step hook: one attribute read when the bus is
    off."""
    pub = _publisher
    if pub is not None:
        pub.publish(step, stats)


def depart(step: int, reason: str = "resize"):
    """Retire this rank's heartbeat lane (planned resize exit) — no-op
    when the bus is off."""
    pub = _publisher
    if pub is not None:
        pub.depart(step, reason=reason)


def recent_heartbeats() -> list:
    """The last N locally-published heartbeats (postmortem appendix)."""
    pub = _publisher
    return list(pub.recent) if pub is not None else []


def healthz_fields() -> dict:
    """The wedged-but-listening probe fields shared by the serving
    ``Server`` and the metrics exporter's ``/healthz``."""
    age = last_step_age_seconds()
    return {"rank": _rank(), "job_id": job_id(),
            "last_step_age_seconds":
                None if age is None else round(age, 3)}


def fleetz_snapshot() -> dict:
    """The ``/fleetz`` document. With an aggregator (rank 0): the full
    fleet rollup. Without: a local-only view (this rank's last
    heartbeat + goodput ledger), so the endpoint is useful on every
    rank and in single-process runs."""
    doc = {"job_id": job_id(), "epoch": _epoch(), "rank": _rank(),
           "unix_time": time.time(), **healthz_fields()}
    agg = _aggregator
    if agg is not None:
        agg.poll_once()
        doc.update(aggregator=True, **agg.rollup())
    else:
        pub = _publisher
        doc.update(aggregator=False, world=_world(),
                   ranks={}, stragglers=[], goodput=None)
        if pub is not None and pub.recent:
            doc["ranks"] = {str(pub.rank): pub.recent[-1]}
    local = goodput.snapshot()
    doc["local_goodput"] = local
    return doc


# -- arming ----------------------------------------------------------------
def enable(store=None, rank: Optional[int] = None,
           world: Optional[int] = None,
           start_aggregator: Optional[bool] = None):
    """Arm the bus: every rank gets a publisher; rank 0 (or
    ``start_aggregator=True``) also gets a polling aggregator."""
    global _publisher, _aggregator
    if _publisher is None:
        _publisher = HeartbeatPublisher(store=store, rank=rank)
    if start_aggregator is None:
        start_aggregator = _publisher.rank == 0
    if start_aggregator and _aggregator is None:
        _aggregator = FleetAggregator(store=store, world=world).start()
    return _publisher


def disable():
    global _publisher, _aggregator
    agg, _aggregator = _aggregator, None
    if agg is not None:
        agg.stop()
    _publisher = None


def maybe_enable_from_env():
    """Import-time gate: the bus arms itself in any job that has a
    control-plane store (``PADDLE_MASTER``), unless ``PADDLE_TPU_FLEET=0``;
    ``PADDLE_TPU_FLEET=1`` forces it on without a store (local fallback
    views only). Never raises — this runs at ``import paddle_tpu``."""
    flag = os.environ.get("PADDLE_TPU_FLEET", "").strip()
    if flag == "0":
        return None
    if flag not in ("1", "true", "on") and \
            not os.environ.get("PADDLE_MASTER"):
        return None
    try:
        return enable()
    except Exception:
        return None
