"""paddle.hub parity (reference: ``python/paddle/hapi/hub.py`` — load
models from a github/gitee repo's hubconf.py).

Zero-egress build: only ``source='local'`` works (a directory containing
``hubconf.py``); remote sources raise with a clear message.
"""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

_HUB_CONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUB_CONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUB_CONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source: str):
    if source != "local":
        raise RuntimeError(
            f"paddle.hub source='{source}' needs network access, which "
            "this build does not have; clone the repo and use "
            "source='local'")


def list(repo_dir: str, source: str = "local",
         force_reload: bool = False) -> List[str]:
    """Entry points exported by the repo's hubconf
    (reference: hub.py list)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False) -> str:
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__ or ""


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    """Instantiate ``model`` from the repo's hubconf
    (reference: hub.py load)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"hubconf has no entry point '{model}'; "
                         f"available: {list(repo_dir)}")
    return getattr(mod, model)(**kwargs)
