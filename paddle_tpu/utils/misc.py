"""paddle.utils small tools: deprecated decorator, try_import, dlpack,
download surface, run_check (reference: ``python/paddle/utils/``
``deprecated.py``, ``lazy_import.py``, ``dlpack.py``, ``download.py``,
``install_check.py``)."""
from __future__ import annotations

import functools
import warnings

__all__ = ["deprecated", "try_import", "to_dlpack", "from_dlpack",
           "get_weights_path_from_url", "run_check"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0):
    """Mark an API deprecated (reference: utils/deprecated.py) — warns
    once per call site; ``level=2`` raises instead."""
    def decorator(fn):
        msg = f"API '{fn.__qualname__}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f"; use '{update_to}' instead"
        if reason:
            msg += f" ({reason})"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        wrapper.__doc__ = f"(deprecated) {fn.__doc__ or ''}"
        return wrapper
    return decorator


def try_import(module_name: str):
    """Import or raise with install guidance (reference:
    utils/lazy_import.py try_import)."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            f"Failed importing {module_name}. This likely means the "
            f"package is not installed; this build cannot download "
            f"packages (no network egress).") from None


def to_dlpack(x):
    """Tensor → DLPack capsule (reference: utils/dlpack.py). The jax
    array itself implements ``__dlpack__``."""
    from paddle_tpu.core.tensor import Tensor
    arr = x.data if isinstance(x, Tensor) else x
    return arr.__dlpack__()


def from_dlpack(capsule):
    """DLPack capsule (or any ``__dlpack__`` object, e.g. a torch CPU
    tensor) → Tensor."""
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Tensor
    return Tensor(jnp.from_dlpack(capsule))


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    raise NotImplementedError(
        "weight download is unavailable in this build (no network "
        "egress); place the file locally and load it with paddle.load")


def run_check():
    """Install sanity check (reference: utils/install_check.py
    paddle.utils.run_check): runs one tiny compiled train step on the
    available device and reports."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn

    model = nn.Linear(4, 4)
    opt = pt.optimizer.SGD(learning_rate=0.1,
                           parameters=model.parameters())
    x = pt.to_tensor(np.ones((2, 4), np.float32))
    loss = pt.ops.mean(pt.ops.square(model(x)))
    loss.backward()
    opt.step()
    opt.clear_grad()
    dev = pt.get_device()
    print(f"PaddlePaddle(TPU-native) works on {dev}: one train step OK "
          f"(loss {float(loss.numpy()):.4f})")
    return True
