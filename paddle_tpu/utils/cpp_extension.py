"""Custom C++ op extension — the native extension seam.

Reference: ``python/paddle/utils/cpp_extension/`` (JIT ``load`` building a
.so) + ``paddle/fluid/framework/custom_operator.cc:746``
(RegisterOperatorWithMetaInfo: op registered from a compiled library with
infer-shape + grad functions).

TPU design: a custom C++ op runs as an XLA *host callback*
(``jax.pure_callback``) so it composes with jit/to_static, and its
gradient is wired through ``jax.custom_vjp`` onto the framework tape. This
is the host-side seam; device-side custom kernels are written in Pallas
(``paddle_tpu/ops/pallas/``) — the TPU analog of the reference's CUDA
custom ops.

Limitation (mirrors the reference, where a deployed model needs the
custom-op .so loaded in the serving process): host callbacks cannot be
*serialized* into a ``jit.save`` artifact (XLA export has no stable
encoding for them), so models containing ctypes custom ops deploy via
``to_static`` in-process, not via ``.pdmodel`` export. Pallas custom
kernels have no such restriction.

C ABI (the analog of ``paddle/extension.h``):

.. code-block:: c

    extern "C" void my_op(const float** ins, const int64_t** in_shapes,
                          const int32_t* in_ndims, int32_t n_in,
                          float** outs, const int64_t** out_shapes,
                          const int32_t* out_ndims, int32_t n_out);

The grad function (optional, named ``<op>_grad`` by convention) has the
same signature; it receives ``inputs + output_grads`` as its inputs and
writes one gradient per forward input.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["load", "CppExtension", "custom_op", "get_build_directory"]

_I64P = ctypes.POINTER(ctypes.c_int64)
_F32P = ctypes.POINTER(ctypes.c_float)
_SIG = [ctypes.POINTER(_F32P), ctypes.POINTER(_I64P),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.POINTER(_F32P), ctypes.POINTER(_I64P),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]

_build_lock = threading.Lock()


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.expanduser("~/.cache/paddle_tpu/extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Compiled-library handle; ``ops`` maps exported op names to python
    callables (populated by ``custom_op``)."""

    def __init__(self, name: str, lib: ctypes.CDLL, path: str):
        self.name = name
        self.lib = lib
        self.path = path
        self.ops = {}

    def __getattr__(self, item):
        ops = self.__dict__.get("ops", {})
        if item in ops:
            return ops[item]
        raise AttributeError(item)


def load(name: str, sources: Sequence[str], extra_cflags: Optional[List[str]]
         = None, extra_ldflags: Optional[List[str]] = None,
         build_directory: Optional[str] = None,
         verbose: bool = False) -> CppExtension:
    """JIT-compile C++ sources to a shared library and load it
    (reference: ``cpp_extension.load`` — same role, g++ instead of the
    setuptools/nvcc path)."""
    build = build_directory or get_build_directory()
    so = os.path.join(build, f"lib{name}.so")
    with _build_lock:
        newest_src = max(os.path.getmtime(s) for s in sources)
        if not os.path.exists(so) or os.path.getmtime(so) < newest_src:
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   *(extra_cflags or []), *sources, "-o",
                   so + f".tmp{os.getpid()}", *(extra_ldflags or [])]
            if verbose:
                print(" ".join(cmd))
            res = subprocess.run(cmd, capture_output=True, text=True)
            if res.returncode != 0:
                raise RuntimeError(
                    f"cpp_extension build failed:\n{res.stderr}")
            os.replace(so + f".tmp{os.getpid()}", so)
    return CppExtension(name, ctypes.CDLL(so), so)


def _bind(lib: ctypes.CDLL, symbol: str):
    fn = getattr(lib, symbol)
    fn.argtypes = _SIG
    fn.restype = None
    return fn


def _invoke(cfn, in_arrays: Sequence[np.ndarray],
            out_shapes: Sequence[tuple]) -> List[np.ndarray]:
    ins = [np.ascontiguousarray(a, dtype=np.float32) for a in in_arrays]
    outs = [np.zeros(s, dtype=np.float32) for s in out_shapes]
    n_in, n_out = len(ins), len(outs)

    in_ptrs = (_F32P * n_in)(*[a.ctypes.data_as(_F32P) for a in ins])
    in_shape_arrs = [(ctypes.c_int64 * a.ndim)(*a.shape) for a in ins]
    in_shapes = (_I64P * n_in)(*[ctypes.cast(s, _I64P)
                                 for s in in_shape_arrs])
    in_ndims = (ctypes.c_int32 * n_in)(*[a.ndim for a in ins])

    out_ptrs = (_F32P * n_out)(*[a.ctypes.data_as(_F32P) for a in outs])
    out_shape_arrs = [(ctypes.c_int64 * a.ndim)(*a.shape) for a in outs]
    out_shapes_c = (_I64P * n_out)(*[ctypes.cast(s, _I64P)
                                     for s in out_shape_arrs])
    out_ndims = (ctypes.c_int32 * n_out)(*[a.ndim for a in outs])

    cfn(in_ptrs, in_shapes, in_ndims, n_in,
        out_ptrs, out_shapes_c, out_ndims, n_out)
    return outs


def custom_op(extension: CppExtension, op_name: str,
              infer_shape: Callable[..., Sequence],
              grad_op: Optional[str] = "auto",
              num_outputs: int = 1) -> Callable:
    """Register a compiled C function as a framework op.

    ``infer_shape(*input_shapes) -> output shape (or list of shapes)`` is
    the analog of the reference's SetInferShapeFn. ``grad_op="auto"``
    looks for ``<op>_grad`` in the library; pass None for a
    non-differentiable op. Returns an eager callable over Tensors that
    also works under ``paddle_tpu.jit`` (host callback inside the
    compiled program).
    """
    import jax

    from paddle_tpu.core.autograd import apply_op

    cfwd = _bind(extension.lib, op_name)
    cbwd = None
    if grad_op == "auto":
        try:
            cbwd = _bind(extension.lib, f"{op_name}_grad")
        except AttributeError:
            cbwd = None
    elif grad_op:
        cbwd = _bind(extension.lib, grad_op)

    def out_struct(*arrays):
        shapes = infer_shape(*[tuple(a.shape) for a in arrays])
        if num_outputs == 1:
            # a single shape arrives bare: (3, 4), [3, 4], or () for a
            # scalar — wrap unless it is already a list OF shapes
            if not (isinstance(shapes, (tuple, list)) and len(shapes)
                    and isinstance(shapes[0], (tuple, list))):
                shapes = [tuple(shapes)]
        return [jax.ShapeDtypeStruct(tuple(s), np.float32) for s in shapes]

    def host_fwd(*arrays):
        return _invoke(cfwd, arrays,
                       [s.shape for s in out_struct(*arrays)])

    @jax.custom_vjp
    def fn(*arrays):
        res = jax.pure_callback(host_fwd, out_struct(*arrays), *arrays)
        return res[0] if num_outputs == 1 else tuple(res)

    def fwd(*arrays):
        return fn(*arrays), arrays

    def bwd(arrays, gouts):
        if cbwd is None:
            raise RuntimeError(
                f"custom op '{op_name}' has no grad function; mark its "
                "inputs stop_gradient or provide <op>_grad")
        gouts = (gouts,) if num_outputs == 1 else tuple(gouts)

        def host_bwd(*ins_and_gouts):
            n = len(arrays)
            return _invoke(cbwd, ins_and_gouts,
                           [a.shape for a in ins_and_gouts[:n]])
        gin_struct = [jax.ShapeDtypeStruct(tuple(a.shape), np.float32)
                      for a in arrays]
        gins = jax.pure_callback(host_bwd, gin_struct, *arrays, *gouts)
        return tuple(gins)

    fn.defvjp(fwd, bwd)

    def op_callable(*tensors):
        return apply_op(fn, *tensors, op_name=op_name)

    op_callable.__name__ = op_name
    extension.ops[op_name] = op_callable
    return op_callable
