"""paddle.utils parity (reference: ``python/paddle/utils/``)."""
from . import cpp_extension  # noqa: F401

__all__ = ["cpp_extension"]
