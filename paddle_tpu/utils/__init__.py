"""paddle.utils parity (reference: ``python/paddle/utils/``)."""
from . import cpp_extension  # noqa: F401
from . import unique_name  # noqa: F401
from .misc import (  # noqa: F401
    deprecated, from_dlpack, get_weights_path_from_url, run_check,
    to_dlpack, try_import,
)

__all__ = ["cpp_extension", "unique_name", "deprecated", "try_import",
           "to_dlpack", "from_dlpack", "get_weights_path_from_url",
           "run_check"]
