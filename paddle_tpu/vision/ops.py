"""paddle.vision.ops parity — detection ops (reference:
``python/paddle/vision/ops.py``: nms, roi_align, roi_pool, box_coder,
deform_conv2d, yolo_box...; kernels under ``paddle/phi/kernels``).

TPU-native notes: roi_align/roi_pool are gather+bilinear compositions (one
fused tape node, differentiable w.r.t. the feature map); nms is the
classic sequential-suppression algorithm expressed as a ``lax.scan`` over
score-sorted boxes (static shapes, no host sync under jit).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor

__all__ = ["box_area", "box_iou", "nms", "roi_align", "roi_pool",
           "box_coder"]


def box_area(boxes):
    """[N, 4] xyxy -> [N] areas (reference: vision/ops.py)."""
    def f(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply_op(f, boxes, op_name="box_area")


def _iou_matrix(b1, b2):
    area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
    area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
    lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
    rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                               1e-10)


def box_iou(boxes1, boxes2):
    """Pairwise IoU, [N, M]."""
    return apply_op(_iou_matrix, boxes1, boxes2, op_name="box_iou")


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None):
    """Greedy non-maximum suppression (reference: vision/ops.py nms).

    Returns kept indices sorted by descending score. With
    ``category_idxs``/``categories``, suppression is per-category
    (batched-NMS offset trick).
    """
    def f(b, s):
        n = b.shape[0]
        order = jnp.argsort(-s)
        b_sorted = b[order]
        iou = _iou_matrix(b_sorted, b_sorted)

        def body(keep, i):
            # suppressed if any higher-scored KEPT box overlaps > thresh
            over = (iou[i] > iou_threshold) & keep & \
                (jnp.arange(n) < i)
            k = ~jnp.any(over)
            return keep.at[i].set(k), None

        keep0 = jnp.ones(n, bool)
        keep, _ = jax.lax.scan(body, keep0, jnp.arange(n))
        return order, keep

    bt = boxes if isinstance(boxes, Tensor) else Tensor(jnp.asarray(boxes))
    if scores is None:
        st = Tensor(jnp.arange(bt.data.shape[0], 0, -1,
                               dtype=jnp.float32))
    else:
        st = scores if isinstance(scores, Tensor) \
            else Tensor(jnp.asarray(scores))
    keep_map = None
    if category_idxs is not None and categories is not None:
        # reference semantics: only boxes whose category is listed
        # participate; others are excluded from the output entirely
        cat_np = np.asarray(category_idxs.data
                            if isinstance(category_idxs, Tensor)
                            else category_idxs)
        sel = np.isin(cat_np, np.asarray(categories))
        keep_map = np.where(sel)[0]
        if len(keep_map) == 0:  # nothing listed: empty result, no reduce
            return Tensor(jnp.zeros((0,), jnp.int64))
        bt = Tensor(bt.data[keep_map])
        st = Tensor(st.data[keep_map])
        category_idxs = Tensor(jnp.asarray(cat_np[keep_map]))
    if category_idxs is not None:
        # batched NMS: offset boxes per category so cross-category boxes
        # never overlap (the reference applies NMS per category)
        cat = category_idxs.data if isinstance(category_idxs, Tensor) \
            else jnp.asarray(category_idxs)
        span = jnp.max(bt.data) - jnp.min(bt.data) + 1
        offset = cat.astype(bt.data.dtype)[:, None] * span
        bt = Tensor(bt.data + offset)

    order, keep = apply_op(f, bt, st, op_name="nms")
    order_np = np.asarray(order.data)
    keep_np = np.asarray(keep.data)
    kept = order_np[np.where(keep_np)[0]]
    if keep_map is not None:
        kept = keep_map[kept]  # back to original box indices
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept.astype(np.int64)))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True, name=None):
    """RoIAlign (reference: vision/ops.py roi_align; kernel
    ``phi/kernels/cpu/roi_align_kernel.cc``): bilinear sampling on a
    regular grid inside each box, averaged per output cell.

    x: [N, C, H, W]; boxes: [R, 4] xyxy in input coords; boxes_num: [N]
    rois per image. Returns [R, C, out_h, out_w]; differentiable in x.
    """
    if isinstance(output_size, int):
        out_h = out_w = output_size
    else:
        out_h, out_w = output_size
    if sampling_ratio > 0:
        ratio = sampling_ratio
    else:
        # reference adaptive rule (roi_align_kernel.cc:276): ceil of the
        # largest roi-cell size; computed host-side from concrete boxes
        # (capped at 8 samples/axis), falling back to 2 under tracing
        try:
            b = np.asarray(boxes.data if isinstance(boxes, Tensor)
                           else boxes)
            cell = max(float(np.max((b[:, 3] - b[:, 1]))) * spatial_scale
                       / out_h,
                       float(np.max((b[:, 2] - b[:, 0]))) * spatial_scale
                       / out_w, 1.0)
            ratio = int(min(np.ceil(cell), 8))
        except Exception:  # traced boxes: no concrete values
            ratio = 2

    bn = boxes_num.data if isinstance(boxes_num, Tensor) \
        else jnp.asarray(boxes_num)
    batch_of_roi = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                              total_repeat_length=int(jnp.sum(bn)))

    def f(feat, rois):
        H, W = feat.shape[2], feat.shape[3]
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        # sample grid: out_h*ratio x out_w*ratio points per roi
        gy = (jnp.arange(out_h * ratio) + 0.5) / ratio  # in output cells
        gx = (jnp.arange(out_w * ratio) + 0.5) / ratio
        ys = y1[:, None] + rh[:, None] * gy[None, :] / out_h  # [R, oh*r]
        xs = x1[:, None] + rw[:, None] * gx[None, :] / out_w

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy [oh*r], xx [ow*r] -> [C, oh*r, ow*r]
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy, 0, H - 1) - y0
            wx = jnp.clip(xx, 0, W - 1) - x0
            y0i, y1i = y0.astype(jnp.int32), y1_.astype(jnp.int32)
            x0i, x1i = x0.astype(jnp.int32), x1_.astype(jnp.int32)
            v00 = img[:, y0i][:, :, x0i]
            v01 = img[:, y0i][:, :, x1i]
            v10 = img[:, y1i][:, :, x0i]
            v11 = img[:, y1i][:, :, x1i]
            w00 = ((1 - wy)[:, None] * (1 - wx)[None, :])
            w01 = ((1 - wy)[:, None] * wx[None, :])
            w10 = (wy[:, None] * (1 - wx)[None, :])
            w11 = (wy[:, None] * wx[None, :])
            return v00 * w00 + v01 * w01 + v10 * w10 + v11 * w11

        def per_roi(r):
            img = feat[batch_of_roi[r]]
            s = bilinear(img, ys[r], xs[r])  # [C, oh*ratio, ow*ratio]
            C = s.shape[0]
            s = s.reshape(C, out_h, ratio, out_w, ratio)
            return s.mean(axis=(2, 4))

        return jax.vmap(per_roi)(jnp.arange(rois.shape[0]))

    return apply_op(f, x, boxes, op_name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None):
    """RoIPool (reference: vision/ops.py roi_pool; kernel
    ``phi/kernels/cpu/roi_pool_kernel.cc``): hard max over EVERY pixel in
    each output cell (cell p-range: [floor(start), ceil(end))).

    Expressed as two masked max-reductions (rows then columns) so cells of
    any size reduce over all their pixels with static shapes.
    """
    if isinstance(output_size, int):
        out_h = out_w = output_size
    else:
        out_h, out_w = output_size
    bn = boxes_num.data if isinstance(boxes_num, Tensor) \
        else jnp.asarray(boxes_num)
    batch_of_roi = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                              total_repeat_length=int(jnp.sum(bn)))

    def f(feat, rois):
        H, W = feat.shape[2], feat.shape[3]
        x1 = jnp.round(rois[:, 0] * spatial_scale)
        y1 = jnp.round(rois[:, 1] * spatial_scale)
        x2 = jnp.round(rois[:, 2] * spatial_scale)
        y2 = jnp.round(rois[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        neg = jnp.array(-jnp.inf, feat.dtype)

        def cell_mask(starts, spans, n_cells, size):
            # mask[cell, pixel] — pixel within [floor(start), ceil(end))
            cells = jnp.arange(n_cells, dtype=jnp.float32)
            lo = jnp.floor(starts[:, None] + spans[:, None] * cells[None]
                           / n_cells)                      # [R, cells]
            hi = jnp.ceil(starts[:, None] + spans[:, None]
                          * (cells[None] + 1) / n_cells)
            lo = jnp.clip(lo, 0, size)
            hi = jnp.clip(jnp.maximum(hi, lo + 1), 0, size)
            p = jnp.arange(size, dtype=jnp.float32)
            return (p[None, None, :] >= lo[..., None]) & \
                (p[None, None, :] < hi[..., None])  # [R, cells, size]

        row_m = cell_mask(y1, rh, out_h, H)  # [R, out_h, H]
        col_m = cell_mask(x1, rw, out_w, W)  # [R, out_w, W]

        def per_roi(r):
            img = feat[batch_of_roi[r]]  # [C, H, W]
            # max over masked columns, then masked rows
            tmp = jnp.max(jnp.where(col_m[r][None, None, :, :],
                                    img[:, :, None, :], neg), axis=-1)
            # tmp: [C, H, out_w]
            out = jnp.max(jnp.where(row_m[r][None, :, :, None],
                                    tmp[:, None, :, :], neg), axis=2)
            # out: [C, out_h, out_w]; empty cells (fully clipped) -> 0
            return jnp.where(jnp.isfinite(out), out, 0.0)
        return jax.vmap(per_roi)(jnp.arange(rois.shape[0]))

    return apply_op(f, x, boxes, op_name="roi_pool")


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True, axis: int = 0, name=None):
    """Encode/decode boxes against priors (reference: vision/ops.py
    box_coder / phi box_coder kernel, SSD-style).

    encode: target [N, 4] x prior [M, 4] -> [N, M, 4] (all pairs).
    decode: target [N, M, 4] (or [N, 4]), prior broadcast along ``axis``
    (0: prior indexed by M; 1: prior indexed by N) -> same shape as
    target.
    """
    if code_type not in ("encode_center_size", "decode_center_size"):
        raise ValueError(
            f"unknown code_type '{code_type}'; expected "
            "'encode_center_size' or 'decode_center_size'")
    if axis not in (0, 1):
        raise ValueError(f"axis must be 0 or 1, got {axis}")
    norm = 0.0 if box_normalized else 1.0

    def prior_parts(pb):
        pw = pb[..., 2] - pb[..., 0] + norm
        ph = pb[..., 3] - pb[..., 1] + norm
        pcx = (pb[..., 0] + pb[..., 2]) / 2
        pcy = (pb[..., 1] + pb[..., 3]) / 2
        return pw, ph, pcx, pcy

    def enc(pb, pbv, tb):
        pw, ph, pcx, pcy = prior_parts(pb)          # [M]
        tw = (tb[:, 2] - tb[:, 0] + norm)[:, None]  # [N, 1]
        th = (tb[:, 3] - tb[:, 1] + norm)[:, None]
        tcx = ((tb[:, 0] + tb[:, 2]) / 2)[:, None]
        tcy = ((tb[:, 1] + tb[:, 3]) / 2)[:, None]
        out = jnp.stack([(tcx - pcx[None]) / pw[None],
                         (tcy - pcy[None]) / ph[None],
                         jnp.log(tw / pw[None]),
                         jnp.log(th / ph[None])], axis=-1)  # [N, M, 4]
        return out / pbv if pbv is not None else out

    def dec(pb, pbv, tb):
        pw, ph, pcx, pcy = prior_parts(pb)
        if tb.ndim == 3:
            # broadcast the prior (and its variance) over the
            # non-``axis`` dim
            expand = (lambda a: a[None, :]) if axis == 0 \
                else (lambda a: a[:, None])
            pw, ph, pcx, pcy = map(expand, (pw, ph, pcx, pcy))
            if pbv is not None and pbv.ndim == 2:
                pbv = pbv[None, :, :] if axis == 0 else pbv[:, None, :]
        t = tb * pbv if pbv is not None else tb
        cx = t[..., 0] * pw + pcx
        cy = t[..., 1] * ph + pcy
        w = jnp.exp(t[..., 2]) * pw
        h = jnp.exp(t[..., 3]) * ph
        return jnp.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - norm, cy + h / 2 - norm], axis=-1)

    fn = enc if code_type == "encode_center_size" else dec
    if prior_box_var is None:
        return apply_op(lambda pb, tb: fn(pb, None, tb), prior_box,
                        target_box, op_name="box_coder")
    return apply_op(fn, prior_box, prior_box_var, target_box,
                    op_name="box_coder")
