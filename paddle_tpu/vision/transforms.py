"""Vision transforms (reference: ``python/paddle/vision/transforms/``).

Host-side numpy transforms (the input pipeline runs on host threads; the
device sees the collated batch), matching the reference's functional
semantics for the common set.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform"]


def _chw(img: np.ndarray) -> np.ndarray:
    if img.ndim == 2:
        img = img[:, :, None]
    return img


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference to_tensor)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        img = _chw(np.asarray(img))
        out = img.astype(np.float32)
        if img.dtype == np.uint8:
            out = out / 255.0
        if self.data_format == "CHW":
            out = out.transpose(2, 0, 1)
        return out


class Normalize:
    def __init__(self, mean, std, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize:
    """Bilinear resize on HWC arrays (reference default interpolation)."""

    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = _chw(np.asarray(img))
        h, w, c = img.shape
        th, tw = self.size
        ys = (np.arange(th) + 0.5) * h / th - 0.5
        xs = (np.arange(tw) + 0.5) * w / tw - 0.5
        y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = np.clip(ys - y0, 0, 1)[:, None, None]
        wx = np.clip(xs - x0, 0, 1)[None, :, None]
        im = img.astype(np.float32)
        out = (im[y0][:, x0] * (1 - wy) * (1 - wx) +
               im[y0][:, x1] * (1 - wy) * wx +
               im[y1][:, x0] * wy * (1 - wx) +
               im[y1][:, x1] * wy * wx)
        return out.astype(img.dtype) if img.dtype == np.uint8 else out


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = _chw(np.asarray(img))
        h, w, _ = img.shape
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        img = _chw(np.asarray(img))
        if self.padding:
            p = self.padding
            img = np.pad(img, ((p, p), (p, p), (0, 0)))
        h, w, _ = img.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        img = np.asarray(img)
        factor = 1 + np.random.uniform(-self.value, self.value)
        out = img.astype(np.float32) * factor
        if img.dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out
