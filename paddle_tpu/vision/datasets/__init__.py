"""Vision datasets (reference: ``python/paddle/vision/datasets/``).

The sandbox has no network egress, so downloads raise with a clear message;
local-file loading (MNIST idx format) and the synthetic FakeData generator
work everywhere (FakeData is also the perf-bench input source).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["MNIST", "FashionMNIST", "FakeData", "Cifar10", "Cifar100"]


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples=1000, image_shape=(3, 32, 32),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._seed = seed

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed + idx)
        img = rng.randn(*self.image_shape).astype(np.float32)
        label = np.int64(idx % self.num_classes)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    """MNIST from local idx/idx.gz files (reference file-format parity:
    ``python/paddle/vision/datasets/mnist.py``)."""

    _files = {
        "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }
    _cache_name = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None,
                 root=None):
        self.transform = transform
        if image_path is None or label_path is None:
            root = root or os.path.expanduser(
                f"~/.cache/paddle_tpu/{self._cache_name}")
            img_name, lbl_name = self._files[mode]
            image_path = self._find(root, img_name)
            label_path = self._find(root, lbl_name)
            if image_path is None or label_path is None:
                raise FileNotFoundError(
                    f"MNIST files not found under {root}; this environment "
                    "has no network egress — place the idx(.gz) files there "
                    "or pass image_path/label_path explicitly")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _find(root, name):
        for cand in (os.path.join(root, name),
                     os.path.join(root, name + ".gz")):
            if os.path.exists(cand):
                return cand
        return None

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") \
            else open(path, "rb")

    @classmethod
    def _read_images(cls, path):
        with cls._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx3 magic {magic} in {path}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    @classmethod
    def _read_labels(cls, path):
        with cls._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx1 magic {magic} in {path}")
            return np.frombuffer(f.read(n), dtype=np.uint8)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])


class FashionMNIST(MNIST):
    """Same idx file format as MNIST but a distinct cache directory, so a
    default-root FashionMNIST() can never silently pick up MNIST digits."""
    _cache_name = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 from the local ``cifar-10-python.tar.gz`` archive
    (reference file-format parity: ``python/paddle/vision/datasets/
    cifar.py`` — pickle batches of 10000x3072 uint8 rows)."""

    _mode_files = {"train": [f"data_batch_{i}" for i in range(1, 6)],
                   "test": ["test_batch"]}
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        import pickle
        import tarfile
        if mode not in self._mode_files:
            raise ValueError(
                f"mode must be one of {sorted(self._mode_files)}, "
                f"got '{mode}'")
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{type(self).__name__}: automatic download is unavailable "
                "in this build (no network egress); pass data_file= "
                "pointing at the local cifar python tar archive")
        self.transform = transform
        images, labels = [], []
        wanted = self._mode_files[mode]
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in wanted:
                    d = pickle.loads(tf.extractfile(member).read(),
                                     encoding="bytes")
                    images.append(np.asarray(d[b"data"], np.uint8))
                    labels.extend(d[self._label_key])
        if not images:
            raise ValueError(
                f"no {mode} batches ({wanted}) found in {data_file}")
        self.data = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    """CIFAR-100 (fine labels) from ``cifar-100-python.tar.gz``."""

    _mode_files = {"train": ["train"], "test": ["test"]}
    _label_key = b"fine_labels"
