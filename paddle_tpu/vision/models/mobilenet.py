"""MobileNetV1/V2 (reference: ``python/paddle/vision/models/
mobilenetv1.py`` / ``mobilenetv2.py``)."""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(nn.Sequential):
    def __init__(self, cin, cout, kernel=3, stride=1, groups=1,
                 activation=True):
        pad = (kernel - 1) // 2
        layers = [nn.Conv2D(cin, cout, kernel, stride=stride, padding=pad,
                            groups=groups, bias_attr=False),
                  nn.BatchNorm2D(cout)]
        if activation:
            layers.append(nn.ReLU6())
        super().__init__(*layers)


class DepthwiseSeparable(nn.Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.dw = ConvBNReLU(cin, cin, 3, stride=stride, groups=cin)
        self.pw = ConvBNReLU(cin, cout, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    """Reference: mobilenetv1.py MobileNetV1."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        s = lambda c: max(int(c * scale), 8)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        blocks = [ConvBNReLU(3, s(32), stride=2)]
        for cin, cout, stride in cfg:
            blocks.append(DepthwiseSeparable(s(cin), s(cout), stride))
        self.features = nn.Sequential(*blocks)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(s(1024), num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(nn.Flatten(1)(x))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, cin, cout, stride, expand_ratio):
        super().__init__()
        hidden = int(round(cin * expand_ratio))
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(cin, hidden, 1))
        layers += [ConvBNReLU(hidden, hidden, 3, stride=stride,
                              groups=hidden),
                   ConvBNReLU(hidden, cout, 1, activation=False)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        if self.use_res:
            import paddle_tpu.ops as ops
            return ops.add(x, out)
        return out


class MobileNetV2(nn.Layer):
    """Reference: mobilenetv2.py MobileNetV2."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        cin = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        blocks = [ConvBNReLU(3, cin, stride=2)]
        for t, c, n, s in cfg:
            cout = _make_divisible(c * scale)
            for i in range(n):
                blocks.append(InvertedResidual(cin, cout,
                                               s if i == 0 else 1, t))
                cin = cout
        blocks.append(ConvBNReLU(cin, last, 1))
        self.features = nn.Sequential(*blocks)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(
            nn.Dropout(0.2), nn.Linear(last, num_classes)) \
            if num_classes > 0 else None

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.classifier is not None:
            x = self.classifier(nn.Flatten(1)(x))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network download; load a local "
            "state_dict with set_state_dict")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network download; load a local "
            "state_dict with set_state_dict")
    return MobileNetV2(scale=scale, **kwargs)
