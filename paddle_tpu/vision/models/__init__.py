"""Vision model zoo (reference: ``python/paddle/vision/models/``)."""
from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    BasicBlock, BottleneckBlock,
)
from .lenet import LeNet  # noqa: F401
