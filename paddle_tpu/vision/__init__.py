"""paddle.vision parity namespace (reference: ``python/paddle/vision/``)."""
from . import transforms  # noqa: F401
from . import models  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .models import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152, LeNet,
)
