"""Program auditor + trace-safety linter (docs/ANALYSIS.md).

Compiler-style static analysis over the two artifacts this repo
actually ships: the **compiled HLO** of its hot-path programs
(``TrainStep`` / ``ServingEngine`` through their ``compiled_hlo()``
seams) and the **framework Python** itself. Treat the lowered program
as an analyzable artifact, not a black box (MPK / TPU-MLIR,
PAPERS.md) — every invariant here was previously checked by eyeballing
HLO dumps or paid for at runtime.

CLI::

    python -m paddle_tpu.analysis audit   # compiled-program audit
    python -m paddle_tpu.analysis lint    # AST trace-safety lint
    python -m paddle_tpu.analysis knobs   # env-knob registry + drift

Findings gate against the committed ``analysis/baseline.json``
(fingerprint ledger — new findings fail, known debt is tracked);
``bench.py --audit`` exposes the headline numbers to the perf
regression gate.
"""
from .audit import (ProgramAudit, audit_program, audit_serving_engine,
                    audit_train_step, diff_compile_keys, recompile_report)
from .findings import Baseline, Finding, baseline_path, load_baseline
from .knobs import collect_code_knobs, collect_doc_knobs, drift
from .lint import lint_file, lint_tree

__all__ = [
    "ProgramAudit", "audit_program", "audit_train_step",
    "audit_serving_engine", "diff_compile_keys", "recompile_report",
    "Baseline", "Finding", "baseline_path", "load_baseline",
    "collect_code_knobs", "collect_doc_knobs", "drift",
    "lint_file", "lint_tree",
]
