"""CLI: ``python -m paddle_tpu.analysis {audit,lint,knobs,commplan,all}``.

Exit codes: 0 clean, 1 new findings / drift, 2 usage error or unusable
baseline (missing/corrupt ``baseline.json`` prints a one-line hint, not
a traceback). The gate semantics (new-vs-baseline) match the tier-1
tests, so a green local run means a green CI lint job.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .findings import BaselineError, load_baseline, \
    repo_root as _repo_root


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_path():
    p = os.path.join(_repo_root(), "bench.py")
    return (p,) if os.path.exists(p) else ()


def _gate(findings, args, kind: str, extra: dict = None) -> int:
    """Shared baseline gate: print new/known/stale, optionally accept
    the new findings into the baseline file."""
    base = load_baseline(args.baseline)
    new, known, stale = base.split(findings)
    if args.json:
        doc = dict(extra or {})
        doc[kind] = {"new": [f.to_json() for f in new],
                     "known": len(known), "stale": sorted(stale)}
        print(json.dumps(doc, indent=1))
    else:
        for f in new:
            print("NEW  " + f.format())
        if known and not args.quiet:
            print(f"{len(known)} known finding(s) accepted by baseline "
                  f"{base.path}", file=sys.stderr)
        for fp in sorted(stale):
            meta = stale[fp]
            print(f"stale baseline entry {fp} "
                  f"({meta.get('rule')} @ {meta.get('path')}) — fixed? "
                  f"prune it", file=sys.stderr)
    if args.update_baseline and new:
        base.accept(new, note="accepted via --update-baseline")
        base.save()
        print(f"accepted {len(new)} finding(s) into {base.path}",
              file=sys.stderr)
        return 0
    return 1 if new else 0


def cmd_lint(args) -> int:
    from .lint import lint_tree
    findings = lint_tree(args.root, extra_files=_bench_path())
    findings.sort(key=lambda f: (f.severity, f.path, f.line))
    if not getattr(args, "strict_suppressions", False):
        # allow-rot is advisory by default: surface it, don't gate on it
        stale_sup = [f for f in findings if f.rule == "stale-suppression"]
        findings = [f for f in findings if f.rule != "stale-suppression"]
        if stale_sup and not args.quiet and not args.json:
            for f in stale_sup:
                print("warn " + f.format(), file=sys.stderr)
    return _gate(findings, args, "lint")


def cmd_audit(args) -> int:
    from .driver import ensure_cpu_mesh, run_default_audit
    ensure_cpu_mesh()
    result = run_default_audit(include_serving=not args.no_serving)
    findings = result.pop("findings")
    if not args.json:
        for rep in result["reports"]:
            print(f"-- {rep['label']}: all_reduce={rep['all_reduce_count']} "
                  f"donated={rep['donated_bytes']}B "
                  f"undonated={rep['undonated_bytes']}B "
                  f"coverage={rep['donation_coverage']} "
                  f"upcasts={rep['upcast_count']} "
                  f"largest={rep['largest_intermediate_bytes']}B",
                  file=sys.stderr)
    return _gate(findings, args, "audit", extra=result)


def cmd_commplan(args) -> int:
    from .commplan import budget_findings
    from .driver import ensure_cpu_mesh, run_commplan
    ensure_cpu_mesh()
    result = run_commplan(seed_typo=getattr(args, "seed_typo", False),
                          only=getattr(args, "only", None))
    findings = result.pop("findings")
    if not args.json:
        for label, rep in result["reports"].items():
            for axis, slot in sorted(rep["ledger"].items()):
                print(f"-- {label}/{axis}: ops={slot['ops']} "
                      f"bytes={slot['bytes']} hops={slot['hops']} "
                      f"kinds={slot['kinds']}", file=sys.stderr)
            if not rep["ledger"]:
                print(f"-- {label}: no collectives", file=sys.stderr)
        for label, why in result["skipped"].items():
            print(f"-- {label}: SKIPPED ({why})", file=sys.stderr)

    base = load_baseline(args.baseline)
    if args.write_baseline:
        for label, ledger in result["ledgers"].items():
            base.commplan[label] = {
                axis: {"ops": slot["ops"], "bytes": slot["bytes"],
                       "kinds": dict(slot["kinds"])}
                for axis, slot in ledger.items()}
        base.save()
        print(f"pinned comm ledgers for "
              f"{sorted(result['ledgers'])} into {base.path}",
              file=sys.stderr)
    elif not base.commplan:
        raise BaselineError(base.path, "no pinned commplan section")
    else:
        for label, ledger in result["ledgers"].items():
            findings.extend(budget_findings(
                label, ledger, base.commplan.get(label)))
    findings.sort(key=lambda f: (f.severity, f.path, f.anchor))
    return _gate(findings, args, "commplan", extra=result)


def cmd_all(args) -> int:
    """What CI runs: every prong, worst exit code wins (run them all
    even if an early one fails, so one CI log shows the whole picture)."""
    shared = dict(baseline=args.baseline, update_baseline=False,
                  quiet=args.quiet, json=False)
    steps = (
        ("lint", cmd_lint, dict(
            root=None,
            strict_suppressions=args.strict_suppressions, **shared)),
        ("knobs", cmd_knobs, dict(json=False)),
        ("audit", cmd_audit, dict(no_serving=False, **shared)),
        ("commplan", cmd_commplan, dict(
            seed_typo=False, only=None, write_baseline=False, **shared)),
    )
    worst = 0
    for name, fn, ns in steps:
        print(f"== {name}", file=sys.stderr)
        try:
            rc = fn(argparse.Namespace(**ns))
        except BaselineError as e:
            print(str(e), file=sys.stderr)
            rc = 2
        if rc:
            print(f"== {name}: FAIL (exit {rc})", file=sys.stderr)
        worst = max(worst, rc)
    return worst


def cmd_knobs(args) -> int:
    from .knobs import drift
    d = drift(extra_files=_bench_path())
    if args.json:
        print(json.dumps(d, indent=1))
    else:
        for name, sites in d["code"].items():
            # drift() owns coverage semantics (incl. prefix families);
            # the table must agree with the exit code
            mark = "UNDOCUMENTED" if name in d["undocumented"] else "ok "
            site = f"{sites[0][0]}:{sites[0][1]}"
            print(f"{mark:>13}  {name:<38} {site} "
                  f"(+{len(sites) - 1} more)" if len(sites) > 1 else
                  f"{mark:>13}  {name:<38} {site}")
        for name in d["ghosts"]:
            print(f"        GHOST  {name:<38} documented in "
                  f"{', '.join(d['docs'][name])} but never read")
    bad = d["undocumented"] or d["ghosts"]
    if bad and not args.json:
        print(f"drift: undocumented={d['undocumented']} "
              f"ghosts={d['ghosts']}", file=sys.stderr)
    return 1 if bad else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    lint = sub.add_parser("lint", help="AST trace-safety lint")
    lint.add_argument("--root", default=None,
                      help="tree to lint (default: the installed package)")
    lint.add_argument("--strict-suppressions", action="store_true",
                      help="gate on stale `# analysis: allow(...)` "
                           "comments instead of warning")
    audit = sub.add_parser("audit",
                           help="compiled-program audit (committed "
                                "geometry)")
    audit.add_argument("--no-serving", action="store_true",
                       help="skip the serving-engine program")
    commplan = sub.add_parser(
        "commplan", help="SPMD comm-plan audit over the committed "
                         "parallelism matrix")
    commplan.add_argument("--write-baseline", action="store_true",
                          help="pin the current per-axis ledgers into "
                               "the baseline (budget re-baseline)")
    commplan.add_argument("--seed-typo", dest="seed_typo",
                          action="store_true",
                          help="self-test: lower the dp8 geometry with a "
                               "seeded sharding-spec typo (must exit 1)")
    commplan.add_argument("--only", action="append", default=None,
                          metavar="LABEL",
                          help="restrict to named geometries (repeatable)")
    knobs = sub.add_parser("knobs", help="env-knob registry + doc drift")
    knobs.add_argument("--json", action="store_true")
    allp = sub.add_parser("all", help="lint+knobs+audit+commplan, the "
                                      "way CI runs them")
    allp.add_argument("--strict-suppressions", action="store_true",
                      help="gate on stale suppressions in the lint step")
    for sp in (lint, audit, commplan, allp):
        sp.add_argument("--baseline", default=None,
                        help="baseline.json path (default: committed, or "
                             "$PADDLE_TPU_ANALYSIS_BASELINE)")
        sp.add_argument("--quiet", action="store_true")
        sp.add_argument("--json", action="store_true")
    for sp in (lint, audit, commplan):
        sp.add_argument("--update-baseline", action="store_true",
                        help="accept the new findings into the baseline")

    args = p.parse_args(argv)
    try:
        return {"lint": cmd_lint, "audit": cmd_audit, "knobs": cmd_knobs,
                "commplan": cmd_commplan, "all": cmd_all}[args.cmd](args)
    except BaselineError as e:
        print(str(e), file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
