"""SPMD communication-plan auditor: the collective schedule as data.

Prong 3 of the analysis subsystem (docs/ANALYSIS.md). GSPMD decides the
collective schedule — which axes all-reduce, what gets gathered, how
many bytes cross links per step — and that decision is only visible in
the compiled HLO. This module lifts it into a checkable artifact:

- :func:`parse_collectives`: every collective instruction (the five
  stems, async ``-start`` counted once / ``-done`` excluded) with its
  decoded ``replica_groups`` (explicit nested-brace and iota
  ``[G,S]<=[dims]T(perm)`` forms), ``channel_id``,
  ``use_global_device_ids``, ``source_target_pairs`` and operands.
- :func:`map_axes` / :class:`MeshInfo`: replica-group member ids mapped
  back to **named mesh axes** (the axes whose coordinates vary inside a
  group), with an ICI-vs-DCN classification (a group spanning processes
  pays DCN hops; a within-process group stays on ICI).
- :func:`comm_ledger`: the per-axis static ledger — op count, wire
  bytes per step (ring cost model, per participant), collective kinds.
- Defect passes over the plan: **implicit reshard** (an all-gather whose
  operand chains back to a parameter/state leaf that the geometry says
  must never be gathered — the accidental-all-gather P0 class a
  sharding-spec typo produces), **redundant reshard** (an all-gather
  re-scattered on the same axes), and **budget drift** (per-axis bytes
  pinned in ``analysis/baseline.json``; NEW collectives or growth past
  ``PADDLE_TPU_ANALYSIS_COMM_TOL`` fail CI).

Everything below :func:`audit_comm` is pure text+arithmetic — no jax
import — so the parser unit-tests run on doctored fragments and the
same code audits a real TPU dump.

Wire-bytes cost model (per participating device, per step; ``g`` =
replica-group size, ``payload`` = full result bytes):

====================  =============================================
all-reduce            ``2 * (g-1)/g * payload`` (reduce-scatter +
                      all-gather phases of a ring)
all-gather            ``(g-1)/g * payload`` (each device ships its
                      shard around the ring)
reduce-scatter        ``(g-1) * payload`` (payload is the scattered
                      shard; ``g-1`` chunks of it transit)
all-to-all            ``(g-1)/g * payload`` (every device keeps its
                      own slice)
collective-permute    ``payload`` (each source sends one full buffer)
====================  =============================================
"""
from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding, P0, P1
from .hlo import COLLECTIVE_STEMS, _balanced_braces, shape_bytes

__all__ = ["Collective", "MeshInfo", "parse_collectives", "map_axes",
           "wire_bytes", "comm_ledger", "CommReport", "audit_comm",
           "budget_findings", "comm_tolerance"]

#: drift tolerance on per-axis bytes (fraction); growth past it is a
#: finding. Shrink never fails — re-pin with --write-baseline to claim
#: the win.
_DEFAULT_COMM_TOL = 0.05

#: leaf-name prefixes that name persistent state (model parameters and
#: optimizer state) in a TrainStep entry — the buffers an implicit
#: reshard must never gather unless the geometry says so (ZeRO does).
STATE_LEAF_PREFIXES = ("train", "frozen", "states", "buffers")


def comm_tolerance() -> float:
    raw = os.environ.get("PADDLE_TPU_ANALYSIS_COMM_TOL", "")
    try:
        return float(raw) if raw else _DEFAULT_COMM_TOL
    except ValueError:
        return _DEFAULT_COMM_TOL


# -- parsing ----------------------------------------------------------------

#: `%name = <result> <stem>[-start|-done](` — result is a shape or a
#: tuple of shapes; the leading %/ROOT guard keeps computation headers
#: and operand mentions out.
_COLL_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(%[\w.\-]+)\s*=\s*"
    r"(\([^=]*?\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s*"
    r"(" + "|".join(COLLECTIVE_STEMS) + r")(-start|-done)?\(")
_SHAPE_TOK_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_CHANNEL_RE = re.compile(r"\bchannel_id=(\d+)")
_GLOBAL_IDS_RE = re.compile(r"\buse_global_device_ids=(true|false)")
_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
    r"(?:T\(([0-9,]+)\))?")
_SOURCE_RE = re.compile(r'source_file="([^"]*)"(?:\s+source_line=(\d+))?')
#: computation header: `%name (args) -> result {` / `ENTRY %name (...) {`.
#: The `(` must follow the name directly (instructions carry ` = ` there)
#: and the line must end with the open brace; the signature itself can
#: contain `=` inside /*index=N*/ comments, so no char-class shortcuts.
_COMPUTATION_RE = re.compile(
    r"^\s*(ENTRY\s+)?(%[\w.\-]+)\s*\(.*\{\s*$")
_PARAM_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(%[\w.\-]+)\s*=\s*[^=]*\bparameter\((\d+)\)")
#: the entry-parameter leaf label jax stamps into metadata
#: (op_name="train[\'0.bias\']") — shard-shape-proof, unlike aligning
#: on (dtype, dims) which breaks when SPMD rewrites params to shard
#: shapes
_PARAM_LABEL_RE = re.compile(r'metadata=\{op_name="([^"]*)"')
_OPERAND_RE = re.compile(r"%[\w.\-]+")


@dataclass
class Collective:
    """One parsed collective instruction."""
    kind: str                       # one of COLLECTIVE_STEMS
    name: str                       # %all-gather.3
    computation: str                # enclosing computation (% stripped)
    entry: bool                     # lives in the ENTRY computation
    payload_bytes: int              # see module doc (tuple handling)
    groups: Optional[List[List[int]]] = None   # decoded replica groups
    pairs: Optional[List[Tuple[int, int]]] = None  # source_target_pairs
    channel_id: Optional[int] = None
    use_global_ids: bool = False
    operands: Tuple[str, ...] = ()
    source: str = ""                # "file:line" metadata when present
    line: str = ""

    @property
    def group_size(self) -> int:
        if self.groups:
            return max(len(g) for g in self.groups)
        if self.pairs:
            # a permute "group" is the cycle the pairs trace; for the
            # cost model only "more than one participant" matters
            return 2 if self.pairs else 1
        return 1


def _decode_iota(num_groups: int, group_size: int, dims: Sequence[int],
                 perm: Optional[Sequence[int]]) -> List[List[int]]:
    """Decode the iota replica-group form ``[G,S]<=[dims]T(perm)``:
    ``arange(prod(dims)).reshape(dims)``, optionally transposed by
    ``perm``, reshaped to ``[G, S]`` (pure python — no numpy needed for
    the group sizes involved)."""
    n = 1
    for d in dims:
        n *= d
    flat = list(range(n))

    def strides(shape):
        out, acc = [], 1
        for d in reversed(shape):
            out.append(acc)
            acc *= d
        return list(reversed(out))

    if perm:
        src_strides = strides(list(dims))
        tshape = [dims[p] for p in perm]
        tstrides = strides(tshape)
        out = [0] * n
        for j in range(n):
            rem, coords = j, []
            for st in tstrides:
                coords.append(rem // st)
                rem %= st
            src = sum(c * src_strides[p]
                      for c, p in zip(coords, perm))
            out[j] = flat[src]
        flat = out
    return [flat[i * group_size:(i + 1) * group_size]
            for i in range(num_groups)]


def _parse_groups(line: str) -> Optional[List[List[int]]]:
    m = _IOTA_RE.search(line)
    if m:
        dims = [int(d) for d in m.group(3).split(",")]
        perm = [int(p) for p in m.group(4).split(",")] if m.group(4) \
            else None
        return _decode_iota(int(m.group(1)), int(m.group(2)), dims, perm)
    key = "replica_groups="
    i = line.find(key)
    if i < 0 or not line[i + len(key):].startswith("{"):
        return None
    body = _balanced_braces(line, i + len(key))
    groups = []
    for gm in re.finditer(r"\{([0-9,\s]*)\}", body):
        groups.append([int(t) for t in gm.group(1).split(",") if t.strip()])
    if not groups and body.strip():
        # single flat group: replica_groups={0,1,2}
        groups = [[int(t) for t in body.split(",") if t.strip()]]
    return groups


def _parse_pairs(line: str) -> Optional[List[Tuple[int, int]]]:
    key = "source_target_pairs="
    i = line.find(key)
    if i < 0:
        return None
    body = _balanced_braces(line, i + len(key))
    return [(int(pm.group(1)), int(pm.group(2)))
            for pm in re.finditer(r"\{(\d+)\s*,\s*(\d+)\}", body)]


def _result_bytes(result: str, kind: str, is_start: bool) -> int:
    """Payload bytes from the result type. A plain tuple all-to-all
    moves every element (sum); a ``-start`` tuple is (operand, dest,
    context...) — the destination (largest element) is the payload."""
    shapes = [(d, c) for d, c in _SHAPE_TOK_RE.findall(result)]
    if not shapes:
        return 0
    if not result.startswith("("):
        d, c = shapes[0]
        return shape_bytes(d, c)
    sizes = [shape_bytes(d, c) for d, c in shapes]
    if kind == "all-to-all" and not is_start:
        return sum(sizes)
    return max(sizes)


def parse_collectives(hlo_text: str) -> List[Collective]:
    """Every collective instruction in the module, with async ``-start``
    counted once and ``-done`` excluded (it carries no second payload)."""
    out: List[Collective] = []
    computation, entry = "", False
    for raw in hlo_text.splitlines():
        cm = _COMPUTATION_RE.match(raw)
        if cm:
            computation = cm.group(2).lstrip("%")
            entry = bool(cm.group(1))
            continue
        m = _COLL_RE.match(raw)
        if not m:
            continue
        name, result, kind, suffix = m.groups()
        if suffix == "-done":
            continue
        src = ""
        sm = _SOURCE_RE.search(raw)
        if sm:
            src = sm.group(1) + (f":{sm.group(2)}" if sm.group(2) else "")
        ch = _CHANNEL_RE.search(raw)
        gl = _GLOBAL_IDS_RE.search(raw)
        operands = tuple(
            t for t in _OPERAND_RE.findall(raw[m.end():]) if t != name)
        out.append(Collective(
            kind=kind, name=name, computation=computation, entry=entry,
            payload_bytes=_result_bytes(result, kind, suffix == "-start"),
            groups=_parse_groups(raw), pairs=_parse_pairs(raw),
            channel_id=int(ch.group(1)) if ch else None,
            use_global_ids=bool(gl and gl.group(1) == "true"),
            operands=operands, source=src, line=raw.strip()))
    return out


# -- mesh mapping -----------------------------------------------------------

@dataclass
class MeshInfo:
    """The mesh facts axis mapping needs, detached from jax: axis names
    and sizes (in mesh order), device coordinates per flat position, and
    the process index per flat position (DCN detection)."""
    axis_names: Tuple[str, ...]
    axis_sizes: Tuple[int, ...]
    #: flat position (row-major over the device array) -> coords
    coords: List[Tuple[int, ...]]
    #: flat position -> process index
    process: List[int]
    #: global device id -> flat position (use_global_device_ids=true)
    by_device_id: Dict[int, int] = field(default_factory=dict)

    @classmethod
    def from_mesh(cls, mesh) -> "MeshInfo":
        """From a ``jax.sharding.Mesh`` (what ``init_mesh`` returns)."""
        devs = mesh.devices
        names = tuple(mesh.axis_names)
        sizes = tuple(devs.shape)
        coords, process, by_id = [], [], {}
        flat = list(devs.flatten())
        for pos, d in enumerate(flat):
            rem, c = pos, []
            for s in _strides(sizes):
                c.append(rem // s)
                rem %= s
            coords.append(tuple(c))
            process.append(int(getattr(d, "process_index", 0)))
            by_id[int(getattr(d, "id", pos))] = pos
        return cls(names, sizes, coords, process, by_id)

    def position(self, member: int, use_global_ids: bool) -> Optional[int]:
        if use_global_ids and member in self.by_device_id:
            return self.by_device_id[member]
        return member if member < len(self.coords) else None


def _strides(sizes: Sequence[int]) -> List[int]:
    out, acc = [], 1
    for s in reversed(sizes):
        out.append(acc)
        acc *= s
    return list(reversed(out))


def map_axes(c: Collective, mesh: Optional[MeshInfo]) \
        -> Tuple[Tuple[str, ...], bool, bool]:
    """``(axes, exact, crosses_process)`` for one collective: the mesh
    axes whose coordinates vary inside its replica groups (or across its
    permute pairs). ``exact`` when every group's size equals the product
    of the varying axis sizes — i.e. the groups ARE that axis subgrid;
    a False means a partial/irregular group (reported as inexact, still
    attributed to the varying axes)."""
    if mesh is None:
        return ("unmapped",), False, False
    groups = c.groups
    if groups is None and c.pairs:
        groups = [[s, t] for s, t in c.pairs]
    if not groups:
        return (), True, False
    varying: set = set()
    crosses, sizes_ok = False, True
    for g in groups:
        pos = [mesh.position(m, c.use_global_ids) for m in g]
        if any(p is None for p in pos):
            return ("unmapped",), False, False
        ref = mesh.coords[pos[0]]
        gaxes = set()
        for p in pos[1:]:
            for ax, (a, b) in enumerate(zip(ref, mesh.coords[p])):
                if a != b:
                    gaxes.add(ax)
        varying |= gaxes
        procs = {mesh.process[p] for p in pos}
        crosses = crosses or len(procs) > 1
        want = 1
        for ax in gaxes:
            want *= mesh.axis_sizes[ax]
        if len(g) != want:
            sizes_ok = False
    if not varying:
        return (), True, crosses
    axes = tuple(mesh.axis_names[ax] for ax in sorted(varying))
    # permute pairs never cover the full axis subgrid pairwise; a ring
    # along one axis is exact by construction
    exact = sizes_ok or (c.pairs is not None and len(axes) == 1)
    return axes, exact, crosses


def wire_bytes(c: Collective) -> int:
    """Per-participant wire bytes per step (module-doc cost model)."""
    g = c.group_size
    p = c.payload_bytes
    if c.kind == "collective-permute":
        return p if c.pairs or c.groups else 0
    if g <= 1:
        return 0
    if c.kind == "all-reduce":
        return int(2 * (g - 1) * p / g)
    if c.kind == "all-gather":
        return int((g - 1) * p / g)
    if c.kind == "reduce-scatter":
        return (g - 1) * p
    if c.kind == "all-to-all":
        return int((g - 1) * p / g)
    return p


def comm_ledger(collectives: List[Collective],
                mesh: Optional[MeshInfo]) -> Dict[str, dict]:
    """Aggregate per mesh-axis key (``"dp"``, ``"dp+mp"`` for a group
    varying on both, ``"none"`` for degenerate single-member groups):
    op count, wire bytes/step, per-kind counts, hop class."""
    out: Dict[str, dict] = {}
    for c in collectives:
        axes, exact, crosses = map_axes(c, mesh)
        key = "+".join(axes) if axes else "none"
        slot = out.setdefault(key, {
            "ops": 0, "bytes": 0, "kinds": {}, "hops": "ici",
            "inexact_groups": 0})
        slot["ops"] += 1
        slot["bytes"] += wire_bytes(c)
        slot["kinds"][c.kind] = slot["kinds"].get(c.kind, 0) + 1
        if crosses:
            slot["hops"] = "dcn"
        if not exact:
            slot["inexact_groups"] += 1
    return out


# -- def-use chase (implicit / redundant reshard) ---------------------------

def _def_maps(hlo_text: str):
    """``(defs, entry_params, param_labels)``: per-computation
    ``name -> (opcode, operand names)``, the entry computation's
    ``param name -> parameter number``, and ``parameter number -> leaf
    label`` from the op_name metadata jax stamps on entry parameters
    (``train[\\'0.bias\\']``)."""
    defs: Dict[str, Dict[str, Tuple[str, Tuple[str, ...]]]] = {}
    entry_params: Dict[str, int] = {}
    param_labels: Dict[int, str] = {}
    comp, entry = "", False
    op_re = re.compile(
        r"^\s*(?:ROOT\s+)?(%[\w.\-]+)\s*=\s*"
        r"(?:\([^=]*?\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s*"
        r"([a-z][a-z0-9\-]*)\(")
    for raw in hlo_text.splitlines():
        cm = _COMPUTATION_RE.match(raw)
        if cm:
            comp = cm.group(2).lstrip("%")
            entry = bool(cm.group(1))
            continue
        pm = _PARAM_RE.match(raw)
        if pm and entry:
            num = int(pm.group(2))
            entry_params[pm.group(1)] = num
            lm = _PARAM_LABEL_RE.search(raw)
            if lm:
                param_labels[num] = lm.group(1).replace("\\'", "'")
        m = op_re.match(raw)
        if not m:
            continue
        name, opcode = m.group(1), m.group(2)
        operands = tuple(t for t in _OPERAND_RE.findall(raw[m.end():])
                         if t != name)
        defs.setdefault(comp, {})[name] = (opcode, operands)
    return defs, entry_params, param_labels


#: opcodes a param chase may walk through — data-preserving moves only.
#: Anything arithmetic (dot, add, fusion, ...) stops the chase: a gather
#: of a *computed* tensor legitimately has parameters among its distant
#: ancestors, and flagging those would drown the real signal (the MoE
#: routing intermediates chase back to gate.weight through top_k and
#: einsums, and that is not a parameter re-materialization).
_TRANSPARENT_OPS = frozenset({
    "copy", "bitcast", "bitcast-convert", "convert", "reshape",
    "transpose", "broadcast", "get-tuple-element", "tuple",
    "optimization-barrier", "copy-start", "copy-done"})


def _chase_to_params(start_operands, local_defs, entry_params,
                     depth: int = 12) -> List[int]:
    """BFS from instruction operands back to entry parameter numbers,
    within one computation (HLO parameters are computation-local, so a
    chase never crosses a call boundary), walking only through
    :data:`_TRANSPARENT_OPS` so a hit means the gathered bytes ARE the
    parameter's bytes, not merely derived from them."""
    seen, hits = set(), []
    frontier = list(start_operands)
    for _ in range(depth):
        if not frontier:
            break
        nxt = []
        for name in frontier:
            if name in seen:
                continue
            seen.add(name)
            if name in entry_params:
                hits.append(entry_params[name])
                continue
            d = local_defs.get(name)
            if d is not None and d[0] in _TRANSPARENT_OPS:
                nxt.extend(d[1])
        frontier = nxt
    return hits


# -- report -----------------------------------------------------------------

@dataclass
class CommReport:
    """The comm-plan audit result for one compiled program."""
    label: str
    collectives: List[Collective] = field(default_factory=list)
    ledger: Dict[str, dict] = field(default_factory=dict)
    findings: List[Finding] = field(default_factory=list)

    @property
    def census(self) -> Dict[str, int]:
        out = {}
        for c in self.collectives:
            out[c.kind] = out.get(c.kind, 0) + 1
        return out

    def summary(self) -> dict:
        return {
            "label": self.label,
            "census": self.census,
            "ledger": self.ledger,
            "findings": [f.to_json() for f in self.findings],
        }


def audit_comm(hlo_text: str, label: str, mesh=None,
               leaf_names: Optional[List[str]] = None,
               gather_ok: bool = False,
               state_prefixes: Tuple[str, ...] = STATE_LEAF_PREFIXES,
               chase_depth: int = 12) -> CommReport:
    """Parse, map and defect-check one compiled program's comm plan.

    ``mesh``: a ``jax.sharding.Mesh`` or prebuilt :class:`MeshInfo`
    (None = single-program, everything lands in the ``unmapped``
    bucket). ``leaf_names``: entry-parameter leaf names aligned to
    parameter numbers (what ``audit._align_params`` produces) — enables
    the implicit-reshard pass. ``gather_ok``: the geometry legitimately
    gathers its state leaves (ZeRO re-materializes params every step),
    so the implicit-reshard pass stays quiet.
    """
    info = None
    if mesh is not None:
        info = mesh if isinstance(mesh, MeshInfo) else \
            MeshInfo.from_mesh(mesh)
    r = CommReport(label=label)
    r.collectives = parse_collectives(hlo_text)
    r.ledger = comm_ledger(r.collectives, info)

    defs, entry_params, param_labels = _def_maps(hlo_text)
    by_name: Dict[str, Collective] = {c.name: c for c in r.collectives}

    def leaf_label(pnum: int) -> str:
        # metadata label first (shard-shape-proof), caller-supplied
        # alignment as fallback, positional last
        if pnum in param_labels:
            return param_labels[pnum]
        if leaf_names and pnum < len(leaf_names):
            return leaf_names[pnum]
        return f"param{pnum}"

    # implicit reshard: an entry all-gather fed (transitively) by a
    # state leaf that this geometry must never gather
    if not gather_ok:
        for c in r.collectives:
            if c.kind != "all-gather" or not c.entry:
                continue
            local = defs.get(c.computation, {})
            for pnum in _chase_to_params(c.operands, local, entry_params,
                                         chase_depth):
                name = leaf_label(pnum)
                if not name.split("[")[0].split("'")[0].startswith(
                        state_prefixes):
                    continue
                axes, _, _ = map_axes(c, info)
                axkey = "+".join(axes) or "none"
                r.findings.append(Finding(
                    "implicit-reshard", P0, label, "commplan",
                    anchor=f"{name}@{axkey}",
                    message=(f"{c.kind} on axis '{axkey}' gathers state "
                             f"leaf {name} ({c.payload_bytes}B result) — "
                             f"its declared sharding should never need "
                             f"gathering; a sharding-spec typo or GSPMD "
                             f"propagation change re-materializes it "
                             f"every step"
                             + (f" ({c.source})" if c.source else "")),
                    data={"bytes": c.payload_bytes, "leaf": name,
                          "axes": axkey, "source": c.source}))
                break  # one finding per collective

    # redundant reshard: reduce-scatter directly downstream of an
    # all-gather on the same axes (gather immediately undone)
    for c in r.collectives:
        if c.kind != "reduce-scatter":
            continue
        local = defs.get(c.computation, {})
        seen, frontier = set(), list(c.operands)
        for _ in range(3):
            nxt = []
            for name in frontier:
                if name in seen:
                    continue
                seen.add(name)
                up = by_name.get(name)
                if up is not None and up.kind == "all-gather" \
                        and up.computation == c.computation:
                    ag_axes, _, _ = map_axes(up, info)
                    rs_axes, _, _ = map_axes(c, info)
                    if ag_axes == rs_axes:
                        r.findings.append(Finding(
                            "redundant-reshard", P1, label, "commplan",
                            anchor=f"{'+'.join(rs_axes) or 'none'}:"
                                   f"{c.payload_bytes}",
                            message=(f"all-gather immediately re-scattered "
                                     f"on axis "
                                     f"'{'+'.join(rs_axes) or 'none'}' "
                                     f"({up.payload_bytes}B gathered, "
                                     f"{c.payload_bytes}B shard) — the "
                                     f"round trip is pure waste"),
                            data={"gathered": up.payload_bytes,
                                  "shard": c.payload_bytes}))
                    continue
                d = local.get(name)
                if d is not None:
                    nxt.extend(d[1])
            frontier = nxt
    return r


def budget_findings(label: str, ledger: Dict[str, dict],
                    pinned: Optional[Dict[str, dict]],
                    tol: Optional[float] = None) -> List[Finding]:
    """Budget-drift pass: compare one geometry's ledger against its
    pinned baseline section. NEW axes, NEW collective kinds on a known
    axis, and bytes growth past ``tol`` are findings (P1); shrinkage is
    silent (re-pin to claim it). ``pinned`` None means the geometry has
    never been pinned — every axis reports as new."""
    if tol is None:
        tol = comm_tolerance()
    out: List[Finding] = []
    pinned = pinned or {}
    for axis, slot in sorted(ledger.items()):
        pin = pinned.get(axis)
        if pin is None:
            out.append(Finding(
                "comm-new-axis", P1, label, "commplan", anchor=axis,
                message=(f"collectives on unpinned axis '{axis}' "
                         f"({slot['ops']} op(s), {slot['bytes']}B/step) — "
                         f"new communication the budget never saw; "
                         f"re-pin with --write-baseline if intended"),
                data={"ops": slot["ops"], "bytes": slot["bytes"]}))
            continue
        for kind, n in sorted(slot["kinds"].items()):
            if kind not in pin.get("kinds", {}):
                out.append(Finding(
                    "comm-new-collective", P1, label, "commplan",
                    anchor=f"{axis}/{kind}",
                    message=(f"NEW collective kind {kind} (x{n}) on axis "
                             f"'{axis}' — the plan changed shape, not "
                             f"just size"),
                    data={"axis": axis, "kind": kind, "count": n}))
        if slot["bytes"] > pin.get("bytes", 0) * (1 + tol):
            out.append(Finding(
                "comm-budget-drift", P1, label, "commplan",
                anchor=axis,
                message=(f"axis '{axis}' moves {slot['bytes']}B/step, "
                         f"pinned {pin.get('bytes', 0)}B "
                         f"(+{tol:.0%} tolerance) — comm bytes grew past "
                         f"budget"),
                data={"bytes": slot["bytes"],
                      "pinned": pin.get("bytes", 0), "tol": tol}))
    return out
