"""Trace-safety and concurrency lint: the paid-for bug classes, as AST rules.

Prong 2 of the analysis subsystem (docs/ANALYSIS.md). Every rule here
codifies a bug this repo actually shipped and then fixed the hard way:

- ``gc-eager-jax`` (P0): jax/jnp array ops reachable from ``__del__``
  outside ``jax.core.eval_context()``. A GC-time flush that runs while
  *another* function is being traced stages its ops into that foreign
  trace and leaks tracers into live state (the nastiest bug of PR 7 —
  ``TrainStep.__del__`` → ``_flush_flat`` → jnp split).
- ``signal-unsafe-call`` (P0): lock/Event/Condition acquisition or
  metrics calls inside a signal handler. A handler that takes a lock
  deadlocks when the signal interrupts the main thread *holding* it
  (PR 4: preemption handlers write plain GIL-atomic attributes only).
- ``trace-attr-mutation`` (P0): assignment to ``self.<attr>`` inside a
  function that jax traces. The write happens once at trace time — or
  worse, stores a tracer on the object (the removed ``opt._cur_param``
  side channel).
- ``traced-impurity`` (P1): wall-clock / host-randomness calls inside
  traced functions — the value is baked at trace time, silently frozen
  across every subsequent step.
- ``unjoined-thread`` (P1): a non-daemon thread started but never
  joined anywhere in its module — blocks interpreter exit and leaks
  work past the owner's lifetime.
- ``blocking-call-under-lock`` (P0): ``time.sleep``, timeout-less
  ``.join()``/``.result()``/``.get()``/``.wait()`` inside a
  ``with <lock>`` body (depth-2 callees included) — the serving/
  prefetch stall class PR 4/5 paid for at runtime: whoever else wants
  that lock now waits on an unbounded sleep or join.
- ``stale-suppression`` (P2, advisory unless ``--strict-suppressions``):
  an ``# analysis: allow(<rule>)`` comment that no longer suppresses
  anything — allow-rot; either the flagged code was fixed (delete the
  comment) or the comment drifted away from the finding line.

The linter is deliberately *lexical*: it resolves calls one–two levels
deep within the same class/module and never imports the code it scans,
so it runs in milliseconds over the whole tree and can't be crashed by
import-time side effects. Cross-module reachability is out of scope —
the fixture tests in tests/test_analysis.py document the supported
shapes.

Suppression: a finding whose own line or enclosing ``def`` line carries
``# analysis: allow(<rule>)`` is intentionally accepted in place (use
for the rare case where the flagged pattern is the point, e.g. the
serving engine's trace-time compile counter). Everything else gates
against ``analysis/baseline.json`` fingerprints.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, P0, P1, iter_py_files

__all__ = ["lint_file", "lint_tree", "RULES"]

RULES = ("gc-eager-jax", "signal-unsafe-call", "trace-attr-mutation",
         "traced-impurity", "unjoined-thread", "blocking-call-under-lock",
         "stale-suppression")

#: dotted-name suffixes whose first argument is traced by jax
_TRACE_WRAPPERS = ("jax.jit", "jit", "jax.value_and_grad",
                   "value_and_grad", "jax.grad", "shard_map",
                   "shard_map_compat", "pallas_call", "jax.vmap", "vmap",
                   "jax.checkpoint", "jax.remat")
#: wall-clock / host-randomness dotted names (exact or prefix.)
_IMPURE_EXACT = {"time.time", "time.time_ns", "time.perf_counter",
                 "time.perf_counter_ns", "time.monotonic",
                 "time.monotonic_ns", "datetime.now", "datetime.utcnow",
                 "datetime.datetime.now", "datetime.datetime.utcnow"}
_IMPURE_RANDOM_FNS = {"random", "randint", "randn", "rand", "choice",
                      "uniform", "normal", "shuffle", "sample", "seed",
                      "permutation"}
#: method names whose invocation inside a signal handler can deadlock
#: (lock/CV traffic) or take the metrics-registry lock
_SIGNAL_UNSAFE_METHODS = {"acquire": "lock acquisition",
                          "wait": "condition/event wait",
                          "notify": "condition notify",
                          "notify_all": "condition notify",
                          "join": "thread join",
                          "inc": "metrics-registry lock",
                          "observe": "metrics-registry lock"}
_THREADING_PRIMITIVES = {"Lock", "RLock", "Condition", "Event",
                         "Semaphore", "BoundedSemaphore", "Barrier"}


def _dotted(node) -> Optional[str]:
    """``a.b.c`` for an Attribute/Name chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_name(call: ast.Call) -> Optional[str]:
    return _dotted(call.func)


class _Module:
    """Parsed module with the cheap indexes every rule shares."""

    def __init__(self, path: str, rel: str, text: str):
        self.path, self.rel = path, rel
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        # qualname per function node + name -> nodes index
        self.funcs: List[Tuple[ast.AST, str, Optional[str]]] = []
        self.by_name: Dict[str, List[ast.AST]] = {}
        self.methods: Dict[str, Dict[str, ast.AST]] = {}  # class -> name
        self.qual: Dict[ast.AST, str] = {}
        self.jnp_roots: Set[str] = set()
        self.np_aliases: Set[str] = set()
        #: ways signal.signal is callable here: "<alias>.signal"
        #: attribute forms and bare names from `from signal import ...`
        self.signal_attr_roots: Set[str] = {"signal"}
        self.signal_bare_names: Set[str] = set()
        #: (lineno, rule) allow-comments that suppressed something
        self.used_allows: Set[Tuple[int, str]] = set()
        self._index()

    def _index(self):
        def walk(node, prefix, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{prefix}{child.name}"
                    self.funcs.append((child, q, cls))
                    self.qual[child] = q
                    self.by_name.setdefault(child.name, []).append(child)
                    if cls is not None and "." not in q[len(cls) + 1:]:
                        self.methods.setdefault(cls, {})[child.name] = child
                    walk(child, q + ".", cls)
                elif isinstance(child, ast.ClassDef):
                    self.methods.setdefault(child.name, {})
                    walk(child, child.name + ".", child.name)
                else:
                    walk(child, prefix, cls)
        walk(self.tree, "", None)

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    if a.name in ("jax.numpy",):
                        self.jnp_roots.add(a.asname or "jax.numpy")
                    elif a.name == "numpy":
                        self.np_aliases.add(alias)
                    elif a.name == "signal":
                        self.signal_attr_roots.add(alias)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "numpy":
                            self.jnp_roots.add(a.asname or "numpy")
                elif node.module == "signal":
                    for a in node.names:
                        if a.name == "signal":
                            self.signal_bare_names.add(
                                a.asname or "signal")
        # `import jax` makes jax.numpy/jax.lax reachable by full path
        self.jnp_roots.update({"jnp", "jax.numpy"})

    def suppressed(self, rule: str, *linenos: int) -> bool:
        hit = False
        for ln in linenos:
            if 0 < ln <= len(self.lines) \
                    and f"analysis: allow({rule})" in self.lines[ln - 1]:
                # record every match so the stale-suppression pass knows
                # which allow comments actually earn their keep
                self.used_allows.add((ln, rule))
                hit = True
        return hit

    def resolve(self, name: str) -> List[ast.AST]:
        return self.by_name.get(name, [])

    def resolve_method(self, cls: Optional[str], name: str) \
            -> Optional[ast.AST]:
        if cls and name in self.methods.get(cls, {}):
            return self.methods[cls][name]
        return None


# -- traced-function rules --------------------------------------------------

def _traced_functions(mod: _Module) -> List[Tuple[ast.AST, str]]:
    """Functions (and lambdas) whose body jax traces: first args of the
    wrapper calls + decorated defs, plus their lexically nested defs."""
    roots: List[ast.AST] = []

    def wrapped_arg(call: ast.Call):
        name = _call_name(call)
        if name is None:
            return None
        if not any(name == w or name.endswith("." + w)
                   for w in _TRACE_WRAPPERS):
            return None
        if not call.args:
            return None
        arg = call.args[0]
        # functools.partial(kernel, ...) -> kernel
        if isinstance(arg, ast.Call) and (_call_name(arg) or "").endswith(
                "partial") and arg.args:
            arg = arg.args[0]
        return arg

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            arg = wrapped_arg(node)
            if isinstance(arg, ast.Name):
                roots.extend(mod.resolve(arg.id))
            elif isinstance(arg, ast.Lambda):
                roots.append(arg)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                name = _dotted(d) or ""
                if any(name == w or name.endswith("." + w)
                       for w in _TRACE_WRAPPERS):
                    roots.append(node)

    out, seen = [], set()
    for root in roots:
        for node in ast.walk(root):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and id(node) not in seen:
                seen.add(id(node))
                q = mod.qual.get(node, getattr(node, "name", "<lambda>"))
                out.append((node, q))
    return out


def _own_nodes(fn):
    """Nodes of ``fn``'s body excluding nested function/lambda subtrees
    (those are scanned under their own qualname — no double reports)."""
    out = []
    stack = list(fn.body) if isinstance(fn.body, list) else [fn.body]
    while stack:
        node = stack.pop()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                stack.append(child)
    return out


def _check_traced(mod: _Module, findings: List[Finding]):
    for fn, qual in _traced_functions(mod):
        def_line = getattr(fn, "lineno", 0)
        for node in _own_nodes(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        if mod.suppressed("trace-attr-mutation",
                                          node.lineno, def_line):
                            continue
                        findings.append(Finding(
                            "trace-attr-mutation", P0, mod.rel, qual,
                            anchor=t.attr, line=node.lineno,
                            message=(f"self.{t.attr} assigned inside a "
                                     f"jax-traced function — runs once at "
                                     f"trace time and can leak tracers "
                                     f"into live state (the _cur_param "
                                     f"class)")))
            elif isinstance(node, ast.Call):
                name = _call_name(node) or ""
                impure = name in _IMPURE_EXACT
                if not impure and "." in name:
                    root, leaf = name.rsplit(".", 1)
                    if leaf in _IMPURE_RANDOM_FNS and (
                            root == "random"
                            or root.endswith(".random")
                            or root in {f"{a}.random"
                                        for a in mod.np_aliases}):
                        impure = True
                if impure:
                    if mod.suppressed("traced-impurity", node.lineno,
                                      def_line):
                        continue
                    findings.append(Finding(
                        "traced-impurity", P1, mod.rel, qual,
                        anchor=name, line=node.lineno,
                        message=(f"{name}() inside a jax-traced function "
                                 f"— evaluated once at trace time, frozen "
                                 f"into the compiled program")))


# -- __del__ reachability ---------------------------------------------------

def _check_gc_paths(mod: _Module, findings: List[Finding]):
    for cls, methods in mod.methods.items():
        dtor = methods.get("__del__")
        if dtor is None:
            continue
        # BFS self.<m>() within the class plus module-level Name calls
        seen: Set[int] = set()
        frontier = [(dtor, mod.qual.get(dtor, f"{cls}.__del__"))]
        depth = 0
        while frontier and depth <= 3:
            nxt = []
            for fn, qual in frontier:
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                _scan_eager_jax(mod, fn, qual, cls, findings, nxt)
            frontier, depth = nxt, depth + 1


def _scan_eager_jax(mod: _Module, fn, qual, cls, findings, frontier):
    """Flag jnp/jax.lax/jax.random calls in ``fn`` not under
    ``eval_context``; queue same-class/module callees. The guard flag
    follows arbitrary nesting (an ``eval_context`` with-block under an
    ``if``/``try`` still guards its body)."""
    def visit(node, guarded):
        if isinstance(node, ast.With):
            g = guarded
            for item in node.items:
                nm = _call_name(item.context_expr) \
                    if isinstance(item.context_expr, ast.Call) \
                    else _dotted(item.context_expr)
                if nm and "eval_context" in nm:
                    g = True
                visit(item.context_expr, guarded)
            for child in node.body:
                visit(child, g)
            return
        if isinstance(node, ast.Call):
            name = _call_name(node) or ""
            root = name.rsplit(".", 1)[0] if "." in name else ""
            if not guarded and (root in mod.jnp_roots
                                or root in ("jax.lax", "jax.random",
                                            "lax")
                                or name.startswith("jax.numpy.")):
                if not mod.suppressed("gc-eager-jax", node.lineno,
                                      getattr(fn, "lineno", 0)):
                    findings.append(Finding(
                        "gc-eager-jax", P0, mod.rel, qual,
                        anchor=name, line=node.lineno,
                        message=(f"{name}() reachable from __del__ "
                                 f"outside jax.core.eval_context() — "
                                 f"a GC-time run during another "
                                 f"function's trace stages ops into "
                                 f"that trace (the PR 7 flush leak)")))
            # queue callees (self.m() / module fn) for the BFS
            if isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                callee = mod.resolve_method(cls, node.func.attr)
                if callee is not None:
                    frontier.append(
                        (callee, mod.qual.get(callee, node.func.attr)))
            elif isinstance(node.func, ast.Name):
                for callee in mod.resolve(node.func.id):
                    frontier.append(
                        (callee, mod.qual.get(callee, node.func.id)))
        for child in ast.iter_child_nodes(node):
            visit(child, guarded)

    for stmt in (fn.body if isinstance(fn.body, list) else [fn.body]):
        visit(stmt, False)


# -- signal handlers --------------------------------------------------------

def _handler_nodes(mod: _Module):
    """(handler_fn_node, qualname, class) for every function installed
    via ``signal.signal(signum, handler)``."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node) or ""
        registers = (
            name in mod.signal_bare_names                # from signal import signal
            or any(name == f"{root}.signal"
                   for root in mod.signal_attr_roots)    # signal/sig.signal
            or name.split(".")[-2:] == ["signal", "signal"])
        if not registers:
            continue
        if len(node.args) < 2:
            continue
        h = node.args[1]
        if isinstance(h, ast.Attribute) and isinstance(h.value, ast.Name) \
                and h.value.id == "self":
            # enclosing class: find the method whose body contains node
            for cls, methods in mod.methods.items():
                m = methods.get(h.attr)
                if m is not None:
                    out.append((m, mod.qual.get(m, h.attr), cls))
        elif isinstance(h, ast.Name):
            for fn in mod.resolve(h.id):
                out.append((fn, mod.qual.get(fn, h.id), None))
        elif isinstance(h, ast.Lambda):
            out.append((h, "<lambda handler>", None))
    return out


def _check_signal_handlers(mod: _Module, findings: List[Finding]):
    for handler, qual, cls in _handler_nodes(mod):
        seen: Set[int] = set()
        frontier = [(handler, qual)]
        depth = 0
        while frontier and depth <= 2:
            nxt = []
            for fn, q in frontier:
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                _scan_signal_unsafe(mod, fn, q, cls, findings, nxt)
            frontier, depth = nxt, depth + 1


def _scan_signal_unsafe(mod: _Module, fn, qual, cls, findings, frontier):
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    def_line = getattr(fn, "lineno", 0)
    for node in [n for stmt in body for n in ast.walk(stmt)]:
        if isinstance(node, ast.With):
            for item in node.items:
                nm = (_call_name(item.context_expr)
                      if isinstance(item.context_expr, ast.Call)
                      else _dotted(item.context_expr)) or ""
                leaf = nm.split(".")[-1].lower()
                if "lock" in leaf or leaf in ("_cv", "cv", "cond",
                                              "condition"):
                    if not mod.suppressed("signal-unsafe-call",
                                          node.lineno, def_line):
                        findings.append(Finding(
                            "signal-unsafe-call", P0, mod.rel, qual,
                            anchor=f"with:{nm}", line=node.lineno,
                            message=(f"`with {nm}` in signal-handler "
                                     f"context — deadlocks when the "
                                     f"signal interrupts the holder")))
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node) or ""
        leaf = name.split(".")[-1]
        reason = None
        if isinstance(node.func, ast.Attribute) \
                and leaf in _SIGNAL_UNSAFE_METHODS:
            reason = _SIGNAL_UNSAFE_METHODS[leaf]
        elif leaf in _THREADING_PRIMITIVES and (
                name == leaf or name.startswith("threading.")):
            reason = "threading-primitive construction"
        if reason is not None:
            if not mod.suppressed("signal-unsafe-call", node.lineno,
                                  def_line):
                findings.append(Finding(
                    "signal-unsafe-call", P0, mod.rel, qual,
                    anchor=name, line=node.lineno,
                    message=(f"{name}() in signal-handler context "
                             f"({reason}) — only plain GIL-atomic "
                             f"attribute writes are safe; defer the "
                             f"rest to the next poll")))
        # follow self.m() / module-fn callees
        if isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            callee = mod.resolve_method(cls, node.func.attr)
            if callee is not None:
                frontier.append((callee,
                                 mod.qual.get(callee, node.func.attr)))
        elif isinstance(node.func, ast.Name):
            for callee in mod.resolve(node.func.id):
                frontier.append((callee,
                                 mod.qual.get(callee, node.func.id)))


# -- blocking calls under a lock --------------------------------------------

def _lock_item_names(node: ast.With) -> List[str]:
    """Dotted names of with-items that look like lock/CV acquisitions
    (same heuristic the signal pass uses — ONE definition of 'lock')."""
    names = []
    for item in node.items:
        nm = (_call_name(item.context_expr)
              if isinstance(item.context_expr, ast.Call)
              else _dotted(item.context_expr)) or ""
        leaf = nm.split(".")[-1].lower()
        if "lock" in leaf or leaf in ("_cv", "cv", "cond", "condition"):
            names.append(nm)
    return names


def _blocking_reason(node: ast.Call, lock_names) -> Optional[str]:
    """Why this call must not run while holding a lock, or None."""
    name = _call_name(node) or ""
    if name in ("time.sleep", "sleep"):
        return "sleeps while holding the lock"
    if not isinstance(node.func, ast.Attribute):
        return None
    leaf = name.split(".")[-1]
    has_timeout = bool(node.args) or any(
        kw.arg == "timeout" for kw in node.keywords)
    if leaf == "join" and not has_timeout:
        return "timeout-less .join() blocks until the thread exits"
    if leaf == "result" and not has_timeout:
        return "timeout-less Future.result() blocks on the executor"
    if leaf == "get" and not node.args and not node.keywords:
        return "timeout-less Queue.get() blocks until a producer runs"
    if leaf == "wait" and not has_timeout:
        # cv.wait() on the with-item itself RELEASES that lock while
        # waiting — the canonical condition-variable pattern, not a hold
        if (_dotted(node.func.value) or "") in lock_names:
            return None
        return "timeout-less .wait() holds the lock across the wait"
    return None


def _scan_blocking(mod: _Module, nodes, qual, cls, lock_names, lock_name,
                   sup_lines, findings, frontier):
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        reason = _blocking_reason(node, lock_names)
        if reason is not None:
            name = _call_name(node) or "<call>"
            if not mod.suppressed("blocking-call-under-lock",
                                  node.lineno, *sup_lines):
                findings.append(Finding(
                    "blocking-call-under-lock", P0, mod.rel, qual,
                    anchor=f"{lock_name}:{name}", line=node.lineno,
                    message=(f"{name}() inside `with {lock_name}` — "
                             f"{reason}; every other taker of the lock "
                             f"stalls behind it (the serving/prefetch "
                             f"deadlock class)")))
        # queue self.m() / module-fn callees: they run under the lock too
        if isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            callee = mod.resolve_method(cls, node.func.attr)
            if callee is not None:
                frontier.append((callee,
                                 mod.qual.get(callee, node.func.attr)))
        elif isinstance(node.func, ast.Name):
            for callee in mod.resolve(node.func.id):
                frontier.append((callee,
                                 mod.qual.get(callee, node.func.id)))


def _check_blocking_under_lock(mod: _Module, findings: List[Finding]):
    for fn, qual, cls in mod.funcs:
        def_line = getattr(fn, "lineno", 0)
        for node in _own_nodes(fn):
            if not isinstance(node, ast.With):
                continue
            locks = _lock_item_names(node)
            if not locks:
                continue
            lock_name = locks[0]
            body_nodes = []
            stack = list(node.body)
            while stack:
                n = stack.pop()
                body_nodes.append(n)
                for child in ast.iter_child_nodes(n):
                    # a def/lambda created under the lock runs later,
                    # not here
                    if not isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)):
                        stack.append(child)
            seen: Set[int] = set()
            frontier: List[Tuple[ast.AST, str]] = []
            _scan_blocking(mod, body_nodes, qual, cls, locks, lock_name,
                           (node.lineno, def_line), findings, frontier)
            depth = 1
            while frontier and depth <= 2:
                nxt: List[Tuple[ast.AST, str]] = []
                for callee, cq in frontier:
                    if id(callee) in seen:
                        continue
                    seen.add(id(callee))
                    # inside a callee the cv-receiver exception can't be
                    # tracked — pass no lock_names, flag every wait()
                    _scan_blocking(
                        mod, _own_nodes(callee), cq, cls, (), lock_name,
                        (getattr(callee, "lineno", 0), node.lineno,
                         def_line), findings, nxt)
                frontier, depth = nxt, depth + 1


# -- stale suppressions ------------------------------------------------------

_ALLOW_RE = None  # compiled lazily; ast is imported, re is not yet


def _check_stale_suppressions(mod: _Module, findings: List[Finding]):
    """Every ``# analysis: allow(<rule>)`` comment that no check
    consulted is allow-rot: either the finding it silenced was fixed
    (delete the comment) or it drifted off the line the checks look at.
    Runs LAST — it reads ``mod.used_allows`` filled by the other rules."""
    global _ALLOW_RE
    if _ALLOW_RE is None:
        import re
        _ALLOW_RE = re.compile(r"#\s*analysis:\s*allow\(([\w\-]+)\)")
    from .findings import P2
    for ln, line in enumerate(mod.lines, start=1):
        for m in _ALLOW_RE.finditer(line):
            rule = m.group(1)
            if (ln, rule) in mod.used_allows:
                continue
            code = line[:m.start()].split("#")[0].strip()
            findings.append(Finding(
                "stale-suppression", P2, mod.rel, "<module>",
                anchor=f"{rule}@{code[:60]}", line=ln,
                message=(f"allow({rule}) suppresses nothing "
                         f"{'(unknown rule) ' if rule not in RULES else ''}"
                         f"— the finding was fixed or the comment "
                         f"drifted; delete it")))


# -- threads ----------------------------------------------------------------

def _check_threads(mod: _Module, findings: List[Finding]):
    joined: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            tgt = _dotted(node.func.value)
            if tgt:
                joined.add(tgt)

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node) or ""
        if name not in ("threading.Thread", "Thread"):
            continue
        daemon = None
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        if daemon:
            continue  # dies with the process; join is optional
        # the target this Thread lands in (t = ... / self._t = ...)
        target = None
        parent = getattr(node, "_pt_parent", None)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            target = _dotted(parent.targets[0])
        if target and target in joined:
            continue
        if mod.suppressed("unjoined-thread", node.lineno):
            continue
        findings.append(Finding(
            "unjoined-thread", P1, mod.rel,
            target or "<unassigned>", anchor=target or f"L{node.lineno}",
            line=node.lineno,
            message=("non-daemon Thread started with no .join() in this "
                     "module — blocks interpreter exit / leaks work past "
                     "its owner" if target else
                     "non-daemon Thread constructed inline (no handle to "
                     "join) — set daemon=True or keep a joinable handle")))


def _annotate_parents(tree):
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._pt_parent = node


# -- entry points -----------------------------------------------------------

def lint_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    with open(path) as f:
        text = f.read()
    try:
        mod = _Module(path, rel or path, text)
    except SyntaxError as e:
        return [Finding("parse-error", P1, rel or path, "<module>",
                        anchor=str(e.lineno), line=e.lineno or 0,
                        message=f"file does not parse: {e.msg}")]
    _annotate_parents(mod.tree)
    findings: List[Finding] = []
    _check_traced(mod, findings)
    _check_gc_paths(mod, findings)
    _check_signal_handlers(mod, findings)
    _check_threads(mod, findings)
    _check_blocking_under_lock(mod, findings)
    # must run after every suppressible check has queried mod.suppressed
    _check_stale_suppressions(mod, findings)
    return findings


def lint_tree(root: Optional[str] = None,
              extra_files: Tuple[str, ...] = ()) -> List[Finding]:
    """Lint every ``.py`` file under ``root`` (default: the installed
    ``paddle_tpu`` package) plus ``extra_files``; repo-relative paths in
    the findings keep fingerprints machine-independent."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = os.path.dirname(os.path.abspath(root))
    findings: List[Finding] = []
    targets = iter_py_files(root) + list(extra_files)
    for path in targets:
        rel = os.path.relpath(path, base)
        findings.extend(lint_file(path, rel))
    return findings
