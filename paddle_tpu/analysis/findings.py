"""Findings, severities, fingerprints and the committed baseline.

Every analysis pass (HLO audit or AST lint) reports :class:`Finding`
records. A finding's **fingerprint** is a stable hash of *what* it is —
rule id, file, enclosing definition and a rule-specific anchor — and
deliberately excludes line numbers, so unrelated edits above a known
finding don't churn the baseline.

The committed ``paddle_tpu/analysis/baseline.json`` is the accepted-debt
ledger: a finding whose fingerprint is listed there is *known* (tracked,
with a note saying why it's allowed or what the TODO is); any finding
NOT in the baseline is **new** and fails CI. This is the same workflow
as a lint-suppress file, but content-addressed — moving code around
doesn't silently re-admit a fixed bug class.
"""
from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["Finding", "Baseline", "BaselineError", "baseline_path",
           "load_baseline", "SEVERITIES", "P0", "P1", "P2", "repo_root",
           "iter_py_files"]


class BaselineError(RuntimeError):
    """The committed baseline is unusable (corrupt JSON, or a required
    section is missing). The CLI turns this into exit 2 plus a one-line
    hint instead of a traceback."""

    def __init__(self, path: str, problem: str):
        self.path, self.problem = path, problem
        super().__init__(
            f"baseline {path}: {problem} — run `python -m "
            f"paddle_tpu.analysis commplan --write-baseline` (or restore "
            f"the committed file) to regenerate it")


def repo_root() -> str:
    """The checkout root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def iter_py_files(root: str) -> List[str]:
    """Deterministic ``.py`` walk shared by the lint and knob passes —
    ONE place decides what gets scanned (sorted, ``__pycache__``
    skipped), so the two registries can't silently diverge."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames) if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                   if f.endswith(".py"))
    return out

#: severity model (docs/ANALYSIS.md): P0 = a paid-for bug class
#: (deadlock, trace leak, silent wrong numbers, memory doubling);
#: P1 = performance/memory smell worth a look; P2 = hygiene.
P0, P1, P2 = "P0", "P1", "P2"
SEVERITIES = (P0, P1, P2)

#: default committed baseline, next to this module; override with
#: PADDLE_TPU_ANALYSIS_BASELINE or an explicit --baseline path.
_DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def baseline_path(explicit: Optional[str] = None) -> str:
    if explicit:
        return explicit
    return os.environ.get("PADDLE_TPU_ANALYSIS_BASELINE", _DEFAULT_BASELINE)


@dataclass
class Finding:
    """One analysis result.

    ``anchor`` is the rule-specific identity fragment (an attribute
    name, a parameter path, a shape) that — together with rule, path and
    ``where`` (the enclosing class/function or program label) — makes
    the fingerprint stable across line-number drift.
    """
    rule: str
    severity: str
    path: str          # repo-relative file, or a program label for audits
    where: str         # qualname of the enclosing def / program section
    message: str
    anchor: str = ""
    line: int = 0      # 1-based source line (0 for HLO-level findings)
    data: dict = field(default_factory=dict)

    @property
    def fingerprint(self) -> str:
        key = "\x1f".join((self.rule, self.path, self.where, self.anchor))
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return (f"[{self.severity}] {self.rule} {loc} ({self.where}) "
                f"{self.message}  fp={self.fingerprint}")

    def to_json(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "where": self.where, "line": self.line,
                "message": self.message, "anchor": self.anchor,
                "fingerprint": self.fingerprint, **(
                    {"data": self.data} if self.data else {})}


class Baseline:
    """The accepted-findings ledger (``baseline.json``).

    Layout::

        {"version": 1,
         "findings": {"<fingerprint>": {"rule": ..., "path": ...,
                                        "note": "why this is accepted"}},
         "audit": {"<metric>": <pinned number>, ...}}

    ``findings`` gates all prongs; ``audit`` additionally pins headline
    numbers for the committed bench geometry (consumed by the regression
    tests, informational for the CLI); ``commplan`` pins the per-axis
    comm ledger per committed geometry (``{geometry: {axis: {"ops": n,
    "bytes": b, "kinds": {...}}}}``) that the budget-drift pass gates
    against.
    """

    def __init__(self, doc: Optional[dict] = None, path: Optional[str] = None):
        doc = doc or {}
        self.path = path
        self.findings: Dict[str, dict] = dict(doc.get("findings", {}))
        self.audit: Dict[str, float] = dict(doc.get("audit", {}))
        self.commplan: Dict[str, dict] = dict(doc.get("commplan", {}))

    # -- gating ------------------------------------------------------------
    def split(self, findings: List[Finding]):
        """(new, known, stale): findings not in the ledger, findings in
        it, and ledger entries no fresh finding matched (fixed debt that
        can be pruned)."""
        seen = set()
        new, known = [], []
        for f in findings:
            fp = f.fingerprint
            seen.add(fp)
            (known if fp in self.findings else new).append(f)
        stale = {fp: meta for fp, meta in self.findings.items()
                 if fp not in seen}
        return new, known, stale

    # -- mutation ----------------------------------------------------------
    def accept(self, findings: List[Finding], note: str = ""):
        for f in findings:
            self.findings[f.fingerprint] = {
                "rule": f.rule, "severity": f.severity, "path": f.path,
                "where": f.where, "note": note or f.message}

    def to_json(self) -> dict:
        doc = {"version": 1, "findings": self.findings, "audit": self.audit}
        if self.commplan:
            doc["commplan"] = self.commplan
        return doc

    def save(self, path: Optional[str] = None):
        path = path or self.path
        if not path:
            raise ValueError("no baseline path to save to")
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")


def load_baseline(path: Optional[str] = None) -> Baseline:
    """Load the committed baseline (missing file = empty ledger, so a
    fresh checkout without one simply reports everything as new; a file
    that exists but does not parse raises :class:`BaselineError` — a
    truncated merge must fail loudly, not masquerade as zero debt)."""
    p = baseline_path(path)
    try:
        with open(p) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return Baseline({}, path=p)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise BaselineError(p, f"corrupt JSON ({e})") from e
    if not isinstance(doc, dict):
        raise BaselineError(p, f"expected a JSON object, got "
                               f"{type(doc).__name__}")
    return Baseline(doc, path=p)
