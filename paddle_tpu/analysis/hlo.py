"""Compiled-HLO text analysis: the lowered program as a readable artifact.

Every pass here is pure text → data over ``Compiled.as_text()`` output
(the string ``TrainStep.compiled_hlo()`` / ``ServingEngine.compiled_hlo()``
return), so the audits run identically on the CPU smoke box and on chip,
need no XLA internals, and can be unit-tested on doctored fragments.

What the text reliably carries (verified on the pinned jax):

- the module header's ``input_output_alias={ {out}: (param, {...}, kind) }``
  map — buffer donation survives into the compiled module even on CPU,
  where the runtime ignores it;
- ``entry_computation_layout={(<param shapes>)->(<result shapes>)}`` —
  one entry per flattened argument leaf, in ``jax.tree_util`` flatten
  order (which is how :mod:`paddle_tpu.analysis.audit` names leaves);
- one instruction per line, ``%name = dtype[dims]{layout} op(...)``,
  with collective ops spelled ``all-reduce`` / ``all-reduce-start`` /
  ``all-gather`` / ``reduce-scatter`` / ``collective-permute`` /
  ``all-to-all`` and ``metadata={... source_file=... source_line=...}``
  attribution where available.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["parse_entry_params", "donated_params", "collective_census",
           "iter_ops", "shape_bytes", "upcast_ops", "largest_ops",
           "HloOp"]

#: bytes per element for HLO dtype tokens (tokens not listed — tuples,
#: opaque, token — contribute 0, i.e. are never "large")
DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "s32": 4, "u32": 4, "s64": 8, "u64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "bf16": 2, "f16": 2, "f32": 4, "f64": 8, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
#: `%name = dtype[shape]{layout} opname(` — the instruction form; the
#: leading %/ROOT guard keeps computation headers and operands out
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*"
    r"(?:\(.*?\)|([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?)\s*"
    r"([a-z][a-z0-9\-]*)\(")
_ALIAS_ENTRY_RE = re.compile(r"\{[0-9,\s]*\}:\s*\((\d+)")
_ENTRY_RE = re.compile(r"entry_computation_layout=\{\((.*?)\)->")
_METADATA_RE = re.compile(
    r'source_file="([^"]*)"(?:\s+source_line=(\d+))?')

#: collective instruction stems (async forms counted once via -start;
#: *-done carries no second payload)
COLLECTIVE_STEMS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")


def shape_bytes(dtype: str, dims_csv: str) -> int:
    """Byte size of ``dtype[dims]`` (scalar when dims empty)."""
    unit = DTYPE_BYTES.get(dtype, 0)
    if not dims_csv:
        return unit
    n = 1
    for d in dims_csv.split(","):
        if d:
            n *= int(d)
    return n * unit


@dataclass
class HloOp:
    """One parsed instruction line."""
    opcode: str
    dtype: str
    dims: Tuple[int, ...]
    nbytes: int
    line: str
    source: str = ""  # "file:line" from metadata when present

    @property
    def shape(self) -> str:
        return f"{self.dtype}[{','.join(map(str, self.dims))}]"


def iter_ops(hlo_text: str) -> List[HloOp]:
    """Every instruction with a single (non-tuple) array result."""
    out = []
    for line in hlo_text.splitlines():
        m = _OP_RE.match(line)
        if not m or m.group(1) is None:
            continue
        dtype, dims_csv, opcode = m.group(1), m.group(2), m.group(3)
        dims = tuple(int(d) for d in dims_csv.split(",") if d)
        src = ""
        sm = _METADATA_RE.search(line)
        if sm:
            src = sm.group(1) + (f":{sm.group(2)}" if sm.group(2) else "")
        out.append(HloOp(opcode, dtype, dims,
                         shape_bytes(dtype, dims_csv), line.strip(), src))
    return out


def _split_top(s: str) -> List[str]:
    """Split on commas at bracket depth 0 (shapes carry commas inside
    both ``[...]`` and layout ``{...}``)."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        parts.append(tail)
    return parts


def parse_entry_params(hlo_text: str) -> List[Tuple[str, Tuple[int, ...],
                                                    int]]:
    """``[(dtype, dims, nbytes)]`` per entry parameter, in parameter
    order — one entry per flattened argument leaf."""
    m = _ENTRY_RE.search(hlo_text)
    if not m:
        return []
    # XLA interleaves /*index=N*/ position comments into long layouts
    body = re.sub(r"/\*.*?\*/", "", m.group(1))
    out = []
    for tok in _split_top(body):
        sm = _SHAPE_RE.match(tok)
        if not sm:
            out.append(("opaque", (), 0))
            continue
        dtype, dims_csv = sm.group(1), sm.group(2)
        dims = tuple(int(d) for d in dims_csv.split(",") if d)
        out.append((dtype, dims, shape_bytes(dtype, dims_csv)))
    return out


def _balanced_braces(text: str, start: int) -> str:
    """Content of the ``{...}`` group opening at ``text[start]`` (the
    alias map nests braces, so a regex can't delimit it)."""
    assert text[start] == "{"
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start + 1:i]
    return text[start + 1:]


def donated_params(hlo_text: str) -> set:
    """Parameter numbers that alias an output (i.e. whose buffer the
    donation actually landed in)."""
    key = "input_output_alias="
    i = hlo_text.find(key)
    if i < 0:
        return set()
    body = _balanced_braces(hlo_text, i + len(key))
    return {int(g) for g in _ALIAS_ENTRY_RE.findall(body)}


def collective_census(hlo_text: str) -> Dict[str, int]:
    """Instruction count per collective stem. Async pairs count once
    (``-start`` carries the payload; ``-done`` is just the wait)."""
    census = {stem: 0 for stem in COLLECTIVE_STEMS}
    for stem in COLLECTIVE_STEMS:
        census[stem] = len(re.findall(
            rf"= [^=]*\b{stem}(?:-start)?\(", hlo_text))
    return census


def upcast_ops(hlo_text: str, min_bytes: int = 0,
               ops: Optional[List[HloOp]] = None) -> List[HloOp]:
    """``convert`` instructions producing f32/f64 from a narrower float
    operand — the silent-upcast class (a bf16 model paying f32 memory
    bandwidth for an intermediate it never asked for). ``ops`` reuses
    a prior :func:`iter_ops` parse (the text can be tens of MB on the
    chip geometry)."""
    out = []
    for op in (iter_ops(hlo_text) if ops is None else ops):
        if op.opcode != "convert" or op.dtype not in ("f32", "f64"):
            continue
        if op.nbytes < min_bytes:
            continue
        # operand dtype rides the line: convert(bf16[...] %x)
        m = re.search(r"convert\(([a-z][a-z0-9]*)\[", op.line)
        if not m or m.group(1) not in ("bf16", "f16", "f8e4m3fn", "f8e5m2"):
            continue
        out.append(op)
    return out


def largest_ops(hlo_text: str, top: int = 5,
                exclude: Tuple[str, ...] = ("parameter",),
                ops: Optional[List[HloOp]] = None) -> List[HloOp]:
    """The ``top`` largest instruction results by bytes — the giant-
    intermediate detector (a ``[B, seq, vocab]`` logits tensor dwarfs
    everything else in a train step)."""
    pool = [o for o in (iter_ops(hlo_text) if ops is None else ops)
            if o.opcode not in exclude]
    pool.sort(key=lambda o: o.nbytes, reverse=True)
    return pool[:top]
