"""Compiled-program audit: machine-checked invariants over real programs.

Prong 1 of the analysis subsystem (docs/ANALYSIS.md). Each audit takes a
*compiled* step — ``TrainStep`` or ``ServingEngine`` through their
``compiled_hlo()`` inspection seams — and runs the :mod:`.hlo` text
passes plus the host-side contract checks that need the step object:

- **collective census + bucketed-dp contract**: the bucketed path's HLO
  must carry exactly ``len(buckets) + 1`` all-reduces (one per bucket,
  one scalar-loss pmean — docs/PERFORMANCE.md). More means the
  per-param all-reduce storm is back (the GSPMD regression PR 7 counted
  by hand); fewer means a bucket got silently dropped.
- **donation coverage**: every train-param and optimizer-state leaf must
  alias an output buffer. An undonated hot buffer is the 2x-memory
  class — XLA keeps both the old and new copy live across the step.
- **upcasts + giant intermediates**: f32 ``convert``s reachable from
  bf16 inputs, and the largest instruction results (the ``[B, seq,
  vocab]`` logits tensor is the ROADMAP fused-CE target; its byte size
  is that item's before/after metric).
- **recompile diff** (:func:`diff_compile_keys`): name the exact
  aval/leaf two compile keys disagree on, instead of staring at two
  opaque cache keys.

Findings fingerprint against ``analysis/baseline.json`` like lint
findings; the numeric summary feeds ``bench.py --audit``'s report-gate
headlines.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import hlo as H
from .findings import Finding, P0, P1, P2

__all__ = ["ProgramAudit", "audit_program", "audit_train_step",
           "audit_serving_engine", "diff_compile_keys",
           "recompile_report", "train_step_arg_names"]

#: an undonated/upcast buffer below this size is noise, not a finding
#: (the tiny CPU-smoke geometries still produce meaningful reports
#: because the thresholds scale with the audited program via kwargs)
DEFAULT_LARGE_BYTES = 1 << 20

#: positional arg names of the compiled TrainStep ``pure`` function —
#: used to give HLO entry parameters human names (train['w'] etc.)
TRAIN_STEP_ARGS = ("train", "frozen", "buffers", "states", "group_lrs",
                   "rng", "batch")
SERVING_STEP_ARGS = ("state", "tokens", "k_pools", "v_pools",
                     "block_tables", "cu_seqlens", "context_lens",
                     "seq_ids", "positions", "step_seq_map",
                     "step_block_map", "last_idx")


@dataclass
class ProgramAudit:
    """The audit result for one compiled program."""
    label: str
    collectives: Dict[str, int] = field(default_factory=dict)
    #: [(name, dtype, dims, nbytes, donated)] per entry parameter
    params: List[tuple] = field(default_factory=list)
    donated_bytes: int = 0
    undonated_bytes: int = 0
    #: requested-donation leaves that did NOT alias an output
    donation_misses: List[tuple] = field(default_factory=list)
    upcasts: List[H.HloOp] = field(default_factory=list)
    largest: List[H.HloOp] = field(default_factory=list)
    findings: List[Finding] = field(default_factory=list)

    @property
    def all_reduce_count(self) -> int:
        return self.collectives.get("all-reduce", 0)

    @property
    def largest_intermediate_bytes(self) -> int:
        return self.largest[0].nbytes if self.largest else 0

    @property
    def donation_coverage(self) -> float:
        """Donated fraction of the bytes that *should* be donated
        (donated + missed); 1.0 when nothing was expected."""
        missed = sum(nb for _, nb in self.donation_misses)
        want = self.donated_bytes + missed
        return self.donated_bytes / want if want else 1.0

    def summary(self) -> dict:
        return {
            "label": self.label,
            "all_reduce_count": self.all_reduce_count,
            "collectives": {k: v for k, v in self.collectives.items() if v},
            "donated_bytes": self.donated_bytes,
            "undonated_bytes": self.undonated_bytes,
            "donation_coverage": round(self.donation_coverage, 4),
            "donation_misses": [n for n, _ in self.donation_misses],
            "upcast_count": len(self.upcasts),
            "largest_intermediate_bytes": self.largest_intermediate_bytes,
            "largest_intermediates": [
                {"shape": o.shape, "op": o.opcode, "bytes": o.nbytes,
                 "source": o.source} for o in self.largest],
            "findings": [f.to_json() for f in self.findings],
        }


def _align_params(entry_params, leaves_with_names):
    """Match HLO entry parameters (kept args, in order) to flattened
    argument leaves (all args, in order): jit drops unused leaves at
    lowering, so alignment is a sequential merge on (dtype, dims)."""
    out = []
    li = 0
    for dtype, dims, nbytes in entry_params:
        name, donated = f"param{len(out)}", False
        scan = li
        while scan < len(leaves_with_names):
            lname, ldtype, ldims, ldonated = leaves_with_names[scan]
            scan += 1
            if ldtype == dtype and tuple(ldims) == tuple(dims):
                name, donated = lname, ldonated
                li = scan  # consume only up to the match
                break
        out.append((name, dtype, dims, nbytes, donated))
    return out


def _leaf_names(args_info, arg_names):
    """Flatten a ``Lowered.args_info`` pytree into
    ``[(name, dtype, dims, donation_requested)]`` in flatten order."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path(args_info)
    out = []
    for path, info in leaves:
        label = jax.tree_util.keystr(path)
        # paths look like [0][0]['w'] — replace the leading positional
        # index with the human arg name
        if label.startswith("[0]["):
            rest = label[3:]
            idx_end = rest.index("]")
            try:
                pos = int(rest[1:idx_end])
                label = arg_names[pos] + rest[idx_end + 1:] \
                    if pos < len(arg_names) else label
            except ValueError:
                pass
        aval = info.aval if hasattr(info, "aval") else info._aval
        out.append((label, _hlo_dtype(aval.dtype), tuple(aval.shape),
                    bool(getattr(info, "donated", False))))
    return out


def _hlo_dtype(np_dtype) -> str:
    """numpy/jax dtype → HLO dtype token (float32 → f32)."""
    s = str(np_dtype)
    table = {"float32": "f32", "float64": "f64", "float16": "f16",
             "bfloat16": "bf16", "int8": "s8", "int16": "s16",
             "int32": "s32", "int64": "s64", "uint8": "u8",
             "uint16": "u16", "uint32": "u32", "uint64": "u64",
             "bool": "pred", "complex64": "c64", "complex128": "c128"}
    return table.get(s, s)


def audit_program(hlo_text: str, label: str, args_info=None,
                  arg_names: Tuple[str, ...] = (),
                  expected_donated_prefixes: Tuple[str, ...] = (),
                  large_bytes: int = DEFAULT_LARGE_BYTES,
                  expected_all_reduce: Optional[int] = None,
                  top: int = 5) -> ProgramAudit:
    """Run every HLO pass over one compiled program.

    ``expected_donated_prefixes``: leaf-name prefixes (e.g. ``train``,
    ``states``) whose buffers the program contract says must be donated;
    a leaf under them that doesn't alias an output is a finding even
    when donation was never *requested* (the ``donate=False`` class).
    ``expected_all_reduce``: the bucketed-dp contract count
    (``buckets + 1``); ``None`` skips the contract check.
    """
    a = ProgramAudit(label=label)
    a.collectives = H.collective_census(hlo_text)
    entry = H.parse_entry_params(hlo_text)
    donated_idx = H.donated_params(hlo_text)

    if args_info is not None:
        leaves = _leaf_names(args_info, arg_names)
        aligned = _align_params(entry, leaves)
    else:
        aligned = [(f"param{i}", d, dims, nb, False)
                   for i, (d, dims, nb) in enumerate(entry)]

    for i, (name, dtype, dims, nbytes, requested) in enumerate(aligned):
        donated = i in donated_idx
        a.params.append((name, dtype, dims, nbytes, donated))
        if donated:
            a.donated_bytes += nbytes
        else:
            a.undonated_bytes += nbytes
            expected = requested or any(
                name == p or name.startswith(p + "[")
                for p in expected_donated_prefixes)
            if expected:
                a.donation_misses.append((name, nbytes))
                if nbytes >= large_bytes:
                    a.findings.append(Finding(
                        "undonated-buffer", P0, label, "donation", anchor=name,
                        message=(f"{name} ({dtype}{list(dims)}, {nbytes} "
                                 f"bytes) should be donated but does not "
                                 f"alias any output — the step keeps two "
                                 f"copies live (the 2x-memory class)"),
                        data={"bytes": nbytes}))

    ops = H.iter_ops(hlo_text)  # ONE parse shared by the text passes
    a.upcasts = H.upcast_ops(hlo_text, min_bytes=large_bytes, ops=ops)
    for op in a.upcasts:
        a.findings.append(Finding(
            "f32-upcast", P1, label, "dtype", anchor=op.shape,
            message=(f"{op.nbytes}-byte f32 intermediate {op.shape} "
                     f"converted from a narrower float"
                     + (f" at {op.source}" if op.source else "")),
            data={"bytes": op.nbytes, "source": op.source}))

    a.largest = H.largest_ops(hlo_text, top=top, ops=ops)

    if expected_all_reduce is not None \
            and a.all_reduce_count != expected_all_reduce:
        kind = "storm" if a.all_reduce_count > expected_all_reduce \
            else "missing-reduction"
        a.findings.append(Finding(
            "allreduce-contract", P0, label, "collectives",
            anchor=kind,
            message=(f"{a.all_reduce_count} all-reduces, contract says "
                     f"{expected_all_reduce} (buckets + 1) — "
                     + ("per-param collective storm is back"
                        if kind == "storm" else
                        "a bucket reduction disappeared")),
            data={"count": a.all_reduce_count,
                  "expected": expected_all_reduce}))
    return a


def train_step_arg_names() -> Tuple[str, ...]:
    return TRAIN_STEP_ARGS


def audit_train_step(step, *args, large_bytes: int = DEFAULT_LARGE_BYTES,
                     expected_all_reduce: Optional[int] = None,
                     label: str = "train_step",
                     top: int = 5, **kwargs) -> ProgramAudit:
    """Audit one ``jit.TrainStep`` on a concrete batch.

    RNG-neutral like ``TrainStep.compiled_hlo`` (the step never runs;
    the key stream is restored), and contract-aware:

    - all-reduce census vs ``len(step._comm_buckets) + 1`` when the
      bucketed dp path is active, or vs an explicit
      ``expected_all_reduce`` (pass the reference plan's count to catch
      a step that silently fell back to the per-param GSPMD storm);
    - train-param and optimizer-state leaves are ALWAYS expected to be
      donated — a ``donate=False`` step or an XLA-dropped donation is
      exactly the 2x-memory class this pass exists for.
    """
    from paddle_tpu.core import generator as _gen

    rng_state = _gen.get_rng_state()
    try:
        _, compiled, call_args = step._prepare(args, kwargs)
        lowered = compiled.lower(*call_args)
        hlo_text = lowered.compile().as_text()
        args_info = lowered.args_info
    finally:
        _gen.set_rng_state(rng_state)

    expected = expected_all_reduce
    if expected is None and step._comm_buckets is not None:
        expected = len(step._comm_buckets) + 1
    return audit_program(
        hlo_text, label, args_info=args_info,
        arg_names=TRAIN_STEP_ARGS,
        expected_donated_prefixes=("train", "states"),
        large_bytes=large_bytes, expected_all_reduce=expected, top=top)


def audit_serving_engine(engine, large_bytes: int = DEFAULT_LARGE_BYTES,
                         top: int = 5) -> ProgramAudit:
    """Audit the engine's ONE unified serving step (via the
    ``compiled_hlo``/``_lowered_step`` seam — state-neutral, see
    serving/engine.py). ``args_info`` from the lowering names the
    entry parameters (``k_pools[3]``, ``state['...']``, ``tokens``).

    Donation expectations: the KV pools are donated on TPU only (the
    CPU runtime can't honor donation), so pool donation is asserted
    only where the engine requested it — a TPU engine whose pools stop
    aliasing their outputs is the 2x-KV-memory class."""
    import jax

    lowered = engine._lowered_step()
    hlo_text = lowered.compile().as_text()
    prefixes = ("k_pools", "v_pools") \
        if jax.default_backend() == "tpu" else ()
    return audit_program(
        hlo_text, "serving_step", args_info=lowered.args_info,
        arg_names=SERVING_STEP_ARGS, expected_donated_prefixes=prefixes,
        large_bytes=large_bytes, top=top)


# -- recompile diff ---------------------------------------------------------

def _sig_leaf_names(treedef) -> List[str]:
    """Leaf path names for one compile key's batch treedef."""
    import jax

    n = treedef.num_leaves
    tree = jax.tree_util.tree_unflatten(treedef, list(range(n)))
    named = sorted(jax.tree_util.tree_flatten_with_path(tree)[0],
                   key=lambda kv: kv[1])
    return [jax.tree_util.keystr(p) for p, _ in named]


def diff_compile_keys(key_a, key_b) -> List[str]:
    """Human-readable difference between two ``TrainStep`` compile keys
    ``(treedef, sig, training, train_names, instrument)`` — names the
    exact leaf whose structure/shape/dtype changed, the mode flip, the
    trainable-set change, or the numerics-instrumentation flip that
    forced the recompilation."""
    treedef_a, sig_a, training_a, train_a = key_a[:4]
    treedef_b, sig_b, training_b, train_b = key_b[:4]
    # 4-tuple keys predate the instrumentation flag; treat as disarmed
    inst_a = key_a[4] if len(key_a) > 4 else False
    inst_b = key_b[4] if len(key_b) > 4 else False
    out = []
    if training_a != training_b:
        out.append(f"model mode changed: training={training_a} -> "
                   f"{training_b}")
    if inst_a != inst_b:
        # the expected sampled-twin retrace, not a perf smell
        # (docs/OBSERVABILITY.md#numerics)
        out.append(f"numerics instrumentation changed: {inst_a} -> "
                   f"{inst_b}")
    if train_a != train_b:
        frozen = sorted(set(train_a) - set(train_b))
        unfrozen = sorted(set(train_b) - set(train_a))
        if frozen:
            out.append(f"params left the trainable set: {frozen}")
        if unfrozen:
            out.append(f"params entered the trainable set: {unfrozen}")
    if treedef_a != treedef_b:
        out.append(f"batch structure changed: {treedef_a} -> {treedef_b}")
        return out  # leaf-wise sig comparison is meaningless across trees
    if sig_a != sig_b:
        names = _sig_leaf_names(treedef_a)
        for i, (la, lb) in enumerate(zip(sig_a, sig_b)):
            if la == lb:
                continue
            name = names[i] if i < len(names) else f"leaf[{i}]"
            out.append(f"batch leaf {name}: {_fmt_sig(la)} -> "
                       f"{_fmt_sig(lb)}")
    return out or ["keys are identical"]


def _fmt_sig(leaf_sig) -> str:
    if leaf_sig and leaf_sig[0] in ("T", "A") and len(leaf_sig) == 3:
        _, shape, dtype = leaf_sig
        return f"{dtype}{list(shape)}"
    return repr(leaf_sig)


def recompile_report(step) -> List[dict]:
    """Why each retrace after the first happened: consecutive compile-key
    diffs over the step's cache, in insertion order. Empty when the step
    compiled at most once — the healthy steady state."""
    keys = list(step._cache.keys())
    out = []
    for prev, cur in zip(keys, keys[1:]):
        out.append({"causes": diff_compile_keys(prev, cur)})
    return out
