"""Static env-knob registry: every ``PADDLE_TPU_*`` knob, from the AST.

The repo grew ~22 scattered ``PADDLE_TPU_*`` environment knobs across
seven subsystems; nothing guaranteed a knob stayed documented after a
refactor, or that docs didn't advertise a knob whose read site was
deleted. This module collects knobs *statically* — string literals in
non-docstring positions, i.e. actual ``os.environ`` reads, default
tables, and ``startswith`` prefix scans — so the registry needs no
imports and can't miss a knob behind an import guard.

A name ending in ``_`` (``PADDLE_TPU_CHAOS_``) is a *prefix family*:
the code scans for it with ``startswith`` and docs document it as
``PADDLE_TPU_CHAOS_*``.

``drift()`` is the tier-1 contract (modeled on the metrics
``TestDocsMetricDrift``): every knob read in code must appear in
``docs/*.md``/``README.md``, and every documented knob must still have
a read site.
"""
from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Tuple

from .findings import iter_py_files, repo_root as _repo_root

__all__ = ["collect_code_knobs", "collect_doc_knobs", "drift",
           "KNOB_RE"]

KNOB_RE = re.compile(r"PADDLE_TPU_[A-Z0-9_]+")


def _docstring_ids(tree) -> set:
    """ids of Constant nodes that are docstrings (skipped: a knob only
    *mentioned* in prose is not a read site)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def collect_code_knobs(package_root: Optional[str] = None,
                       extra_files: Tuple[str, ...] = ()
                       ) -> Dict[str, List[Tuple[str, int]]]:
    """knob name -> [(repo-relative file, line)] read/reference sites.

    A literal counts when the *whole* string is one knob name (an env
    read, a dict key, a ``startswith`` prefix) — names embedded in
    messages or docstrings don't create registry entries."""
    if package_root is None:
        package_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
    base = _repo_root()
    out: Dict[str, List[Tuple[str, int]]] = {}
    targets = iter_py_files(package_root) + list(extra_files)
    for path in targets:
        try:
            with open(path) as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        doc_ids = _docstring_ids(tree)
        rel = os.path.relpath(path, base)
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and id(node) not in doc_ids \
                    and KNOB_RE.fullmatch(node.value):
                out.setdefault(node.value, []).append((rel, node.lineno))
    return out


def collect_doc_knobs(docs_root: Optional[str] = None
                      ) -> Dict[str, List[str]]:
    """knob name -> [doc files mentioning it] over docs/*.md + README.md
    (a ``PADDLE_TPU_CHAOS_*`` wildcard documents the prefix family)."""
    base = _repo_root() if docs_root is None else docs_root
    paths = sorted(glob.glob(os.path.join(base, "docs", "*.md")))
    readme = os.path.join(base, "README.md")
    if os.path.exists(readme):
        paths.append(readme)
    out: Dict[str, List[str]] = {}
    for path in paths:
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, base)
        for name in set(KNOB_RE.findall(text)):
            out.setdefault(name, []).append(rel)
    return out


def drift(package_root: Optional[str] = None,
          extra_files: Tuple[str, ...] = (),
          docs_root: Optional[str] = None) -> dict:
    """Both drift directions. ``undocumented``: knobs read in code with
    no doc mention; ``ghosts``: documented knobs with no read site left.
    A documented member of a prefix family (``PADDLE_TPU_CHAOS_FOO``)
    is covered by the family's code-side prefix scan and vice versa."""
    code = collect_code_knobs(package_root, extra_files)
    docs = collect_doc_knobs(docs_root)

    def covered(name, other):
        if name in other:
            return True
        # a member is covered by the other side's prefix family...
        if any(name.startswith(p) for p in other if p.endswith("_")):
            return True
        # ...and a prefix family by any member on the other side
        return name.endswith("_") and any(o.startswith(name)
                                          for o in other)

    undocumented = sorted(k for k in code if not covered(k, docs))
    ghosts = sorted(k for k in docs if not covered(k, code))
    return {"code": {k: v for k, v in sorted(code.items())},
            "docs": {k: v for k, v in sorted(docs.items())},
            "undocumented": undocumented, "ghosts": ghosts}
