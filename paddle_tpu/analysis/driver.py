"""Committed audit geometries — ONE definition for CLI, bench and tests.

The audit's regression value comes from pinning numbers on a *fixed*
program; these builders are that fixture. Three programs cover the
contracts:

- :func:`dp8_bucketed_step`: the bucketed-dp ``TrainStep`` whose HLO
  must carry exactly ``buckets + 1`` all-reduces (needs an 8-device
  mesh — virtual on CPU, real on chip).
- :func:`tiny_llama_step`: a single-device causal-LM train step — the
  donation-coverage and giant-intermediate ([B, seq, vocab] logits)
  subject.
- :func:`tiny_serving_engine`: the unified serving step behind
  ``ServingEngine.compiled_hlo()``.

Everything is sized for the 1-CPU smoke box (a few seconds per
compile); ``bench.py --audit`` swaps in the committed bench geometry on
a real TPU.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

__all__ = ["ensure_cpu_mesh", "dp8_bucketed_step", "tiny_llama_step",
           "tiny_serving_engine", "run_default_audit"]


def ensure_cpu_mesh(devices: int = 8) -> bool:
    """Arm an N-virtual-device CPU platform when no TPU is plausibly
    present (same discipline as tests/conftest.py / BENCH_FORCE_CPU:
    the env must be set before the jax backend initializes). Returns
    whether the CPU override was applied."""
    env = os.environ
    from paddle_tpu.device import _tpu_plausible
    if _tpu_plausible(env):
        return False
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    return True


def dp8_bucketed_step(dp: Optional[int] = None):
    """(step, (x, y)) — pure-dp ``DataParallel`` MLP with the bucketed
    collective path active (the PR 7 HLO-contract geometry)."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn

    if dp is None:
        import jax
        dp = jax.device_count()
    mesh = dist.init_mesh({"dp": dp})
    pt.seed(3)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    m = dist.DataParallel(net, mesh=mesh)
    o = pt.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())

    def loss_fn(model, x, y):
        return ((model(x) - y) ** 2).mean()

    step = pt.jit.TrainStep(m, loss_fn, o)
    rng = np.random.RandomState(0)
    X = rng.randn(8 * dp, 16).astype(np.float32)
    Y = X @ rng.randn(16, 4).astype(np.float32)
    return step, (pt.to_tensor(X), pt.to_tensor(Y))


def _tiny_llama(bf16: bool = False, cfg=None):
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if cfg is None:
        cfg = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=448,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=512,
            tie_word_embeddings=True)
    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    if bf16:
        model.bfloat16()
    return model, cfg


def tiny_llama_step(bf16: bool = False, donate: bool = True,
                    batch: Tuple[int, int] = (2, 64), cfg=None):
    """(step, (tokens,)) — single-device causal-LM ``TrainStep``, by
    default on the CPU-smoke geometry (the donation /
    giant-intermediate subject); ``bench.py --audit`` passes the
    committed bench config on chip."""
    import numpy as np

    import paddle_tpu as pt

    model, cfg = _tiny_llama(bf16, cfg)
    opt = pt.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(),
        multi_precision=bf16,
        grad_clip=pt.nn.ClipGradByGlobalNorm(1.0))
    step = pt.jit.TrainStep(model, lambda m, t: m(t, labels=t)[1], opt,
                            donate=donate)
    B, S = batch
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randint(0, cfg.vocab_size, (B, S))
                     .astype(np.int64))
    return step, (x,)


def tiny_serving_engine(attn_impl: Optional[str] = None):
    """A small real ``ServingEngine`` (gather path off-TPU) for the
    serving-step audit."""
    from paddle_tpu.serving import ServingEngine

    model, _ = _tiny_llama()
    return ServingEngine(model, max_batch=2, max_blocks=16, block_size=4,
                         prefill_chunk=8, attn_impl=attn_impl)


def run_default_audit(include_serving: bool = True,
                      dp: Optional[int] = None, bf16: bool = False,
                      batch: Tuple[int, int] = (2, 64),
                      llama_cfg=None) -> dict:
    """The full committed-geometry audit: every report's summary plus
    the three headline numbers ``bench.py --audit`` emits. ``dp`` None
    = all local devices (dp census skipped when fewer than 2); the
    llama kwargs let the bench swap in the committed chip geometry."""
    import jax

    from .audit import audit_serving_engine, audit_train_step

    out = {"reports": [], "findings": []}
    n_dev = jax.device_count()
    if dp is None:
        dp = n_dev if n_dev >= 2 else 0

    if dp >= 2:
        step, dp_batch = dp8_bucketed_step(dp)
        rep = audit_train_step(step, *dp_batch,
                               label=f"train_step[dp{dp}]")
        assert step._comm_buckets is not None, (
            "bucketed path ineligible on the committed geometry: "
            f"{step._bucketed_reason}")
        out["reports"].append(rep.summary())
        out["findings"].extend(rep.findings)
        out["train_step_allreduce_count"] = rep.all_reduce_count
        out["expected_allreduce_count"] = len(step._comm_buckets) + 1
    else:
        out["train_step_allreduce_count"] = None

    step, batch = tiny_llama_step(bf16=bf16, batch=batch, cfg=llama_cfg)
    rep = audit_train_step(step, *batch)
    out["reports"].append(rep.summary())
    out["findings"].extend(rep.findings)
    out["train_step_undonated_bytes"] = rep.undonated_bytes
    out["train_step_donation_coverage"] = round(rep.donation_coverage, 4)
    out["train_step_largest_intermediate_bytes"] = \
        rep.largest_intermediate_bytes
    # runtime-truth counterpart from XLA's buffer assignment
    # (observability.memory.MemoryReport; rides the same cached
    # executable, so no extra trace)
    mr = step.memory_report(*batch)
    out["train_step_peak_hbm_bytes"] = \
        None if mr is None else mr.total_bytes

    if include_serving:
        engine = tiny_serving_engine()
        rep = audit_serving_engine(engine)
        out["reports"].append(rep.summary())
        out["findings"].extend(rep.findings)
    return out
