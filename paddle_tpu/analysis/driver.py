"""Committed audit geometries — ONE definition for CLI, bench and tests.

The audit's regression value comes from pinning numbers on a *fixed*
program; these builders are that fixture. Three programs cover the
contracts:

- :func:`dp8_bucketed_step`: the bucketed-dp ``TrainStep`` whose HLO
  must carry exactly ``buckets + 1`` all-reduces (needs an 8-device
  mesh — virtual on CPU, real on chip).
- :func:`tiny_llama_step`: a single-device causal-LM train step — the
  donation-coverage and giant-intermediate ([B, seq, vocab] logits)
  subject.
- :func:`tiny_serving_engine`: the unified serving step behind
  ``ServingEngine.compiled_hlo()``.

Everything is sized for the 1-CPU smoke box (a few seconds per
compile); ``bench.py --audit`` swaps in the committed bench geometry on
a real TPU.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

__all__ = ["ensure_cpu_mesh", "dp8_bucketed_step", "tiny_llama_step",
           "tiny_serving_engine", "run_default_audit", "run_commplan",
           "COMMPLAN_GEOMETRIES"]


def ensure_cpu_mesh(devices: int = 8) -> bool:
    """Arm an N-virtual-device CPU platform when no TPU is plausibly
    present (same discipline as tests/conftest.py / BENCH_FORCE_CPU:
    the env must be set before the jax backend initializes). Returns
    whether the CPU override was applied."""
    env = os.environ
    from paddle_tpu.device import _tpu_plausible
    if _tpu_plausible(env):
        return False
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    return True


def dp8_bucketed_step(dp: Optional[int] = None, seed_typo: bool = False):
    """(step, (x, y)) — pure-dp ``DataParallel`` MLP with the bucketed
    collective path active (the PR 7 HLO-contract geometry).

    ``seed_typo`` plants the accidental-all-gather defect the commplan
    auditor exists to catch: one bias declared sharded over ``dp`` (a
    one-token sharding-spec mistake), which forces GSPMD to all-gather
    that parameter every step. Used by ``commplan --seed-typo`` and the
    regression tests — never by a real audit."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn

    if dp is None:
        import jax
        dp = jax.device_count()
    mesh = dist.init_mesh({"dp": dp})
    pt.seed(3)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    if seed_typo:
        from paddle_tpu.distributed import P
        net[0].bias._sharding_spec = P("dp")
    m = dist.DataParallel(net, mesh=mesh)
    o = pt.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())

    def loss_fn(model, x, y):
        return ((model(x) - y) ** 2).mean()

    step = pt.jit.TrainStep(m, loss_fn, o)
    rng = np.random.RandomState(0)
    X = rng.randn(8 * dp, 16).astype(np.float32)
    Y = X @ rng.randn(16, 4).astype(np.float32)
    return step, (pt.to_tensor(X), pt.to_tensor(Y))


def _tiny_llama(bf16: bool = False, cfg=None):
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if cfg is None:
        cfg = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=448,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=512,
            tie_word_embeddings=True)
    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    if bf16:
        model.bfloat16()
    return model, cfg


def tiny_llama_step(bf16: bool = False, donate: bool = True,
                    batch: Tuple[int, int] = (2, 64), cfg=None):
    """(step, (tokens,)) — single-device causal-LM ``TrainStep``, by
    default on the CPU-smoke geometry (the donation /
    giant-intermediate subject); ``bench.py --audit`` passes the
    committed bench config on chip."""
    import numpy as np

    import paddle_tpu as pt

    model, cfg = _tiny_llama(bf16, cfg)
    opt = pt.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(),
        multi_precision=bf16,
        grad_clip=pt.nn.ClipGradByGlobalNorm(1.0))
    step = pt.jit.TrainStep(model, lambda m, t: m(t, labels=t)[1], opt,
                            donate=donate)
    B, S = batch
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randint(0, cfg.vocab_size, (B, S))
                     .astype(np.int64))
    return step, (x,)


def tiny_serving_engine(attn_impl: Optional[str] = None):
    """A small real ``ServingEngine`` (gather path off-TPU) for the
    serving-step audit."""
    from paddle_tpu.serving import ServingEngine

    model, _ = _tiny_llama()
    return ServingEngine(model, max_batch=2, max_blocks=16, block_size=4,
                         prefill_chunk=8, attn_impl=attn_impl)


def run_default_audit(include_serving: bool = True,
                      dp: Optional[int] = None, bf16: bool = False,
                      batch: Tuple[int, int] = (2, 64),
                      llama_cfg=None) -> dict:
    """The full committed-geometry audit: every report's summary plus
    the three headline numbers ``bench.py --audit`` emits. ``dp`` None
    = all local devices (dp census skipped when fewer than 2); the
    llama kwargs let the bench swap in the committed chip geometry."""
    import jax

    from .audit import audit_serving_engine, audit_train_step

    out = {"reports": [], "findings": []}
    n_dev = jax.device_count()
    if dp is None:
        dp = n_dev if n_dev >= 2 else 0

    if dp >= 2:
        step, dp_batch = dp8_bucketed_step(dp)
        rep = audit_train_step(step, *dp_batch,
                               label=f"train_step[dp{dp}]")
        assert step._comm_buckets is not None, (
            "bucketed path ineligible on the committed geometry: "
            f"{step._bucketed_reason}")
        out["reports"].append(rep.summary())
        out["findings"].extend(rep.findings)
        out["train_step_allreduce_count"] = rep.all_reduce_count
        out["expected_allreduce_count"] = len(step._comm_buckets) + 1
    else:
        out["train_step_allreduce_count"] = None

    step, batch = tiny_llama_step(bf16=bf16, batch=batch, cfg=llama_cfg)
    rep = audit_train_step(step, *batch)
    out["reports"].append(rep.summary())
    out["findings"].extend(rep.findings)
    out["train_step_undonated_bytes"] = rep.undonated_bytes
    out["train_step_donation_coverage"] = round(rep.donation_coverage, 4)
    out["train_step_largest_intermediate_bytes"] = \
        rep.largest_intermediate_bytes
    # runtime-truth counterpart from XLA's buffer assignment
    # (observability.memory.MemoryReport; rides the same cached
    # executable, so no extra trace)
    mr = step.memory_report(*batch)
    out["train_step_peak_hbm_bytes"] = \
        None if mr is None else mr.total_bytes

    if include_serving:
        engine = tiny_serving_engine()
        rep = audit_serving_engine(engine)
        out["reports"].append(rep.summary())
        out["findings"].extend(rep.findings)
    return out


# -- commplan geometries ----------------------------------------------------
#
# One tiny committed program per MULTICHIP parallelism segment, lowered
# through the same RNG-neutral ``compiled_hlo`` seam the audits use.
# The per-axis comm ledgers these produce are pinned in baseline.json —
# the budget-drift gate compares every run against them.

def _lower_train_step(step, *args):
    """(hlo_text, leaf_names) via the RNG-neutral ``_prepare`` seam —
    leaf names aligned to entry-parameter numbers so the
    implicit-reshard pass can name the gathered leaf."""
    from paddle_tpu.core import generator as _gen

    from .audit import TRAIN_STEP_ARGS, _align_params, _leaf_names
    from .hlo import parse_entry_params

    rng_state = _gen.get_rng_state()
    try:
        _, compiled, call_args = step._prepare(args, {})
        lowered = compiled.lower(*call_args)
        hlo_text = lowered.compile().as_text()
        args_info = lowered.args_info
    finally:
        _gen.set_rng_state(rng_state)
    leaves = _leaf_names(args_info, TRAIN_STEP_ARGS)
    aligned = _align_params(parse_entry_params(hlo_text), leaves)
    return hlo_text, [name for name, *_ in aligned]


def _geo_dp8(seed_typo: bool = False):
    step, (x, y) = dp8_bucketed_step(seed_typo=seed_typo)
    hlo, names = _lower_train_step(step, x, y)
    import paddle_tpu.distributed as dist
    return {"hlo": hlo, "mesh": dist.get_mesh(), "leaf_names": names,
            "gather_ok": False}


def _geo_dpxmp():
    """Data x tensor parallel: the zoo Llama with Megatron-style mpu
    layers over {dp: 4, mp: 2} (graft-entry segment (a))."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed import P
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    mesh = dist.init_mesh({"dp": 4, "mp": 2})
    pt.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=True))
    o = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def loss_fn(m, toks):
        _, loss = m(toks, labels=toks)
        return loss

    step = pt.jit.TrainStep(model, loss_fn, o, mesh=mesh,
                            input_spec=P("dp"))
    rng = np.random.RandomState(0)
    toks = pt.to_tensor(rng.randint(0, 256, (8, 8)).astype(np.int32))
    hlo, names = _lower_train_step(step, toks)
    return {"hlo": hlo, "mesh": mesh, "leaf_names": names,
            "gather_ok": False}


def _pp_train_step(mesh):
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.distributed.fleet as fleet
    import paddle_tpu.optimizer as opt
    from paddle_tpu import nn

    pt.seed(4)
    layer = fleet.SpmdPipelineLayer(
        lambda: nn.Sequential(nn.Linear(8, 8), nn.Tanh()),
        num_virtual_stages=2, mesh=mesh)
    mse = nn.MSELoss()

    def loss_fn(m, xs, ys):
        out = m(xs)
        return mse(pt.reshape(out, [-1, 8]), pt.reshape(ys, [-1, 8]))

    o = opt.AdamW(learning_rate=1e-3, parameters=layer.parameters())
    rng = np.random.RandomState(0)
    return layer, loss_fn, o, rng


def _geo_pp():
    """SPMD pipeline over a pure {pp: 8} mesh — stage hops are compiled
    ppermutes (collective-permute in the ledger)."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import P

    mesh = dist.init_mesh({"pp": 8})
    layer, loss_fn, o, rng = _pp_train_step(mesh)
    step = pt.jit.TrainStep(layer, loss_fn, o, mesh=mesh, input_spec=P())
    X = pt.to_tensor(rng.randn(8, 2, 8).astype(np.float32))
    Y = pt.to_tensor(rng.randn(8, 2, 8).astype(np.float32))
    hlo, names = _lower_train_step(step, X, Y)
    return {"hlo": hlo, "mesh": mesh, "leaf_names": names,
            "gather_ok": False}


def _geo_dpxpp():
    """Data x pipeline over {dp: 2, pp: 4} — the partial-manual
    shard_map geometry. On jax builds whose shard_map cannot mix a
    manual pp axis with an auto dp axis this raises and the runner
    records the geometry as skipped (capability-gated, not silently
    dropped)."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import P

    mesh = dist.init_mesh({"dp": 2, "pp": 4})
    layer, loss_fn, o, rng = _pp_train_step(mesh)
    step = pt.jit.TrainStep(layer, loss_fn, o, mesh=mesh,
                            input_spec=P(None, "dp"))
    X = pt.to_tensor(rng.randn(4, 4, 8).astype(np.float32))
    Y = pt.to_tensor(rng.randn(4, 4, 8).astype(np.float32))
    hlo, names = _lower_train_step(step, X, Y)
    return {"hlo": hlo, "mesh": mesh, "leaf_names": names,
            "gather_ok": False}


def _geo_zero():
    """ZeRO stage-3 (p_g_os) over {sharding: 8}. ``gather_ok``: the
    whole POINT of ZeRO is re-gathering sharded params every step, so
    the implicit-reshard pass must stay quiet here."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    from paddle_tpu import nn
    from paddle_tpu.distributed import P

    mesh = dist.init_mesh({"sharding": 8})
    pt.seed(5)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 16))
    o = opt.AdamW(learning_rate=1e-3, parameters=net.parameters())
    m, o, _ = dist.group_sharded_parallel(net, o, level="p_g_os")

    def loss_fn(model, x, y):
        return ((model(x) - y) ** 2).mean()

    step = pt.jit.TrainStep(m, loss_fn, o, mesh=mesh,
                            input_spec=P("sharding"))
    rng = np.random.RandomState(0)
    X = pt.to_tensor(rng.randn(16, 16).astype(np.float32))
    Y = pt.to_tensor(rng.randn(16, 16).astype(np.float32))
    hlo, names = _lower_train_step(step, X, Y)
    return {"hlo": hlo, "mesh": mesh, "leaf_names": names,
            "gather_ok": True}


def _geo_sp():
    """Sequence-parallel ring attention over {sp: 8} — a pure
    collective-permute ring (no TrainStep; the kernel is a function, so
    the lowering goes through a plain jax.jit)."""
    import jax
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    import paddle_tpu.distributed.fleet as fleet

    mesh = dist.init_mesh({"sp": 8})

    def fn(q, k, v):
        out = fleet.ring_attention(pt.to_tensor(q), pt.to_tensor(k),
                                   pt.to_tensor(v), mesh=mesh, axis="sp",
                                   causal=True)
        return out.data

    rng = np.random.RandomState(0)
    args = [rng.randn(2, 32, 2, 8).astype(np.float32) for _ in range(3)]
    hlo = jax.jit(fn).lower(*args).compile().as_text()
    return {"hlo": hlo, "mesh": mesh, "leaf_names": None,
            "gather_ok": False}


def _geo_ep():
    """Expert-parallel MoE (GShard gate) over {ep: 8} — token dispatch
    is the all-to-all pair. Activations legitimately reshard around the
    expert boundary; parameters must not, so gather_ok stays False."""
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    import paddle_tpu.distributed.fleet as fleet
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed import P

    mesh = dist.init_mesh({"ep": 8})
    pt.seed(6)
    moe = fleet.MoELayer(16, 32, num_experts=8, gate="gshard",
                         mesh=mesh, axis="ep")
    o = opt.AdamW(learning_rate=1e-3, parameters=moe.parameters())

    def loss_fn(model, x, y):
        out = model(x)
        return ((out - y) ** 2).mean() + 0.01 * model.l_aux

    step = pt.jit.TrainStep(moe, loss_fn, o, mesh=mesh, input_spec=P("ep"))
    rng = np.random.RandomState(0)
    X = pt.to_tensor(rng.randn(8, 4, 16).astype(np.float32))
    Y = pt.to_tensor(rng.randn(8, 4, 16).astype(np.float32))
    hlo, names = _lower_train_step(step, X, Y)
    return {"hlo": hlo, "mesh": mesh, "leaf_names": names,
            "gather_ok": False}


def _geo_serving():
    """The unified serving step (single device off-TPU — an empty
    ledger is itself the pinned fact: serving must not grow collectives
    without review)."""
    engine = tiny_serving_engine()
    lowered = engine._lowered_step()
    return {"hlo": lowered.compile().as_text(), "mesh": None,
            "leaf_names": None, "gather_ok": False}


def _geo_serving_mp2():
    """The tensor-parallel unified serving step (ISSUE 15): the tiny
    zoo Llama built with mpu layers, engine ``mesh=`` over {mp: 2} —
    weights and KV pools sharded, ONE step executable. The pinned
    per-axis ledger is the reshard-storm tripwire: a sharding
    annotation regression in the serving path shows up as new mp-axis
    collectives and fails the budget gate."""
    import jax

    import paddle_tpu.distributed as dist
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.serving import ServingEngine

    if jax.device_count() < 2:
        raise RuntimeError("needs >= 2 devices for the mp=2 mesh")
    mesh = dist.init_mesh({"mp": 2}, devices=jax.devices()[:2])
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=448,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=512,
        tie_word_embeddings=True, tensor_parallel=True)
    model, _ = _tiny_llama(cfg=cfg)
    engine = ServingEngine(model, max_batch=2, max_blocks=16,
                           block_size=4, prefill_chunk=8,
                           attn_impl="gather", mesh=mesh)
    lowered = engine._lowered_step()
    return {"hlo": lowered.compile().as_text(), "mesh": mesh,
            "leaf_names": None, "gather_ok": False}


def _geo_serving_mp2_int8():
    """The quantized tensor-parallel unified serving step (ISSUE 20):
    same tiny mpu Llama and {mp: 2} mesh as ``serving_mp2`` but with
    ``quantize="int8_wo"`` — int8 weight values + f32 scales sharded in
    place of the bf16 leaves, dequantized inside the trace. The pinned
    fact: dequant is LOCAL, so the mp-axis comm bytes must NOT grow
    over the bf16 geometry's ledger."""
    import jax

    import paddle_tpu.distributed as dist
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.serving import ServingEngine

    if jax.device_count() < 2:
        raise RuntimeError("needs >= 2 devices for the mp=2 mesh")
    mesh = dist.init_mesh({"mp": 2}, devices=jax.devices()[:2])
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=448,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=512,
        tie_word_embeddings=True, tensor_parallel=True)
    model, _ = _tiny_llama(cfg=cfg)
    engine = ServingEngine(model, max_batch=2, max_blocks=16,
                           block_size=4, prefill_chunk=8,
                           attn_impl="gather", mesh=mesh,
                           quantize="int8_wo")
    lowered = engine._lowered_step()
    return {"hlo": lowered.compile().as_text(), "mesh": mesh,
            "leaf_names": None, "gather_ok": False}


#: label -> builder; labels are baseline keys — NEVER rename casually
#: (a rename orphans the pinned ledger and reports everything as new)
COMMPLAN_GEOMETRIES = (
    ("dp8", _geo_dp8),
    ("dpxmp", _geo_dpxmp),
    ("pp", _geo_pp),
    ("dpxpp", _geo_dpxpp),
    ("zero", _geo_zero),
    ("sp", _geo_sp),
    ("ep", _geo_ep),
    ("serving", _geo_serving),
    ("serving_mp2", _geo_serving_mp2),
    ("serving_mp2_int8", _geo_serving_mp2_int8),
)


def run_commplan(seed_typo: bool = False, only=None) -> dict:
    """Lower every committed geometry and run the comm-plan audit.

    Returns ``{"reports": {label: summary}, "ledgers": {label: ledger},
    "findings": [...], "skipped": {label: reason}}``. A geometry whose
    *construction* is unsupported on the running jax (the partial-manual
    dp x pp shard_map) lands in ``skipped`` with the error string —
    visible, not silently absent. ``seed_typo`` swaps in the defective
    dp8 variant (the accidental-all-gather regression fixture)."""
    import paddle_tpu.distributed as dist

    from .commplan import audit_comm

    prev_mesh = dist.get_mesh()
    out = {"reports": {}, "ledgers": {}, "findings": [], "skipped": {}}
    try:
        for label, build in COMMPLAN_GEOMETRIES:
            if only and label not in only:
                continue
            try:
                geo = build(seed_typo=True) if (
                    seed_typo and label == "dp8") else build()
            except Exception as e:  # capability gate, not error-hiding:
                # the skip reason is part of the report and the tests
                # assert the supported set
                out["skipped"][label] = f"{type(e).__name__}: {e}"
                continue
            rep = audit_comm(geo["hlo"], label, mesh=geo["mesh"],
                             leaf_names=geo["leaf_names"],
                             gather_ok=geo["gather_ok"])
            out["reports"][label] = rep.summary()
            out["ledgers"][label] = rep.ledger
            out["findings"].extend(rep.findings)
    finally:
        dist.set_mesh(prev_mesh)
    return out
