"""hapi Model — the Keras-style high-level API.

Parity with the reference's ``python/paddle/hapi/model.py`` (``Model.fit:1036``,
``evaluate``, ``predict``, ``prepare``, ``save``/``load``; callbacks in
``hapi/callbacks.py``). The train step runs through ``jit.TrainStep`` so
hapi users get the compiled hot path for free — the analog of the
reference's dygraph/static adapter pair collapsing into one compiled mode.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer

__all__ = ["Model", "Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "EarlyStopping", "LRScheduler", "StepTelemetry"]


class Callback:
    """Reference: hapi/callbacks.py Callback."""

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        """``logs`` carries ``data_time`` (seconds the fit loop spent
        fetching this batch) and ``batch_size`` when determinable."""
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                              else f"{k}: {v}"
                              for k, v in (logs or {}).items())
            print(f"step {step} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                              else f"{k}: {v}"
                              for k, v in (logs or {}).items())
            print(f"epoch {epoch} - {items}")


class ModelCheckpoint(Callback):
    """Epoch-end checkpointing through ``paddle_tpu.checkpoint`` (see
    docs/CHECKPOINT.md): model + optimizer state commit atomically as ONE
    step — no torn model/opt pairs — and with ``async_=True`` (default)
    the fit loop pays only the device→host snapshot; shard writing runs
    on the background writer. ``keep_last_k`` bounds disk usage.

    Resume with ``model.load(save_dir)`` (dir-dispatch to the latest
    committed step) or a ``CheckpointManager`` directly."""

    def __init__(self, save_freq=1, save_dir="checkpoint", async_=True,
                 keep_last_k=None):
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.async_ = async_
        self.keep_last_k = keep_last_k
        self._mgr = None

    def manager(self):
        if self._mgr is None:
            from paddle_tpu.checkpoint import CheckpointManager
            self._mgr = CheckpointManager(self.save_dir,
                                          keep_last_k=self.keep_last_k,
                                          async_=self.async_)
        return self._mgr

    def on_epoch_end(self, epoch, logs=None):
        if epoch % self.save_freq == 0:
            state = {"model": self.model.network.state_dict()}
            if self.model._optimizer is not None and \
                    hasattr(self.model._optimizer, "state_dict"):
                state["optimizer"] = self.model._optimizer.state_dict()
            # overwrite: a restarted fit re-saves the same epoch ids
            self.manager().save(epoch, state, metadata={"epoch": epoch},
                                overwrite=True)

    def on_train_end(self, logs=None):
        if self._mgr is not None:
            self._mgr.wait_all()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="min", patience=0,
                 min_delta=0.0, baseline=None, save_best_model=False):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best = baseline
        self.wait = 0
        self.stopped = False

    def _better(self, cur, best):
        if best is None:
            return True
        return cur < best - self.min_delta if self.mode == "min" \
            else cur > best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped = True
                self.model._stop_training = True


class VisualDL(Callback):
    """Scalar logger callback (reference: hapi/callbacks.py VisualDL).

    The visualdl package is not available in this build, so scalars are
    written as JSON lines (`{"step", "epoch", "tag", "value"}` per line)
    under ``log_dir`` — trivially parseable and plottable."""

    def __init__(self, log_dir: str = "./vdl_log"):
        import os
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._path = None
        self._step = 0
        self._epoch = 0

    def _file(self):
        if self._path is None:
            import os
            import time
            self._path = os.path.join(
                self.log_dir, f"scalars_{int(time.time())}.jsonl")
        return self._path

    def _write(self, tag, value):
        import json
        with open(self._file(), "a") as f:
            f.write(json.dumps({"step": self._step, "epoch": self._epoch,
                                "tag": tag, "value": float(value)}) + "\n")

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            try:
                self._write(f"train/{k}", v)
            except (TypeError, ValueError):
                pass  # non-scalar log entries are skipped

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            try:
                self._write(f"eval/{k}", v)
            except (TypeError, ValueError):
                pass


class StepTelemetry(Callback):
    """Step telemetry callback (the observability layer's trainer hook,
    docs/OBSERVABILITY.md): drives an ``observability.StepTimer`` from the
    fit loop's batch hooks, recording per-step time decomposition
    (data / compute / collective), samples-per-sec, optional tokens-per-sec
    and an MFU estimate into the metrics registry — and injects the same
    stats (plus the goodput ledger's running ``goodput_fraction``) into
    the batch ``logs`` so ProgBarLogger/VisualDL surface them.

    ``flops_per_sample``: training FLOPs per sample (fwd+bwd+update); when
    omitted, a ``flops_per_sample`` attribute on the network is used if
    present. ``peak`` overrides peak-FLOP/s detection (useful off-TPU).
    Starting it also arms the env-gated metrics exporter — note that the
    exporter serves the DEFAULT registry, so a custom ``registry`` here
    (mostly a test convenience) keeps these metrics off the env-gated
    scrape endpoint; serve it with ``MetricsExporter(port, registry)``."""

    def __init__(self, flops_per_sample=None, tokens_per_sample=None,
                 registry=None, peak=None):
        self.flops_per_sample = flops_per_sample
        self.tokens_per_sample = tokens_per_sample
        self.registry = registry
        self.peak = peak
        self.timer = None
        self.last_stats = None
        self._batch_size = None

    def on_train_begin(self, logs=None):
        from paddle_tpu.observability import (StepTimer,
                                              maybe_start_exporter)
        maybe_start_exporter()
        flops = self.flops_per_sample
        if flops is None:
            flops = getattr(self.model.network, "flops_per_sample", None)
        self.timer = StepTimer(registry=self.registry,
                               flops_per_sample=flops,
                               tokens_per_sample=self.tokens_per_sample,
                               peak=self.peak)

    def on_train_batch_begin(self, step, logs=None):
        logs = logs or {}
        self._batch_size = logs.get("batch_size")
        self.timer.begin_step(data_time=logs.get("data_time", 0.0))

    def on_train_batch_end(self, step, logs=None):
        stats = self.timer.end_step(
            samples=self._batch_size,
            grad_norm=(logs or {}).get("grad_norm"))
        self.last_stats = stats
        if logs is not None:
            for k in ("step_time_s", "samples_per_sec", "tokens_per_sec",
                      "mfu", "goodput_fraction"):
                if k in stats:
                    logs[k] = stats[k]


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from paddle_tpu.optimizer.lr import LRScheduler as S
        lr = getattr(self.model._optimizer, "_lr", None)
        return lr if isinstance(lr, S) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s is not None and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and self.by_epoch:
            s.step()


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


class Model:
    """Reference: hapi/model.py Model (fit:1036 / evaluate:1731)."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List = []
        self._train_step = None
        self._stop_training = False

    # -- setup ----------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        """``loss=None`` with an optimizer takes the SELF-SUPERVISED path:
        the network computes its own loss — ``net(*batch)`` (or
        ``net(**batch)`` for dict batches, the packed-pipeline shape,
        docs/DATA.md) returns the scalar loss or an ``(out, loss)``
        tuple, the causal-LM convention (``LlamaForCausalLM(input_ids,
        labels=…)``)."""
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        else:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        if optimizer is not None:
            import paddle_tpu as pt

            if loss is not None:
                def loss_fn(net, x, y):
                    return self._loss(net(x), y)
            else:
                def loss_fn(net, *args, **kwargs):
                    out = net(*args, **kwargs)
                    return out[1] if isinstance(out, (tuple, list)) \
                        else out
            self._train_step = pt.jit.TrainStep(self.network, loss_fn,
                                                optimizer)
        return self

    # -- core steps -----------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        if isinstance(inputs, dict):
            # packed-pipeline batches: keys ARE the network kwargs
            # (input_ids / attention_mask / …) — needs the loss=None
            # self-supervised TrainStep from prepare()
            if self._train_step is None or self._loss is not None:
                raise RuntimeError(
                    "dict (packed-pipeline) batches require "
                    "prepare(optimizer, loss=None) — the network "
                    "computes its own loss from the batch kwargs")
            loss = self._train_step(
                **{k: _as_tensor(v) for k, v in inputs.items()})
            return [float(loss.numpy())]
        x = _as_tensor(inputs[0] if isinstance(inputs, (list, tuple))
                       else inputs)
        y = _as_tensor(labels[0] if isinstance(labels, (list, tuple))
                       else labels)
        loss = self._train_step(x, y)
        return [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        from paddle_tpu.core.autograd import no_grad
        if isinstance(inputs, dict):
            # packed-pipeline batch: keys are the network kwargs and the
            # network computes its own loss (prepare(opt, loss=None));
            # hapi metrics don't apply — there is no (out, label) pair
            if self._loss is not None:
                raise RuntimeError(
                    "dict (packed-pipeline) batches require "
                    "prepare(..., loss=None) — the network computes "
                    "its own loss from the batch kwargs")
            with no_grad():
                out = self.network(
                    **{k: _as_tensor(v) for k, v in inputs.items()})
            loss = out[1] if isinstance(out, (tuple, list)) else out
            return [float(loss.numpy())]
        x = _as_tensor(inputs[0] if isinstance(inputs, (list, tuple))
                       else inputs)
        y = _as_tensor(labels[0] if isinstance(labels, (list, tuple))
                       else labels)
        with no_grad():
            out = self.network(x)
            loss = self._loss(out, y) if self._loss else None
        for m in self._metrics:
            res = m.compute(out, y)
            if not isinstance(res, tuple):
                res = (res,)
            m.update(*res)
        return [float(loss.numpy())] if loss is not None else []

    def predict_batch(self, inputs):
        from paddle_tpu.core.autograd import no_grad
        x = _as_tensor(inputs[0] if isinstance(inputs, (list, tuple))
                       else inputs)
        with no_grad():
            out = self.network(x)
        return [out.numpy()]

    # -- loops ----------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=1,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = self._to_loader(train_data, batch_size, shuffle, drop_last,
                                 num_workers)
        callbacks = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger)
                               for c in callbacks):
            callbacks.append(ProgBarLogger(log_freq, verbose))
        for cb in callbacks:
            cb.set_model(self)
        self._stop_training = False
        history = {"loss": []}
        for cb in callbacks:
            cb.on_train_begin()
        import time as _time
        try:
            history = self._fit_loop(loader, eval_data, batch_size, epochs,
                                     eval_freq, save_dir, save_freq,
                                     num_workers, callbacks, num_iters,
                                     history, _time)
        finally:
            # runs on exceptions/KeyboardInterrupt too: callbacks with
            # teardown duties (ModelCheckpoint draining async saves) must
            # not be skipped when the loop dies mid-epoch
            for cb in callbacks:
                cb.on_train_end()
        return history

    @staticmethod
    def _maybe_chaos():
        """The resilience chaos harness (docs/RESILIENCE.md), active only
        when a PADDLE_TPU_CHAOS_* env var is set — launched workers under
        fault-injection tests pick it up with zero cost to normal fits."""
        import os
        if not any(k.startswith("PADDLE_TPU_CHAOS_") and v
                   for k, v in os.environ.items()):
            return None
        from paddle_tpu.resilience import chaos
        chaos.refresh()
        return chaos

    @staticmethod
    def _maybe_profile_window():
        """Env-armed device-profiler window (docs/OBSERVABILITY.md#device-
        profiler): ``PADDLE_TPU_PROFILE_AT_STEP=<start>:<stop>`` captures
        a jax.profiler trace over that 1-based step range. Zero cost and
        no imports when the var is unset — normal fits never touch the
        profiler module."""
        import os
        if not os.environ.get("PADDLE_TPU_PROFILE_AT_STEP"):
            return None
        from paddle_tpu.observability import profile
        return profile.step_window_from_env()

    def _fit_loop(self, loader, eval_data, batch_size, epochs, eval_freq,
                  save_dir, save_freq, num_workers, callbacks, num_iters,
                  history, _time):
        step = 0
        chaos = self._maybe_chaos()
        pwin = self._maybe_profile_window()
        try:
            return self._fit_epochs(loader, eval_data, batch_size, epochs,
                                    eval_freq, save_dir, save_freq,
                                    num_workers, callbacks, num_iters,
                                    history, _time, step, chaos, pwin)
        finally:
            if pwin is not None:
                # a window still open when the loop dies (crash, stop
                # inside the range) must not leak the process-wide
                # capture slot
                pwin.close()

    def _fit_epochs(self, loader, eval_data, batch_size, epochs, eval_freq,
                    save_dir, save_freq, num_workers, callbacks, num_iters,
                    history, _time, step, chaos, pwin):
        for epoch in range(epochs):
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            self.network.train()
            epoch_losses = []
            t_fetch = _time.perf_counter()
            for batch in loader:
                # loader-fetch time, handed to telemetry callbacks as the
                # step's data component (StepTimer decomposition)
                data_time = _time.perf_counter() - t_fetch
                if isinstance(batch, dict):
                    # packed-pipeline batch: the whole dict goes to
                    # train_batch as network kwargs
                    x, y = batch, None
                    first = next(iter(batch.values()))
                else:
                    x, y = batch[0], batch[1]
                    first = x[0] if isinstance(x, (list, tuple)) else x
                shape = getattr(first, "shape", None)
                blogs = {"data_time": data_time,
                         "batch_size": int(shape[0]) if shape else None}
                for cb in callbacks:
                    cb.on_train_batch_begin(step + 1, blogs)
                if pwin is not None:
                    pwin.on_step(step + 1)
                if chaos is not None:
                    x = chaos.poison_batch(step + 1, x)
                loss = self.train_batch(x, y)[0]
                if chaos is not None:
                    loss = chaos.corrupt_loss(step + 1, loss)
                epoch_losses.append(loss)
                step += 1
                logs = {"loss": loss}
                gn = getattr(self._train_step, "last_grad_norm", None)
                if gn is not None:
                    # satellite of the numerics observatory: the global
                    # grad norm the clip path already computed — console
                    # line (ProgBarLogger), train_grad_norm gauge
                    # (StepTelemetry) and NaNGuard's grad_nan check all
                    # read it from here
                    logs["grad_norm"] = float(np.asarray(gn))
                for cb in callbacks:
                    cb.on_train_batch_end(step, logs)
                if chaos is not None:
                    chaos.kill_at_step(step)
                if self._stop_training:
                    # mid-epoch stop (preemption listener, NaN-guard
                    # give-up, user callback): leave at a step boundary
                    # without waiting for the epoch to drain
                    break
                if num_iters is not None and step >= num_iters:
                    break
                t_fetch = _time.perf_counter()
            if epoch_losses:
                logs = {"loss": float(np.mean(epoch_losses))}
                history["loss"].append(logs["loss"])
            else:
                # a resumed epoch can legitimately deliver zero batches
                # (checkpoint cursor already past the last full batch
                # with drop_last=True) — a mean over nothing would log
                # a spurious NaN that reads as a training blow-up
                logs = {}
            if eval_data is not None and not self._stop_training and \
                    (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, batch_size,
                                          verbose=0,
                                          num_workers=num_workers)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            for cb in callbacks:
                cb.on_epoch_end(epoch, logs)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                import os
                self.save(os.path.join(save_dir, str(epoch)))
            if self._stop_training or (num_iters is not None and
                                       step >= num_iters):
                break
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=1,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._to_loader(eval_data, batch_size, False, False,
                                 num_workers)
        self.network.eval()
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            if isinstance(batch, dict):
                res = self.eval_batch(batch)
            else:
                res = self.eval_batch(batch[0], batch[1])
            if res:
                losses.append(res[0])
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            names = m.name()
            vals = m.accumulate()
            if isinstance(names, list):
                logs.update(dict(zip(names, vals)))
            else:
                logs[names] = vals
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=0):
        loader = self._to_loader(test_data, batch_size, False, False,
                                 num_workers)
        self.network.eval()
        outs = [self.predict_batch(b[0] if isinstance(b, (tuple, list))
                                   else b)[0] for b in loader]
        if stack_outputs:
            return [np.concatenate(outs, axis=0)]
        return [outs]

    # -- persistence / introspection ------------------------------------------
    def save(self, path, training=True):
        from paddle_tpu.framework.io import save
        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os
        from paddle_tpu.framework.io import load
        if os.path.isdir(path):
            # ModelCheckpoint layout: one committed step holding
            # {"model": ..., "optimizer": ...}; a step holding a flat
            # state_dict loads as model weights only (docs/CHECKPOINT.md)
            state = load(path)
            if isinstance(state.get("model"), dict):
                self.network.set_state_dict(state["model"])
                if not reset_optimizer and self._optimizer is not None \
                        and "optimizer" in state:
                    self._optimizer.set_state_dict(state["optimizer"])
            else:
                self.network.set_state_dict(state)
            return
        self.network.set_state_dict(load(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        total = sum(int(np.prod(p.shape)) for p in
                    self.network.parameters())
        trainable = sum(int(np.prod(p.shape)) for p in
                        self.network.parameters() if not p.stop_gradient)
        lines = [repr(self.network),
                 f"Total params: {total:,}",
                 f"Trainable params: {trainable:,}"]
        s = "\n".join(lines)
        print(s)
        return {"total_params": total, "trainable_params": trainable}

    @staticmethod
    def _to_loader(data, batch_size, shuffle, drop_last, num_workers):
        from paddle_tpu.io import DataLoader, Dataset
        if data is None:
            raise ValueError("data must not be None")
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data  # assume an iterable of batches
