"""hapi high-level API (reference: ``python/paddle/hapi/``)."""
from .model import (  # noqa: F401
    Model, Callback, ProgBarLogger, ModelCheckpoint, EarlyStopping, VisualDL,
    LRScheduler, StepTelemetry,
)
