"""The classic paddle static-graph workflow (Program/Executor). Run:
    python examples/static_regression.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static


def main():
    paddle.enable_static()
    rng = np.random.RandomState(0)
    w_true = rng.randn(13, 1).astype(np.float32)

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 13], "float32")
        y = static.data("y", [None, 1], "float32")
        pred = static.nn.fc(x, size=1)
        loss = paddle.ops.mean(paddle.ops.square(
            paddle.ops.subtract(pred, y)))
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)

    exe = static.Executor()
    exe.run(static.default_startup_program())
    for it in range(50):
        xb = rng.randn(64, 13).astype(np.float32)
        (lv,) = exe.run(prog, feed={"x": xb, "y": xb @ w_true},
                        fetch_list=[loss])
        if it % 10 == 0:
            print(f"step {it}: loss {float(lv):.5f}")
    paddle.disable_static()


if __name__ == "__main__":
    main()
