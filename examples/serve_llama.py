"""End-to-end continuous-batching serving demo.

Starts ``serving.Server`` (HTTP front-end + background engine loop) on a
tiny Llama, fires a handful of CONCURRENT ``/generate`` requests with
mixed prompt/output lengths, and prints each request's TTFT and total
latency plus the engine's final stats — note ``step_compiles: 1``:
every request, prefill chunks and decode alike, rode ONE compiled
unified step (the Ragged-Paged-Attention layout, docs/SERVING.md). Run:

    python examples/serve_llama.py
"""
import json
import threading
import urllib.request

import numpy as np

from _common import build_tiny_llama
from paddle_tpu.serving import Server, ServingEngine


def main():
    model = build_tiny_llama(seed=0, num_hidden_layers=1)
    engine = ServingEngine(model, max_batch=4, max_blocks=32,
                           block_size=4, prefill_chunk=8)
    rng = np.random.RandomState(0)
    prompts = [[int(t) for t in rng.randint(1, 256, n)]
               for n in (6, 14, 9)]
    budgets = [6, 8, 4]
    results = [None] * len(prompts)

    with Server(engine) as server:
        print(f"serving on {server.url}")

        def client(i):
            req = urllib.request.Request(
                server.url + "/generate",
                data=json.dumps({"prompt_ids": prompts[i],
                                 "max_new_tokens": budgets[i]}).encode(),
                headers={"Content-Type": "application/json"})
            results[i] = json.loads(
                urllib.request.urlopen(req, timeout=300).read())

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        # check completeness BEFORE formatting, so a failed client
        # surfaces as the real error instead of a NoneType print crash
        assert all(r is not None for r in results), results
        for i, res in enumerate(results):
            print(f"req {i}: prompt {len(prompts[i]):>2} tok -> "
                  f"{res['num_generated']:>2} tok | "
                  f"ttft {res['ttft_ms']:8.1f} ms | "
                  f"latency {res['latency_ms']:8.1f} ms")
        health = json.loads(urllib.request.urlopen(
            server.url + "/healthz", timeout=10).read())
        print("engine stats:", {k: health[k] for k in
                                ("step_compiles", "attn_impl", "kv_headroom",
                                 "preemptions", "kv_blocks_in_use")})


if __name__ == "__main__":
    main()
