"""Train a tiny Llama on a variable-length corpus with the packed pipeline.

The ``paddle_tpu.data`` subsystem end to end (docs/DATA.md): a
deterministic sharded stream over a synthetic document corpus, first-fit
sequence packing into fixed [B, seq] batches (segment ids + per-document
positions feed the flash-attention mask), async device prefetch, and
``Model.prepare(opt, loss=None)`` so the packed dict batches flow into
``LlamaForCausalLM`` as kwargs. ``FitResilience(pipeline=…)`` makes the
run preemption-safe with exactly-once data. Run:
    python examples/train_packed.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.data import DataPipeline
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


class Corpus:
    """Synthetic documents of 8..48 tokens (a stand-in for tokenized
    text shards); deterministic per index, so any restart replays it."""

    def __init__(self, n=96, vocab=256):
        self.n, self.vocab = n, vocab

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.RandomState(1000 + i)
        return rng.randint(1, self.vocab, rng.randint(8, 49)).astype(
            np.int32)


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    net = LlamaForCausalLM(cfg)
    model = paddle.hapi.Model(net)
    model.prepare(
        paddle.optimizer.AdamW(learning_rate=3e-3,
                               parameters=net.parameters(),
                               grad_clip=nn.ClipGradByGlobalNorm(1.0)),
        loss=None)  # the network computes its own causal-LM loss

    pipeline = DataPipeline(
        Corpus(vocab=cfg.vocab_size), batch_size=2, seq_len=128,
        pack=True, base_seed=7, shuffle=True, drop_last=True,
        device_prefetch=2)

    model.fit(pipeline, epochs=2, verbose=1, log_freq=5)
    eff = pipeline.packer.efficiency_stats()
    print(f"packed {pipeline.step} batches, "
          f"mean packing efficiency {eff['mean']:.1%}")


if __name__ == "__main__":
    main()
