"""Multi-host-capable pipeline parallelism: the whole interleaved
schedule compiled into ONE program (stage hops are lax.ppermute
collectives — the same program runs across hosts on a pod).

On CPU this runs on 8 virtual devices. Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pipeline_spmd.py
"""
import jax
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn as nn


def main():
    n = len(jax.devices())
    pp = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    mesh = dist.init_mesh({"dp": n // pp, "pp": pp})
    print(f"mesh: dp={n // pp} pp={pp}")

    paddle.seed(0)

    def block():  # one homogeneous trunk chunk per (stage, virtual stage)
        return nn.Sequential(nn.Linear(32, 32), nn.Tanh())

    pipe = fleet.SpmdPipelineLayer(block, num_virtual_stages=2, mesh=mesh,
                                   loss_fn=nn.MSELoss())
    engine = fleet.SpmdPipelineParallel(pipe, accumulate_steps=2 * pp)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=engine.parameters())

    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.randn(4 * pp, 32).astype(np.float32))
    Y = paddle.to_tensor((rng.randn(4 * pp, 32) * 0.1).astype(np.float32))
    for step in range(10):
        loss = engine.train_batch((X, Y), opt)
        if step % 3 == 0:
            stats = engine.last_schedule_stats
            print(f"step {step}: loss {float(loss.numpy()):.4f} "
                  f"(bubble {stats['bubble_fraction']}, "
                  f"{stats['n_chunks']} chunks)")
    print("done — every stage hop was a compiled collective-permute")


if __name__ == "__main__":
    main()
