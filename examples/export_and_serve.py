"""Export the flagship model as an XLA artifact and serve it with the
inference Predictor (the TensorRT/ONNX-engine analog; for the
continuous-batching request runtime see serve_llama.py). Run:
    python examples/export_and_serve.py
"""
import numpy as np

import paddle_tpu as paddle
from _common import build_tiny_llama
from paddle_tpu.inference import Config, Predictor
from paddle_tpu.static import InputSpec


def main():
    import os
    import tempfile
    model = build_tiny_llama(seed=0, num_hidden_layers=1)
    with tempfile.TemporaryDirectory(prefix="llama_serving_") as tmp:
        path = os.path.join(tmp, "model")
        paddle.jit.save(model, path,
                        input_spec=[InputSpec([2, 16], "int32")])
        print("exported to", path)

        predictor = Predictor(Config(path))
        ids = np.random.RandomState(0).randint(0, 256, (2, 16)) \
            .astype(np.int32)
        (logits,) = predictor.run([ids])
        print("served logits:", np.asarray(logits).shape)


if __name__ == "__main__":
    main()
