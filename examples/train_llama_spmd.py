"""The same training step SPMD over a device mesh (dp x mp).

On a TPU pod this uses the real chips; on CPU it runs on 8 virtual
devices. Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_llama_spmd.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed import P
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    n = len(__import__("jax").devices())
    mp = 2 if n % 2 == 0 else 1
    mesh = dist.init_mesh({"dp": n // mp, "mp": mp})
    print(f"mesh: dp={n // mp} mp={mp}")

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=(mp > 1)))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    def loss_fn(m, ids, labels):
        _, loss = m(ids, labels=labels)
        return loss

    step = paddle.jit.TrainStep(model, loss_fn, opt, mesh=mesh,
                                input_spec=P("dp"))
    rng = np.random.RandomState(0)
    batch = (rng.randint(0, 256, ((n // mp) * 2, 16))).astype(np.int32)
    for it in range(5):
        loss = step(paddle.to_tensor(batch), paddle.to_tensor(batch))
        print(f"step {it}: loss {float(loss.numpy()):.4f}")


if __name__ == "__main__":
    main()
