"""Whole-loop compiled generation: prefill + every decode step in ONE
XLA program over static KV buffers — the serving hot path (on a v5e this
decodes the 0.7B zoo Llama at ~0.5K tok/s B=1 / ~4K tok/s B=8; see
BENCHMARKS.md). Run:
    JAX_PLATFORMS=cpu python examples/generate_compiled.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()

    prompt = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 256, (2, 12)).astype(np.int64))

    eager = model.generate(prompt, max_new_tokens=16, temperature=0.0)
    compiled = model.generate_compiled(prompt, max_new_tokens=16,
                                       temperature=0.0)
    same = bool((eager.numpy() == compiled.numpy()).all())
    print("greedy compiled == eager token-for-token:", same)

    # second call with the same signature reuses the compiled executable
    again = model.generate_compiled(prompt, max_new_tokens=16,
                                    temperature=0.0)
    print("deterministic:", bool((again.numpy() == compiled.numpy()).all()))
    print("generated shape:", compiled.numpy().shape,
          "(prompt 12 + 16 new)")

    # sampled decoding threads an explicit RNG split chain inside the
    # compiled loop
    sampled = model.generate_compiled(prompt, max_new_tokens=8,
                                      temperature=0.8, top_k=20)
    print("sampled tail:", sampled.numpy()[0, -8:].tolist())


if __name__ == "__main__":
    main()
