"""Train a tiny LM on a repeating pattern, then sample from it with the
KV-cached generate(). Run:
    python examples/generate_llama.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=1, vocab_size=16)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters())
    pattern = np.tile(np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int64), 4)
    ids = paddle.to_tensor(pattern[None, :])
    for _ in range(150):
        _, loss = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
    model.eval()
    prompt = paddle.to_tensor(pattern[None, :8])
    out = model.generate(prompt, max_new_tokens=8, temperature=0)
    print("prompt   :", pattern[:8].tolist())
    print("generated:", np.asarray(out.data)[0, 8:].tolist())


if __name__ == "__main__":
    main()
