"""Calibrate -> convert -> REAL int8 execution -> export.

The PTQ pipeline observes activation ranges on calibration batches,
``convert`` bakes fake-quant scales, and ``convert_to_int8`` rewrites the
model for true int8 compute (XLA's s8 x s8 -> s32 dot — 2x the bf16 MXU
rate on v5e, 4x smaller weights). Run:
    JAX_PLATFORMS=cpu python examples/int8_deploy.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (
    AbsmaxObserver, FakeQuanterWithAbsMaxObserver, PTQ, QuantConfig,
    convert_to_int8,
)


def main():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))
    model.eval()

    # 1) observe activation ranges on calibration data
    ptq = PTQ(QuantConfig(activation=AbsmaxObserver(),
                          weight=FakeQuanterWithAbsMaxObserver()))
    observed = ptq.quantize(model)
    for _ in range(8):
        observed(paddle.to_tensor(rng.randn(32, 16).astype(np.float32)))

    # 2) bake scales (fake-quant simulation), then go REAL int8
    deployed = ptq.convert(observed)
    int8_model = convert_to_int8(deployed)

    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    fp32 = model(x).numpy()
    sim = deployed(x).numpy()
    int8 = int8_model(x).numpy()
    print("fp32 vs int8 mean |err|:", float(np.abs(fp32 - int8).mean()))
    print("simulation vs int8 match:",
          bool(np.allclose(sim, int8, atol=1e-5)))
    print("int8 weight dtype:", int8_model[0].w_q.data.dtype)

    # 3) the int8 model exports like any Layer (weights become int8
    # constants in the saved program)
    import tempfile
    with tempfile.TemporaryDirectory(prefix="int8_deploy_") as tmp:
        path = tmp + "/int8_model"
        paddle.jit.save(int8_model, path,
                        input_spec=[paddle.static.InputSpec([8, 16],
                                                            "float32")])
        served = paddle.jit.load(path)
        print("served == int8:",
              bool(np.allclose(served(x).numpy(), int8, atol=1e-6)))


if __name__ == "__main__":
    main()
