"""Shared example plumbing (not a demo itself — running it is a no-op).

Every example that needs a small, fast causal LM builds it here, so the
model-construction recipe lives in one place.
"""
import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def build_tiny_llama(seed: int = 0, **config_overrides) -> LlamaForCausalLM:
    """Deterministic tiny Llama in eval mode (runs in <1s on CPU).

    ``config_overrides`` land on :meth:`LlamaConfig.tiny` — e.g.
    ``num_hidden_layers=1`` for the export demo's minimal artifact.
    """
    paddle.seed(seed)
    model = LlamaForCausalLM(LlamaConfig.tiny(**config_overrides))
    model.eval()
    return model


if __name__ == "__main__":
    print("helper module; see serve_llama.py / export_and_serve.py")
