"""Train a (tiny) Llama on synthetic data with the compiled TrainStep.

Scale up by swapping LlamaConfig.tiny() for LlamaConfig.llama3_8b() and
adding a mesh (see train_llama_spmd.py). Run:
    python examples/train_llama.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(
        learning_rate=3e-3, parameters=model.parameters(),
        grad_clip=nn.ClipGradByGlobalNorm(1.0))

    def loss_fn(m, ids, labels):
        _, loss = m(ids, labels=labels)
        return loss

    step = paddle.jit.TrainStep(model, loss_fn, opt)  # one XLA program
    rng = np.random.RandomState(0)
    data = (np.arange(64 * 32).reshape(64, 32) % 97).astype(np.int32)
    for it in range(30):
        batch = paddle.to_tensor(data[rng.randint(0, 64, 8)])
        loss = step(batch, batch)
        if it % 10 == 0:
            print(f"step {it}: loss {float(loss.numpy()):.4f}")
    print("done; final loss", float(loss.numpy()))


if __name__ == "__main__":
    main()
