"""incubate.optimizer — LookAhead / ModelAverage / EMA."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.incubate.optimizer import (
    ExponentialMovingAverage, LookAhead, ModelAverage,
)


def _setup(seed=0):
    pt.seed(seed)
    m = nn.Linear(4, 4)
    x = pt.to_tensor(np.random.RandomState(seed).randn(8, 4)
                     .astype(np.float32))
    return m, x


def test_lookahead_trains_and_syncs_slow_weights():
    m, x = _setup()
    inner = pt.optimizer.SGD(learning_rate=0.1,
                             parameters=m.parameters())
    la = LookAhead(inner, alpha=0.5, k=2)
    w0 = np.asarray(m.weight.data).copy()
    losses = []
    for _ in range(6):
        loss = pt.ops.mean(pt.ops.square(m(x)))
        loss.backward()
        la.step()
        la.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    # after a sync step, fast weights == slow weights
    assert la._step % la.k == 0
    np.testing.assert_allclose(np.asarray(m.weight.data),
                               np.asarray(la._slow[id(m.weight)]),
                               rtol=1e-6)
    assert not np.allclose(np.asarray(m.weight.data), w0)
    with pytest.raises(ValueError):
        LookAhead(inner, alpha=2.0)


def test_lookahead_state_roundtrip():
    m, x = _setup(1)
    inner = pt.optimizer.Adam(learning_rate=0.01,
                              parameters=m.parameters())
    la = LookAhead(inner, k=3)
    for _ in range(2):
        loss = pt.ops.mean(pt.ops.square(m(x)))
        loss.backward()
        la.step()
        la.clear_grad()
    sd = la.state_dict()
    la2 = LookAhead(inner, k=3)
    la2.set_state_dict(sd)
    assert la2._step == la._step


def test_model_average_apply_restore():
    m, x = _setup(2)
    opt = pt.optimizer.SGD(learning_rate=0.2, parameters=m.parameters())
    ma = ModelAverage(parameters=m.parameters())
    snapshots = []
    for _ in range(5):
        loss = pt.ops.mean(pt.ops.square(m(x)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        ma.step()
        snapshots.append(np.asarray(m.weight.data).copy())
    live = np.asarray(m.weight.data).copy()
    with ma.apply():
        avg = np.asarray(m.weight.data)
        np.testing.assert_allclose(avg, np.mean(snapshots, axis=0),
                                   rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m.weight.data), live, rtol=1e-7)


def test_ema_update_and_apply():
    m, x = _setup(3)
    ema = ExponentialMovingAverage(m.parameters(), decay=0.5)
    w0 = np.asarray(m.weight.data).copy()
    m.weight._data = m.weight.data + 1.0
    ema.update()
    with ema.apply():
        got = np.asarray(m.weight.data)
        np.testing.assert_allclose(got, 0.5 * w0 + 0.5 * (w0 + 1.0),
                                   rtol=1e-6)
    np.testing.assert_allclose(np.asarray(m.weight.data), w0 + 1.0,
                               rtol=1e-6)
