"""ZeRO group-sharded tests: state/param placement per stage and loss
parity vs plain DP on the 8-device mesh."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import P


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32))


@pytest.fixture()
def mesh8():
    return dist.init_mesh({"sharding": 8})


def _model(seed=3):
    pt.seed(seed)
    return nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 8))


def _data():
    rng = np.random.RandomState(0)
    X = rng.randn(32, 16).astype(np.float32)
    return X, X @ rng.randn(16, 8).astype(np.float32)


class TestGroupSharded:
    def test_stage3_shards_params(self, mesh8):
        m = _model()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        m, o, _ = dist.group_sharded_parallel(m, o, level="p_g_os")
        w = m[0].weight  # [16, 64] dim0 divisible by 8
        assert w._sharding_spec == P("sharding", None)
        assert len({str(s.device) for s in w.data.addressable_shards}) == 8

    def test_stage1_keeps_params_replicated(self, mesh8):
        m = _model()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        m, o, _ = dist.group_sharded_parallel(m, o, level="os")
        assert getattr(m[0].weight, "_sharding_spec", None) is None
        assert o._shard_states_axis == "sharding"

    def test_bad_level_raises(self, mesh8):
        m = _model()
        o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
        with pytest.raises(ValueError):
            dist.group_sharded_parallel(m, o, level="zz")

    @pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
    def test_loss_parity_vs_plain(self, mesh8, level):
        X, Y = _data()
        loss_fn = lambda m, a, b: nn.MSELoss()(m(a), b)

        m1 = _model()
        o1 = opt.AdamW(learning_rate=0.01, parameters=m1.parameters())
        s1 = pt.jit.TrainStep(m1, loss_fn, o1)
        base = [float(s1(t(X), t(Y)).numpy()) for _ in range(8)]

        m2 = _model()
        o2 = opt.AdamW(learning_rate=0.01, parameters=m2.parameters())
        m2, o2, _ = dist.group_sharded_parallel(m2, o2, level=level)
        s2 = pt.jit.TrainStep(m2, loss_fn, o2, mesh=mesh8,
                              input_spec=P("sharding"))
        got = [float(s2(t(X), t(Y)).numpy()) for _ in range(8)]
        np.testing.assert_allclose(got, base, rtol=3e-4, atol=1e-6)

    def test_stage1_states_actually_sharded(self, mesh8):
        X, Y = _data()
        m = _model()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        m, o, _ = dist.group_sharded_parallel(m, o, level="os")
        s = pt.jit.TrainStep(m, lambda mm, a, b: nn.MSELoss()(mm(a), b), o,
                             mesh=mesh8, input_spec=P("sharding"))
        s(t(X), t(Y))
        w = m[0].weight
        moment = o._state[id(w)]["moment1"]
        # accumulator sharded over 8 devices while the param is replicated
        assert len({str(sh.device)
                    for sh in moment.addressable_shards}) == 8
        shard0 = moment.addressable_shards[0].data
        assert shard0.shape[0] == w.shape[0] // 8

    def test_save_group_sharded_model(self, mesh8, tmp_path):
        m = _model()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        m, o, _ = dist.group_sharded_parallel(m, o, level="p_g_os")
        dist.save_group_sharded_model(m, str(tmp_path), o)
        back = pt.load(str(tmp_path / "model.pdmodel"))
        np.testing.assert_allclose(back["0.weight"].numpy(),
                                   m[0].weight.numpy())
