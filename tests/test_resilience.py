"""Fault-tolerance layer (docs/RESILIENCE.md): preemption-aware training,
hang watchdog, NaN rollback, chaos injection, loader bad-sample budget,
serving degradation, AMP nonfinite unification, launcher resumable exits.

Tier-1 keeps everything in-process through the injection seams; the
multiprocess launcher integrations (real SIGKILL/SIGTERM + relaunch) are
slow-marked — the 1-CPU sandbox budget pays ~8s of jax import per
subprocess.
"""
import glob
import hashlib
import json
import os
import signal
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.observability.metrics import MetricsRegistry, get_registry
from paddle_tpu.resilience import (
    RESUMABLE_EXIT_CODE, FitResilience, NaNGuard, NumericError,
    PreemptionListener, Watchdog, chaos,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHAOS_VARS = ("PADDLE_TPU_CHAOS_KILL_AT_STEP",
              "PADDLE_TPU_CHAOS_HANG_COLLECTIVE",
              "PADDLE_TPU_CHAOS_POISON_BATCH",
              "PADDLE_TPU_CHAOS_CORRUPT_LOSS",
              "PADDLE_TPU_CHAOS_MARK_DIR")


@pytest.fixture(autouse=True)
def _chaos_clean():
    """Chaos env must never leak between tests (or into other files)."""
    yield
    for k in CHAOS_VARS:
        os.environ.pop(k, None)
    chaos.refresh()


def _tiny_model():
    model = pt.hapi.Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                        nn.Linear(16, 1)))
    model.prepare(pt.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters()),
                  nn.MSELoss())
    return model


def _tiny_data(n=6, bs=4):
    rng = np.random.RandomState(0)
    return [(rng.randn(bs, 8).astype(np.float32),
             rng.randn(bs, 1).astype(np.float32)) for _ in range(n)]


def _digest(named):
    h = hashlib.sha256()
    for name in sorted(named):
        h.update(name.encode())
        h.update(np.ascontiguousarray(np.asarray(named[name])).tobytes())
    return h.hexdigest()


def _model_digest(model):
    return _digest({k: v.numpy()
                    for k, v in model.network.state_dict().items()})


# ---------------------------------------------------------------------------
# ElasticManager heartbeat staleness (satellite: cheap, no subprocesses)
# ---------------------------------------------------------------------------

class TestElasticHeartbeat:
    def test_stale_beat_dead_then_recovers(self):
        from paddle_tpu.distributed.launch import ElasticManager
        from paddle_tpu.distributed.tcp_store import TCPStore
        store = TCPStore(is_master=True, world_size=1)
        em = ElasticManager(store, rank=0, world_size=2,
                            heartbeat_timeout=0.2)
        em._beat()                      # rank 0 beats; rank 1 never did
        assert em.dead_ranks() == [1]   # no beat at all counts as dead
        store.set("__hb/1", str(time.time()))
        assert em.all_alive()
        time.sleep(0.3)                 # both beats go stale
        assert em.dead_ranks() == [0, 1]
        em._beat()                      # rank 0 recovers by beating again
        assert em.dead_ranks() == [1]


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_expiry_log_action_counts(self):
        reg = MetricsRegistry()
        wd = Watchdog(action="log", registry=reg)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            tok = wd.arm("stuck_phase", 0.05, step=7)
            time.sleep(0.25)
            wd.disarm(tok)
        wd.stop()
        assert [e["name"] for e in wd.expired] == ["stuck_phase"]
        assert reg.get("resilience_watchdog_expired_total").value(
            span="stuck_phase") == 1
        assert wd.last_dump is None  # log rung: no postmortem file
        assert any("stuck_phase" in str(x.message) for x in w)

    def test_disarm_in_time_never_fires(self):
        wd = Watchdog(action="log", registry=MetricsRegistry())
        with wd.watch("fast", 5.0):
            pass
        time.sleep(0.05)
        wd.stop()
        assert wd.expired == []

    def test_dump_names_span_rank_step(self, tmp_path):
        wd = Watchdog(action="dump", registry=MetricsRegistry(),
                      trace_dir=str(tmp_path))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with wd.watch("train_step", 0.05, step=42):
                time.sleep(0.25)
        wd.stop()
        doc = json.load(open(wd.last_dump))
        assert doc["stuck_span"]["name"] == "train_step"
        assert doc["stuck_span"]["context"]["step"] == 42
        assert doc["rank"] == 0 and "pid" in doc

    def test_collective_hang_triggers_within_deadline(self, tmp_path):
        """Acceptance: an induced collective hang trips the watchdog
        within its deadline and the postmortem names the stuck span and
        rank."""
        os.environ["PADDLE_TPU_CHAOS_HANG_COLLECTIVE"] = "barrier:0.4"
        chaos.refresh()
        wd = Watchdog(action="dump", registry=MetricsRegistry(),
                      trace_dir=str(tmp_path)).watch_collectives(0.05)
        t0 = time.monotonic()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            pt.distributed.barrier()
        hung = time.monotonic() - t0
        time.sleep(0.1)  # let the monitor thread finish the dump
        wd.stop()
        assert hung >= 0.4  # the chaos hang really stalled the collective
        assert wd.expired and \
            wd.expired[0]["name"] == "collective:barrier@world"
        doc = json.load(open(wd.last_dump))
        assert doc["stuck_span"]["name"] == "collective:barrier@world"
        assert "rank" in doc
        # the deadline fired DURING the hang, not after it resolved
        assert wd.expired[0]["elapsed_s"] < hung + 0.05


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------

class TestPreemption:
    def test_sigterm_one_final_commit_and_resumable_code(self, tmp_path):
        """Acceptance: SIGTERM during fit → exactly one committed step,
        the resumable exit code, no torn step dirs."""
        fr = FitResilience(checkpoint_dir=str(tmp_path), preemption=True)
        model = _tiny_model()

        class KillAt(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                if step == 2:
                    os.kill(os.getpid(), signal.SIGTERM)

        model.fit(_tiny_data(), epochs=3, verbose=0,
                  callbacks=[KillAt(), fr])
        assert fr.preempted and fr.exit_code == RESUMABLE_EXIT_CODE
        assert fr.manager.all_steps() == [fr.final_step]  # exactly one
        assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
        meta = fr.manager.metadata(fr.final_step)
        assert meta["preempted"] and meta["reason"] == "SIGTERM"
        # the commit is bit-identical to the live post-step parameters
        state = fr.manager.restore()
        assert _digest({k: v for k, v in state["model"].items()}) == \
            _model_digest(model)

    def test_restore_resumes_and_completes(self, tmp_path):
        fr = FitResilience(checkpoint_dir=str(tmp_path), preemption=True)
        model = _tiny_model()

        class KillAt(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                if step == 2:
                    os.kill(os.getpid(), signal.SIGTERM)

        model.fit(_tiny_data(), epochs=1, verbose=0,
                  callbacks=[KillAt(), fr])
        stopped_at = fr.final_step

        fr2 = FitResilience(checkpoint_dir=str(tmp_path), preemption=False,
                            save_every_steps=2)
        model2 = _tiny_model()
        assert fr2.restore(model2) == stopped_at
        assert _model_digest(model2) == _model_digest(model)
        model2.fit(_tiny_data(n=4), epochs=1, verbose=0, callbacks=[fr2])
        # global step numbering continued past the preempted commit
        assert max(fr2.manager.all_steps()) > stopped_at

    def test_notice_file_and_env_channels(self, tmp_path):
        notice = tmp_path / "preempt-notice"
        lst = PreemptionListener(notice_file=str(notice), use_store=False,
                                 registry=MetricsRegistry())
        assert not lst.should_stop()
        notice.write_text("maintenance")
        assert lst.should_stop() and lst.reason == "notice_file"

        os.environ["PADDLE_TPU_PREEMPTION_NOTICE"] = "1"
        try:
            lst2 = PreemptionListener(use_store=False,
                                      registry=MetricsRegistry())
            assert lst2.should_stop() and lst2.reason == "notice_env"
        finally:
            del os.environ["PADDLE_TPU_PREEMPTION_NOTICE"]

    def test_ranks_agree_on_consensus_stop_step(self):
        """With a job store, all ranks stop at the SAME step boundary:
        the first observer claims the announcement atomically and
        publishes stop_at = its step + 1; nobody stops before it."""
        from paddle_tpu.distributed.tcp_store import TCPStore
        store = TCPStore(is_master=True, world_size=1)
        a = PreemptionListener(use_store=True, registry=MetricsRegistry())
        b = PreemptionListener(use_store=True, registry=MetricsRegistry())
        a._store = b._store = store  # inject the shared job store
        a.request("SIGTERM")               # only rank A saw the signal
        assert not a.should_stop(step=5)   # announcer keeps stepping too
        assert not b.should_stop(step=5)   # B learned, boundary not hit
        assert b.reason == "store:SIGTERM"
        assert a.should_stop(step=6)       # ...both stop at step 6
        assert b.should_stop(step=6)
        assert a.should_stop(step=7)       # decision is sticky

    def test_handlers_restored_after_fit(self, tmp_path):
        before = signal.getsignal(signal.SIGTERM)
        fr = FitResilience(checkpoint_dir=str(tmp_path), preemption=True)
        _tiny_model().fit(_tiny_data(n=2), epochs=1, verbose=0,
                          callbacks=[fr])
        assert signal.getsignal(signal.SIGTERM) is before


# ---------------------------------------------------------------------------
# NaNGuard
# ---------------------------------------------------------------------------

class TestNaNGuard:
    def test_nan_loss_rolls_back_and_run_completes(self, tmp_path):
        """Acceptance: induced NaN loss → restore-and-continue; training
        still reaches the target step count."""
        os.environ["PADDLE_TPU_CHAOS_CORRUPT_LOSS"] = "3"
        reg = get_registry()
        before = reg.counter("resilience_nonfinite_total").value(
            kind="loss_nan")
        fr = FitResilience(checkpoint_dir=str(tmp_path), save_every_steps=1,
                           nan_guard=True, preemption=False)
        model = _tiny_model()
        steps_run = []

        class Count(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                steps_run.append(step)

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model.fit(_tiny_data(n=6), epochs=1, verbose=0,
                      callbacks=[fr, Count()])
        assert steps_run[-1] == 6  # reached the target despite the NaN
        assert fr.nan_guard.rollbacks == 1
        assert fr.nan_guard.trips[0]["kind"] == "loss_nan"
        assert reg.counter("resilience_nonfinite_total").value(
            kind="loss_nan") == before + 1
        # post-rollback parameters are finite
        for _, v in model.network.state_dict().items():
            assert np.isfinite(v.numpy()).all()

    def test_rollback_budget_exhausted_raises(self):
        guard = NaNGuard(manager=None, max_rollbacks=2,
                         registry=MetricsRegistry())
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert guard.check(1, float("nan")) == "loss_nan"
            assert guard.check(2, float("nan")) == "loss_nan"
            with pytest.raises(NumericError, match="budget"):
                guard.check(3, float("nan"))

    def test_spike_window_trips_and_cooldown(self):
        guard = NaNGuard(manager=None, max_rollbacks=10, spike_window=3,
                         spike_factor=10.0, registry=MetricsRegistry())
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for s, loss in enumerate((1.0, 1.1, 0.9), 1):
                assert guard.check(s, loss) is None
            assert guard.check(4, 100.0) == "loss_spike"
            # cooldown: the very next large value doesn't re-trip (the
            # window is rebuilt from post-rollback losses first)
            assert guard.check(5, 100.0) is None

    def test_grad_norm_nan_trips(self):
        guard = NaNGuard(manager=None, max_rollbacks=10,
                         registry=MetricsRegistry())
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert guard.check(1, 0.5, grad_norm=float("inf")) == "grad_nan"


# ---------------------------------------------------------------------------
# Chaos seams
# ---------------------------------------------------------------------------

class TestChaosSeams:
    def test_poison_batch_nan_fills_floats_only(self):
        os.environ["PADDLE_TPU_CHAOS_POISON_BATCH"] = "2"
        chaos.refresh()
        x = np.ones((2, 3), np.float32)
        ids = np.ones((2,), np.int32)
        px, pids = chaos.poison_batch(2, (x, ids))
        assert np.isnan(px).all()
        assert (pids == 1).all()  # integer leaves untouched
        x2 = chaos.poison_batch(3, x)
        assert not np.isnan(x2).any()  # wrong step: untouched

    def test_poison_int_batch_escalates_to_loss(self):
        """Packed-pipeline batches are all-int — nothing to NaN-fill, so
        the poison must land on the step's loss instead of silently not
        firing (the NaN guard still needs a fault to prove recovery)."""
        os.environ["PADDLE_TPU_CHAOS_POISON_BATCH"] = "2"
        chaos.refresh()
        batch = {"input_ids": np.ones((2, 4), np.int32),
                 "labels": np.ones((2, 4), np.int32)}
        out = chaos.poison_batch(2, batch)
        assert (out["input_ids"] == 1).all()  # ints stay valid tokens
        assert np.isnan(chaos.corrupt_loss(2, 1.0))  # fault still fires
        assert chaos.corrupt_loss(2, 1.0) == 1.0  # exactly once

    def test_mark_dir_fires_once_per_job(self, tmp_path):
        os.environ["PADDLE_TPU_CHAOS_CORRUPT_LOSS"] = "5"
        os.environ["PADDLE_TPU_CHAOS_MARK_DIR"] = str(tmp_path)
        chaos.refresh()
        assert np.isnan(chaos.corrupt_loss(5, 1.0))
        # second delivery (e.g. the relaunched worker replaying step 5)
        assert chaos.corrupt_loss(5, 1.0) == 1.0

    def test_corrupt_loss_disabled_is_identity(self):
        chaos.refresh()
        assert chaos.corrupt_loss(5, 1.25) == 1.25


# ---------------------------------------------------------------------------
# DataLoader bad-sample budget (satellite)
# ---------------------------------------------------------------------------

class _FlakyDataset:
    """Map-style dataset where the listed indices always raise, and
    'heal' indices raise once then succeed (transient IO)."""

    def __init__(self, n=16, bad=(), heal=()):
        self.n = n
        self.bad = set(bad)
        self.heal = dict.fromkeys(heal, 1)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if i in self.bad:
            raise IOError(f"corrupt shard at {i}")
        if self.heal.get(i, 0) > 0:
            self.heal[i] -= 1
            raise IOError(f"transient read at {i}")
        return (np.full((2,), i, np.float32), np.zeros((1,), np.float32))


class TestLoaderBudget:
    def test_skip_bad_samples_and_count(self):
        from paddle_tpu.io import DataLoader
        reg = get_registry()
        before = reg.counter("loader_bad_samples_total").value(
            stage="fetch")
        ds = _FlakyDataset(n=8, bad=(3,))
        dl = DataLoader(ds, batch_size=4, shuffle=False,
                        use_buffer_reader=False, max_bad_samples=2)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            batches = list(dl)
        assert len(batches) == 2
        assert batches[0][0].shape[0] == 3  # sample 3 dropped, epoch lives
        assert batches[1][0].shape[0] == 4
        assert reg.counter("loader_bad_samples_total").value(
            stage="fetch") == before + 1

    def test_retry_heals_transient_failure(self):
        from paddle_tpu.io import DataLoader
        ds = _FlakyDataset(n=8, heal=(2, 5))
        dl = DataLoader(ds, batch_size=4, shuffle=False,
                        use_buffer_reader=False, max_bad_samples=1)
        batches = list(dl)
        # both flaky samples were retried successfully: nothing dropped
        assert all(b[0].shape[0] == 4 for b in batches)

    def test_budget_exhausted_raises_loudly(self):
        from paddle_tpu.io import DataLoader
        ds = _FlakyDataset(n=8, bad=(1, 2, 3))
        dl = DataLoader(ds, batch_size=4, shuffle=False,
                        use_buffer_reader=False, max_bad_samples=2)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(RuntimeError, match="budget exhausted"):
                list(dl)

    def test_env_var_enables_policy(self):
        from paddle_tpu.io import DataLoader
        ds = _FlakyDataset(n=4, bad=(0,))
        os.environ["PADDLE_TPU_LOADER_MAX_BAD_SAMPLES"] = "3"
        try:
            dl = DataLoader(ds, batch_size=4, shuffle=False,
                            use_buffer_reader=False)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                (x, y), = list(dl)
            assert x.shape[0] == 3
        finally:
            del os.environ["PADDLE_TPU_LOADER_MAX_BAD_SAMPLES"]

    def test_budget_persists_across_epochs(self):
        """The budget must not reset per __iter__: a permanently corrupt
        sample re-skipped every epoch still exhausts it."""
        from paddle_tpu.io import DataLoader
        ds = _FlakyDataset(n=4, bad=(1,))
        dl = DataLoader(ds, batch_size=4, shuffle=False,
                        use_buffer_reader=False, max_bad_samples=2)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            list(dl)   # epoch 1: skip (1/2)
            list(dl)   # epoch 2: skip (2/2)
            with pytest.raises(RuntimeError, match="budget exhausted"):
                list(dl)   # epoch 3: over budget

    def test_off_by_default_raises_unchanged(self):
        from paddle_tpu.io import DataLoader
        ds = _FlakyDataset(n=4, bad=(0,))
        dl = DataLoader(ds, batch_size=4, shuffle=False,
                        use_buffer_reader=False)
        with pytest.raises(IOError):
            list(dl)

    def test_threaded_path_skips_too(self):
        from paddle_tpu.io import DataLoader
        ds = _FlakyDataset(n=16, bad=(5,))
        dl = DataLoader(ds, batch_size=4, shuffle=False, num_workers=2,
                        use_buffer_reader=False, max_bad_samples=1)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            batches = list(dl)
        assert sorted(b[0].shape[0] for b in batches) == [3, 4, 4, 4]


# ---------------------------------------------------------------------------
# Serving graceful degradation (satellite) — stub engine, no compile cost
# ---------------------------------------------------------------------------

class _StuckHandle:
    def result(self, timeout=None):
        time.sleep(min(timeout or 0.0, 0.5))
        raise TimeoutError("never finishes")

    def wait(self, timeout=None):
        return False


class _StubEngine:
    def __init__(self, waiting=0):
        self.waiting = waiting

    def start(self):
        return self

    def shutdown(self, drain=True):
        pass

    def stats(self):
        return {"running": 0, "waiting": self.waiting}

    def submit(self, prompt_ids, **kw):
        return _StuckHandle()


class TestServingDegradation:
    def _post(self, url, body, timeout=10):
        import urllib.request
        req = urllib.request.Request(
            url + "/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        try:
            resp = urllib.request.urlopen(req, timeout=timeout)
            return resp.status, dict(resp.headers), resp.read()
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), e.read()

    def test_queue_full_503_retry_after_and_degraded_healthz(self):
        from paddle_tpu.serving.server import Server
        import urllib.request
        srv = Server(_StubEngine(waiting=5), max_queue_depth=3,
                     retry_after_s=7).start()
        try:
            code, headers, body = self._post(srv.url, {"prompt_ids": [1]})
            assert code == 503
            assert headers["Retry-After"] == "7"
            assert b"overloaded" in body
            health = json.loads(urllib.request.urlopen(
                srv.url + "/healthz").read())
            assert health["status"] == "degraded"
            assert health["max_queue_depth"] == 3
            assert get_registry().counter(
                "serving_rejections_total").value(reason="queue_full") >= 1
        finally:
            srv.close()

    def test_under_threshold_still_serves_and_healthy(self):
        from paddle_tpu.serving.server import Server
        import urllib.request
        srv = Server(_StubEngine(waiting=0), max_queue_depth=3,
                     request_timeout=0.2).start()
        try:
            health = json.loads(urllib.request.urlopen(
                srv.url + "/healthz").read())
            assert health["status"] == "ok"
            code, _, _ = self._post(srv.url, {"prompt_ids": [1]})
            assert code == 504  # accepted, then global timeout applies
        finally:
            srv.close()

    def test_per_request_deadline_beats_global_timeout(self):
        from paddle_tpu.serving.server import Server
        srv = Server(_StubEngine(waiting=0), request_timeout=300.0).start()
        try:
            t0 = time.monotonic()
            code, _, body = self._post(
                srv.url, {"prompt_ids": [1], "deadline_s": 0.2})
            assert code == 504
            assert time.monotonic() - t0 < 5.0
            assert b"timed out" in body
            code, _, _ = self._post(
                srv.url, {"prompt_ids": [1], "deadline_s": -1})
            assert code == 400
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# AMP unification (satellite)
# ---------------------------------------------------------------------------

class TestGradScalerUnified:
    def test_found_inf_bumps_resilience_family(self):
        from paddle_tpu.core.tensor import Tensor
        net = nn.Linear(4, 4)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
        for p in opt._parameter_list:
            p.grad = Tensor(np.full(p.shape, np.inf, np.float32),
                            stop_gradient=True)
        reg = get_registry()
        before = reg.counter("resilience_nonfinite_total").value(
            kind="grad_scaler")
        scaler = pt.amp.GradScaler()
        scaler.unscale_(opt)
        assert scaler._found_inf
        assert reg.counter("resilience_nonfinite_total").value(
            kind="grad_scaler") == before + 1


# ---------------------------------------------------------------------------
# Multiprocess integrations (slow: real kills + relaunch, one jax import
# per attempt)
# ---------------------------------------------------------------------------

def _worker_env(run_dir, **extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["RESILIENCE_TEST_DIR"] = str(run_dir)
    env.pop("XLA_FLAGS", None)
    for k, v in extra.items():
        env[k] = str(v)
    return env


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


WORKER = os.path.join(REPO, "tests", "resilience_worker.py")


@pytest.mark.slow  # SIGKILL + elastic relaunch, ~2 jax imports
def test_chaos_kill_restart_resumes_bit_identical(tmp_path):
    """Acceptance: a fit killed mid-epoch restarts via the elastic
    launcher and resumes from the last committed step with bit-identical
    parameters (PR 3 restore oracle, recomputed from the checkpoint)."""
    env = _worker_env(tmp_path, RESILIENCE_TEST_STEPS=8,
                      PADDLE_TPU_CHAOS_KILL_AT_STEP=4,
                      PADDLE_TPU_CHAOS_MARK_DIR=str(tmp_path))
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--max_restarts", "1", WORKER],
        cwd=REPO, env=env, timeout=240, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    done = json.load(open(tmp_path / "done.json"))
    assert done["final_step"] == 8
    steps = _read_jsonl(tmp_path / "steps.jsonl")
    pids = list(dict.fromkeys(s["pid"] for s in steps))
    assert len(pids) == 2  # exactly one SIGKILL + relaunch
    # the relaunched worker recorded what it restored; recompute the
    # digest from the checkpoint itself — bit-identical restore
    resume_files = glob.glob(str(tmp_path / "resume_*.json"))
    assert len(resume_files) == 1
    resume = json.load(open(resume_files[0]))
    assert resume["resumed_from"] <= 4
    from paddle_tpu.checkpoint import CheckpointManager
    state = CheckpointManager(str(tmp_path / "ckpt")).restore(
        step=resume["resumed_from"])
    import tests.resilience_worker as rw
    assert rw.state_digest(state["model"]) == resume["digest"]


@pytest.mark.slow  # real SIGTERM to a live fit in a subprocess
def test_sigterm_subprocess_resumable_exit_and_single_commit(tmp_path):
    """Acceptance (process-level): SIGTERM during fit → one final
    committed checkpoint, the resumable exit status, no torn dirs."""
    env = _worker_env(tmp_path, RESILIENCE_TEST_STEPS=500,
                      RESILIENCE_TEST_STEP_SLEEP=0.05,
                      RESILIENCE_TEST_SAVE_EVERY="")
    proc = subprocess.Popen([sys.executable, WORKER], cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.monotonic() + 120
        steps_file = tmp_path / "steps.jsonl"
        while time.monotonic() < deadline:
            if steps_file.exists() and len(_read_jsonl(steps_file)) >= 2:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("worker never started stepping")
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
        assert proc.returncode == RESUMABLE_EXIT_CODE, out
    finally:
        if proc.poll() is None:
            proc.kill()
    from paddle_tpu.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    assert len(mgr.all_steps()) == 1  # the final save is the ONLY commit
    assert not [n for n in os.listdir(tmp_path / "ckpt")
                if n.endswith(".tmp")]
    assert mgr.metadata(mgr.latest_step())["preempted"]


@pytest.mark.slow  # launcher-level resumable contract, ~2 jax imports
def test_launcher_relaunches_resumable_without_crash_budget(tmp_path):
    """A worker that self-preempts (exit 79) is relaunched even with
    --max_restarts 0, resumes, and the job completes cleanly."""
    env = _worker_env(tmp_path, RESILIENCE_TEST_STEPS=6,
                      RESILIENCE_TEST_SELF_PREEMPT_STEP=2)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--max_restarts", "0", WORKER],
        cwd=REPO, env=env, timeout=240, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    done = json.load(open(tmp_path / "done.json"))
    assert done["final_step"] == 6
    resume_files = glob.glob(str(tmp_path / "resume_*.json"))
    assert len(resume_files) == 1  # exactly one preempt→resume cycle
