"""Training numerics observatory (paddle_tpu.observability.numerics).

Coverage contract (ISSUE 14): the disarmed-tap bit-identity guarantee
(tap-on-but-disarmed program == never-instrumented program, compiled-HLO
text AND loss bits), arming mid-run compiles exactly ONE instrumented
twin (then compile-once), sampled-step tap/grad/update stat sanity plus
the ``numerics_*`` gauge families, sampling cadence
(``PADDLE_TPU_NUMERICS_EVERY``), the NaN-provenance probe (poisoned
layer named as the FIRST non-finite tap in topological order, end to
end through a NaNGuard rollback in ``Model.fit``), the host-side-only
corruption counterexample (``verdict: "finite_in_graph"``), calibration
sketch accumulation + checkpoint round-trip (``FitResilience``), the
``grad_norm`` fit-log / ``train_grad_norm`` gauge satellite, and the
serving decode-path drift gauges.
"""
import json
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import numerics
from paddle_tpu.observability.metrics import get_registry

NUM_VARS = ("PADDLE_TPU_NUMERICS", "PADDLE_TPU_NUMERICS_EVERY",
            "PADDLE_TPU_NUMERICS_PROVENANCE", "PADDLE_TPU_TRACE_DIR",
            "PADDLE_TPU_CHAOS_CORRUPT_LOSS")


@pytest.fixture(autouse=True)
def _numerics_clean():
    """Numerics env and the observatory singleton must never leak
    between tests (sketches accumulate per process)."""
    saved = {k: os.environ.get(k) for k in NUM_VARS}
    yield
    for k, v in saved.items():
        os.environ.pop(k, None) if v is None \
            else os.environ.__setitem__(k, v)
    numerics._observatory = None
    from paddle_tpu.resilience import chaos
    chaos.refresh()


def _tiny_lm(seed=0):
    pt.seed(seed)
    return LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=True))


def _lm_step(seed=0, clip=True):
    """(TrainStep, batch) on the tap-instrumented tiny llama."""
    model = _tiny_lm(seed)
    opt = pt.optimizer.AdamW(
        learning_rate=1e-3, parameters=model.parameters(),
        grad_clip=pt.nn.ClipGradByGlobalNorm(1.0) if clip else None)
    step = pt.jit.TrainStep(model, lambda m, t: m(t, labels=t)[1], opt)
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randint(0, 64, (2, 16)).astype(np.int64))
    return model, step, (x,)


def _lm_batches(n=4, bs=2, seqlen=16, vocab=64):
    rng = np.random.RandomState(1)
    out = []
    for _ in range(n):
        ids = rng.randint(0, vocab, (bs, seqlen)).astype(np.int64)
        out.append({"input_ids": ids, "labels": ids.copy()})
    return out


# ---------------------------------------------------------------------------
# disarmed-tap contract: bit-identical program, zero extra compiles
# ---------------------------------------------------------------------------

class TestDisarmedContract:
    def test_disarmed_program_bit_identical_to_never_instrumented(
            self, monkeypatch):
        """The tap seam disarmed must cost NOTHING: same compiled-HLO
        text and bit-equal losses as a build where the seam never
        existed (taps monkeypatched to bare identity)."""
        os.environ.pop("PADDLE_TPU_NUMERICS", None)

        _, step_a, batch = _lm_step(seed=3)
        hlo_a = step_a.compiled_hlo(*batch)
        losses_a = [float(step_a(*batch).numpy())]

        # a build whose model code never had the seam: tap is identity,
        # scope/suppress are inert context managers
        from contextlib import contextmanager

        @contextmanager
        def _null(*a, **k):
            yield

        monkeypatch.setattr(numerics, "tap", lambda name, x: x)
        monkeypatch.setattr(numerics, "scope", _null)
        monkeypatch.setattr(numerics, "suppress", _null)
        _, step_b, batch_b = _lm_step(seed=3)
        hlo_b = step_b.compiled_hlo(*batch_b)
        losses_b = [float(step_b(*batch_b).numpy())]

        assert hlo_a == hlo_b, \
            "disarmed tap seam changed the compiled program"
        assert losses_a == losses_b, \
            "disarmed tap seam changed the training math"
        assert len(step_a._cache) == len(step_b._cache) == 1

    def test_arming_mid_run_compiles_exactly_one_twin(self):
        os.environ.pop("PADDLE_TPU_NUMERICS", None)
        _, step, batch = _lm_step(seed=4)
        step(*batch)
        step(*batch)
        assert len(step._cache) == 1
        os.environ["PADDLE_TPU_NUMERICS"] = "1"
        os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "1"
        step(*batch)
        assert len(step._cache) == 2, \
            "arming must add exactly ONE instrumented executable"
        step(*batch)
        step(*batch)
        assert len(step._cache) == 2, "instrumented twin must be cached"
        # disarming goes back to the plain executable, no new compiles
        os.environ["PADDLE_TPU_NUMERICS"] = "0"
        step(*batch)
        assert len(step._cache) == 2


# ---------------------------------------------------------------------------
# sampled-step stats
# ---------------------------------------------------------------------------

class TestSampledStats:
    def test_sample_contents_and_gauges(self):
        os.environ["PADDLE_TPU_NUMERICS"] = "1"
        os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "1"
        _, step, batch = _lm_step(seed=5)
        step(*batch)
        s = step.last_numerics
        assert s is not None
        # taps in topological (execution) order, all stats finite
        names = list(s["taps"])
        assert names[0] == "embed" and names[-1] == "logits"
        assert names.index("layers.0.attn") < names.index("layers.1.attn")
        assert len(names) == 11  # embed + 2x(attn,mlp_act,mlp,resid) + 2
        for name, (absmax, mean, rms, nonfinite) in s["taps"].items():
            assert np.isfinite((absmax, mean, rms)).all(), name
            assert nonfinite == 0, name
            assert absmax >= rms >= 0, name
        # fused-bucket grad stats + update/param norms + global norm
        assert s["grads"] and s["updates"]
        for norm, nonfinite in s["grads"].values():
            assert np.isfinite(norm) and nonfinite == 0
        for unorm, pnorm in s["updates"].values():
            assert np.isfinite(unorm) and pnorm > 0
        assert np.isfinite(s["grad_norm"]) and np.isfinite(s["loss"])
        # observatory published the gauge families
        doc = get_registry().to_json()
        assert any(v["labels"].get("tap") == "embed"
                   for v in doc["numerics_tap_absmax"]["samples"])
        assert doc["numerics_grad_norm"]["samples"]
        assert doc["numerics_update_ratio"]["samples"]

    def test_sampling_cadence_every_n(self):
        """The cadence decision function alone — the EVERY=1 publication
        path through a real compiled twin is pinned above."""
        os.environ["PADDLE_TPU_NUMERICS"] = "1"
        os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "3"
        sampled = [i for i in range(1, 13)
                   if numerics.sample_this_step(i)]
        assert sampled == [1, 3, 6, 9, 12]  # step 1 always sampled
        # malformed / non-positive periods fall back to the default
        os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "banana"
        assert numerics.every() == 32
        os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "-3"
        assert numerics.every() == 32
        os.environ["PADDLE_TPU_NUMERICS"] = "0"
        assert not numerics.sample_this_step(1)


# ---------------------------------------------------------------------------
# NaN provenance
# ---------------------------------------------------------------------------

def _poison(model, value=float("nan")):
    """NaN-poison layer 1's down_proj weight: the first tap to go
    non-finite in topological order is layers.1.mlp."""
    w = model.model.layers[1].mlp.down_proj.weight
    arr = w.numpy().copy()
    arr[0, 0] = value
    w.set_value(pt.to_tensor(arr))


class TestNaNProvenance:
    def test_probe_names_first_nonfinite_tap(self, tmp_path):
        os.environ["PADDLE_TPU_NUMERICS_PROVENANCE"] = "1"
        os.environ["PADDLE_TPU_TRACE_DIR"] = str(tmp_path)
        model, step, batch = _lm_step(seed=7)
        step(*batch)  # stashes the batch + rng parts
        _poison(model)
        # neutrality pins around the probe: weights, the rng stream and
        # the compile-once guard on ``_cache`` must all be untouched (a
        # probe that perturbs what it inspects breaks resume digests)
        from paddle_tpu.core import generator
        state0 = {k: v.numpy().copy()
                  for k, v in model.state_dict().items()}
        rng0 = generator.get_rng_state()
        cache0 = len(step._cache)
        path = numerics.write_provenance(step, step=1,
                                         trip_kind="loss_nan")
        doc = json.load(open(path))
        assert doc["schema"] == "nan_provenance_v1"
        assert doc["verdict"] == "nonfinite_in_graph"
        assert doc["first_nonfinite"]["kind"] == "tap"
        assert doc["first_nonfinite"]["name"] == "layers.1.mlp"
        # upstream of the poison stays finite in the replay record
        taps = doc["replay"]["taps"]
        assert taps["layers.1.mlp_act"]["nonfinite"] == 0
        assert taps["layers.1.mlp"]["nonfinite"] > 0
        assert generator.get_rng_state() == rng0
        assert len(step._cache) == cache0
        for k, v in model.state_dict().items():
            np.testing.assert_array_equal(v.numpy(), state0[k])

    def test_fit_nan_drill_end_to_end(self, tmp_path):
        """Acceptance drill: poison committed INTO the checkpoint (the
        poison callback runs before FitResilience's save), next step's
        loss goes NaN, the guard rolls back and the forced replay names
        the poisoned layer."""
        from paddle_tpu.resilience import FitResilience
        os.environ["PADDLE_TPU_NUMERICS_PROVENANCE"] = "1"
        os.environ["PADDLE_TPU_TRACE_DIR"] = str(tmp_path / "trace")
        lm = _tiny_lm(seed=9)
        model = pt.hapi.Model(lm)
        model.prepare(pt.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=model.parameters()))

        # poison at the END of step 2 (before FitResilience's save of
        # step 2 — the poison is committed INTO the checkpoint); step 3
        # is the last batch, so the guard trips exactly once
        class Poison(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                if step == 2:
                    _poison(lm)

        fr = FitResilience(checkpoint_dir=str(tmp_path / "ckpt"),
                           save_every_steps=1, nan_guard=True,
                           preemption=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model.fit(_lm_batches(n=3), epochs=1, verbose=0, shuffle=False,
                      callbacks=[Poison(), fr])
        assert fr.nan_guard.rollbacks == 1
        files = [f for f in os.listdir(tmp_path / "trace")
                 if f.startswith("nan_provenance_")]
        assert len(files) == 1
        doc = json.load(open(tmp_path / "trace" / files[0]))
        assert doc["trip_kind"] == "loss_nan"
        assert doc["verdict"] == "nonfinite_in_graph"
        assert doc["first_nonfinite"]["name"] == "layers.1.mlp"

    def test_host_side_corruption_replays_finite(self, tmp_path):
        """A chaos-injected host-side NaN loss replays all-finite: the
        provenance document must say so instead of inventing a layer."""
        from paddle_tpu.resilience import FitResilience
        os.environ["PADDLE_TPU_NUMERICS_PROVENANCE"] = "1"
        os.environ["PADDLE_TPU_TRACE_DIR"] = str(tmp_path / "trace")
        os.environ["PADDLE_TPU_CHAOS_CORRUPT_LOSS"] = "2"
        model = pt.hapi.Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                            nn.Linear(16, 1)))
        model.prepare(pt.optimizer.SGD(learning_rate=0.01,
                                       parameters=model.parameters()),
                      nn.MSELoss())
        rng = np.random.RandomState(0)
        data = [(rng.randn(4, 8).astype(np.float32),
                 rng.randn(4, 1).astype(np.float32)) for _ in range(4)]
        fr = FitResilience(checkpoint_dir=str(tmp_path / "ckpt"),
                           save_every_steps=1, nan_guard=True,
                           preemption=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model.fit(data, epochs=1, verbose=0, callbacks=[fr])
        assert fr.nan_guard.rollbacks == 1
        files = [f for f in os.listdir(tmp_path / "trace")
                 if f.startswith("nan_provenance_")]
        assert len(files) == 1
        doc = json.load(open(tmp_path / "trace" / files[0]))
        assert doc["verdict"] == "finite_in_graph"
        assert doc["first_nonfinite"] is None


# ---------------------------------------------------------------------------
# calibration sketches + checkpoint aux state
# ---------------------------------------------------------------------------

class TestCalibration:
    def test_sketch_accumulates_and_merges(self):
        sk = numerics._Sketch()
        for v in (0.5, 1.5, 3.0, 100.0):
            sk.add(v)
        s = sk.summary()
        assert s["n"] == 4 and s["absmax"] == 100.0
        assert s["p99"] >= 100.0  # bucket upper edge covers the max
        other = numerics._Sketch()
        other.merge(s)
        other.add(200.0)
        assert other.absmax == 200.0 and other.summary()["n"] == 5

    def test_fit_commits_and_restores_calibration(self, tmp_path):
        from paddle_tpu.resilience import FitResilience
        os.environ["PADDLE_TPU_NUMERICS"] = "1"
        os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "1"
        lm = _tiny_lm(seed=10)
        model = pt.hapi.Model(lm)
        model.prepare(pt.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=model.parameters()))
        fr = FitResilience(checkpoint_dir=str(tmp_path),
                           save_every_steps=1, preemption=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model.fit(_lm_batches(n=2), epochs=1, verbose=0,
                      callbacks=[fr])
        state = fr.manager.restore()
        assert "numerics" in state
        taps = state["numerics"]["taps"]
        assert "final_norm" in taps and taps["final_norm"]["n"] >= 1
        # a fresh process (serving calibration load) merges the summary
        numerics._observatory = None
        obs = numerics.get_observatory()
        obs.load_summary(state["numerics"])
        assert obs.sketches["final_norm"].absmax == \
            taps["final_norm"]["absmax"]


# ---------------------------------------------------------------------------
# satellites: fit-log grad_norm, flight-recorder appendix, serving drift
# ---------------------------------------------------------------------------

class TestSatellites:
    def test_grad_norm_in_fit_logs_and_gauge(self):
        seen = []

        class Grab(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                seen.append((logs or {}).get("grad_norm"))

        model = pt.hapi.Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                            nn.Linear(16, 1)))
        model.prepare(
            pt.optimizer.SGD(learning_rate=0.01,
                             parameters=model.parameters(),
                             grad_clip=pt.nn.ClipGradByGlobalNorm(1.0)),
            nn.MSELoss())
        rng = np.random.RandomState(0)
        data = [(rng.randn(4, 8).astype(np.float32),
                 rng.randn(4, 1).astype(np.float32)) for _ in range(3)]
        model.fit(data, epochs=1, verbose=0,
                  callbacks=[pt.callbacks.StepTelemetry(peak=0), Grab()])
        assert len(seen) == 3
        assert all(g is not None and np.isfinite(g) for g in seen)
        doc = get_registry().to_json()
        assert doc["train_grad_norm"]["samples"]

    def test_flight_recorder_appendix_carries_last_sample(self):
        os.environ["PADDLE_TPU_NUMERICS"] = "1"
        os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "1"
        _, step, batch = _lm_step(seed=11)
        step(*batch)
        from paddle_tpu.observability import flight_recorder as fr
        appendix = fr._ledger_appendix()
        assert appendix.get("numerics", {}).get("step") == 1
        assert "taps" in appendix["numerics"]

    def test_serving_decode_drift_gauges(self):
        os.environ["PADDLE_TPU_NUMERICS"] = "1"
        os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "2"
        from paddle_tpu.serving import ServingEngine
        lm = _tiny_lm(seed=12)
        lm.eval()
        # a training calibration sketch makes the drift ratio computable
        obs = numerics.get_observatory()
        obs.load_summary({"version": 1, "taps": {
            "final_norm": {"n": 1, "absmax": 1.0, "p50": 1.0,
                           "p99": 1.0, "buckets": {}}}})
        eng = ServingEngine(lm, max_batch=2, max_blocks=16, block_size=4,
                            prefill_chunk=4)
        h = eng.submit([1, 2, 3], max_new_tokens=4, temperature=0.0)
        eng.start()
        h.result(timeout=60)
        eng.shutdown()
        assert eng.step_traces == 2  # plain + the instrumented twin
        doc = get_registry().to_json()
        assert any(v["labels"].get("tap") == "final_norm"
                   for v in doc["numerics_decode_absmax"]["samples"])
        assert any(v["labels"].get("tap") == "final_norm" and v["value"] > 0
                   for v in doc["numerics_decode_drift_ratio"]["samples"])

    def test_disarmed_serving_engine_untouched(self):
        os.environ.pop("PADDLE_TPU_NUMERICS", None)
        from paddle_tpu.serving import ServingEngine
        lm = _tiny_lm(seed=13)
        lm.eval()
        eng = ServingEngine(lm, max_batch=2, max_blocks=16, block_size=4,
                            prefill_chunk=4)
        h = eng.submit([1, 2, 3], max_new_tokens=3, temperature=0.0)
        eng.start()
        h.result(timeout=60)
        eng.shutdown()
        assert eng.step_traces == 1 and eng._numerics_step is None
