"""Autograd tape tests, incl. numeric-gradient checks in the style of the
reference's OpTest.check_grad (op_test.py:2261 — analytic vs finite difference).
"""
import numpy as np
import pytest

import paddle_tpu as pt


def numeric_grad(f, x, eps=1e-3):
    """Central finite differences of scalar f at numpy point x."""
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp = x.copy(); xp[i] += eps
        xm = x.copy(); xm[i] -= eps
        g[i] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


def check_grad(op, x_np, analytic_fn=None, rtol=1e-2, atol=1e-3):
    x = pt.to_tensor(x_np, stop_gradient=False)
    y = op(x).sum()
    y.backward()
    num = numeric_grad(lambda v: float(op(pt.to_tensor(v)).sum().numpy()), x_np)
    np.testing.assert_allclose(x.grad.numpy(), num, rtol=rtol, atol=atol)


@pytest.mark.parametrize("op_name", [
    "exp", "log", "sqrt", "tanh", "sigmoid", "sin", "cos", "square", "abs",
    "rsqrt", "log1p", "erf",
])
def test_unary_numeric_grad(op_name):
    x_np = (np.random.rand(3, 4).astype(np.float32) * 0.8 + 0.2)
    check_grad(getattr(pt, op_name), x_np)


def test_chain_rule():
    x = pt.to_tensor(2.0, stop_gradient=False)
    y = x * x * x  # y = x^3, dy/dx = 3x^2 = 12
    y.backward()
    np.testing.assert_allclose(float(x.grad.numpy()), 12.0, rtol=1e-6)


def test_grad_accumulation():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
    x.clear_grad()
    assert x.grad is None


def test_branching_graph():
    # diamond: z = (x*2) + (x*3); dz/dx = 5
    x = pt.to_tensor([1.0], stop_gradient=False)
    a = x * 2
    b = x * 3
    z = (a + b).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_matmul_grad():
    A = np.random.rand(3, 4).astype(np.float32)
    B = np.random.rand(4, 5).astype(np.float32)
    a = pt.to_tensor(A, stop_gradient=False)
    b = pt.to_tensor(B, stop_gradient=False)
    (a @ b).sum().backward()
    np.testing.assert_allclose(a.grad.numpy(),
                               np.ones((3, 5)) @ B.T, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(),
                               A.T @ np.ones((3, 5)), rtol=1e-5)


def test_stop_gradient_blocks():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = pt.to_tensor([2.0])  # stop_gradient=True
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach_cuts_graph():
    x = pt.to_tensor([3.0], stop_gradient=False)
    y = (x * 2).detach() * x
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # only through 2nd factor


def test_no_grad_context():
    x = pt.to_tensor([1.0], stop_gradient=False)
    with pt.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._grad_node is None


def test_no_grad_decorator():
    @pt.no_grad()
    def f(t):
        return t * 2

    x = pt.to_tensor([1.0], stop_gradient=False)
    assert f(x).stop_gradient


def test_multi_output_op_grad():
    x_np = np.random.rand(2, 6).astype(np.float32)
    x = pt.to_tensor(x_np, stop_gradient=False)
    vals, idx = pt.topk(x, 3)
    vals.sum().backward()
    # grad is 1 at top-3 positions per row
    expect = np.zeros_like(x_np)
    for r in range(2):
        expect[r, np.argsort(-x_np[r])[:3]] = 1.0
    np.testing.assert_allclose(x.grad.numpy(), expect)
    assert idx.stop_gradient  # int output not differentiable


def test_non_scalar_backward_requires_grad_tensor():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    with pytest.raises(RuntimeError):
        (x * 2).backward()
    (x * 2).backward(pt.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_backward_frees_graph_unless_retained():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).sum()
    y.backward(retain_graph=True)
    y.backward(retain_graph=False)  # still works (graph retained from before)
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


def test_tensor_register_hook():
    x = pt.to_tensor([1.0, 1.0], stop_gradient=False)
    calls = []

    def double_hook(g):
        calls.append(1)
        return g * 2

    x.register_hook(double_hook)
    (x * 3).sum().backward()
    assert calls
    np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])


def test_grad_api():
    x = pt.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    (gx,) = pt.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [4.0])
    assert x.grad is None  # .grad untouched by paddle.grad


def test_grad_allow_unused():
    x = pt.to_tensor([1.0], stop_gradient=False)
    u = pt.to_tensor([1.0], stop_gradient=False)
    with pytest.raises(RuntimeError):
        pt.grad((x * 2).sum(), [x, u])
    x.clear_grad()
    gx, gu = pt.grad((x * 2).sum(), [x, u], allow_unused=True)
    assert gu is None
    np.testing.assert_allclose(gx.numpy(), [2.0])


def test_broadcast_grad():
    x = pt.to_tensor(np.ones((3, 4), np.float32), stop_gradient=False)
    b = pt.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
    ((x + b) * 2).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [6.0] * 4)  # summed over bcast


def test_pylayer():
    import paddle_tpu.autograd as ag

    class Double(ag.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, dy):
            return dy * 2

    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [2.0, 4.0])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_getitem_grad():
    x = pt.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                     stop_gradient=False)
    x[0].sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 1, 1], [0, 0, 0]])


def test_check_nan_inf_flag():
    pt.set_flags({"check_nan_inf": True})
    try:
        x = pt.to_tensor([1.0], stop_gradient=False)
        with pytest.raises(FloatingPointError):
            pt.log(x - 1.0) * 1.0  # log(0) = -inf
    finally:
        pt.set_flags({"check_nan_inf": False})
