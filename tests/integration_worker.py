"""Two-process DCN integration worker (run via
`python -m paddle_tpu.distributed.launch --nproc_per_node 2` — NOT a
pytest file). Exercises the full host-protocol stack end to end:
launcher env -> TCPStore rendezvous -> ElasticManager heartbeats ->
rpc -> parameter-server pull/push -> store-backed object collectives.
Mirrors the reference's test_dist_base.py subprocess-cluster pattern."""
import os
import socket
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed import rpc  # noqa: E402
from paddle_tpu.distributed.launch import ElasticManager  # noqa: E402
from paddle_tpu.distributed.tcp_store import (barrier_via_store,  # noqa: E402
                                              job_store)


def remote_add(a, b):
    return a + b


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    assert world == 2, f"expected 2 trainers, got {world}"
    assert dist.get_rank() == rank and dist.get_world_size() == world

    # 1. rendezvous against the launcher's TCPStore
    store = job_store()
    barrier_via_store(store, "itest/boot", world)

    # 2. cross-process device collective FIRST (jax.distributed must
    # initialize before anything touches the XLA backend): coordinator
    # negotiated through the store, global mesh over both processes' CPU
    # devices — the DCN device-mesh half, not just the host protocol
    dist.init_parallel_env()
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    assert jax.process_count() == world, jax.process_count()
    assert jax.device_count() == world, jax.device_count()
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, PartitionSpec("dp")),
        np.full((1, 4), float(rank + 1), np.float32), (world, 4))
    total = float(jax.jit(lambda a: a.sum())(arr))
    want = sum(range(1, world + 1)) * 4.0
    assert total == want, (total, want)

    # 2b. CROSS-PROCESS PIPELINE: a 2-stage SPMD pipeline whose stage hop
    # (the compiled ppermute) crosses the process boundary — the multi-host
    # path the reference takes with send_v2/recv_v2 and the device_put
    # engine cannot (VERDICT r3 item 1 'done' criterion)
    import jax.numpy as jnp
    import paddle_tpu.distributed.fleet as fleet
    pp_mesh = Mesh(np.array(jax.devices()), ("pp",))
    rng = np.random.RandomState(0)  # same seed both ranks: shared weights
    Ws = rng.randn(2, 8, 8).astype(np.float32) * 0.3
    xs_np = rng.randn(3, 2, 8).astype(np.float32)  # M=3 micro-batches
    # each process contributes its OWN stage's weights; GSPMD assembles
    params = jax.make_array_from_process_local_data(
        NamedSharding(pp_mesh, PartitionSpec(None, "pp")),
        Ws[None, rank:rank + 1], (1, 2, 8, 8))
    xs = jax.make_array_from_process_local_data(
        NamedSharding(pp_mesh, PartitionSpec()), xs_np, xs_np.shape)

    def body(p, x):
        return jnp.tanh(x @ p["W"])

    out = fleet.pipeline_spmd(body, {"W": params}, xs, mesh=pp_mesh,
                              axis="pp")
    got = np.asarray(out.addressable_data(0))
    ref = xs_np
    for c in range(2):
        ref = np.tanh(ref @ Ws[c])
    np.testing.assert_allclose(got, ref, atol=1e-5)

    # 2c. HETEROGENEOUS + TIED stages ACROSS PROCESSES: stage 0 embeds
    # through a shared weight E, stage 1 projects through E.T (the tied
    # embedding/head LM shape); one SGD training step with loss+grad
    # parity vs the local sequential oracle (VERDICT r4 item 3 'done'
    # bar — the reference reaches this with SharedLayerDesc's manual
    # grad allreduce, pp_layers.py:77)
    W_t = rng.randn(8, 8).astype(np.float32) * 0.3
    E_t = rng.randn(8, 8).astype(np.float32) * 0.3
    xs_t = rng.randn(4, 2, 8).astype(np.float32)
    bodies = [
        lambda p, s, x: jnp.tanh((x @ s["E"]) @ p["W"]),  # embed + mix
        lambda p, s, x: (x @ s["E"].T),                   # tied head
    ]
    chunk_params = [{"W": jnp.asarray(W_t)}, {}]

    def tied_loss(E, xs):
        out = fleet.pipeline_spmd_hetero(
            bodies, chunk_params, xs, mesh=pp_mesh, axis="pp",
            shared_params={"E": E})
        return (out ** 2).mean()

    lval, gE = jax.value_and_grad(tied_loss)(jnp.asarray(E_t),
                                             jnp.asarray(xs_t))

    def tied_loss_ref(E, xs):
        h = jnp.tanh((xs @ E) @ jnp.asarray(W_t))
        return ((h @ E.T) ** 2).mean()

    lref, gref = jax.value_and_grad(tied_loss_ref)(jnp.asarray(E_t),
                                                   jnp.asarray(xs_t))
    np.testing.assert_allclose(float(lval), float(lref), rtol=1e-5)
    # grad comparison via a global reduction (a multi-host sharded array
    # cannot be pulled whole onto one host)
    assert float(jnp.abs(gE - gref).max()) < 1e-5
    # one SGD step on the tied weight, loss must drop identically
    E2 = jnp.asarray(E_t) - 0.1 * gE
    l2 = float(tied_loss(E2, jnp.asarray(xs_t)))
    l2_ref = float(tied_loss_ref(jnp.asarray(E_t) - 0.1 * gref,
                                 jnp.asarray(xs_t)))
    np.testing.assert_allclose(l2, l2_ref, rtol=1e-5)
    assert l2 < float(lval)

    # 2d. conv -> rnn -> head HETEROGENEOUS stack across processes: stage
    # bodies with entirely different structures (conv kernel vs recurrent
    # scan + head), trained one step with loss/grad parity vs the local
    # sequential oracle
    F = 8
    K_t = (rng.randn(F, F, 3) * 0.2).astype(np.float32)   # OIH
    Wx_t = (rng.randn(F, F) * 0.3).astype(np.float32)
    Wh_t = (rng.randn(F, F) * 0.3).astype(np.float32)
    Wo_t = (rng.randn(F, F) * 0.3).astype(np.float32)
    xs_h = rng.randn(4, 2, 6, F).astype(np.float32)       # [M, B, T, F]

    def body_conv(p, s, x):                               # [B, T, F]
        h = jnp.moveaxis(x, 1, 2)                         # [B, F, T]
        h = jax.lax.conv_general_dilated(
            h, p["K"], (1,), "SAME",
            dimension_numbers=("NCH", "OIH", "NCH"))
        return jnp.moveaxis(jax.nn.relu(h), 2, 1)

    def body_rnn_head(p, s, x):
        def step(h, xt):
            h2 = jnp.tanh(xt @ p["Wx"] + h @ p["Wh"])
            return h2, h2
        # derive the initial state FROM x so it inherits x's varying
        # manual axes (a fresh zeros constant would break the scan's
        # carry typing inside the manual pipeline region)
        h0 = x[:, 0, :] * 0
        _, ys = jax.lax.scan(step, h0, jnp.moveaxis(x, 1, 0))
        return jnp.moveaxis(ys, 0, 1) @ p["Wo"]

    hparams = [{"K": jnp.asarray(K_t)},
               {"Wx": jnp.asarray(Wx_t), "Wh": jnp.asarray(Wh_t),
                "Wo": jnp.asarray(Wo_t)}]

    def hetero_loss(params, xs):
        out = fleet.pipeline_spmd_hetero(
            [body_conv, body_rnn_head], params, xs, mesh=pp_mesh,
            axis="pp")
        return (out ** 2).mean()

    def hetero_loss_ref(params, xs):
        h = xs.reshape((-1,) + xs.shape[2:])
        h = body_conv(params[0], None, h)
        h = body_rnn_head(params[1], None, h)
        return (h ** 2).mean()

    lv, gv = jax.value_and_grad(hetero_loss)(hparams, jnp.asarray(xs_h))
    lr_, gr_ = jax.value_and_grad(hetero_loss_ref)(hparams,
                                                   jnp.asarray(xs_h))
    np.testing.assert_allclose(float(lv), float(lr_), rtol=1e-5)
    for got_p, ref_p in zip(gv, gr_):
        for kk in got_p:
            err = float(jnp.abs(got_p[kk] - ref_p[kk]).max())
            assert err < 1e-5, (kk, err)
    # one SGD step: loss drops identically in both formulations
    upd = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, hparams, gv)
    upd_ref = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, hparams,
                                     gr_)
    np.testing.assert_allclose(float(hetero_loss(upd, jnp.asarray(xs_h))),
                               float(hetero_loss_ref(upd_ref,
                                                     jnp.asarray(xs_h))),
                               rtol=1e-5)

    # 3. elastic heartbeats: both ranks beat, both see everyone alive
    em = ElasticManager(store, rank, world, heartbeat_interval=0.2,
                        heartbeat_timeout=5.0).start()
    deadline = time.monotonic() + 10
    while not em.all_alive() and time.monotonic() < deadline:
        time.sleep(0.1)
    assert em.all_alive(), f"rank {rank} sees dead peers: {em.dead_ranks()}"

    # 4. rpc mesh on its own store (endpoint negotiated via the job store)
    if rank == 0:
        from paddle_tpu.distributed.tcp_store import free_port
        store.set("itest/rpc_ep", str(free_port()).encode())
    port = int(store.wait("itest/rpc_ep"))
    rpc.init_rpc(f"w{rank}", rank=rank, world_size=world,
                 master_endpoint=f"127.0.0.1:{port}")
    got = rpc.rpc_sync(f"w{(rank + 1) % world}", remote_add, args=(3, 4))
    assert got == 7, got

    # 5. parameter server hosted on w0, client pulls/pushes from w1
    from paddle_tpu.distributed.ps import PSClient, PSServer
    if rank == 0:
        srv = PSServer()
        srv.add_sparse_table("emb", dim=4, lr=0.5, seed=7)
    barrier_via_store(store, "itest/ps_up", world)
    if rank == 1:
        client = PSClient("w0")
        before = client.pull_sparse("emb", [3])[0].copy()
        client.push_sparse_grad("emb", [3],
                                np.ones((1, 4), np.float32))
        after = client.pull_sparse("emb", [3])[0]
        np.testing.assert_allclose(after, before - 0.5, rtol=1e-6)
    barrier_via_store(store, "itest/ps_done", world)

    # 6. store-backed object collectives across the two processes
    gathered = []
    dist.all_gather_object(gathered, {"rank": rank, "msg": f"hello-{rank}"})
    assert [g["rank"] for g in gathered] == [0, 1], gathered
    assert gathered[1 - rank]["msg"] == f"hello-{1 - rank}"

    objs = [{"cfg": 123, "src": 0}] if rank == 0 else [None]
    dist.broadcast_object_list(objs, src=0)
    assert objs[0] == {"cfg": 123, "src": 0}, objs

    outs = []
    dist.scatter_object_list(outs, [f"part{r}" for r in range(world)],
                             src=0)
    assert outs == [f"part{rank}"], outs

    # 6b. SUBGROUP object collectives (host-rank groups): members talk,
    # non-members pass through untouched
    g1 = dist.new_group(ranks=[1])
    sub = []
    dist.all_gather_object(sub, {"r": rank}, group=g1)
    if rank == 1:
        assert sub == [{"r": 1}], sub
    else:
        assert sub == [], sub  # non-member: untouched
    objs2 = [f"sub-{rank}"] if rank == 1 else ["original"]
    dist.broadcast_object_list(objs2, src=1, group=g1)
    if rank == 1:
        assert objs2 == ["sub-1"], objs2
    else:
        assert objs2 == ["original"], objs2  # non-member: untouched
    g01 = dist.new_group(ranks=[0, 1])
    sub2 = []
    dist.all_gather_object(sub2, rank * 10, group=g01)
    assert sub2 == [0, 10], sub2

    em.stop()
    rpc.shutdown()
    print(f"INTEGRATION OK rank={rank}", flush=True)


if __name__ == "__main__":
    main()
