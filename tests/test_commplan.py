"""SPMD communication-plan auditor (ISSUE 12): collective parser on
doctored HLO fragments (five kinds, async -start/-done, nested-brace and
iota replica_groups, use_global_device_ids), replica-group -> named-axis
mapping, the ring-cost ledger, implicit/redundant-reshard defect passes,
the comm-bytes budget gate, and the ``python -m paddle_tpu.analysis
commplan`` CLI over the real parallelism matrix (docs/ANALYSIS.md)."""
import itertools
import json
import os

import pytest

from paddle_tpu.analysis import commplan as CP
from paddle_tpu.analysis.findings import (BaselineError, load_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(names, sizes, procs=None):
    """Hand-built MeshInfo (row-major coords, identity device ids)."""
    coords = [tuple(c) for c in
              itertools.product(*[range(s) for s in sizes])]
    n = len(coords)
    return CP.MeshInfo(tuple(names), tuple(sizes), coords,
                       procs or [0] * n, {i: i for i in range(n)})


def _coll(kind, payload, groups=None, pairs=None, **kw):
    return CP.Collective(kind=kind, name=f"%{kind}.1",
                         computation="main", entry=True,
                         payload_bytes=payload, groups=groups,
                         pairs=pairs, **kw)


# ---------------- parser: doctored fragments --------------------------------

FIVE_KINDS = """\
HloModule jit_step, entry_computation_layout={(f32[4]{0})->(f32[4]{0})}

ENTRY %main.9_spmd (param.1: f32[4]) -> (f32[4]) {
  %param.1 = f32[4]{0} parameter(0)
  %all-reduce.1 = f32[4]{0} all-reduce(f32[4]{0} %param.1), channel_id=1, replica_groups=[1,8]<=[8], use_global_device_ids=true, to_apply=%add
  %all-gather.2 = f32[32]{0} all-gather(f32[4]{0} %param.1), channel_id=2, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %reduce-scatter.3 = f32[4]{0} reduce-scatter(f32[32]{0} %all-gather.2), channel_id=3, replica_groups=[1,8]<=[8], dimensions={0}, to_apply=%add
  %all-to-all.4 = (f32[4]{0}, f32[4]{0}) all-to-all(f32[4]{0} %param.1, f32[4]{0} %param.1), channel_id=4, replica_groups={{0,1},{2,3},{4,5},{6,7}}
  %collective-permute.5 = f32[4]{0} collective-permute(f32[4]{0} %param.1), channel_id=5, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}, metadata={op_name="ring" source_file="ring.py" source_line=7}
  ROOT %tuple = (f32[4]{0}) tuple(f32[4]{0} %param.1)
}
"""


def test_parser_five_kinds():
    cs = CP.parse_collectives(FIVE_KINDS)
    by_kind = {c.kind: c for c in cs}
    assert set(by_kind) == {"all-reduce", "all-gather", "reduce-scatter",
                            "all-to-all", "collective-permute"}
    assert all(c.entry and c.computation == "main.9_spmd" for c in cs)
    ar = by_kind["all-reduce"]
    assert ar.channel_id == 1 and ar.use_global_ids
    assert ar.groups == [list(range(8))]
    assert ar.payload_bytes == 16
    ag = by_kind["all-gather"]
    assert not ag.use_global_ids and ag.groups == [list(range(8))]
    assert ag.payload_bytes == 128          # f32[32] result
    # plain all-to-all tuple result moves every element
    assert by_kind["all-to-all"].payload_bytes == 32
    assert by_kind["all-to-all"].groups == [[0, 1], [2, 3], [4, 5], [6, 7]]
    cp = by_kind["collective-permute"]
    assert cp.pairs == [(0, 1), (1, 2), (2, 3), (3, 0)]
    assert cp.source == "ring.py:7"


ASYNC_PAIR = """\
ENTRY %main (p: f32[64]) -> f32[64] {
  %p = f32[64]{0} parameter(0)
  %all-gather-start.1 = (f32[8]{0}, f32[64]{0}) all-gather-start(f32[8]{0} %p), channel_id=7, replica_groups=[1,8]<=[8], dimensions={0}
  ROOT %all-gather-done.1 = f32[64]{0} all-gather-done((f32[8]{0}, f32[64]{0}) %all-gather-start.1)
}
"""


def test_async_start_counted_once_done_excluded():
    cs = CP.parse_collectives(ASYNC_PAIR)
    assert len(cs) == 1
    c = cs[0]
    assert c.kind == "all-gather" and c.name == "%all-gather-start.1"
    # -start tuple payload = the destination (largest element), not sum
    assert c.payload_bytes == 256


def test_iota_transpose_decode():
    # [4,2]<=[2,4]T(1,0): arange(8).reshape(2,4).T.reshape(4,2)
    line = ("  %all-reduce.2 = f32[4]{0} all-reduce(f32[4]{0} %x), "
            "replica_groups=[4,2]<=[2,4]T(1,0), to_apply=%add")
    cs = CP.parse_collectives("ENTRY %e (x: f32[4]) -> f32[4] {\n"
                              + line + "\n}\n")
    assert cs[0].groups == [[0, 4], [1, 5], [2, 6], [3, 7]]


def test_nested_brace_groups_tail_fields_ignored():
    line = ("  %reduce-scatter.8 = f32[2]{0} reduce-scatter(f32[8]{0} %x), "
            "replica_groups={{0,2},{1,3}}, dimensions={0}, to_apply=%add, "
            'metadata={op_name="scatter{nested}"}')
    cs = CP.parse_collectives("ENTRY %e (x: f32[8]) -> f32[2] {\n"
                              + line + "\n}\n")
    assert cs[0].groups == [[0, 2], [1, 3]]


ENTRY_COMMENTS = r"""HloModule jit_train, entry_computation_layout={(f32[4]{0}, f32[8,4]{1,0})->(f32[], /*index=1*/f32[4]{0})}

%fused_computation.15 (param_0.3: f32[4]) -> f32[4] {
  %param_0.3 = f32[4]{0} parameter(0)
  ROOT %all-reduce.7 = f32[4]{0} all-reduce(f32[4]{0} %param_0.3), replica_groups=[1,8]<=[8], to_apply=%add
}

ENTRY %main.185_spmd (param.2: f32[4], param.1: f32[8,4]) -> (f32[], /*index=1*/f32[4]) {
  %param.2 = f32[4]{0} parameter(0), sharding={devices=[8]<=[8]}, metadata={op_name="train[\'0.bias\']"}
  %param.1 = f32[8,4]{1,0} parameter(1), metadata={op_name="flat_batch[0]"}
  %all-gather.3 = f32[32]{0} all-gather(f32[4]{0} %param.2), channel_id=1, replica_groups=[1,8]<=[8], dimensions={0}, use_global_device_ids=true, metadata={op_name="g" source_file="m.py" source_line=9}
  ROOT %fusion.2 = f32[4]{0} fusion(f32[4]{0} %param.2), kind=kLoop, calls=%fused_computation.15
}
"""


def test_entry_attribution_survives_index_comments():
    """The ENTRY header's /*index=N*/ result comments (they contain `=`)
    must not break computation tracking — the regression that silenced
    the implicit-reshard pass."""
    cs = CP.parse_collectives(ENTRY_COMMENTS)
    by_comp = {c.computation: c for c in cs}
    assert by_comp["main.185_spmd"].entry
    assert not by_comp["fused_computation.15"].entry


def test_entry_param_labels_from_metadata():
    _, entry_params, labels = CP._def_maps(ENTRY_COMMENTS)
    assert entry_params == {"%param.2": 0, "%param.1": 1}
    assert labels == {0: "train['0.bias']", 1: "flat_batch[0]"}


# ---------------- axis mapping and cost model -------------------------------

def test_map_axes_single_and_combined():
    mesh = _mesh(("dp", "mp"), (4, 2))
    dp_groups = [[0, 2, 4, 6], [1, 3, 5, 7]]
    axes, exact, crosses = CP.map_axes(
        _coll("all-reduce", 16, groups=dp_groups), mesh)
    assert axes == ("dp",) and exact and not crosses
    axes, exact, _ = CP.map_axes(
        _coll("all-reduce", 16, groups=[[0, 1], [2, 3], [4, 5], [6, 7]]),
        mesh)
    assert axes == ("mp",) and exact
    axes, exact, _ = CP.map_axes(
        _coll("all-reduce", 16, groups=[list(range(8))]), mesh)
    assert axes == ("dp", "mp") and exact


def test_map_axes_partial_group_is_inexact():
    mesh = _mesh(("dp", "mp"), (4, 2))
    axes, exact, _ = CP.map_axes(
        _coll("all-gather", 16, groups=[[0, 2]]), mesh)
    assert axes == ("dp",) and not exact


def test_map_axes_dcn_when_group_spans_processes():
    mesh = _mesh(("dp",), (4,), procs=[0, 0, 1, 1])
    axes, _, crosses = CP.map_axes(
        _coll("all-reduce", 16, groups=[[0, 1, 2, 3]]), mesh)
    assert axes == ("dp",) and crosses
    ledger = CP.comm_ledger(
        [_coll("all-reduce", 16, groups=[[0, 1, 2, 3]])], mesh)
    assert ledger["dp"]["hops"] == "dcn"


def test_permute_pairs_map_to_ring_axis():
    mesh = _mesh(("pp",), (4,))
    c = _coll("collective-permute", 64,
              pairs=[(0, 1), (1, 2), (2, 3), (3, 0)])
    axes, exact, _ = CP.map_axes(c, mesh)
    assert axes == ("pp",) and exact
    assert CP.wire_bytes(c) == 64


def test_wire_bytes_cost_model():
    g4 = [[0, 1, 2, 3]]
    assert CP.wire_bytes(_coll("all-reduce", 100, groups=g4)) == 150
    assert CP.wire_bytes(_coll("all-gather", 100, groups=g4)) == 75
    assert CP.wire_bytes(_coll("reduce-scatter", 100, groups=g4)) == 300
    assert CP.wire_bytes(_coll("all-to-all", 100, groups=g4)) == 75
    # degenerate single-member group moves nothing
    assert CP.wire_bytes(_coll("all-reduce", 100, groups=[[3]])) == 0


def test_comm_ledger_aggregates_per_axis():
    mesh = _mesh(("dp", "mp"), (4, 2))
    cs = [_coll("all-reduce", 100, groups=[[0, 2, 4, 6], [1, 3, 5, 7]]),
          _coll("all-reduce", 40, groups=[[0, 2, 4, 6], [1, 3, 5, 7]]),
          _coll("all-gather", 80, groups=[[0, 1], [2, 3], [4, 5], [6, 7]])]
    ledger = CP.comm_ledger(cs, mesh)
    assert ledger["dp"]["ops"] == 2
    assert ledger["dp"]["bytes"] == 150 + 60
    assert ledger["dp"]["kinds"] == {"all-reduce": 2}
    assert ledger["mp"] == {"ops": 1, "bytes": 40,
                            "kinds": {"all-gather": 1}, "hops": "ici",
                            "inexact_groups": 0}


# ---------------- defect passes on doctored programs ------------------------

def test_implicit_reshard_flags_state_leaf_gather():
    mesh = _mesh(("dp",), (8,))
    rep = CP.audit_comm(ENTRY_COMMENTS, "doctored", mesh=mesh)
    p0 = [f for f in rep.findings if f.rule == "implicit-reshard"]
    assert len(p0) == 1
    assert p0[0].severity == "P0"
    assert p0[0].data["leaf"] == "train['0.bias']"
    assert p0[0].data["axes"] == "dp"
    assert "m.py:9" in p0[0].message


def test_implicit_reshard_quiet_when_gather_ok():
    mesh = _mesh(("dp",), (8,))
    rep = CP.audit_comm(ENTRY_COMMENTS, "doctored", mesh=mesh,
                       gather_ok=True)
    assert not [f for f in rep.findings if f.rule == "implicit-reshard"]


def test_implicit_reshard_ignores_batch_leaves():
    hlo = ENTRY_COMMENTS.replace("%param.2)", "%param.1)").replace(
        "all-gather(f32[4]{0}", "all-gather(f32[8,4]{1,0}")
    mesh = _mesh(("dp",), (8,))
    rep = CP.audit_comm(hlo, "doctored", mesh=mesh)
    assert not [f for f in rep.findings if f.rule == "implicit-reshard"]


def test_redundant_reshard_pair():
    hlo = """\
ENTRY %main (p: f32[4]) -> f32[4] {
  %p = f32[4]{0} parameter(0)
  %all-gather.1 = f32[32]{0} all-gather(f32[4]{0} %p), replica_groups=[1,8]<=[8], dimensions={0}
  %convert.2 = f32[32]{0} convert(f32[32]{0} %all-gather.1)
  ROOT %reduce-scatter.3 = f32[4]{0} reduce-scatter(f32[32]{0} %convert.2), replica_groups=[1,8]<=[8], dimensions={0}, to_apply=%add
}
"""
    mesh = _mesh(("dp",), (8,))
    rep = CP.audit_comm(hlo, "doctored", mesh=mesh, gather_ok=True)
    p1 = [f for f in rep.findings if f.rule == "redundant-reshard"]
    assert len(p1) == 1 and p1[0].data["gathered"] == 128


# ---------------- budget gate ------------------------------------------------

def test_budget_findings_new_axis_kind_and_drift(monkeypatch):
    pinned = {"dp": {"ops": 2, "bytes": 1000,
                     "kinds": {"all-reduce": 2}}}
    clean = {"dp": {"ops": 2, "bytes": 1010, "kinds": {"all-reduce": 2},
                    "hops": "ici", "inexact_groups": 0}}
    assert CP.budget_findings("g", clean, pinned) == []
    drift = {"dp": {**clean["dp"], "bytes": 1200}}
    fs = CP.budget_findings("g", drift, pinned)
    assert [f.rule for f in fs] == ["comm-budget-drift"]
    # tolerance knob widens the budget
    monkeypatch.setenv("PADDLE_TPU_ANALYSIS_COMM_TOL", "0.5")
    assert CP.budget_findings("g", drift, pinned) == []
    monkeypatch.delenv("PADDLE_TPU_ANALYSIS_COMM_TOL")
    newkind = {"dp": {**clean["dp"],
                      "kinds": {"all-reduce": 2, "all-gather": 1}}}
    assert [f.rule for f in CP.budget_findings("g", newkind, pinned)] \
        == ["comm-new-collective"]
    newaxis = {**clean, "mp": {"ops": 1, "bytes": 5, "kinds": {},
                               "hops": "ici", "inexact_groups": 0}}
    assert [f.rule for f in CP.budget_findings("g", newaxis, pinned)] \
        == ["comm-new-axis"]
    # shrink is silent (re-pin to claim it)
    shrink = {"dp": {**clean["dp"], "bytes": 10, "ops": 1}}
    assert CP.budget_findings("g", shrink, pinned) == []


def test_corrupt_baseline_raises_baseline_error(tmp_path):
    p = tmp_path / "broken.json"
    p.write_text('{"findings": {')
    with pytest.raises(BaselineError) as ei:
        load_baseline(str(p))
    assert "--write-baseline" in str(ei.value)


# ---------------- real parallelism matrix (integration) ---------------------

@pytest.fixture(scope="module")
def commplan_run():
    from paddle_tpu.analysis.driver import ensure_cpu_mesh, run_commplan
    ensure_cpu_mesh()
    return run_commplan()


def test_matrix_covers_segments_and_maps_every_collective(commplan_run):
    run = commplan_run
    covered = set(run["reports"]) | set(run["skipped"])
    assert {"dp8", "dpxmp", "pp", "dpxpp", "zero", "sp", "ep",
            "serving"} <= covered
    # dp x mp, ZeRO, sp and ep must actually lower on this jax
    assert {"dp8", "dpxmp", "pp", "zero", "sp", "ep"} <= \
        set(run["reports"])
    for label, ledger in run["ledgers"].items():
        assert "unmapped" not in ledger and "none" not in ledger, \
            f"{label}: unattributed collectives {ledger}"
        for slot in ledger.values():
            assert slot["inexact_groups"] == 0
    # real geometries are CLEAN — defects only come from seeded typos
    assert run["findings"] == []


def test_ledgers_match_pinned_baseline(commplan_run):
    pinned = load_baseline().commplan
    assert pinned, "commplan section missing from committed baseline"
    for label, ledger in commplan_run["ledgers"].items():
        assert label in pinned, f"geometry {label} never pinned"
        for axis, slot in ledger.items():
            pin = pinned[label][axis]
            assert slot["ops"] == pin["ops"], (label, axis)
            assert slot["bytes"] == pin["bytes"], (label, axis)
            assert slot["kinds"] == pin["kinds"], (label, axis)
        assert CP.budget_findings(label, ledger, pinned.get(label)) == []


def test_cli_clean_exit0_and_seeded_typo_exit1(capsys):
    from paddle_tpu.analysis.__main__ import main
    assert main(["commplan", "--only", "dp8", "--quiet"]) == 0
    capsys.readouterr()
    assert main(["commplan", "--only", "dp8", "--seed-typo"]) == 1
    out = capsys.readouterr().out
    assert "implicit-reshard" in out and "[P0]" in out
    assert "train['0.bias']" in out


def test_cli_missing_and_corrupt_baseline_exit2(tmp_path, capsys):
    from paddle_tpu.analysis.__main__ import main
    missing = tmp_path / "nope.json"
    assert main(["commplan", "--only", "serving",
                 "--baseline", str(missing)]) == 2
    err = capsys.readouterr().err
    assert "--write-baseline" in err
    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text("{not json")
    assert main(["commplan", "--only", "serving",
                 "--baseline", str(corrupt)]) == 2
    assert "corrupt JSON" in capsys.readouterr().err


def test_cli_write_baseline_pins_ledgers(tmp_path):
    from paddle_tpu.analysis.__main__ import main
    path = tmp_path / "pins.json"
    assert main(["commplan", "--only", "dp8", "--quiet",
                 "--baseline", str(path), "--write-baseline"]) == 0
    doc = json.loads(path.read_text())
    assert doc["commplan"]["dp8"]["dp"]["kinds"] == {"all-reduce": 2}
    # and the freshly pinned file gates clean
    assert main(["commplan", "--only", "dp8", "--quiet",
                 "--baseline", str(path)]) == 0
