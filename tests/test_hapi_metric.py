"""hapi Model + paddle.metric tests: fit/evaluate/predict lifecycle, metric
math vs sklearn-style numpy oracles, callbacks."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.io as io
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import metric


class TestMetrics:
    def test_accuracy_top1(self):
        m = metric.Accuracy()
        pred = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32)
        label = np.array([1, 0, 0])
        m.update(m.compute(pt.to_tensor(pred), pt.to_tensor(label)))
        assert abs(m.accumulate() - 2 / 3) < 1e-6

    def test_accuracy_topk(self):
        m = metric.Accuracy(topk=(1, 2))
        pred = np.array([[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]], np.float32)
        label = np.array([1, 1])
        m.update(m.compute(pt.to_tensor(pred), pt.to_tensor(label)))
        acc = m.accumulate()
        assert abs(acc[0] - 0.0) < 1e-6 and abs(acc[1] - 1.0) < 1e-6
        assert m.name() == ["acc_top1", "acc_top2"]

    def test_precision_recall(self):
        preds = np.array([0.9, 0.8, 0.2, 0.7])
        labels = np.array([1, 0, 1, 1])
        p = metric.Precision()
        p.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-6  # tp=2 fp=1
        r = metric.Recall()
        r.update(preds, labels)
        assert abs(r.accumulate() - 2 / 3) < 1e-6  # tp=2 fn=1

    def test_auc_perfect_and_random(self):
        auc = metric.Auc()
        preds = np.array([0.9, 0.8, 0.2, 0.1])
        labels = np.array([1, 1, 0, 0])
        auc.update(preds, labels)
        assert auc.accumulate() > 0.99
        auc.reset()
        auc.update(np.array([0.5, 0.5, 0.5, 0.5]), labels)
        assert abs(auc.accumulate() - 0.5) < 0.01

    def test_auc_matches_numpy_rank_oracle(self):
        rng = np.random.RandomState(0)
        preds = rng.rand(500)
        labels = (rng.rand(500) < preds).astype(np.int64)  # informative
        auc = metric.Auc()
        auc.update(preds, labels)
        # rank-based AUC oracle
        pos = preds[labels == 1]
        neg = preds[labels == 0]
        oracle = (pos[:, None] > neg[None, :]).mean() + \
            0.5 * (pos[:, None] == neg[None, :]).mean()
        assert abs(auc.accumulate() - oracle) < 0.01


class TestHapiModel:
    def _dataset(self, n=128):
        rng = np.random.RandomState(0)
        X = rng.randn(n, 8).astype(np.float32)
        y = (X.sum(-1) > 0).astype(np.int64)
        return io.TensorDataset([X, y])

    def _model(self):
        pt.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
        m = pt.Model(net)
        m.prepare(optimizer=opt.AdamW(learning_rate=0.01,
                                      parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss(),
                  metrics=metric.Accuracy())
        return m

    def test_fit_evaluate_predict(self, capsys):
        m = self._model()
        hist = m.fit(self._dataset(), batch_size=32, epochs=8, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0] * 0.5
        logs = m.evaluate(self._dataset(), batch_size=32, verbose=0)
        assert logs["acc"] > 0.9
        out = m.predict(self._dataset(), batch_size=32,
                        stack_outputs=True)[0]
        assert out.shape == (128, 2)

    def test_eval_during_fit(self):
        m = self._model()
        hist = m.fit(self._dataset(), eval_data=self._dataset(64),
                     batch_size=32, epochs=2, verbose=0)
        assert len(hist["loss"]) == 2

    def test_save_load_roundtrip(self, tmp_path):
        m = self._model()
        m.fit(self._dataset(), batch_size=32, epochs=1, verbose=0)
        m.save(str(tmp_path / "ck"))
        m2 = self._model()
        m2.load(str(tmp_path / "ck"))
        x = np.zeros((4, 8), np.float32)
        np.testing.assert_allclose(
            m.network(pt.to_tensor(x)).numpy(),
            m2.network(pt.to_tensor(x)).numpy(), rtol=1e-6)

    def test_early_stopping(self):
        m = self._model()
        es = pt.hapi.EarlyStopping(monitor="loss", patience=0,
                                   baseline=-1.0)  # nothing beats -1
        hist = m.fit(self._dataset(), batch_size=32, epochs=10, verbose=0,
                     callbacks=[es])
        assert len(hist["loss"]) < 10  # stopped early

    def test_summary(self, capsys):
        m = self._model()
        info = m.summary()
        assert info["total_params"] == 8 * 32 + 32 + 32 * 2 + 2
        assert info["trainable_params"] == info["total_params"]

    def test_num_iters(self):
        m = self._model()
        m.fit(self._dataset(), batch_size=32, epochs=100, verbose=0,
              num_iters=3)
        assert m._optimizer._step_count == 3
