"""Tensor-parallel mpu layer tests: loss parity vs the non-parallel layers
on the 8-device CPU mesh (the reference's own test pattern, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import P


@pytest.fixture()
def mesh_mp8():
    return dist.init_mesh({"mp": 8})


@pytest.fixture()
def mesh_dp2mp4():
    return dist.init_mesh({"dp": 2, "mp": 4})


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32))


class TestColumnParallel:
    def test_forward_matches_dense(self, mesh_mp8):
        rng = np.random.RandomState(0)
        col = fleet.ColumnParallelLinear(16, 32, has_bias=True)
        x = rng.randn(4, 16).astype(np.float32)
        got = col(t(x)).numpy()
        ref = x @ col.weight.numpy() + col.bias.numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
        # weight is actually feature-sharded across 8 devices
        assert col.weight._sharding_spec == P(None, "mp")
        assert len({str(s.device)
                    for s in col.weight.data.addressable_shards}) == 8

    def test_default_has_no_bias(self, mesh_mp8):
        # reference parity: has_bias defaults falsy (mp_layers.py:282)
        assert fleet.ColumnParallelLinear(4, 8).bias is None

    def test_gather_output_false_keeps_sharded(self, mesh_mp8):
        col = fleet.ColumnParallelLinear(16, 32, gather_output=False)
        out = col(t(np.zeros((4, 16))))
        assert out.shape == [4, 32]  # logically full; physically sharded


class TestRowParallel:
    def test_forward_matches_dense(self, mesh_mp8):
        rng = np.random.RandomState(1)
        row = fleet.RowParallelLinear(32, 16)
        x = rng.randn(4, 32).astype(np.float32)
        got = row(t(x)).numpy()
        ref = x @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
        assert row.weight._sharding_spec == P("mp", None)

    def test_col_row_pair(self, mesh_mp8):
        """The Megatron MLP pattern: column-parallel up, row-parallel down
        with input_is_parallel — one allreduce total."""
        rng = np.random.RandomState(2)
        up = fleet.ColumnParallelLinear(16, 64, has_bias=True,
                                        gather_output=False)
        down = fleet.RowParallelLinear(64, 16, input_is_parallel=True)
        x = rng.randn(4, 16).astype(np.float32)
        got = down(nn.functional.relu(up(t(x)))).numpy()
        h = np.maximum(x @ up.weight.numpy() + up.bias.numpy(), 0)
        ref = h @ down.weight.numpy() + down.bias.numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


class TestVocabParallelEmbedding:
    def test_lookup_matches_dense(self, mesh_mp8):
        emb = fleet.VocabParallelEmbedding(64, 16)
        toks = np.array([[0, 5, 63], [10, 20, 40]], dtype=np.int64)
        got = emb(pt.to_tensor(toks)).numpy()
        ref = emb.weight.numpy()[toks]
        np.testing.assert_allclose(got, ref, rtol=1e-5)
        assert emb.weight._sharding_spec == P("mp", None)


class TestParallelCrossEntropy:
    def test_matches_dense_ce(self, mesh_mp8):
        rng = np.random.RandomState(3)
        logits = rng.randn(8, 64).astype(np.float32)
        labels = rng.randint(0, 64, 8).astype(np.int64)
        pce = fleet.ParallelCrossEntropy()
        got = pce(t(logits), pt.to_tensor(labels)).numpy()
        assert got.shape == (8, 1)  # reference keeps the trailing-1 dim
        ref = nn.functional.cross_entropy(
            t(logits), pt.to_tensor(labels), reduction="none").numpy()
        np.testing.assert_allclose(got[:, 0], ref, rtol=1e-4, atol=1e-5)


class TestTPTrainingParity:
    def test_tp_mlp_matches_dense_training(self, mesh_dp2mp4):
        """Megatron MLP trained compiled on (dp=2, mp=4) must track the
        dense single-logical-device run step for step."""
        rng = np.random.RandomState(0)
        X = rng.randn(32, 16).astype(np.float32)
        Y = X @ rng.randn(16, 16).astype(np.float32)

        class DenseMLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.up = nn.Linear(16, 64)
                self.down = nn.Linear(64, 16)

            def forward(self, x):
                return self.down(nn.functional.relu(self.up(x)))

        class TPMLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.up = fleet.ColumnParallelLinear(
                    16, 64, has_bias=True, gather_output=False)
                self.down = fleet.RowParallelLinear(64, 16,
                                                    input_is_parallel=True)

            def forward(self, x):
                return self.down(nn.functional.relu(self.up(x)))

        pt.seed(7)
        dense = DenseMLP()
        pt.seed(7)
        tp = TPMLP()
        np.testing.assert_allclose(dense.up.weight.numpy(),
                                   tp.up.weight.numpy(), rtol=1e-6)

        loss_fn = lambda m, a, b: nn.MSELoss()(m(a), b)
        od = opt.AdamW(learning_rate=0.01, parameters=dense.parameters())
        ot = opt.AdamW(learning_rate=0.01, parameters=tp.parameters())
        sd = pt.jit.TrainStep(dense, loss_fn, od)
        st = pt.jit.TrainStep(tp, loss_fn, ot, mesh=mesh_dp2mp4,
                              input_spec=P("dp"))
        for i in range(10):
            ld = float(sd(t(X), t(Y)).numpy())
            lt = float(st(t(X), t(Y)).numpy())
            assert abs(ld - lt) / max(abs(ld), 1e-8) < 5e-3, (i, ld, lt)
        # weights stayed sharded through the compiled updates
        assert len({str(s.device)
                    for s in tp.up.weight.data.addressable_shards}) == 8


class TestFleetFacade:
    def test_init_and_wrap(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                                   "pp_degree": 1, "sharding_degree": 1}
        hcg = fleet.init(strategy=strategy)
        assert hcg.get_model_parallel_world_size() == 4
        assert dist.get_mesh().shape == {"dp": 2, "pp": 1, "sharding": 1,
                                         "mp": 4}
        m = nn.Linear(4, 4)
        wrapped = fleet.distributed_model(m)
        assert wrapped is m  # mp>1: parallelism lives in the layers

        o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
        assert fleet.distributed_optimizer(o) is o

    def test_dp_only_wraps_dataparallel(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 1}
        fleet.init(strategy=strategy)
        m = nn.Linear(4, 4)
        wrapped = fleet.distributed_model(m)
        assert isinstance(wrapped, dist.DataParallel)
