"""paddle_tpu.jit tests: functional_call purity, to_static parity + caching,
TrainStep equivalence with eager training, and a compiled-vs-eager speedup."""
import time

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def _mlp(seed=0):
    pt.seed(seed)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32))


class TestFunctionalCall:
    def test_matches_direct_and_is_pure(self):
        m = _mlp()
        x = t(np.random.RandomState(0).randn(4, 8))
        direct = m(x).numpy()
        params = {k: v for k, v in m.state_dict().items()}
        out = pt.jit.functional_call(m, params, x)
        np.testing.assert_allclose(out.numpy(), direct, rtol=1e-6)
        # swapped values: different weights give different output, storage
        # untouched afterwards
        zeroed = {k: np.zeros_like(np.asarray(v.data))
                  for k, v in params.items()}
        out0 = pt.jit.functional_call(m, zeroed, x)
        assert not np.allclose(out0.numpy(), direct)
        np.testing.assert_allclose(m(x).numpy(), direct, rtol=1e-6)


class TestToStatic:
    def test_layer_parity(self):
        m = _mlp()
        x = t(np.random.RandomState(0).randn(4, 8))
        eager = m(x).numpy()
        sm = pt.jit.to_static(m)
        np.testing.assert_allclose(sm(x).numpy(), eager, rtol=1e-5,
                                   atol=1e-6)

    def test_sees_param_updates_without_retrace(self):
        m = _mlp()
        x = t(np.random.RandomState(0).randn(4, 8))
        sm = pt.jit.to_static(m)
        out1 = sm(x).numpy()
        n_compiled = len(sm.code_cache)
        m[0].weight.set_value(m[0].weight.numpy() * 2.0)
        out2 = sm(x).numpy()
        assert not np.allclose(out1, out2)
        assert len(sm.code_cache) == n_compiled  # no retrace

    def test_cache_per_shape(self):
        m = _mlp()
        sm = pt.jit.to_static(m)
        sm(t(np.zeros((2, 8))))
        sm(t(np.zeros((2, 8))))
        assert len(sm.code_cache) == 1
        sm(t(np.zeros((5, 8))))
        assert len(sm.code_cache) == 2

    def test_plain_function(self):
        @pt.jit.to_static
        def f(a, b):
            return pt.matmul(a, b) + 1.0
        a = t(np.random.RandomState(0).randn(3, 4))
        b = t(np.random.RandomState(1).randn(4, 2))
        np.testing.assert_allclose(
            f(a, b).numpy(), a.numpy() @ b.numpy() + 1.0, rtol=1e-5)

    def test_batchnorm_buffers_update_under_jit(self):
        m = nn.Sequential(nn.Linear(4, 6), nn.BatchNorm1D(6))
        m.train()
        sm = pt.jit.to_static(m)
        before = m[1]._mean.numpy().copy()
        sm(t(np.random.RandomState(0).randn(16, 4) * 3 + 2))
        after = m[1]._mean.numpy()
        assert not np.allclose(before, after)
        assert np.isfinite(after).all()

    def test_dropout_varies_across_calls(self):
        m = nn.Dropout(0.5)
        m.train()
        sm = pt.jit.to_static(m)
        x = t(np.ones((32, 32)))
        y1 = sm(x).numpy()
        y2 = sm(x).numpy()
        assert (y1 == 0).any()
        assert not np.array_equal(y1, y2)  # rng threads through, not baked


class TestTrainStep:
    def _data(self):
        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype(np.float32)
        W = rng.randn(8, 4).astype(np.float32)
        y = X @ W
        return X, y

    def test_matches_eager_training(self):
        X, y = self._data()
        loss_layer = nn.MSELoss()

        def loss_fn(model, xb, yb):
            return loss_layer(model(xb), yb)

        # eager run
        m1 = _mlp(seed=7)
        o1 = opt.AdamW(learning_rate=0.01, parameters=m1.parameters(),
                       grad_clip=nn.ClipGradByGlobalNorm(1.0))
        eager_losses = []
        for _ in range(10):
            loss = loss_fn(m1, t(X), t(y))
            loss.backward()
            o1.step()
            o1.clear_grad()
            eager_losses.append(float(loss.numpy()))

        # compiled run (identical init via same seed)
        m2 = _mlp(seed=7)
        o2 = opt.AdamW(learning_rate=0.01, parameters=m2.parameters(),
                       grad_clip=nn.ClipGradByGlobalNorm(1.0))
        step = pt.jit.TrainStep(m2, loss_fn, o2)
        jit_losses = [float(step(t(X), t(y)).numpy()) for _ in range(10)]

        np.testing.assert_allclose(jit_losses, eager_losses, rtol=1e-4,
                                   atol=1e-6)
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-4,
                                       atol=1e-5)

    def test_single_compile_across_steps(self):
        X, y = self._data()
        m = _mlp()
        o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
        step = pt.jit.TrainStep(m, lambda mm, a, b: nn.MSELoss()(mm(a), b), o)
        for _ in range(5):
            step(t(X), t(y))
        assert len(step._cache) == 1

    def test_scheduler_lr_no_retrace(self):
        X, y = self._data()
        m = _mlp()
        sched = opt.lr.StepDecay(0.1, step_size=1, gamma=0.5)
        o = opt.SGD(learning_rate=sched, parameters=m.parameters())
        step = pt.jit.TrainStep(m, lambda mm, a, b: nn.MSELoss()(mm(a), b), o)
        for _ in range(3):
            step(t(X), t(y))
            sched.step()
        assert len(step._cache) == 1

    def test_momentum_state_advances(self):
        X, y = self._data()
        m = _mlp()
        o = opt.Momentum(learning_rate=0.01, momentum=0.9,
                         parameters=m.parameters())
        step = pt.jit.TrainStep(m, lambda mm, a, b: nn.MSELoss()(mm(a), b), o)
        step(t(X), t(y))
        p0 = m[0].weight
        # _sync_state flushes the fused path's flat accumulators into the
        # per-parameter layout (state_dict() does this implicitly)
        o._sync_state()
        v1 = np.asarray(o._state[id(p0)]["velocity"]).copy()
        step(t(X), t(y))
        o._sync_state()
        v2 = np.asarray(o._state[id(p0)]["velocity"])
        assert not np.allclose(v1, v2)

    def test_excluded_params_stay_frozen(self):
        # freeze-by-exclusion: only the head is given to the optimizer
        X, y = self._data()
        m = _mlp()
        head_params = [m[2].weight, m[2].bias]
        o = opt.SGD(learning_rate=0.1, parameters=head_params)
        step = pt.jit.TrainStep(m, lambda mm, a, b: nn.MSELoss()(mm(a), b), o)
        backbone_before = m[0].weight.numpy().copy()
        head_before = m[2].weight.numpy().copy()
        step(t(X), t(y))
        np.testing.assert_allclose(m[0].weight.numpy(), backbone_before)
        assert not np.allclose(m[2].weight.numpy(), head_before)

    def test_group_lr_scheduler_threads(self):
        X, y = self._data()
        m = _mlp()
        sched = opt.lr.StepDecay(0.5, step_size=1, gamma=0.1)
        o = opt.SGD(learning_rate=0.1, parameters=[
            {"params": [m[0].weight, m[0].bias]},
            {"params": [m[2].weight, m[2].bias], "learning_rate": sched},
        ])
        step = pt.jit.TrainStep(m, lambda mm, a, b: nn.MSELoss()(mm(a), b), o)
        w0 = m[2].weight.numpy().copy()
        step(t(X), t(y))
        w1 = m[2].weight.numpy().copy()
        d1 = np.abs(w1 - w0).max()
        sched.step()  # group lr drops 10x; no retrace, new value threads in
        step(t(X), t(y))
        d2 = np.abs(m[2].weight.numpy() - w1).max()
        assert len(step._cache) == 1
        # the second update must be much smaller — proves the scheduler value
        # threads into the compiled step instead of being baked at trace time
        assert d2 < d1 * 0.3, (d1, d2)

    def test_unfreeze_after_construction(self):
        X, y = self._data()
        m = _mlp()
        m[0].weight.stop_gradient = True
        o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
        step = pt.jit.TrainStep(m, lambda mm, a, b: nn.MSELoss()(mm(a), b), o)
        step(t(X), t(y))
        frozen_w = m[0].weight.numpy().copy()
        m[0].weight.stop_gradient = False  # progressive unfreeze
        step(t(X), t(y))
        assert not np.allclose(m[0].weight.numpy(), frozen_w)

    def test_swap_state_typo_does_not_corrupt(self):
        m = _mlp()
        before = m[0].weight.numpy().copy()
        with pytest.raises(KeyError):
            pt.jit.functional_call(
                m, {"0.weight": np.zeros((8, 32), np.float32),
                    "bogus": np.zeros(3, np.float32)},
                t(np.zeros((2, 8))))
        np.testing.assert_allclose(m[0].weight.numpy(), before)

    def test_compiled_beats_eager(self):
        # soft speedup floor for CI stability; the >=10x claim is checked in
        # the verify drive on a bigger model
        X, y = self._data()
        loss_fn = lambda mm, a, b: nn.MSELoss()(mm(a), b)
        m1 = _mlp(seed=3)
        o1 = opt.AdamW(learning_rate=1e-3, parameters=m1.parameters())
        t0 = time.perf_counter()
        for _ in range(30):
            loss = loss_fn(m1, t(X), t(y))
            loss.backward()
            o1.step()
            o1.clear_grad()
        eager_t = time.perf_counter() - t0

        m2 = _mlp(seed=3)
        o2 = opt.AdamW(learning_rate=1e-3, parameters=m2.parameters())
        step = pt.jit.TrainStep(m2, loss_fn, o2)
        step(t(X), t(y))  # compile outside the timed region
        t0 = time.perf_counter()
        for _ in range(30):
            step(t(X), t(y))
        jit_t = time.perf_counter() - t0
        assert jit_t < eager_t, (jit_t, eager_t)
