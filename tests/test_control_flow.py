"""static.nn control flow: cond / while_loop / case / switch_case lowering
to lax.cond / lax.while_loop, eager + compiled capture parity, and the
actionable trace-time error for Python `if tensor:` (reference:
python/paddle/static/nn/control_flow.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.static import nn as snn


def t(x, sg=True):
    return pt.to_tensor(np.asarray(x, np.float32), stop_gradient=sg)


class TestCond:
    def test_eager_concrete_pred_runs_taken_branch(self):
        x = t([1.0, 2.0])
        out = snn.cond(t(np.float32(1.0)) > 0,
                       lambda: x * 2, lambda: x * 3)
        np.testing.assert_allclose(out.numpy(), [2.0, 4.0])
        out = snn.cond(t(np.float32(-1.0)) > 0,
                       lambda: x * 2, lambda: x * 3)
        np.testing.assert_allclose(out.numpy(), [3.0, 6.0])

    def test_operands_style_tapes_and_differentiates(self):
        x = t([1.0, 2.0], sg=False)
        pred = t(np.float32(1.0)) > 0
        out = snn.cond(pred, lambda a: a * 2, lambda a: a * 3,
                       operands=(x,))
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])
        x.clear_grad()
        out = snn.cond(t(np.float32(-1.0)) > 0, lambda a: a * 2,
                       lambda a: a * 3, operands=(x,))
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])

    def test_compiled_data_dependent_branch(self):
        @pt.jit.to_static
        def f(x):
            return snn.cond(x.sum() > 0, lambda a: a * 2,
                            lambda a: a - 1, operands=(x,))

        np.testing.assert_allclose(f(t([1.0, 2.0])).numpy(), [2.0, 4.0])
        # SAME compiled program, other branch — data-dependent at runtime
        np.testing.assert_allclose(f(t([-1.0, -2.0])).numpy(),
                                   [-2.0, -3.0])

    def test_closure_style_under_trace(self):
        @pt.jit.to_static
        def f(x):
            return snn.cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)

        np.testing.assert_allclose(f(t([2.0])).numpy(), [4.0])
        np.testing.assert_allclose(f(t([-2.0])).numpy(), [-3.0])


class TestWhileLoop:
    def test_newton_sqrt_eager(self):
        # loop-until-converged: Newton iteration for sqrt(2)
        def cond_fn(i, y):
            err = pt.ops.abs(y * y - 2.0)
            return pt.ops.logical_and(err > 1e-6, i < 50)

        def body_fn(i, y):
            return i + 1, (y + 2.0 / y) / 2.0

        i0 = pt.to_tensor(np.int32(0))
        y0 = t(np.float32(1.0))
        i, y = snn.while_loop(cond_fn, body_fn, [i0, y0])
        assert abs(float(y.numpy()) - np.sqrt(2.0)) < 1e-5
        assert int(i.numpy()) < 50

    def test_newton_sqrt_compiled_matches_eager(self):
        def run(v):
            def cond_fn(y):
                return pt.ops.abs(y * y - v) > 1e-6

            def body_fn(y):
                return (y + v / y) / 2.0
            return snn.while_loop(cond_fn, body_fn, [t(np.float32(1.0))])[0]

        eager = float(run(3.0).numpy())

        @pt.jit.to_static
        def compiled(x):
            def cond_fn(y):
                return pt.ops.abs(y * y - x) > 1e-6

            def body_fn(y):
                return (y + x / y) / 2.0
            return snn.while_loop(cond_fn, body_fn,
                                  [pt.ops.ones_like(x)])[0]

        got = float(compiled(t(np.float32(3.0))).numpy())
        np.testing.assert_allclose(got, eager, rtol=1e-6)
        np.testing.assert_allclose(got, np.sqrt(3.0), rtol=1e-5)

    def test_requires_grad_raises(self):
        y0 = t(np.float32(1.0), sg=False)
        with pytest.raises(ValueError, match="forward-only"):
            snn.while_loop(lambda y: y < 10, lambda y: y * 2, [y0])

    def test_loop_until_converged_model_compiles(self):
        """VERDICT item 6 'done' bar: a model with a data-dependent inner
        loop compiles under to_static and matches eager."""
        pt.seed(0)

        class IterNorm(nn.Layer):
            """Normalizes by iterating x /= 2 until max|x| <= 1."""

            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)

                def cond_fn(v):
                    return pt.ops.max(pt.ops.abs(v)) > 1.0

                def body_fn(v):
                    return v / 2.0
                h = snn.while_loop(cond_fn, body_fn, [h.detach()])[0]
                return h

        m = IterNorm()
        m.eval()
        x = t(np.array([[8.0, 1.0, -16.0, 0.5]] * 2))
        eager = m(x).numpy()
        compiled = pt.jit.to_static(m)(x).numpy()
        np.testing.assert_allclose(compiled, eager, rtol=1e-6)
        assert np.abs(eager).max() <= 1.0


class TestCaseSwitch:
    def test_case_first_true_wins(self):
        x = t([1.0])
        out = snn.case([(t(np.float32(0.0)) > 0, lambda: x * 10),
                        (t(np.float32(1.0)) > 0, lambda: x * 20)],
                       default=lambda: x * 30)
        np.testing.assert_allclose(out.numpy(), [20.0])

    def test_switch_case(self):
        x = t([1.0])
        idx = pt.to_tensor(np.int32(2))
        out = snn.switch_case(idx, {1: lambda: x * 10, 2: lambda: x * 20},
                              default=lambda: x * 30)
        np.testing.assert_allclose(out.numpy(), [20.0])


class TestActionableTraceError:
    def test_python_if_on_tensor_names_cond(self):
        @pt.jit.to_static
        def f(x):
            if x.sum() > 0:  # data-dependent Python branch: uncapturable
                return x * 2
            return x * 3

        with pytest.raises(TypeError, match="static.nn.cond"):
            f(t([1.0, 2.0]))

    def test_eager_bool_still_works(self):
        assert bool(t(np.float32(1.0)) > 0)


class TestReviewRegressions:
    def test_false_fn_none_is_noop(self):
        x = t([1.0, 2.0])
        ran = []
        out = snn.cond(t(np.float32(-1.0)) > 0,
                       lambda: ran.append(1) or x * 2)
        assert out is None and not ran  # False + no false_fn: nothing runs

    def test_traced_cond_requires_both_branches(self):
        with pytest.raises(ValueError, match="BOTH branches"):
            snn.cond(t(np.float32(1.0)) > 0, lambda a: a,
                     operands=(t([1.0]),))

    def test_dict_branch_outputs(self):
        x = t([1.0, 2.0], sg=False)
        out = snn.cond(t(np.float32(1.0)) > 0,
                       lambda a: {"y": a * 2, "z": (a + 1, a - 1)},
                       lambda a: {"y": a * 3, "z": (a, a)},
                       operands=(x,))
        np.testing.assert_allclose(out["y"].numpy(), [2.0, 4.0])
        np.testing.assert_allclose(out["z"][0].numpy(), [2.0, 3.0])
        out["y"].sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_while_loop_with_check_nan_inf_flag(self):
        from paddle_tpu.core import flags
        old = flags.flag("check_nan_inf")
        flags.set_flags({"FLAGS_check_nan_inf": True})
        try:
            y, = snn.while_loop(lambda y: y < 10.0,
                                lambda y: y * 2.0,
                                [t(np.float32(1.0))])
            assert float(y.numpy()) == 16.0
        finally:
            flags.set_flags({"FLAGS_check_nan_inf": old})

    def test_mismatched_branch_structures_raise(self):
        x = t([1.0], sg=False)
        with pytest.raises(ValueError, match="different structures"):
            snn.cond(t(np.float32(1.0)) > 0, lambda a: {"x": a * 2},
                     lambda a: {"y": a * 3}, operands=(x,))

    def test_while_loop_closure_captured_layer_raises(self):
        fc = nn.Linear(2, 2)  # trainable params captured by body closure
        y0 = t([1.0, 1.0])  # loop var itself detached
        with pytest.raises(ValueError, match="forward-only"):
            snn.while_loop(lambda y: y.sum() < 10, lambda y: fc(y), [y0])
