"""static.nn control flow: cond / while_loop / case / switch_case lowering
to lax.cond / lax.while_loop, eager + compiled capture parity, and the
actionable trace-time error for Python `if tensor:` (reference:
python/paddle/static/nn/control_flow.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.static import nn as snn


def t(x, sg=True):
    return pt.to_tensor(np.asarray(x, np.float32), stop_gradient=sg)


class TestCond:
    def test_eager_concrete_pred_runs_taken_branch(self):
        x = t([1.0, 2.0])
        out = snn.cond(t(np.float32(1.0)) > 0,
                       lambda: x * 2, lambda: x * 3)
        np.testing.assert_allclose(out.numpy(), [2.0, 4.0])
        out = snn.cond(t(np.float32(-1.0)) > 0,
                       lambda: x * 2, lambda: x * 3)
        np.testing.assert_allclose(out.numpy(), [3.0, 6.0])

    def test_operands_style_tapes_and_differentiates(self):
        x = t([1.0, 2.0], sg=False)
        pred = t(np.float32(1.0)) > 0
        out = snn.cond(pred, lambda a: a * 2, lambda a: a * 3,
                       operands=(x,))
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])
        x.clear_grad()
        out = snn.cond(t(np.float32(-1.0)) > 0, lambda a: a * 2,
                       lambda a: a * 3, operands=(x,))
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])

    def test_compiled_data_dependent_branch(self):
        @pt.jit.to_static
        def f(x):
            return snn.cond(x.sum() > 0, lambda a: a * 2,
                            lambda a: a - 1, operands=(x,))

        np.testing.assert_allclose(f(t([1.0, 2.0])).numpy(), [2.0, 4.0])
        # SAME compiled program, other branch — data-dependent at runtime
        np.testing.assert_allclose(f(t([-1.0, -2.0])).numpy(),
                                   [-2.0, -3.0])

    def test_closure_style_under_trace(self):
        @pt.jit.to_static
        def f(x):
            return snn.cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)

        np.testing.assert_allclose(f(t([2.0])).numpy(), [4.0])
        np.testing.assert_allclose(f(t([-2.0])).numpy(), [-3.0])


class TestWhileLoop:
    def test_newton_sqrt_eager(self):
        # loop-until-converged: Newton iteration for sqrt(2)
        def cond_fn(i, y):
            err = pt.ops.abs(y * y - 2.0)
            return pt.ops.logical_and(err > 1e-6, i < 50)

        def body_fn(i, y):
            return i + 1, (y + 2.0 / y) / 2.0

        i0 = pt.to_tensor(np.int32(0))
        y0 = t(np.float32(1.0))
        i, y = snn.while_loop(cond_fn, body_fn, [i0, y0])
        assert abs(float(y.numpy()) - np.sqrt(2.0)) < 1e-5
        assert int(i.numpy()) < 50

    def test_newton_sqrt_compiled_matches_eager(self):
        def run(v):
            def cond_fn(y):
                return pt.ops.abs(y * y - v) > 1e-6

            def body_fn(y):
                return (y + v / y) / 2.0
            return snn.while_loop(cond_fn, body_fn, [t(np.float32(1.0))])[0]

        eager = float(run(3.0).numpy())

        @pt.jit.to_static
        def compiled(x):
            def cond_fn(y):
                return pt.ops.abs(y * y - x) > 1e-6

            def body_fn(y):
                return (y + x / y) / 2.0
            return snn.while_loop(cond_fn, body_fn,
                                  [pt.ops.ones_like(x)])[0]

        got = float(compiled(t(np.float32(3.0))).numpy())
        np.testing.assert_allclose(got, eager, rtol=1e-6)
        np.testing.assert_allclose(got, np.sqrt(3.0), rtol=1e-5)

    def test_requires_grad_raises(self):
        y0 = t(np.float32(1.0), sg=False)
        with pytest.raises(ValueError, match="forward-only"):
            snn.while_loop(lambda y: y < 10, lambda y: y * 2, [y0])

    def test_loop_until_converged_model_compiles(self):
        """VERDICT item 6 'done' bar: a model with a data-dependent inner
        loop compiles under to_static and matches eager."""
        pt.seed(0)

        class IterNorm(nn.Layer):
            """Normalizes by iterating x /= 2 until max|x| <= 1."""

            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)

                def cond_fn(v):
                    return pt.ops.max(pt.ops.abs(v)) > 1.0

                def body_fn(v):
                    return v / 2.0
                h = snn.while_loop(cond_fn, body_fn, [h.detach()])[0]
                return h

        m = IterNorm()
        m.eval()
        x = t(np.array([[8.0, 1.0, -16.0, 0.5]] * 2))
        eager = m(x).numpy()
        compiled = pt.jit.to_static(m)(x).numpy()
        np.testing.assert_allclose(compiled, eager, rtol=1e-6)
        assert np.abs(eager).max() <= 1.0


class TestCaseSwitch:
    def test_case_first_true_wins(self):
        x = t([1.0])
        out = snn.case([(t(np.float32(0.0)) > 0, lambda: x * 10),
                        (t(np.float32(1.0)) > 0, lambda: x * 20)],
                       default=lambda: x * 30)
        np.testing.assert_allclose(out.numpy(), [20.0])

    def test_switch_case(self):
        x = t([1.0])
        idx = pt.to_tensor(np.int32(2))
        out = snn.switch_case(idx, {1: lambda: x * 10, 2: lambda: x * 20},
                              default=lambda: x * 30)
        np.testing.assert_allclose(out.numpy(), [20.0])


class TestActionableTraceError:
    def test_python_if_on_tensor_names_cond(self):
        @pt.jit.to_static
        def f(x):
            if x.sum() > 0:  # data-dependent Python branch: uncapturable
                return x * 2
            return x * 3

        with pytest.raises(TypeError, match="static.nn.cond"):
            f(t([1.0, 2.0]))

    def test_eager_bool_still_works(self):
        assert bool(t(np.float32(1.0)) > 0)


class TestReviewRegressions:
    def test_false_fn_none_is_noop(self):
        x = t([1.0, 2.0])
        ran = []
        out = snn.cond(t(np.float32(-1.0)) > 0,
                       lambda: ran.append(1) or x * 2)
        assert out is None and not ran  # False + no false_fn: nothing runs

    def test_traced_cond_requires_both_branches(self):
        with pytest.raises(ValueError, match="BOTH branches"):
            snn.cond(t(np.float32(1.0)) > 0, lambda a: a,
                     operands=(t([1.0]),))

    def test_dict_branch_outputs(self):
        x = t([1.0, 2.0], sg=False)
        out = snn.cond(t(np.float32(1.0)) > 0,
                       lambda a: {"y": a * 2, "z": (a + 1, a - 1)},
                       lambda a: {"y": a * 3, "z": (a, a)},
                       operands=(x,))
        np.testing.assert_allclose(out["y"].numpy(), [2.0, 4.0])
        np.testing.assert_allclose(out["z"][0].numpy(), [2.0, 3.0])
        out["y"].sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_while_loop_with_check_nan_inf_flag(self):
        from paddle_tpu.core import flags
        old = flags.flag("check_nan_inf")
        flags.set_flags({"FLAGS_check_nan_inf": True})
        try:
            y, = snn.while_loop(lambda y: y < 10.0,
                                lambda y: y * 2.0,
                                [t(np.float32(1.0))])
            assert float(y.numpy()) == 16.0
        finally:
            flags.set_flags({"FLAGS_check_nan_inf": old})

    def test_mismatched_branch_structures_raise(self):
        x = t([1.0], sg=False)
        with pytest.raises(ValueError, match="different structures"):
            snn.cond(t(np.float32(1.0)) > 0, lambda a: {"x": a * 2},
                     lambda a: {"y": a * 3}, operands=(x,))

    def test_while_loop_closure_captured_layer_raises(self):
        fc = nn.Linear(2, 2)  # trainable params captured by body closure
        y0 = t([1.0, 1.0])  # loop var itself detached
        with pytest.raises(ValueError, match="forward-only"):
            snn.while_loop(lambda y: y.sum() < 10, lambda y: fc(y), [y0])


class TestBoundedWhileLoop:
    """static.nn.bounded_while_loop: the DIFFERENTIABLE bounded loop
    (reference capability: while_op.cc:349 WhileGradOp — paddle trains
    through while loops; here a masked lax.scan reverses exactly)."""

    def test_newton_sqrt_grads_match_eager_oracle(self):
        import paddle_tpu as pt
        from paddle_tpu import static

        def run(use_bounded):
            a = pt.to_tensor(np.float32(2.0), stop_gradient=False)
            x = pt.to_tensor(np.float32(1.5), stop_gradient=False)

            def cond_fn(xv):
                return pt.abs(xv * xv - a) > 1e-4

            def body_fn(xv):
                return xv - (xv * xv - a) / (2.0 * xv)

            if use_bounded:
                (out,) = static.nn.bounded_while_loop(
                    cond_fn, body_fn, [x], max_iters=25)
            else:
                out = x
                while bool(cond_fn(out).numpy()):
                    out = body_fn(out)
            out.backward()
            return float(out.numpy()), float(a.grad.numpy()), \
                float(x.grad.numpy())

        got_val, got_ga, got_gx = run(True)
        ref_val, ref_ga, ref_gx = run(False)
        np.testing.assert_allclose(got_val, ref_val, rtol=1e-6)
        np.testing.assert_allclose(got_ga, ref_ga, rtol=1e-5)
        # d sqrt(a)/da = 1/(2 sqrt(a))
        np.testing.assert_allclose(got_ga, 1 / (2 * np.sqrt(2.0)),
                                   rtol=1e-3)
        np.testing.assert_allclose(got_gx, ref_gx, atol=1e-6)

    def test_loop_until_converged_model_trains(self):
        """A fixed-point ('deep equilibrium'-style) block: iterate h until
        the update is small, train the captured Layer through the loop —
        the model the forward-only while_loop rejects."""
        import paddle_tpu as pt
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        from paddle_tpu import static

        rng = np.random.RandomState(0)
        pt.seed(0)
        lin = nn.Linear(4, 4)
        X = pt.to_tensor(rng.randn(8, 4).astype(np.float32))
        Y = pt.to_tensor((rng.randn(8, 4) * 0.3).astype(np.float32))
        o = opt.Adam(learning_rate=3e-2, parameters=lin.parameters())

        def fixed_point(x):
            h0 = pt.zeros_like(x)
            d0 = pt.to_tensor(np.float32(1.0))

            def cond_fn(h, d):
                return d > 1e-3

            def body_fn(h, d):
                h2 = 0.5 * h + 0.5 * pt.tanh(lin(h) + x)
                return [h2, pt.max(pt.abs(h2 - h))]

            h, _ = static.nn.bounded_while_loop(cond_fn, body_fn,
                                                [h0, d0], max_iters=40)
            return h

        losses = []
        for _ in range(25):
            loss = nn.MSELoss()(fixed_point(X), Y)
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.5, losses[::8]

    def test_grads_match_eager_loop_through_layer(self):
        """Parameter gradients through the bounded loop == eager Python
        while loop (same trip count, masked iterations are exact
        identity)."""
        import paddle_tpu as pt
        import paddle_tpu.nn as nn
        from paddle_tpu import static

        rng = np.random.RandomState(1)
        pt.seed(3)
        lin = nn.Linear(4, 4)
        X = pt.to_tensor(rng.randn(2, 4).astype(np.float32))

        def cond_fn(h, d):
            return d > 1e-3

        def body(h, x):
            return 0.5 * h + 0.5 * pt.tanh(lin(h) + x)

        h0 = pt.zeros_like(X)
        d0 = pt.to_tensor(np.float32(1.0))
        h, _ = static.nn.bounded_while_loop(
            cond_fn, lambda h, d: [body(h, X),
                                   pt.max(pt.abs(body(h, X) - h))],
            [h0, d0], max_iters=50)
        h.mean().backward()
        got = lin.weight.grad.numpy().copy()
        for p in lin.parameters():
            p.grad = None

        h = pt.zeros_like(X)
        d = 1.0
        while d > 1e-3:
            h2 = body(h, X)
            d = float(pt.max(pt.abs(h2 - h)).numpy())
            h = h2
        h.mean().backward()
        ref = lin.weight.grad.numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-6)

    def test_truncates_at_max_iters(self):
        import paddle_tpu as pt
        from paddle_tpu import static

        i0 = pt.to_tensor(np.float32(0.0))
        (out,) = static.nn.bounded_while_loop(
            lambda i: i < 1e9, lambda i: i + 1.0, [i0], max_iters=7)
        assert float(out.numpy()) == 7.0

    def test_zero_iters_passthrough(self):
        import paddle_tpu as pt
        from paddle_tpu import static

        x = pt.to_tensor(np.float32(3.0))
        outs = static.nn.bounded_while_loop(
            lambda v: v > 0, lambda v: v - 1, [x], max_iters=0)
        assert float(outs[0].numpy()) == 3.0


class TestFlatSwitch:
    def test_switch_case_single_flat_switch_in_jaxpr(self):
        """A 10-branch switch compiles ONE lax.switch (cond primitive with
        11 branches), not a 10-deep nested cond chain."""
        import jax
        import jax.numpy as jnp
        import paddle_tpu as pt
        from paddle_tpu import static

        fns = {i: (lambda i=i: pt.to_tensor(np.float32(i)) * 2.0)
               for i in range(10)}

        def fn(idx):
            return static.nn.switch_case(pt.Tensor(idx), fns).data

        jaxpr = jax.make_jaxpr(fn)(jnp.asarray(3, jnp.int32))
        conds = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "cond"]
        assert len(conds) == 1, jaxpr
        # no explicit default: the max-key branch doubles as fallback
        # WITHOUT being traced twice — exactly 10 branches
        assert len(conds[0].params["branches"]) == 10
        # and it dispatches correctly
        assert float(fn(jnp.asarray(4, jnp.int32))) == 8.0
        # unmatched index, no default: max-key branch (reference契约)
        assert float(fn(jnp.asarray(99, jnp.int32))) == 18.0

    def test_case_first_true_wins_traced(self):
        import jax
        import jax.numpy as jnp
        import paddle_tpu as pt
        from paddle_tpu import static

        def fn(x):
            xt = pt.Tensor(x)
            return static.nn.case([
                (xt > 2.0, lambda: pt.to_tensor(np.float32(10.0))),
                (xt > 1.0, lambda: pt.to_tensor(np.float32(20.0))),
            ], default=lambda: pt.to_tensor(np.float32(30.0))).data

        assert float(fn(jnp.asarray(5.0))) == 10.0
        assert float(fn(jnp.asarray(1.5))) == 20.0
        assert float(fn(jnp.asarray(0.5))) == 30.0

    def test_switch_case_default_called_for_unmatched(self):
        import paddle_tpu as pt
        from paddle_tpu import static

        out = static.nn.switch_case(
            pt.to_tensor(np.int32(7)),
            {1: lambda: pt.to_tensor(np.float32(1.0)),
             2: lambda: pt.to_tensor(np.float32(2.0))},
            default=lambda: pt.to_tensor(np.float32(-1.0)))
        assert float(out.numpy()) == -1.0


class TestClosureCollection:
    def test_layers_in_container_receive_grads(self):
        """Layers captured inside a plain Python list must be collected
        and differentiated (review regression)."""
        import paddle_tpu as pt
        import paddle_tpu.nn as nn
        from paddle_tpu import static

        pt.seed(5)
        blocks = [nn.Linear(4, 4), nn.Linear(4, 4)]
        x = pt.to_tensor(np.random.RandomState(0).randn(2, 4)
                         .astype(np.float32))
        h0 = pt.zeros_like(x)
        d0 = pt.to_tensor(np.float32(1.0))

        def body_fn(h, d):
            h2 = 0.5 * h + 0.5 * pt.tanh(blocks[1](blocks[0](h)) + x)
            return [h2, pt.max(pt.abs(h2 - h))]

        h, _ = static.nn.bounded_while_loop(
            lambda h, d: d > 1e-3, body_fn, [h0, d0], max_iters=40)
        h.mean().backward()
        for b in blocks:
            assert b.weight.grad is not None
            assert np.abs(b.weight.grad.numpy()).max() > 0

    def test_while_loop_guard_sees_helper_indirection(self):
        """The forward-only guard must catch a trainable layer reached
        only through a helper lambda (review regression)."""
        import paddle_tpu as pt
        import paddle_tpu.nn as nn
        from paddle_tpu import static

        lin = nn.Linear(2, 2)
        step = lambda h: lin(h)  # noqa: E731
        h0 = pt.to_tensor(np.zeros((1, 2), np.float32))
        with pytest.raises(ValueError, match="forward-only"):
            static.nn.while_loop(
                lambda h: pt.max(pt.abs(h)) < 10.0,
                lambda h: step(h), [h0])

    def test_body_arity_mismatch_raises(self):
        import paddle_tpu as pt
        from paddle_tpu import static

        h0 = pt.to_tensor(np.float32(0.0))
        with pytest.raises(ValueError, match="loop vars"):
            static.nn.bounded_while_loop(
                lambda h: h < 5, lambda h: [h + 1, h], [h0], max_iters=3)
