"""Multi-tenant LoRA (ISSUE 20): train small, serve thousands.

Coverage contract: training-mode adapters actually train (loss falls,
base frozen, adapter state a sliver of the model), the KB-scale
adapter checkpoint roundtrips, a trained adapter served from an
engine slot greedy-matches the eager base+adapter model, and the
acceptance run — 8 tenants decoding concurrently from ONE quantized
base engine, each greedy-identical to a dedicated engine serving only
that tenant, with the unified step compiled exactly once through
every adapter load and tenant mix.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import tuning
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import ServingEngine


def _tiny(seed=0):
    pt.seed(seed)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=True))
    m.eval()
    return m


def _eager_continuation(model, prompt, max_new_tokens):
    out = model.generate(pt.to_tensor(np.asarray(prompt)[None, :]),
                         max_new_tokens=max_new_tokens,
                         temperature=0.0).numpy()[0]
    return [int(t) for t in out[len(prompt):]]


def _model_bytes(model):
    return sum(np.asarray(v.numpy()).nbytes
               for v in model.state_dict().values())


# ---------------- training mode ----------------------------------------------

def test_lora_trains_base_frozen(tmp_path):
    model = _tiny(0)
    base_before = {k: np.asarray(v.numpy()).copy()
                   for k, v in model.state_dict().items()}
    tuning.apply_lora(model, tuning.LoRAConfig(rank=4, alpha=8.0))
    # adapters are a sliver of the model
    assert tuning.lora_param_bytes(model) < 0.1 * _model_bytes(model)

    trainable = [p for p in model.parameters() if not p.stop_gradient]
    assert len(trainable) == 2 * 2 * 7  # (A, B) x layers x targets

    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randint(1, 128, (4, 16)))
    y = pt.to_tensor(rng.randint(1, 128, (4, 16)))
    opt = pt.optimizer.Adam(learning_rate=5e-3,
                            parameters=model.parameters())
    losses = []
    for _ in range(6):
        logits = model(x)
        loss = pt.nn.functional.cross_entropy(
            logits.reshape([-1, 128]), y.reshape([-1]))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0], losses

    # the frozen base is bit-identical; the adapters moved
    after = {k: np.asarray(v.numpy()) for k, v in
             model.state_dict().items()}
    for k, v in base_before.items():
        np.testing.assert_array_equal(after[k], v, err_msg=k)
    lora = tuning.lora_state_dict(model)
    assert lora and any(np.abs(v).max() > 0 for k, v in lora.items()
                        if k.endswith("lora_B"))  # B left its zero init

    # KB-scale checkpoint roundtrip
    path = tuning.save_adapter(model, str(tmp_path / "adapter"))
    back = tuning.load_adapter_state(path)
    assert set(back) == set(lora)
    for k in lora:
        np.testing.assert_allclose(np.asarray(back[k]), lora[k],
                                   rtol=0, atol=0, err_msg=k)


def test_trained_adapter_serves_from_slot(tmp_path):
    """fit -> save_adapter -> load_adapter -> submit(adapter_id=):
    the served tenant greedy-matches the eager base+adapter model, and
    adapter_id=0 still serves the pristine base."""
    trained = _tiny(7)
    tuning.apply_lora(trained, tuning.LoRAConfig(rank=4, alpha=16.0))
    rng = np.random.RandomState(1)
    x = pt.to_tensor(rng.randint(1, 128, (4, 16)))
    y = pt.to_tensor(rng.randint(1, 128, (4, 16)))
    opt = pt.optimizer.Adam(learning_rate=2e-2,
                            parameters=trained.parameters())
    for _ in range(8):
        logits = trained(x)
        loss = pt.nn.functional.cross_entropy(
            logits.reshape([-1, 128]), y.reshape([-1]))
        loss.backward()
        opt.step()
        opt.clear_grad()
    path = tuning.save_adapter(trained, str(tmp_path / "tenant-a"))

    base = _tiny(7)  # same seed: identical frozen base
    prompt = list(rng.randint(1, 128, 10))
    base_oracle = _eager_continuation(base, prompt, 6)
    tuned_oracle = _eager_continuation(trained, prompt, 6)

    tuning.apply_lora(base, tuning.LoRAConfig(rank=4, alpha=16.0),
                      n_slots=2)
    engine = ServingEngine(base, max_batch=4, max_blocks=32,
                           block_size=4, prefill_chunk=4)
    engine.start()
    engine.load_adapter(1, tuning.load_adapter_state(path),
                        name="tenant-a")
    got_base = engine.submit(prompt, max_new_tokens=6).result(
        timeout=60)["token_ids"]
    got_tuned = engine.submit(prompt, max_new_tokens=6,
                              adapter_id=1).result(timeout=60)["token_ids"]
    assert got_base == base_oracle
    assert got_tuned == tuned_oracle
    assert got_tuned != got_base  # the adapter is actually dispatched
    assert engine.step_traces == 1
    stats = engine.stats()["adapters"]
    assert stats["slots"] == 2 and stats["loaded"] == 1
    assert stats["occupancy"] == {"1": "tenant-a"}
    engine.shutdown()


# ---------------- the 8-tenant acceptance run --------------------------------

def _adapter_state(engine, seed, scale=0.5):
    """A synthetic tenant: random rows for every lora leaf of the
    engine's stacked state, shaped per load_adapter's contract."""
    rng = np.random.RandomState(seed)
    return {k: (rng.randn(*v.shape[1:]) * scale).astype(np.float32)
            for k, v in engine._st.items()
            if k.rsplit(".", 1)[-1].startswith("lora_")}


@pytest.mark.slow
def test_eight_tenants_one_quantized_engine():
    """≥8 adapters concurrently from ONE int8 base engine, each tenant
    greedy-identical to a dedicated engine serving it alone."""
    n_tenants = 8
    rng = np.random.RandomState(3)
    prompts = {s: list(rng.randint(1, 128, 8 + (s % 3)))
               for s in range(1, n_tenants + 1)}

    model = _tiny(9)
    tuning.apply_lora(model, tuning.LoRAConfig(rank=4), n_slots=n_tenants)
    multi = ServingEngine(model, max_batch=4, max_blocks=32,
                          block_size=4, prefill_chunk=4,
                          quantize="int8_wo")
    multi.start()
    for s in range(1, n_tenants + 1):
        multi.load_adapter(s, _adapter_state(multi, seed=100 + s),
                           name=f"tenant-{s}")
    assert multi.stats()["adapters"]["loaded"] == n_tenants

    handles = {s: multi.submit(prompts[s], max_new_tokens=6,
                               adapter_id=s)
               for s in range(1, n_tenants + 1)}
    multi.drain(timeout=120)
    served = {s: h.result(timeout=5)["token_ids"]
              for s, h in handles.items()}
    assert multi.step_traces == 1  # every tenant mix, one executable
    multi.shutdown()

    # dedicated oracles: same frozen base (same seed), same int8
    # quantization (deterministic), ONE tenant each
    for s in range(1, n_tenants + 1):
        solo_model = _tiny(9)
        tuning.apply_lora(solo_model, tuning.LoRAConfig(rank=4),
                          n_slots=1)
        solo = ServingEngine(solo_model, max_batch=2, max_blocks=16,
                             block_size=4, prefill_chunk=4,
                             quantize="int8_wo")
        solo.start()
        solo.load_adapter(1, _adapter_state(solo, seed=100 + s))
        got = solo.submit(prompts[s], max_new_tokens=6,
                          adapter_id=1).result(timeout=60)["token_ids"]
        solo.shutdown()
        assert got == served[s], f"tenant {s} diverged from its " \
                                 f"dedicated engine"

    # tenants are genuinely distinct programs, not one shared delta
    assert len({tuple(t) for t in served.values()}) > 1


def test_adapter_slot_hygiene():
    """Slot-occupancy edges: submit to an empty slot refuses, loads
    refuse bad keys/shapes, unload restores the base row."""
    model = _tiny(11)
    tuning.apply_lora(model, tuning.LoRAConfig(rank=4), n_slots=2)
    engine = ServingEngine(model, max_batch=2, max_blocks=16,
                           block_size=4, prefill_chunk=4)
    engine.start()
    prompt = [2, 4, 6, 8, 10]
    base_out = engine.submit(prompt, max_new_tokens=4).result(
        timeout=60)["token_ids"]

    with pytest.raises(ValueError):
        engine.submit(prompt, adapter_id=1)  # slot 1 empty
    with pytest.raises(ValueError):
        engine.submit(prompt, adapter_id=9)  # out of range
    with pytest.raises(KeyError):
        engine.load_adapter(1, {"nonsense.lora_A": np.zeros((4, 4))})

    state = _adapter_state(engine, seed=5)
    engine.load_adapter(1, state, name="t")
    tuned = engine.submit(prompt, max_new_tokens=4,
                          adapter_id=1).result(timeout=60)["token_ids"]
    assert tuned != base_out

    engine.unload_adapter(1)
    with pytest.raises(ValueError):
        engine.submit(prompt, adapter_id=1)  # empty again
    again = engine.submit(prompt, max_new_tokens=4).result(
        timeout=60)["token_ids"]
    assert again == base_out  # base row back to exactly zero delta
    assert engine.step_traces == 1
    engine.shutdown()
