"""Continuous-batching serving subsystem (paddle_tpu.serving).

Coverage contract (ISSUE 2, upgraded by ISSUE 8): block
alloc/free/refcount invariants (no leak after preemption), a short
request admitted while a long one is mid-decode with both matching
their sequential baselines, the HTTP ``/generate`` round trip, a
compile-exactly-once guard over the ONE unified token-packed step
executable, and unified-step scheduler invariants (decode-first
starvation-freedom, multi-chunk budget packing, stale-entry preemption
safety). The full ≥8-concurrent-request acceptance run is marked
``slow``; a single-request smoke stays in tier-1. RPA-vs-gather kernel
parity lives in ``test_ragged_paged_attention.py``.
"""
import json
import threading
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (BlockAllocator, Server, ServingEngine)
from paddle_tpu.serving.scheduler import RequestState


def _tiny(seed=0):
    pt.seed(seed)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=True))
    m.eval()
    return m


def _eager_continuation(model, prompt, max_new_tokens, eos_token_id=None):
    """Solo greedy baseline: the tokens after the prompt."""
    out = model.generate(pt.to_tensor(np.asarray(prompt)[None, :]),
                         max_new_tokens=max_new_tokens, temperature=0.0,
                         eos_token_id=eos_token_id).numpy()[0]
    return [int(t) for t in out[len(prompt):]]


@pytest.fixture(scope="module")
def served():
    """One model + engine shared by the tier-1 tests — engine reuse
    across tests doubles as an organic compile-once check."""
    model = _tiny(0)
    eng = ServingEngine(model, max_batch=4, max_blocks=32, block_size=4,
                        prefill_chunk=4)
    return model, eng


# ---------------- block allocator invariants ---------------------------------
def test_allocator_alloc_free_roundtrip():
    a = BlockAllocator(8)
    assert a.num_free() == 8 and a.capacity == 8
    blocks = a.allocate(5)
    assert len(set(blocks)) == 5 and 0 not in blocks  # null block reserved
    assert a.blocks_in_use() == 5 and a.num_free() == 3
    a.free(blocks)
    assert a.blocks_in_use() == 0 and a.num_free() == 8
    a.assert_no_leaks()


def test_allocator_exhaustion_and_double_free():
    a = BlockAllocator(2)
    blocks = a.allocate(2)
    with pytest.raises(MemoryError):
        a.allocate(1)
    a.free(blocks)
    with pytest.raises(ValueError, match="double free"):
        a.free([blocks[0]])


def test_allocator_refcount_shared_block():
    a = BlockAllocator(4)
    (b,) = a.allocate(1)
    a.incref(b)
    assert a.refcount(b) == 2
    a.free([b])                      # first holder drops it
    assert a.blocks_in_use() == 1    # still live: second holder
    a.free([b])
    assert a.blocks_in_use() == 0
    with pytest.raises(ValueError):
        a.incref(b)


# ---------------- paged attention numerics -----------------------------------
def test_paged_cache_matches_concat_cache():
    """Prefill + decode through PagedLayerCache must reproduce the
    legacy growing-concat path's hidden states."""
    import jax.numpy as jnp
    from paddle_tpu.ops.paged_attention import PagedLayerCache

    m = _tiny(4)
    rng = np.random.RandomState(5)
    ids = pt.to_tensor(rng.randint(0, 128, (1, 7)).astype(np.int64))
    tok = pt.to_tensor(rng.randint(0, 128, (1, 1)).astype(np.int64))

    caches = [(None, None)] * m.cfg.num_hidden_layers
    h1, caches = m.model(ids, caches=caches)
    h2, caches = m.model(tok, caches=caches)

    n_kv = m.cfg.num_key_value_heads
    hd = m.cfg.hidden_size // m.cfg.num_attention_heads
    bs, nblk = 4, 3  # capacity 12 >= 8 cached tokens
    bt = pt.to_tensor(np.array([[1, 2, 3]], np.int32))  # blocks 1..3
    pools = [[pt.to_tensor(jnp.zeros((nblk + 1, bs, n_kv, hd))),
              pt.to_tensor(jnp.zeros((nblk + 1, bs, n_kv, hd)))]
             for _ in range(m.cfg.num_hidden_layers)]

    def run(x, ctx, n_new):
        nonlocal pools
        pc = [PagedLayerCache(k, v, bt,
                              pt.to_tensor(np.array([ctx], np.int32)),
                              pt.to_tensor(np.array([n_new], np.int32)))
              for k, v in pools]
        h, new_c = m.model(x, caches=pc)
        pools = [[c.k_pool, c.v_pool] for c in new_c]
        return h

    g1 = run(ids, 0, 7)
    g2 = run(tok, 7, 1)
    np.testing.assert_allclose(g1.numpy(), h1.numpy(), atol=2e-5)
    np.testing.assert_allclose(g2.numpy(), h2.numpy(), atol=2e-5)


def test_decode_outranks_prefill_for_the_last_block():
    """Unified-step planning order (ISSUE 8): decode plans FIRST, so an
    OLDER running request takes the pool's last block ahead of a younger
    prompt's prefill chunk — FCFS holds exactly when the pool is the
    contended resource, and the running request is never starved by a
    streaming prompt."""
    from paddle_tpu.serving import PagedKVCache
    from paddle_tpu.serving.scheduler import Request, Scheduler

    cache = PagedKVCache(num_layers=1, num_blocks=3, block_size=4,
                         num_kv_heads=1, head_dim=4)
    sch = Scheduler(cache, max_batch=2, prefill_chunk=4)
    a = Request(prompt_tokens=[1] * 8)   # older: running, block-boundary
    sch.add(a)
    b = Request(prompt_tokens=[2] * 8)   # younger: about to prefill
    sch.add(b)
    sch._admit()
    a.block_ids = cache.allocator.allocate(2)
    a.prefill_pos = a.num_cached = 8     # next decode needs a 3rd block
    a.state = RequestState.RUNNING
    a.generated = [5]
    plan = sch.schedule()
    # A's decode takes the last free block; B's chunk finds the pool
    # empty and must WAIT (evicting would require a victim younger than
    # B — there is none) — never run through an all-null block table
    assert a in plan.decode and len(a.block_ids) == 3
    assert plan.prefills == []
    assert b.slot is not None and b.state is RequestState.PREFILL
    assert b.block_ids == []             # waiting, not corrupted


def test_multi_chunk_packing_and_budget():
    """Several prompts' chunks ride ONE step up to the token budget,
    FCFS order, each capped at prefill_chunk; running decoders are all
    planned first and never skipped while prompts stream
    (starvation-freedom under the unified step)."""
    from paddle_tpu.serving import PagedKVCache
    from paddle_tpu.serving.scheduler import Request, Scheduler

    cache = PagedKVCache(num_layers=1, num_blocks=32, block_size=4,
                         num_kv_heads=1, head_dim=4)
    sch = Scheduler(cache, max_batch=4, prefill_chunk=4, step_tokens=8)
    d = Request(prompt_tokens=[9] * 4)          # oldest: mid-decode
    sch.add(d)
    p1 = Request(prompt_tokens=[1] * 10)        # long prompt, streams
    p2 = Request(prompt_tokens=[2] * 3)
    p3 = Request(prompt_tokens=[3] * 6)
    for r in (p1, p2, p3):
        sch.add(r)
    sch._admit()
    d.block_ids = cache.allocator.allocate(1)
    d.prefill_pos = d.num_cached = 4
    d.state = RequestState.RUNNING
    d.generated = [7]
    plan = sch.schedule()
    # decode first, then chunks FCFS into the remaining 7-token budget:
    # p1 gets its full 4-token chunk, p2 its whole 3-token prompt; p3
    # must wait for the next step
    assert plan.decode == [d]
    assert [(r is p1 or r is p2 or r is p3, n)
            for r, n in plan.prefills] == [(True, 4), (True, 3)]
    assert plan.prefills[0][0] is p1 and plan.prefills[1][0] is p2
    assert plan.total_tokens == 8 <= sch.step_tokens
    # the long prompt streams: next plan gives its SECOND chunk and p3
    # enters; decode is still never skipped
    for seq, n in plan.prefills:
        seq.prefill_pos += n
        seq.num_cached += n
    p2.state = RequestState.RUNNING          # p2's prompt is complete
    p2.generated = [1]
    plan2 = sch.schedule()
    assert d in plan2.decode and p2 in plan2.decode
    assert plan2.prefills[0][0] is p1 and plan2.prefills[0][1] == 4
    assert plan2.total_tokens <= sch.step_tokens


def test_prefill_candidate_preempted_mid_loop_is_skipped():
    """A prefill candidate evicted by a SENIOR candidate's allocation
    earlier in the same _plan_prefills loop must be skipped, not
    planned: planning it would attach fresh blocks to a slotless WAITING
    request (invisible to _pick_victim, so senior requests would starve
    on an unreclaimable block) or spuriously evict a third sequence for
    a plan entry the engine discards anyway."""
    import time as _time

    from paddle_tpu.serving import PagedKVCache
    from paddle_tpu.serving.scheduler import Request, Scheduler

    cache = PagedKVCache(num_layers=1, num_blocks=3, block_size=4,
                         num_kv_heads=1, head_dim=4)
    sch = Scheduler(cache, max_batch=2, prefill_chunk=4, step_tokens=8)
    senior = Request(prompt_tokens=[1] * 4)
    sch.add(senior)
    _time.sleep(0.001)
    junior = Request(prompt_tokens=[2] * 12)  # mid-prefill, holds blocks
    sch.add(junior)
    sch._admit()
    junior.block_ids = cache.allocator.allocate(2)
    junior.prefill_pos = junior.num_cached = 8
    cache.allocator.allocate(1)               # drain the last free block
    plan = sch.schedule()
    # senior's chunk evicts junior (frees 2, takes 1, 1 left); the loop
    # then reaches junior — now WAITING/slotless — and must skip it
    assert [r for r, _ in plan.prefills] == [senior]
    assert junior.state is RequestState.WAITING and junior.slot is None
    assert junior.block_ids == []             # no blocks parked on it
    assert cache.allocator.num_free() == 1


def test_evicted_plan_entry_goes_stale_not_corrupt():
    """Protected-victim guarantee under the unified step: when a
    senior prefill's allocation preempts a younger request that the SAME
    plan already scheduled for decode, the victim's entry is left stale
    (slot released, state WAITING) — exactly what the engine's
    stale-entry filter checks — and its blocks are returned, never
    written through."""
    import time as _time

    from paddle_tpu.serving import PagedKVCache
    from paddle_tpu.serving.scheduler import Request, Scheduler

    cache = PagedKVCache(num_layers=1, num_blocks=2, block_size=4,
                         num_kv_heads=1, head_dim=4)
    sch = Scheduler(cache, max_batch=2, prefill_chunk=4, step_tokens=5)
    old = Request(prompt_tokens=[1] * 4)     # senior, needs 1 block
    sch.add(old)
    _time.sleep(0.001)
    young = Request(prompt_tokens=[2] * 4)   # junior: running on 1 block
    sch.add(young)
    sch._admit()
    young.block_ids = cache.allocator.allocate(1)
    young.prefill_pos = young.num_cached = 3  # 4th token fits block 1
    young.state = RequestState.RUNNING
    young.generated = [5]
    cache.allocator.allocate(1)               # drain the rest of the pool
    plan = sch.schedule()
    # young decodes within its block -> planned; old's 4-token chunk
    # then needs a block -> evicts young (the only junior victim)
    assert young in plan.decode
    assert sch.num_preemptions == 1
    assert young.slot is None and young.state is RequestState.WAITING
    assert young.block_ids == []              # returned, not dangling
    # the engine-side stale filter must drop it
    live = [s for s in plan.decode
            if s.slot is not None and s.state is RequestState.RUNNING]
    assert live == []
    # and the senior prefill got real blocks for its planned chunk
    assert plan.prefills and plan.prefills[0][0] is old
    seq, n = plan.prefills[0]
    assert cache.blocks_for(seq.prefill_pos + n) <= len(seq.block_ids)


# ---------------- engine: tier-1 smoke ---------------------------------------
def test_engine_single_request_matches_eager(served):
    model, eng = served
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, 128, 9)
    h = eng.submit(prompt, max_new_tokens=8)
    eng.run_until_idle()
    res = h.result(timeout=30)
    assert res["token_ids"] == _eager_continuation(model, prompt, 8)
    assert res["finish_reason"] == "length"
    assert res["ttft_s"] > 0 and res["latency_s"] >= res["ttft_s"]
    assert eng.cache.allocator.blocks_in_use() == 0
    assert eng.step_traces == 1  # ONE unified executable, traced once


def test_engine_streaming_and_eos(served):
    model, eng = served
    rng = np.random.RandomState(1)
    prompt = rng.randint(1, 128, 6)
    first = _eager_continuation(model, prompt, 1)[0]
    got = []
    h = eng.submit(prompt, max_new_tokens=10, eos_token_id=first,
                   on_token=lambda req, tok: got.append(tok))
    eng.run_until_idle()
    res = h.result(timeout=30)
    # greedy first token IS the eos: one streamed token, eos finish
    assert res["token_ids"] == [first] == got
    assert res["finish_reason"] == "eos"
    eng.cache.allocator.assert_no_leaks()


def test_short_request_joins_mid_decode(served):
    """Continuous batching: a short request admitted while a long one is
    mid-decode; both match their solo sequential baselines and the short
    one finishes first."""
    model, eng = served
    rng = np.random.RandomState(2)
    long_p, short_p = rng.randint(1, 128, 14), rng.randint(1, 128, 5)
    h_long = eng.submit(long_p, max_new_tokens=16)
    while h_long._req.state is not RequestState.RUNNING:
        assert eng.step()
    eng.step()  # at least one pure-decode step before the newcomer
    h_short = eng.submit(short_p, max_new_tokens=3)
    eng.run_until_idle()
    assert h_short.result(30)["token_ids"] == \
        _eager_continuation(model, short_p, 3)
    assert h_long.result(30)["token_ids"] == \
        _eager_continuation(model, long_p, 16)
    assert h_short._req.finish_time < h_long._req.finish_time
    assert eng.step_traces == 1  # the newcomer reused the executable


@pytest.mark.slow
def test_preemption_recompute_no_leak():
    """A pool too small for all admitted sequences forces preemption-by-
    recompute; outputs stay equal to the solo baselines and every block
    returns to the pool. (Slow lane: needs its own engine — tier-1 keeps
    the allocator invariants + shared-engine leak asserts.)"""
    model = _tiny(5)
    eng = ServingEngine(model, max_batch=3, max_blocks=8, block_size=4,
                        prefill_chunk=4)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 128, n) for n in (9, 12, 7)]
    handles = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.run_until_idle()
    for hd, p in zip(handles, prompts):
        assert hd.result(30)["token_ids"] == \
            _eager_continuation(model, p, 8)
    assert eng.scheduler.num_preemptions >= 1
    eng.cache.allocator.assert_no_leaks()
    assert eng.step_traces == 1
    # recompute-tail invariant (ISSUE 15): across every admission, a
    # request prefills AT MOST its pending demand minus what the prefix
    # cache served — readmission never recomputes a cached block
    for hd in handles:
        r = hd._req
        assert r.prefilled_tokens <= \
            r.admitted_pending_total - r.cached_tokens_total
        if r.preemptions == 0:
            assert r.prefilled_tokens == \
                r.admitted_pending_total - r.cached_tokens_total


def test_submit_validation(served):
    _, eng = served
    with pytest.raises(ValueError, match="empty"):
        eng.submit([])
    with pytest.raises(ValueError, match="max sequence length"):
        eng.submit([1] * 8, max_new_tokens=10_000)


def test_abort_releases_queued_request():
    """Resilience seam (docs/RESILIENCE.md): aborting a request frees
    its queue entry/slot/blocks and fails the handle — the HTTP server
    uses this when a request blows its deadline_s. Engine never steps,
    so no compile cost in tier-1."""
    model = _tiny(7)
    eng = ServingEngine(model, max_batch=2, max_blocks=16, block_size=4,
                        prefill_chunk=4)
    h1 = eng.submit([1, 2, 3], max_new_tokens=4)
    h2 = eng.submit([4, 5, 6], max_new_tokens=4)
    assert eng.abort(h1.req_id, reason="client deadline")
    assert not eng.abort(h1.req_id)      # already finished: no-op
    assert not eng.abort(424242)         # unknown id: no-op
    with pytest.raises(RuntimeError, match="client deadline"):
        h1.result(1)
    # the aborted request left the scheduler entirely; the other stays
    assert h2._req in eng.scheduler.waiting or h2._req.slot is not None
    assert h1._req not in eng.scheduler.waiting and h1._req.slot is None
    assert eng.stats()["waiting"] + eng.stats()["running"] == 1
    eng.cache.allocator.assert_no_leaks()


# ---------------- HTTP front-end ---------------------------------------------
def test_http_generate_roundtrip(served):
    """Rides the shared module engine (no extra compile in tier-1): the
    server only wraps the engine's already-traced executables."""
    model, eng = served
    rng = np.random.RandomState(4)
    prompt = [int(t) for t in rng.randint(1, 128, 6)]
    srv = Server(eng).start()
    try:
        req = urllib.request.Request(
            srv.url + "/generate",
            data=json.dumps({"prompt_ids": prompt,
                             "max_new_tokens": 5}).encode(),
            headers={"Content-Type": "application/json"})
        res = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert res["token_ids"] == _eager_continuation(model, prompt, 5)
        assert res["ttft_ms"] > 0

        hz = json.loads(urllib.request.urlopen(
            srv.url + "/healthz", timeout=10).read())
        assert hz["status"] == "ok" and hz["step_compiles"] == 1
        # KV-pool pressure is visible to operators before preemption
        # starts churning (ISSUE 8 satellite)
        assert 0.0 <= hz["kv_headroom"] <= 1.0
        assert hz["attn_impl"] in ("rpa", "gather")
        # fleet identity fields (ISSUE 13): which rank of which job
        # answered, and is it actually making progress
        assert hz["rank"] == 0 and hz["job_id"]
        assert hz["last_step_age_seconds"] >= 0.0
        fz = json.loads(urllib.request.urlopen(
            srv.url + "/fleetz", timeout=10).read())
        assert fz["job_id"] == hz["job_id"] and "local_goodput" in fz

        # streaming: one NDJSON line per token, then the summary
        req = urllib.request.Request(
            srv.url + "/generate",
            data=json.dumps({"prompt_ids": prompt, "max_new_tokens": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        lines = [json.loads(ln) for ln in urllib.request.urlopen(
            req, timeout=60).read().decode().strip().split("\n")]
        toks = [ln["token"] for ln in lines if "token" in ln]
        assert toks == _eager_continuation(model, prompt, 4)
        assert lines[-1]["done"] is True

        bad = urllib.request.Request(srv.url + "/generate", data=b"nope",
                                     headers={"Content-Type": "text/plain"})
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bad, timeout=10)
    finally:
        # engine outlives the listener (later tests may reuse it)
        srv.close(stop_engine=False)
    eng.cache.allocator.assert_no_leaks()


def test_metrics_families_exposed(served):
    """serving_* metric families are live in the registry after an
    engine run (acceptance: non-zero TTFT + token totals). Drives one
    request itself so the test holds in isolation."""
    from paddle_tpu.observability import get_registry
    model, eng = served
    h = eng.submit(np.random.RandomState(6).randint(1, 128, 4),
                   max_new_tokens=2)
    eng.start()  # idempotent — the HTTP test may have started the loop
    h.result(timeout=60)
    reg = get_registry()
    ttft = reg.get("serving_ttft_seconds")
    toks = reg.get("serving_tokens_total")
    assert ttft is not None and ttft.stats() and ttft.stats()["count"] > 0
    assert toks is not None and toks.total() > 0
    text = reg.prometheus_text()
    for family in ("serving_ttft_seconds", "serving_tokens_total",
                   "serving_queue_depth", "serving_requests_running",
                   "serving_kv_blocks_in_use",
                   "serving_inter_token_seconds"):
        assert family in text


# ---------------- generate_loop early exit (satellite) -----------------------
def test_generate_loop_breaks_on_all_eos():
    """The eager decode loop must stop as soon as every row has hit
    eos_token_id — not run all max_new_tokens steps."""
    from paddle_tpu.models.generation import generate_loop

    m = _tiny(7)
    ids = pt.to_tensor(np.random.RandomState(8).randint(
        1, 128, (1, 6)).astype(np.int64))
    eos = int(m.generate(ids, max_new_tokens=1,
                         temperature=0.0).numpy()[0, -1])
    calls = {"decode": 0}

    def prefill(x):
        caches = [(None, None)] * m.cfg.num_hidden_layers
        h, caches = m.model(x, caches=caches)
        return m._logits(h[:, -1:]), caches

    def decode(tok, caches):
        calls["decode"] += 1
        h, caches = m.model(tok, caches=caches)
        return m._logits(h), caches

    out = generate_loop(prefill, decode, ids, max_new_tokens=20,
                        temperature=0.0, eos_token_id=eos)
    n_new = out.numpy().shape[1] - 6
    assert n_new < 20, "loop ran the full budget despite universal eos"
    # the loop may decode only while some row is unfinished
    assert calls["decode"] == n_new - 1


@pytest.mark.slow
def test_moe_served_independent_of_inactive_slots():
    """MoE through the engine: inactive decode slots and padded prefill
    tails must not perturb expert-capacity routing for real tokens — the
    same request gives identical tokens whether it runs in a 1-slot or a
    4-slot engine (regression for garbage tokens stealing GShard
    capacity positions), and matches the eager oracle here."""
    from paddle_tpu.models.moe import MoeConfig, MoeForCausalLM

    pt.seed(3)
    cfg = MoeConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                    moe_intermediate_size=32, num_hidden_layers=2,
                    num_attention_heads=4, num_key_value_heads=2,
                    num_experts=4, num_experts_per_tok=2,
                    num_shared_experts=1, first_k_dense_replace=1)
    m = MoeForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(21)
    p = rng.randint(1, 128, 9)
    outs = []
    for mb in (1, 4):
        eng = ServingEngine(m, max_batch=mb, max_blocks=32, block_size=4,
                            prefill_chunk=4)
        h = eng.submit(p, max_new_tokens=6)
        eng.run_until_idle()
        outs.append(h.result(30)["token_ids"])
        eng.cache.allocator.assert_no_leaks()
    assert outs[0] == outs[1], \
        "occupancy changed an MoE request's routing/output"
    assert m.aux_loss() is None  # decode tracers cleared via the hook
    assert outs[0] == _eager_continuation(m, p, 6)


# ---------------- acceptance integration (slow) ------------------------------
@pytest.mark.slow
def test_serving_acceptance_concurrent_mixed():
    """ISSUE 2 acceptance: >= 8 concurrent requests with mixed
    prompt/output lengths — decode compiles exactly once, every KV block
    returns to the pool, serving metrics are non-zero, every output
    token-matches its sequential baseline."""
    model = _tiny(9)
    eng = ServingEngine(model, max_batch=8, max_blocks=48, block_size=4,
                        prefill_chunk=8)
    rng = np.random.RandomState(11)
    lens = [5, 11, 17, 8, 13, 7, 20, 9, 15, 6]
    mnts = [6, 10, 4, 12, 8, 5, 7, 9, 3, 11]
    prompts = [rng.randint(1, 128, n) for n in lens]
    eng.start()
    handles = [eng.submit(p, max_new_tokens=mn)
               for p, mn in zip(prompts, mnts)]
    eng.drain(timeout=300)
    for hd, p, mn in zip(handles, prompts, mnts):
        assert hd.result(30)["token_ids"] == \
            _eager_continuation(model, p, mn)
    assert eng.step_traces == 1
    eng.cache.allocator.assert_no_leaks()
    eng.shutdown()

    from paddle_tpu.observability import get_registry
    reg = get_registry()
    assert reg.get("serving_ttft_seconds").stats()["count"] >= 10
    assert reg.get("serving_tokens_total").total() > 0


@pytest.mark.slow
def test_http_concurrent_clients():
    """Parallel HTTP clients against one server: every response matches
    its solo baseline (the engine multiplexes them into one batch)."""
    model = _tiny(10)
    eng = ServingEngine(model, max_batch=4, max_blocks=32, block_size=4,
                        prefill_chunk=4)
    rng = np.random.RandomState(12)
    prompts = [[int(t) for t in rng.randint(1, 128, n)]
               for n in (5, 9, 12, 7, 10)]
    results = [None] * len(prompts)

    with Server(eng) as srv:
        def client(i):
            req = urllib.request.Request(
                srv.url + "/generate",
                data=json.dumps({"prompt_ids": prompts[i],
                                 "max_new_tokens": 6}).encode(),
                headers={"Content-Type": "application/json"})
            results[i] = json.loads(
                urllib.request.urlopen(req, timeout=120).read())

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    for i, p in enumerate(prompts):
        assert results[i]["token_ids"] == _eager_continuation(model, p, 6)
    eng.cache.allocator.assert_no_leaks()


def test_request_span_chain_in_trace(served, tmp_path):
    """PR 6 tentpole: the engine writes a per-request span chain
    (queue_wait -> prefill_chunk(s) -> decode -> request_done) into the
    trace layer, so a slow TTFT decomposes into admission vs
    compile vs preemption right in the merged trace."""
    from paddle_tpu.observability import trace
    model, eng = served
    trace.disable()
    trace.enable(str(tmp_path), rank=0)
    try:
        prompt = list(range(1, 7))
        h = eng.submit(prompt, max_new_tokens=4)
        eng.run_until_idle()
        res = h.result(timeout=60)
    finally:
        writer_path = trace.active().path
        trace.disable()
    events = [json.loads(ln) for ln in open(writer_path)][1:]
    mine = [e for e in events
            if (e.get("args") or {}).get("req") == res["request_id"]]
    names = [e["name"] for e in mine]
    assert "queue_wait" in names
    # prefill_chunk=4 and a 6-token prompt: two chunks
    assert names.count("prefill_chunk") == 2
    assert "decode" in names and "request_done" in names
    # chain ordering: queue_wait ends before the first prefill chunk
    # starts; decode covers first->last token; done is terminal
    qw = next(e for e in mine if e["name"] == "queue_wait")
    pf = [e for e in mine if e["name"] == "prefill_chunk"]
    dec = next(e for e in mine if e["name"] == "decode")
    done = next(e for e in mine if e["name"] == "request_done")
    assert qw["ts"] + qw["dur"] <= pf[0]["ts"]
    assert pf[-1]["ts"] + pf[-1]["dur"] <= dec["ts"] + dec["dur"]
    assert done["args"]["finish_reason"] == "length"
    assert done["args"]["generated"] == 4
    assert done["args"]["ttft_s"] > 0
    # compile attribution rides the chunk spans (engine is warm: 0)
    assert all("compiles" in e["args"] for e in pf)
    # and the queue-wait histogram got its observation
    from paddle_tpu.observability import get_registry
    qwh = get_registry().get("serving_queue_wait_seconds")
    assert qwh is not None and qwh.stats()["count"] >= 1
