"""paddle_tpu.data — deterministic pipeline, packing, prefetch, resume.

Tier-1 tests are in-process and cheap (tiny models, no fresh traces
where avoidable); the SIGKILL → relaunch → identical-digest integration
test is ``@pytest.mark.slow`` (worker: ``tests/data_worker.py``).
"""
import hashlib
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import io
from paddle_tpu.data import (DataPipeline, DevicePrefetcher, SequencePacker,
                             ShardedStream)
from paddle_tpu.io.sampler import epoch_seed
from paddle_tpu.observability.metrics import MetricsRegistry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Docs:
    """Deterministic variable-length token documents."""

    def __init__(self, n=64, lo=5, hi=40, vocab=100):
        self.n, self.lo, self.hi, self.vocab = n, lo, hi, vocab

    def __getitem__(self, i):
        rng = np.random.RandomState(900 + i)
        return rng.randint(1, self.vocab,
                           rng.randint(self.lo, self.hi)).astype(np.int32)

    def __len__(self):
        return self.n


class Pairs:
    """Deterministic (x, y) samples for fit-shaped pipelines."""

    def __init__(self, n=24):
        self.n = n

    def __getitem__(self, i):
        rng = np.random.RandomState(50 + i)
        return (rng.randn(4).astype(np.float32),
                rng.randn(1).astype(np.float32))

    def __len__(self):
        return self.n


class ToyLM(nn.Layer):
    """Tiny self-supervised net with the packed-batch kwargs signature."""

    def __init__(self):
        super().__init__()
        self.emb = nn.Embedding(100, 8)
        self.head = nn.Linear(8, 100)

    def forward(self, input_ids, labels, attention_mask=None,
                position_ids=None):
        h = self.emb(input_ids)
        logits = self.head(h)
        loss = nn.functional.cross_entropy(
            logits, labels, ignore_index=-100)
        return logits, loss


def digest(batch) -> str:
    h = hashlib.sha256()
    if isinstance(batch, dict):
        parts = [batch[k] for k in sorted(batch)]
    else:
        parts = list(batch)
    for p in parts:
        arr = np.asarray(getattr(p, "data", p))
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


# ============================ epoch seeding =================================
class TestEpochSeed:
    def test_stable_and_distinct(self):
        assert epoch_seed(7, 3) == epoch_seed(7, 3)
        seen = {epoch_seed(s, e) for s in range(4) for e in range(64)}
        assert len(seen) == 4 * 64  # no collisions across nearby keys

    def test_two_fresh_loaders_agree(self):
        """The satellite regression: a REBUILT DataLoader replays the
        same shuffled order (prerequisite for deterministic resume)."""
        ds = Pairs(16)

        def orders(n_epochs=2):
            dl = io.DataLoader(ds, batch_size=4, shuffle=True, base_seed=9)
            return [digest(b) for _ in range(n_epochs) for b in dl]

        assert orders() == orders()

    def test_epochs_shuffle_differently(self):
        s = io.RandomSampler(Pairs(32), base_seed=1)
        e0, e1 = list(s), list(s)
        assert sorted(e0) == sorted(e1)
        assert e0 != e1  # epoch-keyed, not frozen

    def test_set_epoch_pins_order(self):
        a = io.RandomSampler(Pairs(32), base_seed=1)
        b = io.RandomSampler(Pairs(32), base_seed=1)
        list(a)  # advance a to epoch 1
        b.set_epoch(1)
        assert list(a) == list(b)

    def test_distributed_sampler_rebuild_replays(self):
        ds = Pairs(16)

        def order(epoch):
            s = io.DistributedBatchSampler(ds, batch_size=2,
                                           num_replicas=2, rank=0,
                                           shuffle=True, base_seed=3)
            s.set_epoch(epoch)
            return [i for b in s for i in b]

        assert order(2) == order(2)
        assert order(2) != order(3)


# ============================ sharded stream ================================
class TestShardedStream:
    def test_shards_disjoint_cover_balanced(self):
        ds = Pairs(24)
        per_shard = []
        for k in range(3):
            s = ShardedStream(ds, base_seed=5, shard_index=k, num_shards=3)
            per_shard.append([int(i) for i in s.epoch_order(0)])
        flat = [i for sh in per_shard for i in sh]
        assert sorted(flat) == list(range(24))
        assert all(len(sh) == 8 for sh in per_shard)

    def test_rebuild_replays_and_epochs_differ(self):
        def epochs():
            s = ShardedStream(Pairs(12), base_seed=2, shard_index=0,
                              num_shards=1)
            return [digest(b) for b in s], [digest(b) for b in s]

        (a0, a1), (b0, b1) = epochs(), epochs()
        assert a0 == b0 and a1 == b1
        assert a0 != a1

    def test_state_roundtrip_mid_epoch(self):
        ds = Pairs(12)
        ref = [digest(x) for x in
               ShardedStream(ds, base_seed=4, shard_index=0, num_shards=1)]
        s1 = ShardedStream(ds, base_seed=4, shard_index=0, num_shards=1)
        it = iter(s1)
        got = [digest(next(it)) for _ in range(5)]
        state = s1.state_dict()
        assert state["cursor"] == 5
        s2 = ShardedStream(ds, base_seed=4, shard_index=0, num_shards=1)
        s2.load_state_dict(state)
        got += [digest(x) for x in s2]
        assert got == ref

    def test_mesh_size_change_refused(self):
        s1 = ShardedStream(Pairs(12), shard_index=0, num_shards=2,
                           shuffle=False)
        s2 = ShardedStream(Pairs(12), shard_index=0, num_shards=3,
                           shuffle=False)
        with pytest.raises(ValueError, match="reshard_state"):
            s2.load_state_dict(s1.state_dict())

    def test_geometry_disagreement_refused(self):
        """drop_remainder / shard identity change the order the cursor
        indexes — restoring across them must refuse, not drift."""
        s1 = ShardedStream(Pairs(13), shard_index=0, num_shards=2,
                           shuffle=False, drop_remainder=True)
        s2 = ShardedStream(Pairs(13), shard_index=0, num_shards=2,
                           shuffle=False, drop_remainder=False)
        with pytest.raises(ValueError, match="drop_remainder"):
            s2.load_state_dict(s1.state_dict())
        s3 = ShardedStream(Pairs(13), shard_index=1, num_shards=2,
                           shuffle=False)
        with pytest.raises(ValueError, match="OWN data state"):
            s3.load_state_dict(s1.state_dict())

    def test_iterable_resume_skips_and_counts(self):
        class It(io.IterableDataset):
            def __iter__(self):
                return iter(np.arange(10, dtype=np.float32))

        reg = MetricsRegistry()
        s1 = ShardedStream(It(), shuffle=False, shard_index=0,
                           num_shards=1, registry=reg)
        it = iter(s1)
        first = [float(next(it)) for _ in range(4)]
        s2 = ShardedStream(It(), shuffle=False, shard_index=0,
                           num_shards=1, registry=reg)
        s2.load_state_dict(s1.state_dict())
        rest = [float(x) for x in s2]
        assert first + rest == list(range(10))
        skipped = reg.get("data_skipped_on_resume_total")
        assert skipped.total() == 4  # the fast-forwarded samples

    def test_dataset_size_change_refused(self):
        """A map-style dataset that grew or shrank since the checkpoint
        reshuffles the epoch permutation — the cursor would index
        different samples, so resume must refuse, not silently drift."""
        s1 = ShardedStream(Pairs(10), base_seed=4, shard_index=0,
                           num_shards=1)
        it = iter(s1)
        for _ in range(6):
            next(it)
        s2 = ShardedStream(Pairs(12), base_seed=4, shard_index=0,
                           num_shards=1)
        with pytest.raises(ValueError, match="same dataset"):
            s2.load_state_dict(s1.state_dict())

    def test_iterable_resume_truncated_source_raises(self):
        """A saved cursor past the end of a shrunken iterable source
        must fail loudly (the epoch would otherwise silently complete
        having yielded nothing) and the skip metric must count only the
        samples actually replayed, not the full cursor upfront."""
        class It(io.IterableDataset):
            def __init__(self, n):
                self.n = n

            def __iter__(self):
                return iter(np.arange(self.n, dtype=np.float32))

        reg = MetricsRegistry()
        s1 = ShardedStream(It(10), shuffle=False, shard_index=0,
                           num_shards=1, registry=reg)
        it = iter(s1)
        for _ in range(6):
            next(it)
        s2 = ShardedStream(It(4), shuffle=False, shard_index=0,
                           num_shards=1, registry=reg)
        s2.load_state_dict(s1.state_dict())
        with pytest.raises(RuntimeError, match="exhausted"):
            list(s2)
        # only the 4 existing samples were replayed-and-skipped
        assert reg.get("data_skipped_on_resume_total").total() == 4

    def test_epoch_boundary_state_normalizes(self):
        s1 = ShardedStream(Pairs(8), base_seed=1, shard_index=0,
                           num_shards=1)
        it = iter(s1)
        for _ in range(8):
            next(it)
        # state captured at the final sample: cursor == epoch length
        state = s1.state_dict()
        assert state["cursor"] == 8 and state["epoch"] == 0
        s2 = ShardedStream(Pairs(8), base_seed=1, shard_index=0,
                           num_shards=1)
        s2.load_state_dict(state)
        assert s2.epoch == 1 and s2.cursor == 0


# ============================== packer ======================================
class TestSequencePacker:
    def test_exactly_once_and_layout(self):
        docs = [Docs()[i] for i in range(20)]
        p = SequencePacker(seq_len=64, batch_size=2,
                           registry=MetricsRegistry())
        batches = []
        for d in docs:
            batches += p.add(d)
        tail = p.flush()
        if tail is not None:
            batches.append(tail)
        # every token appears exactly once, in order within its doc
        packed = np.concatenate(
            [b["input_ids"][b["attention_mask"] > 0] for b in batches])
        assert len(packed) == sum(len(d) for d in docs)
        for b in batches:
            ids, seg, pos, lab = (b["input_ids"], b["attention_mask"],
                                  b["position_ids"], b["labels"])
            assert ids.shape == seg.shape == pos.shape == lab.shape
            for r in range(seg.shape[0]):
                for sid in np.unique(seg[r]):
                    if sid == 0:
                        continue
                    span = np.where(seg[r] == sid)[0]
                    # contiguous doc, positions restart at 0
                    assert np.array_equal(span,
                                          np.arange(span[0],
                                                    span[-1] + 1))
                    assert np.array_equal(pos[r, span],
                                          np.arange(len(span)))
                    # first token of each doc and padding are unlabeled
                    assert lab[r, span[0]] == -100
                    assert np.array_equal(lab[r, span[1:]],
                                          ids[r, span[1:]])
            assert np.all(lab[seg == 0] == -100)

    def test_efficiency_on_synthetic_corpus(self):
        """The bench.py --data acceptance geometry, asserted in-process:
        first-fit reaches >= 85% density."""
        reg = MetricsRegistry()
        corpus = Docs(n=256, lo=24, hi=129, vocab=500)
        pipe = DataPipeline(corpus, batch_size=2, seq_len=256, pack=True,
                            base_seed=3, shuffle=True, drop_last=True,
                            registry=reg)
        n = 0
        for _ in pipe:
            n += 1
            if n >= 20:
                break
        stats = reg.get("data_packing_efficiency").stats()
        assert stats["count"] >= 20
        assert stats["mean"] >= 0.85

    def test_long_doc_splits(self):
        p = SequencePacker(seq_len=16, batch_size=1)
        batches = p.add(np.arange(1, 41, dtype=np.int32))  # 40 tokens
        tail = p.flush()
        got = np.concatenate(
            [b["input_ids"][b["attention_mask"] > 0]
             for b in batches + [tail]])
        assert np.array_equal(got, np.arange(1, 41))

    def test_carry_roundtrip(self):
        docs = [Docs()[i] for i in range(30)]
        ref_p = SequencePacker(seq_len=64, batch_size=2)
        ref = []
        for d in docs:
            ref += [digest(b) for b in ref_p.add(d)]

        p1 = SequencePacker(seq_len=64, batch_size=2)
        got = []
        for d in docs[:13]:
            got += [digest(b) for b in p1.add(d)]
        state = p1.state_dict()
        assert any(len(bins) for bins in state["bins"])  # real carry
        p2 = SequencePacker(seq_len=64, batch_size=2)
        p2.load_state_dict(state)
        for d in docs[13:]:
            got += [digest(b) for b in p2.add(d)]
        assert got == ref

    def test_efficiency_stats_per_instance(self):
        """The histogram is process-global; efficiency_stats() must
        report only this packer's batches."""
        reg = MetricsRegistry()
        a = SequencePacker(seq_len=8, batch_size=1, registry=reg)
        b = SequencePacker(seq_len=8, batch_size=1, registry=reg)
        a.add(np.arange(1, 9, dtype=np.int32))   # fills, next add flushes
        a.add(np.arange(1, 9, dtype=np.int32))   # flush: eff 1.0
        b.add(np.arange(1, 3, dtype=np.int32))
        assert b.flush() is not None             # eff 0.25
        assert a.efficiency_stats() == {"mean": 1.0, "count": 1}
        assert b.efficiency_stats()["mean"] == pytest.approx(0.25)

    def test_geometry_mismatch_refused(self):
        p1 = SequencePacker(seq_len=64, batch_size=2)
        p2 = SequencePacker(seq_len=32, batch_size=2)
        with pytest.raises(ValueError, match="geometry"):
            p2.load_state_dict(p1.state_dict())


# ============================= pipeline =====================================
class TestDataPipeline:
    def _digests(self, pipe, epochs=2):
        return [digest(b) for _ in range(epochs) for b in pipe]

    def test_packed_resume_matches_uninterrupted(self):
        kw = dict(batch_size=2, seq_len=64, pack=True, base_seed=7,
                  shuffle=True, drop_last=True)
        ref = self._digests(DataPipeline(Docs(40), **kw))
        p1 = DataPipeline(Docs(40), **kw)
        it = iter(p1)
        got = [digest(next(it)) for _ in range(4)]
        state = p1.state_dict()
        p2 = DataPipeline(Docs(40), **kw)
        p2.load_state_dict(state)
        # p2's first __iter__ finishes epoch 0's remainder, the second
        # runs epoch 1 — same coverage as the uninterrupted reference
        got += self._digests(p2, epochs=2)
        assert got == ref

    def test_plain_resume_matches_uninterrupted(self):
        kw = dict(batch_size=4, shuffle=True, base_seed=5, drop_last=True)
        ref = self._digests(DataPipeline(Pairs(), **kw))
        p1 = DataPipeline(Pairs(), **kw)
        it = iter(p1)
        got = [digest(next(it)) for _ in range(3)]
        p2 = DataPipeline(Pairs(), **kw)
        p2.load_state_dict(p1.state_dict())
        got += self._digests(p2, epochs=2)
        assert got == ref

    def test_epoch_property_owes_tail_on_resume(self):
        """A state restored at an epoch tail (stream normalized to the
        next epoch, carry unflushed) must still report the FINISHED
        epoch — `epochs - pipe.epoch` relaunch loops would otherwise
        skip the tail batch AND a whole trailing epoch."""
        ds = Docs(13, lo=36, hi=61)
        kw = dict(batch_size=2, seq_len=64, pack=True, base_seed=7,
                  shuffle=True, drop_last=False)
        ref = [digest(b) for b in DataPipeline(ds, **kw)]
        p1 = DataPipeline(ds, **kw)
        it = iter(p1)
        for _ in range(len(ref) - 1):  # stop just before the tail flush
            next(it)
        p2 = DataPipeline(ds, **kw)
        p2.load_state_dict(p1.state_dict())
        assert p2.epoch == 0  # epoch 0 still owes its tail batch
        assert [digest(b) for b in p2] == ref[-1:]
        assert p2.epoch == 1

    def test_drop_last_mismatch_refused(self):
        """drop_last decides whether a restored epoch-tail carry flushes
        or rides into the next epoch — resuming across a flip must
        refuse, not silently change the batch sequence."""
        kw = dict(batch_size=2, seq_len=64, pack=True, base_seed=1)
        p1 = DataPipeline(Docs(8), drop_last=True, **kw)
        p2 = DataPipeline(Docs(8), drop_last=False, **kw)
        with pytest.raises(ValueError, match="drop_last"):
            p2.load_state_dict(p1.state_dict())

    def test_pack_state_into_nonpack_pipeline_refused(self):
        """A packing state restored into a non-packing pipeline would
        silently drop the carry and pending batches — refuse instead."""
        p1 = DataPipeline(Docs(8), batch_size=2, seq_len=64, pack=True,
                          base_seed=1)
        p2 = DataPipeline(Docs(8), batch_size=2, base_seed=1)
        with pytest.raises(ValueError, match="pack=True"):
            p2.load_state_dict(p1.state_dict())

    def test_prefetch_preserves_order_slow_dataset(self):
        class Slow(Pairs):
            def __getitem__(self, i):
                time.sleep(0.003)
                return super().__getitem__(i)

        kw = dict(batch_size=4, shuffle=True, base_seed=3, drop_last=True)
        sync = [digest(b) for b in DataPipeline(Slow(), **kw)]
        pre = [digest(b) for b in
               DataPipeline(Slow(), device_prefetch=3, **kw)]
        assert pre == sync

    def test_prefetch_commits_at_delivery(self):
        pipe = DataPipeline(Pairs(), batch_size=4, shuffle=True,
                            base_seed=3, drop_last=True,
                            device_prefetch=3)
        it = iter(pipe)
        next(it)
        next(it)
        time.sleep(0.1)  # let the producer run ahead into the buffer
        assert pipe.state_dict()["step"] == 2  # delivered, not produced
        rest = list(it)
        assert pipe.state_dict()["step"] == 2 + len(rest)

    def test_epoch_reads_committed_not_producer(self):
        """Under prefetch the producer can run to the end of an epoch
        while the trainer is still inside it — pipe.epoch must report
        the DELIVERED position, like step, not the producer's."""
        pipe = DataPipeline(Pairs(8), batch_size=4, shuffle=True,
                            base_seed=3, drop_last=True,
                            device_prefetch=4)
        it = iter(pipe)
        next(it)  # 1 of epoch 0's 2 batches delivered
        time.sleep(0.15)  # producer buffers the rest of the epoch
        assert pipe.epoch == 0
        next(it)
        assert list(it) == []
        assert pipe.epoch == 1  # epoch 0 fully delivered

    def test_prefetch_early_break_replays_buffered_batches(self):
        """An early-exiting consumer (num_iters / preemption) must not
        lose the batches the producer had buffered: re-iteration
        re-anchors at the delivered position."""
        kw = dict(batch_size=4, shuffle=True, base_seed=3, drop_last=True)
        ref = [digest(b) for b in DataPipeline(Pairs(), **kw)]
        pipe = DataPipeline(Pairs(), device_prefetch=4, **kw)
        it = iter(pipe)
        got = [digest(next(it))]
        time.sleep(0.1)  # the producer buffers well past batch 1
        del it  # consumer breaks out
        got += [digest(b) for b in pipe]  # re-enter the epoch
        assert got == ref

    @pytest.mark.parametrize("drop_last", [True, False])
    def test_checkpoint_between_multi_batch_flush(self, drop_last):
        """One long document can flush SEVERAL batches from a single
        packer.add() while the stream cursor is already past the doc; a
        checkpoint taken between those flushes must not lose the later
        batches (they ride the state as `pending`). With drop_last=False
        the epoch-tail flush after the last pending batch must survive
        the same cut points."""
        class LongDocs:
            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                return rng.randint(1, 50, 70).astype(np.int32)

            def __len__(self):
                return 4

        kw = dict(batch_size=2, seq_len=8, pack=True, shuffle=False,
                  drop_last=drop_last)
        ref = [digest(b) for b in DataPipeline(LongDocs(), **kw)]
        assert len(ref) > len(LongDocs())  # multi-batch adds happened
        for cut in range(1, len(ref)):
            p1 = DataPipeline(LongDocs(), **kw)
            it = iter(p1)
            got = [digest(next(it)) for _ in range(cut)]
            p2 = DataPipeline(LongDocs(), **kw)
            p2.load_state_dict(p1.state_dict())
            got += [digest(b) for b in p2]
            assert got == ref, f"diverged after checkpoint at batch {cut}"

    def test_packed_no_drop_last_resume_any_cut(self):
        """Epoch-tail regression: with drop_last=False, a checkpoint
        committed after the epoch's last in-loop batch (stream cursor
        normalized to next-epoch/0) but before the tail flush is
        delivered left an unflushed carry that bled into the next
        epoch's packing. Resume must deliver that tail batch exactly
        where the uninterrupted run would have — checked at EVERY cut
        point across two epochs."""
        # 13 docs of 36..60 tokens at [B=2, seq=64]: no two docs share a
        # bin, so the odd 13th doc always triggers an in-loop emit at
        # the epoch's END (cursor at epoch length) leaving itself as the
        # unflushed carry; a bled carry then merges with the next
        # epoch's docs into a batch the uninterrupted run never produces
        ds = Docs(13, lo=36, hi=61)
        kw = dict(batch_size=2, seq_len=64, pack=True, base_seed=7,
                  shuffle=True, drop_last=False)
        ref = self._digests(DataPipeline(ds, **kw), epochs=2)
        for cut in range(1, len(ref)):
            p1 = DataPipeline(ds, **kw)
            got = []
            while len(got) < cut:
                for b in p1:
                    got.append(digest(b))
                    if len(got) == cut:
                        break
            p2 = DataPipeline(ds, **kw)
            p2.load_state_dict(p1.state_dict())
            while len(got) < len(ref):
                before = len(got)
                for b in p2:
                    got.append(digest(b))
                    if len(got) == len(ref):
                        break
                assert len(got) > before  # every __iter__ makes progress
            assert got == ref, f"diverged after checkpoint at batch {cut}"

    def test_prefetch_consumer_exit_joins_producer(self):
        """Leaving a prefetching iteration must JOIN the producer thread:
        a straggler still running inside the pairs generator would race
        the re-anchoring load_state_dict of the next __iter__."""
        import threading
        pipe = DataPipeline(Pairs(), batch_size=4, shuffle=True,
                            base_seed=3, drop_last=True, device_prefetch=2)
        it = iter(pipe)
        next(it)
        it.close()  # early consumer exit — must synchronously stop+join
        assert not [t for t in threading.enumerate()
                    if t.name == "pt-data-prefetch" and t.is_alive()]

    def test_to_device_nondivisible_falls_back_and_warns_once(self):
        """Only the non-divisible case may downgrade to an unsharded
        put, and it announces itself once per run instead of silently."""
        import warnings as w

        import paddle_tpu.data.prefetch as pf

        class Odd:  # sharding whose shard_shape rejects every shape
            def shard_shape(self, shape):
                raise ValueError("not divisible")

        class TooDeep:  # rank-mismatch: jax raises IndexError for this
            def shard_shape(self, shape):
                return shape[5]

        pf._unsharded_fallback_warned = False
        with pytest.warns(RuntimeWarning, match="unsharded"):
            out = pf.to_device({"x": np.ones((3, 2), np.float32)},
                               sharding=Odd())
        assert isinstance(out["x"], pt.Tensor)
        with w.catch_warnings():  # second fallback stays quiet
            w.simplefilter("error")
            pf.to_device(np.ones((3,), np.float32), sharding=Odd())
            # a leaf whose rank is below the PartitionSpec falls back
            # too instead of killing the prefetch producer
            pf.to_device(np.float32(1.0), sharding=TooDeep())

    def test_to_device_real_sharding_failure_raises(self):
        """A sharding that claims the shape fits but fails at placement
        is a real misconfiguration — it must raise, not silently fall
        back to an unsharded put."""
        from paddle_tpu.data.prefetch import to_device

        class Bogus:  # passes the divisibility pre-check, not a Sharding
            def shard_shape(self, shape):
                return tuple(shape)

        with pytest.raises(Exception):
            to_device(np.ones((4,), np.float32), sharding=Bogus())

    def test_external_prefetcher_on_pipeline_refused(self):
        pipe = DataPipeline(Pairs(), batch_size=4)
        with pytest.raises(ValueError, match="device_prefetch"):
            DevicePrefetcher(pipe)

    def test_device_prefetcher_wraps_plain_loader(self):
        dl = io.DataLoader(Pairs(), batch_size=4, shuffle=True,
                           base_seed=1)
        ref = [digest(b) for b in
               io.DataLoader(Pairs(), batch_size=4, shuffle=True,
                             base_seed=1)]
        got = []
        for b in DevicePrefetcher(dl, depth=2):
            assert isinstance(b[0], pt.Tensor)  # already device-resident
            got.append(digest(b))
        assert got == ref

    def test_bad_samples_share_loader_budget(self):
        class Flaky(Pairs):
            def __getitem__(self, i):
                if i == 3:
                    raise IOError("shard rot")
                return super().__getitem__(i)

        reg = MetricsRegistry()
        pipe = DataPipeline(Flaky(8), batch_size=2, shuffle=False,
                            max_bad_samples=2, registry=reg)
        with pytest.warns(RuntimeWarning, match="stream"):
            n = sum(1 for _ in pipe)
        assert n == 4  # 7 good samples -> 3 full pairs + 1 tail
        from paddle_tpu.observability.metrics import get_registry
        c = get_registry().get("loader_bad_samples_total")
        assert c is not None and c.value(stage="stream") >= 1

    def test_bad_sample_budget_exhausts_loudly(self):
        class Broken(Pairs):
            def __getitem__(self, i):
                raise IOError("all gone")

        pipe = DataPipeline(Broken(6), batch_size=2, shuffle=False,
                            max_bad_samples=2)
        with pytest.raises(RuntimeError, match="budget exhausted"), \
                pytest.warns(RuntimeWarning):
            list(pipe)


# ========================= packed model path ================================
class TestPackedModelPath:
    def test_packed_attention_equals_separate_docs(self):
        """The kernel-facing contract: packing with segment ids +
        per-document positions is bit-identical to attending each
        document alone (flash kernel's segment masking + RoPE gather)."""
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        pt.seed(0)
        m = LlamaForCausalLM(LlamaConfig.tiny())
        m.eval()
        d1 = np.arange(1, 9, dtype=np.int32)
        d2 = np.arange(20, 26, dtype=np.int32)
        S = 16
        ids = np.zeros((1, S), np.int32)
        seg = np.zeros((1, S), np.int32)
        pos = np.zeros((1, S), np.int32)
        ids[0, :8], seg[0, :8], pos[0, :8] = d1, 1, np.arange(8)
        ids[0, 8:14], seg[0, 8:14], pos[0, 8:14] = d2, 2, np.arange(6)
        packed = m(pt.to_tensor(ids), attention_mask=pt.to_tensor(seg),
                   position_ids=pt.to_tensor(pos)).numpy()
        l1 = m(pt.to_tensor(d1[None, :])).numpy()
        l2 = m(pt.to_tensor(d2[None, :])).numpy()
        np.testing.assert_allclose(packed[0, :8], l1[0], atol=1e-5)
        np.testing.assert_allclose(packed[0, 8:14], l2[0], atol=1e-5)

    def test_fit_packed_dict_batches(self):
        """Dict batches flow through Model.prepare(loss=None) as network
        kwargs (the packed-pipeline fit contract)."""
        net = ToyLM()
        model = pt.hapi.Model(net)
        model.prepare(pt.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters()),
                      loss=None)
        pipe = DataPipeline(Docs(24), batch_size=2, seq_len=32, pack=True,
                            base_seed=1, drop_last=True)
        history = model.fit(pipe, epochs=1, verbose=0)
        assert np.isfinite(history["loss"][0])
        assert pipe.step > 0

    def test_dict_batch_with_loss_prepared_refused(self):
        """A loss-prepared model can't consume packed dict batches — the
        error must say so instead of dying inside jit tracing."""
        net = ToyLM()
        model = pt.hapi.Model(net)
        model.prepare(pt.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters()),
                      loss=nn.MSELoss())
        batch = {"input_ids": np.ones((2, 8), np.int32),
                 "labels": np.ones((2, 8), np.int32)}
        with pytest.raises(RuntimeError, match="loss=None"):
            model.train_batch(batch)
        with pytest.raises(RuntimeError, match="loss=None"):
            model.eval_batch(batch)

    def test_evaluate_packed_dict_batches(self):
        """evaluate() routes dict batches through the self-supervised
        network too — fit(train, eval_data=packed_pipe) must work."""
        net = ToyLM()
        model = pt.hapi.Model(net)
        model.prepare(pt.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters()),
                      loss=None)
        train = DataPipeline(Docs(16), batch_size=2, seq_len=32, pack=True,
                             base_seed=1, drop_last=True)
        ev = DataPipeline(Docs(12), batch_size=2, seq_len=32, pack=True,
                          base_seed=2, drop_last=True)
        history = model.fit(train, eval_data=ev, epochs=1, verbose=0)
        assert np.isfinite(history["loss"][0])
        logs = model.evaluate(ev, verbose=0)
        assert np.isfinite(logs["loss"])


# ===================== resilience / checkpoint integration ==================
class TestExactlyOnceResume:
    def _run(self, tmp_path, trip_at=None, epochs=3):
        """One trainer 'process' (in-process): tiny fit over the
        pipeline with FitResilience committing data state every step;
        returns the digests of batches actually trained."""
        from paddle_tpu.resilience import FitResilience

        seen = []
        pipe = DataPipeline(Pairs(), batch_size=4, shuffle=True,
                            base_seed=5, drop_last=True)
        pt.seed(11)
        model = pt.hapi.Model(nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                            nn.Linear(8, 1)))
        model.prepare(pt.optimizer.SGD(learning_rate=0.05,
                                       parameters=model.parameters()),
                      nn.MSELoss())
        fr = FitResilience(checkpoint_dir=str(tmp_path / "ckpt"),
                           save_every_steps=1, preemption=True,
                           pipeline=pipe)
        fr.restore(model)

        class Trip(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                if trip_at is not None and fr.global_step == trip_at:
                    fr.listener.request("test")

        class Wrap:
            def __iter__(self):
                for b in pipe:
                    seen.append(digest(b))
                    yield b

        remaining = epochs - pipe.epoch
        if remaining > 0:
            model.fit(Wrap(), epochs=remaining, verbose=0,
                      callbacks=[fr, Trip()])
        return seen, fr

    def test_preempt_resume_is_exactly_once(self, tmp_path):
        """The acceptance criterion: batch digests across kill+relaunch
        equal an uninterrupted run's, and the iterator state commits in
        the SAME step dir as model+opt."""
        ref, _ = self._run(tmp_path / "ref")
        first, fr1 = self._run(tmp_path / "killed", trip_at=8)
        assert fr1.preempted and fr1.exit_code == 79
        # the final committed step carries model+opt+data atomically
        state = fr1.manager.restore()
        assert set(state) >= {"model", "optimizer", "data"}
        assert state["data"]["step"] == len(first)
        second, fr2 = self._run(tmp_path / "killed")
        assert not fr2.preempted
        assert first + second == ref

    def test_resumed_empty_epoch_remainder_no_nan(self, tmp_path):
        """A resumed epoch whose remainder holds no full batch
        (drop_last=True, cursor already past the last full batch) must
        not log a spurious NaN epoch loss in fit history."""
        kw = dict(batch_size=4, shuffle=True, base_seed=5,
                  drop_last=True)
        p1 = DataPipeline(Pairs(10), **kw)
        it = iter(p1)
        next(it)
        next(it)  # cursor now 8 of 10: the remainder can't fill a batch
        p2 = DataPipeline(Pairs(10), **kw)
        p2.load_state_dict(p1.state_dict())
        assert p2.epoch == 0
        pt.seed(11)
        model = pt.hapi.Model(nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                            nn.Linear(8, 1)))
        model.prepare(pt.optimizer.SGD(learning_rate=0.05,
                                       parameters=model.parameters()),
                      nn.MSELoss())
        history = model.fit(p2, epochs=2 - p2.epoch, verbose=0)
        assert history["loss"]  # epoch 1 really trained
        assert all(np.isfinite(v) for v in history["loss"])

    def test_data_state_survives_checkpoint_roundtrip(self, tmp_path):
        """Packer carry (numpy arrays inside aux/shards) round-trips
        bit-exactly through the CheckpointManager layout."""
        from paddle_tpu.checkpoint import CheckpointManager

        pipe = DataPipeline(Docs(30), batch_size=2, seq_len=64, pack=True,
                            base_seed=2, drop_last=True)
        it = iter(pipe)
        for _ in range(3):
            next(it)
        state = pipe.state_dict()
        assert any(len(b) for b in state["packer"]["bins"])  # live carry
        mgr = CheckpointManager(str(tmp_path), async_=False)
        mgr.save(1, {"data": state})
        restored = mgr.restore()["data"]
        p2 = DataPipeline(Docs(30), batch_size=2, seq_len=64, pack=True,
                          base_seed=2, drop_last=True)
        p2.load_state_dict(restored)
        a = [digest(b) for b in it]
        b = [digest(x) for x in p2]
        assert b == a


# ========================= slow integration =================================
@pytest.mark.slow
def test_sigkill_relaunch_digest_identical(tmp_path):
    """Chaos SIGKILL mid-run → relaunch → the ledger of trained-batch
    digests across both processes equals an uninterrupted run's
    (exactly-once data through a REAL process death, not an in-process
    simulation)."""
    def run_job(run_dir, kill_step=None):
        env = dict(os.environ)
        env.update({"DATA_TEST_DIR": str(run_dir),
                    "DATA_TEST_EPOCHS": "3",
                    "JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": ROOT})
        if kill_step is not None:
            env["PADDLE_TPU_CHAOS_KILL_AT_STEP"] = str(kill_step)
            env["PADDLE_TPU_CHAOS_MARK_DIR"] = str(run_dir)
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "tests",
                                          "data_worker.py")],
            env=env, timeout=300, capture_output=True, text=True)

    def ledger(run_dir):
        path = os.path.join(run_dir, "batches.jsonl")
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]

    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    r = run_job(ref_dir)
    assert r.returncode == 0, r.stderr
    ref = [e["digest"] for e in ledger(ref_dir)]

    job_dir = tmp_path / "job"
    job_dir.mkdir()
    r1 = run_job(job_dir, kill_step=7)
    assert r1.returncode != 0  # SIGKILL'd
    r2 = run_job(job_dir)  # relaunch (mark dir suppresses a second kill)
    assert r2.returncode == 0, r2.stderr
    entries = ledger(job_dir)
    pids = list(dict.fromkeys(e["pid"] for e in entries))
    assert len(pids) == 2  # really two processes
    assert [e["digest"] for e in entries] == ref
    assert os.path.exists(os.path.join(job_dir, "done.json"))
