"""incubate.autograd (prim) — jvp/vjp/Jacobian/Hessian/forward_grad.

Oracle parity with the reference's ``python/paddle/incubate/autograd``
functional API, checked against analytic numpy derivatives.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.incubate import autograd as pag


def _np(t):
    return np.asarray(t.data)


def test_jvp_matches_analytic():
    x = pt.to_tensor(np.array([0.3, 1.1, -0.4], np.float32))
    v = pt.to_tensor(np.array([1.0, -2.0, 0.5], np.float32))
    out, dot = pag.jvp(lambda t: pt.ops.sin(t), x, v)
    np.testing.assert_allclose(_np(out), np.sin(_np(x)), rtol=1e-6)
    np.testing.assert_allclose(_np(dot), np.cos(_np(x)) * _np(v), rtol=1e-6)


def test_jvp_default_tangent_is_ones():
    x = pt.to_tensor(np.array([2.0, 3.0], np.float32))
    _, dot = pag.jvp(lambda t: pt.ops.multiply(t, t), x)
    np.testing.assert_allclose(_np(dot), 2 * _np(x), rtol=1e-6)


def test_vjp_matches_analytic():
    x = pt.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    v = pt.to_tensor(np.ones((2, 2), np.float32))
    out, g = pag.vjp(lambda t: pt.ops.multiply(t, t), x, v)
    np.testing.assert_allclose(_np(out), _np(x) ** 2, rtol=1e-6)
    np.testing.assert_allclose(_np(g), 2 * _np(x), rtol=1e-6)


def test_vjp_multiple_inputs():
    a = pt.to_tensor(np.array([1.0, 2.0], np.float32))
    b = pt.to_tensor(np.array([3.0, 4.0], np.float32))
    (out, (ga, gb)) = pag.vjp(lambda x, y: pt.ops.multiply(x, y), [a, b])
    np.testing.assert_allclose(_np(out), _np(a) * _np(b), rtol=1e-6)
    np.testing.assert_allclose(_np(ga), _np(b), rtol=1e-6)
    np.testing.assert_allclose(_np(gb), _np(a), rtol=1e-6)


def test_jacobian_dense():
    W = np.array([[1.0, 2.0, 0.0], [0.5, -1.0, 3.0]], np.float32)
    x = pt.to_tensor(np.array([0.2, -0.3, 0.7], np.float32))
    jac = pag.Jacobian(lambda t: pt.ops.matmul(
        pt.to_tensor(W), t), x)
    np.testing.assert_allclose(jac.numpy(), W, rtol=1e-6)
    assert jac.shape == [2, 3]
    np.testing.assert_allclose(np.asarray(jac[0, :].data), W[0], rtol=1e-6)


def test_jacobian_batched():
    x = pt.to_tensor(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    jac = pag.Jacobian(lambda t: pt.ops.multiply(t, t), x, is_batched=True)
    got = jac.numpy()
    assert got.shape == (4, 3, 3)
    for b in range(4):
        np.testing.assert_allclose(got[b], np.diag(2 * np.asarray(x.data)[b]),
                                   rtol=1e-5)


def test_hessian_quadratic():
    A = np.array([[2.0, 1.0], [1.0, 4.0]], np.float32)

    def f(t):
        At = pt.ops.matmul(pt.to_tensor(A), t)
        return pt.ops.multiply(pt.to_tensor(np.float32(0.5)),
                               pt.ops.sum(pt.ops.multiply(t, At)))

    x = pt.to_tensor(np.array([0.3, -0.2], np.float32))
    hess = pag.Hessian(f, x)
    # Hessian of 0.5 x^T A x (A symmetric) is A
    np.testing.assert_allclose(hess.numpy(), A, rtol=1e-5)


def test_forward_grad_on_tape():
    x = pt.to_tensor(np.array([0.5, 1.5], np.float32))
    x.stop_gradient = False
    y = pt.ops.sum(pt.ops.multiply(pt.ops.sin(x), x))
    v = pt.to_tensor(np.array([1.0, -1.0], np.float32))
    (jv,) = pag.forward_grad([y], [x], [v])
    expect = np.sum((np.cos(_np(x)) * _np(x) + np.sin(_np(x))) * _np(v))
    np.testing.assert_allclose(np.asarray(jv.data), expect, rtol=1e-5)


def test_prim_grad_differentiable():
    x = pt.to_tensor(np.array(1.2, np.float32))
    x.stop_gradient = False
    y = pt.ops.multiply(pt.ops.multiply(x, x), x)  # x^3
    g = pag.grad(y, x)  # 3x^2, still differentiable
    g2 = pag.grad(g, x)  # 6x
    np.testing.assert_allclose(np.asarray(g2.data), 6 * 1.2, rtol=1e-5)


def test_prim_toggle():
    assert not pag.prim_enabled()
    pag.enable_prim()
    assert pag.prim_enabled()
    pag.disable_prim()
    assert not pag.prim_enabled()


def test_jacobian_multiple_inputs():
    a = pt.to_tensor(np.array([1.0, 2.0], np.float32))
    b = pt.to_tensor(np.array([3.0], np.float32))
    jac = pag.Jacobian(lambda x, y: pt.ops.multiply(
        x, pt.ops.expand(y, [2])), [a, b])
    got = jac.numpy()  # [2, 3]: d(x*y)/dx = diag(y), d/dy = x
    assert got.shape == (2, 3)
    np.testing.assert_allclose(got[:, :2], np.diag([3.0, 3.0]), rtol=1e-6)
    np.testing.assert_allclose(got[:, 2], [1.0, 2.0], rtol=1e-6)


def test_hessian_multiple_inputs():
    a = pt.to_tensor(np.array([1.0], np.float32))
    b = pt.to_tensor(np.array([2.0], np.float32))
    hess = pag.Hessian(lambda x, y: pt.ops.sum(
        pt.ops.multiply(pt.ops.multiply(x, x), y)), [a, b])
    got = hess.numpy()  # f = x^2 y: [[2y, 2x], [2x, 0]]
    np.testing.assert_allclose(got, [[4.0, 2.0], [2.0, 0.0]], rtol=1e-5)
