"""create_graph / higher-order gradient tests vs analytic oracles and the
reference's double-grad use cases (gradient penalty)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


def t(x, sg=False):
    return pt.to_tensor(np.asarray(x, dtype=np.float32), stop_gradient=sg)


class TestCreateGraph:
    def test_second_derivative_polynomial(self):
        x = t([2.0])
        y = x * x * x  # y = x^3
        (gx,) = pt.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(gx.numpy(), [12.0], rtol=1e-5)  # 3x^2
        (gxx,) = pt.grad(gx, [x])
        np.testing.assert_allclose(gxx.numpy(), [12.0], rtol=1e-5)  # 6x

    def test_third_derivative(self):
        x = t([1.5])
        y = x * x * x * x  # x^4
        (g1,) = pt.grad(y, [x], create_graph=True)
        (g2,) = pt.grad(g1, [x], create_graph=True)
        (g3,) = pt.grad(g2, [x])
        np.testing.assert_allclose(g3.numpy(), [24 * 1.5], rtol=1e-5)

    def test_mixed_partial(self):
        x, y = t([2.0]), t([3.0])
        z = x * x * y  # d/dx = 2xy; d2/dxdy = 2x
        (gx,) = pt.grad(z, [x], create_graph=True)
        (gxy,) = pt.grad(gx, [y])
        np.testing.assert_allclose(gxy.numpy(), [4.0], rtol=1e-5)

    def test_through_nonlinearity(self):
        x = t([0.7])
        y = pt.tanh(x)
        (g1,) = pt.grad(y, [x], create_graph=True)
        (g2,) = pt.grad(g1, [x])
        th = np.tanh(0.7)
        np.testing.assert_allclose(g2.numpy(),
                                   [-2 * th * (1 - th ** 2)], rtol=1e-4)

    def test_unused_input(self):
        x, z = t([1.0]), t([1.0])
        y = x * 2.0
        with pytest.raises(RuntimeError):
            pt.grad(y, [z], create_graph=True)
        gx, gz = pt.grad(y, [x, z], create_graph=True, allow_unused=True)
        assert gz is None
        np.testing.assert_allclose(gx.numpy(), [2.0], rtol=1e-6)

    def test_grad_outputs_seed(self):
        x = t([3.0])
        y = x * x
        (g,) = pt.grad(y, [x], grad_outputs=[t([2.0], sg=True)],
                       create_graph=True)
        np.testing.assert_allclose(g.numpy(), [12.0], rtol=1e-5)  # 2 * 2x

    def test_gradient_penalty_trains(self):
        # WGAN-GP pattern: loss includes ||dD/dx||^2 — needs create_graph
        pt.seed(0)
        rng = np.random.RandomState(0)
        lin = nn.Linear(4, 1)
        o = pt.optimizer.SGD(learning_rate=0.1,
                             parameters=lin.parameters())
        X = rng.randn(8, 4).astype(np.float32)
        for _ in range(5):
            x = t(X)
            out = lin(x).sum()
            (gx,) = pt.grad(out, [x], create_graph=True)
            gp = (gx * gx).sum()  # ||grad||^2 penalty term
            gp.backward()
            o.step()
            o.clear_grad(set_to_zero=False)
        # d(gp)/d(w): gp = 8 * ||w||^2 -> w shrinks toward 0
        assert np.linalg.norm(lin.weight.numpy()) < 1.0

    def test_first_order_result_matches_plain_grad(self):
        x = t([1.0, 2.0, 3.0])
        w = t([0.5, -1.0, 2.0])
        y = (x * w).sum()
        (g_plain,) = pt.grad(y, [x], retain_graph=True)
        (g_cg,) = pt.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(g_cg.numpy(), g_plain.numpy(), rtol=1e-6)
        assert not g_cg.stop_gradient  # lives on the tape
        assert g_plain.stop_gradient


class TestReviewRegressions:
    def test_grad_outputs_none_entry(self):
        x = t([3.0])
        y = x * x
        (g,) = pt.grad(y, [x], grad_outputs=[None], create_graph=True)
        np.testing.assert_allclose(g.numpy(), [6.0], rtol=1e-5)

    def test_grad_of_output_wrt_itself(self):
        x = t([2.0])
        y = x * x
        (g,) = pt.grad(y, [y], create_graph=True)
        np.testing.assert_allclose(g.numpy(), [1.0], rtol=1e-6)

    def test_freed_graph_raises_in_create_graph(self):
        x = t([2.0])
        y = x * x
        y.backward()  # frees residuals AND replay metadata
        with pytest.raises(RuntimeError, match="freed"):
            pt.grad(y, [x], create_graph=True)

    def test_retain_graph_keeps_replay(self):
        x = t([2.0])
        y = x * x
        y.backward(retain_graph=True)
        (g,) = pt.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(g.numpy(), [4.0], rtol=1e-6)


class TestStopGradientInputs:
    def test_create_graph_respects_stop_gradient(self):
        import numpy as np
        import paddle_tpu as pt
        x = pt.to_tensor(np.float32(3.0), stop_gradient=True)
        w = pt.to_tensor(np.float32(2.0), stop_gradient=False)
        y = x * w
        with pytest.raises(RuntimeError):
            pt.grad(y, [x], create_graph=True)
        (gx,) = pt.grad(y, [x], create_graph=True, allow_unused=True)
        assert gx is None
