"""Semi-auto SPMD Engine tests: the reference's own validation pattern —
multi-device loss parity vs a single-device eager run (SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import P
from paddle_tpu.distributed.auto_parallel import (
    Engine, Strategy, ProcessMesh, shard_tensor, Shard,
)
from paddle_tpu.io import Dataset


class RandomDataset(Dataset):
    def __init__(self, n=64, din=8, dout=4, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, din).astype(np.float32)
        w = rng.randn(din, dout).astype(np.float32)
        self.y = (self.x @ w).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def make_model(seed=0):
    pt.seed(seed)
    return nn.Sequential(
        nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


class TestEngine:
    def test_fit_dp_matches_single_device(self):
        ds = RandomDataset()
        # single-device eager reference
        ref_model = make_model()
        ref_opt = opt.SGD(learning_rate=0.1,
                          parameters=ref_model.parameters())
        mse = nn.MSELoss()
        ref_losses = []
        for i in range(0, 64, 16):
            xb = pt.to_tensor(ds.x[i:i + 16])
            yb = pt.to_tensor(ds.y[i:i + 16])
            loss = mse(ref_model(xb), yb)
            loss.backward()
            ref_opt.step()
            ref_opt.clear_grad()
            ref_losses.append(float(loss.numpy()))

        # Engine over the 8-device mesh, dp-sharded batches
        dist.init_mesh({"dp": 8})
        model = make_model()
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        history = engine.fit(ds, epochs=1, batch_size=16)
        np.testing.assert_allclose(history["loss"], ref_losses, rtol=2e-4,
                                   atol=1e-5)

    def test_fit_with_tp_annotations(self):
        ds = RandomDataset(seed=1)
        mesh = dist.init_mesh({"dp": 4, "mp": 2})
        model = make_model(seed=1)
        # Megatron column/row sharding on the two linears
        shard_tensor(model[0].weight, mesh, spec=P(None, "mp"))
        shard_tensor(model[0].bias, mesh, spec=P("mp"))
        shard_tensor(model[2].weight, mesh, spec=P("mp", None))
        o = opt.Adam(learning_rate=0.05, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.prepare(input_spec=P("dp"))
        history = engine.fit(ds, epochs=3, batch_size=16)
        losses = history["loss"]
        assert losses[-1] < losses[0] * 0.5
        assert np.isfinite(losses).all()

    def test_evaluate_and_predict(self):
        dist.init_mesh({"dp": 8})
        ds = RandomDataset(seed=2)
        model = make_model(seed=2)
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        from paddle_tpu.metric import Accuracy
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.fit(ds, epochs=2, batch_size=16)
        res = engine.evaluate(ds, batch_size=16)
        assert res["loss"] is not None and np.isfinite(res["loss"])
        preds = engine.predict([(ds.x[:16],)], batch_size=16)
        assert preds[0].shape == (16, 4)

    def test_save_load_roundtrip(self, tmp_path):
        dist.init_mesh({"dp": 8})
        ds = RandomDataset(seed=3)
        model = make_model(seed=3)
        o = opt.Adam(learning_rate=0.05, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.fit(ds, epochs=1, batch_size=16)
        path = str(tmp_path / "ckpt")
        engine.save(path)

        model2 = make_model(seed=4)
        o2 = opt.Adam(learning_rate=0.05, parameters=model2.parameters())
        engine2 = Engine(model=model2, loss=nn.MSELoss(), optimizer=o2)
        engine2.load(path)
        x = pt.to_tensor(ds.x[:8])
        np.testing.assert_allclose(model2(x).numpy(), model(x).numpy(),
                                   rtol=1e-6)

    def test_process_mesh_prepare(self):
        pm = ProcessMesh(mesh=[2, 4], dim_names=["x", "y"],
                         process_ids=list(range(8)))
        model = make_model(seed=5)
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.prepare(mesh=pm, input_spec=P("x"))
        ds = RandomDataset(seed=5)
        history = engine.fit(ds, epochs=1, batch_size=16)
        assert np.isfinite(history["loss"]).all()

    def test_strategy_defaults(self):
        s = Strategy()
        assert not s.amp.enable and not s.sharding.enable
        assert s.pipeline.schedule_mode == "1F1B"
