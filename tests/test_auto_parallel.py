"""Semi-auto SPMD Engine tests: the reference's own validation pattern —
multi-device loss parity vs a single-device eager run (SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import P
from paddle_tpu.distributed.auto_parallel import (
    Engine, Strategy, ProcessMesh, shard_tensor, Shard,
)
from paddle_tpu.io import Dataset


class RandomDataset(Dataset):
    def __init__(self, n=64, din=8, dout=4, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, din).astype(np.float32)
        w = rng.randn(din, dout).astype(np.float32)
        self.y = (self.x @ w).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def make_model(seed=0):
    pt.seed(seed)
    return nn.Sequential(
        nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


class TestEngine:
    def test_fit_dp_matches_single_device(self):
        ds = RandomDataset()
        # single-device eager reference
        ref_model = make_model()
        ref_opt = opt.SGD(learning_rate=0.1,
                          parameters=ref_model.parameters())
        mse = nn.MSELoss()
        ref_losses = []
        for i in range(0, 64, 16):
            xb = pt.to_tensor(ds.x[i:i + 16])
            yb = pt.to_tensor(ds.y[i:i + 16])
            loss = mse(ref_model(xb), yb)
            loss.backward()
            ref_opt.step()
            ref_opt.clear_grad()
            ref_losses.append(float(loss.numpy()))

        # Engine over the 8-device mesh, dp-sharded batches
        dist.init_mesh({"dp": 8})
        model = make_model()
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        history = engine.fit(ds, epochs=1, batch_size=16)
        np.testing.assert_allclose(history["loss"], ref_losses, rtol=2e-4,
                                   atol=1e-5)

    def test_fit_with_tp_annotations(self):
        ds = RandomDataset(seed=1)
        mesh = dist.init_mesh({"dp": 4, "mp": 2})
        model = make_model(seed=1)
        # Megatron column/row sharding on the two linears
        shard_tensor(model[0].weight, mesh, spec=P(None, "mp"))
        shard_tensor(model[0].bias, mesh, spec=P("mp"))
        shard_tensor(model[2].weight, mesh, spec=P("mp", None))
        o = opt.Adam(learning_rate=0.05, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.prepare(input_spec=P("dp"))
        history = engine.fit(ds, epochs=3, batch_size=16)
        losses = history["loss"]
        assert losses[-1] < losses[0] * 0.5
        assert np.isfinite(losses).all()

    def test_evaluate_and_predict(self):
        dist.init_mesh({"dp": 8})
        ds = RandomDataset(seed=2)
        model = make_model(seed=2)
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        from paddle_tpu.metric import Accuracy
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.fit(ds, epochs=2, batch_size=16)
        res = engine.evaluate(ds, batch_size=16)
        assert res["loss"] is not None and np.isfinite(res["loss"])
        preds = engine.predict([(ds.x[:16],)], batch_size=16)
        assert preds[0].shape == (16, 4)

    def test_save_load_roundtrip(self, tmp_path):
        dist.init_mesh({"dp": 8})
        ds = RandomDataset(seed=3)
        model = make_model(seed=3)
        o = opt.Adam(learning_rate=0.05, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.fit(ds, epochs=1, batch_size=16)
        path = str(tmp_path / "ckpt")
        engine.save(path)

        model2 = make_model(seed=4)
        o2 = opt.Adam(learning_rate=0.05, parameters=model2.parameters())
        engine2 = Engine(model=model2, loss=nn.MSELoss(), optimizer=o2)
        engine2.load(path)
        x = pt.to_tensor(ds.x[:8])
        np.testing.assert_allclose(model2(x).numpy(), model(x).numpy(),
                                   rtol=1e-6)

    def test_process_mesh_prepare(self):
        pm = ProcessMesh(mesh=[2, 4], dim_names=["x", "y"],
                         process_ids=list(range(8)))
        model = make_model(seed=5)
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.prepare(mesh=pm, input_spec=P("x"))
        ds = RandomDataset(seed=5)
        history = engine.fit(ds, epochs=1, batch_size=16)
        assert np.isfinite(history["loss"]).all()

    def test_strategy_defaults(self):
        s = Strategy()
        assert not s.amp.enable and not s.sharding.enable
        assert s.pipeline.schedule_mode == "1F1B"


class TestPlanner:
    """Automatic parallel-plan search (reference: planner_v2.py:21 Planner
    + tuner/parallel_tuner.py:36 ParallelTuner): enumerate mesh
    factorizations, score with the cost model, install the winner."""

    def test_llama_big_model_prefers_mp_over_pure_dp(self):
        """8B-class Llama: the 16 GB gradient all-reduce makes pure dp
        lose to a dp x mp split (the 'framework helps on a v5p-64' case)."""
        from paddle_tpu.distributed.auto_parallel import Planner, ModelDesc
        from paddle_tpu.models.llama import LlamaConfig

        desc = ModelDesc.from_llama(LlamaConfig())  # 8B
        planner = Planner(desc)
        best = planner.plan(64, (16, 8192))
        assert best.mp > 1, best.describe()
        ranked = planner.ranked(64, (16, 8192))
        pure_dp = next(p for p in ranked
                       if p.mp == 1 and p.zero is None)
        assert pure_dp.cost["seconds"] > best.cost["seconds"]

    def test_llama_tiny_prefers_pure_dp(self):
        """Small model, small vocab: mp's activation all-reduces buy
        nothing — pure dp wins."""
        from paddle_tpu.distributed.auto_parallel import Planner, ModelDesc
        from paddle_tpu.models.llama import LlamaConfig

        desc = ModelDesc.from_llama(LlamaConfig.tiny())
        best = Planner(desc).plan(8, (8, 32))
        assert best.mp == 1 and best.dp == 8, best.describe()

    def test_big_vocab_small_trunk_prefers_mp(self):
        """Embedding-dominated model (big tied vocab, thin trunk): the
        param all-reduce dwarfs compute, mp shards it away."""
        from paddle_tpu.distributed.auto_parallel import Planner, ModelDesc
        from paddle_tpu.models.llama import LlamaConfig

        desc = ModelDesc.from_llama(LlamaConfig(
            vocab_size=128256, hidden_size=1024, intermediate_size=2048,
            num_hidden_layers=2, num_attention_heads=8,
            num_key_value_heads=8, tie_word_embeddings=True))
        best = Planner(desc).plan(8, (8, 256))
        assert best.mp == 8, best.describe()

    def test_mp_respects_model_divisibility(self):
        from paddle_tpu.distributed.auto_parallel import Planner, ModelDesc
        from paddle_tpu.models.llama import LlamaConfig

        desc = ModelDesc.from_llama(LlamaConfig.tiny())  # kv_heads=2
        assert desc.max_mp == 2
        plans = Planner(desc).candidates(8)
        assert {p.mp for p in plans} == {1, 2}

    def test_infeasible_raises_with_guidance(self):
        from paddle_tpu.distributed.auto_parallel import (
            Planner, ModelDesc, Cluster)
        from paddle_tpu.models.llama import LlamaConfig

        desc = ModelDesc.from_llama(LlamaConfig())  # 8B: 16 GB params
        tiny_hbm = Cluster(hbm_capacity=1e9)
        with pytest.raises(ValueError, match="no plan fits"):
            Planner(desc, cluster=tiny_hbm).plan(4, (16, 8192))

    def test_zero_plan_reduces_memory_footprint(self):
        from paddle_tpu.distributed.auto_parallel import Planner, ModelDesc
        from paddle_tpu.models.llama import LlamaConfig

        desc = ModelDesc.from_llama(LlamaConfig())
        planner = Planner(desc)
        ranked = planner.ranked(64, (16, 8192))
        plain = next(p for p in ranked if p.mp == 1 and p.zero is None)
        zero = next(p for p in ranked if p.mp == 1 and p.zero == "p_g_os")
        assert zero.cost["hbm_bytes_per_device"] < \
            0.2 * plain.cost["hbm_bytes_per_device"]

    def _skewed_mlp(self, seed=0):
        """Param-heavy, compute-light: the dp gradient all-reduce is the
        dominant cost, so plan ordering is robustly measurable even on
        the CPU virtual mesh (collectives are real memory traffic)."""
        pt.seed(seed)
        return nn.Sequential(nn.Linear(1024, 4096), nn.ReLU(),
                             nn.Linear(4096, 1024))

    def test_predicted_order_matches_measured_order(self):
        """VERDICT r4 'done' bar: predicted cost ORDER matches measured
        step-time order across >=3 single-axis plan variants."""
        import time
        from paddle_tpu.distributed.auto_parallel import (
            Planner, ModelDesc, ParallelPlan, auto_shard_params)

        desc = ModelDesc.from_model(
            self._skewed_mlp(), flops_per_token=2 * (1024 * 4096 * 2),
            num_layers=2, hidden_size=4096, max_mp=8)
        planner = Planner(desc, allow_zero=False)
        plans = [ParallelPlan({"dp": 8, "mp": 1}),
                 ParallelPlan({"dp": 2, "mp": 4}),
                 ParallelPlan({"dp": 1, "mp": 8})]
        batch = np.random.RandomState(0).randn(16, 1024).astype(np.float32)
        target = np.random.RandomState(1).randn(16, 1024).astype(np.float32)
        mse = nn.MSELoss()

        measured, predicted = {}, {}
        for plan in plans:
            planner.estimate(plan, batch.shape)
            predicted[plan.describe().split()[0]] = plan.cost["seconds"]
            mesh = plan.build_mesh()
            model = self._skewed_mlp()
            auto_shard_params(model, mesh)
            o = opt.SGD(learning_rate=0.0, parameters=model.parameters())
            step = pt.jit.TrainStep(model, lambda m, a, b: mse(m(a), b),
                                    o, mesh=mesh,
                                    input_spec=plan.input_spec)
            xb, yb = pt.to_tensor(batch), pt.to_tensor(target)
            step(xb, yb)  # compile + warm
            times = []
            for _ in range(7):
                t0 = time.perf_counter()
                float(step(xb, yb).numpy())
                times.append(time.perf_counter() - t0)
            measured[plan.describe().split()[0]] = min(times)

        pred_order = sorted(predicted, key=predicted.get)
        meas_order = sorted(measured, key=measured.get)
        # the extremes must agree (middle rank may tie within noise)
        assert pred_order[0] == meas_order[0], (predicted, measured)
        assert pred_order[-1] == meas_order[-1], (predicted, measured)

    def test_engine_auto_end_to_end(self):
        """Engine.prepare(auto=True): the planner picks the mesh from the
        first batch and fit trains through the planned TrainStep."""
        from paddle_tpu.distributed.auto_parallel import ModelDesc

        ds = RandomDataset(n=64, din=8, dout=4, seed=7)
        model = make_model(seed=7)
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        desc = ModelDesc.from_model(
            model, flops_per_token=2 * (8 * 16 + 16 * 4),
            num_layers=2, hidden_size=16)
        engine.prepare(auto=True, model_desc=desc)
        history = engine.fit(ds, epochs=2, batch_size=16)
        assert engine.plan is not None
        assert engine.plan.dp * engine.plan.mp == 8
        losses = history["loss"]
        assert np.isfinite(losses).all() and losses[-1] < losses[0] * 0.7

    def test_engine_auto_matches_single_device(self):
        """Auto-planned training must still be EXACT training: loss curve
        equals the single-device eager run (the plan only moves data)."""
        ds = RandomDataset(seed=9)
        ref_model = make_model(seed=9)
        ref_opt = opt.SGD(learning_rate=0.1,
                          parameters=ref_model.parameters())
        mse = nn.MSELoss()
        ref_losses = []
        for i in range(0, 64, 16):
            loss = mse(ref_model(pt.to_tensor(ds.x[i:i + 16])),
                       pt.to_tensor(ds.y[i:i + 16]))
            loss.backward()
            ref_opt.step()
            ref_opt.clear_grad()
            ref_losses.append(float(loss.numpy()))

        from paddle_tpu.distributed.auto_parallel import ModelDesc
        model = make_model(seed=9)
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.prepare(auto=True, model_desc=ModelDesc.from_model(
            model, flops_per_token=2 * (8 * 16 + 16 * 4), num_layers=2,
            hidden_size=16))
        history = engine.fit(ds, epochs=1, batch_size=16)
        np.testing.assert_allclose(history["loss"], ref_losses, rtol=2e-4,
                                   atol=1e-5)

    def test_plan_submesh_of_visible_devices(self):
        """Planning for fewer devices than visible takes a device-list
        prefix (review regression: build_mesh crashed on sub-meshes)."""
        from paddle_tpu.distributed.auto_parallel import Planner, ModelDesc
        from paddle_tpu.models.llama import LlamaConfig

        desc = ModelDesc.from_llama(LlamaConfig.tiny())
        plan = Planner(desc).plan(4, (8, 32))
        mesh = plan.build_mesh()
        assert mesh.devices.size == 4

    def test_engine_auto_batch_shape_defers_for_generic_model(self):
        """prepare(auto=True, batch_shape=...) on a generic model (no
        desc, no Llama config) defers planning to the first fit batch
        instead of raising (review regression)."""
        ds = RandomDataset(seed=13)
        model = make_model(seed=13)
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=o)
        engine.prepare(auto=True, batch_shape=(16, 8))
        assert engine.plan is None  # deferred, not crashed
        history = engine.fit(ds, epochs=1, batch_size=16)
        assert engine.plan is not None
        assert np.isfinite(history["loss"]).all()

    def test_from_model_measures_flops_via_xla(self):
        """ModelDesc.from_model closes the CostEstimator loop: forward
        FLOPs come from XLA's own cost analysis."""
        from paddle_tpu.distributed.auto_parallel import ModelDesc

        model = make_model(seed=11)
        x = np.zeros((4, 8), np.float32)
        desc = ModelDesc.from_model(model, example_args=[pt.to_tensor(x)])
        # linear stack: ~2*(8*16 + 16*4) flops per row = 384
        per_row = 2 * (8 * 16 + 16 * 4)
        assert 0.5 * per_row <= desc.flops_per_token <= 3 * per_row
        assert desc.param_bytes == (8 * 16 + 16 + 16 * 4 + 4) * 4
