"""nn.functional long tail — torch CPU and analytic oracles."""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


def _t(x):
    return pt.to_tensor(np.asarray(x))


def _np(t):
    return np.asarray(t.data)


def test_pad_modes_vs_torch():
    x = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4)
    for mode, tmode in [("constant", "constant"), ("reflect", "reflect"),
                        ("replicate", "replicate"),
                        ("circular", "circular")]:
        got = _np(F.pad(_t(x), [1, 2, 1, 0], mode=mode, value=9.0))
        want = TF.pad(torch.tensor(x), (1, 2, 1, 0), mode=tmode,
                      value=9.0 if mode == "constant" else 0.0).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6, err_msg=mode)
    # full-rank pad list
    got = _np(F.pad(_t(x), [0, 0, 0, 1, 2, 0, 0, 3]))
    assert got.shape == (1, 3, 5, 7)


def test_zeropad2d():
    x = np.ones((1, 1, 2, 2), np.float32)
    out = _np(F.zeropad2d(_t(x), [1, 1, 2, 0]))
    assert out.shape == (1, 1, 4, 4)
    assert out.sum() == 4.0


def test_diag_embed_vs_torch():
    x = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    np.testing.assert_allclose(_np(F.diag_embed(_t(x))),
                               torch.diag_embed(torch.tensor(x)).numpy(),
                               rtol=1e-6)
    np.testing.assert_allclose(
        _np(F.diag_embed(_t(x), offset=1)),
        torch.diag_embed(torch.tensor(x), offset=1).numpy(), rtol=1e-6)


def test_gumbel_softmax_hard_is_onehot_and_differentiable():
    pt.seed(0)
    x = _t(np.random.RandomState(1).randn(4, 6).astype(np.float32))
    x.stop_gradient = False
    y = F.gumbel_softmax(x, temperature=0.5, hard=True)
    arr = _np(y)
    np.testing.assert_allclose(arr.sum(-1), 1.0, rtol=1e-5)
    assert ((arr == 0) | (np.isclose(arr, 1.0))).all()
    pt.ops.sum(pt.ops.multiply(y, y)).backward()  # straight-through grads
    assert x.grad is not None


def test_affine_grid_and_grid_sample_identity_vs_torch():
    x = np.random.RandomState(2).randn(2, 3, 5, 7).astype(np.float32)
    theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], np.float32),
                    (2, 1, 1))
    grid = F.affine_grid(_t(theta), [2, 3, 5, 7], align_corners=True)
    tgrid = TF.affine_grid(torch.tensor(theta), [2, 3, 5, 7],
                           align_corners=True)
    np.testing.assert_allclose(_np(grid), tgrid.numpy(), rtol=1e-5,
                               atol=1e-6)
    out = F.grid_sample(_t(x), grid, align_corners=True)
    tout = TF.grid_sample(torch.tensor(x), tgrid, align_corners=True)
    np.testing.assert_allclose(_np(out), tout.numpy(), rtol=1e-4,
                               atol=1e-5)
    # identity transform reproduces the input
    np.testing.assert_allclose(_np(out), x, rtol=1e-4, atol=1e-5)


def test_grid_sample_rotation_vs_torch():
    x = np.random.RandomState(3).randn(1, 2, 8, 8).astype(np.float32)
    th = np.array([[[0.0, -1.0, 0.1], [1.0, 0.0, -0.2]]], np.float32)
    for ac in (True, False):
        grid = F.affine_grid(_t(th), [1, 2, 8, 8], align_corners=ac)
        out = F.grid_sample(_t(x), grid, align_corners=ac)
        tg = TF.affine_grid(torch.tensor(th), [1, 2, 8, 8],
                            align_corners=ac)
        tout = TF.grid_sample(torch.tensor(x), tg, align_corners=ac)
        np.testing.assert_allclose(_np(out), tout.numpy(), rtol=1e-3,
                                   atol=1e-4, err_msg=f"ac={ac}")


def test_losses_vs_torch():
    rng = np.random.RandomState(4)
    x = rng.randn(6, 5).astype(np.float32)
    y = (rng.rand(6, 5) > 0.5).astype(np.float32)
    np.testing.assert_allclose(
        float(_np(F.multi_label_soft_margin_loss(_t(x), _t(y)))),
        TF.multilabel_soft_margin_loss(torch.tensor(x),
                                       torch.tensor(y)).item(),
        rtol=1e-5)

    logx = rng.rand(8).astype(np.float32)
    tgt = rng.poisson(2.0, 8).astype(np.float32)
    np.testing.assert_allclose(
        float(_np(F.poisson_nll_loss(_t(logx), _t(tgt)))),
        TF.poisson_nll_loss(torch.tensor(logx),
                            torch.tensor(tgt)).item(), rtol=1e-5)

    mu = rng.randn(8).astype(np.float32)
    var = rng.rand(8).astype(np.float32) + 0.1
    tgt2 = rng.randn(8).astype(np.float32)
    np.testing.assert_allclose(
        float(_np(F.gaussian_nll_loss(_t(mu), _t(tgt2), _t(var)))),
        TF.gaussian_nll_loss(torch.tensor(mu), torch.tensor(tgt2),
                             torch.tensor(var)).item(), rtol=1e-4)


def test_sigmoid_focal_loss_matches_torchvision_formula():
    rng = np.random.RandomState(5)
    x = rng.randn(10).astype(np.float32)
    y = (rng.rand(10) > 0.5).astype(np.float32)
    got = float(_np(F.sigmoid_focal_loss(_t(x), _t(y), reduction="sum")))
    # reference formula oracle
    p = 1 / (1 + np.exp(-x))
    ce = -(y * np.log(p) + (1 - y) * np.log(1 - p))
    p_t = p * y + (1 - p) * (1 - y)
    want = (0.25 * y + 0.75 * (1 - y)) * ce * (1 - p_t) ** 2
    np.testing.assert_allclose(got, want.sum(), rtol=1e-4)


def test_dice_loss_perfect_prediction_is_zero():
    label = np.array([[[0], [1], [2]]], np.int64)  # [1, 3, 1]
    probs = np.eye(3, dtype=np.float32)[label[..., 0]]  # [1, 3, 3]
    loss = float(_np(F.dice_loss(_t(probs), _t(label))))
    assert loss < 1e-4


def test_npair_loss_runs_and_separates():
    a = np.eye(4, dtype=np.float32)
    p = np.eye(4, dtype=np.float32)
    y = np.arange(4, dtype=np.int64)
    aligned = float(_np(F.npair_loss(_t(a), _t(p), _t(y))))
    shuffled = float(_np(F.npair_loss(_t(a), _t(np.roll(p, 1, 0)),
                                      _t(y))))
    assert aligned < shuffled


def test_max_pool_index_unpool_roundtrip_vs_torch():
    rng = np.random.RandomState(6)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    out, idx = F.max_pool2d_with_index(_t(x), 2, stride=2)
    tout, tidx = TF.max_pool2d(torch.tensor(x), 2, stride=2,
                               return_indices=True)
    np.testing.assert_allclose(_np(out), tout.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(_np(idx), tidx.numpy())
    un = F.max_unpool2d(out, idx, 2, stride=2)
    tun = TF.max_unpool2d(tout, tidx, 2, stride=2)
    np.testing.assert_allclose(_np(un), tun.numpy(), rtol=1e-6)


def test_new_layer_classes():
    import paddle_tpu.nn as nn
    rng = np.random.RandomState(7)
    x = _t(rng.randn(2, 3, 8, 8).astype(np.float32))
    out, idx = F.max_pool2d_with_index(x, 2, stride=2)
    un = nn.MaxUnPool2D(2, stride=2)(out, idx)
    assert list(un.shape) == [2, 3, 8, 8]

    mu = _t(rng.randn(8).astype(np.float32))
    var = _t((rng.rand(8) + 0.1).astype(np.float32))
    y = _t(rng.randn(8).astype(np.float32))
    l1 = nn.GaussianNLLLoss()(mu, y, var)
    assert np.isfinite(float(_np(l1)))
    l2 = nn.PoissonNLLLoss()(_t(rng.rand(8).astype(np.float32)),
                             _t(rng.poisson(2.0, 8).astype(np.float32)))
    assert np.isfinite(float(_np(l2)))
    l3 = nn.MultiLabelSoftMarginLoss()(
        _t(rng.randn(4, 5).astype(np.float32)),
        _t((rng.rand(4, 5) > 0.5).astype(np.float32)))
    assert np.isfinite(float(_np(l3)))


def test_unpool_overlapping_windows_write_once():
    # kernel 2 stride 1: the center max is recorded by several windows;
    # unpool must write v, not k*v
    x = np.zeros((1, 1, 3, 3), np.float32)
    x[0, 0, 1, 1] = 5.0
    out, idx = F.max_pool2d_with_index(_t(x), 2, stride=1)
    un = _np(F.max_unpool2d(out, idx, 2, stride=1))
    assert un[0, 0, 1, 1] == 5.0
    with pytest.raises(NotImplementedError):
        F.max_unpool2d(out, idx, 2, stride=1, data_format="NHWC")
    with pytest.raises(NotImplementedError):
        F.grid_sample(_t(x), _t(np.zeros((1, 3, 3, 2), np.float32)),
                      padding_mode="reflection")
