"""Long-tail ops (ops/extras.py) — numpy/scipy oracles."""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as pt


def _t(x):
    return pt.to_tensor(np.asarray(x))


def _np(t):
    return np.asarray(t.data)


def test_kron_trace_mm_tensordot():
    a = np.arange(4, dtype=np.float32).reshape(2, 2)
    b = np.ones((2, 2), np.float32)
    np.testing.assert_allclose(_np(pt.kron(_t(a), _t(b))), np.kron(a, b))
    np.testing.assert_allclose(float(_np(pt.trace(_t(a)))), np.trace(a))
    np.testing.assert_allclose(_np(pt.mm(_t(a), _t(b))), a @ b)
    np.testing.assert_allclose(_np(pt.tensordot(_t(a), _t(b), axes=1)),
                               np.tensordot(a, b, axes=1))


def test_trapezoid_family():
    y = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    np.testing.assert_allclose(float(_np(pt.trapezoid(_t(y), dx=0.5))),
                               np.trapezoid(y, dx=0.5))
    cum = _np(pt.cumulative_trapezoid(_t(y), dx=1.0))
    np.testing.assert_allclose(cum, [1.5, 4.0, 7.5], rtol=1e-6)


def test_angles_and_special():
    x = np.array([0.5, 1.5], np.float32)
    np.testing.assert_allclose(_np(pt.rad2deg(_t(x))), np.rad2deg(x),
                               rtol=1e-6)
    np.testing.assert_allclose(_np(pt.deg2rad(_t(x))), np.deg2rad(x),
                               rtol=1e-6)
    np.testing.assert_allclose(_np(pt.i0(_t(x))), sps.i0(x), rtol=1e-5)
    np.testing.assert_allclose(_np(pt.i1(_t(x))), sps.i1(x), rtol=1e-5)
    a = np.array([0.5, 2.0], np.float32)
    v = np.array([1.5, 0.3], np.float32)
    # torch/paddle convention: igamma = lower P, igammac = upper Q
    import torch
    np.testing.assert_allclose(_np(pt.igamma(_t(a), _t(v))),
                               torch.igamma(torch.tensor(a),
                                            torch.tensor(v)).numpy(),
                               rtol=1e-5)
    np.testing.assert_allclose(_np(pt.igammac(_t(a), _t(v))),
                               torch.igammac(torch.tensor(a),
                                             torch.tensor(v)).numpy(),
                               rtol=1e-5)
    np.testing.assert_allclose(_np(pt.polygamma(_t(x), 1)),
                               sps.polygamma(1, x), rtol=1e-4)


def test_renorm_caps_norms():
    x = np.array([[3.0, 4.0], [0.3, 0.4]], np.float32)
    out = _np(pt.renorm(_t(x), p=2.0, axis=0, max_norm=1.0))
    np.testing.assert_allclose(np.linalg.norm(out[0]), 1.0, rtol=1e-5)
    np.testing.assert_allclose(out[1], x[1], rtol=1e-6)  # under the cap


def test_label_smooth_and_splits():
    onehot = np.eye(4, dtype=np.float32)[:2]
    sm = _np(pt.label_smooth(_t(onehot), epsilon=0.1))
    np.testing.assert_allclose(sm[0, 0], 0.9 + 0.1 / 4, rtol=1e-6)
    np.testing.assert_allclose(sm[0, 1], 0.1 / 4, rtol=1e-6)

    x = np.arange(24, dtype=np.float32).reshape(4, 3, 2)
    parts = pt.vsplit(_t(x), 2)
    assert len(parts) == 2 and list(parts[0].shape) == [2, 3, 2]
    parts = pt.tensor_split(_t(x), [1, 3], axis=0)
    assert [p.shape[0] for p in parts] == [1, 2, 1]
    us = pt.unstack(_t(x), axis=1)
    assert len(us) == 3 and list(us[0].shape) == [4, 2]


def test_matrix_exp_vander_householder_pdist():
    a = np.diag([0.0, np.log(2.0)]).astype(np.float32)
    np.testing.assert_allclose(_np(pt.matrix_exp(_t(a))),
                               np.diag([1.0, 2.0]), rtol=1e-5, atol=1e-6)
    v = np.array([1.0, 2.0, 3.0], np.float32)
    np.testing.assert_allclose(_np(pt.vander(_t(v))), np.vander(v),
                               rtol=1e-6)
    pts = np.array([[0.0, 0.0], [3.0, 4.0], [0.0, 1.0]], np.float32)
    np.testing.assert_allclose(_np(pt.pdist(_t(pts))),
                               [5.0, 1.0, np.sqrt(18.0)], rtol=1e-6)


def test_inplace_clone_index_fill():
    x = _t(np.array([1.0, 5.0, -3.0], np.float32))
    c = pt.clone(x)
    pt.clip_(x, min=0.0, max=2.0)
    np.testing.assert_allclose(_np(x), [1.0, 2.0, 0.0])
    np.testing.assert_allclose(_np(c), [1.0, 5.0, -3.0])  # clone unaffected
    pt.increment(x, 1.0)
    np.testing.assert_allclose(_np(x), [2.0, 3.0, 1.0])
    y = pt.index_fill(_t(np.zeros((3, 2), np.float32)),
                      _t(np.array([0, 2])), 0, 7.0)
    np.testing.assert_allclose(_np(y)[:, 0], [7.0, 0.0, 7.0])
    assert int(_np(pt.rank(_t(np.zeros((2, 3)))))) == 2


def test_quantile_digitize_polar_binomial():
    x = np.array([1.0, np.nan, 3.0, 2.0], np.float32)
    np.testing.assert_allclose(float(_np(pt.nanquantile(_t(x), 0.5))),
                               2.0, rtol=1e-6)
    bins = np.array([0.0, 1.0, 2.0], np.float32)
    np.testing.assert_array_equal(
        _np(pt.digitize(_t(np.array([0.5, 1.5, 5.0], np.float32)),
                        _t(bins))), [1, 2, 3])
    z = _np(pt.polar(_t(np.array([2.0], np.float32)),
                     _t(np.array([np.pi / 2], np.float32))))
    np.testing.assert_allclose([z[0].real, z[0].imag], [0.0, 2.0],
                               atol=1e-6)
    pt.seed(0)
    draws = _np(pt.binomial(_t(np.array([100], np.int64)),
                            _t(np.array([0.3], np.float32))))
    assert 10 < int(draws[0]) < 60


def test_extras_gradients():
    x = _t(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    x.stop_gradient = False
    pt.ops.sum(pt.kron(x, x)).backward()
    assert x.grad is not None
    assert np.all(np.isfinite(_np(x.grad)))


def test_cumulative_trapezoid_with_x_2d():
    y = np.arange(12, dtype=np.float32).reshape(3, 4)
    x = np.cumsum(np.ones((3, 4), np.float32), axis=0)
    out = _np(pt.cumulative_trapezoid(_t(y), _t(x), axis=0))
    import scipy.integrate as si
    want = si.cumulative_trapezoid(y, x, axis=0)
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_binomial_large_count_normal_approx():
    pt.seed(1)
    draws = _np(pt.binomial(_t(np.array([1_000_000], np.int64)),
                            _t(np.array([0.5], np.float32))))
    # mean 500k, std 500: a 6-sigma window
    assert 497_000 < int(draws[0]) < 503_000
