"""paddle.io + checkpoint tests: datasets, samplers, DataLoader collation /
prefetch / workers, save->load->resume reproducing the loss trajectory."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.io as io
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


class RangeSquares(io.Dataset):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        x = np.float32([i])
        return x, x * x


class CountStream(io.IterableDataset):
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.float32([i])


class TestDatasets:
    def test_tensor_dataset(self):
        a = np.arange(12, dtype=np.float32).reshape(6, 2)
        b = np.arange(6, dtype=np.int64)
        ds = io.TensorDataset([a, b])
        assert len(ds) == 6
        x, y = ds[3]
        np.testing.assert_allclose(x, a[3])
        assert y == 3

    def test_concat_and_subset(self):
        d1, d2 = RangeSquares(3), RangeSquares(4)
        cat = io.ConcatDataset([d1, d2])
        assert len(cat) == 7
        np.testing.assert_allclose(cat[5][0], [2.0])  # item 2 of d2
        sub = io.Subset(d1, [2, 0])
        assert len(sub) == 2
        np.testing.assert_allclose(sub[0][0], [2.0])

    def test_compose(self):
        ds = io.ComposeDataset([RangeSquares(4), RangeSquares(4)])
        item = ds[1]
        assert len(item) == 4

    def test_random_split(self):
        parts = io.random_split(RangeSquares(10), [7, 3])
        assert [len(p) for p in parts] == [7, 3]
        all_idx = sorted(parts[0].indices + parts[1].indices)
        assert all_idx == list(range(10))

    def test_random_split_fractions(self):
        parts = io.random_split(RangeSquares(10), [0.5, 0.5])
        assert sorted(len(p) for p in parts) == [5, 5]


class TestSamplers:
    def test_sequence_and_random(self):
        ds = RangeSquares(8)
        assert list(io.SequenceSampler(ds)) == list(range(8))
        got = list(io.RandomSampler(ds))
        assert sorted(got) == list(range(8))

    def test_batch_sampler(self):
        bs = io.BatchSampler(RangeSquares(10), batch_size=3)
        batches = list(bs)
        assert [len(b) for b in batches] == [3, 3, 3, 1]
        assert len(bs) == 4
        bs2 = io.BatchSampler(RangeSquares(10), batch_size=3, drop_last=True)
        assert len(list(bs2)) == 3 == len(bs2)

    def test_weighted(self):
        w = [0.0, 0.0, 1.0]
        s = io.WeightedRandomSampler(w, 20)
        assert set(s) == {2}

    def test_distributed_batch_sampler_partitions(self):
        ds = RangeSquares(16)
        seen = []
        for rank in range(4):
            s = io.DistributedBatchSampler(ds, batch_size=2, num_replicas=4,
                                           rank=rank)
            for b in s:
                seen.extend(b)
        assert sorted(seen) == list(range(16))

    def test_distributed_sampler_pads_uneven(self):
        ds = RangeSquares(10)
        total = sum(len(list(io.DistributedBatchSampler(
            ds, batch_size=2, num_replicas=4, rank=r))) for r in range(4))
        # ceil(10/4)=3 samples per rank → 2 batches each
        assert total == 8


class TestDataLoader:
    def test_basic_collation(self):
        dl = io.DataLoader(RangeSquares(10), batch_size=4)
        batches = list(dl)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == (4, 1)
        np.testing.assert_allclose(y[:, 0], x[:, 0] ** 2)

    def test_shuffle_covers_all(self):
        dl = io.DataLoader(RangeSquares(12), batch_size=4, shuffle=True)
        seen = np.concatenate([b[0][:, 0] for b in dl])
        assert sorted(seen.tolist()) == list(range(12))

    def test_iterable_dataset(self):
        dl = io.DataLoader(CountStream(7), batch_size=3)
        batches = list(dl)
        assert [b.shape[0] for b in batches] == [3, 3, 1]

    def test_iterable_drop_last(self):
        dl = io.DataLoader(CountStream(7), batch_size=3, drop_last=True)
        assert [b.shape[0] for b in dl] == [3, 3]

    def test_num_workers_same_result(self):
        d0 = list(io.DataLoader(RangeSquares(20), batch_size=5))
        d4 = list(io.DataLoader(RangeSquares(20), batch_size=5,
                                num_workers=4))
        for (x0, y0), (x4, y4) in zip(d0, d4):
            np.testing.assert_allclose(x0, x4)
            np.testing.assert_allclose(y0, y4)

    def test_worker_exception_propagates(self):
        class Bad(io.Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                if i == 2:
                    raise RuntimeError("boom")
                return np.float32([i])

        with pytest.raises(RuntimeError, match="boom"):
            list(io.DataLoader(Bad(), batch_size=2))

    def test_dict_collation(self):
        class D(io.Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                return {"x": np.float32([i]), "y": np.int64(i)}

        batch = next(iter(io.DataLoader(D(), batch_size=4)))
        assert set(batch) == {"x", "y"}
        assert batch["x"].shape == (4, 1)

    def test_custom_batch_sampler(self):
        bs = io.BatchSampler(sampler=io.SequenceSampler(RangeSquares(6)),
                             batch_size=2)
        dl = io.DataLoader(RangeSquares(6), batch_sampler=bs)
        assert len(list(dl)) == 3

    def test_feeds_training_loop(self):
        rng = np.random.RandomState(0)
        X = rng.randn(64, 4).astype(np.float32)
        Y = X @ rng.randn(4, 1).astype(np.float32)
        dl = io.DataLoader(io.TensorDataset([X, Y]), batch_size=16,
                           shuffle=True, num_workers=2)
        m = nn.Linear(4, 1)
        o = opt.Adam(learning_rate=0.05, parameters=m.parameters())
        epoch_means = []
        for epoch in range(12):
            losses = []
            for xb, yb in dl:
                loss = nn.MSELoss()(m(pt.to_tensor(xb)), pt.to_tensor(yb))
                loss.backward()
                o.step()
                o.clear_grad()
                losses.append(float(loss.numpy()))
            epoch_means.append(np.mean(losses))
        assert epoch_means[-1] < epoch_means[0] * 0.05, epoch_means


class TestCheckpoint:
    def test_save_load_state_dict(self, tmp_path):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        path = str(tmp_path / "model.pdparams")
        pt.save(m.state_dict(), path)
        loaded = pt.load(path)
        m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        missing, unexpected = m2.set_state_dict(loaded)
        assert not missing and not unexpected
        x = pt.to_tensor(np.random.RandomState(0).randn(3, 4).astype(
            np.float32))
        np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)

    def test_save_load_nested_python(self, tmp_path):
        obj = {"step": 7, "names": ["a", "b"],
               "tensor": pt.to_tensor([1.0, 2.0]),
               "nested": {"lr": 0.1}}
        path = str(tmp_path / "misc.pdopt")
        pt.save(obj, path)
        back = pt.load(path)
        assert back["step"] == 7 and back["nested"]["lr"] == 0.1
        np.testing.assert_allclose(back["tensor"].numpy(), [1.0, 2.0])

    def test_resume_reproduces_trajectory(self, tmp_path):
        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype(np.float32)
        Y = X @ rng.randn(8, 2).astype(np.float32)

        def make():
            pt.seed(4)
            m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
            o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
            return m, o

        def step(m, o):
            loss = nn.MSELoss()(m(pt.to_tensor(X)), pt.to_tensor(Y))
            loss.backward()
            o.step()
            o.clear_grad()
            return float(loss.numpy())

        # run A: 6 steps straight
        mA, oA = make()
        traj_a = [step(mA, oA) for _ in range(6)]

        # run B: 3 steps, checkpoint, fresh objects, resume, 3 more
        mB, oB = make()
        traj_b = [step(mB, oB) for _ in range(3)]
        pt.save(mB.state_dict(), str(tmp_path / "m.pdparams"))
        pt.save(oB.state_dict(), str(tmp_path / "o.pdopt"))

        mC, oC = make()
        mC.set_state_dict(pt.load(str(tmp_path / "m.pdparams")))
        oC.set_state_dict(pt.load(str(tmp_path / "o.pdopt")))
        traj_b += [step(mC, oC) for _ in range(3)]

        np.testing.assert_allclose(traj_b, traj_a, rtol=1e-5)

    def test_atomic_write_no_partial(self, tmp_path):
        path = str(tmp_path / "x.pdparams")
        pt.save({"a": pt.to_tensor([1.0])}, path)
        assert os.path.exists(path)
        assert not os.path.exists(path + ".tmp")

    def test_load_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            pt.load(str(tmp_path / "nope.pdparams"))


class TestReviewRegressions:
    def test_early_break_no_deadlock(self):
        # consumer abandons iteration; producer must unblock and exit
        import threading
        before = threading.active_count()
        for _ in range(5):
            for batch in io.DataLoader(RangeSquares(64), batch_size=2,
                                       prefetch_factor=2):
                break
        import time
        time.sleep(0.5)  # let producers observe stop and exit
        assert threading.active_count() <= before + 1

    def test_batch_size_none_unstacked(self):
        class Pre(io.Dataset):
            def __len__(self):
                return 3

            def __getitem__(self, i):
                return np.zeros((5, 2), np.float32)

        items = list(io.DataLoader(Pre(), batch_size=None))
        assert items[0].shape == (5, 2)  # no spurious leading dim

    def test_generator_reproducible(self):
        ds = RangeSquares(16)
        g1 = np.random.default_rng(42)
        g2 = np.random.default_rng(42)
        s1 = list(io.RandomSampler(ds, generator=g1))
        s2 = list(io.RandomSampler(ds, generator=g2))
        assert s1 == s2
        p1 = [p.indices for p in io.random_split(ds, [8, 8], generator=7)]
        p2 = [p.indices for p in io.random_split(ds, [8, 8], generator=7)]
        assert p1 == p2

    def test_scaler_flag_and_state_fields(self):
        from paddle_tpu import amp
        s = amp.GradScaler(enable=True, use_dynamic_loss_scaling=False)
        assert s.is_use_dynamic_loss_scaling() is False
        s1 = amp.GradScaler(incr_ratio=4.0, incr_every_n_steps=500)
        s2 = amp.GradScaler()
        s2.load_state_dict(s1.state_dict())
        assert s2._incr_ratio == 4.0 and s2._incr_every_n_steps == 500


class TestLoaderThroughput:
    def test_dataloader_keeps_up_with_train_step(self):
        """Round-1 'done' criterion: the loader must not bottleneck the
        bench loop. The bench's measured full-model step is ~170ms for a
        (2, 2048)-token batch on chip; the thread-prefetch loader must
        produce such batches far faster than it consumes them."""
        import time
        import paddle_tpu.io as io

        class TokenDataset(io.Dataset):
            def __len__(self):
                return 512

            def __getitem__(self, i):
                # per-sample work modeled on tokenized text: numpy slice
                # + copy (transforms are numpy-bound by design — that's
                # why threads, not processes, are the right workers here)
                rng = np.random.RandomState(i)
                return rng.randint(0, 128256, (2048,)).astype(np.int64)

        ds = TokenDataset()
        # same-host baseline: raw per-sample cost without the loader, so a
        # loaded CI host scales both sides and the bound stays meaningful
        t0 = time.perf_counter()
        for i in range(64):
            ds[i]
        raw_per_batch = (time.perf_counter() - t0) / 64 * 2

        loader = io.DataLoader(ds, batch_size=2, num_workers=2,
                               shuffle=False)
        it = iter(loader)
        next(it)  # warm the prefetch pipeline
        t0 = time.perf_counter()
        n = 0
        for _ in it:
            n += 1
        dt = (time.perf_counter() - t0) / max(n, 1)
        # the threaded loader must stay within a headroom factor of the raw
        # dataset cost (collation + queue overhead); an absolute ms budget
        # here would flake on loaded shared hardware
        budget = max(raw_per_batch * 6.0, 0.021)
        assert dt < budget, \
            f"loader at {dt*1e3:.1f} ms/batch vs raw dataset " \
            f"{raw_per_batch*1e3:.1f} ms/batch (budget {budget*1e3:.1f} ms)"

    def test_process_workers_beat_threads_on_gil_bound_transform(self):
        """VERDICT r4 item 8 'done' bar: a CPU-heavy (GIL-bound Python)
        transform runs >=2x faster through the subprocess pool than the
        thread pool at num_workers=4 (reference:
        fluid/dataloader/worker.py:264 subprocess workers). The speedup
        needs real cores — on a 1-core CI quota the pool time-slices and
        only the correctness half runs (the reference gates its dist
        tests on capable machines the same way, RUN_TYPE=DIST)."""
        import os
        import time
        import paddle_tpu.io as io

        try:
            cores = len(os.sched_getaffinity(0))
        except AttributeError:
            cores = os.cpu_count() or 1

        class HeavyTransform(io.Dataset):
            def __len__(self):
                return 96

            def __getitem__(self, i):
                # pure-Python arithmetic loop: holds the GIL the whole
                # time (the image-augment shape without the pillow dep)
                acc = 0
                for k in range(40000):
                    acc = (acc + i * k) % 1000003
                return np.array([i, acc], np.int64)

        ds = HeavyTransform()

        def run(**kw):
            loader = io.DataLoader(ds, batch_size=8, shuffle=False, **kw)
            it = iter(loader)
            first = next(it)  # pool spin-up outside the timed region
            t0 = time.perf_counter()
            batches = [first] + list(it)
            dt = time.perf_counter() - t0
            return dt, batches

        t_threads, b1 = run(num_workers=4)
        t_procs, b2 = run(num_workers=4, use_process_workers=True)
        # identical content in identical order, regardless of core count
        for x, y in zip(b1, b2):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        if cores < 3:
            # a 2-core host caps the pool at ~2x which the >2x assert
            # cannot clear net of fork overhead
            pytest.skip(f"speedup needs >=3 cores (host exposes {cores}); "
                        "correctness half verified")
        assert t_procs * 2.0 < t_threads, \
            f"process pool {t_procs*1e3:.0f} ms vs threads " \
            f"{t_threads*1e3:.0f} ms — expected >=2x speedup on "\
            f"{cores} cores"

    def test_process_workers_propagate_errors(self):
        import paddle_tpu.io as io

        class Exploding(io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise KeyError("boom at 5")
                return np.array([i])

        loader = io.DataLoader(Exploding(), batch_size=4,
                               num_workers=2, use_process_workers=True)
        with pytest.raises(RuntimeError, match="worker .* failed"):
            list(loader)

    def test_process_workers_reject_iterable(self):
        import paddle_tpu.io as io

        class Stream(io.IterableDataset):
            def __iter__(self):
                yield np.array([1])

        with pytest.raises(ValueError, match="map-style"):
            io.DataLoader(Stream(), batch_size=2, num_workers=2,
                          use_process_workers=True)

    def test_process_workers_worker_init_fn(self):
        import paddle_tpu.io as io

        class WithInit(io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                import os
                return np.array([int(os.environ.get("WKR_SET", 0))])

        def init_fn(worker_id):
            import os
            os.environ["WKR_SET"] = "7"

        loader = io.DataLoader(WithInit(), batch_size=4, num_workers=2,
                               use_process_workers=True,
                               worker_init_fn=init_fn)
        for batch in loader:
            assert (np.asarray(batch) == 7).all()
