"""paddle.fft / paddle.signal / paddle.linalg / paddle.device — numpy
oracles and gradient checks."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import fft, linalg, signal


def _t(x):
    return pt.to_tensor(np.asarray(x))


# -------------------------------------------------------------------- fft
def test_fft_roundtrip_and_oracle():
    rng = np.random.RandomState(0)
    x = rng.randn(16).astype(np.float32)
    got = np.asarray(fft.fft(_t(x)).data)
    np.testing.assert_allclose(got, np.fft.fft(x), rtol=1e-4, atol=1e-5)
    back = np.asarray(fft.ifft(fft.fft(_t(x))).data)
    np.testing.assert_allclose(back.real, x, rtol=1e-4, atol=1e-5)


def test_rfft_irfft_norms():
    rng = np.random.RandomState(1)
    x = rng.randn(32).astype(np.float32)
    for norm in ("backward", "ortho", "forward"):
        got = np.asarray(fft.rfft(_t(x), norm=norm).data)
        np.testing.assert_allclose(got, np.fft.rfft(x, norm=norm),
                                   rtol=1e-4, atol=1e-5, err_msg=norm)
    y = np.asarray(fft.irfft(fft.rfft(_t(x)), n=32).data)
    np.testing.assert_allclose(y, x, rtol=1e-4, atol=1e-5)
    with pytest.raises(ValueError):
        fft.fft(_t(x), norm="bogus")


def test_fft2_fftn_shift_freq():
    rng = np.random.RandomState(2)
    x = rng.randn(4, 8).astype(np.float32)
    np.testing.assert_allclose(np.asarray(fft.fft2(_t(x)).data),
                               np.fft.fft2(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(fft.fftn(_t(x)).data),
                               np.fft.fftn(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(fft.fftshift(_t(x)).data),
                               np.fft.fftshift(x), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fft.fftfreq(8, 0.5).data),
                               np.fft.fftfreq(8, 0.5), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fft.rfftfreq(8).data),
                               np.fft.rfftfreq(8), rtol=1e-6)


def test_hfft_ihfft():
    rng = np.random.RandomState(3)
    x = (rng.randn(9) + 1j * rng.randn(9)).astype(np.complex64)
    np.testing.assert_allclose(np.asarray(fft.hfft(_t(x)).data),
                               np.fft.hfft(x), rtol=1e-3, atol=1e-4)
    r = rng.randn(16).astype(np.float32)
    np.testing.assert_allclose(np.asarray(fft.ihfft(_t(r)).data),
                               np.fft.ihfft(r), rtol=1e-3, atol=1e-5)


def test_fft_is_differentiable():
    x = _t(np.random.RandomState(4).randn(8).astype(np.float32))
    x.stop_gradient = False
    y = pt.ops.sum(pt.ops.abs(fft.rfft(x)))
    y.backward()
    assert x.grad is not None
    assert np.all(np.isfinite(np.asarray(x.grad.data)))


# ----------------------------------------------------------------- signal
def test_frame_overlap_add_roundtrip():
    x = np.arange(16, dtype=np.float32)
    framed = signal.frame(_t(x), frame_length=4, hop_length=4)
    assert list(framed.shape) == [4, 4]
    # non-overlapping: overlap_add inverts exactly
    back = signal.overlap_add(framed, hop_length=4)
    np.testing.assert_allclose(np.asarray(back.data), x, rtol=1e-6)


def test_frame_overlapping_matches_manual():
    x = np.arange(10, dtype=np.float32)
    framed = np.asarray(signal.frame(_t(x), 4, 2).data)  # [4, n]
    want = np.stack([x[i:i + 4] for i in range(0, 7, 2)], axis=1)
    np.testing.assert_allclose(framed, want, rtol=1e-6)


def test_stft_istft_roundtrip():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 512).astype(np.float32)
    from paddle_tpu.audio.functional import get_window
    win = get_window("hann", 64)
    spec = signal.stft(_t(x), n_fft=64, hop_length=16, window=win)
    assert list(spec.shape)[:2] == [2, 33]  # onesided freq bins
    back = signal.istft(spec, n_fft=64, hop_length=16, window=win,
                        length=512)
    np.testing.assert_allclose(np.asarray(back.data), x, rtol=1e-3,
                               atol=1e-4)


# ----------------------------------------------------------------- linalg
def test_linalg_namespace():
    a = np.random.RandomState(6).randn(4, 4).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(linalg.det(_t(spd)).data),
                               np.linalg.det(spd), rtol=1e-3)
    sol = np.asarray(linalg.solve(_t(spd), _t(np.ones(4, np.float32))).data)
    np.testing.assert_allclose(spd @ sol, np.ones(4), rtol=1e-3, atol=1e-4)
    c = np.asarray(linalg.cholesky(_t(spd)).data)
    np.testing.assert_allclose(c @ c.T, spd, rtol=1e-3, atol=1e-4)


# ----------------------------------------------------------------- device
def test_device_queries():
    assert pt.device_count() >= 1
    assert isinstance(pt.get_device(), str)
    assert pt.set_device("cpu") == "cpu"
    assert pt.get_device() == "cpu"
    assert not pt.is_compiled_with_cuda()
    assert pt.device.cuda.device_count() == 0
    avail = pt.device.get_available_device()
    assert len(avail) == pt.device_count()
    pt.device.synchronize()
    # cuda shims degrade gracefully
    s = pt.device.cuda.current_stream()
    s.synchronize()
    ev = s.record_event()
    assert ev.query()


def test_frame_axis0_matches_reference_layout():
    x = np.arange(8, dtype=np.float32)
    y0 = np.asarray(signal.frame(_t(x), 4, 2, axis=0).data)
    assert y0.shape == (3, 4)
    np.testing.assert_allclose(y0[1], [2, 3, 4, 5], rtol=1e-6)
    back = signal.overlap_add(_t(y0), hop_length=4, axis=0)
    # non-overlapping case roundtrip check on a fresh frame
    f2 = signal.frame(_t(x), 4, 4, axis=0)
    back2 = np.asarray(signal.overlap_add(f2, 4, axis=0).data)
    np.testing.assert_allclose(back2, x, rtol=1e-6)
    with pytest.raises(ValueError):
        signal.frame(_t(x), 4, 2, axis=1)


def test_stft_complex_onesided_raises():
    z = (np.random.randn(256) + 1j * np.random.randn(256)).astype(
        np.complex64)
    with pytest.raises(ValueError, match="onesided"):
        signal.stft(_t(z), n_fft=64)
    spec = signal.stft(_t(z), n_fft=64, onesided=False)
    assert spec.shape[0] == 64
