"""Performance-attribution layer (ISSUE 6): phase-level step attribution
with cost-analysis FLOPs, the bench.py --report regression gate over the
committed BENCH_r0*/MULTICHIP_r0* trajectory, and the docs-vs-registry
metric-family drift check (docs/OBSERVABILITY.md)."""
import importlib.util
import json
import os
import re

import numpy as np
import pytest

import paddle_tpu as pt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    spec = importlib.util.spec_from_file_location(
        "bench_for_tests", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------- attribution table ------------------------------------------

class TestAttribution:
    @pytest.fixture(scope="class")
    def report(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.observability.attribution import \
            attribute_train_step
        cfg = LlamaConfig(
            vocab_size=2048, hidden_size=64, intermediate_size=128,
            num_hidden_layers=1, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            tie_word_embeddings=True)
        pt.seed(0)
        model = LlamaForCausalLM(cfg)
        opt = pt.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
        x = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 32)).astype(np.int64)
        return attribute_train_step(model, opt, x, steps=2, warmup=1,
                                    reps=2, data_time_s=0.003)

    def test_phases_sum_to_step_time(self, report):
        # the acceptance bound: phases explain the measured step within 5%
        assert report.check(0.05), (report.sum_seconds,
                                    report.step_time_s)
        assert set(report.phases) == {
            "data", "embedding_layers", "loss_head", "optimizer",
            "exposed_collective"}

    def test_loss_head_and_optimizer_carry_time(self, report):
        # this geometry's vocab matmul + CE and the AdamW update are
        # real costs: the glue the full-vs-layer MFU gap hides in
        assert report.phases["loss_head"]["seconds"] > 0
        assert report.phases["optimizer"]["seconds"] > 0
        assert report.glue_share() > 0

    def test_flops_from_cost_analysis(self, report):
        fl_layers = report.phases["embedding_layers"]["flops"]
        fl_head = report.phases["loss_head"]["flops"]
        assert fl_layers and fl_layers > 0
        # loss head adds the [T, d]x[d, V] matmul fwd+bwd: ~6*T*d*V
        assert fl_head == pytest.approx(6 * 2 * 32 * 64 * 2048, rel=0.5)
        assert report.total_flops == pytest.approx(fl_layers + fl_head)

    def test_data_phase_passthrough_and_table(self, report):
        assert report.phases["data"]["seconds"] == pytest.approx(0.003)
        table = report.table()
        assert "loss_head" in table and "step(measured)" in table
        doc = report.to_json()
        json.dumps(doc)
        assert doc["phases"]["embedding_layers"]["share_pct"] > 0

    def test_frozen_params_attribution(self):
        # grads must cover only the TRAIN subset: with a frozen backbone
        # chunk, differentiating frozen params too would inflate t_grad
        # and clamp the optimizer phase to ~0
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.observability.attribution import \
            attribute_train_step
        cfg = LlamaConfig(
            vocab_size=256, hidden_size=32, intermediate_size=64,
            num_hidden_layers=1, num_attention_heads=2,
            num_key_value_heads=1, max_position_embeddings=32)
        pt.seed(0)
        model = LlamaForCausalLM(cfg)
        for p in model.model.embed_tokens.parameters():
            p.stop_gradient = True
        trainable = [p for p in model.parameters() if not p.stop_gradient]
        assert len(trainable) < len(list(model.parameters()))
        opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=trainable)
        x = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 16)).astype(np.int64)
        from paddle_tpu.observability.metrics import MetricsRegistry
        rep = attribute_train_step(model, opt, x, steps=2, warmup=1,
                                   reps=1, registry=MetricsRegistry())
        assert rep.check(0.05)
        assert rep.phases["optimizer"]["seconds"] > 0

    def test_registry_gauges_published(self, report):
        from paddle_tpu.observability import get_registry
        g = get_registry().get("attribution_phase_seconds")
        assert g is not None
        assert g.value(phase="loss_head") == pytest.approx(
            report.phases["loss_head"]["seconds"])
        assert get_registry().get("attribution_step_seconds").value() > 0


# ---------------- bench.py --report gate -------------------------------------

class TestBenchReportGate:
    @pytest.fixture(scope="class")
    def bench(self):
        return _bench()

    @pytest.fixture(scope="class")
    def baseline(self, bench):
        name, metrics = bench.report_baseline(REPO)
        assert name and metrics, "committed trajectory must parse"
        return metrics

    def test_baseline_extraction(self, baseline):
        # the committed r05 round: headline MFU + parsed details
        assert baseline["llama_full_train_step_mfu_bf16"] == \
            pytest.approx(63.48)
        assert baseline["step_ms"] == pytest.approx(287.88)

    def test_equal_run_passes(self, bench, baseline, tmp_path):
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps({"parsed": baseline}))
        rc = bench.bench_report(["--report", "--current", str(cur),
                                 "--baseline-dir", REPO])
        assert rc == 0

    @pytest.mark.parametrize("doctor", [
        {"llama_full_train_step_mfu_bf16": 0.9},   # MFU down 10%
        {"step_ms": 1.2},                           # step 20% slower
        {"tokens_per_sec": 0.8},
        {"spread_pct_of_mean": 4.0},                # stability blown
    ])
    def test_doctored_regression_fails(self, bench, baseline, tmp_path,
                                       doctor):
        bad = dict(baseline)
        for k, f in doctor.items():
            bad[k] = bad[k] * f
        cur = tmp_path / "bad.json"
        cur.write_text(json.dumps({"parsed": bad}))
        rc = bench.bench_report(["--report", "--current", str(cur),
                                 "--baseline-dir", REPO])
        assert rc == 1

    def test_improvement_passes(self, bench, baseline, tmp_path):
        good = dict(baseline)
        good["llama_full_train_step_mfu_bf16"] *= 1.1  # faster is fine
        good["step_ms"] *= 0.9
        cur = tmp_path / "good.json"
        cur.write_text(json.dumps({"parsed": good}))
        assert bench.bench_report(["--report", "--current", str(cur),
                                   "--baseline-dir", REPO]) == 0

    def test_tolerance_is_configurable(self, bench, baseline, tmp_path):
        near = dict(baseline)
        near["step_ms"] *= 1.04  # 4% slower
        cur = tmp_path / "near.json"
        cur.write_text(json.dumps({"parsed": near}))
        assert bench.bench_report(
            ["--report", "--current", str(cur), "--baseline-dir", REPO,
             "--tolerance", "5"]) == 0
        assert bench.bench_report(
            ["--report", "--current", str(cur), "--baseline-dir", REPO,
             "--tolerance", "2"]) == 1

    def test_crashed_current_run_fails_gate(self, bench, baseline,
                                            tmp_path):
        # a crashed bench's partial numbers are not proof of no
        # regression — rc != 0 fails regardless of the numbers
        cur = tmp_path / "crashed.json"
        cur.write_text(json.dumps({"rc": 1, "parsed": dict(baseline)}))
        rc = bench.bench_report(["--report", "--current", str(cur),
                                 "--baseline-dir", REPO])
        assert rc == 1

    def test_baseline_skips_metricless_round(self, bench, tmp_path):
        # a newer round with only bookkeeping numerics (rc) or a null
        # headline is not a usable baseline — fall back to the previous
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps({"rc": 0, "parsed": {"step_ms": 100.0}}))
        (tmp_path / "BENCH_r02.json").write_text(
            json.dumps({"rc": 0,
                        "tail": '{"metric": "mfu", "value": null}'}))
        name, base = bench.report_baseline(str(tmp_path))
        assert name == "BENCH_r01.json"
        assert base == {"step_ms": 100.0}

    def test_baseline_orders_rounds_numerically(self, bench, tmp_path):
        # r10 must beat r09 — lexicographic file order would pin the
        # gate to r09 forever once double-digit rounds land
        for n, ms in ((9, 300.0), (10, 200.0)):
            (tmp_path / f"BENCH_r{n:02d}.json").write_text(
                json.dumps({"rc": 0, "parsed": {"step_ms": ms}}))
        (tmp_path / "BENCH_r2.json").write_text(
            json.dumps({"rc": 0, "parsed": {"step_ms": 900.0}}))
        name, base = bench.report_baseline(str(tmp_path))
        assert name == "BENCH_r10.json"
        assert base["step_ms"] == 200.0

    def test_missing_metrics_skip_unless_strict(self, bench, tmp_path):
        cur = tmp_path / "cpu.json"
        cur.write_text(json.dumps(
            {"parsed": {"tokens_per_sec_cpu_smoke": 123.0}}))
        argv = ["--report", "--current", str(cur), "--baseline-dir", REPO]
        assert bench.bench_report(argv) == 0            # visible but soft
        assert bench.bench_report(argv + ["--strict"]) == 1

    def test_multichip_coverage_gate(self, bench, tmp_path):
        with open(os.path.join(REPO, "MULTICHIP_r05.json")) as f:
            mc = json.load(f)
        ok = bench.report_multichip(REPO, mc)
        assert ok["status"] == "ok"
        shrunk = dict(mc)
        shrunk["tail"] = mc["tail"].split("| zero")[0]
        bad = bench.report_multichip(REPO, shrunk)
        assert bad["status"] == "fail"
        assert "zero" in bad["missing_segments"]

    def test_emit_metrics_carries_exposure_families(self, bench,
                                                    tmp_path):
        # acceptance: comm_exposed/overlapped appear in --emit-metrics
        out = tmp_path / "m.json"
        bench.emit_metrics({"x": 1.0}, str(out))
        doc = json.load(open(out))
        assert "comm_exposed_seconds_total" in doc
        assert "comm_overlapped_seconds_total" in doc
        assert "bench_result" in doc


# ---------------- docs <-> registry drift ------------------------------------

#: family-name prefixes owned by this framework's telemetry
_FAMILY_PREFIXES = ("comm_", "train_", "serving_", "ckpt_",
                    "resilience_", "data_", "loader_", "attribution_",
                    "hbm_", "fleet_", "goodput_", "job_", "numerics_",
                    "quantization_")

#: backticked doc tokens that look like families but are not registry
#: metrics: `comm_bytes` is the chrome-trace counter-track name,
#: `comm_scope` an API; the two `serving_*` names are bench.py --serve
#: report-gate headlines (stdout {"metric","value"} lines gated by
#: --report, ISSUE 8) — percentile aggregates of the registry's
#: serving_ttft_seconds / serving_tokens_total families, not families
#: themselves
_NON_FAMILY_DOC_TOKENS = {"comm_bytes", "comm_scope", "comm_event",
                          "comm_totals", "data_time_s",
                          # fleet/goodput non-families (ISSUE 13):
                          # /healthz + heartbeat record fields and
                          # bench.py --chaos output keys, not registry
                          # metric families
                          "job_id", "goodput_fraction", "goodput_bins",
                          "goodput_wall_coverage", "goodput_restart_s",
                          "goodput_incarnations",
                          # goodput bin names / heartbeat record fields
                          # (docs backtick them; they are not families)
                          "data_stall", "ckpt_s", "hbm_in_use",
                          "serving_p99_ttft_seconds",
                          "serving_decode_tokens_per_sec",
                          # bench.py --serve shared-prefix report-gate
                          # headlines (ISSUE 15, docs/SERVING.md) —
                          # stdout {"metric","value"} lines, not
                          # registry families
                          "serving_prefix_cache_hit_rate",
                          "serving_shared_prefix_speedup",
                          "serving_cached_p99_ttft_seconds",
                          "serving_cold_p99_ttft_seconds",
                          # bench.py --serve --replicas N fleet
                          # report-gate headlines (ISSUE 17,
                          # docs/SERVING.md#serving-fleet) — stdout
                          # {"metric","value"} lines, not registry
                          # families
                          "serving_fleet_tokens_per_sec",
                          "serving_fleet_scaling_efficiency",
                          # commplan geometry label (ISSUE 15,
                          # docs/SERVING.md), not a metric family
                          "serving_mp2",
                          # bench.py --audit report-gate headlines
                          # (docs/ANALYSIS.md), not registry families
                          "train_step_allreduce_count",
                          "train_step_undonated_bytes",
                          "train_step_largest_intermediate_bytes",
                          # bench.py --audit runtime-memory headline
                          # (ISSUE 11, docs/ANALYSIS.md) — a report-gate
                          # stdout line, not a registry family
                          "train_step_peak_hbm_bytes",
                          # per-axis comm-plan headline family
                          # (docs/ANALYSIS.md Prong 3) — bench.py
                          # --audit report-gate stdout lines, not
                          # registry families
                          "train_step_comm_bytes_dp",
                          # HBM-ledger owner names (the {owner} label
                          # values of hbm_bytes, docs/OBSERVABILITY.md
                          # #memory), not families themselves
                          "serving_params", "data_prefetch",
                          # bench.py --numerics report-gate headline
                          # (ISSUE 14) — a stdout {"metric","value"}
                          # line, not a registry family
                          "numerics_step_overhead_frac",
                          # bench.py --serve ledger-cost headline
                          # (ISSUE 16) — a report-gate stdout line, not
                          # a registry family
                          "serving_request_ledger_overhead_frac",
                          # bench.py --serve quantization/multi-tenant
                          # headlines (ISSUE 20, docs/QUANTIZATION.md) —
                          # report-gate stdout lines, not registry
                          # families
                          "serving_int8_tokens_per_sec",
                          "serving_kv_quant_max_batch",
                          "serving_adapters_served",
                          # commplan geometry label (ISSUE 20), not a
                          # metric family
                          "serving_mp2_int8"}


def _documented_families():
    """Every metric family name mentioned in docs/*.md + README.md.
    Handles `name{label}` / `name{label="v"}` suffixes and
    `a_{x,y}_b` brace alternations."""
    found = set()
    doc_paths = [os.path.join(REPO, "README.md")] + [
        os.path.join(REPO, "docs", f)
        for f in os.listdir(os.path.join(REPO, "docs"))
        if f.endswith(".md")]
    for path in doc_paths:
        with open(path) as f:
            text = f.read()
        for token in re.findall(r"`([^`\n]+)`", text):
            if not re.match(r"^[a-z][a-z0-9_{},=\"]*$", token):
                continue
            # strip a trailing label-set: family{kind} / family{kind="x"}
            m = re.match(r"^([a-z][a-z0-9_]*)\{[^}]*\}$", token)
            names = [m.group(1)] if m else None
            if names is None and "{" in token:
                # alternation: train_step_{data,compute}_seconds
                m = re.match(r"^([a-z0-9_]*)\{([a-z0-9_,]+)\}([a-z0-9_]*)$",
                             token)
                if not m:
                    continue
                names = [m.group(1) + alt + m.group(3)
                         for alt in m.group(2).split(",")]
            if names is None:
                names = [token]
            for name in names:
                if name.startswith(_FAMILY_PREFIXES) and \
                        name not in _NON_FAMILY_DOC_TOKENS:
                    found.add(name)
    return found


def _registered_families():
    """Instantiate every subsystem's metric accessor, then read the
    default registry — "exists in the registry after importing the
    instrumented modules" per the docs-drift contract."""
    from paddle_tpu.checkpoint.writer import ckpt_metrics
    from paddle_tpu.data.metrics import data_metrics
    from paddle_tpu.io.dataloader import loader_metrics
    from paddle_tpu.observability import StepTimer, get_registry
    from paddle_tpu.observability.attribution import attribution_metrics
    from paddle_tpu.observability.fleet import fleet_metrics
    from paddle_tpu.observability.goodput import goodput_metrics
    from paddle_tpu.observability.memory import memory_metrics
    from paddle_tpu.observability.numerics import numerics_metrics
    from paddle_tpu.observability.requests import request_metrics
    from paddle_tpu.observability.slo import slo_metrics
    from paddle_tpu.resilience.counters import (
        nonfinite_counter, preemption_counter, rollback_counter,
        watchdog_metrics)
    from paddle_tpu.quantization.weight_only import quantization_metrics
    from paddle_tpu.serving.engine import serving_metrics
    from paddle_tpu.serving.fleet.router import router_metrics

    StepTimer(peak=0)
    ckpt_metrics()
    data_metrics()
    loader_metrics()
    attribution_metrics()
    fleet_metrics()
    goodput_metrics()
    memory_metrics()
    numerics_metrics()
    serving_metrics()
    router_metrics()
    quantization_metrics()
    request_metrics()
    slo_metrics()
    nonfinite_counter(), rollback_counter(), preemption_counter()
    watchdog_metrics()
    return {n for n in get_registry().names()
            if n.startswith(_FAMILY_PREFIXES)}


class TestDocsMetricDrift:
    """Doc/metric skew crept across five PRs; this pins both directions."""

    def test_every_registered_family_is_documented(self):
        missing = _registered_families() - _documented_families()
        assert not missing, (
            f"metric families registered in code but absent from "
            f"docs/*.md: {sorted(missing)} — add them to the family "
            f"index in docs/OBSERVABILITY.md")

    def test_every_documented_family_is_registered(self):
        ghosts = _documented_families() - _registered_families()
        assert not ghosts, (
            f"metric families documented in docs/*.md but never "
            f"registered by the instrumented modules: {sorted(ghosts)} — "
            f"fix the doc or the registration")
