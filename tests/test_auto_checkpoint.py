"""Auto-checkpoint epoch range + VisualDL callback + fleet strategy
recompute wiring."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.incubate.checkpoint import TrainEpochRange


def _model(seed=0):
    pt.seed(seed)
    return nn.Linear(4, 2)


def test_train_epoch_range_resumes(tmp_path):
    m = _model()
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = pt.to_tensor(np.ones((4, 4), np.float32))

    # first "process": runs (and checkpoints) epochs 0..2, then dies
    seen = []
    r = TrainEpochRange(3, str(tmp_path), model=m, optimizer=opt,
                        name="job1")
    for epoch in r:
        loss = pt.ops.mean(pt.ops.square(m(x)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        seen.append(epoch)
    assert seen == [0, 1, 2]
    w_after_crash = np.asarray(m.weight.data).copy()

    # fresh process: a NEW model restores weights and resumes at epoch 3
    m2 = _model(seed=99)  # different init — must be overwritten by restore
    opt2 = pt.optimizer.SGD(learning_rate=0.1, parameters=m2.parameters())
    r2 = TrainEpochRange(5, str(tmp_path), model=m2, optimizer=opt2,
                         name="job1")
    assert r2.restored_from == 3
    np.testing.assert_allclose(np.asarray(m2.weight.data), w_after_crash,
                               rtol=1e-6)
    resumed = list(r2)
    assert resumed == [3, 4]
    meta = json.load(open(os.path.join(str(tmp_path), "job1",
                                       "meta.json")))
    assert meta["epoch"] == 4


def test_train_epoch_range_fresh_job(tmp_path):
    r = TrainEpochRange(3, str(tmp_path), name="job_fresh")
    assert r.restored_from == 0
    assert list(r) == [0, 1, 2]


def test_visualdl_callback_writes_jsonl(tmp_path):
    from paddle_tpu.callbacks import VisualDL
    cb = VisualDL(log_dir=str(tmp_path))
    cb.on_epoch_begin(0)
    cb.on_train_batch_end(0, {"loss": 1.5})
    cb.on_train_batch_end(1, {"loss": 1.2, "note": "skip-me-not-scalar"})
    cb.on_eval_end({"acc": 0.8})
    files = [f for f in os.listdir(tmp_path) if f.endswith(".jsonl")]
    assert len(files) == 1
    rows = [json.loads(l) for l in
            open(os.path.join(tmp_path, files[0]))]
    tags = {r["tag"] for r in rows}
    assert "train/loss" in tags and "eval/acc" in tags


def test_fleet_strategy_recompute_flag_enables_model_recompute():
    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    strategy = fleet.DistributedStrategy()
    strategy.recompute = True
    strategy.hybrid_configs["dp_degree"] = 8  # conftest's 8-device mesh
    fleet.init(strategy=strategy)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    assert not model.cfg.recompute
    wrapped = fleet.distributed_model(model)
    # pure-DP mesh: wrapped in DataParallel; recompute was enabled on the
    # inner model before wrapping
    assert model.cfg.recompute
    assert wrapped._layers is model
