"""Sequence-parallel tests: ring attention parity with single-device sdpa
(causal and full), Ulysses parity, gradient flow, long-sequence memory
scaling property (per-rank score block is (S/n)^2)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn.functional as F


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32), stop_gradient=False)


@pytest.fixture()
def mesh_sp8():
    return dist.init_mesh({"sp": 8})


def _qkv(B=2, S=64, H=4, D=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(B, S, H, D).astype(np.float32)
    return mk(), mk(), mk()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_sdpa(self, mesh_sp8, causal):
        q, k, v = _qkv()
        got = fleet.ring_attention(t(q), t(k), t(v), causal=causal)
        ref = F.scaled_dot_product_attention(t(q), t(k), t(v),
                                             is_causal=causal)
        np.testing.assert_allclose(got.numpy(), ref.numpy(), rtol=2e-4,
                                   atol=2e-5)

    def test_gradients_flow(self, mesh_sp8):
        q, k, v = _qkv()
        qt, kt, vt = t(q), t(k), t(v)
        out = fleet.ring_attention(qt, kt, vt, causal=True)
        out.mean().backward()
        for x in (qt, kt, vt):
            assert x.grad is not None
            assert np.isfinite(x.grad.numpy()).all()

    def test_grad_matches_sdpa(self, mesh_sp8):
        q, k, v = _qkv(S=32)
        q1, k1, v1 = t(q), t(k), t(v)
        fleet.ring_attention(q1, k1, v1, causal=True).mean().backward()
        q2, k2, v2 = t(q), t(k), t(v)
        F.scaled_dot_product_attention(q2, k2, v2,
                                       is_causal=True).mean().backward()
        np.testing.assert_allclose(q1.grad.numpy(), q2.grad.numpy(),
                                   rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(k1.grad.numpy(), k2.grad.numpy(),
                                   rtol=1e-3, atol=1e-5)

    def test_requires_sp_axis(self):
        dist.init_mesh({"dp": 8})
        q, k, v = _qkv()
        with pytest.raises(RuntimeError):
            fleet.ring_attention(t(q), t(k), t(v))

    def test_scatter_gather_roundtrip(self, mesh_sp8):
        x = t(np.random.RandomState(0).randn(2, 64, 8))
        s = fleet.scatter_sequence(x)
        g = fleet.gather_sequence(s)
        np.testing.assert_allclose(g.numpy(), x.numpy(), rtol=1e-6)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_sdpa(self, mesh_sp8, causal):
        q, k, v = _qkv(H=8)  # heads divisible by sp=8
        got = fleet.ulysses_attention(t(q), t(k), t(v), causal=causal)
        ref = F.scaled_dot_product_attention(t(q), t(k), t(v),
                                             is_causal=causal)
        np.testing.assert_allclose(got.numpy(), ref.numpy(), rtol=2e-4,
                                   atol=2e-5)


class TestRingFlash:
    """Ring attention with the Pallas flash kernel per block (interpret
    mode on CPU): O(block) VMEM per ring step and the ring-flash backward
    (per-block kernel bwd against the GLOBAL lse)."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_sdpa_fwd_and_grads(self, causal):
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        import paddle_tpu.distributed.fleet as fleet
        import paddle_tpu.nn as nn

        dist.init_mesh({"sp": 8})
        rng = np.random.RandomState(0)
        B, S, H, D = 2, 32, 2, 8
        rng_state = [rng.randn(B, S, H, D) for _ in range(3)]
        q, k, v = (pt.to_tensor(a.astype(np.float32), stop_gradient=False)
                   for a in rng_state)
        out = fleet.ring_attention(q, k, v, causal=causal, use_flash=True)
        ref = nn.functional.scaled_dot_product_attention(
            q, k, v, is_causal=causal)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=2e-5)

        out.mean().backward()
        q2, k2, v2 = (pt.to_tensor(a.astype(np.float32),
                                   stop_gradient=False)
                      for a in rng_state)
        nn.functional.scaled_dot_product_attention(
            q2, k2, v2, is_causal=causal).mean().backward()
        for g, r in [(q.grad, q2.grad), (k.grad, k2.grad),
                     (v.grad, v2.grad)]:
            np.testing.assert_allclose(g.numpy(), r.numpy(), atol=2e-5)

    def test_flash_and_jnp_paths_agree(self):
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        import paddle_tpu.distributed.fleet as fleet

        dist.init_mesh({"sp": 8})
        rng = np.random.RandomState(1)
        B, S, H, D = 1, 16, 2, 8
        q = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32))
        k = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32))
        v = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32))
        a = fleet.ring_attention(q, k, v, causal=True, use_flash=True)
        b = fleet.ring_attention(q, k, v, causal=True, use_flash=False)
        np.testing.assert_allclose(a.numpy(), b.numpy(), atol=2e-5)
