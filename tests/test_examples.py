"""Every examples/ script must run end-to-end (smoke contract)."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# glob, not a hardcoded list: every future example joins the contract
# (underscore-prefixed files are shared helpers, not demos)
EXAMPLES = sorted(f for f in os.listdir(os.path.join(ROOT, "examples"))
                  if f.endswith(".py") and not f.startswith("_"))


@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs(script):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = ROOT
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script)],
        capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout}\n{r.stderr}"
