"""paddle.static Program/Executor (tape-replay) + enforce machinery."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import static
from paddle_tpu.core import enforce as E


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def test_static_linear_regression_trains(static_mode):
    """The classic static workflow: data -> fc -> loss -> minimize ->
    Executor.run loop. Teacher data: y = x @ w_true."""
    rng = np.random.RandomState(0)
    w_true = rng.randn(13, 1).astype(np.float32)

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data(name="x", shape=[None, 13], dtype="float32")
        y = static.data(name="y", shape=[None, 1], dtype="float32")
        pred = static.nn.fc(x, size=1)
        loss = pt.ops.mean(pt.ops.square(pt.ops.subtract(pred, y)))
        opt = pt.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)  # params auto-collected from the loss graph

    exe = static.Executor()
    exe.run(static.default_startup_program())
    first = last = None
    for step in range(60):
        xb = rng.randn(32, 13).astype(np.float32)
        yb = xb @ w_true
        (lv,) = exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss])
        first = first if first is not None else float(lv)
        last = float(lv)
    assert last < first * 0.05, (first, last)


def test_static_fetch_without_optimizer(static_mode):
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data(name="x", shape=[None, 4], dtype="float32")
        out = pt.ops.sum(pt.ops.multiply(x, x))
    exe = static.Executor()
    xv = np.arange(8, dtype=np.float32).reshape(2, 4)
    (got,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(got, (xv * xv).sum(), rtol=1e-6)


def test_static_missing_feed_raises(static_mode):
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data(name="x", shape=[2, 2], dtype="float32")
        out = pt.ops.sum(x)
    with pytest.raises(ValueError, match="missing feed"):
        static.Executor().run(prog, feed={}, fetch_list=[out])


def test_mode_toggles():
    assert pt.in_dynamic_mode()
    pt.enable_static()
    assert not pt.in_dynamic_mode()
    pt.disable_static()
    assert pt.in_dynamic_mode()


# ------------------------------------------------------------------ enforce
def test_enforce_helpers():
    E.enforce(True)
    E.enforce_eq(3, 3)
    E.enforce_ge(3, 3)
    E.enforce_not_none(0) == 0  # 0 is not None
    with pytest.raises(E.EnforceNotMet, match="Expected 3 == 4"):
        E.enforce_eq(3, 4, "ranks must match")
    with pytest.raises(E.EnforceNotMet, match="ranks must match"):
        E.enforce_eq(3, 4, "ranks must match")
    with pytest.raises(E.EnforceNotMet):
        E.enforce_not_none(None)


def test_enforce_shape_match_wildcards():
    E.enforce_shape_match([-1, 4], [8, 4])
    E.enforce_shape_match([None, 4], [8, 4])
    with pytest.raises(E.EnforceNotMet):
        E.enforce_shape_match([3, 4], [8, 4])
    with pytest.raises(E.EnforceNotMet):
        E.enforce_shape_match([3, 4], [3, 4, 5])


def test_enforce_error_carries_stack():
    try:
        E.enforce(False, "boom")
    except E.EnforceNotMet as e:
        assert "Error Message Summary" in str(e)
        assert "test_static_enforce" in e.stack


def test_static_eval_then_minimize_trains(static_mode):
    """Attaching an optimizer after an eval run must not reuse the eval
    closure (regression: cache key now includes the optimizer)."""
    rng = np.random.RandomState(1)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "float32")
        pred = static.nn.fc(x, size=1)
        loss = pt.ops.mean(pt.ops.square(pt.ops.subtract(pred, y)))
    exe = static.Executor()
    xb = rng.randn(16, 4).astype(np.float32)
    yb = (xb.sum(1, keepdims=True)).astype(np.float32)
    (l0,) = exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss])
    with static.program_guard(prog):
        pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    last = None
    for _ in range(40):
        (lv,) = exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss])
        last = float(lv)
    assert last < float(l0) * 0.5, (float(l0), last)


def test_static_fc_rank3_dynamic_batch(static_mode):
    """fc must not bake the dummy batch size into its flatten reshape."""
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 3, 4], "float32")
        out = static.nn.fc(x, size=2)
    exe = static.Executor()
    xv = np.random.RandomState(2).randn(8, 3, 4).astype(np.float32)
    (got,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
    assert got.shape == (8, 2)
