"""paddle.static Program/Executor (tape-replay) + enforce machinery."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import static
from paddle_tpu.core import enforce as E


@pytest.fixture
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def test_static_linear_regression_trains(static_mode):
    """The classic static workflow: data -> fc -> loss -> minimize ->
    Executor.run loop. Teacher data: y = x @ w_true."""
    rng = np.random.RandomState(0)
    w_true = rng.randn(13, 1).astype(np.float32)

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data(name="x", shape=[None, 13], dtype="float32")
        y = static.data(name="y", shape=[None, 1], dtype="float32")
        pred = static.nn.fc(x, size=1)
        loss = pt.ops.mean(pt.ops.square(pt.ops.subtract(pred, y)))
        opt = pt.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)  # params auto-collected from the loss graph

    exe = static.Executor()
    exe.run(static.default_startup_program())
    first = last = None
    for step in range(60):
        xb = rng.randn(32, 13).astype(np.float32)
        yb = xb @ w_true
        (lv,) = exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss])
        first = first if first is not None else float(lv)
        last = float(lv)
    assert last < first * 0.05, (first, last)


def test_static_fetch_without_optimizer(static_mode):
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data(name="x", shape=[None, 4], dtype="float32")
        out = pt.ops.sum(pt.ops.multiply(x, x))
    exe = static.Executor()
    xv = np.arange(8, dtype=np.float32).reshape(2, 4)
    (got,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(got, (xv * xv).sum(), rtol=1e-6)


def test_static_missing_feed_raises(static_mode):
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data(name="x", shape=[2, 2], dtype="float32")
        out = pt.ops.sum(x)
    with pytest.raises(ValueError, match="missing feed"):
        static.Executor().run(prog, feed={}, fetch_list=[out])


def test_mode_toggles():
    assert pt.in_dynamic_mode()
    pt.enable_static()
    assert not pt.in_dynamic_mode()
    pt.disable_static()
    assert pt.in_dynamic_mode()


# ------------------------------------------------------------------ enforce
def test_enforce_helpers():
    E.enforce(True)
    E.enforce_eq(3, 3)
    E.enforce_ge(3, 3)
    E.enforce_not_none(0) == 0  # 0 is not None
    with pytest.raises(E.EnforceNotMet, match="Expected 3 == 4"):
        E.enforce_eq(3, 4, "ranks must match")
    with pytest.raises(E.EnforceNotMet, match="ranks must match"):
        E.enforce_eq(3, 4, "ranks must match")
    with pytest.raises(E.EnforceNotMet):
        E.enforce_not_none(None)


def test_enforce_shape_match_wildcards():
    E.enforce_shape_match([-1, 4], [8, 4])
    E.enforce_shape_match([None, 4], [8, 4])
    with pytest.raises(E.EnforceNotMet):
        E.enforce_shape_match([3, 4], [8, 4])
    with pytest.raises(E.EnforceNotMet):
        E.enforce_shape_match([3, 4], [3, 4, 5])


def test_enforce_error_carries_stack():
    try:
        E.enforce(False, "boom")
    except E.EnforceNotMet as e:
        assert "Error Message Summary" in str(e)
        assert "test_static_enforce" in e.stack
