"""Fused multi-tensor optimizer + bucketed dp gradient collectives.

Covers ISSUE 7's acceptance matrix: fused-vs-eager parity (bit-exact at the
update-rule level where the same gradients are fed; tight-tolerance end to
end, where XLA's differing backward fusion injects ~1-ulp gradient noise —
docs/PERFORMANCE.md#numerics), per-parameter ``state_dict`` preservation and
CheckpointManager round trips across the fused/eager boundary, compile-once
guards, HLO-verified bucketed (not per-param, not monolithic) dp gradient
reductions with the env-tunable bucket size, the flat-state flush protocol,
the XLA tuning flag gate, and the bench report-gate wiring.
"""
import re

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.jit.fused_update import (build_flat_states, build_layout,
                                         fused_clip_and_update,
                                         split_flat_states)
from paddle_tpu.jit.bucketing import plan_comm_buckets


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32))


def _mlp(seed=0):
    pt.seed(seed)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def _data():
    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    W = rng.randn(8, 4).astype(np.float32)
    return X, X @ W


def _loss(mm, a, b):
    return nn.MSELoss()(mm(a), b)


OPTIMIZERS = {
    "adamw": lambda ps, **kw: opt.AdamW(learning_rate=0.01, parameters=ps,
                                        **kw),
    "adam": lambda ps, **kw: opt.Adam(learning_rate=0.01, parameters=ps,
                                      **kw),
    "sgd": lambda ps, **kw: opt.SGD(learning_rate=0.05, parameters=ps,
                                    **kw),
    "momentum": lambda ps, **kw: opt.Momentum(
        learning_rate=0.01, momentum=0.9, parameters=ps, **kw),
}


def _run_pair(make_opt, fused, steps=5, seed=7, bf16=False):
    X, Y = _data()
    pt.seed(seed)
    m = _mlp(seed)
    if bf16:
        m.bfloat16()
    o = make_opt(m.parameters())
    s = pt.jit.TrainStep(m, _loss, o, fused=fused)
    losses = [float(s(t(X), t(Y)).numpy()) for _ in range(steps)]
    return m, o, losses


def _assert_state_dicts_match(sd1, sd2, rtol=0.0, atol=0.0):
    assert set(sd1) == set(sd2)
    for k in sd2:
        a, b = sd1[k], sd2[k]
        if not hasattr(b, "data"):
            assert a == b, k
            continue
        a, b = np.asarray(a.data), np.asarray(b.data)
        assert a.dtype == b.dtype and a.shape == b.shape, k
        if rtol == 0.0 and atol == 0.0:
            np.testing.assert_array_equal(a, b, err_msg=k)
        else:
            np.testing.assert_allclose(
                a.astype(np.float64), b.astype(np.float64), rtol=rtol,
                atol=atol, err_msg=k)


class TestRuleLevelBitExact:
    """Same gradients in -> the fused bucket update and the per-param loop
    produce bitwise identical parameters and accumulators (f32, no clip:
    the update math itself reorders nothing)."""

    @pytest.mark.parametrize("name", sorted(OPTIMIZERS))
    def test_fused_update_bitwise(self, name):
        m = _mlp()
        o = OPTIMIZERS[name](m.parameters())
        params = dict(m.named_parameters())
        names = list(params)
        rng = np.random.RandomState(3)
        grads = {n: np.asarray(
            rng.randn(*params[n].shape).astype(np.float32))
            for n in names}
        import jax.numpy as jnp
        grads = {n: jnp.asarray(g) for n, g in grads.items()}
        layout = build_layout(o, params, names)
        assert layout is not None and layout.buckets and not layout.residue
        flats = build_flat_states(o, layout, params)
        train = {n: params[n].data for n in names}
        lrs = [np.float32(o.get_lr())]

        new_train, new_flats, _, _ = fused_clip_and_update(
            o, layout, train, grads, flats, lrs, lambda g: g)
        per = split_flat_states(layout, new_flats)

        # reference: the optimizer's own rule, one param at a time
        for b, dicts in zip(layout.buckets, per):
            for n, fused_state in zip(b.names, dicts):
                p = params[n]
                st = o._ensure_state(p)
                ref_p, ref_s = o._update(
                    train[n], grads[n], st, np.float32(o.get_lr()),
                    weight_decay=b.decay_coeff, **b.kwargs)
                np.testing.assert_array_equal(
                    np.asarray(new_train[n]), np.asarray(ref_p), err_msg=n)
                for k, v in ref_s.items():
                    np.testing.assert_array_equal(
                        np.asarray(fused_state[k]), np.asarray(v),
                        err_msg=f"{n}.{k}")


class TestTrainStepParity:
    """End-to-end fused-vs-looped TrainStep: identical state layout, and
    values equal to float ulp noise (XLA compiles two different programs;
    their backward reductions fuse differently)."""

    TOL = dict(rtol=5e-6, atol=1e-7)

    @pytest.mark.parametrize("name", sorted(OPTIMIZERS))
    def test_plain_f32(self, name):
        m1, o1, l1 = _run_pair(OPTIMIZERS[name], fused=True)
        m2, o2, l2 = _run_pair(OPTIMIZERS[name], fused=False)
        np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-7)
        for a, b in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(a.numpy(), b.numpy(), **self.TOL)
        _assert_state_dicts_match(o1.state_dict(), o2.state_dict(),
                                  rtol=1e-5, atol=1e-7)

    def test_global_norm_clip(self):
        mk = lambda ps: opt.AdamW(learning_rate=0.01, parameters=ps,
                                  grad_clip=nn.ClipGradByGlobalNorm(0.5))
        m1, o1, _ = _run_pair(mk, fused=True)
        m2, o2, _ = _run_pair(mk, fused=False)
        for a, b in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(a.numpy(), b.numpy(), **self.TOL)

    def test_clip_by_value_and_by_norm(self):
        for clip in (nn.ClipGradByValue(0.01),
                     nn.ClipGradByNorm(0.05)):  # per-tensor: pre-clip path
            mk = lambda ps: opt.SGD(learning_rate=0.05, parameters=ps,
                                    grad_clip=clip)
            m1, _, _ = _run_pair(mk, fused=True)
            m2, _, _ = _run_pair(mk, fused=False)
            for a, b in zip(m1.parameters(), m2.parameters()):
                np.testing.assert_allclose(a.numpy(), b.numpy(), **self.TOL)

    def test_master_weights_bf16(self):
        mk = lambda ps: opt.AdamW(learning_rate=0.01, parameters=ps,
                                  multi_precision=True)
        m1, o1, _ = _run_pair(mk, fused=True, bf16=True)
        m2, o2, _ = _run_pair(mk, fused=False, bf16=True)
        for a, b in zip(m1.parameters(), m2.parameters()):
            assert str(a.data.dtype) == "bfloat16"
            np.testing.assert_allclose(
                a.numpy().astype(np.float32), b.numpy().astype(np.float32),
                rtol=2e-2, atol=1e-3)  # bf16 tolerance (issue acceptance)
        sd1, sd2 = o1.state_dict(), o2.state_dict()
        assert any(k.endswith(".master_weight") for k in sd1)
        _assert_state_dicts_match(sd1, sd2, rtol=1e-4, atol=1e-5)

    def test_param_groups_per_group_lr_and_decay(self):
        X, Y = _data()

        def mk(m):
            sched = opt.lr.StepDecay(0.5, step_size=1, gamma=0.1)
            return opt.AdamW(learning_rate=0.01, parameters=[
                {"params": [m[0].weight, m[0].bias], "weight_decay": 0.1},
                {"params": [m[2].weight, m[2].bias],
                 "learning_rate": sched, "weight_decay": 0.0},
            ])

        outs = []
        for fused in (True, False):
            pt.seed(7)
            m = _mlp(7)
            o = mk(m)
            s = pt.jit.TrainStep(m, _loss, o, fused=fused)
            for _ in range(4):
                s(t(X), t(Y))
            if fused:
                # the two groups must not share a bucket (distinct
                # group lr/decay feed the fused kernel as constants)
                assert len(s._layout.buckets) == 2
            outs.append(m)
        for a, b in zip(outs[0].parameters(), outs[1].parameters()):
            np.testing.assert_allclose(a.numpy(), b.numpy(), **self.TOL)

    def test_adamw_lr_ratio_and_decay_mask(self):
        """Per-param host-resolved hooks (the old opt._cur_param side
        channel): lr_ratio and apply_decay_param_fun split buckets and
        match the eager loop."""
        X, Y = _data()

        def mk(m):
            names_no_decay = {m[0].bias.name, m[2].bias.name}
            return opt.AdamW(
                learning_rate=0.01, parameters=m.parameters(),
                weight_decay=0.1,
                lr_ratio=lambda p: 0.1 if p.ndim == 1 else 1.0,
                apply_decay_param_fun=lambda n: n not in names_no_decay)

        outs = []
        for fused in (True, False):
            pt.seed(7)
            m = _mlp(7)
            s = pt.jit.TrainStep(m, _loss, mk(m), fused=fused)
            for _ in range(3):
                s(t(X), t(Y))
            if fused:
                assert len(s._layout.buckets) >= 2  # ratio/mask split
            outs.append(m)
        for a, b in zip(outs[0].parameters(), outs[1].parameters()):
            np.testing.assert_allclose(a.numpy(), b.numpy(), **self.TOL)

    def test_frozen_subset_stays_frozen(self):
        X, Y = _data()
        pt.seed(3)
        m = _mlp(3)
        head = [m[2].weight, m[2].bias]
        o = opt.AdamW(learning_rate=0.05, parameters=head)
        s = pt.jit.TrainStep(m, _loss, o, fused=True)
        backbone_before = m[0].weight.numpy().copy()
        head_before = m[2].weight.numpy().copy()
        s(t(X), t(Y))
        assert s._layout is not None and s._layout.buckets
        np.testing.assert_array_equal(m[0].weight.numpy(), backbone_before)
        assert not np.allclose(m[2].weight.numpy(), head_before)

    def test_lamb_exclude_fn_without_cur_param(self):
        """Lamb is unfusable (trust-ratio norms) but must keep its
        per-param decay exclusion through the host-resolved kwargs hook —
        the traced body no longer writes opt._cur_param."""
        X, Y = _data()
        m = _mlp(5)
        bias_ids = {id(m[0].bias), id(m[2].bias)}
        o = opt.Lamb(learning_rate=0.01, lamb_weight_decay=0.5,
                     parameters=m.parameters(),
                     exclude_from_weight_decay_fn=lambda p: id(p) in
                     bias_ids)
        s = pt.jit.TrainStep(m, _loss, o)
        assert s is not None
        s(t(X), t(Y))
        assert s._layout is None  # Lamb never fuses
        assert not hasattr(o, "_cur_param")
        kw = o._param_group_kwargs(m[0].bias, o._param_groups[0])
        assert kw["lamb_weight_decay"] == 0.0
        kw = o._param_group_kwargs(m[0].weight, o._param_groups[0])
        assert kw["lamb_weight_decay"] == 0.5


class TestCompileOnceAndLayoutStability:
    def test_scheduler_tick_no_retrace_no_relayout(self, monkeypatch):
        import paddle_tpu.jit.train_step as ts_mod
        builds = []
        orig = ts_mod.build_layout
        monkeypatch.setattr(ts_mod, "build_layout",
                            lambda *a, **k: builds.append(1) or orig(*a, **k))
        X, Y = _data()
        m = _mlp()
        sched = opt.lr.StepDecay(0.1, step_size=1, gamma=0.5)
        o = opt.AdamW(learning_rate=sched, parameters=m.parameters())
        s = pt.jit.TrainStep(m, _loss, o, fused=True)
        for _ in range(4):
            s(t(X), t(Y))
            sched.step()
        assert len(s._cache) == 1          # LR tick never retraces
        assert len(builds) == 1            # bucket layout built once
        assert len(s._plans) == 1

    def test_flat_state_not_rebuilt_across_steps(self, monkeypatch):
        import paddle_tpu.jit.train_step as ts_mod
        rebuilds = []
        orig = ts_mod.build_flat_states
        monkeypatch.setattr(
            ts_mod, "build_flat_states",
            lambda *a, **k: rebuilds.append(1) or orig(*a, **k))
        X, Y = _data()
        m = _mlp()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        s = pt.jit.TrainStep(m, _loss, o, fused=True)
        for _ in range(4):
            s(t(X), t(Y))
        assert len(rebuilds) == 1  # donated flats round-trip, no concat


class TestFlushProtocol:
    def test_state_dict_reflects_fused_steps(self):
        X, Y = _data()
        m = _mlp()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        s = pt.jit.TrainStep(m, _loss, o, fused=True)
        for _ in range(2):
            s(t(X), t(Y))
        sd = o.state_dict()
        moments = [np.abs(np.asarray(v.data)).max()
                   for k, v in sd.items() if k.endswith(".moment1")]
        assert moments and all(mv > 0 for mv in moments)

    def test_set_state_dict_wins_over_flat_cache(self):
        X, Y = _data()
        m = _mlp()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        s = pt.jit.TrainStep(m, _loss, o, fused=True)
        for _ in range(3):
            s(t(X), t(Y))
        zeroed = {}
        for k, v in o.state_dict().items():
            if hasattr(v, "data") and "pow" not in k:
                zeroed[k] = pt.to_tensor(np.zeros_like(np.asarray(v.data)))
            else:
                zeroed[k] = v
        o.set_state_dict(zeroed)
        s(t(X), t(Y))  # must rebuild flats from the restored zeros
        sd = o.state_dict()
        # one step from zeroed moments: |moment1| == (1-beta1)*|g| — far
        # smaller than 3 accumulated steps would leave behind
        m1 = [np.asarray(v.data) for k, v in sd.items()
              if k.endswith(".moment1")]
        assert all(np.isfinite(a).all() for a in m1)

    def test_mixed_fused_then_eager_steps(self):
        X, Y = _data()
        m1, o1, _ = _run_pair(OPTIMIZERS["momentum"], fused=True, steps=2)
        m2, o2, _ = _run_pair(OPTIMIZERS["momentum"], fused=False, steps=2)
        # TWO extra EAGER steps on both: the first flushes the fused
        # run's flat velocity; the second's _sync_state must NOT
        # re-install the now-stale flats over the first eager step's
        # writes (regression: flush clobbered newer external state)
        for m, o in ((m1, o1), (m2, o2)):
            for _ in range(2):
                loss = _loss(m, t(X), t(Y))
                loss.backward()
                o.step()
                o.clear_grad()
        for a, b in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=5e-6,
                                       atol=1e-7)

    def test_per_param_arrays_released_while_flat(self):
        """No duplicate accumulator memory: while the flats are
        authoritative the per-param dicts are empty (identity kept),
        and state reads re-materialize through the flush."""
        X, Y = _data()
        m = _mlp()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        s = pt.jit.TrainStep(m, _loss, o, fused=True)
        s(t(X), t(Y))
        assert all(not o._state[id(p)] for p in m.parameters())
        sd = o.state_dict()  # flush reinstalls full per-param dicts
        assert any(k.endswith(".moment1") for k in sd)
        s(t(X), t(Y))  # the next step releases them again
        assert all(not o._state[id(p)] for p in m.parameters())

    def test_dropped_trainstep_flushes_on_del(self):
        import gc
        X, Y = _data()
        m = _mlp()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        s = pt.jit.TrainStep(m, _loss, o, fused=True)
        s(t(X), t(Y))
        del s
        gc.collect()
        sd = o.state_dict()  # the flat state must have been flushed
        vals = [np.abs(np.asarray(v.data)).max()
                for k, v in sd.items() if k.endswith(".moment1")]
        assert vals and all(v > 0 for v in vals)
        # and the dead holder's weakref hook is pruned on next register
        assert all(r() is None for r in o._state_sync_hooks)

    def test_alternating_batch_shapes_share_flats(self, monkeypatch):
        """Two compile keys (different batch signatures) over one
        trainable set reuse ONE flat cache — no per-step flush/rebuild
        round trip (regression: single-slot cache keyed by compile
        key)."""
        import paddle_tpu.jit.train_step as ts_mod
        rebuilds = []
        orig = ts_mod.build_flat_states
        monkeypatch.setattr(
            ts_mod, "build_flat_states",
            lambda *a, **k: rebuilds.append(1) or orig(*a, **k))
        X, Y = _data()
        m = _mlp()
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        s = pt.jit.TrainStep(m, _loss, o, fused=True)
        for _ in range(3):
            s(t(X), t(Y))            # full batch
            s(t(X[:8]), t(Y[:8]))    # tail batch: second compile key
        assert len(s._cache) == 2
        assert len(rebuilds) == 1

    def test_two_trainsteps_one_optimizer_stay_coherent(self):
        X, Y = _data()
        pt.seed(7)
        m = _mlp(7)
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        sa = pt.jit.TrainStep(m, _loss, o, fused=True)
        sb = pt.jit.TrainStep(m, _loss, o, fused=True)
        la = float(sa(t(X), t(Y)).numpy())
        lb = float(sb(t(X), t(Y)).numpy())
        assert lb < la  # second step saw the first step's accumulators
        m2, o2, losses2 = _run_pair(OPTIMIZERS["adamw"], fused=True,
                                    steps=2)
        np.testing.assert_allclose([la, lb], losses2, rtol=1e-5, atol=1e-7)


class TestCheckpointRoundTrip:
    """Optimizer state crosses the fused/eager boundary through
    CheckpointManager with the per-parameter layout intact."""

    def _ckpt(self, tmp_path, o):
        from paddle_tpu.checkpoint import CheckpointManager
        mgr = CheckpointManager(str(tmp_path), async_=False)
        mgr.save(0, {"optimizer": o.state_dict()})
        return mgr

    def test_save_fused_restore_eager(self, tmp_path):
        X, Y = _data()
        m1, o1, _ = _run_pair(OPTIMIZERS["adamw"], fused=True, steps=3)
        mgr = self._ckpt(tmp_path, o1)
        state = mgr.restore()["optimizer"]

        # an EAGER continuation from the checkpoint == the fused run's own
        # eager continuation (state crossed the boundary losslessly)
        pt.seed(11)
        m2 = _mlp(11)
        for p2, p1 in zip(m2.parameters(), m1.parameters()):
            p2.set_value(p1.numpy())
        o2 = OPTIMIZERS["adamw"](m2.parameters())
        o2.set_state_dict(state)
        _assert_state_dicts_match(o1.state_dict(), o2.state_dict())
        for m, o in ((m1, o1), (m2, o2)):
            loss = _loss(m, t(X), t(Y))
            loss.backward()
            o.step()
            o.clear_grad()
        for a, b in zip(m1.parameters(), m2.parameters()):
            np.testing.assert_array_equal(a.numpy(), b.numpy())

    def test_save_eager_restore_fused(self, tmp_path):
        X, Y = _data()
        # eager-trained state restored into a fused TrainStep
        pt.seed(9)
        m1 = _mlp(9)
        o1 = OPTIMIZERS["adamw"](m1.parameters())
        for _ in range(3):
            loss = _loss(m1, t(X), t(Y))
            loss.backward()
            o1.step()
            o1.clear_grad()
        mgr = self._ckpt(tmp_path, o1)
        state = mgr.restore()["optimizer"]

        pt.seed(9)
        m2 = _mlp(9)
        for p2, p1 in zip(m2.parameters(), m1.parameters()):
            p2.set_value(p1.numpy())
        o2 = OPTIMIZERS["adamw"](m2.parameters())
        o2.set_state_dict(state)
        s = pt.jit.TrainStep(m2, _loss, o2, fused=True)
        s(t(X), t(Y))
        # the fused step consumed the restored accumulators: state_dict
        # advanced from the checkpoint, layout still per-parameter
        sd = o2.state_dict()
        assert set(sd) == set(state)
        for k in state:
            if hasattr(state[k], "data") and k.endswith(".moment1"):
                assert not np.array_equal(np.asarray(sd[k].data),
                                          np.asarray(state[k].data))

    def test_per_parameter_layout_byte_identical(self, tmp_path):
        """The checkpoint written after fused steps has the same keys,
        dtypes and shapes as one written by the eager loop — the PR 3
        manager sees no layout difference at all."""
        m1, o1, _ = _run_pair(OPTIMIZERS["adamw"], fused=True, steps=2)
        m2, o2, _ = _run_pair(OPTIMIZERS["adamw"], fused=False, steps=2)
        sd1, sd2 = o1.state_dict(), o2.state_dict()
        assert set(sd1) == set(sd2)
        for k in sd1:
            a, b = sd1[k], sd2[k]
            if hasattr(a, "data"):
                assert np.asarray(a.data).dtype == np.asarray(b.data).dtype
                assert np.asarray(a.data).shape == np.asarray(b.data).shape


@pytest.fixture()
def dp8():
    import paddle_tpu.distributed as dist
    return dist.init_mesh({"dp": 8})


def _count_all_reduce(hlo_text):
    return len(re.findall(r"all-reduce(?:-start)?\(", hlo_text))


class TestBucketedCollectives:
    def _dp_step(self, mesh, fused=True, bucketed=None, seed=3):
        import paddle_tpu.distributed as dist
        pt.seed(seed)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        m = dist.DataParallel(net, mesh=mesh)
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        return m, o, pt.jit.TrainStep(m, _loss, o, fused=fused,
                                      bucketed=bucketed)

    def _batch(self):
        rng = np.random.RandomState(0)
        X = rng.randn(64, 16).astype(np.float32)
        return X, X @ rng.randn(16, 4).astype(np.float32)

    def test_hlo_reductions_equal_bucket_count(self, dp8):
        X, Y = self._batch()
        m, o, s = self._dp_step(dp8)
        hlo = s.compiled_hlo(t(X), t(Y))
        assert s._bucketed_reason is None
        n_buckets = len(s._comm_buckets)
        # default 25MB target: one bucket for this model — bucketed, not
        # per-param (4 trainable tensors), not a per-param count
        assert n_buckets == 1
        # + 1 is the scalar loss pmean
        assert _count_all_reduce(hlo) == n_buckets + 1

    def test_bucket_size_env_changes_count(self, dp8, monkeypatch):
        X, Y = self._batch()
        monkeypatch.setenv("PADDLE_TPU_COMM_BUCKET_MB", "0.000001")
        m, o, s = self._dp_step(dp8)
        hlo = s.compiled_hlo(t(X), t(Y))
        n_buckets = len(s._comm_buckets)
        assert n_buckets == 4  # one per parameter at a ~1-byte target
        assert _count_all_reduce(hlo) == n_buckets + 1

    def test_gspmd_fallback_emits_per_param_reductions(self, dp8):
        X, Y = self._batch()
        m, o, s = self._dp_step(dp8, bucketed=False)
        hlo = s.compiled_hlo(t(X), t(Y))
        assert s._comm_buckets is None
        # per-param grads + loss: strictly more reductions than the
        # bucketed step's 2
        assert _count_all_reduce(hlo) > 2

    def test_bucketed_matches_single_device(self, dp8):
        X, Y = self._batch()
        pt.seed(3)
        m1 = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        o1 = opt.AdamW(learning_rate=0.01, parameters=m1.parameters())
        s1 = pt.jit.TrainStep(m1, _loss, o1)
        base = [float(s1(t(X), t(Y)).numpy()) for _ in range(6)]
        m2, o2, s2 = self._dp_step(dp8)
        got = [float(s2(t(X), t(Y)).numpy()) for _ in range(6)]
        assert s2._bucketed_reason is None
        np.testing.assert_allclose(got, base, rtol=2e-4, atol=1e-6)
        # params stay replicated across the mesh after bucketed steps
        p = m2.parameters()[0]
        assert len({str(sh.device)
                    for sh in p.data.addressable_shards}) == 8

    def test_buckets_reverse_order_and_size_target(self):
        import jax.numpy as jnp
        train = {f"p{i}": jnp.zeros((256,), jnp.float32) for i in range(6)}
        # 1KB per tensor; 2KB target -> 3 buckets of 2, reverse order
        buckets = plan_comm_buckets(train, target_bytes=2048)
        assert buckets == [("p5", "p4"), ("p3", "p2"), ("p1", "p0")]
        # mixed dtypes never share a payload
        train["p6"] = jnp.zeros((256,), jnp.bfloat16)
        buckets = plan_comm_buckets(train, target_bytes=10 ** 9)
        assert buckets[0] == ("p6",)

    def test_eligibility_reasons(self, dp8):
        import paddle_tpu.distributed as dist
        X, Y = self._batch()
        # plain (non-DataParallel) mesh step keeps GSPMD
        pt.seed(0)
        m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        s = pt.jit.TrainStep(m, _loss, o, mesh=dp8,
                             input_spec=pt.distributed.P("dp"))
        s(t(X), t(Y))
        assert s._comm_buckets is None
        assert "DataParallel" in s._bucketed_reason

    def test_zero_keeps_gspmd_and_sharded_states(self):
        """ZeRO stage 1: fused layout disabled, bucketed path disabled,
        accumulators still shard over the mesh exactly as before."""
        import paddle_tpu.distributed as dist
        mesh = dist.init_mesh({"sharding": 8})
        rng = np.random.RandomState(0)
        X = rng.randn(32, 16).astype(np.float32)
        Y = X @ rng.randn(16, 8).astype(np.float32)
        pt.seed(3)
        m = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 8))
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        m, o, _ = dist.group_sharded_parallel(m, o, level="os")
        s = pt.jit.TrainStep(m, _loss, o, mesh=mesh,
                             input_spec=dist.P("sharding"))
        s(t(X), t(Y))
        assert s._layout is None and s._comm_buckets is None
        w = m[0].weight
        moment = o._state[id(w)]["moment1"]
        assert len({str(sh.device)
                    for sh in moment.addressable_shards}) == 8


class TestCompiledHloInspection:
    def test_rng_neutral(self):
        """Inspecting the program mid-training must not shift the key
        stream (resume == uninterrupted digest equality rides on it)."""
        X, Y = _data()

        def run(inspect):
            pt.seed(7)
            m = _mlp(7)
            o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
            s = pt.jit.TrainStep(m, _loss, o, fused=True)
            out = [float(s(t(X), t(Y)).numpy())]
            if inspect:
                s.compiled_hlo(t(X), t(Y))
            out += [float(s(t(X), t(Y)).numpy()) for _ in range(2)]
            return out

        np.testing.assert_array_equal(run(True), run(False))


class TestXlaTuning:
    def test_flags_applied_when_forced(self):
        from paddle_tpu.device import apply_xla_tuning, XLA_TUNING_FLAGS
        env = {"XLA_FLAGS": "--xla_foo=1"}
        applied = apply_xla_tuning(env, force=True)
        assert len(applied) == len(XLA_TUNING_FLAGS)
        assert env["XLA_FLAGS"].startswith("--xla_foo=1 ")
        for name in XLA_TUNING_FLAGS:
            assert name + "=" in env["XLA_FLAGS"]

    def test_user_setting_wins(self):
        from paddle_tpu.device import apply_xla_tuning
        user = "--xla_tpu_enable_latency_hiding_scheduler=false"
        env = {"XLA_FLAGS": user}
        apply_xla_tuning(env, force=True)
        assert env["XLA_FLAGS"].count(
            "--xla_tpu_enable_latency_hiding_scheduler") == 1
        assert user in env["XLA_FLAGS"]

    def test_longer_user_flag_does_not_shadow_prefix_flag(self):
        """Exact flag-name matching: a user flag whose name merely
        CONTAINS a tuning flag's name must not suppress it."""
        from paddle_tpu.device import apply_xla_tuning
        env = {"XLA_FLAGS":
               "--xla_tpu_enable_async_collective_fusion_fuse_all_gather"
               "=false"}
        applied = apply_xla_tuning(env, force=True)
        assert "--xla_tpu_enable_async_collective_fusion=true" in applied
        # and the user's longer flag stays exactly once, untouched
        assert env["XLA_FLAGS"].count("fuse_all_gather=false") == 1
        assert "fuse_all_gather=true" not in env["XLA_FLAGS"]

    def test_disable_env(self):
        from paddle_tpu.device import apply_xla_tuning
        env = {"PADDLE_TPU_NO_XLA_TUNING": "1"}
        assert apply_xla_tuning(env, force=True) == []
        assert "XLA_FLAGS" not in env

    def test_tpu_gate(self):
        from paddle_tpu.device import apply_xla_tuning
        # explicit non-TPU platform: never applied (a CPU XLA client
        # ABORTS on unknown --xla_tpu_* flags)
        assert apply_xla_tuning({"JAX_PLATFORMS": "cpu"}) == []
        # tpu / the axon tunnel plugin: applied
        env = {"JAX_PLATFORMS": "tpu"}
        assert apply_xla_tuning(env)
        env = {"JAX_PLATFORMS": "axon"}
        assert apply_xla_tuning(env)
        # TPU runtime env hint without JAX_PLATFORMS
        env = {"TPU_NAME": "v5e-8"}
        assert apply_xla_tuning(env)
        # bare CPU sandbox: nothing
        assert apply_xla_tuning({}) == []

    def test_cpu_child_strips_inherited_tpu_flags(self):
        """A CPU-forced child of a TPU parent inherits XLA_FLAGS carrying
        our tpu-only flags; the gate-off path must strip exactly our
        name=value pairs (a CPU XLA client aborts on unknown
        --xla_tpu_* flags) while leaving user flags — even same-name
        ones with a different value — alone."""
        from paddle_tpu.device import apply_xla_tuning, XLA_TUNING_FLAGS
        parent = {"JAX_PLATFORMS": "tpu", "XLA_FLAGS": "--xla_user=1"}
        apply_xla_tuning(parent)
        child = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": parent["XLA_FLAGS"]}
        assert apply_xla_tuning(child) == []
        assert child["XLA_FLAGS"] == "--xla_user=1"
        # a user's own different-valued setting survives the strip
        env = {"JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_enable_async_all_gather=false"}
        apply_xla_tuning(env)
        assert env["XLA_FLAGS"] == "--xla_enable_async_all_gather=false"

    def test_flags_documented(self):
        from paddle_tpu.device import XLA_TUNING_FLAGS
        for name, (value, why) in XLA_TUNING_FLAGS.items():
            assert name.startswith("--xla")
            assert value and why and len(why) > 10


class TestReportGateWiring:
    def test_optimizer_phase_gates_lower_better(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench", __file__.replace(
                "tests/test_fused_optimizer.py", "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        for metric in ("optimizer_phase_seconds",
                       "train_step_exposed_collective_seconds"):
            assert metric in bench.REPORT_LOWER_BETTER
            worse = bench.report_compare({metric: 1.0}, {metric: 1.5}, 3.0)
            assert worse["failures"] == [metric]
            better = bench.report_compare({metric: 1.0}, {metric: 0.5}, 3.0)
            assert not better["failures"]
