"""HBM memory observability (ISSUE 11, paddle_tpu.observability.memory
+ .profile).

Coverage contract: MemoryReport field accounting off a fake
``memory_analysis``; the ledger's named/unattributed decomposition over
the fake-backend stats seam (CPU reports nothing, so every
headroom/residual path runs against injected stats); the once-per-run
near-OOM warning; the seeded-OOM drill — a fake RESOURCE_EXHAUSTED out
of the compiled train step AND the serving engine's unified step each
produce exactly one postmortem JSON naming the top ledger owners and
the failing executable's memory report, then re-raise; compile-once
guards proving ``memory_report()`` and profiler arming never retrace
(rng stream restored, ``step_compiles`` unchanged); the bounded
profiler windows (step-window arming in ``Model.fit``, the serving
``POST /debug/profile`` 200/400/409 contract) against fake trace
seams; and the static-vs-runtime cross-check on the committed
geometries (audit ``largest_intermediate_bytes`` <= XLA's
``temp_bytes``).
"""
import json
import os
import urllib.error
import urllib.request
import warnings
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.observability import memory, profile
from paddle_tpu.observability.memory import (MemoryLedger, MemoryReport,
                                             tree_bytes)
from paddle_tpu.observability.metrics import MetricsRegistry


class _FakeCompiled:
    """Stands in for jax.stages.Compiled in unit tests."""

    def __init__(self, stats):
        self._stats = stats

    def memory_analysis(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


# ---------------- MemoryReport -----------------------------------------------

def test_memory_report_accounting():
    ma = SimpleNamespace(argument_size_in_bytes=100,
                         output_size_in_bytes=40,
                         temp_size_in_bytes=60,
                         alias_size_in_bytes=30,
                         generated_code_size_in_bytes=7)
    rep = MemoryReport.from_compiled(_FakeCompiled(ma), source="unit")
    assert rep.argument_bytes == 100 and rep.temp_bytes == 60
    # aliased (donated) bytes counted in both args and outputs: once
    assert rep.total_bytes == 100 + 40 + 60 + 7 - 30
    doc = rep.to_json()
    assert doc["total_bytes"] == rep.total_bytes
    assert doc["source"] == "unit"
    assert set(MemoryReport.FIELDS) <= set(doc)


def test_memory_report_none_when_backend_silent():
    assert MemoryReport.from_compiled(_FakeCompiled(None)) is None
    assert MemoryReport.from_compiled(
        _FakeCompiled(NotImplementedError("no"))) is None
    assert MemoryReport.from_compiled(object()) is None  # no method at all


def test_tree_bytes_prices_arrays_and_tensors():
    x = np.zeros((4, 8), np.float32)            # 128 B
    t = pt.to_tensor(np.zeros(16, np.float32))  # 64 B behind .data
    assert tree_bytes({"a": x, "b": [t, None]}) == 128 + t._data.nbytes
    assert tree_bytes([]) == 0


# ---------------- ledger decomposition ---------------------------------------

def _fake_stats(in_use=1000, limit=2000, peak=1500):
    return lambda: {"bytes_in_use": in_use, "bytes_limit": limit,
                    "peak_bytes_in_use": peak}


def test_ledger_named_vs_unattributed():
    led = MemoryLedger(stats_fn=_fake_stats())
    led.register("params", np.zeros(100, np.float32))   # 400 B
    led.register("kv", lambda: 100)                     # pre-priced int
    snap = led.snapshot()
    assert snap["owners"] == {"params": 400, "kv": 100}
    assert snap["named_bytes"] == 500
    assert snap["bytes_in_use"] == 1000
    assert snap["unattributed_bytes"] == 500
    assert snap["headroom"] == 0.5
    assert snap["peak_bytes_in_use"] == 1500


def test_ledger_cpu_backend_reports_nothing():
    """The real CPU shape: no allocator stats — named bytes still real,
    residual/headroom unknowable (None), never a crash."""
    led = MemoryLedger(stats_fn=lambda: {})
    led.register("params", np.zeros(10, np.float32))
    snap = led.snapshot()
    assert snap["owners"] == {"params": 40}
    assert snap["bytes_in_use"] is None
    assert snap["unattributed_bytes"] is None
    assert snap["headroom"] is None


def test_ledger_dead_broken_and_replaced_owners():
    led = MemoryLedger(stats_fn=lambda: {})
    led.register("dead", lambda: None)       # weakref closure post-mortem
    led.register("broken", lambda: 1 / 0)    # must not kill telemetry
    led.register("x", np.zeros(4, np.float32))
    led.register("x", np.zeros(8, np.float32))  # replace, latest wins
    snap = led.snapshot()
    assert snap["owners"] == {"x": 32}
    assert "dead" not in led.owners()        # dropped itself
    assert "broken" in led.owners()          # skipped, not evicted
    led.unregister("x")
    assert "x" not in led.owners()


def test_ledger_peak_tracks_host_side_max():
    stats = {"bytes_in_use": 100, "bytes_limit": 1000}
    led = MemoryLedger(stats_fn=lambda: dict(stats))
    led.snapshot()
    stats["bytes_in_use"] = 700
    led.snapshot()
    stats["bytes_in_use"] = 300
    assert led.snapshot()["peak_bytes_in_use"] == 700  # backend has none
    led._peak_seen = 0  # reset_peak's host half, without touching device
    assert led.snapshot()["peak_bytes_in_use"] == 300


def test_headroom_warns_once(monkeypatch):
    monkeypatch.setenv(memory.ENV_HEADROOM_WARN, "0.4")
    led = MemoryLedger(stats_fn=_fake_stats(in_use=1800, limit=2000))
    led.register("params", np.zeros(8, np.float32))
    with pytest.warns(RuntimeWarning, match="HBM headroom"):
        led.snapshot()
    with warnings.catch_warnings():          # once per run, not per poll
        warnings.simplefilter("error")
        led.snapshot()
    # typo'd threshold is ignored, healthy headroom never warns
    led2 = MemoryLedger(stats_fn=_fake_stats(in_use=1999, limit=2000))
    led3 = MemoryLedger(stats_fn=_fake_stats(in_use=100, limit=2000))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        monkeypatch.setenv(memory.ENV_HEADROOM_WARN, "lots")
        led2.snapshot()
        monkeypatch.setenv(memory.ENV_HEADROOM_WARN, "0.4")
        led3.snapshot()


def test_publish_sets_hbm_gauges():
    led = MemoryLedger(stats_fn=_fake_stats())
    led.register("params", np.zeros(100, np.float32))
    reg = MetricsRegistry()
    led.publish(reg)
    assert reg.get("hbm_bytes").value(owner="params") == 400
    assert reg.get("hbm_bytes").value(owner="unattributed") == 600
    assert reg.get("hbm_bytes_in_use").value() == 1000
    assert reg.get("hbm_peak_bytes").value() == 1500
    assert reg.get("hbm_headroom").value() == 0.5


# ---------------- OOM postmortem ---------------------------------------------

def _oom_error():
    return RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 1234 bytes")


def test_handle_oom_dumps_once_and_only_for_oom(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    assert memory.handle_oom(ValueError("shape mismatch"),
                             source="train_step") is None
    assert list(tmp_path.iterdir()) == []

    exc = _oom_error()
    rep = MemoryReport(argument_bytes=10, temp_bytes=5, source="unit")
    path = memory.handle_oom(exc, source="train_step",
                             report_fn=lambda: rep)
    assert path is not None and os.path.exists(path)
    # exactly-once: the same exception (nested wraps) reuses the dump
    assert memory.handle_oom(exc, source="server_loop") == path
    files = [p for p in tmp_path.iterdir()
             if p.name.startswith("oom_postmortem")]
    assert len(files) == 1
    doc = json.load(open(path))
    assert doc["reason"] == "RESOURCE_EXHAUSTED"
    assert doc["source"] == "train_step"
    assert doc["memory_report"]["temp_bytes"] == 5
    assert "ledger" in doc and "flight_recorder_tail" in doc


def test_handle_oom_survives_broken_report_fn(tmp_path, monkeypatch):
    """After a real OOM even metadata reads can fail — the postmortem
    still lands, with a null report."""
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    path = memory.handle_oom(_oom_error(), source="serving_step",
                             report_fn=lambda: 1 / 0)
    doc = json.load(open(path))
    assert doc["memory_report"] is None


# ---------------- compiled-step integration ----------------------------------

@pytest.fixture(scope="module")
def llama_step():
    from paddle_tpu.analysis.driver import tiny_llama_step
    import jax
    step, batch = tiny_llama_step()
    jax.block_until_ready(step(*batch))  # one real compile, shared below
    return step, batch


class _Boom:
    """Raises RESOURCE_EXHAUSTED on call but stays a real executable for
    inspection — the postmortem's memory report must be the truth, not
    a fabrication."""

    def __init__(self, real):
        self._real = real

    def __call__(self, *a, **k):
        raise _oom_error()

    def lower(self, *a, **k):
        return self._real.lower(*a, **k)


def test_train_step_oom_drill(llama_step, tmp_path, monkeypatch):
    """Seeded OOM out of the compiled train step: exactly one postmortem
    naming the top ledger owners and the failing executable's real
    memory report, then the error re-raises."""
    step, batch = llama_step
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    key = next(iter(step._cache))
    real = step._cache[key]
    monkeypatch.setitem(step._cache, key, _Boom(real))
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        step(*batch)
    files = [p for p in tmp_path.iterdir()
             if p.name.startswith("oom_postmortem")]
    assert len(files) == 1 and files[0].name.endswith("train_step.json")
    doc = json.load(open(files[0]))
    assert doc["source"] == "train_step"
    assert "model_params" in doc["ledger"]["owners"]
    assert "optimizer_state" in doc["ledger"]["owners"]
    assert doc["memory_report"]["temp_bytes"] > 0
    assert doc["memory_report"]["total_bytes"] > 0


def test_train_step_memory_report_is_neutral(llama_step):
    """The compile-once + rng-neutrality guard: memory_report rides the
    cached executable (no retrace) and hands back the key _prepare
    drew (inspection must not shift the training key stream)."""
    from paddle_tpu.core import generator as _gen
    step, batch = llama_step
    n_compiled = len(step._cache)
    rng0 = _gen.get_rng_state()
    rep = step.memory_report(*batch)
    assert rep is not None and rep.source == "train_step"
    assert rep.temp_bytes > 0 and rep.total_bytes > 0
    assert len(step._cache) == n_compiled       # no new executable
    assert _gen.get_rng_state() == rng0          # key stream untouched
    # registered owners price to real, non-zero byte totals
    snap = memory.snapshot()
    assert snap["owners"].get("model_params", 0) > 0
    assert snap["owners"].get("optimizer_state", 0) > 0


def test_static_watermark_below_runtime_temp(llama_step):
    """The cross-check the accounting hangs on: the static audit's
    largest single intermediate is a lower bound on XLA's whole-program
    scratch high-water (one buffer cannot exceed the sum of live
    buffers at the peak)."""
    from paddle_tpu.analysis.audit import audit_train_step
    step, batch = llama_step
    rep = audit_train_step(step, *batch)
    mr = step.memory_report(*batch)
    assert 0 < rep.largest_intermediate_bytes <= mr.temp_bytes


@pytest.mark.slow
def test_static_watermark_below_runtime_temp_dp8():
    """Same inequality on the committed dp8 bucketed geometry."""
    from paddle_tpu.analysis.audit import audit_train_step
    from paddle_tpu.analysis.driver import dp8_bucketed_step
    step, batch = dp8_bucketed_step(8)
    rep = audit_train_step(step, *batch)
    mr = step.memory_report(*batch)
    assert 0 < rep.largest_intermediate_bytes <= mr.temp_bytes


# ---------------- serving engine ---------------------------------------------

def _tiny_engine(seed=11):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine
    pt.seed(seed)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=True))
    m.eval()
    return ServingEngine(m, max_batch=2, max_blocks=16, block_size=4,
                         prefill_chunk=4)


@pytest.fixture(scope="module")
def engine():
    return _tiny_engine()


def test_engine_oom_drill(engine, tmp_path, monkeypatch):
    """Seeded OOM out of the unified serving step: one postmortem with
    the KV/param owners and the step's real memory report, re-raised
    into the caller."""
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    monkeypatch.setattr(engine, "_step", _Boom(engine._step))
    engine.submit([1, 2, 3], max_new_tokens=2)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        while engine.step():
            pass
    files = [p for p in tmp_path.iterdir()
             if p.name.startswith("oom_postmortem")]
    assert len(files) == 1 and files[0].name.endswith("serving_step.json")
    doc = json.load(open(files[0]))
    assert doc["source"] == "serving_step"
    assert "kv_cache" in doc["ledger"]["owners"]
    assert "serving_params" in doc["ledger"]["owners"]
    assert doc["memory_report"]["argument_bytes"] > 0


def test_engine_memory_report_and_gauges(engine):
    """memory_report and the new serving gauges ride the jit trace
    cache: step_traces (and its serving_step_compiles gauge) stays
    truthful across inspection."""
    engine.memory_report()   # warm: the FIRST inspection legitimately
    traces0 = engine.step_traces  # traces (shared jit cache, counted)
    rep = engine.memory_report()
    assert rep is not None and rep.source == "serving_step"
    assert rep.argument_bytes > 0
    assert engine.step_traces == traces0       # no hidden retrace
    engine._update_gauges()
    assert engine._m_step_compiles.value() == engine.step_traces
    assert 0.0 <= engine._m_kv_headroom.value() <= 1.0
    snap = memory.snapshot()
    assert snap["owners"].get("kv_cache", 0) > 0
    assert snap["owners"].get("serving_params", 0) > 0


# ---------------- profiler windows -------------------------------------------

@pytest.fixture()
def fake_trace(monkeypatch):
    """Swap the jax.profiler seams for recorders; guarantee the
    process-wide capture slot is free before and after."""
    calls = {"start": [], "stop": 0}
    profile.stop_capture()
    monkeypatch.setattr(profile, "_start_trace",
                        lambda path: calls["start"].append(path))

    def _stop():
        calls["stop"] += 1
    monkeypatch.setattr(profile, "_stop_trace", _stop)
    yield calls
    profile.stop_capture()


def test_bound_seconds_contract():
    assert profile.bound_seconds("2.5") == 2.5
    assert profile.bound_seconds(10 ** 6) == profile.MAX_CAPTURE_SECONDS
    for bad in (0, -1, "nope", float("nan")):
        with pytest.raises(ValueError):
            profile.bound_seconds(bad)


def test_capture_exclusive_and_idempotent_stop(fake_trace, tmp_path,
                                               monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    out = profile.start_capture("unit")
    assert os.path.isdir(out) and profile.capture_active() == out
    with pytest.raises(profile.CaptureBusy):
        profile.start_capture("another")
    assert profile.stop_capture() == out
    assert profile.stop_capture() is None      # idempotent
    assert fake_trace["start"] == [out] and fake_trace["stop"] == 1


def test_step_window_opens_and_closes_on_edges(fake_trace, tmp_path,
                                               monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    win = profile.StepWindow(2, 3)
    win.on_step(1)
    assert fake_trace["start"] == []           # before the window
    win.on_step(2)
    assert len(fake_trace["start"]) == 1       # opened entering start
    win.on_step(3)
    assert fake_trace["stop"] == 0             # stop is INCLUSIVE
    win.on_step(4)
    assert fake_trace["stop"] == 1             # closed past stop
    win.on_step(5)
    win.close()
    assert len(fake_trace["start"]) == 1 and fake_trace["stop"] == 1


def test_step_window_busy_slot_warns_not_kills(fake_trace, tmp_path,
                                               monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    profile.start_capture("occupant")
    win = profile.StepWindow(1, 2)
    with pytest.warns(RuntimeWarning, match="window skipped"):
        win.on_step(1)
    win.on_step(2)                             # disarmed, no retries
    assert len(fake_trace["start"]) == 1       # only the occupant


def test_step_window_from_env(monkeypatch):
    monkeypatch.setenv(profile.ENV_PROFILE_AT_STEP, "2:5")
    win = profile.step_window_from_env()
    assert (win.start, win.stop) == (2, 5)
    monkeypatch.setenv(profile.ENV_PROFILE_AT_STEP, "7")
    win = profile.step_window_from_env()
    assert (win.start, win.stop) == (7, 7)
    monkeypatch.setenv(profile.ENV_PROFILE_AT_STEP, "three:4")
    with pytest.warns(RuntimeWarning, match="malformed"):
        assert profile.step_window_from_env() is None
    monkeypatch.delenv(profile.ENV_PROFILE_AT_STEP)
    assert profile.step_window_from_env() is None


def test_fit_loop_profile_window(fake_trace, tmp_path, monkeypatch):
    """PADDLE_TPU_PROFILE_AT_STEP drives exactly one capture window out
    of a real Model.fit."""
    from paddle_tpu import io, nn
    from paddle_tpu import optimizer as opt
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv(profile.ENV_PROFILE_AT_STEP, "2:3")
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    m = pt.Model(net)
    m.prepare(optimizer=opt.AdamW(learning_rate=0.01,
                                  parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    y = (X.sum(-1) > 0).astype(np.int64)
    m.fit(io.TensorDataset([X, y]), batch_size=8, epochs=1, verbose=0)
    assert len(fake_trace["start"]) == 1
    assert fake_trace["stop"] == 1
    assert "profile_fit_" in fake_trace["start"][0]


def test_server_debug_profile_endpoint(engine, fake_trace, tmp_path,
                                       monkeypatch):
    """POST /debug/profile: 200 opens a bounded capture, garbage seconds
    is 400, a live capture is 409 — and none of it touches the engine's
    executables (step_compiles unchanged)."""
    from paddle_tpu.serving import Server
    monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
    traces0 = engine.step_traces
    srv = Server(engine).start()
    try:
        def post(q):
            req = urllib.request.Request(
                srv.url + f"/debug/profile?seconds={q}", data=b"")
            return json.loads(urllib.request.urlopen(
                req, timeout=10).read())

        res = post("0.05")
        assert res["status"] == "capturing" and res["seconds"] == 0.05
        assert str(tmp_path) in res["trace_dir"]

        with pytest.raises(urllib.error.HTTPError) as ei:
            post("banana")
        assert ei.value.code == 400
        profile.stop_capture()                 # free the timed window

        profile.start_capture("occupant")      # now the slot is busy
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("1")
        assert ei.value.code == 409
        assert engine.step_traces == traces0
    finally:
        srv.close(stop_engine=False)


# ---------------- data prefetch owner ----------------------------------------

def test_prefetcher_registers_ledger_owner():
    from paddle_tpu import io
    X = np.zeros((64, 8), np.float32)
    y = np.zeros((64,), np.int64)
    loader = io.DataLoader(io.TensorDataset([X, y]), batch_size=8)
    assert loader.prefetch_depth >= 2          # buffer reader is on
    seen = []
    for _ in loader:
        seen.append("data_prefetch" in memory.get_ledger().owners())
    assert any(seen)                           # live while iterating
    assert "data_prefetch" not in memory.get_ledger().owners()


# ---------------- device satellites ------------------------------------------

def test_device_memory_stats_spellings():
    import jax
    from paddle_tpu import device
    assert device.memory_stats() == {}         # CPU backend: no stats
    assert device.memory_stats("cpu:0") == {}
    assert device.memory_stats(0) == {}
    assert device.memory_stats(jax.devices()[0]) == {}  # Device object
    assert device.memory_allocated() == 0
    assert device.max_memory_allocated("cpu:0") == 0
    with pytest.raises(IndexError, match="out of range"):
        device.memory_stats("cpu:99")
    with pytest.raises(IndexError, match="out of range"):
        device.memory_stats(99)


def test_device_reset_peak_warning_noop():
    from paddle_tpu import device
    with pytest.warns(RuntimeWarning, match="no peak-reset"):
        assert device.reset_max_memory_allocated() is False


def test_audit_headline_includes_peak_hbm():
    """bench.py --audit's new LOWER_BETTER headline is wired end to
    end: the driver emits it and the report gate knows its
    direction."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert "train_step_peak_hbm_bytes" in bench.REPORT_LOWER_BETTER
