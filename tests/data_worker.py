"""Trainer worker for the data-pipeline exactly-once integration test
(run as a subprocess — NOT a pytest file).

A tiny deterministic fit over a ``paddle_tpu.data.DataPipeline`` wrapped
in ``FitResilience(pipeline=…)``, checkpointing SYNCHRONOUSLY every step
so a SIGKILL at any step boundary loses nothing (the chaos harness's
``PADDLE_TPU_CHAOS_KILL_AT_STEP`` fires right after the step's save
commits; async saves would re-run the kill-window batches and the digest
ledger would show them twice — steps_lost is the MTTR bench's metric,
not this test's).

Env contract:

* ``DATA_TEST_DIR`` — run directory (checkpoint root + ledger).
* ``DATA_TEST_EPOCHS`` — total epochs to train (default 3).
* ``PADDLE_TPU_CHAOS_KILL_AT_STEP`` / ``PADDLE_TPU_CHAOS_MARK_DIR`` —
  the chaos kill (fires once per job thanks to the mark dir).

Appends one ``{"gs", "pid", "digest"}`` line per TRAINED batch to
``batches.jsonl`` — the digest ledger the test compares against an
uninterrupted run's. Writes ``done.json`` on completion.
"""
import hashlib
import json
import os
import sys

import numpy as np


def batch_digest(batch) -> str:
    h = hashlib.sha256()
    for part in batch:
        arr = np.asarray(getattr(part, "data", part))
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


class LedgerDS:
    """Deterministic per-index samples."""

    def __getitem__(self, i):
        rng = np.random.RandomState(50 + i)
        return (rng.randn(4).astype(np.float32),
                rng.randn(1).astype(np.float32))

    def __len__(self):
        return 24


def main():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.data import DataPipeline
    from paddle_tpu.resilience import FitResilience

    run_dir = os.environ["DATA_TEST_DIR"]
    epochs = int(os.environ.get("DATA_TEST_EPOCHS", "3"))
    ledger = os.path.join(run_dir, "batches.jsonl")

    pipe = DataPipeline(LedgerDS(), batch_size=4, shuffle=True,
                        base_seed=5, drop_last=True)

    pt.seed(11)
    model = pt.hapi.Model(nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                        nn.Linear(8, 1)))
    model.prepare(pt.optimizer.SGD(learning_rate=0.05,
                                   parameters=model.parameters()),
                  nn.MSELoss())
    mgr = CheckpointManager(os.path.join(run_dir, "ckpt"),
                            keep_last_k=None, async_=False)
    fr = FitResilience(manager=mgr, save_every_steps=1, preemption=True,
                      pipeline=pipe)
    fr.restore(model)

    last = {"d": None}

    class Ledger(pt.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            with open(ledger, "a") as f:
                f.write(json.dumps({"gs": fr.global_step,
                                    "pid": os.getpid(),
                                    "digest": last["d"]}) + "\n")

    class Wrap:
        """Digest each batch at DELIVERY (what the trainer consumed)."""

        def __iter__(self):
            for b in pipe:
                last["d"] = batch_digest(b)
                yield b

    remaining = epochs - pipe.epoch
    if remaining > 0:
        model.fit(Wrap(), epochs=remaining, verbose=0,
                  callbacks=[fr, Ledger()])
    if not fr.preempted:
        with open(os.path.join(run_dir, "done.json"), "w") as f:
            json.dump({"pid": os.getpid(), "steps": fr.global_step}, f)
    fr.exit_if_preempted()


if __name__ == "__main__":
    sys.exit(main())
