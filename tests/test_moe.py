"""MoE tests: dispatch correctness against a dense per-token oracle,
capacity dropping, aux losses, expert sharding, and training."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import P


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32))


@pytest.fixture()
def mesh_ep8():
    return dist.init_mesh({"ep": 8})


def _dense_oracle(moe, x, top_k):
    """Per-token dense computation with unlimited capacity."""
    xw = x.reshape(-1, x.shape[-1])
    gw = moe.gate.weight.numpy()
    logits = xw @ gw
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    w1, b1 = moe.w1.numpy(), moe.b1.numpy()
    w2, b2 = moe.w2.numpy(), moe.b2.numpy()
    from scipy.special import erf
    gelu = lambda v: 0.5 * v * (1 + erf(v / np.sqrt(2)))
    out = np.zeros_like(xw)
    for i, row in enumerate(xw):
        top = np.argsort(-probs[i])[:top_k]
        denom = probs[i][top].sum()
        for ei in top:
            h = gelu(row @ w1[ei] + b1[ei])
            out[i] += (probs[i][ei] / denom) * (h @ w2[ei] + b2[ei])
    return out.reshape(x.shape)


class TestMoE:
    def test_matches_dense_oracle_when_capacity_ample(self, mesh_ep8):
        pt.seed(0)
        moe = fleet.MoELayer(16, 32, num_experts=8, gate="gshard",
                             capacity_factor=8.0)
        x = np.random.RandomState(0).randn(24, 16).astype(np.float32)
        got = moe(t(x)).numpy()
        ref = _dense_oracle(moe, x, top_k=2)
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-4)

    def test_switch_top1(self, mesh_ep8):
        pt.seed(1)
        moe = fleet.MoELayer(8, 16, num_experts=4, gate="switch",
                             capacity_factor=8.0)
        x = np.random.RandomState(1).randn(12, 8).astype(np.float32)
        got = moe(t(x)).numpy()
        ref = _dense_oracle(moe, x, top_k=1)
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-4)
        assert float(moe.l_aux.numpy()) > 0

    def test_capacity_drops_tokens(self, mesh_ep8):
        pt.seed(2)
        # capacity so small most tokens drop -> output rows become zero
        moe = fleet.MoELayer(8, 16, num_experts=4, gate="switch",
                             capacity_factor=0.01)
        x = np.random.RandomState(2).randn(32, 8).astype(np.float32)
        out = moe(t(x)).numpy()
        zero_rows = (np.abs(out).sum(-1) < 1e-6).sum()
        assert zero_rows > 0

    def test_expert_weights_sharded(self, mesh_ep8):
        moe = fleet.MoELayer(8, 16, num_experts=8)
        assert moe.w1._sharding_spec == P("ep", None, None)
        assert len({str(s.device)
                    for s in moe.w1.data.addressable_shards}) == 8

    def test_grad_flows_and_trains(self, mesh_ep8):
        pt.seed(3)
        rng = np.random.RandomState(0)
        X = rng.randn(64, 8).astype(np.float32)
        Y = X @ rng.randn(8, 8).astype(np.float32)
        moe = fleet.MoELayer(8, 32, num_experts=4, gate="gshard",
                             capacity_factor=4.0)
        gate_init = moe.gate.weight.numpy().copy()
        o = opt.AdamW(learning_rate=0.01, parameters=moe.parameters())
        losses = []
        for _ in range(40):
            out = moe(t(X))
            loss = nn.MSELoss()(out, t(Y)) + moe.l_aux * 0.01
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # the gate actually learns (grads flow through the router)
        assert not np.allclose(moe.gate.weight.numpy(), gate_init)

    def test_3d_input(self, mesh_ep8):
        moe = fleet.MoELayer(8, 16, num_experts=4, capacity_factor=8.0)
        out = moe(t(np.random.randn(2, 6, 8)))
        assert out.shape == [2, 6, 8]

    def test_compiled_step(self, mesh_ep8):
        pt.seed(4)
        rng = np.random.RandomState(1)
        X = rng.randn(32, 8).astype(np.float32)
        Y = X @ rng.randn(8, 8).astype(np.float32)
        moe = fleet.MoELayer(8, 16, num_experts=8, capacity_factor=4.0)
        o = opt.AdamW(learning_rate=0.01, parameters=moe.parameters())

        def loss_fn(m, a, b):
            out = m(a)
            return nn.MSELoss()(out, b) + m.l_aux * 0.01
        step = pt.jit.TrainStep(moe, loss_fn, o, mesh=dist.get_mesh(),
                                input_spec=P())
        l0 = float(step(t(X), t(Y)).numpy())
        for _ in range(15):
            l = float(step(t(X), t(Y)).numpy())
        assert np.isfinite(l) and l < l0


class TestRaggedDispatch:
    """Index-routing dispatch (reference global_scatter/global_gather,
    moe_layer.py:97-147) vs the dense one-hot oracle."""

    @pytest.mark.parametrize("gate,topk", [("naive", 2), ("switch", 1),
                                           ("gshard", 2)])
    def test_parity_vs_dense(self, mesh_ep8, gate, topk):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 16, 16).astype(np.float32)
        outs = {}
        for mode in ("dense", "ragged"):
            pt.seed(42)
            moe = fleet.MoELayer(16, 32, num_experts=8, gate=gate,
                                 top_k=topk, capacity_factor=1.0,
                                 dispatch_mode=mode)
            xt = pt.to_tensor(x, stop_gradient=False)
            y = moe(xt)
            (y.mean() + moe.l_aux).backward()
            outs[mode] = (y.numpy(), float(moe.l_aux.numpy()),
                          xt.grad.numpy(),
                          moe.w1.grad.numpy())
        for a, b in zip(outs["dense"], outs["ragged"]):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_capacity_drop_parity(self, mesh_ep8):
        # tight capacity forces drops; the drop RULE must match exactly
        rng = np.random.RandomState(1)
        x = rng.randn(2, 64, 8).astype(np.float32)
        outs = {}
        for mode in ("dense", "ragged"):
            pt.seed(7)
            moe = fleet.MoELayer(8, 16, num_experts=4, gate="gshard",
                                 capacity_factor=0.5, dispatch_mode=mode)
            outs[mode] = moe(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(outs["dense"], outs["ragged"],
                                   rtol=1e-5, atol=1e-6)

    def test_no_dense_tensor_at_scale(self, mesh_ep8):
        """E=32, T=8K: the traced program must contain no intermediate
        anywhere near the [T, E, C] one-hot size (the memory wall the
        index routing removes)."""
        import jax
        import jax.numpy as jnp

        E, T, M, K, capf = 32, 8192, 64, 2, 1.25
        C = max(int(capf * T * K / E), 1)
        dense_elems = T * E * C  # ~167M elements
        pt.seed(0)
        moe = fleet.MoELayer(M, 2 * M, num_experts=E, gate="gshard",
                             capacity_factor=capf, dispatch_mode="ragged")
        import paddle_tpu.distributed.fleet.moe as moe_mod

        captured = {}
        orig = moe_mod.apply_op

        def capture(f, *args, **kw):
            captured["f"] = f
            captured["args"] = [a.data if hasattr(a, "data") else a
                                for a in args]
            return orig(f, *args, **kw)

        moe_mod.apply_op = capture
        try:
            moe(pt.to_tensor(np.zeros((1, T, M), np.float32)))
        finally:
            moe_mod.apply_op = orig
        jaxpr = jax.make_jaxpr(captured["f"])(*captured["args"])

        def walk(jx):
            """Max intermediate size, RECURSING into sub-jaxprs
            (custom_jvp/pjit/remat bodies would otherwise hide tensors)."""
            big = 0
            for eqn in jx.eqns:
                for v in list(eqn.invars) + list(eqn.outvars):
                    aval = getattr(v, "aval", None)
                    if aval is not None and getattr(aval, "shape", None):
                        big = max(big, int(np.prod(aval.shape)))
                for val in eqn.params.values():
                    for sub in (val if isinstance(val, (list, tuple))
                                else [val]):
                        inner = getattr(sub, "jaxpr", None)  # ClosedJaxpr
                        if inner is not None:
                            big = max(big, walk(inner))
                        elif hasattr(sub, "eqns"):  # raw Jaxpr
                            big = max(big, walk(sub))
            return big

        biggest = walk(jaxpr.jaxpr)
        # E*C*M buffer (~2.6M) and [T, E] gate tensors are fine; anything
        # within 10x of the dense one-hot tensor means the wall is back
        assert biggest < dense_elems / 10, (
            f"largest intermediate {biggest} elements — dense-scale "
            f"tensor leaked into the ragged path (dense = {dense_elems})")
