"""MoE tests: dispatch correctness against a dense per-token oracle,
capacity dropping, aux losses, expert sharding, and training."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import P


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32))


@pytest.fixture()
def mesh_ep8():
    return dist.init_mesh({"ep": 8})


def _dense_oracle(moe, x, top_k):
    """Per-token dense computation with unlimited capacity."""
    xw = x.reshape(-1, x.shape[-1])
    gw = moe.gate.weight.numpy()
    logits = xw @ gw
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    w1, b1 = moe.w1.numpy(), moe.b1.numpy()
    w2, b2 = moe.w2.numpy(), moe.b2.numpy()
    from scipy.special import erf
    gelu = lambda v: 0.5 * v * (1 + erf(v / np.sqrt(2)))
    out = np.zeros_like(xw)
    for i, row in enumerate(xw):
        top = np.argsort(-probs[i])[:top_k]
        denom = probs[i][top].sum()
        for ei in top:
            h = gelu(row @ w1[ei] + b1[ei])
            out[i] += (probs[i][ei] / denom) * (h @ w2[ei] + b2[ei])
    return out.reshape(x.shape)


class TestMoE:
    def test_matches_dense_oracle_when_capacity_ample(self, mesh_ep8):
        pt.seed(0)
        moe = fleet.MoELayer(16, 32, num_experts=8, gate="gshard",
                             capacity_factor=8.0)
        x = np.random.RandomState(0).randn(24, 16).astype(np.float32)
        got = moe(t(x)).numpy()
        ref = _dense_oracle(moe, x, top_k=2)
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-4)

    def test_switch_top1(self, mesh_ep8):
        pt.seed(1)
        moe = fleet.MoELayer(8, 16, num_experts=4, gate="switch",
                             capacity_factor=8.0)
        x = np.random.RandomState(1).randn(12, 8).astype(np.float32)
        got = moe(t(x)).numpy()
        ref = _dense_oracle(moe, x, top_k=1)
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-4)
        assert float(moe.l_aux.numpy()) > 0

    def test_capacity_drops_tokens(self, mesh_ep8):
        pt.seed(2)
        # capacity so small most tokens drop -> output rows become zero
        moe = fleet.MoELayer(8, 16, num_experts=4, gate="switch",
                             capacity_factor=0.01)
        x = np.random.RandomState(2).randn(32, 8).astype(np.float32)
        out = moe(t(x)).numpy()
        zero_rows = (np.abs(out).sum(-1) < 1e-6).sum()
        assert zero_rows > 0

    def test_expert_weights_sharded(self, mesh_ep8):
        moe = fleet.MoELayer(8, 16, num_experts=8)
        assert moe.w1._sharding_spec == P("ep", None, None)
        assert len({str(s.device)
                    for s in moe.w1.data.addressable_shards}) == 8

    def test_grad_flows_and_trains(self, mesh_ep8):
        pt.seed(3)
        rng = np.random.RandomState(0)
        X = rng.randn(64, 8).astype(np.float32)
        Y = X @ rng.randn(8, 8).astype(np.float32)
        moe = fleet.MoELayer(8, 32, num_experts=4, gate="gshard",
                             capacity_factor=4.0)
        gate_init = moe.gate.weight.numpy().copy()
        o = opt.AdamW(learning_rate=0.01, parameters=moe.parameters())
        losses = []
        for _ in range(40):
            out = moe(t(X))
            loss = nn.MSELoss()(out, t(Y)) + moe.l_aux * 0.01
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # the gate actually learns (grads flow through the router)
        assert not np.allclose(moe.gate.weight.numpy(), gate_init)

    def test_3d_input(self, mesh_ep8):
        moe = fleet.MoELayer(8, 16, num_experts=4, capacity_factor=8.0)
        out = moe(t(np.random.randn(2, 6, 8)))
        assert out.shape == [2, 6, 8]

    def test_compiled_step(self, mesh_ep8):
        pt.seed(4)
        rng = np.random.RandomState(1)
        X = rng.randn(32, 8).astype(np.float32)
        Y = X @ rng.randn(8, 8).astype(np.float32)
        moe = fleet.MoELayer(8, 16, num_experts=8, capacity_factor=4.0)
        o = opt.AdamW(learning_rate=0.01, parameters=moe.parameters())

        def loss_fn(m, a, b):
            out = m(a)
            return nn.MSELoss()(out, b) + m.l_aux * 0.01
        step = pt.jit.TrainStep(moe, loss_fn, o, mesh=dist.get_mesh(),
                                input_spec=P())
        l0 = float(step(t(X), t(Y)).numpy())
        for _ in range(15):
            l = float(step(t(X), t(Y)).numpy())
        assert np.isfinite(l) and l < l0
