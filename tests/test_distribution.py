"""paddle.distribution — scipy.stats oracles for densities/entropies,
sample-moment checks, KL registry dispatch."""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as pt
from paddle_tpu import distribution as D


def _np(t):
    return np.asarray(t.data)


def test_normal_log_prob_entropy_vs_scipy():
    d = D.Normal(1.5, 2.0)
    v = np.array([-1.0, 0.0, 3.7], np.float32)
    np.testing.assert_allclose(_np(d.log_prob(pt.to_tensor(v))),
                               st.norm(1.5, 2.0).logpdf(v), rtol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               st.norm(1.5, 2.0).entropy(), rtol=1e-5)
    np.testing.assert_allclose(float(_np(d.variance)), 4.0, rtol=1e-6)


def test_normal_rsample_pathwise_grad():
    pt.seed(0)
    loc = pt.to_tensor(np.float32(0.0))
    loc.stop_gradient = False
    d = D.Normal(loc, 1.0)
    s = d.rsample((256,))
    pt.ops.mean(s).backward()
    # d mean(loc + eps)/d loc = 1
    np.testing.assert_allclose(float(_np(loc.grad)), 1.0, rtol=1e-5)


def test_normal_sample_moments():
    pt.seed(1)
    d = D.Normal(2.0, 0.5)
    s = _np(d.sample((20000,)))
    assert abs(s.mean() - 2.0) < 0.02
    assert abs(s.std() - 0.5) < 0.02


def test_uniform_laplace_gumbel_vs_scipy():
    v = np.array([0.3, 0.6], np.float32)
    u = D.Uniform(0.0, 2.0)
    np.testing.assert_allclose(_np(u.log_prob(pt.to_tensor(v))),
                               st.uniform(0, 2).logpdf(v), rtol=1e-5)
    np.testing.assert_allclose(float(_np(u.entropy())),
                               st.uniform(0, 2).entropy(), rtol=1e-5)

    l = D.Laplace(0.5, 1.5)
    np.testing.assert_allclose(_np(l.log_prob(pt.to_tensor(v))),
                               st.laplace(0.5, 1.5).logpdf(v), rtol=1e-5)
    np.testing.assert_allclose(float(_np(l.entropy())),
                               st.laplace(0.5, 1.5).entropy(), rtol=1e-5)

    g = D.Gumbel(0.0, 2.0)
    np.testing.assert_allclose(_np(g.log_prob(pt.to_tensor(v))),
                               st.gumbel_r(0, 2).logpdf(v), rtol=1e-5)
    np.testing.assert_allclose(float(_np(g.entropy())),
                               st.gumbel_r(0, 2).entropy(), rtol=1e-5)


def test_lognormal_vs_scipy():
    d = D.LogNormal(0.2, 0.7)
    v = np.array([0.5, 1.0, 2.5], np.float32)
    np.testing.assert_allclose(
        _np(d.log_prob(pt.to_tensor(v))),
        st.lognorm(s=0.7, scale=np.exp(0.2)).logpdf(v), rtol=1e-4)
    np.testing.assert_allclose(
        float(_np(d.mean)), st.lognorm(s=0.7, scale=np.exp(0.2)).mean(),
        rtol=1e-5)


def test_beta_dirichlet_vs_scipy():
    b = D.Beta(2.0, 3.0)
    v = np.array([0.2, 0.7], np.float32)
    np.testing.assert_allclose(_np(b.log_prob(pt.to_tensor(v))),
                               st.beta(2, 3).logpdf(v), rtol=1e-4)
    np.testing.assert_allclose(float(_np(b.entropy())),
                               st.beta(2, 3).entropy(), rtol=1e-4)

    c = np.array([1.5, 2.0, 3.0], np.float32)
    d = D.Dirichlet(pt.to_tensor(c))
    x = np.array([0.2, 0.3, 0.5], np.float32)
    np.testing.assert_allclose(float(_np(d.log_prob(pt.to_tensor(x)))),
                               st.dirichlet(c).logpdf(x), rtol=1e-4)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               st.dirichlet(c).entropy(), rtol=1e-4)


def test_categorical_and_multinomial():
    logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
    c = D.Categorical(pt.to_tensor(logits))
    np.testing.assert_allclose(_np(c.probs()), [0.2, 0.3, 0.5], rtol=1e-5)
    lp = _np(c.log_prob(pt.to_tensor(np.array([2], np.int64))))
    np.testing.assert_allclose(lp, [np.log(0.5)], rtol=1e-5)
    np.testing.assert_allclose(
        float(_np(c.entropy())),
        -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5)),
        rtol=1e-5)
    pt.seed(3)
    s = _np(c.sample((5000,)))
    freq = np.bincount(s, minlength=3) / 5000
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)

    m = D.Multinomial(10, pt.to_tensor(np.array([0.3, 0.7], np.float32)))
    v = np.array([3.0, 7.0], np.float32)
    np.testing.assert_allclose(
        float(_np(m.log_prob(pt.to_tensor(v)))),
        st.multinomial(10, [0.3, 0.7]).logpmf([3, 7]), rtol=1e-4)
    s = _np(m.sample())
    assert s.sum() == 10


def test_bernoulli():
    d = D.Bernoulli(pt.to_tensor(np.float32(0.3)))
    np.testing.assert_allclose(
        float(_np(d.log_prob(pt.to_tensor(np.float32(1.0))))),
        np.log(0.3), rtol=1e-4)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               st.bernoulli(0.3).entropy(), rtol=1e-4)


def test_independent_sums_event_dims():
    locs = np.zeros(4, np.float32)
    base = D.Normal(pt.to_tensor(locs), 1.0)
    ind = D.Independent(base, 1)
    v = pt.to_tensor(np.ones(4, np.float32))
    np.testing.assert_allclose(float(_np(ind.log_prob(v))),
                               st.norm(0, 1).logpdf(1.0) * 4, rtol=1e-5)
    assert ind.event_shape == (4,)


def test_transformed_distribution_lognormal_equivalence():
    base = D.Normal(0.2, 0.7)
    td = D.TransformedDistribution(base, [D.ExpTransform()])
    ln = D.LogNormal(0.2, 0.7)
    v = pt.to_tensor(np.array([0.5, 1.5], np.float32))
    np.testing.assert_allclose(_np(td.log_prob(v)), _np(ln.log_prob(v)),
                               rtol=1e-4)


def test_affine_sigmoid_transforms():
    t = D.AffineTransform(1.0, 2.0)
    x = pt.to_tensor(np.array([0.5], np.float32))
    y = t.forward(x)
    np.testing.assert_allclose(_np(y), [2.0], rtol=1e-6)
    np.testing.assert_allclose(_np(t.inverse(y)), [0.5], rtol=1e-6)
    np.testing.assert_allclose(_np(t.forward_log_det_jacobian(x)),
                               [np.log(2.0)], rtol=1e-6)
    s = D.SigmoidTransform()
    np.testing.assert_allclose(_np(s.inverse(s.forward(x))), [0.5],
                               rtol=1e-5)


def test_kl_registry_vs_scipy_numeric():
    p, q = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
    got = float(_np(D.kl_divergence(p, q)))
    # analytic: log(s2/s1) + (s1^2 + (m1-m2)^2)/(2 s2^2) - 1/2
    want = np.log(2.0) + (1 + 1) / 8 - 0.5
    np.testing.assert_allclose(got, want, rtol=1e-5)

    c1 = D.Categorical(pt.to_tensor(np.log(
        np.array([0.5, 0.5], np.float32))))
    c2 = D.Categorical(pt.to_tensor(np.log(
        np.array([0.1, 0.9], np.float32))))
    got = float(_np(D.kl_divergence(c1, c2)))
    want = 0.5 * np.log(0.5 / 0.1) + 0.5 * np.log(0.5 / 0.9)
    np.testing.assert_allclose(got, want, rtol=1e-4)

    with pytest.raises(NotImplementedError):
        D.kl_divergence(D.Normal(0, 1), D.Gumbel(0, 1))


def test_kl_lognormal_uses_most_derived_rule():
    """LogNormal subclasses Normal; KL(LogNormal, LogNormal) must pick the
    Normal/Normal rule (KL is invariant under the shared bijector) rather
    than fail."""
    p, q = D.LogNormal(0.0, 1.0), D.LogNormal(1.0, 2.0)
    got = float(_np(D.kl_divergence(p, q)))
    want = np.log(2.0) + (1 + 1) / 8 - 0.5
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_dirichlet_batched_sample_shape():
    d = D.Dirichlet(pt.to_tensor(np.ones((3, 4), np.float32)))
    s = _np(d.sample((5,)))
    assert s.shape == (5, 3, 4)
    np.testing.assert_allclose(s.sum(-1), np.ones((5, 3)), rtol=1e-5)


def test_sample_is_detached_rsample_is_not():
    for cls, args in ((D.Uniform, (0.0,)), (D.Laplace, (1.0,)),
                      (D.Gumbel, (1.0,))):
        p = pt.to_tensor(np.float32(0.5))
        p.stop_gradient = False
        d = cls(p, *args) if cls is not D.Uniform else D.Uniform(p, 1.0)
        assert d.sample((3,)).stop_gradient
        assert not d.rsample((3,)).stop_gradient
