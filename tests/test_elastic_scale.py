"""Elastic scale-in/out (reference: fleet/elastic/manager.py --np range):
kill a worker -> the job continues at the surviving size with rewritten
ranks/world; announce a replacement -> it scales back out to max; the
crash budget is not consumed by scale events."""
import glob
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_for(pattern, run_dir, n, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        found = glob.glob(os.path.join(run_dir, pattern))
        if len(found) >= n:
            return found
        time.sleep(0.1)
    raise AssertionError(
        f"timed out waiting for {n} x {pattern}; have "
        f"{os.listdir(run_dir)}")


@pytest.mark.slow  # multi-minute multiprocess elastic integration
def test_kill_and_replace_worker(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_TEST_DIR"] = str(tmp_path)
    env.pop("XLA_FLAGS", None)
    launcher = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--np", "1:2", "--max_restarts", "0",
         os.path.join(REPO, "tests", "elastic_worker.py")],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        # epoch 0: two workers up (world 2)
        files = _wait_for("epoch0.rank*.world2.pid", str(tmp_path), 2)

        # connect to the job store like a peer would (port from env file
        # is not written; recover it from the worker's PADDLE_STORE_PORT
        # via /proc)  -- simpler: workers share it through the run dir
        pids = {f: int(open(f).read()) for f in files}
        store_port = None
        for pid in pids.values():
            environ = open(f"/proc/{pid}/environ", "rb").read().decode(
                errors="ignore")
            for kv in environ.split("\0"):
                if kv.startswith("PADDLE_STORE_PORT="):
                    store_port = int(kv.split("=", 1)[1])
        assert store_port, "could not recover store port"

        # SCALE-IN: kill rank 1; job must continue at world 1, re-ranked
        victim = [p for f, p in pids.items() if ".rank1." in f][0]
        os.kill(victim, signal.SIGKILL)
        _wait_for("epoch*.rank0.world1.pid", str(tmp_path), 1)

        # SCALE-OUT: a replacement announces itself via the store counter
        from paddle_tpu.distributed.tcp_store import TCPStore
        store = TCPStore("127.0.0.1", store_port, is_master=False)
        store.add("__scale_out", 1)
        later = _wait_for("epoch*.rank*.world2.pid", str(tmp_path), 4,
                          timeout=60)
        # the scale-out epoch is a NEW epoch (not the original files)
        new_epochs = {os.path.basename(f).split(".")[0] for f in later
                      if "epoch0." not in os.path.basename(f)}
        assert new_epochs, later

        # clean finish: max_restarts=0 yet the job survived both scale
        # events — they must not consume the crash budget
        store.set("elastic_test/finish", b"1")
        rc = launcher.wait(timeout=60)
        out = launcher.stdout.read()
        assert rc == 0, out[-3000:]
    finally:
        if launcher.poll() is None:
            launcher.kill()


@pytest.mark.slow  # multi-minute multiprocess elastic integration
def test_multinode_scale_in_and_out(tmp_path):
    """VERDICT r3 item 7: two LAUNCHERS (one trainer each). Killing one
    node's worker exhausts that launcher's budget and its heartbeat goes
    stale -> the surviving launcher re-decides membership and continues at
    world 1 (scale-in); a REPLACEMENT launcher announces itself through
    __scale_out and the next round grows back to world 2 (scale-out)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    master = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_TEST_DIR"] = str(tmp_path)
    env.pop("XLA_FLAGS", None)

    def start_launcher(node):
        return subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--node_rank", str(node),
             "--master", master, "--np", "1:2", "--max_restarts", "0",
             os.path.join(REPO, "tests", "elastic_worker.py")],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    la = start_launcher(0)
    lb = start_launcher(1)
    lb2 = None
    try:
        # epoch 0: one worker per node, world 2, contiguous re-ranked ids
        files = _wait_for("epoch*.rank*.world2.pid", str(tmp_path), 2)
        ranks = {os.path.basename(f).split(".")[1] for f in files}
        assert ranks == {"rank0", "rank1"}, files

        # SCALE-IN: kill node 1's worker; its launcher (budget 0) exits
        # nonzero; node 0 detects and continues alone at world 1
        victim_file = [f for f in files if ".rank1." in f][0]
        victim = int(open(victim_file).read())
        os.kill(victim, signal.SIGKILL)
        _wait_for("epoch*.rank0.world1.pid", str(tmp_path), 1, timeout=90)
        assert lb.wait(timeout=60) != 0

        # SCALE-OUT: a replacement launcher for node 1 self-announces
        lb2 = start_launcher(1)
        later = _wait_for("epoch*.rank*.world2.pid", str(tmp_path), 4,
                          timeout=90)
        new = [f for f in later
               if not os.path.basename(f).startswith("epoch0.")]
        assert len(new) >= 2, later  # a NEW epoch reached world 2

        # clean finish for the scaled-out job
        from paddle_tpu.distributed.tcp_store import TCPStore
        store = TCPStore("127.0.0.1", port, is_master=False)
        store.set("elastic_test/finish", b"1")
        rc_a = la.wait(timeout=90)
        rc_b2 = lb2.wait(timeout=90)
        out = la.stdout.read()
        assert rc_a == 0, out[-3000:]
        assert rc_b2 == 0
    finally:
        for p in (la, lb, lb2):
            if p is not None and p.poll() is None:
                p.kill()
