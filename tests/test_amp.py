"""AMP tests: autocast policy routing, O1/O2 semantics, GradScaler dynamics,
and a bf16 transformer step training within tolerance of fp32."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import amp


def t(x, dtype=np.float32):
    return pt.to_tensor(np.asarray(x, dtype=dtype))


class TestAutoCast:
    def test_white_op_runs_low_precision(self):
        a = t(np.random.RandomState(0).randn(4, 4))
        with amp.auto_cast(dtype="bfloat16"):
            out = pt.matmul(a, a)
        assert out.dtype.name == "bfloat16"

    def test_black_op_stays_fp32(self):
        a = t(np.random.RandomState(0).randn(4, 4))
        with amp.auto_cast(dtype="bfloat16"):
            out = pt.nn.functional.softmax(a)
        assert out.dtype.name == "float32"

    def test_o1_other_ops_keep_dtype(self):
        a = t(np.random.RandomState(0).randn(4, 4))
        with amp.auto_cast(level="O1", dtype="bfloat16"):
            out = a + a
        assert out.dtype.name == "float32"

    def test_o2_other_ops_cast(self):
        a = t(np.random.RandomState(0).randn(4, 4))
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            out = a + a
        assert out.dtype.name == "bfloat16"

    def test_disabled_and_nested_restore(self):
        a = t(np.random.RandomState(0).randn(4, 4))
        with amp.auto_cast(enable=False):
            assert pt.matmul(a, a).dtype.name == "float32"
        with amp.auto_cast(dtype="bfloat16"):
            with amp.auto_cast(enable=False):
                assert pt.matmul(a, a).dtype.name == "float32"
            assert pt.matmul(a, a).dtype.name == "bfloat16"
        assert pt.matmul(a, a).dtype.name == "float32"

    def test_custom_lists(self):
        a = t(np.random.RandomState(0).randn(4, 4))
        with amp.auto_cast(custom_black_list={"matmul"}, dtype="bfloat16"):
            assert pt.matmul(a, a).dtype.name == "float32"
        with amp.auto_cast(custom_white_list={"softmax"}, dtype="bfloat16"):
            assert nn.functional.softmax(a).dtype.name == "bfloat16"

    def test_decorate_o2_casts_params(self):
        m = nn.Linear(4, 4)
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        m2, o2 = amp.decorate(m, o, level="O2", dtype="bfloat16")
        assert str(m2.weight.data.dtype) == "bfloat16"
        assert o2._multi_precision


class TestGradScaler:
    def _param(self):
        p = pt.Parameter(np.ones((2, 2), np.float32))
        return p

    def test_scale_and_step(self):
        p = self._param()
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        scaler = amp.GradScaler(init_loss_scaling=8.0)
        loss = (p * t(np.ones((2, 2)))).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        # grads are 8x
        np.testing.assert_allclose(p.grad.numpy(), 8 * np.ones((2, 2)))
        scaler.step(o)
        scaler.update()
        # effective update used the unscaled grad
        np.testing.assert_allclose(p.numpy(), 1.0 - 0.1, rtol=1e-6)

    def test_inf_skips_step_and_decreases_scale(self):
        p = self._param()
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        scaler = amp.GradScaler(init_loss_scaling=8.0, decr_ratio=0.5)
        p.grad = pt.to_tensor(np.array([[np.inf, 1], [1, 1]], np.float32))
        before = p.numpy().copy()
        scaler.step(o)
        scaler.update()
        np.testing.assert_allclose(p.numpy(), before)  # step skipped
        assert scaler.get_loss_scaling() == 4.0

    def test_scale_grows_after_n_good_steps(self):
        p = self._param()
        o = opt.SGD(learning_rate=0.0, parameters=[p])
        scaler = amp.GradScaler(init_loss_scaling=2.0, incr_ratio=2.0,
                                incr_every_n_steps=2)
        for _ in range(2):
            p.grad = pt.to_tensor(np.ones((2, 2), np.float32))
            scaler.step(o)
            scaler.update()
        assert scaler.get_loss_scaling() == 4.0

    def test_disabled_passthrough(self):
        p = self._param()
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        scaler = amp.GradScaler(enable=False)
        loss = (p * t(np.ones((2, 2)))).sum()
        assert scaler.scale(loss) is loss
        loss.backward()
        scaler.step(o)
        np.testing.assert_allclose(p.numpy(), 0.9, rtol=1e-6)

    def test_state_roundtrip(self):
        s1 = amp.GradScaler(init_loss_scaling=4.0)
        s1._good_steps = 7
        s2 = amp.GradScaler()
        s2.load_state_dict(s1.state_dict())
        assert s2.get_loss_scaling() == 4.0 and s2._good_steps == 7


class TestEndToEnd:
    def test_bf16_training_tracks_fp32(self):
        rng = np.random.RandomState(0)
        X = rng.randn(64, 16).astype(np.float32)
        Y = X @ rng.randn(16, 4).astype(np.float32)

        def run(use_amp):
            pt.seed(5)
            m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 4))
            o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
            losses = []
            for _ in range(30):
                if use_amp:
                    with amp.auto_cast(dtype="bfloat16"):
                        loss = nn.MSELoss()(m(t(X)), t(Y))
                else:
                    loss = nn.MSELoss()(m(t(X)), t(Y))
                loss.backward()
                o.step()
                o.clear_grad()
                losses.append(float(loss.numpy()))
            return losses

        base = run(False)
        mixed = run(True)
        assert mixed[-1] < base[0] * 0.1  # converges
        # within a few percent of the fp32 trajectory at the end
        assert abs(mixed[-1] - base[-1]) / base[0] < 0.05

    def test_fp16_scaler_loop(self):
        rng = np.random.RandomState(1)
        X = rng.randn(32, 8).astype(np.float32)
        Y = X @ rng.randn(8, 2).astype(np.float32)
        pt.seed(2)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        o = opt.Momentum(learning_rate=0.05, momentum=0.9,
                         parameters=m.parameters())
        scaler = amp.GradScaler(init_loss_scaling=1024.0)
        losses = []
        for _ in range(60):
            with amp.auto_cast(dtype="float16"):
                loss = nn.MSELoss()(m(t(X)), t(Y))
            scaler.scale(loss).backward()
            scaler.step(o)
            scaler.update()
            o.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.2
