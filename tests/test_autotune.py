"""Runtime kernel-config autotune cache (reference:
phi/kernels/autotune/cache.h AutoTuneCache + auto_tune_base.h Run):
measure candidates once per (op, shape, dtype, variant) signature, serve
the cached winner afterwards."""
import json

import numpy as np
import pytest

from paddle_tpu.core import flags
from paddle_tpu.ops.pallas import autotune as at


@pytest.fixture(autouse=True)
def fresh_cache():
    at.AutoTuneCache.instance().clear()
    yield
    at.AutoTuneCache.instance().clear()
    flags.set_flags({"FLAGS_use_autotune": False})


def test_cache_hit_miss_accounting():
    c = at.AutoTuneCache.instance()
    assert c.lookup(("op", 1)) is None
    c.put(("op", 1), (512, 512))
    assert c.lookup(("op", 1)) == (512, 512)
    st = c.stats()
    assert st["hits"] == 1 and st["misses"] == 1 and st["size"] == 1
    assert st["hit_rate"] == 0.5


def test_disabled_returns_default_uncached():
    calls = []

    def build(cand):
        calls.append(cand)
        return lambda: None

    got = at.autotune("op", (1,), [(1,), (2,)], build, default=(9,))
    assert got == (9,) and not calls
    # not cached: enabling the flag later still sweeps
    assert at.AutoTuneCache.instance().stats()["size"] == 0


def test_enabled_sweeps_once_then_hits(monkeypatch):
    flags.set_flags({"FLAGS_use_autotune": True})
    timings = {"a": 3.0, "b": 1.0, "c": 2.0}
    measured = []
    monkeypatch.setattr(at, "_measure", lambda fn, iters=4: fn())

    def build(cand):
        measured.append(cand)
        return lambda: timings[cand]

    got = at.autotune("op", (7,), ["a", "b", "c"], build, default="a")
    assert got == "b"  # fastest wins
    assert measured == ["a", "b", "c"]
    # second call: cache hit, nothing re-measured
    got2 = at.autotune("op", (7,), ["a", "b", "c"], build, default="a")
    assert got2 == "b" and measured == ["a", "b", "c"]
    # a DIFFERENT signature sweeps again
    at.autotune("op", (8,), ["a", "b"], build, default="a")
    assert len(measured) == 5


def test_failing_candidates_skipped(monkeypatch):
    flags.set_flags({"FLAGS_use_autotune": True})
    monkeypatch.setattr(at, "_measure", lambda fn, iters=4: fn())

    def build(cand):
        if cand == "bad":
            raise ValueError("illegal tile")
        return lambda: {"slow": 5.0, "fast": 1.0}[cand]

    got = at.autotune("op", (1,), ["bad", "slow", "fast"], build,
                      default="slow")
    assert got == "fast"


def test_all_candidates_fail_keeps_default(monkeypatch):
    flags.set_flags({"FLAGS_use_autotune": True})
    monkeypatch.setattr(at, "_measure", lambda fn, iters=4: fn())

    def build(cand):
        raise ValueError("nope")

    got = at.autotune("op", (2,), ["x", "y"], build, default="dflt")
    assert got == "dflt"
    # NOT cached: a later call deserves a real sweep
    assert at.AutoTuneCache.instance().stats()["size"] == 0


def test_persistence_roundtrip(tmp_path, monkeypatch):
    path = tmp_path / "autotune.json"
    monkeypatch.setenv("PADDLE_AUTOTUNE_CACHE", str(path))
    c = at.AutoTuneCache()
    c.put(("flash_attention", 2048, "bfloat16"), (1024, 512))
    data = json.load(open(path))
    assert list(data.values()) == [[1024, 512]]
    c2 = at.AutoTuneCache()  # fresh instance loads the file
    assert c2.lookup(("flash_attention", 2048, "bfloat16")) == (1024, 512)


def test_flash_auto_blocks_default_off_tpu():
    """CPU/interpret mode: blocks=None resolves to the hand-swept default
    without any sweep (timing interpret kernels is meaningless)."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa

    got = fa._auto_blocks(2, 256, 256, 64, 4, 2, "float32", True, None,
                          False, False)
    assert got == (fa._DEF_BLOCK_Q, fa._DEF_BLOCK_K)
    # and the public entry accepts block_q=None end-to-end
    q = jnp.zeros((1, 2, 256, 64), jnp.float32)
    k = jnp.zeros((1, 2, 256, 64), jnp.float32)
    out = fa.flash_attention_bhsd(q, k, k, causal=True)
    assert out.shape == q.shape


def test_fused_ce_auto_chunks_default_off_tpu():
    import jax.numpy as jnp
    from paddle_tpu.ops import fused_ce

    assert fused_ce._auto_chunks(64, 256, 32, "float32") == \
        fused_ce._DEF_CHUNKS
    h = jnp.zeros((8, 16), jnp.float32)
    w = jnp.zeros((64, 16), jnp.float32)
    lab = jnp.zeros((8,), jnp.int32)
    loss = fused_ce.matmul_cross_entropy(h, w, lab)  # n_chunks=None
    assert loss.shape == (8,)


def test_int_winner_persists(tmp_path, monkeypatch):
    """fused-CE winners are plain ints — persistence must handle both int
    and tuple values (review regression)."""
    path = tmp_path / "at.json"
    monkeypatch.setenv("PADDLE_AUTOTUNE_CACHE", str(path))
    c = at.AutoTuneCache()
    c.put(("fused_ce_chunks", 8192, 128256), 16)
    c.put(("flash_attention", 2048), (1024, 1024))
    c2 = at.AutoTuneCache()
    assert c2.lookup(("fused_ce_chunks", 8192, 128256)) == 16
    assert c2.lookup(("flash_attention", 2048)) == (1024, 1024)


def test_flag_off_ignores_cache():
    """Disabled autotune means hand-swept defaults even when the cache
    holds a tuned winner (A/B debugging contract)."""
    flags.set_flags({"FLAGS_use_autotune": True})
    c = at.AutoTuneCache.instance()
    c.put(("op", 3), "tuned")
    assert at.autotune("op", (3,), [], lambda c_: None, "dflt") == "tuned"
    flags.set_flags({"FLAGS_use_autotune": False})
    assert at.autotune("op", (3,), [], lambda c_: None, "dflt") == "dflt"


def test_unstable_timing_rejected(monkeypatch):
    """A candidate whose slope is non-positive (noise) must fail, not win
    as 'infinitely fast' (review regression)."""
    flags.set_flags({"FLAGS_use_autotune": True})

    # noisy candidate: _measure raises after two non-positive slopes (the
    # real implementation's behavior); steady measures fine -> steady wins
    def fake_measure(fn, iters=4):
        if fn() == "noisy":
            raise RuntimeError("unstable timing (non-positive slope)")
        return 0.5

    monkeypatch.setattr(at, "_measure", fake_measure)
    got = at.autotune("op", (9,), ["noisy", "steady"],
                      lambda c_: (lambda: c_), default="noisy")
    assert got == "steady"


def test_concurrent_put_merges_file(tmp_path, monkeypatch):
    """Two processes sharing PADDLE_AUTOTUNE_CACHE must not erase each
    other's winners from stale snapshots (review regression)."""
    path = tmp_path / "shared.json"
    monkeypatch.setenv("PADDLE_AUTOTUNE_CACHE", str(path))
    a = at.AutoTuneCache()   # loads empty
    b = at.AutoTuneCache()   # loads empty (simulates a second process)
    a.put(("op_a", 1), (512, 512))
    b.put(("op_b", 2), 16)   # b's snapshot lacks op_a; merge must keep it
    c = at.AutoTuneCache()
    assert c.lookup(("op_a", 1)) == (512, 512)
    assert c.lookup(("op_b", 2)) == 16
