"""incubate.nn fused layers + TensorArray ops."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.incubate.nn import (
    FusedEcMoe, FusedFeedForward, FusedLinear, FusedMultiHeadAttention,
    FusedMultiTransformer, FusedTransformerEncoderLayer,
)


def test_fused_linear_matches_linear():
    pt.seed(0)
    fl = FusedLinear(8, 4)
    x = pt.to_tensor(np.random.RandomState(0).randn(2, 8)
                     .astype(np.float32))
    out = fl(x)
    want = np.asarray(x.data) @ np.asarray(fl.weight.data) \
        + np.asarray(fl.bias.data)
    np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-5)

    flt = FusedLinear(8, 4, transpose_weight=True)
    assert list(flt.weight.shape) == [4, 8]
    out_t = flt(x)
    assert list(out_t.shape) == [2, 4]


def test_fused_mha_forward_backward():
    pt.seed(1)
    attn = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                   attn_dropout_rate=0.0)
    x = pt.to_tensor(np.random.RandomState(1).randn(2, 6, 16)
                     .astype(np.float32))
    out = attn(x)
    assert list(out.shape) == [2, 6, 16]
    pt.ops.sum(out).backward()
    assert attn.qkv_weight.grad is not None
    assert attn.linear_weight.grad is not None


def test_fused_ffn_pre_post_ln():
    pt.seed(2)
    x = pt.to_tensor(np.random.RandomState(2).randn(2, 4, 8)
                     .astype(np.float32))
    for pre in (True, False):
        ffn = FusedFeedForward(8, 16, dropout_rate=0.0,
                               normalize_before=pre)
        out = ffn(x)
        assert list(out.shape) == [2, 4, 8]
        pt.ops.sum(out).backward()


def test_fused_encoder_layer_and_stack_train():
    pt.seed(3)
    layer = FusedTransformerEncoderLayer(16, 2, 32, dropout_rate=0.0)
    x = pt.to_tensor(np.random.RandomState(3).randn(2, 5, 16)
                     .astype(np.float32))
    out = layer(x)
    assert list(out.shape) == [2, 5, 16]

    stack = FusedMultiTransformer(16, 2, 32, num_layers=2)
    out2 = stack(x)
    assert list(out2.shape) == [2, 5, 16]
    pt.ops.sum(out2).backward()
    grads = [p.grad for _, p in stack.named_parameters()
             if p.grad is not None]
    assert len(grads) > 10


def test_fused_ec_moe():
    pt.seed(4)
    moe = FusedEcMoe(8, 16, num_experts=4)
    x = pt.to_tensor(np.random.RandomState(4).randn(2, 6, 8)
                     .astype(np.float32))
    out = moe(x)
    assert list(out.shape) == [2, 6, 8]
    pt.ops.sum(out).backward()


def test_tensor_array_ops():
    arr = pt.ops.create_array("float32")
    a = pt.to_tensor(np.ones(3, np.float32))
    b = pt.to_tensor(np.zeros(3, np.float32))
    pt.ops.array_write(a, 0, arr)
    pt.ops.array_write(b, 1, arr)
    assert int(pt.ops.array_length(arr).numpy()) == 2
    got = pt.ops.array_read(arr, pt.to_tensor(np.int64(0)))
    np.testing.assert_array_equal(np.asarray(got.data), np.ones(3))
    # overwrite in place
    pt.ops.array_write(b, 0, arr)
    np.testing.assert_array_equal(
        np.asarray(pt.ops.array_read(arr, 0).data), np.zeros(3))
    with pytest.raises(IndexError):
        pt.ops.array_write(a, 5, arr)


def test_memory_efficient_attention_alias():
    from paddle_tpu.incubate.nn import memory_efficient_attention
    rng = np.random.RandomState(8)
    q = pt.to_tensor(rng.randn(2, 8, 4, 16).astype(np.float32))
    k = pt.to_tensor(rng.randn(2, 8, 4, 16).astype(np.float32))
    v = pt.to_tensor(rng.randn(2, 8, 4, 16).astype(np.float32))
    out = memory_efficient_attention(q, k, v, training=False)
    assert list(out.shape) == [2, 8, 4, 16]
    # matches the plain SDPA path
    import paddle_tpu.nn.functional as F
    want = F.flash_attention(q, k, v, training=False)
    np.testing.assert_allclose(np.asarray(out.data),
                               np.asarray(want.data), rtol=1e-5)


def test_memory_efficient_attention_scale():
    from paddle_tpu.incubate.nn import memory_efficient_attention
    rng = np.random.RandomState(9)
    q = pt.to_tensor(rng.randn(1, 4, 2, 16).astype(np.float32))
    k = pt.to_tensor(rng.randn(1, 4, 2, 16).astype(np.float32))
    v = pt.to_tensor(rng.randn(1, 4, 2, 16).astype(np.float32))
    # scale=0 -> uniform attention weights -> output = mean over keys
    out = memory_efficient_attention(q, k, v, scale=0.0, training=False)
    want = np.asarray(v.data).mean(axis=1, keepdims=True)
    np.testing.assert_allclose(np.asarray(out.data),
                               np.broadcast_to(want, out.shape),
                               rtol=1e-5, atol=1e-6)
