"""Distributed tests on the 8-virtual-device CPU mesh (conftest).

Mirrors the reference's test strategy (SURVEY.md §4): collective results
checked against numpy-computed per-rank expectations, and parallel training
asserted loss-equal to the single-device run.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import P


@pytest.fixture()
def mesh8():
    return dist.init_mesh({"dp": 8})


@pytest.fixture()
def mesh24():
    return dist.init_mesh({"dp": 2, "mp": 4})


class TestMesh:
    def test_init_and_get(self, mesh8):
        assert dist.get_mesh() is mesh8
        assert mesh8.shape["dp"] == 8

    def test_shape_mismatch_raises(self):
        with pytest.raises(ValueError):
            dist.init_mesh({"dp": 3})

    def test_process_mesh(self):
        pm = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                              dim_names=["x", "y"])
        assert pm.shape == [2, 4]
        assert pm.get_dim_size("y") == 4
        jm = pm.to_jax()
        assert jm.axis_names == ("x", "y")

    def test_world_size(self, mesh8):
        assert dist.get_world_size() == 8
        assert dist.get_rank() == 0


class TestCollectives:
    def test_all_reduce_sum(self, mesh8):
        data = np.arange(8, dtype=np.float32).reshape(8, 1)

        f = dist.spmd(lambda x: dist.all_reduce(x, group=dist.Group("dp")),
                      mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
        out = f(pt.to_tensor(data))
        np.testing.assert_allclose(out.numpy(), np.full((8, 1), data.sum()),
                                   rtol=1e-6)

    def test_all_reduce_max_min(self, mesh8):
        data = np.arange(8, dtype=np.float32).reshape(8, 1)
        for op, expect in [(dist.ReduceOp.MAX, 7.0), (dist.ReduceOp.MIN, 0.0)]:
            f = dist.spmd(lambda x: dist.all_reduce(x, op=op,
                                                    group=dist.Group("dp")),
                          mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
            out = f(pt.to_tensor(data)).numpy()
            np.testing.assert_allclose(out, np.full((8, 1), expect))

    def test_all_gather(self, mesh8):
        data = np.arange(16, dtype=np.float32).reshape(8, 2)

        f = dist.spmd(lambda x: dist.all_gather(x, group=dist.Group("dp")),
                      mesh=mesh8, in_specs=P("dp"),
                      out_specs=P("dp", None))
        out = f(pt.to_tensor(data))
        # every rank holds the full 8x2 -> global shape [64, 2]
        assert out.shape == [64, 2]
        np.testing.assert_allclose(out.numpy()[:8], data)

    def test_reduce_scatter(self, mesh8):
        # rank r holds [8] values data[8r:8r+8]; result on rank r is the
        # cross-rank sum of element r
        data = np.arange(64, dtype=np.float32)

        f = dist.spmd(
            lambda x: dist.reduce_scatter(x, group=dist.Group("dp")),
            mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
        out = f(pt.to_tensor(data))
        expect = data.reshape(8, 8).sum(axis=0)
        np.testing.assert_allclose(out.numpy(), expect)

    def test_broadcast(self, mesh8):
        data = np.arange(8, dtype=np.float32).reshape(8, 1)

        f = dist.spmd(
            lambda x: dist.broadcast(x, src=3, group=dist.Group("dp")),
            mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
        out = f(pt.to_tensor(data))
        np.testing.assert_allclose(out.numpy(), np.full((8, 1), 3.0))

    def test_all_to_all(self, mesh8):
        # rank r holds row r ([1, 8] view); split columns across ranks and
        # concat received chunks on rows: rank r ends up with column r
        data = np.arange(64, dtype=np.float32).reshape(8, 8)

        f = dist.spmd(
            lambda x: dist.all_to_all(x, group=dist.Group("dp"),
                                      split_axis=1, concat_axis=0),
            mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
        out = f(pt.to_tensor(data))
        np.testing.assert_allclose(out.numpy(), data.T.reshape(64, 1))

    def test_p2p_shift_ring(self, mesh8):
        data = np.arange(8, dtype=np.float32).reshape(8, 1)

        f = dist.spmd(
            lambda x: dist.p2p_shift(x, group=dist.Group("dp"), shift=1),
            mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
        out = f(pt.to_tensor(data)).numpy().ravel()
        # rank i receives from rank i-1
        np.testing.assert_allclose(out, np.roll(np.arange(8), 1))

    def test_scatter(self, mesh8):
        data = np.arange(64, dtype=np.float32)  # rank r holds [8r..8r+8)

        f = dist.spmd(
            lambda x: dist.scatter(x, src=2, group=dist.Group("dp")),
            mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
        out = f(pt.to_tensor(data)).numpy()
        # rank i gets chunk i of src rank 2's local [16..24)
        np.testing.assert_allclose(out, data.reshape(8, 8)[2])

    def test_outside_spmd_raises(self, mesh8):
        with pytest.raises(RuntimeError):
            dist.all_reduce(pt.to_tensor([1.0]), group=dist.Group("dp"))

    def test_single_rank_identity(self):
        dist.init_mesh({"dp": 8})
        t = pt.to_tensor([1.0, 2.0])
        # group=None with no mapped context and nranks grouping: identity
        out = dist.all_reduce(t, group=None)
        np.testing.assert_allclose(out.numpy(), [1.0, 2.0])


class TestShardTensor:
    def test_placements_and_spec(self, mesh24):
        x = pt.to_tensor(np.zeros((8, 16), np.float32))
        out = dist.shard_tensor(x, mesh24,
                                placements=[dist.Shard(0), dist.Shard(1)])
        assert out._sharding_spec == P("dp", "mp")

    def test_param_annotation_in_place(self, mesh24):
        p = pt.Parameter(np.zeros((8, 16), np.float32))
        out = dist.shard_tensor(p, mesh24, spec=P(None, "mp"))
        assert out is p
        assert p._sharding_spec == P(None, "mp")
        # storage actually sharded
        shards = {str(s.device) for s in p.data.addressable_shards}
        assert len(shards) == 8

    def test_reshard(self, mesh24):
        p = pt.Parameter(np.zeros((8, 16), np.float32))
        dist.shard_tensor(p, mesh24, spec=P("dp", None))
        dist.reshard(p, mesh24, spec=P(None, "mp"))
        assert p._sharding_spec == P(None, "mp")


class TestTopology:
    def test_coord_math(self):
        topo = dist.CommunicateTopology(["data", "pipe", "model"], [2, 2, 2])
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, pipe=0, model=1) == 5
        assert topo.get_coord(5) == (1, 0, 1)
        assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]
        comm = topo.get_comm_list("model")
        assert [0, 1] in comm and [6, 7] in comm

    def test_hybrid_group(self):
        topo = dist.CommunicateTopology(["data", "pipe", "sharding",
                                         "model"], [2, 1, 1, 4])
        hcg = dist.HybridCommunicateGroup(topo)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 4
        assert dist.get_mesh().shape["mp"] == 4
        assert hcg.get_model_parallel_group().nranks == 4


class TestDataParallelTraining:
    def _make(self, seed):
        pt.seed(seed)
        return nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                             nn.Linear(32, 4))

    def test_dp8_matches_single_device_loss(self):
        rng = np.random.RandomState(0)
        X = rng.randn(64, 16).astype(np.float32)
        W = rng.randn(16, 4).astype(np.float32)
        Y = X @ W

        def loss_fn(model, xb, yb):
            return nn.MSELoss()(model(xb), yb)

        # single-device compiled baseline
        m1 = self._make(3)
        o1 = opt.AdamW(learning_rate=0.01, parameters=m1.parameters())
        s1 = pt.jit.TrainStep(m1, loss_fn, o1)
        base = [float(s1(pt.to_tensor(X), pt.to_tensor(Y)).numpy())
                for _ in range(8)]

        # 8-way DP over the mesh
        mesh = dist.init_mesh({"dp": 8})
        m2 = dist.DataParallel(self._make(3), mesh=mesh)
        o2 = opt.AdamW(learning_rate=0.01, parameters=m2.parameters())
        s2 = pt.jit.TrainStep(m2, loss_fn, o2)
        par = [float(s2(pt.to_tensor(X), pt.to_tensor(Y)).numpy())
               for _ in range(8)]

        np.testing.assert_allclose(par, base, rtol=2e-4, atol=1e-6)

    def test_dp_batch_actually_sharded(self):
        mesh = dist.init_mesh({"dp": 8})
        m = dist.DataParallel(self._make(0), mesh=mesh)
        o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
        s = pt.jit.TrainStep(m, lambda mm, a, b: nn.MSELoss()(mm(a), b), o)
        X = np.zeros((16, 16), np.float32)
        Y = np.zeros((16, 4), np.float32)
        s(pt.to_tensor(X), pt.to_tensor(Y))
        # params stay replicated after the step
        p = m.parameters()[0]
        assert len({str(sh.device) for sh in p.data.addressable_shards}) == 8
        np.testing.assert_allclose(
            np.asarray(p.data.addressable_shards[0].data),
            np.asarray(p.data.addressable_shards[1].data))


def test_communication_namespace_and_stream():
    """paddle.distributed.communication + .stream task contract."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.distributed import communication as comm

    x = pt.to_tensor(np.ones(4, np.float32))
    out = comm.all_reduce(x)  # single-process: identity
    task = comm.stream.all_reduce(pt.to_tensor(np.ones(4, np.float32)))
    assert task.is_completed() in (True,)
    task.wait()
    t2 = comm.stream.broadcast(pt.to_tensor(np.ones(2, np.float32)),
                               src=0, use_calc_stream=True)
    assert t2.is_completed()


def test_device_memory_stats_surface():
    import paddle_tpu as pt
    stats = pt.device.memory_stats()
    assert isinstance(stats, dict)
    assert pt.device.memory_allocated() >= 0
    assert pt.device.max_memory_allocated() >= 0


def test_compat_surface():
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist

    assert dist.get_backend() == "XCCL"
    assert isinstance(dist.is_initialized(), bool)

    t = pt.to_tensor(np.ones(4, np.float32))
    assert dist.wait(t) is t
    parts = dist.gather(t)
    assert len(parts) >= 1

    # raw p2p keeps dist.send's honest contract: no XLA analog outside
    # an spmd region — the API exists and points at p2p_shift
    import pytest
    with pytest.raises(NotImplementedError, match="p2p_shift"):
        dist.isend(t, dst=0)
    with pytest.raises(NotImplementedError):
        dist.batch_isend_irecv([dist.P2POp(dist.isend, t, 0)])

    objs = ["a"]
    dist.broadcast_object_list(objs, src=0)
    out = []
    dist.scatter_object_list(out, ["x", "y"], src=0)
    assert out and out[0] in ("x", "y")


def test_split_api_builds_parallel_layers():
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist

    mesh = dist.init_mesh({"mp": 8})
    try:
        x = pt.to_tensor(np.random.RandomState(0).randn(2, 16)
                         .astype(np.float32))
        out = dist.split(x, (16, 32), operation="linear", axis=1)
        assert list(out.shape) == [2, 32]
        ids = pt.to_tensor(np.array([[1, 2, 3]], np.int64))
        emb = dist.split(ids, (64, 8), operation="embedding")
        assert list(emb.shape) == [1, 3, 8]
        import pytest
        with pytest.raises(ValueError):
            dist.split(x, (16, 32), operation="conv")
    finally:
        dist.set_mesh(None)


def test_spawn_runs_workers(tmp_path):
    import os
    import paddle_tpu.distributed as dist
    marker = os.path.join(tmp_path, "rank")
    dist.spawn(_spawn_worker, args=(str(marker),), nprocs=2)
    assert os.path.exists(marker + "0") and os.path.exists(marker + "1")


def _spawn_worker(marker):
    # paddle contract: func(*args); rank comes from the injected env
    import os
    rank = os.environ["PADDLE_TRAINER_ID"]
    open(marker + rank, "w").write("ok")


def test_spawn_workers_see_their_rank():
    """Regression: dist.get_rank()/get_world_size() inside spawned
    workers honor the injected launcher env (the documented contract)."""
    import os
    import paddle_tpu.distributed as dist
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        marker = os.path.join(tmp, "r")
        dist.spawn(_rank_worker, args=(marker,), nprocs=2)
        got = {open(marker + str(i)).read() for i in range(2)}
        assert got == {"0/2", "1/2"}


def _rank_worker(marker):
    import os
    import paddle_tpu.distributed as dist
    r, w = dist.get_rank(), dist.get_world_size()
    open(marker + os.environ["PADDLE_TRAINER_ID"], "w").write(f"{r}/{w}")


def test_partial_placement_raises(mesh8):
    import numpy as np
    import pytest as _pytest
    x = pt.to_tensor(np.zeros((8, 4), np.float32))
    with _pytest.raises(NotImplementedError, match="Partial"):
        dist.shard_tensor(x, placements=[dist.Partial()])


def test_broadcast_src_out_of_range_raises(mesh8):
    import numpy as np
    import pytest as _pytest
    x = pt.to_tensor(np.ones((8, 4), np.float32))
    with _pytest.raises(ValueError, match="out of range"):
        f = dist.spmd(
            lambda t: dist.broadcast(t, src=8, group=dist.Group("dp")),
            mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
        f(x)


class TestHostGroups:
    """new_group(ranks=[...]) builds a HOST group for the store-backed
    object collectives (reference ProcessGroup subgroups); device
    collectives reject it with an actionable error."""

    def test_new_group_ranks_subset_is_host_group(self):
        import paddle_tpu.distributed as dist
        g = dist.new_group(ranks=[0, 2])
        assert g.ranks == (0, 2) and g.nranks == 2

    def test_host_group_rejected_by_device_collectives(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.collective import _axes
        g = dist.new_group(ranks=[0, 2])
        with pytest.raises(RuntimeError, match="host-rank"):
            _axes(g)

    def test_group_members_validation(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.collective import _group_members
        g = dist.new_group(ranks=[0, 5])
        with pytest.raises(ValueError, match="outside world"):
            _group_members(g, "test")

    def test_single_process_world_group_gather(self):
        import paddle_tpu.distributed as dist
        out = []
        dist.all_gather_object(out, {"a": 1})
        assert out == [{"a": 1}]

    def test_user_rank_order_preserved(self):
        import paddle_tpu.distributed as dist
        g = dist.new_group(ranks=[2, 0])
        assert g.ranks == (2, 0)  # group-rank order = user order
        with pytest.raises(ValueError, match="duplicate"):
            dist.new_group(ranks=[1, 1])
