"""Pipeline-parallel tests: segmentation, placement, 1F1B loss parity with
the non-pipelined run (the reference's own test bar)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32))


@pytest.fixture()
def mesh_pp4():
    return dist.init_mesh({"dp": 2, "pp": 4})


def _descs():
    return [
        fleet.LayerDesc(nn.Linear, 8, 16),
        fleet.LayerDesc(nn.ReLU),
        fleet.LayerDesc(nn.Linear, 16, 16),
        fleet.LayerDesc(nn.GELU),
        fleet.LayerDesc(nn.Linear, 16, 8),
        fleet.LayerDesc(nn.ReLU),
        fleet.LayerDesc(nn.Linear, 8, 2),
    ]


class TestPipelineLayer:
    def test_uniform_segmentation(self, mesh_pp4):
        pl = fleet.PipelineLayer(_descs(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        sizes = [len(seg) for seg in pl._stage_layers]
        assert sum(sizes) == 7
        assert sizes == [2, 2, 2, 1]

    def test_params_placed_per_stage(self, mesh_pp4):
        pl = fleet.PipelineLayer(_descs(), num_stages=4)
        d0 = next(iter(pl._stage_layers[0][0].parameters())).data.devices()
        d3 = next(iter(pl._stage_layers[3][0].parameters())).data.devices()
        assert d0 != d3

    def test_sequential_forward_matches_plain(self, mesh_pp4):
        pt.seed(0)
        pl = fleet.PipelineLayer(_descs(), num_stages=4)
        pt.seed(0)
        plain = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                              nn.Linear(16, 16), nn.GELU(),
                              nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 2))
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        np.testing.assert_allclose(pl(t(x)).numpy(), plain(t(x)).numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_shared_layer_desc_ties_weights(self, mesh_pp4):
        descs = [fleet.SharedLayerDesc("emb", nn.Linear, 4, 4),
                 fleet.LayerDesc(nn.ReLU),
                 fleet.SharedLayerDesc("emb", nn.Linear, 4, 4)]
        pl = fleet.PipelineLayer(descs, num_stages=2)
        p = pl._stage_layers[0][0].weight
        q = pl._stage_layers[-1][-1].weight
        assert p is q


class TestPipelineTraining:
    def test_1f1b_matches_nonpipelined(self, mesh_pp4):
        rng = np.random.RandomState(0)
        X = rng.randn(16, 8).astype(np.float32)
        Y = X @ rng.randn(8, 2).astype(np.float32)

        # non-pipelined reference with identical micro-batch accumulation
        pt.seed(11)
        plain = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                              nn.Linear(16, 16), nn.GELU(),
                              nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 2))
        op = opt.AdamW(learning_rate=0.01, parameters=plain.parameters())
        n_micro = 4
        ref_losses = []
        for step in range(5):
            mb_losses = []
            for k in range(n_micro):
                xb = t(X[k * 4:(k + 1) * 4])
                yb = t(Y[k * 4:(k + 1) * 4])
                loss = nn.MSELoss()(plain(xb), yb)
                loss.backward(pt.to_tensor(np.float32(1.0 / n_micro)))
                mb_losses.append(float(loss.numpy()))
            op.step()
            op.clear_grad(set_to_zero=False)
            ref_losses.append(np.mean(mb_losses))

        # pipelined 4-stage 1F1B
        pt.seed(11)
        pl = fleet.PipelineLayer(_descs(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=n_micro)
        opp = opt.AdamW(learning_rate=0.01, parameters=pp.parameters())
        pp_losses = []
        for step in range(5):
            loss = pp.train_batch((t(X), t(Y)), opp)
            pp_losses.append(float(loss.numpy()))

        np.testing.assert_allclose(pp_losses, ref_losses, rtol=1e-4,
                                   atol=1e-6)

    def test_eval_batch(self, mesh_pp4):
        pl = fleet.PipelineLayer(_descs(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl)
        X = np.zeros((8, 8), np.float32)
        Y = np.zeros((8, 2), np.float32)
        loss = pp.eval_batch((t(X), t(Y)))
        assert np.isfinite(float(loss.numpy()))

    def test_micro_not_divisible_raises_or_works(self, mesh_pp4):
        pl = fleet.PipelineLayer(_descs(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=2)
        o = opt.SGD(learning_rate=0.1, parameters=pp.parameters())
        X = np.zeros((8, 8), np.float32)
        Y = np.zeros((8, 2), np.float32)
        loss = pp.train_batch((t(X), t(Y)), o)
        assert np.isfinite(float(loss.numpy()))


def _deep_descs(n=8, d=8):
    """n Linear+activation blocks of equal width (uniform chunks)."""
    out = []
    for i in range(n):
        out.append(fleet.LayerDesc(nn.Linear, d, d))
        out.append(fleet.LayerDesc(nn.GELU))
    out.append(fleet.LayerDesc(nn.Linear, d, 2))
    return out


def _plain_deep(n=8, d=8):
    layers = []
    for i in range(n):
        layers += [nn.Linear(d, d), nn.GELU()]
    layers.append(nn.Linear(d, 2))
    return nn.Sequential(*layers)


def _ref_losses(seed, X, Y, n_micro, steps, n=8, d=8):
    pt.seed(seed)
    plain = _plain_deep(n, d)
    op = opt.AdamW(learning_rate=0.01, parameters=plain.parameters())
    out = []
    mb = X.shape[0] // n_micro
    for _ in range(steps):
        mbl = []
        for k in range(n_micro):
            xb, yb = t(X[k * mb:(k + 1) * mb]), t(Y[k * mb:(k + 1) * mb])
            loss = nn.MSELoss()(plain(xb), yb)
            loss.backward(pt.to_tensor(np.float32(1.0 / n_micro)))
            mbl.append(float(loss.numpy()))
        op.step()
        op.clear_grad(set_to_zero=False)
        out.append(np.mean(mbl))
    return out


class TestInterleave:
    """Virtual-pipeline interleave (reference:
    pipeline_parallel.py:461 PipelineParallelWithInterleave)."""

    def test_chunk_round_robin_placement(self, mesh_pp4):
        pl = fleet.PipelineLayer(_deep_descs(), num_stages=4,
                                 num_virtual_pipeline_stages=2,
                                 loss_fn=nn.MSELoss())
        assert pl.num_chunks == 8
        # chunk c sits on stage c % 4 — first and fifth chunk share devices
        assert pl.chunk_device(0) is pl.chunk_device(4)
        assert pl.chunk_device(1) is not pl.chunk_device(0)

    @pytest.mark.parametrize("v", [1, 2])
    def test_interleave_loss_parity_depth4(self, mesh_pp4, v):
        rng = np.random.RandomState(1)
        X = rng.randn(16, 8).astype(np.float32)
        Y = rng.randn(16, 2).astype(np.float32)
        n_micro, steps = 8, 3
        ref = _ref_losses(7, X, Y, n_micro, steps)
        pt.seed(7)
        pl = fleet.PipelineLayer(_deep_descs(), num_stages=4,
                                 num_virtual_pipeline_stages=v,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=n_micro)
        op = opt.AdamW(learning_rate=0.01, parameters=pp.parameters())
        got = [float(pp.train_batch((t(X), t(Y)), op).numpy())
               for _ in range(steps)]
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-6)

    def test_interleave_shrinks_bubble(self, mesh_pp4):
        """The measured schedule bubble must drop with v=2 vs v=1 —
        the documented bubble measurement the interleave exists for."""
        n_micro = 8
        bubbles = {}
        for v in (1, 2):
            pt.seed(3)
            pl = fleet.PipelineLayer(_deep_descs(), num_stages=4,
                                     num_virtual_pipeline_stages=v,
                                     loss_fn=nn.MSELoss())
            pp = fleet.PipelineParallel(pl, accumulate_steps=n_micro)
            op = opt.SGD(learning_rate=0.01, parameters=pp.parameters())
            X = np.zeros((16, 8), np.float32)
            Y = np.zeros((16, 2), np.float32)
            pp.train_batch((t(X), t(Y)), op)
            bubbles[v] = pp.last_schedule_stats["bubble_fraction"]
        assert bubbles[2] < bubbles[1], bubbles

    def test_1f1b_bounds_in_flight_activations(self, mesh_pp4):
        """1F1B's point: peak live activation sets stay far below n_micro
        (all-forward-then-all-backward would hold n_micro * C)."""
        n_micro = 8
        pt.seed(3)
        pl = fleet.PipelineLayer(_deep_descs(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=n_micro)
        op = opt.SGD(learning_rate=0.01, parameters=pp.parameters())
        X = np.zeros((16, 8), np.float32)
        Y = np.zeros((16, 2), np.float32)
        pp.train_batch((t(X), t(Y)), op)
        stats = pp.last_schedule_stats
        S = pl.num_stages
        # textbook 1F1B ramp: stage s holds <= S - s sets; total S(S+1)/2
        assert stats["peak_in_flight_activations"] <= S * (S + 1) // 2
        assert stats["peak_in_flight_activations"] < n_micro * pl.num_chunks

    def test_schedule_emits_profiler_spans(self, mesh_pp4):
        import paddle_tpu.profiler as prof
        p = prof.Profiler(targets=[prof.ProfilerTarget.CPU])
        pt.seed(3)
        pl = fleet.PipelineLayer(_deep_descs(), num_stages=4,
                                 num_virtual_pipeline_stages=2,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=4)
        op = opt.SGD(learning_rate=0.01, parameters=pp.parameters())
        X = np.zeros((16, 8), np.float32)
        Y = np.zeros((16, 2), np.float32)
        p.start()
        pp.train_batch((t(X), t(Y)), op)
        p.stop()
        names = {e.name for e in p._events}
        assert any(n.startswith("pp_fwd_") for n in names)
        assert any(n.startswith("pp_bwd_") for n in names)


class TestRecomputeInterval:
    def test_recompute_interval_loss_parity(self, mesh_pp4):
        rng = np.random.RandomState(2)
        X = rng.randn(16, 8).astype(np.float32)
        Y = rng.randn(16, 2).astype(np.float32)
        n_micro, steps = 4, 3
        ref = _ref_losses(9, X, Y, n_micro, steps)
        pt.seed(9)
        pl = fleet.PipelineLayer(_deep_descs(), num_stages=4,
                                 recompute_interval=2,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=n_micro)
        op = opt.AdamW(learning_rate=0.01, parameters=pp.parameters())
        got = [float(pp.train_batch((t(X), t(Y)), op).numpy())
               for _ in range(steps)]
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-6)

    def test_recompute_interval_frees_tape_storage(self, mesh_pp4):
        """recompute must actually be engaged: count recompute-op nodes on
        the live tape by tracing chunk_forward with the interval on/off."""
        pt.seed(5)
        pl_on = fleet.PipelineLayer(_deep_descs(), num_stages=4,
                                    recompute_interval=2,
                                    loss_fn=nn.MSELoss())
        pl_on.train()
        x = pt.to_tensor(np.zeros((2, 8), np.float32),
                         stop_gradient=False)
        out = pl_on.chunk_forward(0, x)
        node = out._grad_node
        assert node is not None and "recompute" in (node.name or "")

    def test_recompute_interval_grad_parity(self, mesh_pp4):
        """Identical post-step parameters with recompute on vs off — i.e.
        the rematerialized backward produced the same gradients."""
        rng = np.random.RandomState(4)
        X = rng.randn(8, 8).astype(np.float32)
        Y = rng.randn(8, 2).astype(np.float32)
        params = {}
        for tag, interval in (("on", 2), ("off", 0)):
            pt.seed(6)
            pl = fleet.PipelineLayer(_deep_descs(), num_stages=4,
                                     recompute_interval=interval,
                                     loss_fn=nn.MSELoss())
            pp = fleet.PipelineParallel(pl, accumulate_steps=4)
            op = opt.SGD(learning_rate=0.1, parameters=pp.parameters())
            pp.train_batch((t(X), t(Y)), op)
            params[tag] = dict(pp.named_parameters())
        assert params["on"].keys() == params["off"].keys()
        for name in params["on"]:
            np.testing.assert_allclose(
                params["on"][name].numpy(), params["off"][name].numpy(),
                rtol=1e-5, atol=1e-6, err_msg=name)


class _SplitHead(nn.Layer):
    """Emits a (main, aux) tuple — multi-stream boundary."""

    def __init__(self, d):
        super().__init__()
        self.lin = nn.Linear(d, d)
        self.aux = nn.Linear(d, d)

    def forward(self, x):
        return self.lin(x), self.aux(x)


class _DualBlock(nn.Layer):
    """Transforms both streams (takes tuple, returns tuple)."""

    def __init__(self, d):
        super().__init__()
        self.a = nn.Linear(d, d)
        self.b = nn.Linear(d, d)

    def forward(self, x, aux):
        return nn.functional.relu(self.a(x)), nn.functional.relu(
            self.b(aux))


class _MergeHead(nn.Layer):
    """Merges the streams back to one tensor."""

    def __init__(self, d):
        super().__init__()
        self.lin = nn.Linear(2 * d, d)

    def forward(self, x, aux):
        return self.lin(pt.concat([x, aux], axis=-1))


class TestTupleActivations:
    """Pytree activations across stage boundaries (reference _p2p_helper
    handshakes arbitrary tensor tuples, p2p_communication.py:298):
    encoder-decoder-style dual-stream pipeline parity."""

    def _dual_layers(self):
        return [_SplitHead(8), _DualBlock(8), _DualBlock(8), _MergeHead(8)]

    def test_tuple_pipeline_loss_parity(self, mesh_pp4):
        rng = np.random.RandomState(4)
        X = rng.randn(8, 8).astype(np.float32)
        Y = rng.randn(8, 8).astype(np.float32)
        n_micro = 4

        pt.seed(21)
        plain_layers = self._dual_layers()
        op = opt.AdamW(learning_rate=0.01,
                       parameters=[p for l in plain_layers
                                   for p in l.parameters()])
        ref_losses = []
        for step in range(4):
            mb = []
            for k in range(n_micro):
                h = t(X[k * 2:(k + 1) * 2])
                for i, l in enumerate(plain_layers):
                    h = l(*h) if isinstance(h, tuple) else l(h)
                loss = nn.MSELoss()(h, t(Y[k * 2:(k + 1) * 2]))
                loss.backward(pt.to_tensor(np.float32(1.0 / n_micro)))
                mb.append(float(loss.numpy()))
            op.step()
            op.clear_grad(set_to_zero=False)
            ref_losses.append(np.mean(mb))

        pt.seed(21)
        pl = fleet.PipelineLayer(self._dual_layers(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=n_micro)
        opp = opt.AdamW(learning_rate=0.01, parameters=pp.parameters())
        got = []
        for step in range(4):
            got.append(float(pp.train_batch((t(X), t(Y)), opp).numpy()))
        np.testing.assert_allclose(got, ref_losses, rtol=1e-4, atol=1e-6)

    def test_tuple_inputs_supported(self, mesh_pp4):
        """Multi-tensor model INPUT: each element is micro-split."""
        pl = fleet.PipelineLayer([_DualBlock(8), _DualBlock(8),
                                  _DualBlock(8), _MergeHead(8)],
                                 num_stages=4, loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=2)
        o = opt.SGD(learning_rate=0.01, parameters=pp.parameters())
        rng = np.random.RandomState(0)
        xa = t(rng.randn(4, 8)); xb = t(rng.randn(4, 8))
        loss = pp.train_batch(((xa, xb), t(rng.randn(4, 8))), o)
        assert np.isfinite(float(loss.numpy()))


class TestSegmentation:
    def test_layer_regex_segmentation(self, mesh_pp4):
        """reference SegmentLayers 'layer:NAME': chunks get equal shares
        of matching layers; boundaries fall after each share."""
        descs = []
        for _ in range(8):
            descs += [fleet.LayerDesc(nn.Linear, 8, 8),
                      fleet.LayerDesc(nn.ReLU)]
        pl = fleet.PipelineLayer(descs, num_stages=4,
                                 seg_method="layer:Linear")
        sizes = [len(seg) for seg in pl._stage_layers]
        # reference cut: right AFTER each share's last match (the 2nd
        # Linear), so the trailing ReLUs ride with the NEXT chunk
        assert sizes == [3, 4, 4, 5]
        for seg in pl._stage_layers:
            assert sum(1 for l in seg
                       if type(l).__name__ == "Linear") == 2

    def test_layer_regex_uneven_raises(self, mesh_pp4):
        descs = [fleet.LayerDesc(nn.Linear, 8, 8) for _ in range(6)] + \
            [fleet.LayerDesc(nn.ReLU), fleet.LayerDesc(nn.ReLU)]
        with pytest.raises(ValueError, match="evenly"):
            fleet.PipelineLayer(descs, num_stages=4,
                                seg_method="layer:Linear")

    def test_uniform_params_balances_unbalanced_stack(self, mesh_pp4):
        """Embedding-heavy stage-0 stack: parameter-weighted segmentation
        must NOT put the same layer count everywhere."""
        descs = [fleet.LayerDesc(nn.Embedding, 1000, 64)] + \
            [fleet.LayerDesc(nn.Linear, 64, 64) for _ in range(7)]
        pl = fleet.PipelineLayer(descs, num_stages=4,
                                 seg_method="uniform_params")
        sizes = [len(seg) for seg in pl._stage_layers]
        assert sum(sizes) == 8 and min(sizes) >= 1
        # the embedding (64K params) dominates: stage 0 holds ONLY it,
        # while uniform would have put 2 layers there
        assert sizes[0] == 1
        counts = [sum(int(np.prod(p.shape)) for l in seg
                      for p in l.parameters())
                  for seg in pl._stage_layers]
        assert counts[0] >= max(counts[1:])

    def test_unknown_seg_method_raises(self, mesh_pp4):
        with pytest.raises(NotImplementedError):
            fleet.PipelineLayer(_descs(), num_stages=4,
                                seg_method="cost_model")
