"""Pipeline-parallel tests: segmentation, placement, 1F1B loss parity with
the non-pipelined run (the reference's own test bar)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32))


@pytest.fixture()
def mesh_pp4():
    return dist.init_mesh({"dp": 2, "pp": 4})


def _descs():
    return [
        fleet.LayerDesc(nn.Linear, 8, 16),
        fleet.LayerDesc(nn.ReLU),
        fleet.LayerDesc(nn.Linear, 16, 16),
        fleet.LayerDesc(nn.GELU),
        fleet.LayerDesc(nn.Linear, 16, 8),
        fleet.LayerDesc(nn.ReLU),
        fleet.LayerDesc(nn.Linear, 8, 2),
    ]


class TestPipelineLayer:
    def test_uniform_segmentation(self, mesh_pp4):
        pl = fleet.PipelineLayer(_descs(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        sizes = [len(seg) for seg in pl._stage_layers]
        assert sum(sizes) == 7
        assert sizes == [2, 2, 2, 1]

    def test_params_placed_per_stage(self, mesh_pp4):
        pl = fleet.PipelineLayer(_descs(), num_stages=4)
        d0 = next(iter(pl._stage_layers[0][0].parameters())).data.devices()
        d3 = next(iter(pl._stage_layers[3][0].parameters())).data.devices()
        assert d0 != d3

    def test_sequential_forward_matches_plain(self, mesh_pp4):
        pt.seed(0)
        pl = fleet.PipelineLayer(_descs(), num_stages=4)
        pt.seed(0)
        plain = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                              nn.Linear(16, 16), nn.GELU(),
                              nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 2))
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        np.testing.assert_allclose(pl(t(x)).numpy(), plain(t(x)).numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_shared_layer_desc_ties_weights(self, mesh_pp4):
        descs = [fleet.SharedLayerDesc("emb", nn.Linear, 4, 4),
                 fleet.LayerDesc(nn.ReLU),
                 fleet.SharedLayerDesc("emb", nn.Linear, 4, 4)]
        pl = fleet.PipelineLayer(descs, num_stages=2)
        p = pl._stage_layers[0][0].weight
        q = pl._stage_layers[-1][-1].weight
        assert p is q


class TestPipelineTraining:
    def test_1f1b_matches_nonpipelined(self, mesh_pp4):
        rng = np.random.RandomState(0)
        X = rng.randn(16, 8).astype(np.float32)
        Y = X @ rng.randn(8, 2).astype(np.float32)

        # non-pipelined reference with identical micro-batch accumulation
        pt.seed(11)
        plain = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                              nn.Linear(16, 16), nn.GELU(),
                              nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 2))
        op = opt.AdamW(learning_rate=0.01, parameters=plain.parameters())
        n_micro = 4
        ref_losses = []
        for step in range(5):
            mb_losses = []
            for k in range(n_micro):
                xb = t(X[k * 4:(k + 1) * 4])
                yb = t(Y[k * 4:(k + 1) * 4])
                loss = nn.MSELoss()(plain(xb), yb)
                loss.backward(pt.to_tensor(np.float32(1.0 / n_micro)))
                mb_losses.append(float(loss.numpy()))
            op.step()
            op.clear_grad(set_to_zero=False)
            ref_losses.append(np.mean(mb_losses))

        # pipelined 4-stage 1F1B
        pt.seed(11)
        pl = fleet.PipelineLayer(_descs(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=n_micro)
        opp = opt.AdamW(learning_rate=0.01, parameters=pp.parameters())
        pp_losses = []
        for step in range(5):
            loss = pp.train_batch((t(X), t(Y)), opp)
            pp_losses.append(float(loss.numpy()))

        np.testing.assert_allclose(pp_losses, ref_losses, rtol=1e-4,
                                   atol=1e-6)

    def test_eval_batch(self, mesh_pp4):
        pl = fleet.PipelineLayer(_descs(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl)
        X = np.zeros((8, 8), np.float32)
        Y = np.zeros((8, 2), np.float32)
        loss = pp.eval_batch((t(X), t(Y)))
        assert np.isfinite(float(loss.numpy()))

    def test_micro_not_divisible_raises_or_works(self, mesh_pp4):
        pl = fleet.PipelineLayer(_descs(), num_stages=4,
                                 loss_fn=nn.MSELoss())
        pp = fleet.PipelineParallel(pl, accumulate_steps=2)
        o = opt.SGD(learning_rate=0.1, parameters=pp.parameters())
        X = np.zeros((8, 8), np.float32)
        Y = np.zeros((8, 2), np.float32)
        loss = pp.train_batch((t(X), t(Y)), o)
        assert np.isfinite(float(loss.numpy()))
