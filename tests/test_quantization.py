"""Quantization (QAT/PTQ) and ASP tests — numpy oracles for the quant math,
training-behavior checks for STE and sparsity guarantees."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.quantization import (
    QAT, PTQ, QuantConfig, FakeQuanterWithAbsMaxObserver, AbsmaxObserver,
    QuantedLinear,
)
from paddle_tpu.quantization.quanters import (
    FakeQuanterWithAbsMaxObserverLayer,
)
import paddle_tpu.incubate.asp as asp


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


class TestFakeQuant:
    def test_fake_quant_oracle(self):
        q = FakeQuanterWithAbsMaxObserverLayer(bit_length=8)
        q.train()
        x = pt.to_tensor(np.array([-1.0, -0.5, 0.0, 0.26, 1.0], np.float32))
        out = q(x)
        # scale = absmax = 1.0; q = round(x*127)/127
        expect = np.round(np.array([-1, -0.5, 0, 0.26, 1]) * 127) / 127
        np.testing.assert_allclose(out.numpy(), expect, rtol=1e-6)

    def test_ste_gradient_is_identity(self):
        q = FakeQuanterWithAbsMaxObserverLayer()
        q.train()
        x = pt.to_tensor(np.array([0.3, -0.7, 0.9], np.float32),
                         stop_gradient=False)
        q(x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(3), rtol=1e-6)

    def test_moving_average_scale(self):
        q = FakeQuanterWithAbsMaxObserverLayer(moving_rate=0.9)
        q.train()
        q(pt.to_tensor(np.array([2.0], np.float32)))
        s1 = float(q.scales().numpy())
        assert s1 == pytest.approx(2.0)
        q(pt.to_tensor(np.array([4.0], np.float32)))
        s2 = float(q.scales().numpy())
        # (0.9*2*1 + 4) / (0.9*1 + 1)
        assert s2 == pytest.approx((0.9 * 2 + 4) / 1.9)


class TestQAT:
    def test_quantize_replaces_layers(self):
        pt.seed(0)
        model = Net()
        cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                          weight=FakeQuanterWithAbsMaxObserver())
        q = QAT(cfg)
        qmodel = q.quantize(model)
        assert isinstance(qmodel.fc1, QuantedLinear)
        assert isinstance(qmodel.fc2, QuantedLinear)
        # original is untouched (inplace=False)
        assert isinstance(model.fc1, nn.Linear)
        # no duplicate parameters
        params = qmodel.parameters()
        assert len(params) == len({id(p) for p in params})

    def test_qat_trains(self):
        pt.seed(0)
        rng = np.random.RandomState(0)
        model = Net()
        cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                          weight=FakeQuanterWithAbsMaxObserver())
        qmodel = QAT(cfg).quantize(model)
        qmodel.train()
        x = rng.randn(64, 8).astype(np.float32)
        w_true = rng.randn(8, 4).astype(np.float32)
        y = x @ w_true
        o = opt.Adam(learning_rate=0.01, parameters=qmodel.parameters())
        losses = []
        for _ in range(60):
            pred = qmodel(pt.to_tensor(x))
            loss = ((pred - pt.to_tensor(y)) ** 2).mean()
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.3

    def test_convert_bakes_quantized_weights(self):
        pt.seed(1)
        model = Net()
        cfg = QuantConfig(activation=None,
                          weight=FakeQuanterWithAbsMaxObserver())
        q = QAT(cfg)
        qmodel = q.quantize(model)
        qmodel.train()
        qmodel(pt.to_tensor(np.random.RandomState(1)
                            .randn(4, 8).astype(np.float32)))
        deployed = q.convert(qmodel)
        assert isinstance(deployed.fc1, nn.Linear)
        w = np.asarray(deployed.fc1.weight.data)
        scale = np.abs(np.asarray(qmodel.fc1.weight.data)).max()
        # every weight sits on the 255-level grid
        grid = w / (scale / 127)
        np.testing.assert_allclose(grid, np.round(grid), atol=1e-4)

    def test_type_and_name_config(self):
        pt.seed(0)
        model = Net()
        cfg = QuantConfig()
        cfg.add_type_config(nn.Linear,
                            activation=FakeQuanterWithAbsMaxObserver(),
                            weight=FakeQuanterWithAbsMaxObserver())
        qmodel = QAT(cfg).quantize(model)
        assert isinstance(qmodel.fc1, QuantedLinear)


class TestPTQ:
    def test_ptq_calibrate_and_convert(self):
        pt.seed(2)
        rng = np.random.RandomState(2)
        model = Net()
        cfg = QuantConfig(activation=AbsmaxObserver(),
                          weight=FakeQuanterWithAbsMaxObserver())
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        for _ in range(4):
            observed(pt.to_tensor(rng.randn(16, 8).astype(np.float32)))
        deployed = ptq.convert(observed)
        assert isinstance(deployed.fc1, QuantedLinear)
        fq = deployed.fc1.activation_quanter
        assert float(fq.scales().numpy()) > 0
        # deployed forward runs and is close to fp32 on calib data
        x = rng.randn(16, 8).astype(np.float32)
        ref = model(pt.to_tensor(x)).numpy()
        got = deployed(pt.to_tensor(x)).numpy()
        assert np.abs(ref - got).mean() < 0.1 * np.abs(ref).mean() + 0.05


class TestASP:
    def test_mask_1d(self):
        rng = np.random.RandomState(3)
        mat = rng.randn(8, 16).astype(np.float32)
        mask = asp.get_mask_1d(mat, 2, 4)
        assert asp.check_mask_1d(mat * mask, 2, 4)
        # keeps the largest-|w| entries
        kept = np.abs(mat.reshape(-1, 4) * mask.reshape(-1, 4)).sum()
        assert kept > 0.5 * np.abs(mat).sum()

    def test_mask_2d_greedy(self):
        rng = np.random.RandomState(4)
        mat = rng.randn(8, 8).astype(np.float32)
        mask = asp.get_mask_2d_greedy(mat, 2, 4)
        pruned = mat * mask
        for i0 in range(0, 8, 4):
            for j0 in range(0, 8, 4):
                blk = pruned[i0:i0 + 4, j0:j0 + 4] != 0
                assert (blk.sum(axis=0) <= 2).all()
                assert (blk.sum(axis=1) <= 2).all()

    def test_prune_and_guaranteed_training(self):
        pt.seed(3)
        rng = np.random.RandomState(5)
        model = Net()
        asp.prune_model(model, n=2, m=4)
        assert asp.calculate_density(model.fc1.weight) <= 0.5 + 1e-6
        o = asp.decorate(opt.SGD(learning_rate=0.05,
                                 parameters=model.parameters()))
        x = rng.randn(32, 8).astype(np.float32)
        y = rng.randn(32, 4).astype(np.float32)
        for _ in range(5):
            loss = ((model(pt.to_tensor(x)) - pt.to_tensor(y)) ** 2).mean()
            loss.backward()
            o.step()
            o.clear_grad()
        # sparsity survives training steps
        assert asp.check_mask_1d(np.asarray(model.fc1.weight.data), 2, 4)
        assert asp.calculate_density(model.fc1.weight) <= 0.5 + 1e-6

    def test_excluded_layers(self):
        pt.seed(4)
        model = Net()
        asp.set_excluded_layers(model, ["fc2.weight"])
        asp.prune_model(model)
        assert asp.calculate_density(model.fc2.weight) > 0.9
        asp.reset_excluded_layers(model)


class TestReviewRegressions:
    def test_layer_config_survives_deepcopy(self):
        pt.seed(5)
        model = Net()
        cfg = QuantConfig()
        cfg.add_layer_config(model.fc1,
                             activation=FakeQuanterWithAbsMaxObserver(),
                             weight=FakeQuanterWithAbsMaxObserver())
        qmodel = QAT(cfg).quantize(model)  # inplace=False deepcopies
        assert isinstance(qmodel.fc1, QuantedLinear)
        assert isinstance(qmodel.fc2, nn.Linear)

    def test_name_config_uses_full_path(self):
        pt.seed(6)

        class Outer(nn.Layer):
            def __init__(self):
                super().__init__()
                self.block = Net()

            def forward(self, x):
                return self.block(x)

        model = Outer()
        cfg = QuantConfig()
        cfg.add_name_config("block.fc1",
                            activation=FakeQuanterWithAbsMaxObserver(),
                            weight=FakeQuanterWithAbsMaxObserver())
        qmodel = QAT(cfg).quantize(model)
        assert isinstance(qmodel.block.fc1, QuantedLinear)
        assert isinstance(qmodel.block.fc2, nn.Linear)

    def test_ptq_weight_quanter_calibrated(self):
        pt.seed(7)
        rng = np.random.RandomState(7)
        model = Net()
        cfg = QuantConfig(activation=AbsmaxObserver(),
                          weight=FakeQuanterWithAbsMaxObserver())
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        observed(pt.to_tensor(rng.randn(8, 8).astype(np.float32)))
        deployed = ptq.convert(observed)
        wq = deployed.fc1.weight_quanter
        assert wq is not None and float(wq.scales().numpy()) > 0
        assert not deployed.fc1.activation_quanter.training

    def test_quanter_decorator_makes_factory(self):
        from paddle_tpu.quantization import quanter, BaseQuanter
        from paddle_tpu.quantization.factory import QuanterFactory

        @quanter("MyQ")
        class MyQ(BaseQuanter):
            def __init__(self, k=1):
                super().__init__()
                self.k = k

            def forward(self, x):
                return x

            def scales(self):
                return None

        f = MyQ(k=3)
        assert isinstance(f, QuanterFactory)
        inst = f._instance(None)
        assert inst.k == 3

    def test_asp_registry_weakrefs(self):
        import gc
        import paddle_tpu.incubate.asp as asp_mod
        pt.seed(8)
        gc.collect()
        asp_mod._prune_dead(asp_mod._param_masks)
        before = len(asp_mod._param_masks)
        m = Net()
        asp.prune_model(m)
        assert len(asp_mod._param_masks) > before
        del m
        gc.collect()
        asp_mod._prune_dead(asp_mod._param_masks)
        assert len(asp_mod._param_masks) == before


class TestInt8Execution:
    """TRUE int8 compute (reference executes int8 in its TensorRT
    inference engines; here XLA's s8xs8->s32 dot): converted models hold
    int8 weights and match the fake-quant simulation."""

    def _deployed(self, seed=5):
        pt.seed(seed)
        rng = np.random.RandomState(seed)
        model = Net()
        cfg = QuantConfig(activation=AbsmaxObserver(),
                          weight=FakeQuanterWithAbsMaxObserver())
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        for _ in range(4):
            observed(pt.to_tensor(rng.randn(16, 8).astype(np.float32)))
        return model, ptq.convert(observed), rng

    def test_int8_matches_fake_quant_simulation(self):
        from paddle_tpu.quantization import convert_to_int8, Int8Linear
        import jax.numpy as jnp

        _, deployed, rng = self._deployed()
        int8_model = convert_to_int8(deployed)
        assert isinstance(int8_model.fc1, Int8Linear)
        assert int8_model.fc1.w_q.data.dtype == jnp.int8
        x = rng.randn(16, 8).astype(np.float32)
        sim = deployed(pt.to_tensor(x)).numpy()
        got = int8_model(pt.to_tensor(x)).numpy()
        # int32 accumulation vs f32 simulation of the same grid: exact
        # while products fit f32 (K=8 here)
        np.testing.assert_allclose(got, sim, rtol=1e-5, atol=1e-5)

    def test_int8_close_to_fp32(self):
        from paddle_tpu.quantization import convert_to_int8

        model, deployed, rng = self._deployed(seed=6)
        int8_model = convert_to_int8(deployed)
        x = rng.randn(16, 8).astype(np.float32)
        ref = model(pt.to_tensor(x)).numpy()
        got = int8_model(pt.to_tensor(x)).numpy()
        assert np.abs(ref - got).mean() < 0.1 * np.abs(ref).mean() + 0.05

    def test_int8_conv(self):
        from paddle_tpu.quantization import convert_to_int8, Int8Conv2D
        import paddle_tpu.nn as nn

        class ConvNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(3, 8, 3, padding=1)

            def forward(self, x):
                return self.conv(x)

        pt.seed(7)
        rng = np.random.RandomState(7)
        model = ConvNet()
        cfg = QuantConfig(activation=AbsmaxObserver(),
                          weight=FakeQuanterWithAbsMaxObserver())
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        for _ in range(3):
            observed(pt.to_tensor(rng.randn(2, 3, 8, 8)
                                  .astype(np.float32)))
        deployed = ptq.convert(observed)
        int8_model = convert_to_int8(deployed)
        assert isinstance(int8_model.conv, Int8Conv2D)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        sim = deployed(pt.to_tensor(x)).numpy()
        got = int8_model(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, sim, rtol=1e-4, atol=1e-4)

    def test_uncalibrated_raises(self):
        from paddle_tpu.quantization import convert_to_int8
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization.wrapper import QuantedLinear
        from paddle_tpu.quantization.config import SingleLayerConfig

        lin = nn.Linear(4, 4)
        quanted = QuantedLinear(lin, SingleLayerConfig(
            FakeQuanterWithAbsMaxObserver(), FakeQuanterWithAbsMaxObserver()))

        class Holder(nn.Layer):
            def __init__(self):
                super().__init__()
                self.q = quanted

            def forward(self, x):
                return self.q(x)

        with pytest.raises(ValueError, match="calibrated|scales"):
            convert_to_int8(Holder())

    def test_int8_exports_through_jit_save(self, tmp_path):
        """int8 deployment composes with the inference stack: the int8
        weights export as constants in the saved program and the
        Predictor serves them (the reference's TRT-engine-with-int8
        analog: calibrate -> convert -> serialize -> serve)."""
        from paddle_tpu.quantization import convert_to_int8

        _, deployed, rng = self._deployed(seed=8)
        int8_model = convert_to_int8(deployed)
        x = rng.randn(4, 8).astype(np.float32)
        want = int8_model(pt.to_tensor(x)).numpy()

        path = str(tmp_path / "int8_model")
        pt.jit.save(int8_model, path,
                    input_spec=[pt.static.InputSpec([4, 8], "float32")])
        from paddle_tpu import inference
        cfg = inference.Config(path)
        pred = inference.create_predictor(cfg)
        got = pred.run([x])[0]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_int8_conv_padding_forms_and_nhwc(self):
        """Conv2D padding variants ([h, w] lists, flat pairs) and NHWC
        layouts survive int8 conversion (review regressions)."""
        from paddle_tpu.quantization import convert_to_int8
        import paddle_tpu.nn as nn

        for pad, fmt in [([1, 2], "NCHW"), (1, "NHWC")]:
            class ConvNet(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.conv = nn.Conv2D(3, 4, 3, padding=pad,
                                          data_format=fmt)

                def forward(self, x):
                    return self.conv(x)

            pt.seed(9)
            rng = np.random.RandomState(9)
            model = ConvNet()
            shape = (2, 3, 8, 8) if fmt == "NCHW" else (2, 8, 8, 3)
            cfg = QuantConfig(activation=AbsmaxObserver(),
                              weight=FakeQuanterWithAbsMaxObserver())
            ptq = PTQ(cfg)
            observed = ptq.quantize(model)
            for _ in range(3):
                observed(pt.to_tensor(rng.randn(*shape)
                                      .astype(np.float32)))
            deployed = ptq.convert(observed)
            int8_model = convert_to_int8(deployed)
            x = rng.randn(*shape).astype(np.float32)
            sim = deployed(pt.to_tensor(x)).numpy()
            got = int8_model(pt.to_tensor(x)).numpy()
            np.testing.assert_allclose(got, sim, rtol=1e-4, atol=1e-4,
                                       err_msg=f"pad={pad} fmt={fmt}")

    def test_int8_distinct_weight_bits(self):
        """4-bit weight quanter + 8-bit activations: the int path must use
        each quanter's own bound (review regression)."""
        from paddle_tpu.quantization import convert_to_int8

        pt.seed(11)
        rng = np.random.RandomState(11)
        model = Net()
        cfg = QuantConfig(
            activation=AbsmaxObserver(),
            weight=FakeQuanterWithAbsMaxObserver(bit_length=4))
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        for _ in range(4):
            observed(pt.to_tensor(rng.randn(16, 8).astype(np.float32)))
        deployed = ptq.convert(observed)
        int8_model = convert_to_int8(deployed)
        assert int8_model.fc1.w_bits == 4 and int8_model.fc1.x_bits == 8
        x = rng.randn(16, 8).astype(np.float32)
        sim = deployed(pt.to_tensor(x)).numpy()
        got = int8_model(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, sim, rtol=1e-5, atol=1e-5)

    def test_per_channel_observer_oracle(self):
        """Per-channel weight observer (reference ptq_quantizer.py:137
        PerChannelAbsmaxQuantizer): one scale per output channel, and the
        fake-quant grid applies per channel."""
        from paddle_tpu.quantization.observers import (
            PerChannelAbsmaxObserverLayer)

        q = PerChannelAbsmaxObserverLayer(quant_bits=8, quant_axis=-1)
        q.train()
        w = np.array([[1.0, 0.01], [-2.0, 0.005]], np.float32)
        out = q(pt.to_tensor(w))
        np.testing.assert_allclose(q.scales().numpy(), [2.0, 0.01],
                                   rtol=1e-6)
        # column 1's tiny weights survive on their OWN grid
        expect = np.stack([np.round(w[:, 0] / 2.0 * 127) * 2.0 / 127,
                           np.round(w[:, 1] / 0.01 * 127) * 0.01 / 127], 1)
        np.testing.assert_allclose(out.numpy(), expect, rtol=1e-5)
        assert q.quant_axis() == -1

    def test_per_channel_int8_linear_matches_simulation(self):
        from paddle_tpu.quantization import (
            convert_to_int8, PerChannelAbsmaxObserver, Int8Linear)

        pt.seed(21)
        rng = np.random.RandomState(21)
        model = Net()
        cfg = QuantConfig(activation=AbsmaxObserver(),
                          weight=PerChannelAbsmaxObserver())
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        for _ in range(4):
            observed(pt.to_tensor(rng.randn(16, 8).astype(np.float32)))
        deployed = ptq.convert(observed)
        int8_model = convert_to_int8(deployed)
        assert isinstance(int8_model.fc1, Int8Linear)
        assert np.asarray(int8_model.fc1.w_scale).shape == (16,)
        x = rng.randn(16, 8).astype(np.float32)
        sim = deployed(pt.to_tensor(x)).numpy()
        got = int8_model(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, sim, rtol=1e-5, atol=1e-5)

    def test_per_channel_beats_per_tensor_on_skewed_conv(self):
        """The reference defaults PTQ weight quant to per-channel because
        per-tensor costs accuracy on conv stacks: a hot filter inflates
        every other filter's grid. Measure the delta."""
        from paddle_tpu.quantization import (
            convert_to_int8, PerChannelAbsmaxObserver, Int8Conv2D)

        class ConvNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(3, 8, 3, padding=1)

            def forward(self, x):
                return self.conv(x)

        def build(weight_factory, seed=23):
            pt.seed(seed)
            rng = np.random.RandomState(seed)
            model = ConvNet()
            # skew the filters: one hot filter, the rest tiny
            w = np.asarray(model.conv.weight.data).copy()
            w[0] *= 50.0
            w[1:] *= 0.05
            import jax.numpy as jnp
            model.conv.weight.data = jnp.asarray(w)
            cfg = QuantConfig(activation=AbsmaxObserver(),
                              weight=weight_factory)
            ptq = PTQ(cfg)
            observed = ptq.quantize(model)
            for _ in range(3):
                observed(pt.to_tensor(rng.randn(2, 3, 8, 8)
                                      .astype(np.float32)))
            deployed = ptq.convert(observed)
            x = rng.randn(4, 3, 8, 8).astype(np.float32)
            ref = model(pt.to_tensor(x)).numpy()
            got = convert_to_int8(deployed)(pt.to_tensor(x)).numpy()
            # error on the TINY channels (the ones a shared grid crushes)
            return np.abs(ref[:, 1:] - got[:, 1:]).mean() / \
                np.abs(ref[:, 1:]).mean()

        err_pt = build(FakeQuanterWithAbsMaxObserver())
        err_pc = build(PerChannelAbsmaxObserver())
        assert err_pc < err_pt * 0.2, (err_pc, err_pt)

    def test_per_channel_conv_int8_layer(self):
        from paddle_tpu.quantization import (
            convert_to_int8, PerChannelAbsmaxObserver, Int8Conv2D)

        class ConvNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(3, 4, 3, padding=1)

            def forward(self, x):
                return self.conv(x)

        pt.seed(25)
        rng = np.random.RandomState(25)
        model = ConvNet()
        cfg = QuantConfig(activation=AbsmaxObserver(),
                          weight=PerChannelAbsmaxObserver())
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        for _ in range(3):
            observed(pt.to_tensor(rng.randn(2, 3, 8, 8)
                                  .astype(np.float32)))
        deployed = ptq.convert(observed)
        int8_model = convert_to_int8(deployed)
        assert isinstance(int8_model.conv, Int8Conv2D)
        assert np.asarray(int8_model.conv.w_scale).shape == (4,)
        assert int8_model.conv.w_axis == 0
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        sim = deployed(pt.to_tensor(x)).numpy()
        got = int8_model(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, sim, rtol=1e-4, atol=1e-4)

    def test_per_channel_activation_rejected(self):
        """Activation quantization is per-tensor only; a per-channel
        activation observer must fail LOUDLY at convert time, not crash
        on the first forward of the converted model (review regression)."""
        from paddle_tpu.quantization import PerChannelAbsmaxObserver

        pt.seed(27)
        rng = np.random.RandomState(27)
        model = Net()
        cfg = QuantConfig(activation=PerChannelAbsmaxObserver(),
                          weight=FakeQuanterWithAbsMaxObserver())
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        observed(pt.to_tensor(rng.randn(4, 8).astype(np.float32)))
        with pytest.raises(ValueError, match="per-tensor"):
            ptq.convert(observed)

    def test_per_channel_qat_convert_bakes_weights(self):
        """QAT.convert must bake per-channel fake-quant grids (review
        regression: the sibling convert path crashed on vector scales)."""
        from paddle_tpu.quantization import PerChannelAbsmaxObserver

        pt.seed(31)
        model = Net()
        cfg = QuantConfig(activation=None,
                          weight=PerChannelAbsmaxObserver())
        q = QAT(cfg)
        qmodel = q.quantize(model)
        qmodel.train()
        qmodel(pt.to_tensor(np.random.RandomState(31)
                            .randn(4, 8).astype(np.float32)))
        deployed = q.convert(qmodel)
        assert isinstance(deployed.fc1, nn.Linear)
        w = np.asarray(deployed.fc1.weight.data)
        scales = np.asarray(qmodel.fc1.weight_quanter.scales().numpy())
        assert scales.shape == (16,)
        grid = w / np.maximum(scales[None, :] / 127, 1e-12)
        np.testing.assert_allclose(grid, np.round(grid), atol=1e-3)

    def test_per_channel_scale_survives_state_dict(self):
        """The observer's scale buffer must round-trip through
        state_dict/set_state_dict (review regression: a None buffer
        vanished from checkpoints)."""
        from paddle_tpu.quantization import PerChannelAbsmaxObserver

        pt.seed(33)
        rng = np.random.RandomState(33)

        def build():
            pt.seed(33)
            model = Net()
            cfg = QuantConfig(activation=None,
                              weight=PerChannelAbsmaxObserver())
            return QAT(cfg).quantize(model)

        qmodel = build()
        qmodel.train()
        qmodel(pt.to_tensor(rng.randn(4, 8).astype(np.float32)))
        state = qmodel.state_dict()
        fresh = build()
        fresh.set_state_dict(state)
        got = np.asarray(fresh.fc1.weight_quanter.scales().numpy())
        want = np.asarray(qmodel.fc1.weight_quanter.scales().numpy())
        np.testing.assert_allclose(got, want, rtol=1e-7)

    def test_per_channel_pruned_channel_converts(self):
        """An all-zero (pruned) output channel yields scale 0 for that
        channel; conversion must clamp it, not reject the calibrated
        model (review regression)."""
        from paddle_tpu.quantization import (
            convert_to_int8, PerChannelAbsmaxObserver)
        import jax.numpy as jnp

        pt.seed(35)
        rng = np.random.RandomState(35)
        model = Net()
        w = np.asarray(model.fc1.weight.data).copy()
        w[:, 0] = 0.0  # prune output channel 0
        model.fc1.weight.data = jnp.asarray(w)
        cfg = QuantConfig(activation=AbsmaxObserver(),
                          weight=PerChannelAbsmaxObserver())
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        for _ in range(3):
            observed(pt.to_tensor(rng.randn(8, 8).astype(np.float32)))
        deployed = ptq.convert(observed)
        int8_model = convert_to_int8(deployed)
        x = rng.randn(8, 8).astype(np.float32)
        out = int8_model(pt.to_tensor(x)).numpy()
        assert np.isfinite(out).all()
        sim = deployed(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, sim, rtol=1e-4, atol=1e-4)

    def test_int8_conv_same_padding(self):
        """String padding ('SAME') passes through to lax (review
        regression)."""
        from paddle_tpu.quantization import convert_to_int8
        import paddle_tpu.nn as nn

        class ConvNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(3, 4, 3, padding="SAME")

            def forward(self, x):
                return self.conv(x)

        pt.seed(13)
        rng = np.random.RandomState(13)
        model = ConvNet()
        cfg = QuantConfig(activation=AbsmaxObserver(),
                          weight=FakeQuanterWithAbsMaxObserver())
        ptq = PTQ(cfg)
        observed = ptq.quantize(model)
        for _ in range(3):
            observed(pt.to_tensor(rng.randn(2, 3, 8, 8)
                                  .astype(np.float32)))
        deployed = ptq.convert(observed)
        int8_model = convert_to_int8(deployed)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        sim = deployed(pt.to_tensor(x)).numpy()
        got = int8_model(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, sim, rtol=1e-4, atol=1e-4)
