"""Custom C++ op extension tests: compile a real .so with g++, register ops,
check forward/backward against numpy oracles, eager and under jit."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.utils import cpp_extension


SRC = textwrap.dedent("""
    #include <cstdint>
    #include <cmath>

    static int64_t numel(const int64_t* shape, int32_t nd) {
      int64_t n = 1;
      for (int32_t i = 0; i < nd; ++i) n *= shape[i];
      return n;
    }

    extern "C" void swish(const float** ins, const int64_t** in_shapes,
                          const int32_t* in_ndims, int32_t n_in,
                          float** outs, const int64_t** out_shapes,
                          const int32_t* out_ndims, int32_t n_out) {
      const float* x = ins[0];
      int64_t n = numel(in_shapes[0], in_ndims[0]);
      for (int64_t i = 0; i < n; ++i)
        outs[0][i] = x[i] / (1.0f + std::exp(-x[i]));
    }

    // grad inputs: (x, gout); writes gx
    extern "C" void swish_grad(const float** ins, const int64_t** in_shapes,
                               const int32_t* in_ndims, int32_t n_in,
                               float** outs, const int64_t** out_shapes,
                               const int32_t* out_ndims, int32_t n_out) {
      const float* x = ins[0];
      const float* g = ins[1];
      int64_t n = numel(in_shapes[0], in_ndims[0]);
      for (int64_t i = 0; i < n; ++i) {
        float s = 1.0f / (1.0f + std::exp(-x[i]));
        outs[0][i] = g[i] * (s + x[i] * s * (1.0f - s));
      }
    }

    // two inputs, no grad: elementwise max
    extern "C" void emax(const float** ins, const int64_t** in_shapes,
                         const int32_t* in_ndims, int32_t n_in,
                         float** outs, const int64_t** out_shapes,
                         const int32_t* out_ndims, int32_t n_out) {
      int64_t n = numel(in_shapes[0], in_ndims[0]);
      for (int64_t i = 0; i < n; ++i)
        outs[0][i] = ins[0][i] > ins[1][i] ? ins[0][i] : ins[1][i];
    }
""")


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext")
    src = d / "ops.cpp"
    src.write_text(SRC)
    return cpp_extension.load("testops", [str(src)],
                              build_directory=str(d))


@pytest.fixture(scope="module")
def swish(ext):
    return cpp_extension.custom_op(ext, "swish",
                                   infer_shape=lambda s: s)


def test_forward_oracle(swish):
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    out = swish(pt.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), x / (1 + np.exp(-x)),
                               rtol=1e-6)


def test_backward_matches_finite_diff(swish):
    x = pt.to_tensor(np.random.RandomState(1).randn(3, 3)
                     .astype(np.float32), stop_gradient=False)
    swish(x).sum().backward()
    eps = 1e-3
    xa = x.numpy()
    num = np.zeros_like(xa)
    f = lambda a: (a / (1 + np.exp(-a))).sum()
    for i in range(3):
        for j in range(3):
            p = xa.copy(); p[i, j] += eps
            m = xa.copy(); m[i, j] -= eps
            num[i, j] = (f(p) - f(m)) / (2 * eps)
    np.testing.assert_allclose(x.grad.numpy(), num, rtol=1e-2, atol=1e-3)


def test_two_input_op(ext):
    emax = cpp_extension.custom_op(ext, "emax",
                                   infer_shape=lambda a, b: a,
                                   grad_op=None)
    a = np.random.RandomState(2).randn(6).astype(np.float32)
    b = np.random.RandomState(3).randn(6).astype(np.float32)
    out = emax(pt.to_tensor(a), pt.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), np.maximum(a, b))


def test_custom_op_under_jit(ext, swish):
    """The op must survive to_static capture (host callback in the
    compiled program)."""
    import paddle_tpu.nn as nn

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(5, 5)

        def forward(self, x):
            return swish(self.fc(x))

    pt.seed(0)
    m = M()
    m.eval()
    x = pt.to_tensor(np.random.RandomState(4).randn(2, 5)
                     .astype(np.float32))
    eager = m(x).numpy()
    static = pt.jit.to_static(m)
    np.testing.assert_allclose(static(x).numpy(), eager, rtol=1e-5,
                               atol=1e-6)


def test_accessible_via_extension_attr(ext, swish):
    assert ext.swish is swish


def test_build_error_surfaces(tmp_path):
    bad = tmp_path / "bad.cpp"
    bad.write_text("this is not C++")
    with pytest.raises(RuntimeError, match="build failed"):
        cpp_extension.load("badops", [str(bad)],
                           build_directory=str(tmp_path))
