"""Quantized serving backend (ISSUE 20): weight-only PTQ + int8 KV.

Coverage contract: ``quantize_state`` leaf selection + roundtrip error
bounds + calibration-gated skipping, the int8-weight engine matching
the full-precision greedy oracle (and bounded logit MSE through the
dequantized weights), the int8 paged-KV engine matching the same
oracle, the ``load_weights`` dtype guard (cast loudly / refuse loudly,
naming the leaf), and the memory-ledger-pinned claim that int8 KV
serves 2x ``max_batch`` inside the full-precision engine's pool bytes
— every engine here compiling its unified step exactly once.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.quantization.weight_only import (
    QuantizedLeaf, quantize_state, quantized_bytes, sensitive_params)
from paddle_tpu.serving import ServingEngine


def _tiny(seed=0):
    pt.seed(seed)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=True))
    m.eval()
    return m


def _state_of(model):
    from paddle_tpu.jit.functional import functional_state
    train, frozen, buffers = functional_state(model)
    return {**train, **frozen, **buffers}


def _eager_continuation(model, prompt, max_new_tokens):
    out = model.generate(pt.to_tensor(np.asarray(prompt)[None, :]),
                         max_new_tokens=max_new_tokens,
                         temperature=0.0).numpy()[0]
    return [int(t) for t in out[len(prompt):]]


# ---------------- quantize_state unit ----------------------------------------

def test_quantize_state_targets_and_roundtrip():
    model = _tiny(0)
    state = _state_of(model)
    qstate = quantize_state(state, "int8_wo")
    quantized = {k for k, v in qstate.items()
                 if isinstance(v, QuantizedLeaf)}
    # every projection quantized, embeddings/norms untouched
    assert any(k.endswith("q_proj.weight") for k in quantized)
    assert any(k.endswith("down_proj.weight") for k in quantized)
    assert not any("embed" in k or "norm" in k for k in quantized)
    assert set(qstate) == set(state)  # keys unchanged
    for k in quantized:
        leaf, orig = qstate[k], np.asarray(state[k])
        # logical view: shape/dtype of the tensor it replaced
        assert tuple(leaf.shape) == tuple(orig.shape)
        assert str(leaf.dtype) == str(orig.dtype)
        assert str(leaf.storage_dtype) == "int8"
        err = np.abs(np.asarray(leaf.dequantize()) - orig)
        scale = np.abs(orig).max(axis=0)  # per-channel grid step bound
        assert float((err - scale / 127.0 * 0.51).max()) <= 1e-6, k
    assert quantized_bytes(qstate) > 0


def test_calibration_gate_skips_outlier_layers():
    model = _tiny(0)
    state = _state_of(model)
    # layer-0 attention tap screams outliers; layer-1 looks healthy
    cal = {"version": 1, "taps": {
        "layers.0.attn": {"absmax": 1000.0, "p99": 1.0},
        "layers.1.attn": {"absmax": 2.0, "p99": 1.0},
    }}
    names = [k for k in state if k.endswith("q_proj.weight")]
    skip = sensitive_params(names, cal)
    assert any("layers.0." in k for k in skip)
    assert not any("layers.1." in k for k in skip)
    qstate = quantize_state(state, "int8_wo", calibration=cal)
    for k in names:
        is_q = isinstance(qstate[k], QuantizedLeaf)
        assert is_q != ("layers.0." in k), k


# ---------------- int8 weights vs the full-precision oracle ------------------

def test_int8_weight_engine_greedy_parity_and_logit_mse():
    model = _tiny(1)
    prompt = list(np.random.RandomState(0).randint(1, 128, 12))
    oracle = _eager_continuation(model, prompt, 8)

    engine = ServingEngine(model, max_batch=4, max_blocks=32,
                           block_size=4, prefill_chunk=4,
                           quantize="int8_wo")
    engine.start()
    assert engine.stats()["weight_dtype"] == "int8"
    got = engine.submit(prompt, max_new_tokens=8).result(
        timeout=60)["token_ids"]
    assert got == oracle
    assert engine.step_traces == 1
    engine.shutdown()

    # logit MSE through the exact dequantized weights the step consumes
    state = _state_of(model)
    deq = {k: (v.dequantize() if isinstance(v, QuantizedLeaf) else v)
           for k, v in quantize_state(state, "int8_wo").items()}
    x = pt.to_tensor(np.asarray(prompt)[None, :])
    ref = model(x).numpy()
    model.set_state_dict({k: pt.to_tensor(np.asarray(v))
                          for k, v in deq.items()})
    quant_logits = model(x).numpy()
    mse = float(np.mean((quant_logits - ref) ** 2))
    assert mse < 1e-2, mse


# ---------------- int8 paged KV vs the same oracle ---------------------------

def test_int8_kv_engine_greedy_parity():
    model = _tiny(2)
    rng = np.random.RandomState(1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # rpa->gather fallback warning
        engine = ServingEngine(model, max_batch=4, max_blocks=32,
                               block_size=4, prefill_chunk=4,
                               kv_dtype="int8")
    engine.start()
    assert engine.stats()["kv_dtype"] == "int8"
    for seed in range(2):
        prompt = list(rng.randint(1, 128, 10 + 3 * seed))
        oracle = _eager_continuation(model, prompt, 6)
        got = engine.submit(prompt, max_new_tokens=6).result(
            timeout=60)["token_ids"]
        assert got == oracle, f"prompt {seed}"
    assert engine.step_traces == 1
    engine.shutdown()


# ---------------- load_weights dtype guard (satellite 2) ---------------------

def test_load_weights_dtype_guard(tmp_path):
    model = _tiny(3)
    engine = ServingEngine(model, max_batch=2, max_blocks=16,
                           block_size=4, prefill_chunk=4)
    engine.start()
    sd = {k: np.asarray(v.numpy()) for k, v in model.state_dict().items()}
    victim = next(k for k in sd if k.endswith("q_proj.weight"))

    # floating -> floating mismatch: cast loudly, engine keeps serving
    cast_sd = dict(sd, **{victim: sd[victim].astype(np.float64)})
    p64 = str(tmp_path / "cast.pdparams")
    pt.save(cast_sd, p64)
    with pytest.warns(RuntimeWarning, match=victim):
        engine.load_weights(p64)
    prompt = [3, 5, 7, 11]
    got = engine.submit(prompt, max_new_tokens=4).result(
        timeout=60)["token_ids"]
    assert got == _eager_continuation(model, prompt, 4)
    assert engine.step_traces == 1  # the swap never retraced

    # anything non-floating refuses with the leaf named
    bad_sd = dict(sd, **{victim: np.zeros(sd[victim].shape, np.int32)})
    pbad = str(tmp_path / "refuse.pdparams")
    pt.save(bad_sd, pbad)
    with pytest.raises(ValueError, match=victim):
        engine.load_weights(pbad)
    engine.shutdown()


def test_load_weights_dtype_guard_quantized_logical(tmp_path):
    """The guard reads a QuantizedLeaf's LOGICAL dtype: a matching-dtype
    checkpoint loads into an int8 engine (and is re-quantized), while
    a float64 poke is cast loudly with the leaf named."""
    model = _tiny(4)
    engine = ServingEngine(model, max_batch=2, max_blocks=16,
                           block_size=4, prefill_chunk=4,
                           quantize="int8_wo")
    sd = {k: np.asarray(v.numpy()) for k, v in model.state_dict().items()}
    ok = str(tmp_path / "ok.pdparams")
    pt.save(sd, ok)
    engine.load_weights(ok)  # logical f32 == checkpoint f32: no error
    assert any(isinstance(v, QuantizedLeaf)
               for v in engine._st.values())  # re-quantized after swap
    victim = next(k for k in sd if k.endswith("up_proj.weight"))
    bad = dict(sd, **{victim: sd[victim].astype(np.float64)})
    pbad = str(tmp_path / "bad.pdparams")
    pt.save(bad, pbad)
    with pytest.warns(RuntimeWarning, match=victim):
        engine.load_weights(pbad)
    assert engine.step_traces == 0  # never even compiled: still no trace
    engine.shutdown()


# ---------------- int8 KV doubles max_batch on the same pool bytes -----------

def test_int8_kv_doubles_max_batch_within_pool_bytes():
    from paddle_tpu.observability import memory as obs_memory

    model = _tiny(5)
    base_kw = dict(max_batch=2, max_blocks=16, block_size=4,
                   prefill_chunk=4)
    base = ServingEngine(model, **base_kw)
    base_bytes = obs_memory.get_ledger().snapshot()["owners"]["kv_cache"]
    assert base_bytes > 0
    del base

    dbl_kw = dict(base_kw, max_batch=base_kw["max_batch"] * 2,
                  max_blocks=base_kw["max_blocks"] * 2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dbl = ServingEngine(model, kv_dtype="int8", **dbl_kw)
    dbl.start()
    dbl_bytes = obs_memory.get_ledger().snapshot()["owners"]["kv_cache"]
    # 2x the batch and 2x the blocks, yet inside the old pool budget
    assert dbl_bytes <= base_bytes, (dbl_bytes, base_bytes)
    # and it actually serves that doubled batch
    rng = np.random.RandomState(2)
    hs = [dbl.submit(list(rng.randint(1, 128, 6)), max_new_tokens=3)
          for _ in range(dbl_kw["max_batch"])]
    dbl.drain(timeout=60)
    assert all(len(h.result(timeout=5)["token_ids"]) == 3 for h in hs)
    assert dbl.step_traces == 1
    dbl.shutdown()
