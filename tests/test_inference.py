"""paddle.inference Config/Predictor tests over jit.save artifacts."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.static import InputSpec


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(pt.nn.functional.relu(self.fc1(x)))


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    pt.seed(0)
    model = Net()
    model.eval()
    path = str(tmp_path_factory.mktemp("infer") / "net")
    pt.jit.save(model, path,
                input_spec=[InputSpec([2, 8], "float32", "x")])
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    return path, x, model(pt.to_tensor(x)).numpy()


def test_run_positional(exported):
    path, x, ref = exported
    pred = create_predictor(Config(path))
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_handle_api(exported):
    path, x, ref = exported
    pred = create_predictor(Config(path))
    names = pred.get_input_names()
    assert len(names) == 1
    pred.get_input_handle(names[0]).copy_from_cpu(x)
    pred.run()
    out_names = pred.get_output_names()
    out = pred.get_output_handle(out_names[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    assert pred.get_output_handle(out_names[0]).shape() == [2, 4]


def test_missing_model_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="jit.save"):
        create_predictor(Config(str(tmp_path / "nope")))


def test_pdmodel_suffix_accepted(exported):
    path, x, ref = exported
    pred = create_predictor(Config(path + ".pdmodel"))
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
