"""Tensor basics: creation, properties, conversion, indexing, inplace.

Oracle style follows the reference's OpTest (numpy expectations;
python/paddle/fluid/tests/unittests/op_test.py:326).
"""
import numpy as np
import pytest

import paddle_tpu as pt


def test_to_tensor_defaults():
    t = pt.to_tensor([1.0, 2.0, 3.0])
    assert t.shape == [3]
    assert t.dtype == pt.float32
    assert t.stop_gradient is True
    np.testing.assert_allclose(t.numpy(), [1, 2, 3])


def test_to_tensor_int_dtype():
    t = pt.to_tensor([1, 2, 3])
    assert t.dtype == pt.int64 or t.dtype == pt.int32
    t2 = pt.to_tensor(np.arange(4, dtype=np.int32))
    assert t2.dtype == pt.int32


def test_dtype_cast():
    t = pt.to_tensor([1.5, 2.5])
    i = t.astype("int32")
    assert i.dtype == pt.int32
    b = t.astype(pt.bfloat16)
    assert b.dtype == pt.bfloat16


def test_creation_ops():
    assert pt.zeros([2, 3]).shape == [2, 3]
    assert pt.ones([4]).numpy().sum() == 4
    f = pt.full([2, 2], 7.0)
    np.testing.assert_allclose(f.numpy(), np.full((2, 2), 7.0))
    a = pt.arange(10)
    np.testing.assert_array_equal(a.numpy(), np.arange(10))
    e = pt.eye(3)
    np.testing.assert_allclose(e.numpy(), np.eye(3))
    ln = pt.linspace(0, 1, 5)
    np.testing.assert_allclose(ln.numpy(), np.linspace(0, 1, 5), rtol=1e-6)


def test_random_reproducible():
    pt.seed(7)
    a = pt.randn([4, 4]).numpy()
    pt.seed(7)
    b = pt.randn([4, 4]).numpy()
    np.testing.assert_array_equal(a, b)
    c = pt.randn([4, 4]).numpy()
    assert not np.array_equal(b, c)


def test_arithmetic_dunders():
    x = pt.to_tensor([1.0, 2.0])
    y = pt.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((x + y).numpy(), [4, 6])
    np.testing.assert_allclose((x - y).numpy(), [-2, -2])
    np.testing.assert_allclose((x * y).numpy(), [3, 8])
    np.testing.assert_allclose((y / x).numpy(), [3, 2])
    np.testing.assert_allclose((x ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((2.0 + x).numpy(), [3, 4])
    np.testing.assert_allclose((-x).numpy(), [-1, -2])
    np.testing.assert_allclose(abs(pt.to_tensor([-1.0, 2.0])).numpy(), [1, 2])


def test_comparisons():
    x = pt.to_tensor([1.0, 2.0, 3.0])
    y = pt.to_tensor([2.0, 2.0, 2.0])
    np.testing.assert_array_equal((x < y).numpy(), [True, False, False])
    np.testing.assert_array_equal((x == y).numpy(), [False, True, False])
    np.testing.assert_array_equal((x >= y).numpy(), [False, True, True])


def test_indexing():
    x = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(x[0].numpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(x[1:, 2:].numpy(), [[6, 7], [10, 11]])
    idx = pt.to_tensor([0, 2])
    np.testing.assert_allclose(x[idx].numpy(), [[0, 1, 2, 3], [8, 9, 10, 11]])


def test_setitem():
    x = pt.zeros([3, 3])
    x[1, 1] = 5.0
    assert x.numpy()[1, 1] == 5.0
    x[0] = pt.ones([3])
    np.testing.assert_allclose(x.numpy()[0], [1, 1, 1])


def test_inplace_mutation():
    x = pt.ones([2, 2])
    v0 = x.inplace_version
    x.zero_()
    assert x.numpy().sum() == 0
    assert x.inplace_version == v0 + 1
    x.fill_(3.0)
    np.testing.assert_allclose(x.numpy(), np.full((2, 2), 3.0))
    x.set_value(np.eye(2))
    np.testing.assert_allclose(x.numpy(), np.eye(2))


def test_item_and_scalars():
    s = pt.to_tensor(3.5)
    assert s.item() == 3.5
    assert float(s) == 3.5
    assert int(pt.to_tensor(7)) == 7
    assert s.size == 1
    assert s.ndim == 0


def test_detach_clone():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    c = x.clone()
    assert not np.shares_memory(c.numpy(), x.numpy())


def test_repr_smoke():
    r = repr(pt.to_tensor([1.0, 2.0], stop_gradient=False))
    assert "Tensor" in r and "stop_gradient=False" in r


def test_numpy_interop():
    x = pt.to_tensor([[1.0, 2.0]])
    assert np.asarray(x).shape == (1, 2)
    assert len(x) == 1


def test_parameter():
    p = pt.Parameter(np.zeros((2, 2), np.float32))
    assert not p.stop_gradient
    assert p.persistable
    assert p.trainable


def test_tensor_inplace_and_convenience_methods():
    import numpy as np
    import paddle_tpu as pt
    x = pt.to_tensor(np.ones((2, 3), np.float32))
    x.add_(1.0).multiply_(2.0).subtract_(1.0)
    np.testing.assert_allclose(np.asarray(x.data), 3 * np.ones((2, 3)))
    x.clip_(max=2.5)
    assert float(np.asarray(x.data).max()) == 2.5
    assert x.element_size() == 4 and x.nelement() == 6
    assert x.is_contiguous() and x.contiguous() is x
    assert x.cuda() is x  # no CUDA: placement no-ops
    assert x.bfloat16().dtype.name == "bfloat16"
    assert x.half().dtype.name == "float16"
    assert x.float().dtype.name == "float32"
    y = x.sub(pt.to_tensor(np.ones((2, 3), np.float32)))
    np.testing.assert_allclose(np.asarray(y.data),
                               np.asarray(x.data) - 1)

    pt.seed(0)
    u = pt.to_tensor(np.zeros((100,), np.float32))
    u.uniform_(0.0, 1.0)
    arr = np.asarray(u.data)
    assert 0 <= arr.min() and arr.max() <= 1 and arr.std() > 0.1
    n = pt.to_tensor(np.zeros((500,), np.float32))
    n.normal_(mean=2.0, std=0.1)
    assert abs(float(np.asarray(n.data).mean()) - 2.0) < 0.05
    e = pt.to_tensor(np.zeros((500,), np.float32))
    e.exponential_(lam=2.0)
    assert abs(float(np.asarray(e.data).mean()) - 0.5) < 0.1


def test_inplace_preserves_dtype_and_seeded_uniform():
    import numpy as np
    import paddle_tpu as pt
    t = pt.to_tensor(np.array([1, 2], np.int32))
    t.add_(0.9)  # must not promote to float
    assert t.dtype.name == "int32"
    np.testing.assert_array_equal(np.asarray(t.data), [1, 2])

    a = pt.to_tensor(np.zeros(16, np.float32)).uniform_(0, 1, seed=42)
    b = pt.to_tensor(np.zeros(16, np.float32)).uniform_(0, 1, seed=42)
    np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))


def test_inplace_shape_guard_and_clip_dtype():
    import numpy as np
    import paddle_tpu as pt
    t = pt.to_tensor(np.array([1.0], np.float32))
    import pytest
    with pytest.raises(ValueError, match="shape"):
        t.add_(pt.to_tensor(np.ones((2, 3), np.float32)))
    ti = pt.to_tensor(np.array([1, 2, 3], np.int32))
    ti.clip_(min=0.5, max=2.5)
    assert ti.dtype.name == "int32"
    # seed parity with ops.uniform
    a = pt.to_tensor(np.zeros(4, np.float32)).uniform_(0, 1, seed=7)
    b = pt.uniform([4], min=0.0, max=1.0, seed=7)
    np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
