"""Live elastic resharding (resilience.elastic).

Covers: the cross-mesh reshard property sweep (8→6→4→6, non-power-of-two
membership, uneven largest-dim splits, bf16 + fused flats) asserting the
in-memory exchange is bit-identical to the source state AND to the
checkpoint-file reshard path; the no-filesystem guarantee (write spy);
the consensus resize listener (every rank stops at the same boundary,
env/file/store notice channels, generation isolation); the data-order
remap (exactly-once under membership change, packer carry preserved,
refusals); ``perform_resize`` end to end; the fleet ``departed`` lane
status; the goodput ``reshard`` bin; and the offline trace rollup's
resize classification.
"""
import builtins
import json
import threading
import time
from collections import Counter

import numpy as np
import pytest

from paddle_tpu.checkpoint.layout import flatten_state
from paddle_tpu.data.pipeline import DataPipeline
from paddle_tpu.data.stream import ShardedStream
from paddle_tpu.observability import fleet, goodput
from paddle_tpu.observability.fleet import (FleetAggregator,
                                            HeartbeatPublisher)
from paddle_tpu.observability.goodput import BINS, GoodputLedger
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.resilience import elastic
from paddle_tpu.resilience.elastic import (RESIZE_EXIT_CODE,
                                           ElasticResizeListener)


class MemStore:
    """Dict-backed TCPStore stand-in (set/get/add/wait) for tests that
    never need cross-thread blocking."""

    def __init__(self):
        self.d = {}

    def set(self, key, value):
        self.d[key] = value if isinstance(value, bytes) \
            else str(value).encode()

    def get(self, key):
        return self.d.get(key)

    def add(self, key, n):
        cur = int(self.d.get(key, b"0")) + int(n)
        self.d[key] = str(cur).encode()
        return cur

    def wait(self, key, timeout=None):
        v = self.d.get(key)
        if v is None:
            raise KeyError(key)
        return v


@pytest.fixture(autouse=True)
def _clean_ledger():
    goodput.reset_ledger()
    yield
    fleet.disable()
    goodput.reset_ledger()


class _Spy:
    """Write-mode open() spy: the resize path must never touch files."""

    def __enter__(self):
        self.writes = []
        self._orig = builtins.open

        def spy(f, mode="r", *a, **k):
            if any(c in str(mode) for c in "wxa+"):
                self.writes.append(str(f))
            return self._orig(f, mode, *a, **k)

        builtins.open = spy
        return self

    def __exit__(self, *exc):
        builtins.open = self._orig
        return False


# ---------------- data-order remap (ShardedStream) ---------------------------
class _Ints:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return i


def _streams(n, world, drop, shuffle=True):
    return [ShardedStream(_Ints(n), base_seed=7, shuffle=shuffle,
                          shard_index=k, num_shards=world,
                          drop_remainder=drop) for k in range(world)]


def _epoch_cover(n, world, drop, shuffle=True):
    """The sample multiset one epoch covers at this world size."""
    from paddle_tpu.io.sampler import epoch_seed
    order = (np.random.RandomState(epoch_seed(7, 0)).permutation(n)
             if shuffle else np.arange(n))
    rem = n % world
    if rem == 0:
        full = order
    elif drop:
        full = order[:n - rem]
    else:
        full = np.concatenate([order, order[:world - rem]])
    return Counter(int(x) for x in full)


class TestStreamReshard:
    @pytest.mark.parametrize("n,N,M,drop,shuffle", [
        (64, 8, 6, True, True),
        (61, 8, 6, True, True),     # uneven: 61 % 8, 61 % 6 both != 0
        (61, 8, 6, False, True),    # wrap tail remaps too
        (61, 6, 4, True, True),
        (61, 4, 6, False, True),    # scale UP mid-epoch
        (17, 3, 5, True, True),     # non-power-of-two both sides
        (17, 5, 1, True, True),     # collapse to one shard
        (31, 8, 6, True, False),    # unshuffled arange order
    ])
    def test_exactly_once_under_membership_change(self, n, N, M, drop,
                                                  shuffle):
        streams = _streams(n, N, drop, shuffle)
        rng = np.random.RandomState(N * M)
        rem = n % N
        per_old = (n - rem if drop and rem else
                   n + (N - rem) % N if not drop else n) // N
        pre = []
        for k, st in enumerate(streams):
            it = iter(st)
            # stay strictly mid-epoch: a fully-consumed shard has rolled
            # into the next epoch and reshard rightly refuses mixed epochs
            for _ in range(int(rng.randint(0, min(4, per_old)))):
                pre.append(next(it))
        new_states = ShardedStream.reshard_state(
            [st.state_dict() for st in streams], M)
        post = []
        for j in range(M):
            s = ShardedStream(_Ints(n), base_seed=7, shuffle=shuffle,
                              shard_index=j, num_shards=M,
                              drop_remainder=drop)
            s.load_state_dict(new_states[j])
            post.extend(iter(s))
        want = _epoch_cover(n, M, drop, shuffle)
        have = Counter(pre) + Counter(post)
        # every sample of the new world's epoch seen at least its
        # multiplicity; any extras must come from pre-boundary
        # consumption under the OLD world (coverage difference)
        for s_, cnt in want.items():
            assert have[s_] >= cnt, f"sample {s_} lost in reshard"
        extras = have - want
        assert sum(extras.values()) <= len(pre), "duplicates after remap"

    def test_chain_8_6_4_6(self):
        """Two consecutive mid-epoch reshards then a scale-up — the
        consumed_ahead bookkeeping survives chaining."""
        n = 48  # divisible by 8, 6, 4 → identical coverage at all sizes
        streams = _streams(n, 8, True)
        seen = []
        for world_next, consume in ((6, 2), (4, 1), (6, 1)):
            for st in streams:
                it = iter(st)
                for _ in range(consume):
                    seen.append(next(it))
            new_states = ShardedStream.reshard_state(
                [st.state_dict() for st in streams], world_next)
            streams = []
            for j, state in enumerate(new_states):
                s = ShardedStream(_Ints(n), base_seed=7, shuffle=True,
                                  shard_index=j, num_shards=world_next,
                                  drop_remainder=True)
                s.load_state_dict(state)
                streams.append(s)
        for st in streams:
            seen.extend(iter(st))
        assert Counter(seen) == _epoch_cover(n, 6, True)

    def test_refuses_mixed_epochs(self):
        streams = _streams(16, 4, True)
        it = iter(streams[0])
        for _ in range(4):  # shard 0 rolls into the next epoch
            next(it)
        with pytest.raises(ValueError, match="different epochs"):
            ShardedStream.reshard_state(
                [st.state_dict() for st in streams], 2)

    def test_refuses_consumed_beyond_new_coverage(self):
        # drop_remainder coverage shrinks 17→15 going 1→3 shards: a
        # position consumed under world 1 can sit past world 3's epoch
        streams = _streams(17, 1, True)
        it = iter(streams[0])
        for _ in range(17):
            pass
        for _ in range(16):
            next(it)
        with pytest.raises(ValueError, match="only covers"):
            ShardedStream.reshard_state([streams[0].state_dict()], 3)

    def test_mismatch_refusal_points_at_reshard_state(self):
        st = _streams(16, 4, True)[0]
        state = st.state_dict()
        other = ShardedStream(_Ints(16), base_seed=7, shard_index=0,
                              num_shards=2)
        with pytest.raises(ValueError, match="reshard_state"):
            other.load_state_dict(state)

    def test_consumed_ahead_roundtrip(self):
        st = _streams(24, 4, True)[0]
        st.consumed_ahead = {3, 5}
        st.cursor = 1
        state = st.state_dict()
        assert state["consumed_ahead"] == [3, 5]
        st2 = _streams(24, 4, True)[0]
        st2.load_state_dict(state)
        assert st2.consumed_ahead == {3, 5}
        # iteration skips the ahead positions without yielding them
        got = list(iter(st2))
        assert len(got) == 6 - 1 - 2  # per-shard epoch len - cursor - ahead


# ---------------- data-order remap (DataPipeline, packed) --------------------
class _Docs:
    def __init__(self, docs):
        self.docs = docs

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i]


def _doc_pipes(docs, world):
    return [DataPipeline(_Docs(docs), batch_size=2, seq_len=16, pack=True,
                         base_seed=5, shuffle=True, shard_index=k,
                         num_shards=world, drop_last=False)
            for k in range(world)]


def _tokens(batches):
    c = Counter()
    for b in batches:
        ids, m = b["input_ids"], b["attention_mask"]
        c.update(ids[m > 0].tolist())
    return c


class TestPipelineReshard:
    def test_packed_exactly_once_8_to_6(self):
        rng = np.random.RandomState(0)
        docs = [rng.randint(1, 100, size=rng.randint(3, 40))
                .astype(np.int32) for _ in range(96)]  # 96 % 8 == 96 % 6 == 0
        pipes = _doc_pipes(docs, 8)
        pre = []
        iters = [iter(p) for p in pipes]
        for k, it in enumerate(iters):
            for _ in range(2 + (k % 2)):
                pre.append(next(it))
        new_states = DataPipeline.reshard_state(
            [p.state_dict() for p in pipes], 6)
        assert len(new_states) == 6
        newp = _doc_pipes(docs, 6)
        post = []
        for j, p in enumerate(newp):
            p.load_state_dict(new_states[j])
        # the mid-epoch flag keeps every new shard in the SAME epoch
        assert len({p.epoch for p in newp}) == 1
        for p in newp:
            e0 = p.epoch
            while p.epoch == e0:
                post.extend(iter(p))
                break
        want = Counter()
        for d in docs:
            want.update(d.tolist())
        assert _tokens(pre) + _tokens(post) == want

    def test_pendings_and_carry_redistributed(self):
        rng = np.random.RandomState(3)
        docs = [rng.randint(1, 100, size=rng.randint(3, 30))
                .astype(np.int32) for _ in range(48)]
        pipes = _doc_pipes(docs, 4)
        for p in pipes:
            next(iter(p))
        states = [p.state_dict() for p in pipes]
        new_states = DataPipeline.reshard_state(states, 3)
        # no token lost: open bins + pendings all land on SOME new shard
        def open_tok(ss):
            c = Counter()
            for s in ss:
                for b in s.get("packer", {}).get("bins", []):
                    for doc in b:
                        c.update(np.asarray(doc).tolist())
                for pend in s.get("pending", []):
                    ids = np.asarray(pend["input_ids"])
                    m = np.asarray(pend["attention_mask"])
                    c.update(ids[m > 0].tolist())
            return c
        assert open_tok(new_states) == open_tok(states)


# ---------------- in-memory exchange: bit-identity ---------------------------
def _mixed_state(rng):
    """Uneven largest-dim splits, a scalar, a fused 1-D flat, a reduced-
    precision master — the shapes plan_grid struggles hardest with."""
    import jax.numpy as jnp
    return {
        "model": {"w1": rng.randn(13, 7).astype(np.float32),
                  "emb": rng.randn(31, 5).astype(np.float32),
                  "scalar": np.float32(rng.randn())},
        "opt": {"m": rng.randn(13, 7).astype(np.float32),
                "fused_flat": rng.randn(769).astype(np.float32),
                "step": np.int64(42)},
        "master_bf16": jnp.asarray(rng.randn(9, 6), dtype=jnp.bfloat16),
    }


def _flat_bytes(state):
    _, flat = flatten_state(state)
    return {k: (str(v[0].dtype), v[0].shape, v[0].tobytes())
            for k, v in flat.items()}


class TestExchangeBitIdentity:
    def test_membership_sweep_matches_source(self):
        """8→6→4→6: at every world size the store round trip reassembles
        the exact bytes — and never opens a file."""
        from paddle_tpu.distributed.tcp_store import TCPStore
        rng = np.random.RandomState(1)
        state = _mixed_state(rng)
        src = _flat_bytes(state)
        store = TCPStore(is_master=True, world_size=1)
        with _Spy() as spy:
            for g, world in enumerate((8, 6, 4, 6)):
                prefix = f"__elastic/t/g{g}"
                for r in range(world):
                    elastic.publish_state(store, prefix, state, world, r)
                out = elastic.collect_state(store, prefix)
                assert _flat_bytes(out) == src, f"world {world}"
                state = out  # chain: reshard the resharded state
        assert spy.writes == []

    @pytest.mark.slow  # multi-rank checkpoint write via thread barrier
    def test_matches_checkpoint_file_reshard_path(self, tmp_path):
        from paddle_tpu.checkpoint.reshard import read_state
        from paddle_tpu.checkpoint.writer import snapshot, write_step
        from paddle_tpu.distributed.tcp_store import TCPStore
        rng = np.random.RandomState(2)
        state = _mixed_state(rng)
        world = 4
        ths = [threading.Thread(
            target=write_step, args=(str(tmp_path), 1, snapshot(state)),
            kwargs=dict(process_index=r, process_count=world))
            for r in range(1, world)]
        for t in ths:
            t.start()
        time.sleep(0.2)
        step_dir = write_step(str(tmp_path), 1, snapshot(state),
                              process_index=0, process_count=world)
        for t in ths:
            t.join(timeout=120)
        file_state = read_state(step_dir)

        store = TCPStore(is_master=True, world_size=1)
        for r in range(world):
            elastic.publish_state(store, "__elastic/t/gf", state, world, r)
        mem_state = elastic.collect_state(store, "__elastic/t/gf")
        assert _flat_bytes(mem_state) == _flat_bytes(file_state)

    def test_crc_verification_rejects_corruption(self):
        from paddle_tpu.checkpoint.layout import CheckpointIntegrityError
        rng = np.random.RandomState(3)
        state = {"w": rng.randn(8, 8).astype(np.float32)}
        store = MemStore()
        elastic.publish_state(store, "p", state, 1, 0)
        key = next(k for k in store.d if k.startswith("p/t/"))
        store.d[key] = store.d[key][:-4] + b"\x00\x00\x00\x01"
        with pytest.raises(CheckpointIntegrityError):
            elastic.collect_state(store, "p")


# ---------------- consensus listener -----------------------------------------
class TestConsensusListener:
    def test_all_ranks_stop_at_same_boundary(self):
        store = MemStore()
        lns = [ElasticResizeListener(store=store) for _ in range(4)]
        lns[2].request(3, "test")
        # at the notice step nobody stops (stop_at = step + 1) …
        assert not any(ln.should_resize(step=5) for ln in lns)
        # … at the next boundary EVERY rank stops, on the same verdict
        assert all(ln.should_resize(step=6) for ln in lns)
        assert {ln.target_world for ln in lns} == {3}
        assert {ln.boundary_step for ln in lns} == {6}

    def test_env_notice_channel(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_RESIZE", "6")
        ln = ElasticResizeListener(store=MemStore())
        ln.should_resize(step=1)
        assert ln.should_resize(step=2)
        assert ln.target_world == 6

    def test_file_notice_channel(self, tmp_path):
        notice = tmp_path / "resize"
        notice.write_text("2\n")
        ln = ElasticResizeListener(store=MemStore(),
                                   notice_file=str(notice))
        ln.should_resize(step=1)
        assert ln.should_resize(step=2)
        assert ln.target_world == 2

    def test_store_target_key_channel(self):
        store = MemStore()
        ln = ElasticResizeListener(store=store)
        store.set(f"{elastic.elastic_prefix(0)}/target", b"4:operator")
        ln.should_resize(step=1)
        assert ln.should_resize(step=2)
        assert ln.target_world == 4

    def test_no_store_decides_locally(self):
        ln = ElasticResizeListener(store=None)
        ln._store_failed = True
        ln.request(2)
        assert ln.should_resize(step=7)
        assert ln.target_world == 2

    def test_generation_isolates_completed_resizes(self):
        store = MemStore()
        lns = [ElasticResizeListener(store=store) for _ in range(2)]
        lns[0].request(1, "round1")
        lns[0].should_resize(step=1)
        assert all(ln.should_resize(step=2) for ln in lns)
        # survivors bump the generation after the resize completes …
        store.set("__elastic/0/gen", b"1")
        late = ElasticResizeListener(store=store)
        # … so a fresh listener can never replay the stale verdict
        assert not late.should_resize(step=9)


# ---------------- perform_resize end to end ----------------------------------
class TestPerformResize:
    def test_kill_2_of_8_continue_on_6(self):
        """The drill in miniature: every old rank runs its side
        concurrently; survivors assemble bit-identical state + remapped
        data shards, departing ranks get None — zero file writes."""
        from paddle_tpu.distributed.tcp_store import TCPStore
        OLD, NEW = 8, 6
        rng = np.random.RandomState(4)
        state = {"w": rng.randn(24, 5).astype(np.float32),
                 "m": rng.randn(24, 5).astype(np.float32)}
        src = _flat_bytes(state)
        docs = [rng.randint(1, 50, size=rng.randint(3, 20))
                .astype(np.int32) for _ in range(48)]
        pipes = _doc_pipes(docs, 8)
        for p in pipes:
            next(iter(p))
        server = TCPStore(is_master=True, world_size=1)
        clients = [TCPStore(host="127.0.0.1", port=server.port,
                            is_master=False, world_size=1)
                   for _ in range(OLD)]
        results = [None] * OLD

        def run(r):
            results[r] = elastic.perform_resize(
                clients[r], state=state,
                data_state=pipes[r].state_dict(), world=OLD, rank=r,
                new_world=NEW, generation=0, boundary_step=3, timeout=60)

        with _Spy() as spy:
            ths = [threading.Thread(target=run, args=(r,))
                   for r in range(OLD)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(timeout=120)
        assert spy.writes == [], "filesystem touched on the resize path"
        for s, d in results[NEW:]:
            assert s is None and d is None
        for j, (s, d) in enumerate(results[:NEW]):
            assert _flat_bytes(s) == src
            assert d["stream"]["num_shards"] == NEW
            assert d["stream"]["shard_index"] == j
        # the resize wall landed in the goodput `reshard` bin
        snap = goodput.get_ledger().snapshot()
        assert snap["bins"]["reshard"] > 0
        assert snap["bins"]["restart"] == 0
        # rank 0 opened the next generation for the store listeners
        assert server.get("__elastic/0/gen") == b"1"


# ---------------- fleet: departed, not missing -------------------------------
class FakeStore:
    def __init__(self):
        self.d = {}

    def set(self, key, value):
        self.d[key] = value

    def get(self, key):
        return self.d.get(key)


class TestFleetDeparted:
    def test_departed_rank_retires_cleanly(self):
        reg = MetricsRegistry()
        store = FakeStore()
        pubs = [HeartbeatPublisher(store=store, rank=r, registry=reg)
                for r in range(3)]
        agg = FleetAggregator(store=store, world=3, stale_s=15,
                              registry=reg)
        stats = {"step_time_s": 0.1, "data_time_s": 0.0,
                 "exposed_collective_time_s": 0.0}
        for step in (1, 2):
            for p in pubs:
                p.publish(step, stats)
            agg.poll_once()
        # rank 2 leaves at the consensus resize boundary
        pubs[2].depart(2, reason="resize")
        roll = agg.poll_once(now=time.time() + 100)  # way past stale_s
        assert roll["ranks"]["2"]["status"] == "departed"
        assert reg.get("fleet_ranks_departed").value() == 1
        # ranks 0/1 went silent for real and DO alarm; the planned exit
        # of rank 2 never joins them in the missing count
        assert roll["ranks"]["0"]["status"] == "missing"
        assert roll["ranks"]["1"]["status"] == "missing"
        assert reg.get("fleet_ranks_missing").value() == 2
        assert 2 not in agg.stragglers
        # departed is sticky across polls, not a one-shot
        roll = agg.poll_once(now=time.time() + 200)
        assert roll["ranks"]["2"]["status"] == "departed"
        assert reg.get("fleet_ranks_departed").value() == 1


# ---------------- goodput: the reshard bin -----------------------------------
class TestGoodputReshard:
    def test_reshard_in_bins(self):
        assert "reshard" in BINS

    def test_resize_gap_binned_reshard_not_restart(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_GOODPUT_RESIZE_AT",
                           repr(time.time() - 2.0))
        led = GoodputLedger(registry=MetricsRegistry())
        snap = led.snapshot()
        assert snap["bins"]["reshard"] == pytest.approx(2.0, abs=0.25)
        assert snap["bins"]["restart"] == 0.0
        # the pre-wall gap is inside the accounted span (sums hold)
        assert snap["wall_s"] >= snap["bins"]["reshard"]
        assert sum(snap["bins"].values()) == pytest.approx(
            snap["wall_s"], rel=1e-3)

    def test_in_process_resize_records_reshard(self):
        led = GoodputLedger(registry=MetricsRegistry())
        led.record("productive", 1.0)
        led.record("reshard", 0.25)
        snap = led.snapshot()
        assert snap["bins"]["reshard"] == pytest.approx(0.25)
        assert snap["bins"]["restart"] == 0.0


# ---------------- offline trace rollup ---------------------------------------
def _write_lane(path, pid, spans, marks=()):
    anchor = (time.perf_counter_ns(), time.time_ns())
    lines = [{"type": "header", "version": 1, "rank": 0, "pid": pid,
              "clock": {"perf_ns": anchor[0], "unix_ns": anchor[1]}}]
    for cat, name, t0, t1, args in spans:
        lines.append({"type": "span", "cat": cat, "name": name,
                      "ts": anchor[0] + t0, "dur": t1 - t0, "tid": 0,
                      "args": args})
    for cat, name, t0 in marks:
        lines.append({"type": "mark", "cat": cat, "name": name,
                      "ts": anchor[0] + t0, "tid": 0, "args": {}})
    with open(path, "w") as f:
        f.write("\n".join(json.dumps(ln) for ln in lines) + "\n")


class TestTraceReshardRollup:
    def test_elastic_span_bins_reshard(self, tmp_path):
        import paddle_tpu.observability.trace as tr
        s = int(1e9)
        _write_lane(tmp_path / "trace_rank0_a.jsonl", 1, [
            ("step", "train_step", 0, s,
             {"step": 1, "step_time_s": 1.0}),
            ("elastic", "elastic_resize_8to6", s, 2 * s,
             {"world": 8, "new_world": 6}),
            ("step", "train_step", 2 * s, 3 * s,
             {"step": 2, "step_time_s": 1.0}),
        ])
        off = tr.merge(str(tmp_path), goodput=True)["goodput"]
        assert off["bins"]["reshard"] == pytest.approx(1.0, rel=0.01)
        assert off["bins"]["restart"] == 0.0
        assert off["bins"]["productive"] == pytest.approx(2.0, rel=0.01)

    def test_resized_lane_succession_gap_is_reshard(self, tmp_path):
        """Same rank, two lanes (a resize-relaunch): the gap bins as
        reshard when the successor carries a resize event — the offline
        mirror of PADDLE_TPU_GOODPUT_RESIZE_AT — and restart otherwise."""
        import paddle_tpu.observability.trace as tr
        s = int(1e9)
        _write_lane(tmp_path / "trace_rank0_a.jsonl", 1, [
            ("step", "train_step", 0, s, {"step": 1, "step_time_s": 1.0}),
        ])
        _write_lane(tmp_path / "trace_rank0_b.jsonl", 2, [
            ("step", "train_step", 3 * s, 4 * s,
             {"step": 2, "step_time_s": 1.0}),
        ], marks=[("elastic", "resize_boundary", 3 * s)])
        off = tr.merge(str(tmp_path), goodput=True)["goodput"]
        assert off["bins"]["reshard"] == pytest.approx(2.0, rel=0.01)
        assert off["bins"]["restart"] == 0.0


# ---------------- launcher classification ------------------------------------
class TestLauncherResize:
    def test_exit_codes_distinct(self):
        from paddle_tpu.resilience.preemption import RESUMABLE_EXIT_CODE
        assert RESIZE_EXIT_CODE == 83
        assert RESIZE_EXIT_CODE != RESUMABLE_EXIT_CODE

    def test_resize_target_world_reads_verdict(self):
        from paddle_tpu.distributed.launch import _resize_target_world
        store = MemStore()
        assert _resize_target_world(store, 0) is None
        store.set(f"{elastic.elastic_prefix(0, '0')}/stop",
                  b"6:4:preempt")
        assert _resize_target_world(store, 0) == 4
        # after survivors bump the generation the verdict still resolves
        store.set("__elastic/0/gen", b"1")
        assert _resize_target_world(store, 0) == 4

    def test_fit_resilience_stops_at_boundary(self):
        """FitResilience + elastic listener: fit breaks at the agreed
        step with resize bookkeeping set and NO checkpoint written."""
        import paddle_tpu as pt
        import paddle_tpu.nn as nn
        from paddle_tpu.resilience import FitResilience
        store = MemStore()
        ln = ElasticResizeListener(store=store)
        model = pt.hapi.Model(nn.Linear(4, 2))
        model.prepare(pt.optimizer.SGD(learning_rate=0.01,
                                       parameters=model.parameters()),
                      nn.MSELoss())
        fr = FitResilience(preemption=False, elastic_listener=ln)
        rng = np.random.RandomState(0)
        data = [(rng.randn(2, 4).astype(np.float32),
                 rng.randn(2, 2).astype(np.float32)) for _ in range(8)]

        class Trigger(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                if fr.global_step == 2:
                    ln.request(1, "test")

        model.fit(data, epochs=4, verbose=0, callbacks=[Trigger(), fr])
        assert fr.resized
        assert fr.resize_target == 1
        assert fr.resize_boundary_step == 3  # the step AFTER the notice
        assert not fr.preempted and fr.exit_code == 0
