"""Compiled generation: the whole prefill+decode loop as ONE program over
static KV buffers (reference surface: the inference predictor,
fluid/inference/api/analysis_predictor.cc — this is its TPU answer)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def _tiny(seed=0):
    pt.seed(seed)
    return LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=True))


def test_compiled_equals_eager_greedy():
    """VERDICT r3 item 5 'done' bar: compiled generate == eager generate
    token-for-token (greedy)."""
    m = _tiny()
    m.eval()
    ids = pt.to_tensor(np.random.RandomState(0).randint(
        0, 128, (2, 12)).astype(np.int64))
    eager = m.generate(ids, max_new_tokens=16, temperature=0.0)
    comp = m.generate_compiled(ids, max_new_tokens=16, temperature=0.0)
    np.testing.assert_array_equal(comp.numpy(), eager.numpy())


def test_compiled_greedy_batch_sizes():
    m = _tiny(1)
    m.eval()
    for B in (1, 4):
        ids = pt.to_tensor(np.random.RandomState(B).randint(
            0, 128, (B, 8)).astype(np.int64))
        eager = m.generate(ids, max_new_tokens=8, temperature=0.0)
        comp = m.generate_compiled(ids, max_new_tokens=8, temperature=0.0)
        np.testing.assert_array_equal(comp.numpy(), eager.numpy())


def test_compiled_eos_padding():
    """Finished rows keep emitting eos; prompt is preserved; shapes are
    the full budget (no early exit inside a compiled loop)."""
    m = _tiny(2)
    m.eval()
    ids = pt.to_tensor(np.random.RandomState(3).randint(
        0, 128, (2, 6)).astype(np.int64))
    # force eos = the greedy first token of row 0 so it finishes at once
    first = int(m.generate(ids, max_new_tokens=1,
                           temperature=0.0).numpy()[0, -1])
    out = m.generate_compiled(ids, max_new_tokens=10, temperature=0.0,
                              eos_token_id=first).numpy()
    assert out.shape == (2, 16)
    np.testing.assert_array_equal(out[:, :6], ids.numpy())
    assert (out[0, 6:] == first).all()  # row 0: eos from step 0 onwards


def test_static_cache_matches_concat_cache():
    """The fixed-shape KV path must produce the same logits as the legacy
    growing-concat path (prefill + two decode steps)."""
    import jax.numpy as jnp
    m = _tiny(4)
    m.eval()
    rng = np.random.RandomState(5)
    ids = pt.to_tensor(rng.randint(0, 128, (2, 7)).astype(np.int64))

    # legacy concat path
    caches = [(None, None)] * m.cfg.num_hidden_layers
    h1, caches = m.model(ids, caches=caches)
    tok = pt.to_tensor(rng.randint(0, 128, (2, 1)).astype(np.int64))
    h2, caches = m.model(tok, caches=caches)

    # static path: preallocated buffers, traced position
    L = 12
    n_kv = m.cfg.num_key_value_heads
    hd = m.cfg.hidden_size // m.cfg.num_attention_heads
    st = [(pt.to_tensor(jnp.zeros((2, L, n_kv, hd), jnp.float32)),
           pt.to_tensor(jnp.zeros((2, L, n_kv, hd), jnp.float32)),
           pt.to_tensor(jnp.zeros((), jnp.int32)))
          for _ in range(m.cfg.num_hidden_layers)]
    g1, st = m.model(ids, caches=st)
    g2, st = m.model(tok, caches=st)
    np.testing.assert_allclose(g1.numpy(), h1.numpy(), atol=2e-5)
    np.testing.assert_allclose(g2.numpy(), h2.numpy(), atol=2e-5)
    assert int(st[0][2].numpy()) == 8  # position advanced 7 + 1


def test_compiled_cache_reused():
    m = _tiny(6)
    m.eval()
    ids = pt.to_tensor(np.random.RandomState(1).randint(
        0, 128, (1, 5)).astype(np.int64))
    m.generate_compiled(ids, max_new_tokens=4)
    assert len(m.__dict__["_compiled_generate"]) == 1
    m.generate_compiled(ids, max_new_tokens=4)
    assert len(m.__dict__["_compiled_generate"]) == 1  # same signature
    m.generate_compiled(ids, max_new_tokens=6)
    assert len(m.__dict__["_compiled_generate"]) == 2


def test_moe_compiled_equals_eager_greedy():
    """The MoE family rides the same compiled loop (its cached forward
    lives on the top Layer with an lm_head — the family seam)."""
    from paddle_tpu.models.moe import MoeConfig, MoeForCausalLM

    pt.seed(3)
    cfg = MoeConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                    moe_intermediate_size=32, num_hidden_layers=2,
                    num_attention_heads=4, num_key_value_heads=2,
                    num_experts=4, num_experts_per_tok=2,
                    num_shared_experts=1, first_k_dense_replace=1)
    m = MoeForCausalLM(cfg)
    m.eval()
    ids = pt.to_tensor(np.random.RandomState(0).randint(
        0, 128, (2, 8)).astype(np.int64))
    eager = m.generate(ids, max_new_tokens=8, temperature=0.0)
    comp = m.generate_compiled(ids, max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(comp.numpy(), eager.numpy())


def test_moe_aux_loss_usable_after_compiled_generate():
    """Tracing the compiled loop must not leave escaped tracers in
    layer.mlp.l_aux (review regression: aux_loss() after generation)."""
    from paddle_tpu.models.moe import MoeConfig, MoeForCausalLM

    pt.seed(4)
    cfg = MoeConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                    moe_intermediate_size=32, num_hidden_layers=2,
                    num_attention_heads=4, num_key_value_heads=2,
                    num_experts=4, num_experts_per_tok=2,
                    num_shared_experts=0, first_k_dense_replace=0)
    m = MoeForCausalLM(cfg)
    m.eval()
    ids = pt.to_tensor(np.random.RandomState(0).randint(
        0, 64, (1, 6)).astype(np.int64))
    m.generate_compiled(ids, max_new_tokens=4)
    assert m.aux_loss() is None  # cleared, not an escaped tracer
    # a fresh eager forward restores a REAL aux loss
    m(ids, labels=ids)
    aux = m.aux_loss()
    assert aux is not None and np.isfinite(float(aux.numpy()))


def test_chunked_prefill_matches_one_shot():
    """prefill_chunk processes the prompt through the same static cache
    in offset-causal chunks — identical tokens, O(chunk) prefill scores
    (the long-prompt serving shape)."""
    m = _tiny(12)
    m.eval()
    ids = pt.to_tensor(np.random.RandomState(7).randint(
        0, 128, (2, 12)).astype(np.int64))
    one = m.generate_compiled(ids, max_new_tokens=8, temperature=0.0)
    chunked = m.generate_compiled(ids, max_new_tokens=8, temperature=0.0,
                                  prefill_chunk=4)
    np.testing.assert_array_equal(chunked.numpy(), one.numpy())
    with pytest.raises(ValueError, match="divide"):
        m.generate_compiled(ids, max_new_tokens=4, prefill_chunk=5)


# ---------------- ragged (unequal-prompt) batches -----------------------------
def _pad_left(prompts, pad_id=0):
    """Right-align a list of 1-D token arrays; returns (ids, mask)."""
    S = max(len(p) for p in prompts)
    B = len(prompts)
    ids = np.full((B, S), pad_id, np.int64)
    mask = np.zeros((B, S), np.int64)
    for b, p in enumerate(prompts):
        ids[b, S - len(p):] = p
        mask[b, S - len(p):] = 1
    return ids, mask


def test_ragged_batch_matches_solo_runs():
    """VERDICT r4 item 2 'done' bar: a ragged batch generates each row
    token-for-token equal to running that prompt alone."""
    m = _tiny(11)
    m.eval()
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 128, n).astype(np.int64)
               for n in (5, 9, 12)]
    ids, mask = _pad_left(prompts)
    out = m.generate_compiled(pt.to_tensor(ids), max_new_tokens=8,
                              temperature=0.0,
                              attention_mask=pt.to_tensor(mask)).numpy()
    S = ids.shape[1]
    for b, p in enumerate(prompts):
        solo = m.generate_compiled(pt.to_tensor(p[None, :]),
                                   max_new_tokens=8,
                                   temperature=0.0).numpy()[0]
        np.testing.assert_array_equal(
            out[b, S:], solo[len(p):],
            err_msg=f"row {b} (prompt len {len(p)}) diverges from solo")


def test_ragged_equal_lengths_match_unmasked():
    """A full mask (no pads) must reproduce the maskless path exactly."""
    m = _tiny(12)
    m.eval()
    ids = np.random.RandomState(12).randint(0, 128, (3, 7)).astype(np.int64)
    mask = np.ones_like(ids)
    got = m.generate_compiled(pt.to_tensor(ids), max_new_tokens=6,
                              temperature=0.0,
                              attention_mask=pt.to_tensor(mask)).numpy()
    want = m.generate_compiled(pt.to_tensor(ids), max_new_tokens=6,
                               temperature=0.0).numpy()
    np.testing.assert_array_equal(got, want)


def test_ragged_executable_reused_across_pad_patterns():
    """The mask is a traced input: two batches with different pad
    patterns share one compiled executable."""
    m = _tiny(13)
    m.eval()
    rng = np.random.RandomState(13)
    for lens in [(3, 6), (6, 4)]:
        prompts = [rng.randint(1, 128, n).astype(np.int64) for n in lens]
        ids, mask = _pad_left(prompts)
        m.generate_compiled(pt.to_tensor(ids), max_new_tokens=3,
                            temperature=0.0,
                            attention_mask=pt.to_tensor(mask))
    assert len(m.__dict__["_compiled_generate"]) == 1


def test_ragged_rejects_right_padding():
    m = _tiny(14)
    m.eval()
    ids = np.random.RandomState(14).randint(1, 128, (2, 6)).astype(np.int64)
    mask = np.ones((2, 6), np.int64)
    mask[0, 4:] = 0  # right padding
    with pytest.raises(ValueError, match="LEFT-padded"):
        m.generate_compiled(pt.to_tensor(ids), max_new_tokens=2,
                            temperature=0.0,
                            attention_mask=pt.to_tensor(mask))


def test_ragged_with_chunked_prefill():
    """Ragged + chunked prefill compose (both ride the same static
    cache/key-mask machinery)."""
    m = _tiny(15)
    m.eval()
    rng = np.random.RandomState(15)
    prompts = [rng.randint(1, 128, n).astype(np.int64) for n in (4, 8)]
    ids, mask = _pad_left(prompts)  # S = 8, chunk 4 divides
    want = m.generate_compiled(pt.to_tensor(ids), max_new_tokens=5,
                               temperature=0.0,
                               attention_mask=pt.to_tensor(mask)).numpy()
    got = m.generate_compiled(pt.to_tensor(ids), max_new_tokens=5,
                              temperature=0.0, prefill_chunk=4,
                              attention_mask=pt.to_tensor(mask)).numpy()
    np.testing.assert_array_equal(got, want)


def test_padded_training_forward_matches_solo():
    """Cacheless path: attention_mask -> flash segment ids. A padded row's
    REAL positions produce the same hidden states as the solo run
    (right-padding, the training shape)."""
    m = _tiny(16)
    m.eval()
    rng = np.random.RandomState(16)
    solo = rng.randint(1, 128, (1, 5)).astype(np.int64)
    ids = np.concatenate([solo, np.zeros((1, 3), np.int64)], 1)
    mask = np.concatenate([np.ones((1, 5), np.int64),
                           np.zeros((1, 3), np.int64)], 1)
    logits_pad = m(pt.to_tensor(ids),
                   attention_mask=pt.to_tensor(mask)).numpy()
    logits_solo = m(pt.to_tensor(solo)).numpy()
    np.testing.assert_allclose(logits_pad[:, :5], logits_solo,
                               rtol=2e-4, atol=2e-5)
