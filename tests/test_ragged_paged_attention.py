"""Ragged Paged Attention kernel (ISSUE 8 tentpole).

Parity contract, all tier-1 cheap (interpret mode on the CPU mesh, tiny
shapes — the 870s tier-1 cutoff counts dots):

* kernel vs gather fallback vs an eager per-sequence oracle on random
  ragged mixes of prefill chunks and decode rows, across block sizes
  {8, 16}, GQA ratios {1, 4}, and metadata rows with ``new_len == 0``
  (padding slots contribute no tokens and no kernel work);
* token-level equality through ``ServingEngine`` greedy decode under
  BOTH settings of the impl knob — the engine-level acceptance check
  (the preemption/resume variant rides the slow lane);
* the host-side work-list builder's invariants (every (sequence, page)
  pair exactly once per overlapping tile, only real pages, static
  bound honored).
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.ops.paged_attention import (
    impl_override, paged_attention_impl, ragged_gather_attention,
    write_tokens_to_pool)
from paddle_tpu.ops.pallas.ragged_paged_attention import (
    build_step_maps, ragged_paged_attention, rpa_max_steps)
from paddle_tpu.serving import ServingEngine


# ---------------- raw kernel parity ------------------------------------------
def _ragged_case(rng, seqs, block_size, n_kv, grp, hd=16, tile_q=8,
                 mbps=6, pool_blocks=24):
    """Build one token-packed ragged scenario: ``seqs`` is a list of
    ``(new_len, context_len)`` — new_len 0 models a padding slot whose
    metadata row exists but owns no tokens. Returns everything the two
    impls and the eager oracle need."""
    n_heads = n_kv * grp
    max_seqs = len(seqs) + 1          # one extra never-used row
    total_new = sum(n for n, _ in seqs)
    T = -(-max(total_new, 1) // tile_q) * tile_q
    max_steps = rpa_max_steps(tile_q, mbps, pool_blocks)

    bt = np.zeros((max_seqs + 1, mbps), np.int32)
    nxt = 1
    kv_lens = []
    for s, (n, c) in enumerate(seqs):
        kv = n + c
        kv_lens.append(kv)
        npg = -(-kv // block_size) if kv else 0
        bt[s, :npg] = np.arange(nxt, nxt + npg)
        nxt += npg
    assert nxt - 1 <= pool_blocks

    cu = np.zeros(max_seqs + 2, np.int32)
    cu[1:len(seqs) + 1] = np.cumsum([n for n, _ in seqs])
    cu[len(seqs) + 1:] = cu[len(seqs)]
    ctx = np.zeros(max_seqs + 1, np.int32)
    ctx[:len(seqs)] = [c for _, c in seqs]
    sid = np.full(T, max_seqs, np.int32)
    pos = np.zeros(T, np.int32)
    off = 0
    for s, (n, c) in enumerate(seqs):
        sid[off:off + n] = s
        pos[off:off + n] = c + np.arange(n)
        off += n

    kp = np.zeros((pool_blocks + 1, block_size, n_kv, hd), np.float32)
    vp = np.zeros_like(kp)
    full_k, full_v = [], []
    for s, (n, c) in enumerate(seqs):
        fk = rng.randn(n + c, n_kv, hd).astype(np.float32)
        fv = rng.randn(n + c, n_kv, hd).astype(np.float32)
        full_k.append(fk)
        full_v.append(fv)
        for t in range(c):            # prior context from earlier steps
            kp[bt[s, t // block_size], t % block_size] = fk[t]
            vp[bt[s, t // block_size], t % block_size] = fv[t]
    q = rng.randn(T, n_heads, hd).astype(np.float32)
    knew = np.zeros((T, n_kv, hd), np.float32)
    vnew = np.zeros((T, n_kv, hd), np.float32)
    off = 0
    for s, (n, c) in enumerate(seqs):
        knew[off:off + n] = full_k[s][c:]
        vnew[off:off + n] = full_v[s][c:]
        off += n

    kp2 = write_tokens_to_pool(jnp.asarray(kp), jnp.asarray(knew),
                               jnp.asarray(bt), jnp.asarray(sid),
                               jnp.asarray(pos))
    vp2 = write_tokens_to_pool(jnp.asarray(vp), jnp.asarray(vnew),
                               jnp.asarray(bt), jnp.asarray(sid),
                               jnp.asarray(pos))
    ssq, sbk = build_step_maps(cu[:len(seqs) + 1], kv_lens,
                               total_tokens=T, tile_q=tile_q,
                               block_size=block_size,
                               max_steps=max_steps, max_seqs=max_seqs)
    return dict(q=q, kp=kp2, vp=vp2, bt=bt, cu=cu, ctx=ctx, sid=sid,
                pos=pos, ssq=ssq, sbk=sbk, full_k=full_k, full_v=full_v,
                seqs=seqs, max_seqs=max_seqs, grp=grp, hd=hd)


def _eager_oracle(case):
    """Per-sequence dense softmax over the contiguous K/V — the ground
    truth both paged impls must match."""
    q, seqs = case["q"], case["seqs"]
    grp, hd = case["grp"], case["hd"]
    scale = 1.0 / np.sqrt(hd)
    ref = np.zeros((q.shape[0], q.shape[1], hd), np.float32)
    off = 0
    for s, (n, c) in enumerate(seqs):
        K, V = case["full_k"][s], case["full_v"][s]
        for i in range(n):
            t = off + i
            kvis, vvis = K[:c + i + 1], V[:c + i + 1]
            for h in range(q.shape[1]):
                kh = h // grp
                sc = (kvis[:, kh] @ q[t, h]) * scale
                w = np.exp(sc - sc.max())
                w /= w.sum()
                ref[t, h] = w @ vvis[:, kh]
        off += n
    return ref


@pytest.mark.parametrize("block_size,grp", [(8, 1), (8, 4), (16, 1),
                                            (16, 4)])
def test_kernel_matches_gather_and_eager(block_size, grp):
    """RPA (interpret) vs gather vs eager on a random ragged mix:
    prefill chunks crossing q-tiles and pages, decode rows at varied
    context depths, and a new_len == 0 padding slot in the middle."""
    rng = np.random.RandomState(block_size * 10 + grp)
    seqs = [(5, 0), (1, 2 * block_size + 3), (0, 0), (1, 3),
            (9, block_size)]
    c = _ragged_case(rng, seqs, block_size, n_kv=2, grp=grp)
    out_rpa = np.asarray(ragged_paged_attention(
        jnp.asarray(c["q"]), c["kp"], c["vp"], jnp.asarray(c["bt"]),
        jnp.asarray(c["cu"]), jnp.asarray(c["ctx"]), c["ssq"], c["sbk"]))
    out_g = np.asarray(ragged_gather_attention(
        jnp.asarray(c["q"]), c["kp"], c["vp"], jnp.asarray(c["bt"]),
        jnp.asarray(c["sid"]), jnp.asarray(c["pos"]),
        scale=1.0 / np.sqrt(c["hd"])))
    ref = _eager_oracle(c)
    valid = c["sid"] < c["max_seqs"]
    np.testing.assert_allclose(out_rpa[valid], ref[valid], atol=2e-5)
    np.testing.assert_allclose(out_g[valid], ref[valid], atol=2e-5)
    # padding tokens: the kernel produces exact zeros (l == 0 guard)
    assert np.all(out_rpa[~valid] == 0.0)


def test_step_maps_cover_each_page_exactly_once():
    """Work-list invariants: for every tile, each overlapping sequence
    contributes exactly ceil(kv_len / block_size) steps (its REAL pages,
    nothing more), empty sequences contribute none, and dead steps carry
    the sentinel."""
    cu = np.array([0, 5, 5, 6, 16])  # seq 1 is a new_len == 0 slot
    kv_lens = [5, 8, 9, 16]
    tile_q, bs, max_seqs = 8, 8, 6
    ssq, sbk = build_step_maps(cu, kv_lens, total_tokens=16,
                               tile_q=tile_q, block_size=bs,
                               max_steps=rpa_max_steps(tile_q, 4, 32),
                               max_seqs=max_seqs)
    for j in range(2):
        lo, hi = j * tile_q, (j + 1) * tile_q
        want = {}
        for s in range(4):
            if cu[s] < cu[s + 1] and cu[s + 1] > lo and cu[s] < hi:
                want[s] = -(-kv_lens[s] // bs)
        got = {}
        for s, b in zip(ssq[j], sbk[j]):
            if s == max_seqs:
                continue
            got.setdefault(int(s), []).append(int(b))
        assert {s: len(b) for s, b in got.items()} == want
        for s, blocks in got.items():
            assert blocks == list(range(want[s]))  # each page once, in order
    with pytest.raises(ValueError, match="max_steps"):
        build_step_maps(cu, kv_lens, total_tokens=16, tile_q=tile_q,
                        block_size=bs, max_steps=1, max_seqs=max_seqs)


def test_impl_knob_resolution(monkeypatch):
    """auto = gather off-TPU; env and override win in that order."""
    monkeypatch.delenv("PADDLE_TPU_PAGED_ATTN_IMPL", raising=False)
    assert paged_attention_impl() == "gather"  # CPU mesh
    monkeypatch.setenv("PADDLE_TPU_PAGED_ATTN_IMPL", "rpa")
    assert paged_attention_impl() == "rpa"
    with impl_override("gather"):
        assert paged_attention_impl() == "gather"
    assert paged_attention_impl() == "rpa"
    monkeypatch.setenv("PADDLE_TPU_PAGED_ATTN_IMPL", "bogus")
    with pytest.raises(ValueError, match="bogus"):
        paged_attention_impl()


# ---------------- engine-level acceptance ------------------------------------
def _tiny(seed=0):
    pt.seed(seed)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=True))
    m.eval()
    return m


def _eager_continuation(model, prompt, max_new_tokens):
    out = model.generate(pt.to_tensor(np.asarray(prompt)[None, :]),
                         max_new_tokens=max_new_tokens,
                         temperature=0.0).numpy()[0]
    return [int(t) for t in out[len(prompt):]]


def test_engine_token_streams_identical_across_impls():
    """ISSUE 8 acceptance: bit-level equal greedy token streams from
    ``ServingEngine`` under both impl knob settings, each also matching
    the eager oracle; exactly ONE unified executable per engine, and a
    chunked multi-chunk prefill (prompt >> prefill_chunk) triggers no
    second compile after warmup."""
    model = _tiny(11)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 128, 11), rng.randint(1, 128, 4)]
    streams = {}
    for impl in ("gather", "rpa"):
        eng = ServingEngine(model, max_batch=2, max_blocks=16,
                            block_size=4, prefill_chunk=4,
                            attn_impl=impl)
        handles = [eng.submit(p, max_new_tokens=5) for p in prompts]
        eng.run_until_idle()
        streams[impl] = [h.result(30)["token_ids"] for h in handles]
        # prompt 11 >> chunk 4: three chunks rode the SAME executable
        assert eng.step_traces == 1
        assert eng.stats()["attn_impl"] == impl
        eng.cache.allocator.assert_no_leaks()
    assert streams["rpa"] == streams["gather"]
    assert streams["rpa"] == [
        _eager_continuation(model, p, 5) for p in prompts]


@pytest.mark.slow
def test_engine_impl_parity_under_preemption():
    """Tight pool forces preemption-by-recompute mid-decode; the resumed
    token streams stay identical across impls and vs the solo oracle
    (the acceptance's preemption/resume-trace clause)."""
    streams = {}
    for impl in ("gather", "rpa"):
        model = _tiny(5)
        eng = ServingEngine(model, max_batch=3, max_blocks=8,
                            block_size=4, prefill_chunk=4,
                            attn_impl=impl)
        rng = np.random.RandomState(3)
        prompts = [rng.randint(1, 128, n) for n in (9, 12, 7)]
        handles = [eng.submit(p, max_new_tokens=8) for p in prompts]
        eng.run_until_idle()
        streams[impl] = [h.result(30)["token_ids"] for h in handles]
        assert eng.scheduler.num_preemptions >= 1
        assert streams[impl] == [
            _eager_continuation(model, p, 8) for p in prompts]
        eng.cache.allocator.assert_no_leaks()
    assert streams["rpa"] == streams["gather"]
