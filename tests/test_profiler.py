"""Profiler tests: RecordEvent spans, op auto-instrumentation, scheduler
state machine, chrome-trace export, summary aggregation."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.profiler as profiler


class TestRecordEvent:
    def test_noop_when_inactive(self):
        ev = profiler.RecordEvent("x")
        ev.begin()
        ev.end()  # must not raise nor record anywhere

    def test_spans_recorded(self):
        with profiler.Profiler() as prof:
            with profiler.RecordEvent("my_region"):
                pass
        names = [e.name for e in prof.events]
        assert "my_region" in names

    def test_ops_auto_instrumented(self):
        a = pt.to_tensor(np.ones((4, 4), np.float32))
        with profiler.Profiler() as prof:
            b = pt.matmul(a, a)
            c = pt.add(b, a)
        names = [e.name for e in prof.events]
        assert "matmul" in names and "add" in names

    def test_zero_overhead_off(self):
        # no profiler: apply_op's hook returns None (no events anywhere)
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        pt.matmul(a, a)
        prof = profiler.Profiler()
        assert prof.events == []


class TestRecordShapes:
    def test_shapes_attached(self):
        a = pt.to_tensor(np.ones((4, 8), np.float32))
        b = pt.to_tensor(np.ones((8, 2), np.float32))
        with profiler.Profiler(record_shapes=True) as prof:
            pt.matmul(a, b)
        evs = [e for e in prof.events if e.name == "matmul"]
        assert evs and evs[0].args["input_shapes"] == [[4, 8], [8, 2]]

    def test_shapes_off_by_default(self):
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        with profiler.Profiler() as prof:
            pt.matmul(a, a)
        evs = [e for e in prof.events if e.name == "matmul"]
        assert evs and evs[0].args is None


class TestTimerOnly:
    def test_no_events_but_step_info(self):
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        prof = profiler.Profiler(timer_only=True).start()
        pt.matmul(a, a)
        prof.step()
        pt.matmul(a, a)
        prof.step()
        prof.stop()
        assert prof.events == []  # no op capture at all
        info = prof.step_info()
        assert info["steps"] == 3  # start->step, step->step, step->stop
        assert info["avg_ms"] > 0

    def test_timer_only_does_not_claim_active(self):
        with profiler.Profiler(timer_only=True):
            assert profiler.record_op("x") is None


class TestScheduler:
    def test_state_machine(self):
        sched = profiler.make_scheduler(closed=1, ready=1, record=2,
                                        skip_first=1)
        states = [sched(i) for i in range(6)]
        assert states == ["closed", "closed", "ready", "record", "record",
                          "closed"]

    def test_skip_first_repeat_wraparound(self):
        # skip 2, then (closed 1, record 1) x 2 cycles, closed forever
        sched = profiler.make_scheduler(closed=1, ready=0, record=1,
                                        repeat=2, skip_first=2)
        states = [sched(i) for i in range(8)]
        assert states == ["closed", "closed",          # skip_first
                          "closed", "record",          # cycle 1
                          "closed", "record",          # cycle 2
                          "closed", "closed"]          # repeat exhausted

    def test_zero_closed_ready(self):
        sched = profiler.make_scheduler(closed=0, ready=0, record=2)
        assert [sched(i) for i in range(4)] == ["record"] * 4

    def test_invalid_periods_raise(self):
        with pytest.raises(ValueError):
            profiler.make_scheduler(record=0)
        with pytest.raises(ValueError):
            profiler.make_scheduler(closed=-1)

    def test_profiler_honors_scheduler(self):
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        sched = profiler.make_scheduler(closed=1, ready=0, record=1)
        prof = profiler.Profiler(scheduler=sched).start()
        pt.matmul(a, a)  # step 0: closed
        prof.step()
        pt.matmul(a, a)  # step 1: record
        prof.stop()
        assert len([e for e in prof.events if e.name == "matmul"]) == 1


class TestSinks:
    def test_chrome_trace_export(self, tmp_path):
        a = pt.to_tensor(np.ones((3, 3), np.float32))
        with profiler.Profiler() as prof:
            pt.matmul(a, a)
        path = prof.export_chrome_tracing(str(tmp_path))
        data = json.load(open(path))
        assert data["traceEvents"]
        ev = data["traceEvents"][0]
        assert set(ev) >= {"name", "ph", "ts", "dur"}

    def test_on_trace_ready_callback(self, tmp_path):
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        with profiler.Profiler(
                on_trace_ready=profiler.export_chrome_tracing(
                    str(tmp_path))):
            pt.add(a, a)
        assert any(f.endswith(".trace.json")
                   for f in os.listdir(str(tmp_path)))

    def test_summary(self, capsys):
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        with profiler.Profiler() as prof:
            for _ in range(3):
                pt.matmul(a, a)
        rows = prof.summary()
        agg = dict(rows)
        assert agg["matmul"][1] == 3  # 3 calls
        assert "matmul" in capsys.readouterr().out

    def test_chrome_roundtrip_spans_and_counters(self, tmp_path):
        """export -> load_profiler_result round-trip: op spans, tagged
        comm spans, and counter events all survive serialization."""
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        with profiler.Profiler() as prof:
            pt.matmul(a, a)
            # a comm span the way observability.comm emits one
            profiler._emit_event("comm::all_reduce", 100, 200, tid=1,
                                 args={"bytes": 64, "axes": "dp"},
                                 cat="comm")
        path = prof.export_chrome_tracing(str(tmp_path), "w0")
        data = profiler.load_profiler_result(path)
        evs = data["traceEvents"]
        ops = [e for e in evs if e.get("cat") == "op" and e["ph"] == "X"]
        comm = [e for e in evs if e.get("cat") == "comm" and e["ph"] == "X"]
        ctrs = [e for e in evs if e["ph"] == "C"]
        assert any(e["name"] == "matmul" for e in ops)
        assert comm[0]["args"] == {"bytes": 64, "axes": "dp"}
        assert ctrs and ctrs[0]["name"] == "comm_bytes"
        assert ctrs[0]["args"]["bytes"] == 64
        # loaded doc is exactly what was exported
        assert data == json.load(open(path))


class TestNativeRebuildLock:
    def test_stale_so_rebuilds_under_lock(self):
        """A stale .so triggers a locked recompile; the lock file exists
        and the fresh library still exposes both rings' symbols."""
        import shutil
        if shutil.which("g++") is None:
            pytest.skip("no toolchain")
        tracer = profiler._NativeTracer
        here = os.path.dirname(os.path.dirname(os.path.abspath(
            profiler.__file__)))
        src = os.path.join(os.path.dirname(here), "native",
                           "host_tracer.cpp")
        so = os.path.join(os.path.dirname(src), "build",
                          "libhost_tracer.so")
        os.utime(src)  # make the .so stale
        tracer._lib, tracer._failed = None, False
        lib = tracer.load()
        assert lib is not None
        assert os.path.getmtime(so) >= os.path.getmtime(src)
        assert os.path.exists(so + ".lock")
        assert hasattr(lib, "ht_start") and hasattr(lib, "fr_start")
