"""Profiler tests: RecordEvent spans, op auto-instrumentation, scheduler
state machine, chrome-trace export, summary aggregation."""
import json
import os

import numpy as np

import paddle_tpu as pt
import paddle_tpu.profiler as profiler


class TestRecordEvent:
    def test_noop_when_inactive(self):
        ev = profiler.RecordEvent("x")
        ev.begin()
        ev.end()  # must not raise nor record anywhere

    def test_spans_recorded(self):
        with profiler.Profiler() as prof:
            with profiler.RecordEvent("my_region"):
                pass
        names = [e.name for e in prof.events]
        assert "my_region" in names

    def test_ops_auto_instrumented(self):
        a = pt.to_tensor(np.ones((4, 4), np.float32))
        with profiler.Profiler() as prof:
            b = pt.matmul(a, a)
            c = pt.add(b, a)
        names = [e.name for e in prof.events]
        assert "matmul" in names and "add" in names

    def test_zero_overhead_off(self):
        # no profiler: apply_op's hook returns None (no events anywhere)
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        pt.matmul(a, a)
        prof = profiler.Profiler()
        assert prof.events == []


class TestScheduler:
    def test_state_machine(self):
        sched = profiler.make_scheduler(closed=1, ready=1, record=2,
                                        skip_first=1)
        states = [sched(i) for i in range(6)]
        assert states == ["closed", "closed", "ready", "record", "record",
                          "closed"]

    def test_profiler_honors_scheduler(self):
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        sched = profiler.make_scheduler(closed=1, ready=0, record=1)
        prof = profiler.Profiler(scheduler=sched).start()
        pt.matmul(a, a)  # step 0: closed
        prof.step()
        pt.matmul(a, a)  # step 1: record
        prof.stop()
        assert len([e for e in prof.events if e.name == "matmul"]) == 1


class TestSinks:
    def test_chrome_trace_export(self, tmp_path):
        a = pt.to_tensor(np.ones((3, 3), np.float32))
        with profiler.Profiler() as prof:
            pt.matmul(a, a)
        path = prof.export_chrome_tracing(str(tmp_path))
        data = json.load(open(path))
        assert data["traceEvents"]
        ev = data["traceEvents"][0]
        assert set(ev) >= {"name", "ph", "ts", "dur"}

    def test_on_trace_ready_callback(self, tmp_path):
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        with profiler.Profiler(
                on_trace_ready=profiler.export_chrome_tracing(
                    str(tmp_path))):
            pt.add(a, a)
        assert any(f.endswith(".trace.json")
                   for f in os.listdir(str(tmp_path)))

    def test_summary(self, capsys):
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        with profiler.Profiler() as prof:
            for _ in range(3):
                pt.matmul(a, a)
        rows = prof.summary()
        agg = dict(rows)
        assert agg["matmul"][1] == 3  # 3 calls
        assert "matmul" in capsys.readouterr().out
