"""Program auditor + trace-safety linter (ISSUE 9): compiled-HLO audit
passes (collective census vs the bucketed-dp contract, donation
coverage, f32 upcasts, giant intermediates, compile-key diff), the AST
lint rules reproducing three paid-for bug classes, the env-knob
registry drift gate, and the bench.py --audit report-gate headlines
(docs/ANALYSIS.md)."""
import importlib.util
import json
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.analysis import audit as A
from paddle_tpu.analysis import hlo as H
from paddle_tpu.analysis import knobs as K
from paddle_tpu.analysis.driver import (dp8_bucketed_step,
                                        tiny_llama_step,
                                        tiny_serving_engine)
from paddle_tpu.analysis.findings import Baseline, Finding, load_baseline
from paddle_tpu.analysis.lint import lint_file, lint_tree

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    spec = importlib.util.spec_from_file_location(
        "bench_for_tests", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mlp_step(donate=True, seed=3):
    pt.seed(seed)
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    o = pt.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    step = pt.jit.TrainStep(
        m, lambda mm, x, y: ((mm(x) - y) ** 2).mean(), o, donate=donate)
    rng = np.random.RandomState(0)
    X = rng.randn(8, 16).astype(np.float32)
    Y = X @ rng.randn(16, 4).astype(np.float32)
    return step, (pt.to_tensor(X), pt.to_tensor(Y))


# ---------------- HLO text passes (pure fragments) ---------------------------

HEADER = ("HloModule jit_f, is_scheduled=true, input_output_alias={ "
          "{0}: (0, {}, may-alias), {2}: (3, {}, must-alias) }, "
          "entry_computation_layout={(bf16[8,16]{1,0}, f32[]{:T(256)}, "
          "/*index=2*/s32[2,64]{1,0}, f32[128]{0})->(bf16[8,16]{1,0})}\n")

BODY = textwrap.dedent("""\
    %fused (p: bf16[8,16]) -> f32[] {
      %p = bf16[8,16]{1,0} parameter(0)
      %convert.3 = f32[8,16]{1,0} convert(bf16[8,16]{1,0} %p), metadata={op_name="jit(f)/mul" source_file="m.py" source_line=4}
      %big = f32[128,512]{1,0} broadcast(f32[] %c), dimensions={}
      ROOT %reduce.0 = f32[] reduce(f32[8,16]{1,0} %convert.3, f32[] %c)
    }
    ENTRY %main () -> f32[] {
      %ar0 = f32[100]{0} all-reduce(f32[100]{0} %x), to_apply=%add
      %ars = f32[50]{0} all-reduce-start(f32[50]{0} %y), to_apply=%add
      %ard = f32[50]{0} all-reduce-done(f32[50]{0} %ars)
      %ag = f32[64]{0} all-gather(f32[8]{0} %z), dimensions={0}
      %cp = f32[8]{0} collective-permute(f32[8]{0} %w)
    }
""")


class TestHloPasses:
    def test_shape_bytes(self):
        assert H.shape_bytes("f32", "128,512") == 128 * 512 * 4
        assert H.shape_bytes("bf16", "8,16") == 256
        assert H.shape_bytes("f32", "") == 4
        assert H.shape_bytes("opaque", "7") == 0

    def test_entry_params_skip_index_comments(self):
        params = H.parse_entry_params(HEADER)
        assert [(d, dims) for d, dims, _ in params] == [
            ("bf16", (8, 16)), ("f32", ()), ("s32", (2, 64)),
            ("f32", (128,))]
        assert params[2][2] == 2 * 64 * 4

    def test_donated_params_nested_braces(self):
        assert H.donated_params(HEADER) == {0, 3}
        assert H.donated_params("HloModule x\n") == set()

    def test_collective_census_counts_start_once(self):
        c = H.collective_census(BODY)
        assert c["all-reduce"] == 2          # plain + start, done excluded
        assert c["all-gather"] == 1
        assert c["collective-permute"] == 1
        assert c["all-to-all"] == 0

    def test_upcast_ops(self):
        ups = H.upcast_ops(BODY)
        assert len(ups) == 1 and ups[0].shape == "f32[8,16]"
        assert ups[0].source == "m.py:4"
        assert H.upcast_ops(BODY, min_bytes=10 ** 6) == []

    def test_largest_ops(self):
        top = H.largest_ops(BODY, top=1)
        assert top[0].shape == "f32[128,512]"
        assert top[0].nbytes == 128 * 512 * 4


# ---------------- compiled-program audits ------------------------------------

BASE = load_baseline()


class TestTrainStepAudit:
    @pytest.fixture(scope="class")
    def dp8(self):
        step, batch = dp8_bucketed_step(8)
        rep = A.audit_train_step(step, *batch)
        return step, rep

    @pytest.fixture(scope="class")
    def llama(self):
        step, batch = tiny_llama_step()
        rep = A.audit_train_step(step, *batch)
        return step, rep

    def test_dp8_allreduce_contract_pinned(self, dp8):
        """The PR 7 contract as a machine-checked regression: one
        all-reduce per bucket + one for the loss, exactly."""
        step, rep = dp8
        assert step._comm_buckets is not None
        assert rep.all_reduce_count == len(step._comm_buckets) + 1
        assert rep.all_reduce_count == \
            BASE.audit["train_step_allreduce_count"]
        assert not [f for f in rep.findings
                    if f.rule == "allreduce-contract"]

    def test_dp8_donation_clean(self, dp8):
        _, rep = dp8
        assert rep.donation_coverage == 1.0
        assert rep.donation_misses == []

    def test_unbucketed_storm_flagged(self, dp8):
        """Seeded defect: the same model with the bucketed path doctored
        off carries a per-param all-reduce storm — flagged P0 against
        the reference contract."""
        step, _ = dp8
        contract = len(step._comm_buckets) + 1
        import paddle_tpu.distributed as dist
        mesh = dist.init_mesh({"dp": 8})
        pt.seed(3)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 4))
        m = dist.DataParallel(net, mesh=mesh)
        o = pt.optimizer.AdamW(learning_rate=0.01,
                               parameters=m.parameters())
        doctored = pt.jit.TrainStep(
            m, lambda mm, x, y: ((mm(x) - y) ** 2).mean(), o,
            bucketed=False)
        rng = np.random.RandomState(0)
        X = rng.randn(64, 16).astype(np.float32)
        Y = X @ rng.randn(16, 4).astype(np.float32)
        rep = A.audit_train_step(doctored, pt.to_tensor(X),
                                 pt.to_tensor(Y),
                                 expected_all_reduce=contract)
        assert rep.all_reduce_count > contract
        storms = [f for f in rep.findings if f.rule == "allreduce-contract"]
        assert len(storms) == 1 and storms[0].severity == "P0"
        assert storms[0].anchor == "storm"

    def test_llama_donation_coverage_pinned(self, llama):
        """Committed geometry: every train-param and optimizer-state
        leaf aliases an output; the only undonated bytes are the token
        batch + the lr scalar (pinned)."""
        _, rep = llama
        assert rep.donation_coverage == 1.0
        assert rep.donation_misses == []
        assert rep.undonated_bytes == \
            BASE.audit["train_step_undonated_bytes"]
        assert rep.donation_coverage == \
            BASE.audit["train_step_donation_coverage"]

    def test_llama_param_names_aligned(self, llama):
        _, rep = llama
        names = [p[0] for p in rep.params]
        assert any(n.startswith("train['model.embed_tokens") for n in names)
        # the token batch leaf is undonated, by name
        und = [n for n, _, _, _, don in rep.params if not don]
        assert any(n.startswith("batch") or n.startswith("param")
                   for n in und)

    def test_llama_largest_intermediate_pinned(self, llama):
        _, rep = llama
        assert rep.largest_intermediate_bytes == \
            BASE.audit["train_step_largest_intermediate_bytes"]
        # at least logits-sized ([B=2, S=64, V=512] f32)
        assert rep.largest_intermediate_bytes >= 2 * 64 * 512 * 4

    def test_llama_no_upcasts_clean(self, llama):
        _, rep = llama
        assert rep.upcasts == []
        assert not rep.findings

    def test_donation_miss_flagged(self):
        """Seeded defect: donate=False is exactly the 2x-memory class —
        every train/state leaf is reported missed, large ones as P0."""
        step, batch = _mlp_step(donate=False)
        rep = A.audit_train_step(step, *batch, large_bytes=64)
        assert rep.donation_coverage == 0.0
        assert len(rep.donation_misses) > 0
        misses = [f for f in rep.findings if f.rule == "undonated-buffer"]
        assert misses and all(f.severity == "P0" for f in misses)
        assert any("train['0.weight']" == f.anchor for f in misses)

    def test_injected_upcast_flagged(self):
        """Seeded defect: a bf16 program with an injected f32 upcast of
        a large intermediate is flagged with source attribution."""
        import jax
        import jax.numpy as jnp

        def f(x):
            big = x.astype(jnp.float32) * 2.0   # the injected upcast
            return big.sum()

        x = jnp.ones((256, 512), jnp.bfloat16)
        hlo = jax.jit(f).lower(x).compile().as_text()
        rep = A.audit_program(hlo, "doctored", large_bytes=256 * 512 * 4)
        ups = [f for f in rep.findings if f.rule == "f32-upcast"]
        assert len(ups) == 1
        assert ups[0].anchor == "f32[256,512]"

    def test_audit_is_rng_neutral(self):
        """Auditing mid-training must not shift the key stream (same
        contract as TrainStep.compiled_hlo)."""
        def run(with_audit):
            step, batch = _mlp_step(seed=11)
            out = [float(step(*batch).numpy())]
            if with_audit:
                A.audit_train_step(step, *batch)
            out += [float(step(*batch).numpy()) for _ in range(2)]
            return out

        np.testing.assert_array_equal(run(True), run(False))


class TestServingAudit:
    def test_engine_audit_and_state_neutral_inspection(self):
        """ServingEngine.compiled_hlo: audit sees the unified step (no
        collectives on one mesh), and inspection shares the jit cache
        with real calls — the compile-once counter reads exactly 1
        after inspect + run, same as an uninspected engine after its
        first step."""
        engine = tiny_serving_engine()
        rep = A.audit_serving_engine(engine)
        assert rep.all_reduce_count == 0
        # the ONE unified-step trace happened during inspection
        assert engine.step_traces == 1
        # args_info naming: per-layer pools + metadata leaves by name,
        # so the TPU pool-donation contract has real names to match
        names = [p[0] for p in rep.params]
        assert any(n.startswith("k_pools[") for n in names), names[:6]
        assert any(n.startswith("state['") for n in names)
        # the donation check CAN fire: expecting pool donation on this
        # CPU engine (which never requests it) must produce misses
        doctored = A.audit_program(
            engine.compiled_hlo(), "serving_step",
            args_info=engine._lowered_step().args_info,
            arg_names=A.SERVING_STEP_ARGS,
            expected_donated_prefixes=("k_pools", "v_pools"),
            large_bytes=1024)
        assert doctored.donation_misses
        assert any(f.rule == "undonated-buffer"
                   and f.anchor.startswith("k_pools[")
                   for f in doctored.findings)
        h = engine.compiled_hlo()       # second inspection: cached
        assert "HloModule" in h
        assert engine.step_traces == 1
        # a real request after inspection: no re-trace, tokens out
        handle = engine.submit([3, 5, 7], max_new_tokens=4)
        engine.run_until_idle()
        res = handle.result(timeout=30)
        assert res["num_generated"] == 4
        assert engine.step_traces == 1
        assert engine.stats()["step_compiles"] == 1


# ---------------- recompile diff ---------------------------------------------

class TestRecompileDiff:
    def _key(self, args, kwargs=None, training=False,
             train=("w", "b")):
        from paddle_tpu.jit.api import _sig_of
        treedef, sig = _sig_of((args, kwargs or {}))
        return (treedef, sig, training, tuple(train))

    def test_shape_change_names_leaf(self):
        a = self._key((np.zeros((4, 8), np.float32),))
        b = self._key((np.zeros((4, 16), np.float32),))
        (cause,) = A.diff_compile_keys(a, b)
        assert "f32" not in cause or True
        assert "[4, 8]" in cause and "[4, 16]" in cause

    def test_dtype_change_names_leaf(self):
        a = self._key((np.zeros((4,), np.float32),))
        b = self._key((np.zeros((4,), np.int32),))
        (cause,) = A.diff_compile_keys(a, b)
        assert "float32" in cause and "int32" in cause

    def test_structure_change(self):
        a = self._key((np.zeros((4,), np.float32),))
        b = self._key((np.zeros((4,), np.float32),
                       np.zeros((4,), np.float32)))
        causes = A.diff_compile_keys(a, b)
        assert any("structure" in c for c in causes)

    def test_mode_and_trainable_set(self):
        x = (np.zeros((4,), np.float32),)
        a = self._key(x, training=True, train=("w", "b"))
        b = self._key(x, training=False, train=("w",))
        causes = " | ".join(A.diff_compile_keys(a, b))
        assert "training=True -> False" in causes
        assert "'b'" in causes and "left the trainable set" in causes

    def test_identical_keys(self):
        a = self._key((np.zeros((4,), np.float32),))
        assert A.diff_compile_keys(a, a) == ["keys are identical"]

    def test_recompile_report_on_real_step(self):
        step, (X, Y) = _mlp_step(seed=5)
        step(X, Y)
        rng = np.random.RandomState(1)
        X2 = pt.to_tensor(rng.randn(16, 16).astype(np.float32))
        Y2 = pt.to_tensor(rng.randn(16, 4).astype(np.float32))
        step(X2, Y2)
        report = A.recompile_report(step)
        assert len(report) == 1
        causes = " | ".join(report[0]["causes"])
        assert "[8, 16]" in causes and "[16, 16]" in causes


# ---------------- linter -----------------------------------------------------

GC_LEAK = textwrap.dedent("""\
    import jax
    import jax.numpy as jnp

    class LeakyFlusher:
        def _flush(self):
            self._state = jnp.split(self._flat, self._sizes)

        def __del__(self):
            try:
                self._flush()
            except Exception:
                pass

    class GuardedFlusher:
        def _flush(self):
            with jax.core.eval_context():
                self._state = jnp.split(self._flat, self._sizes)

        def __del__(self):
            self._flush()
""")

SIGNAL_LOCK = textwrap.dedent("""\
    import signal
    import threading

    class Listener:
        def install(self):
            signal.signal(signal.SIGTERM, self._handler)

        def _handler(self, sn, frame):
            with self._lock:
                self._flagged = True
            self._metric.inc(reason="preempt")
            self._note()

        def _note(self):
            self._ev = threading.Event()

    class SafeListener:
        def install(self):
            signal.signal(signal.SIGTERM, self._handler)

        def _handler(self, sn, frame):
            self._flagged = True
            self.reason = "sig"
""")

TRACE_MUT = textwrap.dedent("""\
    import time
    import jax
    import numpy as np

    class Stepper:
        def build(self):
            def step(x):
                self._cur_param = x
                t = time.perf_counter()
                r = np.random.randn(3)
                return x * t + r.sum()
            return jax.jit(step)

        def build_allowed(self):
            def step(x):
                self.traces += 1  # analysis: allow(trace-attr-mutation)
                return x * 2
            return jax.jit(step)

        def eager_ok(self, x):
            self._cur_param = x      # not traced: no finding
            return x
""")

THREADS = textwrap.dedent("""\
    import threading

    def leak():
        t = threading.Thread(target=print)
        t.start()

    def joined():
        u = threading.Thread(target=print)
        u.start()
        u.join()

    def daemonized():
        v = threading.Thread(target=print, daemon=True)
        v.start()
""")


def _lint_src(tmp_path, src, name="fixture.py"):
    p = tmp_path / name
    p.write_text(src)
    return lint_file(str(p), name)


class TestLinter:
    def test_eval_context_guard_nested_in_if(self, tmp_path):
        """An eval_context guard under an ``if``/``try`` still guards
        its body — the natural shape of the PR 7 flush must not raise
        a false P0."""
        src = textwrap.dedent("""\
            import jax
            import jax.numpy as jnp

            class F:
                def _flush(self):
                    if self._flat is not None:
                        try:
                            with jax.core.eval_context():
                                self._state = jnp.split(self._flat, 3)
                        except Exception:
                            pass
                    else:
                        jnp.zeros(())

                def __del__(self):
                    self._flush()
        """)
        fs = _lint_src(tmp_path, src)
        gc = [f for f in fs if f.rule == "gc-eager-jax"]
        # only the UNguarded else-branch call is flagged
        assert len(gc) == 1 and gc[0].anchor == "jnp.zeros"

    def test_gc_trace_leak_caught(self, tmp_path):
        """Historical class 1: the PR 7 GC-time flush that staged jnp
        ops into a foreign trace."""
        fs = _lint_src(tmp_path, GC_LEAK)
        rules = [(f.rule, f.where) for f in fs]
        assert ("gc-eager-jax", "LeakyFlusher._flush") in rules
        # the eval_context-guarded twin is clean
        assert not [f for f in fs if "Guarded" in f.where]
        f = [f for f in fs if f.rule == "gc-eager-jax"][0]
        assert f.severity == "P0" and f.anchor == "jnp.split"

    def test_signal_handler_lock_caught(self, tmp_path):
        """Historical class 2: lock/Event/metrics traffic in signal
        context (PR 4: handlers write plain attributes only)."""
        fs = _lint_src(tmp_path, SIGNAL_LOCK)
        sig = [f for f in fs if f.rule == "signal-unsafe-call"]
        anchors = {f.anchor for f in sig}
        assert "with:self._lock" in anchors       # the with-lock
        assert "self._metric.inc" in anchors      # metrics in handler
        assert "threading.Event" in anchors       # depth-1 callee
        assert all(f.severity == "P0" for f in sig)
        assert not [f for f in sig if "SafeListener" in f.where]

    def test_signal_registration_aliases(self, tmp_path):
        """Aliased registration forms must not dodge the rule:
        `from signal import signal` and `import signal as sig`."""
        src = textwrap.dedent("""\
            import signal as sig
            from signal import signal as reg

            class A:
                def install(self):
                    sig.signal(sig.SIGTERM, self._h)
                    reg(sig.SIGUSR1, self._g)

                def _h(self, sn, frame):
                    self._lock.acquire()

                def _g(self, sn, frame):
                    self._m.observe(1.0)
        """)
        fs = _lint_src(tmp_path, src)
        anchors = {f.anchor for f in fs if f.rule == "signal-unsafe-call"}
        assert "self._lock.acquire" in anchors
        assert "self._m.observe" in anchors

    def test_trace_attr_mutation_caught(self, tmp_path):
        """Historical class 3: the _cur_param trace-time side channel."""
        fs = _lint_src(tmp_path, TRACE_MUT)
        mut = [f for f in fs if f.rule == "trace-attr-mutation"]
        assert len(mut) == 1 and mut[0].anchor == "_cur_param"
        assert mut[0].severity == "P0"
        # eager method and allow()-annotated counter are clean
        assert not [f for f in fs if "eager_ok" in f.where]
        assert not [f for f in fs if f.anchor == "traces"]

    def test_traced_impurity_caught(self, tmp_path):
        fs = _lint_src(tmp_path, TRACE_MUT)
        imp = {f.anchor for f in fs if f.rule == "traced-impurity"}
        assert imp == {"time.perf_counter", "np.random.randn"}

    def test_unjoined_thread(self, tmp_path):
        fs = _lint_src(tmp_path, THREADS)
        th = [f for f in fs if f.rule == "unjoined-thread"]
        assert len(th) == 1 and th[0].anchor == "t"

    def test_fingerprints_stable_under_line_shift(self, tmp_path):
        a = _lint_src(tmp_path, GC_LEAK, "a_fixture.py")
        shifted = "# pad\n" * 7 + GC_LEAK
        b = _lint_src(tmp_path, shifted.replace("a_fixture", "x"),
                      "a_fixture.py")
        assert {f.fingerprint for f in a} == {f.fingerprint for f in b}
        assert a[0].line != b[0].line

    def test_repo_tree_lint_clean_vs_baseline(self):
        """The whole package (+bench.py) lints clean against the
        committed baseline — the CI gate every future PR runs."""
        findings = lint_tree(os.path.join(REPO, "paddle_tpu"),
                             extra_files=(os.path.join(REPO, "bench.py"),))
        new, known, stale = BASE.split(findings)
        assert not new, "new lint findings:\n" + "\n".join(
            f.format() for f in new)
        assert not stale, f"fixed findings still in baseline: {stale}"

    def test_baseline_split_semantics(self):
        f1 = Finding("r", "P0", "a.py", "X.y", "m", anchor="z")
        f2 = Finding("r", "P0", "a.py", "X.q", "m", anchor="w")
        base = Baseline({"findings": {f1.fingerprint: {"rule": "r"},
                                      "deadbeef00000000": {"rule": "r"}}})
        new, known, stale = base.split([f1, f2])
        assert [f.where for f in new] == ["X.q"]
        assert [f.where for f in known] == ["X.y"]
        assert set(stale) == {"deadbeef00000000"}


# ---------------- env-knob registry ------------------------------------------

class TestKnobRegistry:
    def test_collects_real_knobs_with_sites(self):
        code = K.collect_code_knobs(
            os.path.join(REPO, "paddle_tpu"),
            extra_files=(os.path.join(REPO, "bench.py"),))
        assert "PADDLE_TPU_COMM_BUCKET_MB" in code
        files = [f for f, _ in code["PADDLE_TPU_COMM_BUCKET_MB"]]
        assert any(f.endswith("jit/bucketing.py") for f in files)
        # prefix family collected from the startswith scan
        assert "PADDLE_TPU_CHAOS_" in code
        # docstring-only mentions don't create registry entries
        assert all(not f.endswith("serving/engine.py")
                   for f, _ in code.get("PADDLE_TPU_PAGED_ATTN_IMPL", []))

    def test_no_drift_on_committed_tree(self):
        """Tier-1 contract (modeled on TestDocsMetricDrift): every knob
        read in code is documented in docs/*.md or README.md, and every
        documented knob still has a read site."""
        d = K.drift(os.path.join(REPO, "paddle_tpu"),
                    extra_files=(os.path.join(REPO, "bench.py"),))
        assert not d["undocumented"], (
            f"knobs read in code but absent from docs/*.md: "
            f"{d['undocumented']} — document them (docs/ANALYSIS.md has "
            f"the knob table workflow)")
        assert not d["ghosts"], (
            f"knobs documented but never read: {d['ghosts']} — fix the "
            f"doc or restore the read site")

    def test_drift_detects_both_directions(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            'import os\nX = os.environ.get("PADDLE_TPU_NEW_KNOB")\n')
        docs_base = tmp_path / "repo"
        (docs_base / "docs").mkdir(parents=True)
        (docs_base / "docs" / "X.md").write_text(
            "`PADDLE_TPU_GHOST_KNOB` does nothing anymore\n")
        d = K.drift(str(pkg), docs_root=str(docs_base))
        assert d["undocumented"] == ["PADDLE_TPU_NEW_KNOB"]
        assert d["ghosts"] == ["PADDLE_TPU_GHOST_KNOB"]

    def test_prefix_family_covers_members(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            'import os\n'
            'ks = [k for k in os.environ if '
            'k.startswith("PADDLE_TPU_FAM_")]\n')
        docs_base = tmp_path / "repo"
        (docs_base / "docs").mkdir(parents=True)
        (docs_base / "docs" / "X.md").write_text(
            "set any `PADDLE_TPU_FAM_WHATEVER` member\n")
        d = K.drift(str(pkg), docs_root=str(docs_base))
        assert d["undocumented"] == [] and d["ghosts"] == []


# ---------------- CLI + bench gate -------------------------------------------

class TestCliAndGate:
    def test_lint_cli(self, tmp_path, capsys):
        """CLI smoke on a tiny tree (the full-tree gate is
        test_repo_tree_lint_clean_vs_baseline): clean file exits 0, a
        seeded defect exits 1 and prints NEW."""
        from paddle_tpu.analysis.__main__ import main
        (tmp_path / "ok.py").write_text("x = 1\n")
        assert main(["lint", "--root", str(tmp_path)]) == 0
        (tmp_path / "bad.py").write_text(GC_LEAK)
        assert main(["lint", "--root", str(tmp_path)]) == 1
        assert "gc-eager-jax" in capsys.readouterr().out

    def test_knobs_cli_clean(self, capsys):
        from paddle_tpu.analysis.__main__ import main
        assert main(["knobs", "--json"]) == 0

    def test_report_gate_learns_audit_directions(self):
        bench = _bench()
        for name in ("train_step_allreduce_count",
                     "train_step_undonated_bytes",
                     "train_step_largest_intermediate_bytes"):
            assert name in bench.REPORT_LOWER_BETTER
        cmp = bench.report_compare(
            {"train_step_allreduce_count": 2.0,
             "train_step_undonated_bytes": 516.0},
            {"train_step_allreduce_count": 5.0,     # storm: regression
             "train_step_undonated_bytes": 500.0},  # improvement: ok
            tolerance_pct=3)
        by = {r["metric"]: r["status"] for r in cmp["rows"]}
        assert by["train_step_allreduce_count"] == "fail"
        assert by["train_step_undonated_bytes"] == "ok"
        assert cmp["failures"] == ["train_step_allreduce_count"]

    @pytest.mark.slow
    def test_bench_audit_emits_headlines(self):
        """Full bench.py --audit subprocess: the three LOWER_BETTER
        headline JSON lines are on stdout with the _cpu_smoke suffix."""
        import subprocess
        import sys as _sys
        env = dict(os.environ, BENCH_FORCE_CPU="1")
        out = subprocess.run(
            [_sys.executable, os.path.join(REPO, "bench.py"), "--audit"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=REPO)
        assert out.returncode == 0, out.stderr[-2000:]
        metrics = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                obj = json.loads(line)
                if "metric" in obj:
                    metrics[obj["metric"]] = obj["value"]
        for name in ("train_step_allreduce_count",
                     "train_step_undonated_bytes",
                     "train_step_largest_intermediate_bytes"):
            assert f"{name}_cpu_smoke" in metrics
        assert metrics["train_step_allreduce_count_cpu_smoke"] == \
            BASE.audit["train_step_allreduce_count"]


# ---------------- ISSUE 12 lint satellites -----------------------------------

LOCKS = textwrap.dedent("""\
    import time
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition()

        def bad(self):
            with self._lock:
                time.sleep(1.0)
                self._t.join()
                self._q.get()
                self._fut.result()

        def bounded_ok(self):
            with self._lock:
                self._t.join(timeout=2)
                self._fut.result(timeout=1)
                self._ev.wait(0.5)

        def cv_ok(self):
            with self._cv:
                self._cv.wait()

        def via_callee(self):
            with self._lock:
                self._drain()

        def _drain(self):
            self._t2.join()

        def no_lock(self):
            time.sleep(1.0)
""")


class TestBlockingUnderLock:
    def test_blocking_calls_flagged(self, tmp_path):
        fs = _lint_src(tmp_path, LOCKS)
        hits = [f for f in fs if f.rule == "blocking-call-under-lock"]
        assert all(f.severity == "P0" for f in hits)
        anchors = {f.anchor for f in hits}
        assert anchors == {"self._lock:time.sleep",
                           "self._lock:self._t.join",
                           "self._lock:self._q.get",
                           "self._lock:self._fut.result",
                           "self._lock:self._t2.join"}
        # depth-1 callee hit is attributed to the callee's qualname
        callee = [f for f in hits if f.anchor.endswith("_t2.join")]
        assert callee[0].where == "Worker._drain"

    def test_timeouts_and_cv_wait_exempt(self, tmp_path):
        fs = _lint_src(tmp_path, LOCKS)
        lines = {f.line for f in fs
                 if f.rule == "blocking-call-under-lock"}
        src_lines = LOCKS.splitlines()
        for needle in ("join(timeout=2)", "result(timeout=1)",
                       "wait(0.5)", "self._cv.wait()"):
            ln = next(i for i, s in enumerate(src_lines, 1) if needle in s)
            assert ln not in lines, f"{needle} wrongly flagged"

    def test_suppression_honored(self, tmp_path):
        allowed = LOCKS.replace(
            "time.sleep(1.0)",
            "time.sleep(1.0)  # analysis: allow(blocking-call-under-lock)")
        fs = _lint_src(tmp_path, allowed)
        anchors = {f.anchor for f in fs
                   if f.rule == "blocking-call-under-lock"}
        assert "self._lock:time.sleep" not in anchors
        assert "self._lock:self._t.join" in anchors


class TestStaleSuppressions:
    def test_live_allow_not_reported(self, tmp_path):
        fs = _lint_src(tmp_path, TRACE_MUT)
        assert not [f for f in fs if f.rule == "stale-suppression"]

    def test_dead_allow_reported_p2(self, tmp_path):
        src = ("def f():\n"
               "    return 1  # analysis: allow(gc-eager-jax)\n")
        fs = _lint_src(tmp_path, src)
        stale = [f for f in fs if f.rule == "stale-suppression"]
        assert len(stale) == 1 and stale[0].severity == "P2"
        assert "gc-eager-jax" in stale[0].anchor

    def test_strict_suppressions_cli_flag(self, tmp_path, capsys):
        from paddle_tpu.analysis.__main__ import main
        (tmp_path / "mod.py").write_text(
            "x = 1  # analysis: allow(unjoined-thread)\n")
        bl = str(tmp_path / "bl.json")
        assert main(["lint", "--root", str(tmp_path),
                     "--baseline", bl]) == 0
        assert "stale-suppression" in capsys.readouterr().err
        assert main(["lint", "--root", str(tmp_path), "--baseline", bl,
                     "--strict-suppressions"]) == 1
        assert "stale-suppression" in capsys.readouterr().out


class TestCommBytesReportFamily:
    def test_prefix_membership_and_gate_direction(self):
        bench = _bench()
        assert bench._lower_better("train_step_comm_bytes_dp_cpu_smoke")
        assert bench._lower_better("train_step_comm_bytes_mp")
        assert not bench._lower_better("train_step_comm_count")
        cmp = bench.report_compare(
            {"train_step_comm_bytes_dp_cpu_smoke": 4739.0},
            {"train_step_comm_bytes_dp_cpu_smoke": 6000.0},
            tolerance_pct=5)
        assert cmp["failures"] == ["train_step_comm_bytes_dp_cpu_smoke"]
        cmp = bench.report_compare(
            {"train_step_comm_bytes_dp_cpu_smoke": 4739.0}, {},
            tolerance_pct=5)
        assert cmp["skipped"] == ["train_step_comm_bytes_dp_cpu_smoke"]
