"""Elastic scale-in/out worker (run via the launcher with --np min:max —
NOT a pytest file). Each epoch it records (epoch, rank, world, pid) into
RUN_DIR, then idles until the store's finish flag — letting the test kill
a worker (scale-in), announce a replacement (scale-out), and finally end
the job cleanly."""
import os
import sys
import time

from paddle_tpu.distributed.tcp_store import job_store


def main():
    run_dir = os.environ["ELASTIC_TEST_DIR"]
    rank = os.environ["PADDLE_TRAINER_ID"]
    world = os.environ["PADDLE_TRAINERS_NUM"]
    epoch = os.environ["PADDLE_RESTART_EPOCH"]
    store = job_store()
    with open(os.path.join(run_dir,
                           f"epoch{epoch}.rank{rank}.world{world}.pid"),
              "w") as f:
        f.write(str(os.getpid()))
    while store.get("elastic_test/finish") is None:
        time.sleep(0.1)
    print(f"worker rank={rank} world={world} epoch={epoch} done",
          flush=True)


if __name__ == "__main__":
    main()
