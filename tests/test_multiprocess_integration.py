"""Spawn a REAL 2-process cluster (the reference's test_dist_base.py
subprocess pattern): launcher CLI -> TCPStore rendezvous -> heartbeats ->
rpc -> PS -> store-backed object collectives. This is the DCN host
-protocol half of multi-host; device-mesh collectives stay on the
virtual-mesh tests."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_cluster():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # workers need no virtual mesh
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2",
         os.path.join(REPO, "tests", "integration_worker.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=280)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"cluster failed:\n{out[-4000:]}"
    assert "INTEGRATION OK rank=0" in out, out[-4000:]
    assert "INTEGRATION OK rank=1" in out, out[-4000:]
