"""Fused vocab-chunked cross entropy (ops/fused_ce.py) vs the plain
softmax-CE oracle: values, gradients, and the no-[T,V]-intermediate
memory contract."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.fused_ce import matmul_cross_entropy


def oracle(h, w, labels):
    logits = (h.astype(jnp.float32) @ w.astype(jnp.float32).T)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    lab = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return lse - lab


@pytest.mark.parametrize("n_chunks", [1, 4, 8])
def test_value_parity(n_chunks):
    rng = np.random.RandomState(0)
    T, d, V = 64, 32, 256
    h = jnp.asarray(rng.randn(T, d), jnp.float32)
    w = jnp.asarray(rng.randn(V, d), jnp.float32)
    lab = jnp.asarray(rng.randint(0, V, (T,)), jnp.int32)
    got = matmul_cross_entropy(h, w, lab, n_chunks=n_chunks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(
        oracle(h, w, lab)), rtol=1e-5, atol=1e-5)


def test_grad_parity():
    rng = np.random.RandomState(1)
    T, d, V = 32, 16, 128
    h = jnp.asarray(rng.randn(T, d), jnp.float32)
    w = jnp.asarray(rng.randn(V, d), jnp.float32)
    lab = jnp.asarray(rng.randint(0, V, (T,)), jnp.int32)
    scale = jnp.asarray(rng.rand(T), jnp.float32)  # non-uniform cotangent

    def f(a, b):
        return jnp.sum(matmul_cross_entropy(a, b, lab, n_chunks=4) * scale)

    def g(a, b):
        return jnp.sum(oracle(a, b, lab) * scale)

    got = jax.grad(f, argnums=(0, 1))(h, w)
    ref = jax.grad(g, argnums=(0, 1))(h, w)
    for x, y in zip(got, ref):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-4, atol=2e-5)


def test_no_full_logits_intermediate():
    """The jaxpr of value+grad must contain no [T, V]-sized tensor —
    that's the entire point of the chunking + custom VJP."""
    T, d, V, nc = 256, 64, 4096, 8
    h = jnp.zeros((T, d), jnp.bfloat16)
    w = jnp.zeros((V, d), jnp.bfloat16)
    lab = jnp.zeros((T,), jnp.int32)

    def f(a, b):
        return matmul_cross_entropy(a, b, lab, n_chunks=nc).sum()

    jaxpr = jax.make_jaxpr(jax.grad(f, argnums=(0, 1)))(h, w)

    def walk(jx):
        big = 0
        for eqn in jx.eqns:
            for v in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(v, "aval", None)
                if aval is not None and getattr(aval, "shape", None):
                    big = max(big, int(np.prod(aval.shape)))
            for val in eqn.params.values():
                for sub in (val if isinstance(val, (list, tuple))
                            else [val]):
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None:
                        big = max(big, walk(inner))
                    elif hasattr(sub, "eqns"):
                        big = max(big, walk(sub))
        return big

    biggest = walk(jaxpr.jaxpr)
    assert biggest <= T * (V // nc) * 2, (
        f"largest intermediate {biggest} elements — full logits leaked "
        f"(T*V = {T * V})")


def test_llama_fused_path_parity():
    """Tied-vocab Llama above the fusion threshold: the fused loss must
    equal the plain logits+CE path (threshold forced down for the test)."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, tie_word_embeddings=True)
    x = pt.to_tensor(np.random.RandomState(0).randint(
        0, 256, (2, 32)).astype(np.int64))

    pt.seed(0)
    m = LlamaForCausalLM(cfg)
    logits, plain = m(x, labels=x)
    assert logits is not None  # below threshold: plain path

    old = LlamaForCausalLM._FUSED_CE_MIN_VOCAB
    LlamaForCausalLM._FUSED_CE_MIN_VOCAB = 1
    try:
        none_logits, fused = m(x, labels=x)
        assert none_logits is None  # fused path skips logits by contract
        np.testing.assert_allclose(fused.numpy(), plain.numpy(),
                                   rtol=1e-5, atol=1e-6)
        fused.backward()
        g = m.model.embed_tokens.weight.grad
        assert g is not None and np.isfinite(g.numpy()).all()
    finally:
        LlamaForCausalLM._FUSED_CE_MIN_VOCAB = old


def test_ignore_index_parity():
    """-100-padded labels (the HF packing convention): zero loss AND zero
    gradient for ignored tokens, matching F.cross_entropy."""
    rng = np.random.RandomState(3)
    T, d, V = 64, 32, 256
    h = jnp.asarray(rng.randn(T, d), jnp.float32)
    w = jnp.asarray(rng.randn(V, d), jnp.float32)
    lab = rng.randint(0, V, (T,))
    lab[T // 2:] = -100
    lab = jnp.asarray(lab, jnp.int32)

    def ref(a, b):
        valid = lab != -100
        per = jnp.where(valid, oracle(a, b, jnp.where(valid, lab, 0)), 0.0)
        return per

    got = matmul_cross_entropy(h, w, lab, n_chunks=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref(h, w)),
                               rtol=1e-5, atol=1e-5)
    assert np.all(np.asarray(got)[T // 2:] == 0.0)

    g_got = jax.grad(lambda a, b: matmul_cross_entropy(
        a, b, lab, n_chunks=4).mean(), argnums=(0, 1))(h, w)
    g_ref = jax.grad(lambda a, b: ref(a, b).mean(), argnums=(0, 1))(h, w)
    for x, y in zip(g_got, g_ref):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-4, atol=2e-5)
    # dh rows of ignored tokens are exactly zero
    assert np.all(np.asarray(g_got[0])[T // 2:] == 0.0)


def test_cross_entropy_masked_mean_semantics():
    """reference cross_entropy(reduction='mean') divides by the count of
    non-ignored tokens whenever any label equals ignore_index — including
    the default -100 (reference loss.py mask/count branch)."""
    import paddle_tpu as pt
    from paddle_tpu.nn import functional as F

    rng = np.random.RandomState(7)
    T, V = 48, 32
    logits = rng.randn(T, V).astype(np.float32)
    lab = rng.randint(0, V, (T,))
    lab[T // 3:] = -100

    got = F.cross_entropy(pt.to_tensor(logits),
                          pt.to_tensor(lab.astype(np.int64))).numpy()
    lse = np.log(np.exp(logits).sum(-1))
    per = lse - logits[np.arange(T), np.where(lab == -100, 0, lab)]
    want = per[: T // 3].mean()  # mean over VALID tokens only
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_llama_fused_vs_plain_with_padding():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=64, tie_word_embeddings=True)
    ids = np.random.RandomState(5).randint(0, 256, (2, 32))
    labels = ids.copy()
    labels[:, 20:] = -100  # padded tail
    x = pt.to_tensor(ids.astype(np.int64))
    y = pt.to_tensor(labels.astype(np.int64))
    pt.seed(0)
    m = LlamaForCausalLM(cfg)
    _, plain = m(x, labels=y)
    old = LlamaForCausalLM._FUSED_CE_MIN_VOCAB
    LlamaForCausalLM._FUSED_CE_MIN_VOCAB = 1
    try:
        _, fused = m(x, labels=y)
        np.testing.assert_allclose(fused.numpy(), plain.numpy(),
                                   rtol=1e-5, atol=1e-6)
        # reference masked-mean semantics: with -100-padded labels the mean
        # divides by the VALID token count, so loss must equal the mean of
        # per-token losses over unpadded positions only. Cross-check by
        # doubling the padded tail: more padding must NOT shrink the loss.
        labels2 = ids.copy()
        labels2[:, 10:] = -100
        _, fused_more_pad = m(x, labels=pt.to_tensor(
            labels2.astype(np.int64)))
        assert fused_more_pad.numpy() > 0.5 * fused.numpy(), \
            "loss scaled down by the valid fraction — mean is dividing " \
            "by ALL tokens instead of valid tokens"
    finally:
        LlamaForCausalLM._FUSED_CE_MIN_VOCAB = old
