"""Request-level serving observability (ISSUE 16).

Coverage contract: W3C traceparent parse/format round trip and the HTTP
echo (client-supplied id on every response, errors included); ledger
token exactness against the bit-identical greedy stream (prefilled +
cached covers the prompt, decode equals the continuation) and the
ledger-disarmed twin producing the same tokens; the tail sampler
keeping every error/preempted record; multi-window burn rates tripping
on a sustained breach (and NOT on a fast-window-only burst) then
recovering as the windows drain; the ``/statusz`` contract on both HTTP
front ends; the ``serving_rejections_total{reason}`` split; and ``trace
merge --requests`` cross-checked against the live ledger.
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import get_registry, slo
from paddle_tpu.observability import requests as obs_requests
from paddle_tpu.observability.requests import (RequestLedger, RequestRecord,
                                               format_traceparent,
                                               new_trace_id,
                                               parse_traceparent)
from paddle_tpu.serving import Server, ServingEngine


def _tiny(seed=11):
    pt.seed(seed)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=True))
    m.eval()
    return m


def _eager_continuation(model, prompt, max_new_tokens):
    out = model.generate(pt.to_tensor(np.asarray(prompt)[None, :]),
                         max_new_tokens=max_new_tokens,
                         temperature=0.0).numpy()[0]
    return [int(t) for t in out[len(prompt):]]


@pytest.fixture(scope="module")
def served():
    """One model + armed-ledger engine shared module-wide (compile
    once); the ledger is on by default — no env needed."""
    model = _tiny(11)
    eng = ServingEngine(model, max_batch=4, max_blocks=32, block_size=4,
                        prefill_chunk=4)
    assert eng._ledger is not None  # armed by default
    return model, eng


def _post(url, body, headers=None, timeout=60):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


# ---------------- traceparent helpers ----------------------------------------
def test_traceparent_parse_format_roundtrip():
    tid = new_trace_id()
    assert len(tid) == 32 and int(tid, 16) != 0
    hdr = format_traceparent(tid)
    assert parse_traceparent(hdr) == tid
    assert hdr.startswith("00-") and hdr.endswith("-01")
    assert parse_traceparent(format_traceparent(tid, sampled=False)) == tid
    # uppercase inbound headers normalize per spec
    assert parse_traceparent(hdr.upper()) == tid
    for bad in (None, "", "garbage", hdr + "-extra",
                "00-" + "0" * 32 + "-" + "a" * 16 + "-01",   # zero trace id
                "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # zero parent
                "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",   # forbidden ver
                "00-" + "g" * 32 + "-" + "b" * 16 + "-01",   # non-hex
                "00-" + "a" * 31 + "-" + "b" * 16 + "-01"):  # short
        assert parse_traceparent(bad) is None


# ---------------- ledger exactness vs the greedy stream ----------------------
def test_ledger_token_exactness(served):
    model, eng = served
    led = eng._ledger
    old_rate = led.sample_rate
    led.sample_rate = 1.0  # keep every completion in the exemplar ring
    try:
        rng = np.random.RandomState(3)
        prompts = [[int(t) for t in rng.randint(1, 128, n)]
                   for n in (6, 5, 7)]
        handles = [eng.submit(p, max_new_tokens=4) for p in prompts]
        eng.run_until_idle()
        results = [h.result(timeout=60) for h in handles]
    finally:
        led.sample_rate = old_rate
    recs = {d["trace_id"]: d for d in led.exemplars()}
    for p, h, res in zip(prompts, handles, results):
        assert res["token_ids"] == _eager_continuation(model, p, 4)
        rec = recs[h.trace_id]
        # token exactness against the scheduler's lifetime accumulators:
        # cold + cached covers the prompt, decode equals the stream
        assert rec["prefilled_tokens"] + rec["cached_tokens"] == len(p)
        assert rec["decode_tokens"] == len(res["token_ids"]) == 4
        assert rec["state"] == "done" and rec["finish_reason"] == "length"
        assert rec["queue_wait_s"] is not None and rec["queue_wait_s"] >= 0
        assert rec["ttft_s"] > 0 and rec["latency_s"] >= rec["ttft_s"]
        # the request held blocks for a while: both cost fields moved
        assert rec["peak_kv_blocks"] > 0 and rec["kv_block_seconds"] > 0
    assert led.in_flight_count() == 0
    # satellite: stats() carries the new accounting fields, and the
    # pool-level integral is at least the per-request billing
    st = eng.stats()
    assert st["requests_in_flight"] == 0
    assert st["kv_block_seconds_total"] >= sum(
        recs[h.trace_id]["kv_block_seconds"] for h in handles) * 0.5


def test_bit_identical_with_ledger_disarmed(served, monkeypatch):
    """PADDLE_TPU_REQUEST_LEDGER=0: the engine holds no ledger and the
    greedy stream is bit-identical — the ledger is host-side only."""
    model, eng = served
    monkeypatch.setenv("PADDLE_TPU_REQUEST_LEDGER", "0")
    eng2 = ServingEngine(model, max_batch=4, max_blocks=32, block_size=4,
                         prefill_chunk=4)
    assert eng2._ledger is None
    # the process-global ledger stays armed for the other engine
    assert obs_requests.active() is not None
    prompt = [int(t) for t in np.random.RandomState(9).randint(1, 128, 6)]
    try:
        h2 = eng2.submit(prompt, max_new_tokens=5)
        eng2.run_until_idle()
        off_tokens = h2.result(timeout=60)["token_ids"]
    finally:
        eng2.shutdown()
    h1 = eng.submit(prompt, max_new_tokens=5)
    eng.run_until_idle()
    on_tokens = h1.result(timeout=60)["token_ids"]
    assert on_tokens == off_tokens == _eager_continuation(model, prompt, 5)


# ---------------- tail sampler ----------------------------------------------
class _FakeReq:
    def __init__(self, rid):
        self.req_id = rid
        self.trace_id = f"{rid:032x}"
        self.arrival_time = 0.0
        self.prompt_tokens = [1, 2, 3]
        self.max_new_tokens = 4


class _FakeSeq:
    def __init__(self, rid, latency=0.1, failed=False, preemptions=0):
        self.req_id = rid
        self.state = "failed" if failed else "finished"
        self.arrival_time = 0.0
        self.slot_time = 0.01
        self.prefilled_tokens = 3
        self.cached_tokens_total = 0
        self.generated = [7, 8]
        self.preemptions = preemptions
        self.finish_reason = "error" if failed else "length"
        self.error = "boom" if failed else None
        self._latency = latency

    def ttft(self):
        return None if self.error else self._latency / 2

    def latency(self):
        return self._latency


def test_tail_sampler_keeps_every_error_preempted_and_slow(tmp_path):
    led = RequestLedger(log_dir=str(tmp_path), sample_rate=0.0)
    rid = iter(range(1000))

    def run(**kw):
        r = next(rid)
        led.admit(_FakeReq(r))
        led.complete(_FakeSeq(r, **kw))

    for _ in range(30):          # unremarkable, sample_rate=0 -> dropped
        run(latency=0.1)
    run(latency=9.0)             # beyond the window's p95
    run(failed=True)             # error: always kept
    run(preemptions=2)           # preempted: always kept
    assert led.dropped == 30
    assert led.kept == {"error": 1, "preempted": 1, "slow_tail": 1,
                        "sampled": 0}
    ring = led.exemplars()
    assert [d["kept"] for d in ring] == ["slow_tail", "error", "preempted"]
    assert ring[1]["error"] == "boom" and ring[1]["state"] == "failed"
    assert ring[2]["preemptions"] == 2
    # JSONL twin: exactly the kept records, valid JSON per line
    led.close()
    files = list(tmp_path.glob("requests_*.jsonl"))
    assert len(files) == 1
    lines = [json.loads(ln) for ln in files[0].read_text().splitlines()]
    assert [d["kept"] for d in lines] == ["slow_tail", "error", "preempted"]


# ---------------- burn rates -------------------------------------------------
def _rec(ttft_s, failed=False):
    r = RequestRecord(req_id=0, trace_id=None, arrival_s=0.0,
                      prompt_len=4, max_new_tokens=4)
    r.state = "failed" if failed else "done"
    r.ttft_s = ttft_s
    return r


def test_burn_rate_trips_on_sustained_breach_and_recovers():
    mon = slo.configure({"ttft_p99": (0.5, 0.99)}, windows_s=(10.0, 100.0),
                        alert_threshold=2.0)
    try:
        t0 = 1000.0
        for i in range(10):                      # sustained breach
            mon.observe(_rec(5.0), now=t0 + i)
        snap = mon.snapshot(now=t0 + 10.0)
        s = snap["slos"]["ttft_p99"]
        # all-bad traffic burns at 1/budget = 100x on both windows
        assert s["burn_rate"]["fast"] == pytest.approx(100.0)
        assert s["burn_rate"]["slow"] == pytest.approx(100.0)
        assert s["alerting"] is True
        m = slo.slo_metrics()
        assert m["alert"].value(slo="ttft_p99") == 1.0
        assert m["burn"].value(slo="ttft_p99", window="fast") == \
            pytest.approx(100.0)
        # healthy traffic: the fast window drains first — slow-window
        # residue alone must NOT page (the multi-window rule)
        for i in range(40):
            mon.observe(_rec(0.01), now=t0 + 30.0 + i)
        snap = mon.snapshot(now=t0 + 70.0)
        s = snap["slos"]["ttft_p99"]
        assert s["burn_rate"]["fast"] == pytest.approx(0.0)
        assert s["burn_rate"]["slow"] > 0.0
        assert s["alerting"] is False
        assert m["alert"].value(slo="ttft_p99") == 0.0
        # and the slow window eventually forgets the breach entirely
        snap = mon.snapshot(now=t0 + 500.0)
        s = snap["slos"]["ttft_p99"]
        assert s["burn_rate"]["slow"] == pytest.approx(0.0)
        assert s["events_in_window"] == 0
    finally:
        slo.reset()


def test_slo_verdicts_and_env_arming(monkeypatch):
    mon = slo.SloMonitor({"ttft_p99": (0.5, 0.99),
                          "itl_p99": (0.05, 0.99),
                          "success": (0.999, 0.999)})
    # ttft: breach / ok / failed-before-first-token / not-applicable
    assert mon._verdict("ttft_p99", _rec(0.9)) is True
    assert mon._verdict("ttft_p99", _rec(0.1)) is False
    assert mon._verdict("ttft_p99", _rec(None, failed=True)) is True
    assert mon._verdict("ttft_p99", _rec(None)) is None
    # itl: per-request p99 vs target; no samples -> skipped
    r = _rec(0.1)
    r.itl_samples_s = [0.01] * 9 + [0.2]   # nearest-rank p99 = the max
    assert mon._verdict("itl_p99", r) is True
    assert mon._verdict("itl_p99", _rec(0.1)) is None
    # success: failure is the only bad
    assert mon._verdict("success", _rec(None, failed=True)) is True
    assert mon._verdict("success", _rec(0.1)) is False
    # env arming parses targets + windows + threshold
    slo.reset()
    try:
        monkeypatch.setenv("PADDLE_TPU_SLO_TTFT_P99_S", "0.25")
        monkeypatch.setenv("PADDLE_TPU_SLO_SUCCESS", "0.995")
        monkeypatch.setenv("PADDLE_TPU_SLO_WINDOWS", "60:600")
        monkeypatch.setenv("PADDLE_TPU_SLO_BURN_ALERT", "6.0")
        mon = slo.maybe_arm_from_env()
        assert mon is not None
        assert mon.targets == {"ttft_p99": (0.25, 0.99),
                               "success": (0.995, 0.995)}
        assert mon.windows_s == (60.0, 600.0)
        assert mon.alert_threshold == 6.0
        assert slo.maybe_arm_from_env() is mon  # idempotent
    finally:
        slo.reset()


# ---------------- HTTP contract ----------------------------------------------
def test_http_traceparent_echo_and_statusz(served):
    model, eng = served
    tid = "ab" * 16
    srv = Server(eng).start()
    try:
        prompt = [int(t) for t in
                  np.random.RandomState(5).randint(1, 128, 6)]
        # client-supplied trace id echoes on header AND body
        code, headers, body = _post(
            srv.url, {"prompt_ids": prompt, "max_new_tokens": 3},
            headers={"traceparent": format_traceparent(tid)})
        assert code == 200
        assert parse_traceparent(headers["traceparent"]) == tid
        res = json.loads(body)
        assert res["trace_id"] == tid and "request_id" in res
        # absent header: a fresh valid id is minted and echoed
        code, headers, body = _post(
            srv.url, {"prompt_ids": prompt, "max_new_tokens": 3})
        assert code == 200
        minted = json.loads(body)["trace_id"]
        assert len(minted) == 32 and int(minted, 16) != 0
        assert parse_traceparent(headers["traceparent"]) == minted
        # streaming: header echo + trace id in the final NDJSON record
        code, headers, body = _post(
            srv.url, {"prompt_ids": prompt, "max_new_tokens": 3,
                      "stream": True},
            headers={"traceparent": format_traceparent(tid)})
        assert code == 200
        assert parse_traceparent(headers["traceparent"]) == tid
        last = json.loads(body.decode().strip().split("\n")[-1])
        assert last["done"] is True and last["trace_id"] == tid
        # error responses carry the id too (satellite a)
        code, headers, body = _post(
            srv.url, {"prompt_ids": "nope"},
            headers={"traceparent": format_traceparent(tid)})
        assert code == 400
        assert json.loads(body)["trace_id"] == tid
        assert parse_traceparent(headers["traceparent"]) == tid
        # /healthz gained the accounting fields (satellite b)
        hz = json.loads(urllib.request.urlopen(
            srv.url + "/healthz", timeout=10).read())
        assert hz["requests_in_flight"] == 0
        assert hz["kv_block_seconds_total"] > 0
        # /statusz: JSON contract + HTML rendering
        sz = json.loads(urllib.request.urlopen(
            srv.url + "/statusz?format=json", timeout=10).read())
        assert sz["requests"]["enabled"] is True
        assert sz["requests"]["completed"] >= 1
        assert "top_in_flight" in sz["requests"] and "slo" in sz
        assert sz["engine"]["requests_in_flight"] == 0
        html = urllib.request.urlopen(
            srv.url + "/statusz", timeout=10).read().decode()
        assert "<h1>/statusz</h1>" in html
        assert "KV block-seconds" in html
    finally:
        srv.close(stop_engine=False)


def test_statusz_on_metrics_exporter():
    from paddle_tpu.observability.metrics import (MetricsExporter,
                                                  MetricsRegistry)
    exp = MetricsExporter(0, MetricsRegistry())
    try:
        base = f"http://127.0.0.1:{exp.port}"
        sz = json.loads(urllib.request.urlopen(
            base + "/statusz?format=json", timeout=10).read())
        assert "slo" in sz and "requests" in sz
        assert "engine" not in sz  # no engine attached to the exporter
        html = urllib.request.urlopen(
            base + "/statusz", timeout=10).read().decode()
        assert "<h1>/statusz</h1>" in html
    finally:
        exp.stop()


def test_rejection_reasons_split():
    """serving_rejections_total splits queue_full vs deadline, and both
    shed paths hand back a trace id (stub engine: no compile cost)."""
    reg = get_registry()
    rej = reg.counter("serving_rejections_total")

    class _StuckHandle:
        def result(self, timeout=None):
            time.sleep(min(timeout or 0.0, 0.2))
            raise TimeoutError("never finishes")

        def wait(self, timeout=None):
            return False

    class _StubEngine:
        def __init__(self, waiting=0):
            self.waiting = waiting

        def start(self):
            return self

        def shutdown(self, drain=True):
            pass

        def stats(self):
            return {"running": 0, "waiting": self.waiting}

        def submit(self, prompt_ids, **kw):
            h = _StuckHandle()
            h.req_id = 7
            h.trace_id = kw.get("trace_id")
            return h

    before_q = rej.value(reason="queue_full")
    before_d = rej.value(reason="deadline")
    srv = Server(_StubEngine(waiting=9), max_queue_depth=2).start()
    try:
        code, headers, body = _post(srv.url, {"prompt_ids": [1]})
        assert code == 503
        assert len(json.loads(body)["trace_id"]) == 32
        assert "traceparent" in headers and "Retry-After" in headers
    finally:
        srv.close()
    srv = Server(_StubEngine(waiting=0), request_timeout=0.1).start()
    try:
        code, headers, body = _post(srv.url, {"prompt_ids": [1]})
        assert code == 504
        b = json.loads(body)
        assert len(b["trace_id"]) == 32 and "request_id" in b
        assert "traceparent" in headers
    finally:
        srv.close()
    assert rej.value(reason="queue_full") == before_q + 1
    assert rej.value(reason="deadline") == before_d + 1


# ---------------- trace merge --requests -------------------------------------
def test_trace_merge_requests_rollup_matches_ledger(served, tmp_path):
    from paddle_tpu.observability import trace
    model, eng = served
    led = eng._ledger
    old_rate = led.sample_rate
    led.sample_rate = 1.0
    trace.disable()
    trace.enable(str(tmp_path), rank=0)
    try:
        prompts = [list(range(1, 7)), list(range(20, 25))]
        tids = [new_trace_id() for _ in prompts]
        handles = [eng.submit(p, max_new_tokens=4, trace_id=t)
                   for p, t in zip(prompts, tids)]
        eng.run_until_idle()
        results = [h.result(timeout=60) for h in handles]
    finally:
        led.sample_rate = old_rate
        trace.disable()
    summary = trace.merge(str(tmp_path), requests=True)
    roll = summary["requests"]
    assert roll["count"] >= 2
    recs = {d["trace_id"]: d for d in led.exemplars()}
    for p, t, h, res in zip(prompts, tids, handles, results):
        q = roll["requests"][t]
        rec = recs[t]
        assert q["req_id"] == h.req_id and q["trace_id"] == t
        assert q["lanes"] and q["spans"] >= 4
        # span-derived prefill work vs the prompt, and the ledger-
        # enriched completion record vs the live ledger (satellite f)
        assert q["prefill_tokens"] == len(p)
        assert q["prefilled_tokens"] + q["cached_tokens"] == len(p)
        assert q["decode_tokens"] == rec["decode_tokens"] == \
            len(res["token_ids"])
        assert q["generated"] == len(res["token_ids"])
        assert q["finish_reason"] == "length"
        assert q["queue_wait_s"] is not None
        assert q["kv_block_seconds"] == rec["kv_block_seconds"]
        assert q["ttft_s"] == pytest.approx(rec["ttft_s"], abs=1e-5)
