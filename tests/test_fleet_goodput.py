"""Fleet telemetry bus + goodput ledger (ISSUE 13).

Covers: the goodput bin invariant (bins sum to wall), restart/rollback
accounting, the heartbeat bus with live straggler detection, aggregator
resilience (relaunch lane replacement, stale ranks, garbage records,
dead stores), the /fleetz + /healthz endpoints, the postmortem
appendix, and live-vs-offline (``trace merge --goodput``) parity.
"""
import json
import os
import time
import urllib.request

import pytest

from paddle_tpu.observability import fleet, flight_recorder, goodput, trace
from paddle_tpu.observability.fleet import (FleetAggregator,
                                            HeartbeatPublisher, _hb_key)
from paddle_tpu.observability.goodput import BINS, GoodputLedger
from paddle_tpu.observability.metrics import MetricsExporter, MetricsRegistry
from paddle_tpu.observability.step_timer import StepTimer


class FakeStore:
    """Dict-backed stand-in for the job TCPStore (set/get only)."""

    def __init__(self):
        self.d = {}

    def set(self, key, value):
        self.d[key] = value

    def get(self, key):
        return self.d.get(key)


class DeadStore:
    def set(self, key, value):
        raise ConnectionError("store down")

    def get(self, key):
        raise ConnectionError("store down")


@pytest.fixture(autouse=True)
def _clean_module_state():
    goodput._drain_pending_compile()
    goodput.reset_ledger()
    yield
    fleet.disable()
    goodput.reset_ledger()
    goodput._drain_pending_compile()


def _stats(step_time=0.2, data=0.0, exposed=0.0):
    return {"step_time_s": step_time, "data_time_s": data,
            "exposed_collective_time_s": exposed}


# ---------------- goodput ledger ---------------------------------------------
class TestGoodputLedger:
    def test_bins_sum_to_wall_and_fraction(self):
        led = GoodputLedger(registry=MetricsRegistry())
        led._start_mono -= 1.0  # pretend 1s of real wall has passed
        goodput.record_compile(0.03)
        out = led.on_step(_stats(step_time=0.2, data=0.05, exposed=0.02))
        assert out["compile_s"] == pytest.approx(0.03)
        snap = led.snapshot()
        assert set(snap["bins"]) == set(BINS)
        assert sum(snap["bins"].values()) == pytest.approx(
            snap["wall_s"], rel=1e-4)
        assert snap["bins"]["data_stall"] == pytest.approx(0.05)
        assert snap["bins"]["exposed_collective"] == pytest.approx(0.02)
        assert snap["bins"]["compile"] == pytest.approx(0.03)
        assert snap["bins"]["productive"] == pytest.approx(0.10)
        assert 0.0 < snap["job_goodput_fraction"] <= 1.0

    def test_overhead_capped_by_step_wall(self):
        # an async checkpoint blocking longer than the step cannot push
        # productive below zero
        led = GoodputLedger(registry=MetricsRegistry())
        led._start_mono -= 1.0
        led.on_step(_stats(step_time=0.1, data=0.4))
        snap = led.snapshot()
        assert snap["bins"]["productive"] == pytest.approx(0.0)
        assert sum(snap["bins"].values()) == pytest.approx(
            snap["wall_s"], rel=1e-4)

    def test_restart_gap_binned_up_front(self):
        led = GoodputLedger(registry=MetricsRegistry(),
                            down_at=time.time() - 2.0)
        snap = led.snapshot()
        assert snap["bins"]["restart"] == pytest.approx(2.0, abs=0.25)
        # the accounted span covers the down-time, not just ledger life
        assert snap["wall_s"] >= snap["bins"]["restart"]
        assert sum(snap["bins"].values()) == pytest.approx(
            snap["wall_s"], rel=1e-4)

    def test_down_at_env_stamp(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_GOODPUT_DOWN_AT",
                           repr(time.time() - 1.5))
        led = GoodputLedger(registry=MetricsRegistry())
        assert led.snapshot()["bins"]["restart"] == pytest.approx(
            1.5, abs=0.25)

    def test_rollback_reclassifies_productive(self):
        led = GoodputLedger(registry=MetricsRegistry())
        led._start_mono -= 1.0
        for _ in range(3):
            led.on_step(_stats(step_time=0.2))
        before = led.snapshot()["bins"]
        moved = led.discard_recent_steps(2)
        assert moved == pytest.approx(0.4)
        snap = led.snapshot()
        after = snap["bins"]
        assert after["rollback_discarded"] == pytest.approx(0.4)
        assert after["productive"] == pytest.approx(
            before["productive"] - 0.4)
        assert sum(after.values()) == pytest.approx(
            snap["wall_s"], rel=1e-3)

    def test_snapshot_file_written_atomically(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_GOODPUT_DIR", str(tmp_path))
        led = GoodputLedger(registry=MetricsRegistry())
        led.on_step(_stats())
        path = tmp_path / f"goodput_rank0_{os.getpid()}.json"
        doc = json.loads(path.read_text())
        assert doc["steps"] == 1 and set(doc["bins"]) == set(BINS)
        assert not list(tmp_path.glob("*.tmp"))


# ---------------- heartbeat bus + aggregator ---------------------------------
class TestFleetBus:
    def test_straggler_flagged_live_and_recovers(self):
        reg = MetricsRegistry()
        store = FakeStore()
        pub0 = HeartbeatPublisher(store=store, rank=0, registry=reg)
        pub1 = HeartbeatPublisher(store=store, rank=1, registry=reg)
        agg = FleetAggregator(store=store, world=2, stale_s=60,
                              k=1.5, m=2, registry=reg)
        for step in range(1, 4):
            pub0.publish(step, _stats(step_time=0.1))
            pub1.publish(step, _stats(step_time=0.5))
            roll = agg.poll_once()
        assert agg.stragglers == {1}
        assert roll["stragglers"] == [1]
        assert roll["ranks"]["1"]["straggler"] is True
        assert roll["ranks"]["0"]["straggler"] is False
        assert roll["ranks"]["0"]["status"] == "live"
        assert reg.get("fleet_straggler").value(rank=1) == 1
        # recovery: back under k*median clears the flag
        for step in range(4, 6):
            pub0.publish(step, _stats(step_time=0.1))
            pub1.publish(step, _stats(step_time=0.11))
            agg.poll_once()
        assert agg.stragglers == set()
        assert reg.get("fleet_straggler").value(rank=1) == 0

    def test_stale_heartbeat_does_not_advance_streak(self):
        # the same slow record polled repeatedly must not count as M
        # consecutive slow steps
        reg = MetricsRegistry()
        store = FakeStore()
        pub0 = HeartbeatPublisher(store=store, rank=0, registry=reg)
        pub1 = HeartbeatPublisher(store=store, rank=1, registry=reg)
        agg = FleetAggregator(store=store, world=2, stale_s=60,
                              k=1.5, m=3, registry=reg)
        pub0.publish(1, _stats(step_time=0.1))
        pub1.publish(1, _stats(step_time=0.5))
        for _ in range(5):
            agg.poll_once()
        assert agg.stragglers == set()

    def test_relaunched_rank_replaces_lane(self):
        reg = MetricsRegistry()
        store = FakeStore()
        agg = FleetAggregator(store=store, world=2, stale_s=60,
                              registry=reg)
        now = time.time()
        store.set(_hb_key(1), json.dumps(
            {"rank": 1, "pid": 111, "step": 5, "t": now,
             "step_time_s": 0.1}))
        agg.poll_once()
        # relaunch: same rank, new pid → the lane is REPLACED
        store.set(_hb_key(1), json.dumps(
            {"rank": 1, "pid": 222, "step": 1, "t": now + 1,
             "step_time_s": 0.1}))
        roll = agg.poll_once()
        assert list(roll["ranks"]) == ["1"]
        assert roll["ranks"]["1"]["pid"] == 222

    def test_stale_rank_goes_missing_without_crash(self):
        reg = MetricsRegistry()
        store = FakeStore()
        agg = FleetAggregator(store=store, world=2, stale_s=15,
                              registry=reg)
        now = time.time()
        store.set(_hb_key(0), json.dumps(
            {"rank": 0, "pid": 1, "step": 9, "t": now,
             "step_time_s": 0.1}))
        store.set(_hb_key(1), json.dumps(
            {"rank": 1, "pid": 2, "step": 3, "t": now - 100,
             "step_time_s": 0.1}))
        roll = agg.poll_once(now=now)
        assert roll["ranks"]["0"]["status"] == "live"
        assert roll["ranks"]["1"]["status"] == "missing"
        # the last known record is kept for the postmortem
        assert roll["ranks"]["1"]["step"] == 3
        assert reg.get("fleet_ranks_live").value() == 1
        assert reg.get("fleet_ranks_missing").value() == 1

    def test_garbage_record_keeps_old_lane(self):
        store = FakeStore()
        agg = FleetAggregator(store=store, world=1, stale_s=60,
                              registry=MetricsRegistry())
        store.set(_hb_key(0), json.dumps(
            {"rank": 0, "pid": 1, "step": 2, "t": time.time(),
             "step_time_s": 0.1}))
        agg.poll_once()
        store.set(_hb_key(0), "{torn")
        roll = agg.poll_once()
        assert roll["ranks"]["0"]["step"] == 2

    def test_dead_store_degrades_quietly(self):
        agg = FleetAggregator(store=DeadStore(), world=2,
                              registry=MetricsRegistry())
        roll = agg.poll_once()  # must not raise
        assert roll["ranks"] == {}
        pub = HeartbeatPublisher(store=DeadStore(), rank=0,
                                 registry=MetricsRegistry())
        with pytest.warns(RuntimeWarning, match="heartbeat publish"):
            pub.publish(1, _stats())
        pub.publish(2, _stats())  # silent after the first warning
        assert len(pub.recent) == 2  # local postmortem copies survive

    def test_heartbeat_carries_goodput_and_identity(self):
        goodput.get_ledger().on_step(_stats(step_time=0.2, data=0.05))
        store = FakeStore()
        pub = HeartbeatPublisher(store=store, rank=0,
                                 registry=MetricsRegistry())
        pub.publish(7, _stats(step_time=0.2, data=0.05))
        rec = json.loads(store.get(_hb_key(0)))
        assert rec["rank"] == 0 and rec["pid"] == os.getpid()
        assert rec["step"] == 7
        assert rec["step_time_s"] == pytest.approx(0.2)
        assert rec["goodput"]["bins"]["data_stall"] == pytest.approx(0.05)
        assert 0.0 <= rec["goodput"]["fraction"] <= 1.0


# ---------------- endpoints --------------------------------------------------
class TestEndpoints:
    def test_exporter_healthz_and_fleetz(self):
        store = FakeStore()
        fleet.enable(store=store, rank=0, world=2, start_aggregator=True)
        fleet.note_step()
        fleet.publish_step(3, _stats(step_time=0.1))
        reg = MetricsRegistry()
        exp = MetricsExporter(0, reg)
        try:
            base = f"http://127.0.0.1:{exp.port}"
            hz = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=10).read())
            assert hz["status"] == "ok"
            assert hz["rank"] == 0 and hz["job_id"] == "local"
            assert hz["last_step_age_seconds"] >= 0.0
            fz = json.loads(urllib.request.urlopen(
                base + "/fleetz", timeout=10).read())
            assert fz["aggregator"] is True and fz["world"] == 2
            assert fz["ranks"]["0"]["step"] == 3
            assert "local_goodput" in fz
        finally:
            exp.stop()

    def test_fleetz_local_fallback_without_aggregator(self):
        fleet.enable(store=FakeStore(), rank=1, start_aggregator=False)
        fleet.publish_step(5, _stats())
        fz = fleet.fleetz_snapshot()
        assert fz["aggregator"] is False
        assert fz["ranks"]["1"]["step"] == 5
        assert fz["stragglers"] == []

    def test_live_straggler_acceptance(self):
        """ISSUE 13 acceptance: two simulated ranks, one slowed — the
        live /fleetz document names the straggler while the 'job' runs,
        with no trace merge involved."""
        store = FakeStore()
        fleet.enable(store=store, rank=0, world=2, start_aggregator=False)
        agg = FleetAggregator(store=store, world=2, stale_s=60,
                              k=1.5, m=2, registry=MetricsRegistry())
        fleet._aggregator = agg  # un-started: polled by fleetz_snapshot
        slow = HeartbeatPublisher(store=store, rank=1,
                                  registry=MetricsRegistry())
        for step in range(1, 4):
            fleet.publish_step(step, _stats(step_time=0.1))
            slow.publish(step, _stats(step_time=0.4))
            fleet.fleetz_snapshot()
        fz = fleet.fleetz_snapshot()
        assert fz["stragglers"] == [1]
        assert fz["ranks"]["1"]["straggler"] is True

    def test_maybe_enable_from_env_gating(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLEET", "0")
        monkeypatch.setenv("PADDLE_MASTER", "127.0.0.1:1")
        assert fleet.maybe_enable_from_env() is None
        monkeypatch.delenv("PADDLE_MASTER")
        monkeypatch.setenv("PADDLE_TPU_FLEET", "")
        assert fleet.maybe_enable_from_env() is None
        assert fleet._publisher is None


# ---------------- postmortem appendix ----------------------------------------
class TestPostmortemAppendix:
    def test_dump_carries_ledger_and_heartbeats(self, tmp_path):
        goodput.get_ledger().on_step(_stats(step_time=0.2))
        fleet.enable(store=FakeStore(), rank=0, start_aggregator=False)
        fleet.publish_step(1, _stats(step_time=0.2))
        appendix = flight_recorder._ledger_appendix()
        assert set(appendix["goodput"]["bins"]) == set(BINS)
        assert appendix["heartbeats"][-1]["step"] == 1
        fr = flight_recorder.FlightRecorder(capacity=16)
        try:
            t = time.time_ns()
            fr.record(flight_recorder.KIND_STEP, "train_step", t, t)
            path = fr.dump(str(tmp_path / "pm.json"), reason="test")
        finally:
            fr.close()  # release the process-wide native ring
        doc = json.loads(open(path).read())
        assert doc["goodput"]["steps"] == 1
        assert doc["heartbeats"][0]["rank"] == 0

    def test_appendix_empty_without_ledger(self):
        assert flight_recorder._ledger_appendix() == {}


# ---------------- live vs offline parity -------------------------------------
class TestOfflineParity:
    def test_trace_merge_goodput_matches_live_split(self, tmp_path):
        """Satellite 1: ``trace merge --goodput`` replays the live
        ledger's per-step split from the step-span args."""
        trace.enable(str(tmp_path), rank=0)
        try:
            timer = StepTimer(registry=MetricsRegistry(), peak=0)
            goodput.record_compile(0.02)
            for _ in range(4):
                timer.begin_step(data_time=0.01)
                time.sleep(0.015)
                timer.end_step(samples=4)
        finally:
            trace.disable()
        live = goodput.snapshot()
        summary = trace.merge(str(tmp_path), goodput=True)
        off = summary["goodput"]
        assert off["steps"] == 4
        for b in ("productive", "data_stall", "compile"):
            assert off["bins"][b] == pytest.approx(
                live["bins"][b], rel=0.05, abs=5e-3), b
        assert sum(off["bins"].values()) == pytest.approx(
            off["wall_s"], rel=1e-4)
        assert 0.0 < off["job_goodput_fraction"] <= 1.0

    def test_relaunch_gap_is_restart_offline(self, tmp_path):
        """Two lanes of the same rank (a relaunch) → the gap between
        them is restart badput in the offline rollup."""
        import paddle_tpu.observability.trace as tr
        anchor = (time.perf_counter_ns(), time.time_ns())
        for label, t0, t1 in (("a", 0, int(0.5e9)),
                              ("b", int(2.5e9), int(3.0e9))):
            lines = [
                {"type": "header", "version": 1, "rank": 0,
                 "pid": 1 if label == "a" else 2,
                 "clock": {"perf_ns": anchor[0], "unix_ns": anchor[1]}},
                {"type": "span", "cat": "step", "name": "train_step",
                 "ts": anchor[0] + t0, "dur": t1 - t0, "tid": 0,
                 "args": {"step": 1, "step_time_s": (t1 - t0) / 1e9}},
            ]
            with open(tmp_path / f"trace_rank0_{label}.jsonl", "w") as f:
                f.write("\n".join(json.dumps(ln) for ln in lines) + "\n")
        off = tr.merge(str(tmp_path), goodput=True)["goodput"]
        assert off["bins"]["restart"] == pytest.approx(2.0, rel=0.01)
        assert off["bins"]["productive"] == pytest.approx(1.0, rel=0.01)
