"""paddle.sparse parity tests — numpy-oracle for every op family, plus
gradient flow through values (the reference's sparse tests live under
python/paddle/fluid/tests/unittests/test_sparse_*.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.sparse as sparse


def _rand_coo(shape=(4, 5), nnz=6, seed=0, stop_gradient=True):
    rng = np.random.RandomState(seed)
    # unique coordinates
    flat = rng.choice(int(np.prod(shape)), size=nnz, replace=False)
    idx = np.stack(np.unravel_index(flat, shape)).astype(np.int64)
    vals = rng.randn(nnz).astype(np.float32)
    dense = np.zeros(shape, np.float32)
    dense[tuple(idx)] = vals
    sp = sparse.sparse_coo_tensor(idx, vals, shape,
                                  stop_gradient=stop_gradient)
    return sp, dense


class TestCreation:
    def test_coo_roundtrip(self):
        sp, dense = _rand_coo()
        np.testing.assert_allclose(sp.numpy(), dense)
        assert sp.nnz() == 6
        assert sp.shape == [4, 5]

    def test_coo_duplicate_coords_sum(self):
        idx = [[0, 0, 1], [1, 1, 2]]
        sp = sparse.sparse_coo_tensor(idx, [1.0, 2.0, 3.0], (2, 3))
        assert sp.numpy()[0, 1] == 3.0  # to_dense sums duplicates
        co = sp.coalesce()
        assert co.nnz() == 2
        np.testing.assert_allclose(co.numpy(), sp.numpy())

    def test_dense_to_sparse_and_back(self):
        x = pt.to_tensor(np.array([[0, 1.0, 0], [2.0, 0, 3.0]], np.float32))
        sp = x.to_sparse_coo()
        assert sp.nnz() == 3
        np.testing.assert_allclose(sp.numpy(), x.numpy())

    def test_csr_roundtrip(self):
        sp, dense = _rand_coo()
        csr = sp.to_sparse_csr()
        np.testing.assert_allclose(csr.numpy(), dense)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(back.numpy(), dense)

    def test_sparse_csr_tensor_ctor(self):
        # [[1, 0, 2], [0, 3, 0]]
        csr = sparse.sparse_csr_tensor([0, 2, 3], [0, 2, 1],
                                       [1.0, 2.0, 3.0], (2, 3))
        np.testing.assert_allclose(
            csr.numpy(), [[1, 0, 2], [0, 3, 0]])


class TestUnary:
    @pytest.mark.parametrize("name,np_fn", [
        ("sin", np.sin), ("tanh", np.tanh), ("square", np.square),
        ("abs", np.abs), ("expm1", np.expm1), ("neg", np.negative),
    ])
    def test_values_oracle(self, name, np_fn):
        sp, dense = _rand_coo(seed=3)
        out = getattr(sparse, name)(sp)
        mask = dense != 0
        expect = np.where(mask, np_fn(dense), 0)
        np.testing.assert_allclose(out.numpy(), expect, rtol=1e-6)

    def test_pow_and_cast(self):
        sp, dense = _rand_coo(seed=4)
        np.testing.assert_allclose(sparse.pow(sp, 2).numpy(),
                                   np.where(dense != 0, dense ** 2, 0),
                                   rtol=1e-6)
        assert sparse.cast(sp, value_dtype="float16").dtype == pt.float16

    def test_transpose(self):
        sp, dense = _rand_coo(seed=5)
        np.testing.assert_allclose(
            sparse.transpose(sp, [1, 0]).numpy(), dense.T)

    def test_reshape(self):
        sp, dense = _rand_coo(shape=(4, 6), seed=6)
        np.testing.assert_allclose(
            sparse.reshape(sp, [2, 12]).numpy(), dense.reshape(2, 12))
        np.testing.assert_allclose(
            sparse.reshape(sp, [8, -1]).numpy(), dense.reshape(8, 3))


class TestBinary:
    def test_add_subtract_union_pattern(self):
        a, da = _rand_coo(seed=7)
        b, db = _rand_coo(seed=8)
        np.testing.assert_allclose(sparse.add(a, b).numpy(), da + db,
                                   rtol=1e-6)
        np.testing.assert_allclose(sparse.subtract(a, b).numpy(), da - db,
                                   rtol=1e-6)

    def test_multiply_same_pattern(self):
        a, da = _rand_coo(seed=9)
        b = sparse.sparse_coo_tensor(np.asarray(a.indices().data),
                                     np.arange(1.0, 7.0, dtype=np.float32),
                                     a.shape)
        out = sparse.multiply(a, b)
        np.testing.assert_allclose(out.numpy(), da * b.numpy(), rtol=1e-6)

    def test_matmul_oracle(self):
        sp, dense = _rand_coo(shape=(4, 5), seed=10)
        y = np.random.RandomState(1).randn(5, 3).astype(np.float32)
        out = sparse.matmul(sp, pt.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5,
                                   atol=1e-6)

    def test_csr_matmul(self):
        sp, dense = _rand_coo(shape=(4, 5), seed=11)
        y = np.random.RandomState(2).randn(5, 3).astype(np.float32)
        out = sparse.matmul(sp.to_sparse_csr(), pt.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5,
                                   atol=1e-6)

    def test_mv(self):
        sp, dense = _rand_coo(shape=(4, 5), seed=12)
        v = np.random.RandomState(3).randn(5).astype(np.float32)
        np.testing.assert_allclose(sparse.mv(sp, pt.to_tensor(v)).numpy(),
                                   dense @ v, rtol=1e-5, atol=1e-6)

    def test_masked_matmul(self):
        rng = np.random.RandomState(4)
        a = rng.randn(4, 6).astype(np.float32)
        b = rng.randn(6, 5).astype(np.float32)
        mask, dmask = _rand_coo(shape=(4, 5), seed=13)
        out = sparse.masked_matmul(pt.to_tensor(a), pt.to_tensor(b), mask)
        expect = np.where(dmask != 0, a @ b, 0)
        np.testing.assert_allclose(out.numpy(), expect, rtol=1e-5,
                                   atol=1e-6)

    def test_addmm(self):
        rng = np.random.RandomState(5)
        inp = rng.randn(4, 3).astype(np.float32)
        sp, dense = _rand_coo(shape=(4, 5), seed=14)
        y = rng.randn(5, 3).astype(np.float32)
        out = sparse.addmm(pt.to_tensor(inp), sp, pt.to_tensor(y),
                           beta=0.5, alpha=2.0)
        np.testing.assert_allclose(out.numpy(), 0.5 * inp + 2.0 * dense @ y,
                                   rtol=1e-5, atol=1e-6)


class TestAutograd:
    def test_matmul_grad_flows_to_values_and_dense(self):
        sp, dense = _rand_coo(shape=(3, 4), nnz=5, seed=15,
                              stop_gradient=False)
        y = pt.to_tensor(
            np.random.RandomState(6).randn(4, 2).astype(np.float32),
            stop_gradient=False)
        out = sparse.matmul(sp, y)
        out.sum().backward()
        assert sp.grad is not None and sp.grad.shape == [5]
        # d(sum)/dy[c, j] = sum_r dense[r, c]
        np.testing.assert_allclose(
            y.grad.numpy(), np.tile(dense.sum(0)[:, None], (1, 2)),
            rtol=1e-5, atol=1e-6)

    def test_to_dense_grad(self):
        sp, _ = _rand_coo(shape=(3, 3), nnz=4, seed=16,
                          stop_gradient=False)
        (sp.to_dense() * 2.0).sum().backward()
        np.testing.assert_allclose(sp.grad.numpy(), np.full(4, 2.0))

    def test_dense_to_sparse_grad(self):
        x = pt.to_tensor(np.array([[0, 1.0], [2.0, 0]], np.float32),
                         stop_gradient=False)
        sp = x.to_sparse_coo()
        sp.values().sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   [[0, 1], [1, 0]])


class TestNN:
    def test_relu_softmax(self):
        sp, dense = _rand_coo(seed=17)
        np.testing.assert_allclose(
            sparse.nn.functional.relu(sp).numpy(),
            np.where(dense > 0, dense, 0), rtol=1e-6)
        csr = sp.to_sparse_csr()
        sm = sparse.nn.functional.softmax(csr)
        out = sm.numpy()
        # each row's nonzero entries sum to 1
        rows = np.unique(np.asarray(sp.coalesce().indices().data)[0])
        for r in rows:
            np.testing.assert_allclose(out[r][out[r] != 0].sum(), 1.0,
                                       rtol=1e-5)

    def test_batchnorm(self):
        rng = np.random.RandomState(18)
        idx = np.stack([np.arange(8) % 4, np.arange(8) % 3]).astype(np.int64)
        vals = rng.randn(8, 5).astype(np.float32)
        sp = sparse.sparse_coo_tensor(idx, vals, (4, 3, 5))
        bn = sparse.nn.BatchNorm(5)
        bn.train()
        out = bn(sp)
        got = np.asarray(out.values().data)
        np.testing.assert_allclose(got.mean(axis=0), 0, atol=1e-5)

    def test_subm_conv3d_keeps_pattern(self):
        pt.seed(0)
        rng = np.random.RandomState(19)
        # one sample, 4x4x4 grid, 2 channels, 5 active sites
        flat = rng.choice(64, size=5, replace=False)
        d, h, w = np.unravel_index(flat, (4, 4, 4))
        idx = np.stack([np.zeros(5, np.int64), d, h, w])
        vals = rng.randn(5, 2).astype(np.float32)
        sp = sparse.sparse_coo_tensor(idx, vals, (1, 4, 4, 4, 2))
        conv = sparse.nn.SubmConv3D(2, 3, kernel_size=3, padding=1)
        out = conv(sp)
        assert out.shape == [1, 4, 4, 4, 3]
        assert out.nnz() == 5  # submanifold: same active sites
        np.testing.assert_array_equal(
            np.asarray(out.indices().data), idx)

    def test_conv3d_and_maxpool(self):
        pt.seed(0)
        sp, _ = _rand_coo(shape=(1, 4, 4, 4), nnz=6, seed=20)
        sp5 = sparse.sparse_coo_tensor(
            np.concatenate([np.asarray(sp.indices().data)], axis=0),
            np.asarray(sp.values().data)[:, None], (1, 4, 4, 4, 1))
        conv = sparse.nn.Conv3D(1, 2, kernel_size=2)
        out = conv(sp5)
        assert out.shape == [1, 3, 3, 3, 2]
        pooled = sparse.nn.MaxPool3D(kernel_size=2)(sp5)
        assert pooled.shape == [1, 2, 2, 2, 1]
