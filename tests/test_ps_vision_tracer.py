"""PS tables + client/server over rpc, vision model zoo additions,
native host tracer."""
import numpy as np
import pytest

import paddle_tpu as pt


# ------------------------------------------------------------------- PS
def test_sparse_table_lazy_init_and_sgd():
    from paddle_tpu.distributed.ps import SparseTable
    t = SparseTable(dim=4, lr=0.1, seed=0)
    rows = t.pull([5, 9, 5])
    assert rows.shape == (3, 4)
    np.testing.assert_array_equal(rows[0], rows[2])  # same id, same row
    before = rows[0].copy()
    g = np.ones((3, 4), np.float32)
    t.push([5, 9, 5], g)
    after = t.pull([5])[0]
    # id 5 appears twice in the push: two SGD steps of lr*1
    np.testing.assert_allclose(after, before - 0.2, rtol=1e-6)
    assert t.size() == 2


def test_sparse_table_adagrad():
    from paddle_tpu.distributed.ps import SparseTable
    t = SparseTable(dim=2, lr=1.0, optimizer="adagrad",
                    initializer="zeros")
    t.push([1], np.array([[3.0, 4.0]], np.float32))
    row = t.pull([1])[0]
    # adagrad step: -lr * g / sqrt(g^2) = -sign(g)
    np.testing.assert_allclose(row, [-1.0, -1.0], rtol=1e-4)


def test_dense_table():
    from paddle_tpu.distributed.ps import DenseTable
    t = DenseTable((2, 3), lr=0.5)
    t.push(np.ones((2, 3), np.float32))
    np.testing.assert_allclose(t.pull(), -0.5 * np.ones((2, 3)))


def test_ps_client_server_over_rpc():
    """Single-process loopback: this rank is both server and worker
    (rpc serves from a daemon thread)."""
    import socket
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed import ps
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rpc.init_rpc("ps0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        server = ps.init_server()
        server.add_sparse_table("emb", dim=8, lr=0.1, seed=1)
        server.add_dense_table("w", (4,), lr=0.1)
        ps.run_server()
        client = ps.init_worker("ps0")
        rows = client.pull_sparse("emb", [3, 7])
        assert rows.shape == (2, 8)
        client.push_sparse_grad("emb", [3], np.ones((1, 8), np.float32))
        rows2 = client.pull_sparse("emb", [3])
        np.testing.assert_allclose(rows2[0], rows[0] - 0.1, rtol=1e-5)
        client.push_dense_grad("w", np.ones(4, np.float32))
        np.testing.assert_allclose(client.pull_dense("w"), -0.1 *
                                   np.ones(4), rtol=1e-6)
    finally:
        rpc.shutdown()


# ---------------------------------------------------------------- vision
def test_vgg16_forward():
    from paddle_tpu.vision.models import vgg16
    m = vgg16(num_classes=10)
    m.eval()
    x = pt.to_tensor(np.random.RandomState(0).randn(1, 3, 64, 64)
                     .astype(np.float32))
    out = m(x)
    assert list(out.shape) == [1, 10]


def test_mobilenet_v2_forward_backward():
    from paddle_tpu.vision.models import mobilenet_v2
    m = mobilenet_v2(scale=0.25, num_classes=4)
    x = pt.to_tensor(np.random.RandomState(1).randn(2, 3, 32, 32)
                     .astype(np.float32))
    out = m(x)
    assert list(out.shape) == [2, 4]
    pt.ops.sum(out).backward()
    grads = [p.grad for _, p in m.named_parameters() if p.grad is not None]
    assert len(grads) > 20


def test_mobilenet_v1_forward():
    from paddle_tpu.vision.models import mobilenet_v1
    m = mobilenet_v1(scale=0.25, num_classes=3)
    m.eval()
    x = pt.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
    assert list(m(x).shape) == [1, 3]


def test_pretrained_raises():
    from paddle_tpu.vision.models import vgg11, mobilenet_v2
    with pytest.raises(NotImplementedError):
        vgg11(pretrained=True)
    with pytest.raises(NotImplementedError):
        mobilenet_v2(pretrained=True)


# ---------------------------------------------------------- native tracer
def test_native_host_tracer_drains_events():
    from paddle_tpu.profiler import Profiler, _NativeTracer
    p = Profiler().start()
    x = pt.to_tensor(np.ones((4, 4), np.float32))
    for _ in range(3):
        pt.ops.sum(pt.ops.multiply(x, x))
    p.stop()
    names = [e.name for e in p.events]
    assert "multiply" in names and "sum" in names
    assert len(names) >= 6
    # the native ring must actually have been the recorder (compiled ok)
    assert _NativeTracer._lib is not None
    # spans carry sane timestamps
    for e in p.events:
        assert e.end >= e.start > 0


def test_native_tracer_capacity_drop():
    from paddle_tpu.profiler import _NativeTracer
    lib = _NativeTracer.load()
    assert lib is not None
    assert lib.ht_start(4) == 0
    for i in range(10):
        lib.ht_record(f"ev{i}".encode(), i, i + 1, 0)
    assert lib.ht_count() == 10  # counts all
    out = []
    _NativeTracer.drain(out)
    assert len(out) == 4  # ring kept the first `capacity`
