"""Vision tests: transforms vs numpy/torch oracles, ResNet/LeNet forward +
training on FakeData."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.io as io
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.vision import transforms as T
from paddle_tpu.vision.datasets import FakeData


class TestTransforms:
    def test_to_tensor(self):
        img = (np.random.RandomState(0).rand(8, 6, 3) * 255).astype(np.uint8)
        out = T.ToTensor()(img)
        assert out.shape == (3, 8, 6)
        assert out.dtype == np.float32 and out.max() <= 1.0

    def test_normalize(self):
        x = np.ones((3, 4, 4), np.float32)
        out = T.Normalize(mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5])(x)
        np.testing.assert_allclose(out, np.ones_like(x))

    def test_resize_matches_torch(self):
        import torch
        import torch.nn.functional as TF
        img = np.random.RandomState(0).rand(10, 8, 3).astype(np.float32)
        out = T.Resize((5, 4))(img)
        ref = TF.interpolate(torch.tensor(img).permute(2, 0, 1)[None],
                             size=(5, 4), mode="bilinear",
                             align_corners=False)[0].permute(1, 2, 0).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_crops(self):
        img = np.arange(100, dtype=np.float32).reshape(10, 10, 1)
        c = T.CenterCrop(4)(img)
        assert c.shape == (4, 4, 1)
        np.testing.assert_allclose(c[0, 0, 0], 33.0)
        r = T.RandomCrop(6)(img)
        assert r.shape == (6, 6, 1)

    def test_flip_and_compose(self):
        img = np.arange(12, dtype=np.float32).reshape(2, 6, 1)
        out = T.RandomHorizontalFlip(prob=1.0)(img)
        np.testing.assert_allclose(out[:, ::-1], img)
        pipe = T.Compose([T.RandomHorizontalFlip(prob=0.0), T.Transpose()])
        assert pipe(img).shape == (1, 2, 6)


class TestModels:
    def test_resnet18_forward(self):
        pt.seed(0)
        m = pt.vision.resnet18(num_classes=10)
        m.eval()
        x = pt.to_tensor(np.random.RandomState(0).randn(
            2, 3, 32, 32).astype(np.float32))
        out = m(x)
        assert out.shape == [2, 10]
        assert np.isfinite(out.numpy()).all()

    def test_resnet50_structure(self):
        m = pt.vision.resnet50(num_classes=7)
        # bottleneck expansion: final fc in_features 2048
        assert m.fc.weight.shape == [2048, 7]
        n_params = sum(int(np.prod(p.shape)) for p in m.parameters())
        assert 23_000_000 < n_params < 27_000_000  # ~25.6M like the ref

    def test_lenet_trains_on_fakedata(self):
        pt.seed(1)
        ds = FakeData(num_samples=64, image_shape=(1, 28, 28),
                      num_classes=4)
        # learnable rule: class = argmax of 4 fixed projections
        rng = np.random.RandomState(0)
        W = rng.randn(784, 4).astype(np.float32)
        items = [(x, np.int64((x.reshape(-1) @ W).argmax()))
                 for x, _ in [ds[i] for i in range(64)]]
        X = np.stack([x for x, _ in items])
        Y = np.stack([y for _, y in items])
        dl = io.DataLoader(io.TensorDataset([X, Y]), batch_size=16,
                           shuffle=True)
        m = pt.vision.LeNet(num_classes=4)
        o = opt.AdamW(learning_rate=2e-3, parameters=m.parameters())
        ce = nn.CrossEntropyLoss()
        losses = []
        for epoch in range(15):
            for xb, yb in dl:
                loss = ce(m(pt.to_tensor(xb)), pt.to_tensor(yb))
                loss.backward()
                o.step()
                o.clear_grad()
                losses.append(float(loss.numpy()))
        assert np.mean(losses[-4:]) < np.mean(losses[:4]) * 0.7


class TestDatasets:
    def test_fakedata_deterministic(self):
        ds = FakeData(num_samples=10, image_shape=(3, 8, 8), seed=3)
        x1, y1 = ds[5]
        x2, y2 = ds[5]
        np.testing.assert_allclose(x1, x2)
        assert y1 == 5 % 10

    def test_fakedata_with_transform(self):
        ds = FakeData(num_samples=4, image_shape=(8, 8, 3),
                      transform=T.Compose([T.Transpose()]))
        x, _ = ds[0]
        assert x.shape == (3, 8, 8)

    def test_mnist_missing_raises_clearly(self, tmp_path):
        from paddle_tpu.vision.datasets import MNIST
        with pytest.raises(FileNotFoundError, match="no network egress"):
            MNIST(root=str(tmp_path))

    def test_mnist_reads_idx_files(self, tmp_path):
        import struct
        imgs = (tmp_path / "train-images-idx3-ubyte")
        lbls = (tmp_path / "train-labels-idx1-ubyte")
        rng = np.random.RandomState(0)
        data = rng.randint(0, 255, (5, 28, 28), dtype=np.uint8)
        labels = np.arange(5, dtype=np.uint8)
        imgs.write_bytes(struct.pack(">IIII", 2051, 5, 28, 28) +
                         data.tobytes())
        lbls.write_bytes(struct.pack(">II", 2049, 5) + labels.tobytes())
        from paddle_tpu.vision.datasets import MNIST
        ds = MNIST(root=str(tmp_path))
        assert len(ds) == 5
        img, y = ds[3]
        np.testing.assert_array_equal(img, data[3])
        assert y == 3


def test_fashion_mnist_uses_distinct_cache_dir():
    """FashionMNIST() must never silently load MNIST digits from the MNIST
    cache — its default root is a separate directory."""
    from paddle_tpu.vision.datasets import MNIST, FashionMNIST
    assert MNIST._cache_name != FashionMNIST._cache_name
    with pytest.raises(FileNotFoundError, match="fashion-mnist"):
        FashionMNIST(root=None, mode="test")
