"""Test config: force an 8-virtual-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4) with the TPU-build
improvement called out there: SPMD code paths are testable single-process on a
virtual host mesh, which the reference (needing 2 real GPUs + NCCL subprocess
spawning) cannot do.

The sandbox may boot python with a TPU-tunnel PJRT plugin pre-registered
(JAX_PLATFORMS=axon) via sitecustomize; unit tests must never touch the real
chip, so we hard-override to the CPU platform and deregister any non-CPU
backend factory before the first backend initialization.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Pallas registers its TPU MLIR lowerings at import; that must happen while
# the tpu platform is still known, i.e. before we deregister backends below
# (kernels themselves run with interpret=True on the CPU mesh).
try:
    from jax.experimental import pallas as _pallas  # noqa: F401
    from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
except Exception:
    pass
try:
    from jax._src import xla_bridge as _xb
    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except Exception:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.default_backend() == "cpu", "tests must run on the CPU platform"
assert jax.device_count() == 8, "tests expect an 8-device virtual mesh"


@pytest.fixture(autouse=True)
def _seed_rng():
    import paddle_tpu as pt
    pt.seed(2024)
    np.random.seed(2024)
    # exact f32 matmuls for numeric oracles (TPU runs keep the bf16 MXU default)
    pt.set_flags({"matmul_precision": "highest"})
    yield
