"""paddle.vision.ops — nms/roi_align/roi_pool/box utils (torch CPU as the
oracle where available)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.vision import ops as V


def _t(x):
    return pt.to_tensor(np.asarray(x))


BOXES = np.array([
    [0, 0, 10, 10],
    [1, 1, 11, 11],     # heavy overlap with box 0
    [20, 20, 30, 30],
    [21, 21, 29, 29],   # heavy overlap with box 2
    [50, 50, 60, 60],
], np.float32)
SCORES = np.array([0.9, 0.8, 0.7, 0.95, 0.5], np.float32)


def test_box_area_and_iou():
    areas = np.asarray(V.box_area(_t(BOXES)).data)
    np.testing.assert_allclose(areas, [100, 100, 100, 64, 100], rtol=1e-6)
    iou = np.asarray(V.box_iou(_t(BOXES[:2]), _t(BOXES[:2])).data)
    np.testing.assert_allclose(np.diag(iou), 1.0, rtol=1e-6)
    assert 0.5 < iou[0, 1] < 0.8


def _np_nms(boxes, scores, thresh):
    """Greedy NMS numpy oracle (the textbook algorithm)."""
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if suppressed[j] or j == i:
                continue
            xx1 = max(boxes[i, 0], boxes[j, 0])
            yy1 = max(boxes[i, 1], boxes[j, 1])
            xx2 = min(boxes[i, 2], boxes[j, 2])
            yy2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(0, xx2 - xx1) * max(0, yy2 - yy1)
            a_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            a_j = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            if inter / (a_i + a_j - inter) > thresh:
                suppressed[j] = True
    return np.array(keep)


def test_nms_matches_numpy_oracle():
    got = np.asarray(V.nms(_t(BOXES), 0.5, _t(SCORES)).data)
    want = _np_nms(BOXES, SCORES, 0.5)
    np.testing.assert_array_equal(got, want)

    rng = np.random.RandomState(7)
    for _ in range(3):
        b = rng.rand(30, 2) * 50
        boxes = np.hstack([b, b + rng.rand(30, 2) * 20 + 1]) \
            .astype(np.float32)
        scores = rng.rand(30).astype(np.float32)
        got = np.asarray(V.nms(_t(boxes), 0.4, _t(scores)).data)
        want = _np_nms(boxes, scores, 0.4)
        np.testing.assert_array_equal(got, want)


def test_nms_no_scores_and_topk():
    got = np.asarray(V.nms(_t(BOXES), 0.5, _t(SCORES), top_k=2).data)
    assert len(got) == 2
    assert got[0] == 3  # highest score survives first


def test_nms_categories_do_not_suppress_across():
    cats = np.array([0, 1, 0, 1, 0], np.int64)
    got = set(np.asarray(V.nms(_t(BOXES), 0.5, _t(SCORES),
                               category_idxs=_t(cats),
                               categories=[0, 1]).data).tolist())
    # boxes 0 and 1 overlap but are different categories: both kept
    assert {0, 1} <= got


def _np_roi_align(feat, rois, out, ratio):
    """Straightforward-loop RoIAlign oracle (aligned=True)."""
    C, H, W = feat.shape[1], feat.shape[2], feat.shape[3]
    res = np.zeros((len(rois), C, out, out), np.float32)
    for r, roi in enumerate(rois):
        x1, y1, x2, y2 = roi * 1.0
        x1, y1, x2, y2 = x1 - 0.5, y1 - 0.5, x2 - 0.5, y2 - 0.5
        rw, rh = max(x2 - x1, 1e-3), max(y2 - y1, 1e-3)
        for oy in range(out):
            for ox in range(out):
                acc = np.zeros(C)
                for sy in range(ratio):
                    for sx in range(ratio):
                        yy = y1 + rh * (oy + (sy + 0.5) / ratio) / out
                        xx = x1 + rw * (ox + (sx + 0.5) / ratio) / out
                        y0 = int(np.clip(np.floor(yy), 0, H - 1))
                        x0 = int(np.clip(np.floor(xx), 0, W - 1))
                        y1_ = min(y0 + 1, H - 1)
                        x1_ = min(x0 + 1, W - 1)
                        wy = np.clip(yy, 0, H - 1) - y0
                        wx = np.clip(xx, 0, W - 1) - x0
                        acc += ((1 - wy) * (1 - wx) * feat[0, :, y0, x0]
                                + (1 - wy) * wx * feat[0, :, y0, x1_]
                                + wy * (1 - wx) * feat[0, :, y1_, x0]
                                + wy * wx * feat[0, :, y1_, x1_])
                res[r, :, oy, ox] = acc / (ratio * ratio)
    return res


def test_roi_align_matches_numpy_oracle():
    rng = np.random.RandomState(0)
    feat = rng.randn(1, 3, 16, 16).astype(np.float32)
    rois = np.array([[2.0, 2.0, 10.0, 10.0], [0.0, 0.0, 16.0, 16.0]],
                    np.float32)
    got = np.asarray(V.roi_align(_t(feat), _t(rois),
                                 _t(np.array([2], np.int64)),
                                 output_size=4, sampling_ratio=2).data)
    want = _np_roi_align(feat, rois, 4, 2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_roi_align_gradient_flows():
    feat = _t(np.random.RandomState(1).randn(1, 2, 8, 8)
              .astype(np.float32))
    feat.stop_gradient = False
    rois = _t(np.array([[1.0, 1.0, 6.0, 6.0]], np.float32))
    out = V.roi_align(feat, rois, _t(np.array([1], np.int64)), 2)
    pt.ops.sum(out).backward()
    assert feat.grad is not None
    assert float(np.abs(np.asarray(feat.grad.data)).sum()) > 0


def test_roi_pool_shape():
    feat = _t(np.random.RandomState(2).randn(2, 3, 12, 12)
              .astype(np.float32))
    rois = _t(np.array([[0, 0, 6, 6], [2, 2, 10, 10], [0, 0, 12, 12]],
                       np.float32))
    out = V.roi_pool(feat, rois, _t(np.array([2, 1], np.int64)), (3, 3))
    assert list(out.shape) == [3, 3, 3, 3]


def test_box_coder_roundtrip():
    priors = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], np.float32)
    var = np.full((2, 4), 0.1, np.float32)
    targets = np.array([[1, 1, 9, 9], [6, 4, 16, 18]], np.float32)
    enc = V.box_coder(_t(priors), _t(var), _t(targets),
                      code_type="encode_center_size")  # [N, M, 4]
    dec = np.asarray(V.box_coder(_t(priors), _t(var), enc,
                                 code_type="decode_center_size",
                                 axis=0).data)
    for i in range(2):  # decode against the same prior inverts encode
        np.testing.assert_allclose(dec[i, :], np.tile(targets[i], (2, 1)),
                                   rtol=1e-4, atol=1e-4)


def test_top_level_summary_and_flops():
    import paddle_tpu.nn as nn
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    info = pt.summary(net, (1, 8))
    assert info["total_params"] == 8 * 16 + 16 + 16 * 4 + 4
    f = pt.flops(net, (1, 8))
    # 2 MACs per weight element, batch 1
    assert f >= 2 * (8 * 16 + 16 * 4)


def test_fused_ec_moe_with_gate_uses_it():
    from paddle_tpu.incubate.nn import FusedEcMoe
    pt.seed(5)
    moe = FusedEcMoe(8, 16, num_experts=4)
    x = _t(np.random.RandomState(5).randn(2, 3, 8).astype(np.float32))
    # one-hot gate on expert 0 vs expert 1 must give different outputs
    g0 = np.full((2, 3, 4), -1e9, np.float32); g0[..., 0] = 0
    g1 = np.full((2, 3, 4), -1e9, np.float32); g1[..., 1] = 0
    o0 = np.asarray(moe(x, _t(g0)).data)
    o1 = np.asarray(moe(x, _t(g1)).data)
    assert np.abs(o0 - o1).max() > 1e-4
    # gate gradients flow
    gt = _t(g0); gt.stop_gradient = False
    out = moe(x, gt)
    pt.ops.sum(out).backward()
    assert gt.grad is not None


def test_box_coder_rejects_bad_code_type():
    with pytest.raises(ValueError, match="code_type"):
        V.box_coder(_t(BOXES[:2]), None, _t(BOXES[:2]),
                    code_type="encode_center")


def test_roi_pool_true_cell_max():
    """Regression: every pixel in a cell participates in the max (the
    2x2-sample shortcut missed corner pixels)."""
    feat = np.zeros((1, 1, 12, 12), np.float32)
    feat[0, 0, 0, 0] = 100.0
    rois = np.array([[0, 0, 12, 12]], np.float32)
    out = np.asarray(V.roi_pool(_t(feat), _t(rois),
                                _t(np.array([1], np.int64)), 3).data)
    assert out[0, 0, 0, 0] == 100.0
    # and a dense random case vs a numpy loop oracle
    rng = np.random.RandomState(3)
    f2 = rng.randn(1, 2, 12, 12).astype(np.float32)
    out2 = np.asarray(V.roi_pool(_t(f2), _t(rois),
                                 _t(np.array([1], np.int64)), 3).data)
    for oy in range(3):
        for ox in range(3):
            ys = slice(int(np.floor(oy * 13 / 3)),
                       int(np.ceil((oy + 1) * 13 / 3)))
            xs = slice(int(np.floor(ox * 13 / 3)),
                       int(np.ceil((ox + 1) * 13 / 3)))
            want = f2[0, :, :12, :12][:, ys, xs].reshape(2, -1).max(1)
            np.testing.assert_allclose(out2[0, :, oy, ox], want, rtol=1e-5)


def test_nms_categories_filters_unlisted():
    cats = np.array([0, 1, 0, 1, 2], np.int64)
    got = np.asarray(V.nms(_t(BOXES), 0.5, _t(SCORES),
                           category_idxs=_t(cats),
                           categories=[0, 1]).data)
    assert 4 not in got  # category 2 excluded entirely
    assert {0, 1} <= set(got.tolist())


def test_box_coder_encode_all_pairs_and_axis_decode():
    priors = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], np.float32)
    targets = np.array([[1, 1, 9, 9], [6, 4, 16, 18], [0, 0, 4, 4]],
                       np.float32)
    enc = np.asarray(V.box_coder(_t(priors), None, _t(targets)).data)
    assert enc.shape == (3, 2, 4)  # all pairs
    dec = np.asarray(V.box_coder(_t(priors), None, _t(enc),
                                 code_type="decode_center_size",
                                 axis=0).data)
    assert dec.shape == (3, 2, 4)
    for i in range(3):
        for m in range(2):
            np.testing.assert_allclose(dec[i, m], targets[i], rtol=1e-4,
                                       atol=1e-4)
    with pytest.raises(ValueError, match="axis"):
        V.box_coder(_t(priors), None, _t(targets), axis=2)


def test_box_coder_axis1_with_var_and_nms_empty_categories():
    priors = np.array([[0, 0, 10, 10], [5, 5, 15, 15], [1, 1, 3, 3]],
                      np.float32)
    var = np.full((3, 4), 0.2, np.float32)
    tb = np.random.RandomState(8).rand(3, 2, 4).astype(np.float32)
    out = V.box_coder(_t(priors), _t(var), _t(tb),
                      code_type="decode_center_size", axis=1)
    assert list(out.shape) == [3, 2, 4]

    empty = np.asarray(V.nms(_t(BOXES), 0.5, _t(SCORES),
                             category_idxs=_t(np.zeros(5, np.int64)),
                             categories=[7]).data)
    assert empty.shape == (0,)
