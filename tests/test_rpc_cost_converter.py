"""distributed.rpc, auto_parallel cost model, checkpoint Converter."""
import multiprocessing as mp
import os

import numpy as np
import pytest

from paddle_tpu.distributed.auto_parallel import (
    Cluster, CommCost, Converter, CostEstimator,
)


# ------------------------------------------------------------- converter
def test_converter_tp_to_replicated():
    """Merge 4 column shards (TP degree 4) back to the full weight."""
    full = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    shards = [full[:, i * 4:(i + 1) * 4] for i in range(4)]
    pre = {"w": {"process_shape": [4], "process_group": [0, 1, 2, 3],
                 "dims_mapping": [-1, 0]}}
    cur = {"w": {"process_shape": [1], "process_group": [0],
                 "dims_mapping": [-1, -1]}}
    conv = Converter({"w": shards}, pre, cur)
    out = conv.convert(rank=0)
    np.testing.assert_array_equal(out["w"], full)


def test_converter_replicated_to_2d():
    """Re-slice a replicated tensor onto a 2x2 mesh (both dims sharded)."""
    full = np.arange(4 * 8, dtype=np.float32).reshape(4, 8)
    pre = {"w": {"process_shape": [1], "process_group": [0],
                 "dims_mapping": [-1, -1]}}
    cur = {"w": {"process_shape": [2, 2], "process_group": [0, 1, 2, 3],
                 "dims_mapping": [0, 1]}}
    for rank in range(4):
        out = Converter({"w": [full]}, pre, cur).convert(rank=rank)
        r, c = rank // 2, rank % 2
        np.testing.assert_array_equal(
            out["w"], full[r * 2:(r + 1) * 2, c * 4:(c + 1) * 4])


def test_converter_tp4_to_tp2():
    """The headline case: reshard a TP=4 checkpoint to TP=2."""
    full = np.random.RandomState(0).randn(6, 8).astype(np.float32)
    shards4 = [full[:, i * 2:(i + 1) * 2] for i in range(4)]
    pre = {"w": {"process_shape": [4], "process_group": [0, 1, 2, 3],
                 "dims_mapping": [-1, 0]}}
    cur = {"w": {"process_shape": [2], "process_group": [0, 1],
                 "dims_mapping": [-1, 0]}}
    out_r0 = Converter({"w": shards4}, pre, cur).convert(rank=0)
    out_r1 = Converter({"w": shards4}, pre, cur).convert(rank=1)
    np.testing.assert_array_equal(out_r0["w"], full[:, :4])
    np.testing.assert_array_equal(out_r1["w"], full[:, 4:])


def test_converter_errors():
    with pytest.raises(ValueError):
        Converter({}, {"w": {}}, {"w": {}})
    full = np.zeros((4, 4), np.float32)
    with pytest.raises(ValueError):
        Converter.slice_with_dist_attr(
            full, {"process_shape": [2], "process_group": [0, 1],
                   "dims_mapping": [0, -1]}, rank=7)


# ------------------------------------------------------------- cost model
def test_comm_cost_formulas():
    c = CommCost(Cluster(ici_bandwidth=100e9, ici_latency=0.0))
    gb = 1e9
    # ring all-reduce moves 2(n-1)/n of the data
    assert c.all_reduce(gb, 4) == pytest.approx(2 * 3 / 4 * gb / 100e9)
    assert c.all_gather(gb, 4) == pytest.approx(3 / 4 * gb / 100e9)
    assert c.all_reduce(gb, 1) == 0.0


def test_cost_estimator_flops_from_xla():
    import jax.numpy as jnp
    est = CostEstimator(Cluster(peak_flops=1e12, hbm_bandwidth=1e12))
    n = 256
    a = np.zeros((n, n), np.float32)

    def f(x):
        return x @ x

    r = est.analyze(f, a)
    # XLA reports ~2*n^3 flops for a matmul
    assert r["flops"] == pytest.approx(2 * n ** 3, rel=0.2)
    assert r["seconds"] > 0


def test_estimate_step_cost():
    from paddle_tpu.distributed.auto_parallel.cost_model import (
        estimate_step_cost)
    r = estimate_step_cost(flops_per_token=1e9, tokens_per_step=1e6,
                           dp=8, param_bytes=16e9)
    assert r["seconds"] >= r["compute_seconds"]
    assert r["tokens_per_second"] > 0


# ------------------------------------------------------------------ rpc
def _square(x):
    return x * x


def _fail():
    raise RuntimeError("remote boom")


def _rpc_worker(rank, world, port, q):
    import paddle_tpu.distributed.rpc as rpc
    rpc.init_rpc(f"worker{rank}", rank=rank, world_size=world,
                 master_endpoint=f"127.0.0.1:{port}")
    if rank == 0:
        out = rpc.rpc_sync("worker1", _square, args=(7,))
        fut = rpc.rpc_async("worker1", _square, args=(9,))
        got_err = False
        try:
            rpc.rpc_sync("worker1", _fail)
        except RuntimeError:
            got_err = True
        q.put((out, fut.wait(), got_err))
    rpc.shutdown()


def test_rpc_two_workers():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_rpc_worker, args=(r, 2, port, q))
             for r in range(2)]
    for p in procs:
        p.start()
    out, fut_out, got_err = q.get(timeout=240)
    for p in procs:
        p.join(timeout=60)
    assert out == 49 and fut_out == 81 and got_err
