"""Model zoo smoke + convergence tests (BASELINE.md config families).

Pattern follows the reference's model tests (tiny config, forward shape
check, backward produces finite grads, short train run reduces loss)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.models import (
    DiT, DiTConfig, ErnieConfig, ErnieForSequenceClassification, ErnieModel,
    LlamaConfig, LlamaForCausalLM, MoeConfig, MoeForCausalLM, PPOCRRecConfig,
    PPOCRRecModel,
)


def _all_finite_grads(model):
    for n, p in model.named_parameters():
        if p.grad is not None:
            assert np.all(np.isfinite(np.asarray(p.grad.data))), n


def test_llama_forward_backward():
    pt.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    ids = pt.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 16)).astype(np.int64))
    logits, loss = model(ids, labels=ids)
    assert list(logits.shape) == [2, 16, cfg.vocab_size]
    # untrained CE should be near log(vocab)
    assert abs(float(loss.numpy()) - np.log(cfg.vocab_size)) < 1.0
    loss.backward()
    _all_finite_grads(model)


def test_llama_trains():
    pt.seed(1)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=3e-3,
                             parameters=model.parameters())
    ids = pt.to_tensor((np.arange(32).reshape(2, 16) % 8).astype(np.int64))
    first = last = None
    for _ in range(30):
        _, loss = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        val = float(loss.numpy())
        first = val if first is None else first
        last = val
    assert last < first * 0.5, (first, last)


def test_llama_recompute_config():
    pt.seed(2)
    cfg = LlamaConfig.tiny(recompute=True)
    model = LlamaForCausalLM(cfg)
    ids = pt.to_tensor(np.zeros((1, 8), np.int64))
    _, loss = model(ids, labels=ids)
    loss.backward()
    _all_finite_grads(model)


def test_llama_flops_accounting():
    cfg = LlamaConfig.llama3_8b()
    # Llama-3-8B is ~7.2 GFLOPs/token fwd (2 MAC count, incl. lm_head)
    f = LlamaForCausalLM.flops_per_token(cfg)
    assert 10e9 < f < 20e9, f


def test_ernie_forward_and_cls():
    pt.seed(3)
    cfg = ErnieConfig.tiny()
    ids = pt.to_tensor(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (2, 12)).astype(np.int64))
    seq, pooled = ErnieModel(cfg)(ids)
    assert list(seq.shape) == [2, 12, cfg.hidden_size]
    assert list(pooled.shape) == [2, cfg.hidden_size]

    cls = ErnieForSequenceClassification(cfg, num_classes=3)
    labels = pt.to_tensor(np.array([0, 2], np.int64))
    logits, loss = cls(ids, labels=labels)
    assert list(logits.shape) == [2, 3]
    loss.backward()
    _all_finite_grads(cls)


def test_moe_forward_backward_with_aux():
    pt.seed(4)
    cfg = MoeConfig.tiny()
    model = MoeForCausalLM(cfg)
    ids = pt.to_tensor(np.random.RandomState(2).randint(
        0, cfg.vocab_size, (2, 8)).astype(np.int64))
    logits, loss = model(ids, labels=ids)
    # the labeled path is loss-only (logits=None, like the fused-CE
    # branch): the loss never reads the last position's logits and the
    # head matmul over it profiled at ~1.2 ms/step of pure copies
    assert logits is None
    infer = model(ids)
    assert list(infer.shape) == [2, 8, cfg.vocab_size]
    # layer 0 dense (first_k_dense_replace=1), layer 1 MoE with aux loss
    assert model.layers[0].is_dense and not model.layers[1].is_dense
    assert model.aux_loss() is not None
    loss.backward()
    _all_finite_grads(model)
    # expert weights must receive gradient (dispatch reaches the experts)
    g = model.layers[1].mlp.w1.grad
    assert g is not None and float(np.abs(np.asarray(g.data)).sum()) > 0


def test_dit_forward_backward():
    pt.seed(5)
    cfg = DiTConfig.tiny()
    model = DiT(cfg)
    x = pt.to_tensor(np.random.RandomState(3).randn(
        2, cfg.in_channels, cfg.input_size, cfg.input_size)
        .astype(np.float32))
    t = pt.to_tensor(np.array([10, 500], np.int64))
    y = pt.to_tensor(np.array([1, 3], np.int64))
    out = model(x, t, y)
    assert list(out.shape) == [2, model.out_channels, cfg.input_size,
                               cfg.input_size]
    # adaLN-zero: untrained blocks are identity, final layer zero-init →
    # output starts at exactly zero
    np.testing.assert_allclose(np.asarray(out.data), 0.0, atol=1e-6)
    loss = pt.ops.mean(pt.ops.square(out))
    loss.backward()


def test_ppocr_forward_and_ctc():
    pt.seed(6)
    cfg = PPOCRRecConfig.tiny()
    model = PPOCRRecModel(cfg)
    imgs = pt.to_tensor(np.random.RandomState(4).randn(
        2, 3, cfg.img_height, 64).astype(np.float32))
    logits = model(imgs)
    assert logits.shape[0] == 2 and logits.shape[2] == cfg.num_classes
    labels = pt.to_tensor(np.random.RandomState(5).randint(
        1, cfg.num_classes, (2, 5)).astype(np.int64))
    lens = pt.to_tensor(np.array([5, 3], np.int64))
    loss = model.loss(logits, labels, lens)
    assert float(loss.numpy()) > 0
    loss.backward()
    _all_finite_grads(model)


def test_llama_tensor_parallel_builds_sharded():
    """TP construction must produce mpu layers with mesh-sharded weights."""
    import paddle_tpu.distributed as dist
    mesh = dist.init_mesh({"dp": 2, "mp": 4})
    try:
        cfg = LlamaConfig.tiny(tensor_parallel=True)
        model = LlamaForCausalLM(cfg)
        from paddle_tpu.distributed.fleet import ColumnParallelLinear
        assert isinstance(model.model.layers[0].self_attn.q_proj,
                          ColumnParallelLinear)
        ids = pt.to_tensor(np.zeros((2, 8), np.int64))
        logits, loss = model(ids, labels=ids)
        assert list(logits.shape) == [2, 8, cfg.vocab_size]
        loss.backward()
        _all_finite_grads(model)
    finally:
        dist.set_mesh(None)


def test_llama_kv_cache_matches_full_forward():
    """Incremental decode logits must match the full-sequence forward."""
    pt.seed(10)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids_np = np.random.RandomState(10).randint(0, cfg.vocab_size,
                                               (2, 10)).astype(np.int64)
    full_logits = np.asarray(model(pt.to_tensor(ids_np)).data)

    # prefill on the first 6 tokens, then decode 4 more one at a time
    caches = [(None, None)] * cfg.num_hidden_layers
    h, caches = model.model(pt.to_tensor(ids_np[:, :6]), caches=caches)
    step = np.asarray(model._logits(h).data)
    np.testing.assert_allclose(step, full_logits[:, :6], rtol=2e-3,
                               atol=2e-3)
    for t in range(6, 10):
        h, caches = model.model(pt.to_tensor(ids_np[:, t:t + 1]),
                                caches=caches)
        lg = np.asarray(model._logits(h).data)[:, 0]
        np.testing.assert_allclose(lg, full_logits[:, t], rtol=2e-3,
                                   atol=2e-3, err_msg=f"t={t}")


def test_llama_generate_greedy_and_sampling():
    pt.seed(11)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    model.eval()
    prompt = pt.to_tensor(np.array([[5, 7, 9]], np.int64))
    out = model.generate(prompt, max_new_tokens=6, temperature=0)
    assert list(out.shape) == [1, 9]
    np.testing.assert_array_equal(np.asarray(out.data)[:, :3],
                                  [[5, 7, 9]])
    # greedy is deterministic
    out2 = model.generate(prompt, max_new_tokens=6, temperature=0)
    np.testing.assert_array_equal(np.asarray(out.data),
                                  np.asarray(out2.data))
    # sampling with top_k runs and produces valid token ids
    out3 = model.generate(prompt, max_new_tokens=4, temperature=0.8,
                          top_k=10, top_p=0.9)
    got = np.asarray(out3.data)
    assert got.shape == (1, 7)
    assert got.min() >= 0 and got.max() < cfg.vocab_size


def test_llama_generate_eos_stops():
    pt.seed(12)
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    model.eval()
    prompt = pt.to_tensor(np.array([[1, 2]], np.int64))
    out = model.generate(prompt, max_new_tokens=50, temperature=0)
    greedy_first = int(np.asarray(out.data)[0, 2])
    # making the first greedily-chosen token the EOS must stop after 1
    out2 = model.generate(prompt, max_new_tokens=50, temperature=0,
                          eos_token_id=greedy_first)
    assert out2.shape[1] == 3


def test_moe_generate_kv_cache():
    pt.seed(13)
    cfg = MoeConfig.tiny()
    model = MoeForCausalLM(cfg)
    model.eval()
    # capacity routing is not length-equivariant (dropping depends on the
    # token count); raise capacity so no token drops — then incremental
    # and full logits must agree
    for layer in model.layers:
        if not layer.is_dense:
            layer.mlp.capacity_factor = 64.0
    prompt = pt.to_tensor(np.array([[3, 5, 7]], np.int64))
    out = model.generate(prompt, max_new_tokens=5, temperature=0)
    assert list(out.shape) == [1, 8]
    ids_np = np.asarray(out.data)
    full = np.asarray(model(pt.to_tensor(ids_np)).data)
    caches = [(None, None)] * cfg.num_hidden_layers
    h, caches = model(pt.to_tensor(ids_np[:, :4]), caches=caches)
    lg = model.lm_head(h)  # cached path returns hidden states
    np.testing.assert_allclose(np.asarray(lg.data), full[:, :4],
                               rtol=3e-3, atol=3e-3)
    for t in range(4, 8):
        h, caches = model(pt.to_tensor(ids_np[:, t:t + 1]),
                          caches=caches)
        lg = model.lm_head(h)
        np.testing.assert_allclose(np.asarray(lg.data)[:, 0], full[:, t],
                                   rtol=3e-3, atol=3e-3, err_msg=f"t={t}")


def test_llama_chunked_prefill_matches_full_forward():
    """Prefill a long prompt in chunks: logits must match the one-shot
    forward (the offset-causal mask covers P>0, S>1)."""
    pt.seed(14)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids_np = np.random.RandomState(14).randint(
        0, cfg.vocab_size, (2, 12)).astype(np.int64)
    full = np.asarray(model(pt.to_tensor(ids_np)).data)

    caches = [(None, None)] * cfg.num_hidden_layers
    outs = []
    for chunk in (ids_np[:, :5], ids_np[:, 5:9], ids_np[:, 9:]):
        h, caches = model.model(pt.to_tensor(chunk), caches=caches)
        outs.append(np.asarray(model._logits(h).data))
    got = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(got, full, rtol=2e-3, atol=2e-3)
